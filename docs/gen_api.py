"""Generate the API reference (docs/api/*.md) from live docstrings.

The reference ships Sphinx RST covering every public class
(/root/reference/docs/source/*.rst); apex_tpu generates the equivalent
from the package itself so the reference can never drift from the code:

    JAX_PLATFORMS=cpu python docs/gen_api.py

Walks the public surface (every name in each module's ``__all__``, or
its public functions/classes when ``__all__`` is absent), emits one
markdown file per module group with signatures + docstrings, and an
index.  CI can diff the output to catch undocumented additions.
"""

from __future__ import annotations

import importlib
import re
import inspect
import os
import textwrap

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "api")

# module path -> (page, section title)
MODULES = [
    # amp
    ("apex_tpu.amp", "amp", "apex_tpu.amp — mixed precision"),
    ("apex_tpu.amp.frontend", "amp", "amp.frontend — train-step factory"),
    ("apex_tpu.amp.scaler", "amp", "amp.scaler — dynamic loss scaling"),
    ("apex_tpu.amp.policy", "amp", "amp.policy — opt-level policies"),
    ("apex_tpu.amp.patch", "amp", "amp.patch — O1 per-op cast engine"),
    # optimizers
    ("apex_tpu.optimizers", "optimizers",
     "apex_tpu.optimizers — fused optimizers"),
    ("apex_tpu.contrib.optimizers.distributed_fused_adam", "optimizers",
     "contrib.optimizers — ZeRO DistributedFusedAdam"),
    ("apex_tpu.contrib.optimizers.distributed_fused_lamb", "optimizers",
     "contrib.optimizers — ZeRO DistributedFusedLAMB"),
    # ops
    ("apex_tpu.ops.flash_attention", "ops",
     "ops.flash_attention — FlashAttention-2 kernels"),
    ("apex_tpu.ops.layer_norm", "ops", "ops.layer_norm — LN/RMSNorm"),
    ("apex_tpu.ops.softmax", "ops", "ops.softmax — scaled softmax family"),
    ("apex_tpu.ops.xentropy", "ops", "ops.xentropy — fused CE"),
    ("apex_tpu.ops.lm_head_ce", "ops",
     "ops.lm_head_ce — chunked head+CE fusion"),
    ("apex_tpu.ops.swiglu", "ops", "ops.swiglu — fused bias-SwiGLU"),
    ("apex_tpu.ops.rope", "ops", "ops.rope — rotary embeddings"),
    ("apex_tpu.ops.dense", "ops", "ops.dense — fused dense epilogues"),
    ("apex_tpu.ops.flat_adam", "ops", "ops.flat_adam — flat Adam"),
    ("apex_tpu.ops.collective_matmul", "ops",
     "ops.collective_matmul — overlapped ring TP collectives"),
    ("apex_tpu.ops.grouped_matmul", "ops",
     "ops.grouped_matmul — ragged expert segment matmul"),
    ("apex_tpu.ops.paged_attention", "ops",
     "ops.paged_attention — ragged paged-attention decode kernel"),
    ("apex_tpu.ops.fused_sampling", "ops",
     "ops.fused_sampling — fused temperature/top-k/top-p/sample kernel"),
    ("apex_tpu.ops.decode_step", "ops",
     "ops.decode_step — fused decode-layer megakernel "
     "(rope + paged attention + projection)"),
    # comm
    ("apex_tpu.comm", "comm",
     "apex_tpu.comm — compressed gradient collectives"),
    ("apex_tpu.comm.config", "comm",
     "comm.config — grad_comm spec (wire dtype / error feedback / buckets)"),
    ("apex_tpu.comm.quantize", "comm",
     "comm.quantize — block-scaled int8 / bf16 wire formats"),
    ("apex_tpu.comm.bucketing", "comm",
     "comm.bucketing — greedy dtype-segregated buckets"),
    ("apex_tpu.comm.reduce", "comm",
     "comm.reduce — compressed all-reduce / reduce-scatter + telemetry"),
    # checkpoint
    ("apex_tpu.checkpoint", "checkpoint",
     "apex_tpu.checkpoint — elastic fault-tolerant training state"),
    ("apex_tpu.checkpoint.sharded", "checkpoint",
     "checkpoint.sharded — per-process shards + atomic manifest"),
    ("apex_tpu.checkpoint.async_saver", "checkpoint",
     "checkpoint.async_saver — overlapped zero-stall saves"),
    ("apex_tpu.checkpoint.recovery", "checkpoint",
     "checkpoint.recovery — detector-driven rollback + LR re-warm"),
    # analysis (apexlint)
    ("apex_tpu.analysis.rules", "analysis",
     "analysis.rules — Tier-A AST rules (the invariant table)"),
    ("apex_tpu.analysis.linter", "analysis",
     "analysis.linter — rule driver, suppressions, baseline diff"),
    ("apex_tpu.analysis.env_registry", "analysis",
     "analysis.env_registry — the authoritative APEX_TPU_* table"),
    ("apex_tpu.analysis.callgraph", "analysis",
     "analysis.callgraph — traced-code reachability heuristic"),
    ("apex_tpu.analysis.jaxpr_audit", "analysis",
     "analysis.jaxpr_audit — Tier-B trace auditor (census, overlap, "
     "upcasts, donation)"),
    ("apex_tpu.analysis.concurrency", "analysis",
     "analysis.concurrency — Tier-C thread-escape graph + guarded-by "
     "discipline (APX501-503)"),
    ("apex_tpu.analysis.lifecycle", "analysis",
     "analysis.lifecycle — Tier-C thread/server lifecycle + paired "
     "acquire/release (APX504-505)"),
    ("apex_tpu.analysis.stress", "analysis",
     "analysis.stress — seeded concurrency stress smoke (the "
     "concurrency_audit gate's dynamic half)"),
    # parallel
    ("apex_tpu.parallel.mesh", "parallel", "parallel.mesh — device mesh"),
    ("apex_tpu.parallel.launch", "parallel",
     "parallel.launch — multi-host bootstrap"),
    ("apex_tpu.parallel.distributed", "parallel",
     "parallel.distributed — DDP"),
    ("apex_tpu.parallel.sync_batchnorm", "parallel",
     "parallel.sync_batchnorm — SyncBN"),
    ("apex_tpu.parallel.fsdp", "parallel", "parallel.fsdp — ZeRO-3"),
    ("apex_tpu.parallel.ring_attention", "parallel",
     "parallel.ring_attention — context parallelism (ring)"),
    ("apex_tpu.parallel.ulysses", "parallel",
     "parallel.ulysses — context parallelism (all-to-all)"),
    ("apex_tpu.parallel.LARC", "parallel", "parallel.LARC"),
    ("apex_tpu.parallel.clip_grad", "parallel", "parallel.clip_grad"),
    # transformer (Megatron layer)
    ("apex_tpu.transformer.parallel_state", "transformer",
     "transformer.parallel_state — process groups"),
    ("apex_tpu.transformer.tensor_parallel.layers", "transformer",
     "tensor_parallel.layers — Vocab/Column/Row"),
    ("apex_tpu.transformer.tensor_parallel.mappings", "transformer",
     "tensor_parallel.mappings — collectives"),
    ("apex_tpu.transformer.tensor_parallel.cross_entropy", "transformer",
     "tensor_parallel.cross_entropy"),
    ("apex_tpu.transformer.tensor_parallel.random", "transformer",
     "tensor_parallel.random — RNG streams"),
    ("apex_tpu.transformer.pipeline_parallel.schedules", "transformer",
     "pipeline_parallel.schedules — 1F1B / interleaved"),
    ("apex_tpu.transformer.pipeline_parallel.p2p_communication",
     "transformer", "pipeline_parallel.p2p_communication"),
    ("apex_tpu.transformer.microbatches", "transformer",
     "transformer.microbatches"),
    ("apex_tpu.transformer.moe", "transformer",
     "transformer.moe — Switch MoE"),
    ("apex_tpu.transformer._data", "transformer",
     "transformer._data — batch samplers"),
    # models
    ("apex_tpu.models.config", "models", "models.config"),
    ("apex_tpu.models.transformer_lm", "models",
     "models.transformer_lm — decoder backbone"),
    ("apex_tpu.models.gpt", "models", "models.gpt — GPT wiring"),
    ("apex_tpu.models.generate", "models",
     "models.generate — flash prefill + ragged KV-cache decoding"),
    ("apex_tpu.models.speculative", "models",
     "models.speculative — n-gram drafting + batched verification"),
    ("apex_tpu.models.quantized", "models",
     "models.quantized — weight-only int8 serving conversion"),
    ("apex_tpu.models.lora", "models",
     "models.lora — LoRA adapters: merged weights or ragged batched "
     "deltas"),
    ("apex_tpu.models.bert", "models", "models.bert"),
    ("apex_tpu.models.resnet", "models", "models.resnet"),
    # serving
    ("apex_tpu.serving", "serving",
     "apex_tpu.serving — continuous-batching inference engine"),
    ("apex_tpu.serving.engine", "serving",
     "serving.engine — ServingEngine + Request/Response"),
    ("apex_tpu.serving.batching", "serving",
     "serving.batching — prompt buckets + slot pool"),
    ("apex_tpu.serving.paged_cache", "serving",
     "serving.paged_cache — block pool, block tables, prefix sharing"),
    ("apex_tpu.serving.slo", "serving",
     "serving.slo — SLO classes, TTFT/TPOT deadlines, goodput judge"),
    ("apex_tpu.serving.compile_cache", "serving",
     "serving.compile_cache — persistent AOT executables + warmup "
     "ladder"),
    ("apex_tpu.serving.adapter_pool", "serving",
     "serving.adapter_pool — refcounted HBM LoRA slab pool"),
    ("apex_tpu.serving.cluster", "serving",
     "serving.cluster — disaggregated prefill/decode tier"),
    ("apex_tpu.serving.cluster.protocol", "serving",
     "serving.cluster.protocol — length-prefixed socket frames"),
    ("apex_tpu.serving.cluster.handoff", "serving",
     "serving.cluster.handoff — KV wire format (raw/bf16/int8)"),
    ("apex_tpu.serving.cluster.worker", "serving",
     "serving.cluster.worker — prefill/decode pool members"),
    ("apex_tpu.serving.cluster.router", "serving",
     "serving.cluster.router — SLO-aware dispatch + requeue"),
    ("apex_tpu.serving.cluster.controller", "serving",
     "serving.cluster.controller — elastic pool controller "
     "(spawn/drain on autoscale_signal)"),
    # data
    ("apex_tpu.data.image_folder", "data",
     "data.image_folder — file-backed input pipeline"),
    ("apex_tpu.data.prefetch", "data",
     "data.prefetch — device prefetch (data_prefetcher analog)"),
    # contrib
    ("apex_tpu.contrib.multihead_attn", "contrib",
     "contrib.multihead_attn"),
    ("apex_tpu.contrib.transducer", "contrib", "contrib.transducer"),
    ("apex_tpu.contrib.sparsity", "contrib", "contrib.sparsity — ASP"),
    ("apex_tpu.contrib.focal_loss", "contrib", "contrib.focal_loss"),
    ("apex_tpu.contrib.index_mul_2d", "contrib", "contrib.index_mul_2d"),
    ("apex_tpu.contrib.conv_bias_relu", "contrib",
     "contrib.conv_bias_relu"),
    ("apex_tpu.contrib.peer_memory", "contrib",
     "contrib.peer_memory — halo exchange"),
    ("apex_tpu.contrib.bottleneck", "contrib", "contrib.bottleneck"),
    # observability
    ("apex_tpu.observability", "observability",
     "apex_tpu.observability — telemetry"),
    ("apex_tpu.observability.metrics", "observability",
     "observability.metrics — registry, counters/gauges/histograms"),
    ("apex_tpu.observability.spans", "observability",
     "observability.spans — span API + StepTimer"),
    ("apex_tpu.observability.sinks", "observability",
     "observability.sinks — JSONL / stderr-summary sinks"),
    ("apex_tpu.observability.trace", "observability",
     "observability.trace — Chrome trace_events / Perfetto export"),
    ("apex_tpu.observability.recorder", "observability",
     "observability.recorder — flight recorder / crash post-mortem"),
    ("apex_tpu.observability.detectors", "observability",
     "observability.detectors — step-boundary anomaly detectors"),
    ("apex_tpu.observability.device", "observability",
     "observability.device — recompile tracking + HBM gauges"),
    ("apex_tpu.observability.sketches", "observability",
     "observability.sketches — mergeable log-bucket histogram sketch"),
    ("apex_tpu.observability.openmetrics", "observability",
     "observability.openmetrics — OpenMetrics text render/parse"),
    ("apex_tpu.observability.exporter", "observability",
     "observability.exporter — live /metrics + /healthz HTTP endpoint"),
    # misc
    ("apex_tpu.normalization", "misc", "apex_tpu.normalization"),
    ("apex_tpu.fused_dense", "misc", "apex_tpu.fused_dense"),
    ("apex_tpu.mlp", "misc", "apex_tpu.mlp"),
    ("apex_tpu.RNN", "misc", "apex_tpu.RNN"),
    ("apex_tpu.fp16_utils", "misc", "apex_tpu.fp16_utils"),
    ("apex_tpu.multi_tensor", "misc", "apex_tpu.multi_tensor"),
    ("apex_tpu.utils.registry", "misc", "utils.registry — op registry"),
    ("apex_tpu.utils.checkpoint", "misc",
     "utils.checkpoint — save/resume + AutoResume"),
    ("apex_tpu.utils.collectives", "misc", "utils.collectives"),
    ("apex_tpu.testing", "misc", "apex_tpu.testing"),
]


def _public_names(mod):
    if hasattr(mod, "__all__"):
        return list(mod.__all__)
    return [n for n, obj in vars(mod).items()
            if not n.startswith("_")
            and (inspect.isfunction(obj) or inspect.isclass(obj))
            and getattr(obj, "__module__", "").startswith("apex_tpu")]


def _sig(obj) -> str:
    try:
        sig = str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(...)"
    # default-value reprs can embed memory addresses (<function f at
    # 0x7f...>) — strip them so regeneration is deterministic
    return re.sub(r" at 0x[0-9a-f]+", "", sig)


def _doc(obj, indent="") -> str:
    doc = inspect.getdoc(obj) or "*(no docstring)*"
    # docstrings can embed object reprs with process-local addresses
    doc = re.sub(r" at 0x[0-9a-f]+", "", doc)
    return textwrap.indent(doc, indent)


def _emit_entry(lines, name, obj):
    if inspect.isclass(obj):
        lines.append(f"### class `{name}{_sig(obj)}`\n")
        lines.append(_doc(obj) + "\n")
        for mname in sorted(vars(obj)):
            if mname.startswith("_"):
                continue
            raw = inspect.getattr_static(obj, mname)
            if isinstance(raw, property):
                m, kind = raw.fget, "property "
            elif isinstance(raw, (staticmethod, classmethod)):
                m, kind = raw.__func__, ""
            elif inspect.isroutine(raw):
                m, kind = raw, ""
            else:
                continue
            if m is not None and inspect.getdoc(m):
                sig = "" if kind else _sig(m)
                lines.append(f"- **{kind}`{mname}{sig}`** — "
                             f"{(inspect.getdoc(m) or '').splitlines()[0]}")
        lines.append("")
    elif callable(obj):
        lines.append(f"### `{name}{_sig(obj)}`\n")
        lines.append(_doc(obj) + "\n")
    else:
        lines.append(f"### `{name}`\n")
        lines.append(f"*(constant — {type(obj).__name__})*\n")


def main(out_dir: str = OUT):
    os.makedirs(out_dir, exist_ok=True)
    pages: dict = {}
    skipped = []
    for mod_path, page, title in MODULES:
        try:
            mod = importlib.import_module(mod_path)
        except Exception as e:
            skipped.append((mod_path, str(e)))
            continue
        lines = pages.setdefault(page, [])
        lines.append(f"\n## {title}\n")
        head = (inspect.getdoc(mod) or "").strip()
        if head:
            lines.append(head.split("\n\n")[0] + "\n")
        for name in _public_names(mod):
            obj = getattr(mod, name, None)
            if obj is None:
                continue
            _emit_entry(lines, name, obj)

    index = ["# apex_tpu API reference",
             "",
             "Generated from docstrings by `docs/gen_api.py` "
             "(regenerate after API changes).", ""]
    for page in sorted(pages):
        path = os.path.join(out_dir, f"{page}.md")
        with open(path, "w") as f:
            f.write(f"# apex_tpu API — {page}\n")
            f.write("\n".join(pages[page]) + "\n")
        index.append(f"- [{page}]({page}.md)")
        print(f"wrote {path}")
    with open(os.path.join(out_dir, "index.md"), "w") as f:
        f.write("\n".join(index) + "\n")
    if skipped:
        print("skipped:", skipped)
    return skipped


if __name__ == "__main__":
    main()
