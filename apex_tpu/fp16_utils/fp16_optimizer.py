"""Legacy FP16_Optimizer — master-weight wrapper with loss scaling.

Reference: apex/fp16_utils/fp16_optimizer.py:13 (wraps an existing
optimizer: keeps fp32 masters, scales the loss, unscales/copies grads,
skips steps on overflow) — the pre-``amp`` manual path the reference keeps
for backward compatibility. The JAX translation is a thin stateful shell
over the same primitives the functional path uses
(apex_tpu.amp.{policy,scaler} + any optax-style optimizer); prefer
``amp.make_train_step`` for new code — this class exists for API parity
and for porting reference training scripts 1:1.

Usage (mirrors reference README.md:60-97 workflow)::

    opt = FP16_Optimizer(fused_adam(lr=1e-3), params,
                         dynamic_loss_scale=True)
    for batch in data:
        loss, grads = jax.value_and_grad(loss_fn)(opt.model_params, *batch)
        opt.step(grads)          # unscale → check → update → recast
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu.amp import scaler as scaler_lib
from apex_tpu.fp16_utils.fp16util import (
    model_grads_to_master_grads,
    network_to_half,
)

__all__ = ["FP16_Optimizer"]


class FP16_Optimizer:
    def __init__(self, optimizer: Any, params: Any, *,
                 static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: dict = None,
                 cast_model_params: bool = True):
        self.optimizer = optimizer
        self.master_params = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, jnp.float32)
            if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
            else x, params)
        self.model_params = (network_to_half(params) if cast_model_params
                             else params)
        self.opt_state = optimizer.init(self.master_params)
        spec = "dynamic" if dynamic_loss_scale else static_loss_scale
        self.ls_cfg, self.ls_state = scaler_lib.init_loss_scale(
            spec, **(dynamic_loss_args or {}))
        self.overflow = False

    @property
    def loss_scale(self) -> float:
        return float(self.ls_state.loss_scale)

    def scale_loss(self, loss):
        """Multiply the loss by the current scale (use inside your grad
        fn; reference ``backward(loss)`` fused this with autograd)."""
        return scaler_lib.scale_loss(loss, self.ls_state)

    def step(self, model_grads: Any) -> bool:
        """Unscale grads, update masters (skipped on overflow), recast
        model params. Returns True if the step was skipped."""
        master_grads = model_grads_to_master_grads(model_grads)
        master_grads, finite = scaler_lib.unscale_grads(
            master_grads, self.ls_state)
        self.ls_state, skip = scaler_lib.update_loss_scale(
            self.ls_cfg, self.ls_state, ~finite)
        self.overflow = bool(skip)
        if self.overflow:
            return True
        updates, self.opt_state = self.optimizer.update(
            master_grads, self.opt_state, self.master_params)
        self.master_params = jax.tree_util.tree_map(
            lambda p, u: p + u.astype(p.dtype),
            self.master_params, updates)
        self.model_params = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype) if hasattr(p, "dtype") else m,
            self.master_params, self.model_params)
        return False

    # ---- checkpointing (reference fp16_optimizer.py state_dict keys) ----
    def state_dict(self) -> dict:
        return {
            "loss_scaler": {
                "loss_scale": float(self.ls_state.loss_scale),
                "unskipped": int(self.ls_state.unskipped),
            },
            "overflow": self.overflow,
            "master_params": self.master_params,
            "optimizer_state": self.opt_state,
        }

    def load_state_dict(self, d: dict) -> None:
        self.ls_state = scaler_lib.LossScaleState(
            loss_scale=jnp.float32(d["loss_scaler"]["loss_scale"]),
            unskipped=jnp.int32(d["loss_scaler"].get("unskipped", 0)),
        )
        self.overflow = bool(d.get("overflow", False))
        self.master_params = d["master_params"]
        self.opt_state = d["optimizer_state"]
        self.model_params = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype) if hasattr(p, "dtype") else m,
            self.master_params, self.model_params)
