"""Legacy fp16 utility helpers.

Reference: apex/fp16_utils/fp16util.py — module-surgery helpers
(``network_to_half`` :35, ``convert_network`` :60, ``prep_param_lists``
:90, ``model_grads_to_master_grads`` :136, ``master_params_to_model_params``
:158). Functional JAX translation: every helper is a pytree cast; "keep
batchnorm fp32" (BN_convert_float :22) uses the shared norm-path heuristic
from the amp policy.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.policy import _effective, _is_norm_param

__all__ = [
    "network_to_half",
    "convert_network",
    "prep_param_lists",
    "model_grads_to_master_grads",
    "master_params_to_model_params",
    "to_python_float",
]


def _cast_tree(params: Any, dtype, keep_norm_fp32: bool) -> Any:
    dtype = _effective(dtype)

    def leaf(path, x):
        if not hasattr(x, "dtype") or not jnp.issubdtype(
                x.dtype, jnp.floating):
            return x
        names = tuple(str(getattr(p, "key", getattr(p, "name", p)))
                      for p in path)
        if keep_norm_fp32 and _is_norm_param(names):
            return x.astype(jnp.float32)
        return x.astype(dtype)

    return jax.tree_util.tree_map_with_path(leaf, params)


def network_to_half(params: Any) -> Any:
    """Cast a param tree to half precision, keeping norm-layer params fp32
    (reference :35: BN buffers stay fp32)."""
    return _cast_tree(params, jnp.float16, keep_norm_fp32=True)


def convert_network(params: Any, dtype) -> Any:
    """Cast to ``dtype`` with norm params kept fp32 (reference :60)."""
    return _cast_tree(params, dtype, keep_norm_fp32=True)


def prep_param_lists(params: Any) -> Tuple[Any, Any]:
    """(model_params_half, master_params_fp32) pair (reference :90; the
    ``flat_master`` variant is the ZeRO flat buffer —
    contrib.optimizers.distributed_fused_adam)."""
    model = network_to_half(params)
    master = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, jnp.float32)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params,
    )
    return model, master


def model_grads_to_master_grads(model_grads: Any) -> Any:
    """Half grads → fp32 master grads (reference :136)."""
    return jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32)
        if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.inexact)
        else g,
        model_grads,
    )


def master_params_to_model_params(master_params: Any,
                                  model_params: Any) -> Any:
    """fp32 masters → model-dtype params (reference :158)."""
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype)
        if hasattr(p, "dtype") else m,
        master_params, model_params,
    )


def to_python_float(t) -> float:
    """Reference :176 — device scalar → python float (a device sync)."""
    return float(t)
