"""Legacy static/dynamic loss scaler classes.

Reference: apex/fp16_utils/loss_scaler.py (``LossScaler`` :10 static,
``DynamicLossScaler`` :47 — halve on overflow, double after
``scale_window`` clean steps). These wrap the device-side scaler state
from apex_tpu.amp.scaler in the legacy imperative API; the functional
train-step path (amp.make_train_step) uses that state directly.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.amp import scaler as scaler_lib

__all__ = ["LossScaler", "DynamicLossScaler"]


class LossScaler:
    """Static scale (reference :10)."""

    def __init__(self, scale=1.0):
        self.cfg, self.state = scaler_lib.init_loss_scale(float(scale))

    @property
    def loss_scale(self) -> float:
        return float(self.state.loss_scale)

    def scale_loss(self, loss):
        return scaler_lib.scale_loss(loss, self.state)

    def unscale(self, grads):
        grads, finite = scaler_lib.unscale_grads(grads, self.state)
        self._last_finite = finite
        return grads

    def update_scale(self, overflow=None) -> bool:
        """Returns should_skip (always False for static scale)."""
        if overflow is None:
            overflow = ~getattr(self, "_last_finite", jnp.asarray(True))
        self.state, skip = scaler_lib.update_loss_scale(
            self.cfg, self.state, jnp.asarray(overflow))
        return bool(skip)

    # reference checkpoint keys (loss_scaler pickled whole; we keep plain)
    def state_dict(self) -> dict:
        return {"loss_scale": float(self.state.loss_scale),
                "unskipped": int(self.state.unskipped)}

    def load_state_dict(self, d: dict) -> None:
        self.state = scaler_lib.LossScaleState(
            loss_scale=jnp.float32(d["loss_scale"]),
            unskipped=jnp.int32(d.get("unskipped", 0)),
        )


class DynamicLossScaler(LossScaler):
    """Window-doubling dynamic scale (reference :47)."""

    def __init__(self, init_scale=2.0 ** 32, scale_factor=2.0,
                 scale_window=1000):
        self.cfg, self.state = scaler_lib.init_loss_scale(
            "dynamic", init_scale=init_scale, scale_factor=scale_factor,
            scale_window=scale_window)
