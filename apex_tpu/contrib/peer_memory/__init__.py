"""Halo exchange for spatial parallelism.

TPU rebuild of ``apex.contrib.peer_memory`` (reference:
peer_memory.py:5 ``PeerMemoryPool``, peer_halo_exchanger_1d.py:5
``PeerHaloExchanger1d``, csrc peer_memory.cpp:20-28).  The reference
moves halo rows through CUDA-IPC peer mappings with SM-driven push/pull
kernels and spin-lock signal flags; on TPU the same neighbor exchange is
one pair of ``ppermute`` collectives over the spatial mesh axis — XLA
owns the buffers (no allocator/IPC analog needed, SURVEY.md §2.3 row
nccl_allocator) and the latency-hiding scheduler overlaps the transfer
with the convolution the way the reference overlaps with numSM-limited
copy kernels.
"""

from .halo_exchange import HaloExchanger1d, halo_exchange_1d  # noqa: F401
