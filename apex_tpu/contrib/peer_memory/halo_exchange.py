"""1-D halo exchange over a spatial mesh axis.

Reference semantics (peer_halo_exchanger_1d.py:20-67 + csrc
push_pull_halos_1d): each rank holds a spatial shard with ``half_halo``
rows of padding on each side; the rows just inside the low edge go to
the low neighbor's high input halo and vice versa; ranks at the global
boundary receive zeros (``low_zero``/``high_zero``).

``jax.lax.ppermute`` gives exactly this: destinations not named in the
permutation receive zeros, so the non-circular boundary behavior falls
out of sending over the open chain [(1,0),(2,1),...] / [(0,1),(1,2),...].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["halo_exchange_1d", "HaloExchanger1d"]


def halo_exchange_1d(
    x: jax.Array,
    half_halo: int,
    axis_name: str,
    *,
    dim: int = 1,
) -> jax.Array:
    """Pad ``x`` (this rank's spatial shard, NO halo) with ``half_halo``
    rows of neighbor data on each side of ``dim``.

    Call inside ``shard_map`` with the spatial dim sharded over
    ``axis_name``.  Returns shape grown by ``2*half_halo`` along ``dim``;
    the first/last rank's outer halo is zeros (matching the reference's
    low_zero/high_zero edge handling).
    """
    if half_halo <= 0:
        return x
    n = jax.lax.axis_size(axis_name)
    # slices of my edges
    lo_edge = jax.lax.slice_in_dim(x, 0, half_halo, axis=dim)
    hi_edge = jax.lax.slice_in_dim(
        x, x.shape[dim] - half_halo, x.shape[dim], axis=dim)
    # my high edge becomes my high-neighbor's low halo (send i -> i+1);
    # ranks with no source (rank 0's low halo) get zeros from ppermute
    recv_lo = jax.lax.ppermute(
        hi_edge, axis_name, [(i, i + 1) for i in range(n - 1)])
    recv_hi = jax.lax.ppermute(
        lo_edge, axis_name, [(i + 1, i) for i in range(n - 1)])
    return jnp.concatenate([recv_lo, x, recv_hi], axis=dim)


class HaloExchanger1d:
    """API shim matching the reference ``PeerHaloExchanger1d`` call shape.

    The reference's ctor takes (ranks, rank_in_group, peer_pool,
    half_halo); here the mesh axis name replaces the rank group and there
    is no pool to allocate from.  ``__call__(y, H_split=True)`` takes a
    shard WITH halo regions already allocated (the reference writes into
    ``y`` in place) and returns a new array with the halos filled.
    """

    def __init__(self, axis_name: str, half_halo: int):
        self.axis_name = axis_name
        self.half_halo = half_halo

    def __call__(self, y: jax.Array, H_split: bool = True) -> jax.Array:
        hh = self.half_halo
        dim = 1 if H_split else 2  # NHWC
        interior = jax.lax.slice_in_dim(
            y, hh, y.shape[dim] - hh, axis=dim)
        return halo_exchange_1d(
            interior, hh, self.axis_name, dim=dim)
