"""apex.contrib.xentropy parity: the fused label-smoothing CE lives in
apex_tpu.ops.xentropy (reference xentropy/interface.cpp:50 →
SoftmaxCrossEntropyLoss, softmax_xentropy.py:4)."""
from apex_tpu.ops.xentropy import (  # noqa: F401
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)
