"""groupbn — NHWC persistent BatchNorm analog.

Reference: ``apex/contrib/groupbn`` (5.8k LoC: hand-written NHWC
persistent-BN CUDA kernels, CUDA-IPC inter-GPU buffers for ``bn_group``
cross-device stats, CTA-occupancy tuning — batch_norm.py:135
``BatchNorm2d_NHWC(num_features, fuse_relu, bn_group, ...)`` with
``forward(x, z=None)`` where ``z`` is a fused residual add).

TPU disposition (the explicit writeup SURVEY.md §7 promised):

- **NHWC layout** is this package's native conv layout — no dedicated
  kernel needed; XLA fuses normalize+affine(+add+relu) into one
  elementwise epilogue (same class of fusion verified by HLO for
  contrib.conv_bias_relu).
- **persistent kernels / CTA occupancy / multi_stream** are
  CUDA-scheduling machinery with no TPU analog: XLA owns scheduling.
- **bn_group cross-device stats over CUDA-IPC** map to ``lax.pmean``
  over a mesh axis — exactly :class:`apex_tpu.parallel.SyncBatchNorm`.

So :class:`BatchNorm2d_NHWC` here is a thin flax module with the
reference's call shape (``fuse_relu``, optional residual ``z``) backed
by SyncBatchNorm; ``bn_group > 1`` = stats over the ``axis_name`` mesh
axis.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

__all__ = ["BatchNorm2d_NHWC"]


class BatchNorm2d_NHWC(nn.Module):
    """Reference-shaped NHWC BatchNorm (batch_norm.py:135).

    ``bn_group > 1`` enables cross-device stats over ``axis_name``
    (the CUDA-IPC group analog); ``forward(x, z)`` fuses the residual
    add before the optional ReLU like the reference's bn_add_relu
    kernels.
    """

    num_features: int
    fuse_relu: bool = False
    bn_group: int = 1
    axis_name: Optional[str] = "dp"
    momentum: float = 0.1    # torch running-stat convention (SyncBN's)
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array, z: Optional[jax.Array] = None,
                 train: bool = True) -> jax.Array:
        bn = SyncBatchNorm(
            num_features=self.num_features,
            axis_name=self.axis_name if self.bn_group > 1 else None,
            fuse_relu=False,              # relu applied after the add
            momentum=self.momentum,
            eps=self.eps,
        )
        y = bn(x, use_running_average=not train)
        if z is not None:
            y = y + z
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y
