"""Fused gather-multiply: ``out = in1[idx] * in2``.

Reference: apex/contrib/csrc/index_mul_2d/index_mul_2d_cuda.cu (forward,
backward, and a fused backward-into-fp32-accumulator variant) wrapped at
apex/contrib/index_mul_2d/index_mul_2d.py:5. Shapes: in1 [M, D] gathered at
idx [N] and multiplied with in2 [N, D].

On TPU this is ``jnp.take`` + multiply, which XLA fuses into one pass; the
backward's scatter-add (d_in1) lowers to an efficient segmented scatter.
The reference's fp32-accumulation backward variant corresponds to the f32
upcast inside the custom VJP below.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

__all__ = ["index_mul_2d"]


@jax.custom_vjp
def index_mul_2d(in1: jax.Array, in2: jax.Array, idx1: jax.Array):
    if in1.ndim != 2 or in2.ndim != 2:
        raise ValueError("in1 and in2 must be 2-dimensional")
    if idx1.ndim != 1 or in2.shape[0] != idx1.shape[0]:
        raise ValueError("idx1 must be 1-D with len == in2.shape[0]")
    return jnp.take(in1, idx1, axis=0) * in2


def _fwd(in1, in2, idx1):
    return index_mul_2d(in1, in2, idx1), (in1, in2, idx1)


def _bwd(res, g):
    in1, in2, idx1 = res
    g32 = g.astype(jnp.float32)
    # fp32 accumulation for the scatter-add (reference
    # index_mul_2d_grad_grad fp32-accum variant)
    d_in1 = jnp.zeros(in1.shape, jnp.float32).at[idx1].add(
        g32 * in2.astype(jnp.float32))
    d_in2 = jnp.take(in1, idx1, axis=0).astype(jnp.float32) * g32
    return (d_in1.astype(in1.dtype), d_in2.astype(in2.dtype), None)


index_mul_2d.defvjp(_fwd, _bwd)
