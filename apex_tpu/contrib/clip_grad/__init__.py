"""apex.contrib.clip_grad parity (clip_grad.py:16 fused clip_grad_norm_)."""
from apex_tpu.parallel.clip_grad import clip_grad_norm, clip_grad_norm_  # noqa: F401
