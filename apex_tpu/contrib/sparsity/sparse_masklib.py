"""m:n structured-sparsity mask search (vectorized numpy, host-side).

Semantics match the reference ``apex/contrib/sparsity/sparse_masklib.py``:

- ``m4n2_1d``   — best 2-of-4 pattern per group of 4 along the pruned axis
  (reference ``mn_1d_best`` at sparse_masklib.py:37: scores every valid
  pattern with ``|w| @ pattern.T`` and takes the argmax).
- ``m4n2_2d_best`` — exhaustive best 4x4 block pattern such that the block
  is 2:4 along rows AND columns (reference ``mn_2d_best``
  sparse_masklib.py:122; valid patterns = 0/1 matrices with every row sum
  == n and every column sum <= n).
- ``m4n2_2d_greedy`` — greedy magnitude selection per 4x4 block with
  row/column quotas (reference ``mn_2d_greedy`` sparse_masklib.py:67).

Layout convention (deliberate TPU deviation, documented): the reference
views 2-D torch weights as (out, in) and prunes along dim 1 — the GEMM
reduction dim (sparse_masklib.py:157-162), and views OIHW convs as
(R*S*K, C) pruning along input channels C (:179-183).  JAX stores Linear
kernels as (in, out) and convs as HWIO, so ``create_mask`` prunes along
the *reduction* axis of the native JAX layout: axis 0 for 2-D (in, out)
kernels, axis 2 (I) for 4-D HWIO kernels.  The pruned-axis semantics are
identical; only the storage layout differs.
"""

from __future__ import annotations

from itertools import permutations

import numpy as np

__all__ = [
    "create_mask",
    "m4n2_1d",
    "m4n2_2d_best",
    "m4n2_2d_greedy",
    "mn_1d_best",
    "mn_2d_best",
    "mn_2d_greedy",
    "fill",
]


def fill(x) -> float:
    """Density (fraction of nonzeros) — reference sparse_masklib.py:9."""
    x = np.asarray(x)
    return float(np.count_nonzero(x)) / x.size


# ---------------------------------------------------------------------------
# pattern enumeration
# ---------------------------------------------------------------------------

_pattern_cache_1d: dict = {}
_pattern_cache_2d: dict = {}


def compute_valid_1d_patterns(m: int, n: int) -> np.ndarray:
    """All 0/1 vectors of length m with exactly n ones, shape (P, m)."""
    key = (m, n)
    if key not in _pattern_cache_1d:
        base = [1.0] * n + [0.0] * (m - n)
        pats = sorted(set(permutations(base)))
        _pattern_cache_1d[key] = np.array(pats, dtype=np.float32)
    return _pattern_cache_1d[key]


def compute_valid_2d_patterns(m: int, n: int) -> np.ndarray:
    """All m x m 0/1 blocks that are n-of-m along every row and at most
    n-of-m along every column, shape (P, m, m).

    (For m=4, n=2 the column constraint tightens to exactly 2 because the
    4 columns must absorb 8 ones — same effective set as the reference.)
    """
    key = (m, n)
    if key not in _pattern_cache_2d:
        rows = compute_valid_1d_patterns(m, n)  # (R, m)
        # Build up row by row, pruning by running column sums.
        blocks = [(np.zeros((0, m), np.float32), np.zeros(m, np.float32))]
        for _ in range(m):
            nxt = []
            for block, colsum in blocks:
                for r in rows:
                    cs = colsum + r
                    if np.all(cs <= n):
                        nxt.append((np.vstack([block, r]), cs))
            blocks = nxt
        _pattern_cache_2d[key] = np.stack([b for b, _ in blocks])
    return _pattern_cache_2d[key]


# ---------------------------------------------------------------------------
# mask search over a 2-D matrix, pruning along the LAST axis
# ---------------------------------------------------------------------------


def _pad_cols(mat: np.ndarray, m: int):
    """Zero-pad the last dim to a multiple of m (reference reshape_1d)."""
    cols = mat.shape[1]
    pad = (-cols) % m
    if pad:
        mat = np.concatenate(
            [mat, np.zeros((mat.shape[0], pad), mat.dtype)], axis=1
        )
    return mat, pad


def mn_1d_best(matrix: np.ndarray, m: int, n: int) -> np.ndarray:
    """Best m:n pattern per length-m group along the last axis."""
    patterns = compute_valid_1d_patterns(m, n)  # (P, m)
    mat = np.abs(np.asarray(matrix, dtype=np.float32))
    rows, cols = mat.shape
    mat, pad = _pad_cols(mat, m)
    groups = mat.reshape(-1, m)  # (G, m)
    scores = groups @ patterns.T  # (G, P)
    best = np.argmax(scores, axis=1)
    mask = patterns[best].reshape(rows, cols + pad)
    return mask[:, :cols].astype(bool)


def mn_2d_best(matrix: np.ndarray, m: int, n: int) -> np.ndarray:
    """Best m x m block pattern, 2:4 along both rows and columns.

    Requires both dims divisible by m (the reference's ``reshape_2d``
    implies the same); callers fall back to leaving edge blocks dense.
    """
    patterns = compute_valid_2d_patterns(m, n)  # (P, m, m)
    mat = np.abs(np.asarray(matrix, dtype=np.float32))
    rows, cols = mat.shape
    r_full, c_full = (rows // m) * m, (cols // m) * m
    mask = np.ones((rows, cols), dtype=bool)
    if r_full and c_full:
        blocks = (
            mat[:r_full, :c_full]
            .reshape(r_full // m, m, c_full // m, m)
            .transpose(0, 2, 1, 3)
            .reshape(-1, m * m)
        )  # (B, m*m)
        flat_pat = patterns.reshape(-1, m * m)  # (P, m*m)
        best = np.argmax(blocks @ flat_pat.T, axis=1)  # (B,)
        chosen = flat_pat[best].reshape(
            r_full // m, c_full // m, m, m
        )
        mask[:r_full, :c_full] = (
            chosen.transpose(0, 2, 1, 3).reshape(r_full, c_full) > 0
        )
    return mask


def mn_2d_greedy(matrix: np.ndarray, m: int, n: int) -> np.ndarray:
    """Greedy per-block selection with row/column quotas.

    Matches the reference algorithm (sparse_masklib.py:67-96): within each
    m x m block, admit entries in decreasing |w| order while each row and
    column has fewer than n admitted entries.  Edge regions not covered by
    a full block stay dense (mask == 1), like the reference.
    """
    mat = np.abs(np.asarray(matrix, dtype=np.float32))
    rows, cols = mat.shape
    r_full, c_full = (rows // m) * m, (cols // m) * m
    mask = np.ones((rows, cols), dtype=bool)
    if not (r_full and c_full):
        return mask
    blocks = (
        mat[:r_full, :c_full]
        .reshape(r_full // m, m, c_full // m, m)
        .transpose(0, 2, 1, 3)
        .reshape(-1, m, m)
    )  # (B, m, m)
    B = blocks.shape[0]
    order = np.argsort(-blocks.reshape(B, -1), axis=1)  # descending |w|
    bmask = np.zeros((B, m, m), dtype=bool)
    row_cnt = np.zeros((B, m), dtype=np.int32)
    col_cnt = np.zeros((B, m), dtype=np.int32)
    bidx = np.arange(B)
    for k in range(m * m):
        lin = order[:, k]
        r, c = lin // m, lin % m
        ok = (row_cnt[bidx, r] < n) & (col_cnt[bidx, c] < n)
        bmask[bidx[ok], r[ok], c[ok]] = True
        row_cnt[bidx[ok], r[ok]] += 1
        col_cnt[bidx[ok], c[ok]] += 1
    mask[:r_full, :c_full] = (
        bmask.reshape(r_full // m, c_full // m, m, m)
        .transpose(0, 2, 1, 3)
        .reshape(r_full, c_full)
    )
    return mask


def m4n2_1d(mat, density=0.5):
    return mn_1d_best(mat, 4, 2)


def m4n2_2d_best(mat, density=0.5):
    return mn_2d_best(mat, 4, 2)


def m4n2_2d_greedy(mat, density=0.5):
    return mn_2d_greedy(mat, 4, 2)


_PATTERNS = {
    "m4n2_1d": m4n2_1d,
    "m4n2_2d_best": m4n2_2d_best,
    "m4n2_2d_greedy": m4n2_2d_greedy,
}


# ---------------------------------------------------------------------------
# shape handling — reference create_mask (sparse_masklib.py:145)
# ---------------------------------------------------------------------------


def create_mask(tensor, pattern: str = "m4n2_1d", density: float = 0.5):
    """Return a boolean mask with the requested m:n structure.

    Accepts numpy or jax arrays; always returns a host numpy bool array of
    the tensor's shape (the caller multiplies on device).

    Shape handling (reduction-axis pruning in native JAX layouts — see
    module docstring):

    - 1-D ``(n,)``          → viewed as ``(1, n)``, pruned along n
    - 2-D ``(in, out)``     → pruned along in  (view: transpose)
    - 3-D ``(b, in, out)``  → pruned along in  (per-batch transpose view)
    - 4-D ``(H, W, I, O)``  → pruned along I   (view: ``(H*W*O, I)``)
    """
    fn = _PATTERNS.get(pattern)
    if fn is None:
        raise ValueError(
            f"unknown sparsity pattern {pattern!r}; "
            f"one of {sorted(_PATTERNS)}"
        )
    t = np.asarray(tensor, dtype=np.float32)
    shape = t.shape
    if t.ndim == 1:
        return fn(t.reshape(1, -1), density).reshape(shape)
    if t.ndim == 2:
        # (in, out): prune along the reduction dim (axis 0).
        return fn(t.T, density).T.reshape(shape)
    if t.ndim == 3:
        b, i, o = shape
        view = t.transpose(0, 2, 1).reshape(b * o, i)
        mask = fn(view, density)
        return (
            mask.reshape(b, o, i).transpose(0, 2, 1).reshape(shape)
        )
    if t.ndim == 4:
        h, w, i, o = shape
        view = t.transpose(0, 1, 3, 2).reshape(h * w * o, i)
        mask = fn(view, density)
        return (
            mask.reshape(h, w, o, i).transpose(0, 1, 3, 2).reshape(shape)
        )
    raise ValueError(f"cannot sparsify tensor of rank {t.ndim}")
