"""ASP — Automatic SParsity (2:4 structured) for the TPU framework.

TPU rebuild of ``apex.contrib.sparsity`` (reference: asp.py:28,
sparse_masklib.py:145, permutation_lib.py:42).  The mask search is
host-side numpy exactly like the reference; mask *application* is a pure
``params * mask`` multiply that XLA fuses into the optimizer update.
"""

from .sparse_masklib import create_mask  # noqa: F401
from .asp import ASP, sparsify_optimizer  # noqa: F401
from .permutation_lib import (  # noqa: F401
    sum_after_2_to_4,
    apply_2_to_4,
    search_for_good_permutation,
    Permutation,
)
