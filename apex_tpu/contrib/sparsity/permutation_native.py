"""ctypes loader for the native permutation-search kernels.

The reference ships CUDA search kernels and falls back to a slow numpy
path when they are absent (permutation_search_kernels/
permutation_utilities.py:10-16 try-import).  Same shape here: a small
C++ shared library (apex_tpu/csrc/permutation_search.cpp) built lazily
with g++ and cached next to the source; every entry point degrades to
the vectorized-numpy implementation when the toolchain is unavailable
(``available()`` reports which path is active, and
``APEX_TPU_DISABLE_NATIVE=1`` forces the fallback).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "csrc", "permutation_search.cpp")

_lock = threading.Lock()
_lib = None
_tried = False


def _cache_dir() -> str:
    # Prefer the package's csrc/ dir; fall back to a per-user cache when
    # the install is read-only (e.g. root-owned site-packages).
    pkg_dir = os.path.dirname(_SRC)
    if os.access(pkg_dir, os.W_OK):
        return pkg_dir
    import tempfile
    d = os.path.join(tempfile.gettempdir(),
                     f"apex_tpu-permsearch-{os.getuid()}")
    os.makedirs(d, exist_ok=True)
    return d


def _lib_path() -> str:
    # Cache keyed on a hash of the source (mtimes do not survive a git
    # checkout); the .so itself is never committed.
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    cache = _cache_dir()
    # Prune caches left by previous source revisions.
    for stale in os.listdir(cache):
        if (stale.startswith("libpermsearch-") and stale.endswith(".so")
                and stale != f"libpermsearch-{digest}.so"):
            try:
                os.remove(os.path.join(cache, stale))
            except OSError:
                pass
    return os.path.join(cache, f"libpermsearch-{digest}.so")


def _build(lib_path: str) -> bool:
    # Compile to a temp name then rename: the build must be atomic so a
    # concurrent process never CDLLs a half-written library.
    tmp = f"{lib_path}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib_path)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("APEX_TPU_DISABLE_NATIVE") == "1":
            return None
        if not os.path.exists(_SRC):
            return None
        lib_path = _lib_path()
        if not os.path.exists(lib_path) and not _build(lib_path):
            return None
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            # Stale/foreign-arch cache: rebuild once and retry.
            if not _build(lib_path):
                return None
            try:
                lib = ctypes.CDLL(lib_path)
            except OSError:
                return None
        f64, i64, i32p = ctypes.c_double, ctypes.c_int64, ctypes.POINTER(
            ctypes.c_int32)
        f32p, f64p = ctypes.POINTER(ctypes.c_float), ctypes.POINTER(f64)
        lib.ps_sum_after_2_to_4.restype = f64
        lib.ps_sum_after_2_to_4.argtypes = [f32p, i64, i64]
        lib.ps_score_permutations.restype = None
        lib.ps_score_permutations.argtypes = [f32p, i64, i64, i32p, i64,
                                              f64p]
        lib.ps_try_swap_improvement.restype = f64
        lib.ps_try_swap_improvement.argtypes = [f32p, i64, i64, i64, i64]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _f32c(mat: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(mat, dtype=np.float32)


def sum_after_2_to_4(matrix: np.ndarray) -> float | None:
    lib = _load()
    if lib is None:
        return None
    m = _f32c(matrix)
    return float(lib.ps_sum_after_2_to_4(
        m.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        m.shape[0], m.shape[1]))


def score_permutations(matrix: np.ndarray,
                       perms: np.ndarray) -> np.ndarray | None:
    """scores[p] = retained magnitude of matrix[:, perms[p]]."""
    lib = _load()
    if lib is None:
        return None
    m = _f32c(matrix)
    p = np.ascontiguousarray(perms, dtype=np.int32)
    out = np.empty((p.shape[0],), np.float64)
    lib.ps_score_permutations(
        m.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        m.shape[0], m.shape[1],
        p.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        p.shape[0],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out


def try_swap_improvement(matrix: np.ndarray, a: int, b: int) -> float | None:
    lib = _load()
    if lib is None:
        return None
    m = _f32c(matrix)
    return float(lib.ps_try_swap_improvement(
        m.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        m.shape[0], m.shape[1], a, b))
