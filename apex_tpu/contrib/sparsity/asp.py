"""ASP — model/optimizer patcher for 2:4 structured sparse training.

TPU rebuild of ``apex/contrib/sparsity/asp.py:28``.  The reference is a
class-method singleton that (1) registers mask buffers on eligible
``nn.Module``s, (2) monkey-patches ``optimizer.step`` to multiply grads by
the mask before the step and params after it, and (3) computes masks with
``sparse_masklib.create_mask``.

JAX is functional, so the same three operations act on pytrees:

1. :meth:`ASP.init_model_for_pruning` records which param-tree leaves are
   prunable (path predicate + the reference's tensor-core shape gates).
2. :func:`sparsify_optimizer` wraps any optax-style
   ``GradientTransformation`` so its updates (a) zero masked grads and
   (b) land exactly on the masked manifold: the returned update is
   ``u' = (p + u) * mask - p`` — after ``apply_updates`` params are
   masked bit-exactly, matching the reference's post-step ``p.mul_(mask)``
   (asp.py:188-201) without a second pass.  Masks ride in the optimizer
   state as a ``{path: bool array}`` dict (a stable jit-able pytree), so
   refreshed masks swap in via ``state._replace(masks=...)`` with no
   retracing.
3. :meth:`ASP.compute_sparse_masks` runs the (optional) channel
   permutation search then the mask search, prunes the params, and
   stashes pruned values when ``allow_recompute_mask`` — mirroring
   asp.py:204-254, including "checkpoints hold zeros for pruned weights".

The class-method singleton API is kept for parity; :meth:`ASP.reset` is a
TPU addition (tests need to re-init, the reference asserts one init per
process).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...optimizers._common import GradientTransformation
from .sparse_masklib import create_mask

__all__ = ["ASP", "sparsify_optimizer", "SparseState"]


def _path_name(path) -> str:
    return "/".join(
        str(getattr(p, "key", getattr(p, "idx", p))) for p in path
    )


def _flatten_with_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return [(_path_name(p), leaf) for p, leaf in flat], treedef


def _map_masked(fn, tree, masks: dict, *others):
    """Map ``fn(leaf, mask_or_None, *other_leaves)`` over ``tree``,
    looking masks up by flattened path name.  ``others`` must share
    ``tree``'s structure."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    other_leaves = [jax.tree_util.tree_leaves(o) for o in others]
    out = []
    for i, (path, leaf) in enumerate(flat):
        m = masks.get(_path_name(path))
        out.append(fn(leaf, m, *(ol[i] for ol in other_leaves)))
    return jax.tree_util.tree_unflatten(treedef, out)


def _default_whitelist(name: str, leaf) -> bool:
    """Prunable by default: rank>=2 float leaves (Linear/Conv kernels).
    Mirrors the reference whitelist of Linear/Conv1d/2d/3d weights
    (asp.py:42,98-100); biases and norm scales are rank-1 and excluded."""
    return hasattr(leaf, "ndim") and leaf.ndim >= 2


def _tc_shape_ok(leaf) -> bool:
    """The reference auto-skips tensors whose 2-D view is not a multiple
    of (8, 16) — the sparse-MMA tile gate (asp.py:136-141).  The TPU
    analog keeps the same gate (it also guarantees the pruned reduction
    dim tiles onto 8 sublanes), with the dims read from the JAX layout's
    2-D pruning view (see sparse_masklib.create_mask)."""
    shape = leaf.shape
    if len(shape) == 2:
        i, o = shape  # JAX (in, out); the view pruned is (out, in)
        return o % 8 == 0 and i % 16 == 0
    if len(shape) == 3:
        b, i, o = shape
        return (b * o) % 8 == 0 and i % 16 == 0
    if len(shape) == 4:
        h, w, i, o = shape
        return (h * w * o) % 8 == 0 and i % 16 == 0
    return True


class SparseState(NamedTuple):
    """Optimizer-state wrapper: inner state + ``{path: mask}`` dict."""

    inner: Any
    masks: dict


def sparsify_optimizer(
    tx: GradientTransformation,
    masks: Optional[dict] = None,
) -> GradientTransformation:
    """Wrap an optax-style transformation so training stays on the 2:4
    manifold (functional analog of ``ASP.init_optimizer_for_pruning``,
    reference asp.py:176-201).

    ``masks`` maps flattened param paths (as produced by
    ``ASP.compute_sparse_masks``) to bool arrays; params not in the dict
    stay dense.  When ``masks`` is None, the masks current in the ASP
    singleton at ``init`` time are captured (all-ones before
    ``compute_sparse_masks`` — the reference's "sparsity is off by
    default" behavior, asp.py:55).
    """

    def init(params):
        m = masks if masks is not None else ASP.current_masks()
        m = {k: jnp.asarray(v) for k, v in m.items()}
        return SparseState(inner=tx.init(params), masks=m)

    def update(grads, state, params=None, **kw):
        def mask_grad(g, m):
            if m is None or g is None:
                return g
            return g * m.astype(g.dtype)

        masked_grads = _map_masked(mask_grad, grads, state.masks)
        updates, inner = tx.update(masked_grads, state.inner, params, **kw)
        if params is not None:

            def land_on_manifold(u, m, p):
                if m is None or u is None:
                    return u
                mm = m.astype(p.dtype)
                return (p + u.astype(p.dtype)) * mm - p

            updates = _map_masked(
                land_on_manifold, updates, state.masks, params
            )
        return updates, SparseState(inner=inner, masks=state.masks)

    return GradientTransformation(init=init, update=update)


class ASP:
    """Class-method singleton facade matching the reference API."""

    __initialized = False
    __verbosity = 0
    __sparse_names: list = []
    __calculate_mask: Optional[Callable] = None
    __allow_recompute_mask = False
    __pruned_values: dict = {}
    __masks: dict = {}
    __permutation_groups: list = []

    # -- reference API ------------------------------------------------

    @classmethod
    def init_model_for_pruning(
        cls,
        params,
        mask_calculator="m4n2_1d",
        verbosity: int = 3,
        whitelist: Callable[[str, Any], bool] = _default_whitelist,
        allowed_layer_names=None,
        disallowed_layer_names=(),
        allow_recompute_mask: bool = False,
        allow_permutation: bool = False,
        permutation_groups=None,
    ):
        """Record which leaves of ``params`` will be pruned.

        ``whitelist`` is a predicate ``(path_name, leaf) -> bool`` — the
        functional stand-in for the reference's module-type whitelist
        (asp.py:42).  ``permutation_groups`` (TPU deviation, see
        permutation_lib.Permutation) is the explicit replacement for
        torch.fx graph tracing; each group is a list of
        ``(path, axis, kind)``.
        """
        assert not cls.__initialized, "ASP has been initialized already."
        cls.__initialized = True
        cls.__verbosity = verbosity

        if isinstance(mask_calculator, str):
            pattern = mask_calculator
            cls.__calculate_mask = lambda t: create_mask(t, pattern)
        else:
            cls.__calculate_mask = mask_calculator
        cls.__allow_recompute_mask = allow_recompute_mask
        cls.__permutation_groups = (
            list(permutation_groups or []) if allow_permutation else []
        )
        if cls.__permutation_groups and allow_recompute_mask:
            # A second compute_sparse_masks would re-permute the already
            # permuted params while the stashed pruned values stay in the
            # old channel order — restoring would corrupt the weights.
            # The reference applies its permutation once, offline.
            raise ValueError(
                "allow_recompute_mask cannot be combined with "
                "permutation_groups: recomputing masks would re-permute "
                "channels while stashed pruned values keep the old order"
            )

        flat, _ = _flatten_with_paths(params)
        cls.__sparse_names = []
        for name, leaf in flat:
            if not whitelist(name, leaf):
                continue
            if allowed_layer_names is not None and name not in allowed_layer_names:
                continue
            if name in disallowed_layer_names:
                continue
            if not _tc_shape_ok(leaf):
                if verbosity >= 1:
                    print(
                        f"[ASP] Auto skipping pruning {name} of "
                        f"size={tuple(leaf.shape)} for sparsity"
                    )
                continue
            if verbosity >= 3:
                print(
                    f"[ASP] Sparsifying {name} of size={tuple(leaf.shape)} "
                    f"and type={leaf.dtype} for sparsity"
                )
            cls.__sparse_names.append(name)
            cls.__masks[name] = np.ones(leaf.shape, dtype=bool)

    @classmethod
    def already_init_asp_model(cls) -> bool:
        return cls.__initialized

    @classmethod
    def init_optimizer_for_pruning(cls, tx: GradientTransformation):
        """Return the sparsity-preserving wrapped optimizer (functional
        analog of monkey-patching ``optimizer.step``, asp.py:176)."""
        assert cls.__calculate_mask is not None, (
            "Call ASP.init_model_for_pruning before "
            "ASP.init_optimizer_for_pruning."
        )
        return sparsify_optimizer(tx, masks=None)

    @classmethod
    def compute_sparse_masks(cls, params):
        """Run permutation search + mask search; prune ``params``.

        Returns ``(pruned_params, masks)`` where ``masks`` is a
        ``{path: bool array}`` dict for :func:`sparsify_optimizer` (or to
        swap into an existing ``SparseState``).
        """
        assert cls.__calculate_mask is not None, "ASP not initialized."
        from .permutation_lib import Permutation

        # Reference compute_sparse_masks steps 1-3 (asp.py:209-239):
        # offline channel permutation before masking.
        host = jax.tree_util.tree_map(np.asarray, params)
        for group in cls.__permutation_groups:
            host, _perm = Permutation.search_and_apply(host, group)

        flat, treedef = _flatten_with_paths(host)
        new_leaves = []
        for name, leaf in flat:
            if name not in cls.__sparse_names:
                new_leaves.append(jnp.asarray(leaf))
                continue
            prev_mask = cls.__masks.get(name)
            arr = np.asarray(leaf, dtype=np.float32)
            if prev_mask is not None and prev_mask.sum() < prev_mask.size:
                # recomputing: restore dense weights first (asp.py:241-245)
                assert cls.__allow_recompute_mask, (
                    "Unable to restore dense parameter because "
                    "allow_recompute_mask == False"
                )
                arr = arr + cls.__pruned_values[name]
            mask = cls.__calculate_mask(arr)
            cls.__masks[name] = mask
            if cls.__allow_recompute_mask:
                cls.__pruned_values[name] = arr * (~mask)
            new_leaves.append(
                jnp.asarray((arr * mask).astype(np.asarray(leaf).dtype))
            )
            if cls.__verbosity >= 2:
                pct = 100.0 - 100.0 * mask.sum() / mask.size
                print(
                    f"[ASP] Enabled {pct:.2f}% sparsity for {name} "
                    f"of size={tuple(leaf.shape)}"
                )
        new_params = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return new_params, cls.current_masks()

    @classmethod
    def restore_pruned_weights(cls, params):
        """Disable sparsity; add stashed pruned values back
        (reference asp.py:256-269).  Requires allow_recompute_mask."""
        flat, treedef = _flatten_with_paths(
            jax.tree_util.tree_map(np.asarray, params)
        )
        out = []
        for name, leaf in flat:
            if name in cls.__sparse_names:
                mask = cls.__masks[name]
                if mask.sum() < mask.size:
                    assert name in cls.__pruned_values, (
                        "Unable to restore dense parameter because "
                        "allow_recompute_mask == False"
                    )
                    leaf = leaf + cls.__pruned_values[name].astype(leaf.dtype)
                    cls.__masks[name] = np.ones(leaf.shape, dtype=bool)
                    cls.__pruned_values[name] = np.zeros_like(leaf)
            out.append(jnp.asarray(leaf))
        return jax.tree_util.tree_unflatten(treedef, out)

    @classmethod
    def is_sparsity_enabled(cls) -> bool:
        """True iff every tracked mask is exactly 50% dense
        (reference asp.py:271-290 asserts all-dense or all-half)."""
        total, sp100, sp50 = 0, 0, 0
        for name in cls.__sparse_names:
            m = cls.__masks[name]
            total += 1
            s = m.sum()
            if s == m.size:
                sp100 += 1
            elif 2 * s == m.size:
                sp50 += 1
        assert total in (sp100, sp50), "Inconsistent model sparsity"
        return total != 0 and total == sp50

    @classmethod
    def prune_trained_model(cls, params, tx):
        """One-call recipe (reference asp.py:292-297): init, compute
        masks, wrap optimizer.  Returns (pruned_params, wrapped_tx)."""
        cls.init_model_for_pruning(
            params, mask_calculator="m4n2_1d", verbosity=2,
            allow_recompute_mask=False,
        )
        pruned, masks = cls.compute_sparse_masks(params)
        return pruned, sparsify_optimizer(tx, masks)

    # -- TPU additions ------------------------------------------------

    @classmethod
    def current_masks(cls) -> dict:
        """Current masks as a ``{path: bool ndarray}`` dict."""
        return {n: cls.__masks[n] for n in cls.__sparse_names}

    @classmethod
    def sparse_parameter_names(cls):
        return list(cls.__sparse_names)

    @classmethod
    def reset(cls):
        """Forget all state so tests can re-init (TPU addition; the
        reference allows a single init per process)."""
        cls.__initialized = False
        cls.__sparse_names = []
        cls.__calculate_mask = None
        cls.__allow_recompute_mask = False
        cls.__pruned_values = {}
        cls.__masks = {}
        cls.__permutation_groups = []
