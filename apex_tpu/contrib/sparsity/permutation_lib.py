"""Accuracy-preserving input-channel permutation search for 2:4 sparsity.

TPU rebuild of ``apex/contrib/sparsity/permutation_lib.py:42`` and
``permutation_search_kernels/``.  Permuting input channels before pruning
changes which weights land in the same group-of-4, so a good permutation
raises the magnitude retained by the 2:4 mask; the inverse permutation is
absorbed into the *producer* layer's output channels so the network
function is unchanged.

The reference discovers which tensors must co-permute by tracing the
model with torch.fx (permutation_lib.py ``build_offline_permutation_graph``).
A jitted JAX model has no module graph to trace, so this port takes the
coupling explicitly: a *permutation group* is a list of ``(param, axis,
kind)`` entries sharing one channel dimension — see :class:`Permutation`.

Search strategies mirror
``permutation_search_kernels/call_permutation_search_kernels.py``:

- ``exhaustive`` (default, options ``stripe_group_size=8``,
  ``escape_attempts=100``): bounded exhaustive search over windows of
  stripes (groups of 4 channels), iterated to a fixed point, with random
  escape swaps (reference exhaustive_search.py:312 ``Exhaustive_Search``).
- ``progressive channel swap``: random cross-stripe swaps kept when they
  improve retained magnitude, until a time limit.

All search kernels are vectorized numpy (the reference's CUDA search
kernels exist only to accelerate this same host-side math; on TPU the
search stays on host — it runs once, offline).
"""

from __future__ import annotations

import time
from itertools import permutations as _permutations

import numpy as np

__all__ = [
    "sum_after_2_to_4",
    "apply_2_to_4",
    "try_swap",
    "exhaustive_search",
    "progressive_channel_swap",
    "search_for_good_permutation",
    "Permutation",
]


def _group_view(matrix: np.ndarray) -> np.ndarray:
    """abs(matrix) reshaped to (rows, n_groups, 4); trailing columns that
    do not fill a group of 4 are ignored (reference sum_after_2_to_4
    iterates ``range(0, cols, 4)`` over full groups only)."""
    mat = np.abs(np.asarray(matrix, dtype=np.float32))
    cols = (mat.shape[1] // 4) * 4
    return mat[:, :cols].reshape(mat.shape[0], -1, 4)


def sum_after_2_to_4(matrix: np.ndarray) -> float:
    """Total magnitude retained if 2:4 pruning were applied
    (reference permutation_utilities.py ``sum_after_2_to_4``).

    Dispatches to the native C++ kernel when built (the reference's
    CUDA-search-kernel analog — see permutation_native.py); numpy
    otherwise."""
    from . import permutation_native as _native

    result = _native.sum_after_2_to_4(np.asarray(matrix, np.float32))
    if result is not None:
        return result
    g = _group_view(matrix)
    top2 = np.partition(g, 2, axis=-1)[..., 2:]
    return float(top2.sum())


def apply_2_to_4(matrix: np.ndarray) -> np.ndarray:
    """Zero the 2 smallest-|w| entries of every group of 4 (reference
    permutation_utilities.py ``apply_2_to_4``)."""
    mat = np.array(matrix, dtype=np.float32, copy=True)
    cols = (mat.shape[1] // 4) * 4
    g = mat[:, :cols].reshape(mat.shape[0], -1, 4)
    order = np.argsort(np.abs(g), axis=-1)
    rows, ngroups = g.shape[:2]
    ridx = np.arange(rows)[:, None]
    gidx = np.arange(ngroups)[None, :]
    g[ridx, gidx, order[..., 0]] = 0.0
    g[ridx, gidx, order[..., 1]] = 0.0
    mat[:, :cols] = g.reshape(mat.shape[0], cols)
    return mat


def _stripe_sums(matrix: np.ndarray) -> np.ndarray:
    """Retained magnitude per stripe (group of 4 columns), shape (G,)."""
    g = _group_view(matrix)
    top2 = np.partition(g, 2, axis=-1)[..., 2:]
    return top2.sum(axis=(0, 2))


def try_swap(matrix: np.ndarray, dst: int, src: int) -> float:
    """Retained-magnitude improvement if columns src/dst were swapped.
    Only the two affected stripes are re-scored (reference
    permutation_utilities.py ``try_swap``; unlike the reference this
    returns only the improvement — the callers never use the total, and
    computing it would cost a full-matrix rescore per probe)."""
    g_src, g_dst = src // 4, dst // 4
    if g_src == g_dst:
        return 0.0
    cols = [4 * g_src + i for i in range(4)] + [4 * g_dst + i for i in range(4)]
    sub = np.array(matrix[:, cols], copy=True)
    before = sum_after_2_to_4(sub)
    # positions of src/dst inside the 8-col sub-matrix
    p_src = cols.index(src)
    p_dst = cols.index(dst)
    sub[:, [p_src, p_dst]] = sub[:, [p_dst, p_src]]
    return sum_after_2_to_4(sub) - before


# ---------------------------------------------------------------------------
# strategy: bounded exhaustive over stripe windows
# ---------------------------------------------------------------------------

_unique_perm_cache: dict = {}


def _unique_group_permutations(c: int) -> np.ndarray:
    """Unique permutations of c columns into groups of 4 where in-group
    order and group order don't matter (canonical form: groups sorted
    internally, groups sorted by first element, element 0 fixed first —
    reference exhaustive_search.py ``generate_unique_combinations``)."""
    if c in _unique_perm_cache:
        return _unique_perm_cache[c]
    assert c % 4 == 0
    results: list = []

    def rec(built, remaining):
        if not remaining:
            results.append(list(built))
            return
        for i, col in enumerate(remaining):
            if len(built) % 4 == 0:
                # new group: canonical iff everything smaller is placed and
                # this group leader exceeds the previous group leader
                if any(v < col for v in remaining if v != col):
                    # some smaller value is unplaced -> not canonical
                    if min(remaining) != col:
                        continue
                if built and col <= built[-4]:
                    continue
            elif col <= built[-1]:
                continue
            built.append(col)
            rest = remaining[:i] + remaining[i + 1 :]
            rec(built, rest)
            built.pop()

    rec([], list(range(c)))
    perms = np.array(results, dtype=np.int64)
    _unique_perm_cache[c] = perms
    return perms


def _best_window_permutation(sub: np.ndarray) -> np.ndarray:
    """Exhaustively find the best unique grouping of the window's columns
    (native batch scorer when built; vectorized numpy otherwise)."""
    from . import permutation_native as _native

    c = sub.shape[1]
    perms = _unique_group_permutations(c)  # (P, c)
    scores = _native.score_permutations(
        np.asarray(sub, np.float32), perms)
    if scores is None:
        permuted = np.abs(sub[:, perms])  # (rows, P, c)
        g = permuted.reshape(sub.shape[0], perms.shape[0], c // 4, 4)
        top2 = np.partition(g, 2, axis=-1)[..., 2:]
        scores = top2.sum(axis=(0, 2, 3))  # (P,)
    return perms[int(np.argmax(scores))]


def exhaustive_search(
    matrix: np.ndarray,
    stripe_group_size: int = 8,
    escape_attempts: int = 100,
    rng: np.random.Generator | None = None,
):
    """Bounded exhaustive permutation search.

    Slides a window of ``stripe_group_size`` columns (i.e. window of
    stripes) over all stripe pairs/sets, exhaustively re-grouping each
    window, repeating until no window improves; then uses up to
    ``escape_attempts`` random cross-stripe swaps to escape local optima
    (accepted only if they improve).  Returns
    ``(permuted_matrix, seconds, permutation)`` like the reference's
    ``Exhaustive_Search`` (exhaustive_search.py:312).
    """
    t0 = time.perf_counter()
    mat = np.array(matrix, dtype=np.float32, copy=True)
    cols = mat.shape[1]
    perm = np.arange(cols)
    if cols % 4 != 0 or cols < 8:
        return mat, time.perf_counter() - t0, perm
    n_stripes = cols // 4
    win_stripes = max(2, stripe_group_size // 4)
    rng = rng or np.random.default_rng(0)

    def window_pass() -> bool:
        improved = False
        from itertools import combinations

        for stripes in combinations(range(n_stripes), win_stripes):
            idx = np.concatenate([np.arange(4 * s, 4 * s + 4) for s in stripes])
            sub = mat[:, idx]
            base = sum_after_2_to_4(sub)
            best = _best_window_permutation(sub)
            if sum_after_2_to_4(sub[:, best]) > base + 1e-7:
                mat[:, idx] = sub[:, best]
                perm[idx] = perm[idx][best]
                improved = True
        return improved

    while window_pass():
        pass
    for _ in range(escape_attempts):
        src = int(rng.integers(cols))
        dst = int(rng.integers(cols))
        if src // 4 == dst // 4:
            continue
        improvement = try_swap(mat, dst, src)
        if improvement > 1e-9:
            mat[:, [src, dst]] = mat[:, [dst, src]]
            perm[[src, dst]] = perm[[dst, src]]
            while window_pass():
                pass
    return mat, time.perf_counter() - t0, perm


def progressive_channel_swap(
    matrix: np.ndarray,
    search_time_limit: float = 60.0,
    improvement_threshold: float = 1e-9,
    rng: np.random.Generator | None = None,
):
    """Random swap search until the time limit (reference
    call_permutation_search_kernels.py 'progressive channel swap')."""
    t0 = time.perf_counter()
    mat = np.array(matrix, dtype=np.float32, copy=True)
    cols = mat.shape[1]
    perm = np.arange(cols)
    rng = rng or np.random.default_rng(0)
    while time.perf_counter() - t0 < search_time_limit:
        src = int(rng.integers(cols))
        dst = int(rng.integers(cols))
        if src // 4 == dst // 4:
            continue
        improvement = try_swap(mat, dst, src)
        if improvement > improvement_threshold:
            mat[:, [src, dst]] = mat[:, [dst, src]]
            perm[[src, dst]] = perm[[dst, src]]
    return mat, time.perf_counter() - t0, perm


def search_for_good_permutation(matrix, options: dict | None = None):
    """Strategy dispatch — mirror of the reference's
    ``accelerated_search_for_good_permutation``
    (call_permutation_search_kernels.py:5).  Returns the permutation
    sequence (list of column indices)."""
    options = dict(options or {})
    strategy = options.setdefault("strategy", "exhaustive")
    mat = np.asarray(matrix, dtype=np.float32)
    if strategy == "exhaustive":
        _, _, perm = exhaustive_search(
            mat,
            stripe_group_size=options.get("stripe_group_size", 8),
            escape_attempts=options.get("escape_attempts", 100),
        )
    elif strategy == "progressive channel swap":
        _, _, perm = progressive_channel_swap(
            mat,
            search_time_limit=options.get(
                "progressive_search_time_limit", 60
            ),
            improvement_threshold=options.get(
                "improvement_threshold", 1e-9
            ),
        )
    elif strategy == "user defined":
        perm = np.arange(mat.shape[1])
    else:
        raise ValueError(f"unknown permutation strategy {strategy!r}")
    return list(map(int, perm))


# ---------------------------------------------------------------------------
# pytree-level application
# ---------------------------------------------------------------------------


class Permutation:
    """Apply one channel permutation consistently across coupled params.

    A *group* is a list of ``(path, axis, kind)`` where ``kind`` is:

    - ``"consumer"`` — the axis indexes the channels being permuted (the
      pruned layer's reduction axis, or a BatchNorm stat vector); the
      param is gathered with ``perm`` along ``axis``.
    - ``"producer"`` — the axis is the upstream layer's output-channel
      axis; it absorbs the *inverse* permutation so the composition is
      the identity function (reference permutation_lib.py
      ``apply_offline_permutation``).

    Since producer takes ``perm`` on its output exactly when consumer
    takes ``perm`` on its input, both gather with the same index list —
    the distinction is only documentation of intent.
    """

    @staticmethod
    def permute_axis(array, axis: int, perm) -> np.ndarray:
        return np.take(np.asarray(array), np.asarray(perm), axis=axis)

    @staticmethod
    def apply(params: dict, group, perm):
        """Return a copy of the (nested) ``params`` dict with every entry
        in ``group`` permuted.  ``group`` entries are
        ``(path_tuple_or_str, axis, kind)``."""
        import copy

        out = copy.deepcopy(params)
        for path, axis, _kind in group:
            keys = path.split("/") if isinstance(path, str) else list(path)
            node = out
            for k in keys[:-1]:
                node = node[k]
            node[keys[-1]] = Permutation.permute_axis(
                node[keys[-1]], axis, perm
            )
        return out

    @staticmethod
    def search_and_apply(params: dict, group, options: dict | None = None):
        """Search a permutation on the concatenation of the group's
        consumer matrices (reference concatenates all consumers' 2-D
        views along rows — permutation_lib.py ``find_permutations``),
        then apply it to every entry.  Returns (new_params, perm)."""
        views = []
        for path, axis, kind in group:
            if kind != "consumer":
                continue
            keys = path.split("/") if isinstance(path, str) else list(path)
            node = params
            for k in keys:
                node = node[k]
            arr = np.asarray(node, dtype=np.float32)
            if arr.ndim == 1:
                continue  # BN-style stat vectors don't inform the search
            arr = np.moveaxis(arr, axis, -1)
            views.append(arr.reshape(-1, arr.shape[-1]))
        if not views:
            return params, list(range(0))
        matrix = np.concatenate(views, axis=0)
        perm = search_for_good_permutation(matrix, options)
        return Permutation.apply(params, group, perm), perm
