from .bottleneck import (  # noqa: F401
    Bottleneck,
    SpatialBottleneck,
    bottleneck_forward,
    frozen_bn_scale_bias,
    init_bottleneck_params,
    spatial_bottleneck_forward,
)
