"""ResNet bottleneck block + spatially-parallel variant.

TPU rebuild of ``apex.contrib.bottleneck`` (reference: bottleneck.py:134
``Bottleneck``, :603 ``SpatialBottleneck``, csrc/bottleneck/bottleneck.cpp
— cudnn-fused conv+frozen-BN+ReLU chains, with the spatial variant
splitting H across GPUs and exchanging 3x3-conv halos through CUDA-IPC
peer memory).

TPU shape:

- Layout is native NHWC (the reference's fast path is explicit_nhwc);
  convs are ``lax.conv_general_dilated`` which XLA fuses with the
  frozen-BN affine and ReLU epilogues — the same fusion the cudnn v8
  graph builds by hand.
- Frozen BN folds to a per-channel scale/bias
  (``scale = gamma / sqrt(var + eps)``, ``bias = beta - mean * scale``) —
  reference ``FrozenBatchNorm2d.get_scale_bias`` (bottleneck.py:43-52).
- ResNet v1.5 note: the reference deliberately places the stride on the
  first 1x1 conv (bottleneck.py:135-140 "here we put it at 1x1");
  matched here.
- The spatial variant shards H over a mesh axis inside ``shard_map``;
  the 3x3 conv's one-row dependency crosses shard boundaries via
  ``halo_exchange_1d`` (ppermute) instead of peer-memory push/pull
  (reference spatial_method=1, bottleneck.py:267+).
"""

from __future__ import annotations

import math


import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.contrib.peer_memory import halo_exchange_1d

__all__ = [
    "frozen_bn_scale_bias",
    "init_bottleneck_params",
    "bottleneck_forward",
    "spatial_bottleneck_forward",
    "Bottleneck",
    "SpatialBottleneck",
]


def frozen_bn_scale_bias(bn: dict, eps: float = 1e-5):
    """(scale, bias) from frozen-BN stats — reference
    FrozenBatchNorm2d.get_scale_bias (bottleneck.py:43-52)."""
    scale = bn["weight"] / jnp.sqrt(bn["running_var"] + eps)
    bias = bn["bias"] - bn["running_mean"] * scale
    return scale, bias


def _conv(x, w, stride=1, padding="SAME"):
    """NHWC x HWIO convolution."""
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _kaiming_uniform(key, shape, a=1.0):
    """kaiming_uniform_(w, a=1) over HWIO kernels (reference
    bottleneck.py:181-183 init)."""
    h, w, i, _ = shape
    fan_in = h * w * i
    gain = math.sqrt(2.0 / (1.0 + a * a))
    bound = gain * math.sqrt(3.0 / fan_in)
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound)


def init_bottleneck_params(
    key: jax.Array,
    in_channels: int,
    bottleneck_channels: int,
    out_channels: int,
    stride: int = 1,
) -> dict:
    """Parameter pytree: conv kernels (HWIO) + frozen-BN stat dicts."""
    ks = jax.random.split(key, 4)

    def bn(c):
        return {
            "weight": jnp.ones((c,), jnp.float32),
            "bias": jnp.zeros((c,), jnp.float32),
            "running_mean": jnp.zeros((c,), jnp.float32),
            "running_var": jnp.ones((c,), jnp.float32),
        }

    params = {
        "conv1": _kaiming_uniform(
            ks[0], (1, 1, in_channels, bottleneck_channels)),
        "conv2": _kaiming_uniform(
            ks[1], (3, 3, bottleneck_channels, bottleneck_channels)),
        "conv3": _kaiming_uniform(
            ks[2], (1, 1, bottleneck_channels, out_channels)),
        "bn1": bn(bottleneck_channels),
        "bn2": bn(bottleneck_channels),
        "bn3": bn(out_channels),
    }
    if stride != 1 or in_channels != out_channels:
        params["downsample"] = _kaiming_uniform(
            ks[3], (1, 1, in_channels, out_channels))
        params["bn_ds"] = bn(out_channels)
    return params


def bottleneck_forward(params: dict, x: jax.Array, *,
                       stride: int = 1) -> jax.Array:
    """conv1x1(stride)+BN+ReLU → conv3x3+BN+ReLU → conv1x1+BN →
    +identity → ReLU (reference bottleneck.py:220-262, stride at conv1 =
    ResNet v1.5 per the reference's own placement)."""
    s1, b1 = frozen_bn_scale_bias(params["bn1"])
    s2, b2 = frozen_bn_scale_bias(params["bn2"])
    s3, b3 = frozen_bn_scale_bias(params["bn3"])

    out = _conv(x, params["conv1"], stride) * s1 + b1
    out = jax.nn.relu(out)
    out = _conv(out, params["conv2"]) * s2 + b2
    out = jax.nn.relu(out)
    out = _conv(out, params["conv3"]) * s3 + b3

    if "downsample" in params:
        sd, bd = frozen_bn_scale_bias(params["bn_ds"])
        identity = _conv(x, params["downsample"], stride) * sd + bd
    else:
        identity = x
    return jax.nn.relu(out + identity)


def spatial_bottleneck_forward(
    params: dict,
    x: jax.Array,
    *,
    stride: int = 1,
    axis_name: str = "spatial",
) -> jax.Array:
    """The same block with H sharded over ``axis_name`` (call inside
    shard_map; ``x`` is this rank's H-shard, NHWC).

    Only the 3x3 conv sees across shard edges: one halo row is exchanged
    (ppermute) and the conv runs VALID over the H dim on the halo'd
    input — the reference SpatialBottleneckFunction's halo path
    (bottleneck.py:302-420) without the peer-memory machinery.  ppermute
    hands global-edge ranks zero halos, which equals the unsplit conv's
    SAME zero padding.
    """
    s1, b1 = frozen_bn_scale_bias(params["bn1"])
    s2, b2 = frozen_bn_scale_bias(params["bn2"])
    s3, b3 = frozen_bn_scale_bias(params["bn3"])

    out = _conv(x, params["conv1"], stride) * s1 + b1
    out = jax.nn.relu(out)

    # 3x3: halo in H (VALID over the grown dim), SAME zero-pad in W
    out = halo_exchange_1d(out, 1, axis_name, dim=1)
    out = jax.lax.conv_general_dilated(
        out, params["conv2"].astype(out.dtype), (1, 1),
        [(0, 0), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    out = out * s2 + b2
    out = jax.nn.relu(out)

    out = _conv(out, params["conv3"]) * s3 + b3

    if "downsample" in params:
        sd, bd = frozen_bn_scale_bias(params["bn_ds"])
        identity = _conv(x, params["downsample"], stride) * sd + bd
    else:
        identity = x
    return jax.nn.relu(out + identity)


class Bottleneck(nn.Module):
    """Module wrapper (reference ``Bottleneck``, bottleneck.py:134).
    Frozen BN stats live as non-trainable variables."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        params = self.param(
            "block",
            lambda k: init_bottleneck_params(
                k, self.in_channels, self.bottleneck_channels,
                self.out_channels, self.stride))
        return bottleneck_forward(params, x, stride=self.stride)


class SpatialBottleneck(nn.Module):
    """Spatially-parallel module wrapper (reference ``SpatialBottleneck``,
    bottleneck.py:603); use inside shard_map with H sharded over
    ``axis_name``."""

    in_channels: int
    bottleneck_channels: int
    out_channels: int
    stride: int = 1
    axis_name: str = "spatial"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        params = self.param(
            "block",
            lambda k: init_bottleneck_params(
                k, self.in_channels, self.bottleneck_channels,
                self.out_channels, self.stride))
        return spatial_bottleneck_forward(
            params, x, stride=self.stride, axis_name=self.axis_name)
