"""Fused multi-head attention modules.

TPU rebuild of ``apex.contrib.multihead_attn`` (reference:
self_multihead_attn.py:22, encdec_multihead_attn.py:22,
mask_softmax_dropout_func.py).  The reference's hand-written CUDA MHA
(8.4k LoC: rocBLAS GEMMs + Philox dropout + fused softmax + fused
layernorm/residual epilogues) collapses into the Pallas flash-attention
kernel (attention dropout fused in-kernel via a counter-hash PRNG — the
Philox analog) plus XLA-fused projections.
"""

from .self_multihead_attn import SelfMultiheadAttn  # noqa: F401
from .encdec_multihead_attn import EncdecMultiheadAttn  # noqa: F401
from .mask_softmax_dropout_func import fast_mask_softmax_dropout_func  # noqa: F401
