"""Encoder-decoder multi-head attention module.

Reference: ``apex/contrib/multihead_attn/encdec_multihead_attn.py:22`` —
query projected from the decoder stream, fused KV projection from the
encoder output, same fast/norm-add CUDA variants as self-attention.
Flash-attention kernel backend with fused attention dropout; layouts and
init match the reference (q weight xavier, kv fused weight xavier with
gain sqrt(2); norm-add layernorms the *query* stream,
fast_encdec_multihead_attn_norm_add_func.py).
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.ops.layer_norm import fused_layer_norm

from .self_multihead_attn import _resolve_time_mask, _xavier_uniform

__all__ = ["EncdecMultiheadAttn"]


class EncdecMultiheadAttn(nn.Module):
    """Drop-in for reference ``EncdecMultiheadAttn`` (flax edition)."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"

    def setup(self):
        e = self.embed_dim
        assert e % self.num_heads == 0, (
            "embed_dim must be divisible by num_heads"
        )
        self.in_proj_weight_q = self.param(
            "in_proj_weight_q", _xavier_uniform(), (e, e))
        # fused [e, 2e] KV initialized like an [e, e] matrix:
        # sqrt(6/(e+e)) / sqrt(6/(2e+e)) = sqrt(3/2)
        self.in_proj_weight_kv = self.param(
            "in_proj_weight_kv", _xavier_uniform(math.sqrt(1.5)),
            (e, 2 * e))
        self.out_proj_weight = self.param(
            "out_proj_weight", _xavier_uniform(), (e, e))
        if self.bias:
            self.in_proj_bias_q = self.param(
                "in_proj_bias_q", nn.initializers.zeros, (e,))
            self.in_proj_bias_kv = self.param(
                "in_proj_bias_kv", nn.initializers.zeros, (2 * e,))
            self.out_proj_bias = self.param(
                "out_proj_bias", nn.initializers.zeros, (e,))
        if self.include_norm_add:
            self.lyr_nrm_gamma_weights = self.param(
                "lyr_nrm_gamma_weights", nn.initializers.ones, (e,))
            self.lyr_nrm_beta_weights = self.param(
                "lyr_nrm_beta_weights", nn.initializers.zeros, (e,))

    def __call__(
        self,
        query: jax.Array,
        key: jax.Array,
        value: Optional[jax.Array] = None,
        key_padding_mask: Optional[jax.Array] = None,
        need_weights: bool = False,
        attn_mask: Optional[bool] = None,
        is_training: bool = True,
    ):
        """``query``: [tgt_len, batch, e] (decoder); ``key``: [src_len,
        batch, e] (encoder output; ``value`` must alias it — the fused
        KV projection reads one stream, like the reference).  Returns
        ``(output, None)``."""
        assert not need_weights, (
            "need_weights is unsupported on the fused path"
        )
        assert value is None or value is key, (
            "EncdecMultiheadAttn projects K and V from one encoder "
            "stream (fused KV projection, like the reference): value "
            "must alias key"
        )
        tq, b, e = query.shape
        tk = key.shape[0]
        h = self.num_heads
        d = e // h

        residual = query
        q_in = query
        if self.include_norm_add:
            q_in = fused_layer_norm(
                q_in, self.lyr_nrm_gamma_weights,
                self.lyr_nrm_beta_weights)

        q = q_in @ self.in_proj_weight_q
        kv = key @ self.in_proj_weight_kv
        if self.bias:
            q = q + self.in_proj_bias_q
            kv = kv + self.in_proj_bias_kv
        k, v = jnp.split(kv, 2, axis=-1)

        def to_bshd(x, t):
            return x.reshape(t, b, h, d).transpose(1, 0, 2, 3)

        if key_padding_mask is not None:
            key_padding_mask = key_padding_mask.astype(jnp.bool_)

        dropout_rng = None
        attn_dropout = self.dropout if is_training else 0.0
        if attn_dropout > 0.0:
            dropout_rng = self.make_rng("dropout")

        causal, generic_mask = _resolve_time_mask(attn_mask)
        ctx = flash_attention(
            to_bshd(q, tq), to_bshd(k, tk), to_bshd(v, tk),
            causal=causal,
            mask=generic_mask,
            key_padding_mask=key_padding_mask,
            scale=d ** -0.5,
            dropout_p=attn_dropout,
            dropout_rng=dropout_rng,
        )
        ctx = ctx.transpose(1, 0, 2, 3).reshape(tq, b, e)
        out = ctx @ self.out_proj_weight
        if self.bias:
            out = out + self.out_proj_bias

        if self.include_norm_add:
            if is_training and self.dropout > 0.0:
                rng = self.make_rng("dropout")
                keep = jax.random.bernoulli(
                    rng, 1.0 - self.dropout, out.shape)
                out = jnp.where(keep, out / (1.0 - self.dropout), 0.0)
            out = residual + out
        return out, None
