"""Standalone fused masked-softmax + dropout.

Reference: ``apex/contrib/multihead_attn/mask_softmax_dropout_func.py``
(``fast_mask_softmax_dropout_func``) — softmax over attention scores with
a byte or additive padding mask, then dropout, as one fused op (used to
splice the reference MHA's middle section into other models).  Under jit
XLA fuses the chain into one kernel pass; the fused scaled-masked softmax
kernel supplies the softmax core.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["fast_mask_softmax_dropout_func", "mask_softmax_dropout"]

_NEG_INF = -1e30


def mask_softmax_dropout(
    is_training: bool,
    heads: int,
    inputs: jax.Array,
    pad_mask: Optional[jax.Array],
    mask_additive: bool,
    dropout_prob: float,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """``inputs``: [batch*heads, tgt_len, src_len] attention scores (the
    reference layout).  ``pad_mask``: [batch, src_len] — byte (1 =
    masked) or additive float when ``mask_additive``.  Returns dropped
    softmax probabilities."""
    bh, tq, tk = inputs.shape
    s = inputs.astype(jnp.float32)
    if pad_mask is not None:
        b = pad_mask.shape[0]
        rep = bh // b
        m = jnp.repeat(pad_mask, rep, axis=0)[:, None, :]
        if mask_additive:
            s = s + m.astype(jnp.float32)
        else:
            s = jnp.where(m.astype(jnp.bool_), _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1).astype(inputs.dtype)
    if is_training and dropout_prob > 0.0:
        if dropout_rng is None:
            raise ValueError(
                "dropout_rng is required when is_training and "
                "dropout_prob > 0 (JAX has no global PRNG state to "
                "fall back on, unlike the reference's Philox stream)"
            )
        keep = jax.random.bernoulli(
            dropout_rng, 1.0 - dropout_prob, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_prob), 0.0)
    return p


# reference-named alias (positional signature parity:
# fast_mask_softmax_dropout_func(is_training, heads, inputs, pad_mask,
# mask_additive, dropout_prob))
fast_mask_softmax_dropout_func = mask_softmax_dropout
