"""Self multi-head attention module with fused attention dropout.

Reference: ``apex/contrib/multihead_attn/self_multihead_attn.py:22`` —
an nn.Module owning fused-QKV projection weights that dispatches to one
of four CUDA autograd functions (fast / fast-norm-add / default, with
Philox softmax-dropout, additive or byte padding masks, optional causal
time mask).  Here all four collapse onto :func:`flash_attention`, whose
Pallas kernel fuses causal masking, (additive) key-padding masks, and
attention dropout, so training with attention dropout keeps O(s·d)
memory — the direct analog of the reference's in-kernel
``philox.cuh`` dropout.

Layout parity: inputs are seq-first ``[tgt_len, batch, embed_dim]``
exactly like the reference ("Input shape: Time x Batch x Channel").
Weights use the JAX (in, out) convention — ``in_proj_weight`` is
``[embed_dim, 3*embed_dim]`` where the reference stores
``[3*embed_dim, embed_dim]``; initialization matches the reference's
``xavier_uniform_(gain=sqrt(2))`` fused-QKV recipe
(self_multihead_attn.py:113-124).
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention
from apex_tpu.ops.layer_norm import fused_layer_norm

__all__ = ["SelfMultiheadAttn"]


def _resolve_time_mask(attn_mask):
    """(causal_flag, generic_mask) from the reference's attn_mask arg:
    None → no mask; non-tensor truthy → causal; a [tgt, tgt] byte/bool
    tensor (1 = masked) → generic boolean mask broadcast over
    batch/heads (the XLA fallback path)."""
    if attn_mask is None:
        return False, None
    if isinstance(attn_mask, (bool, int)):
        return bool(attn_mask), None
    m = jnp.asarray(attn_mask)
    if m.ndim == 0:
        return bool(m), None
    return False, m.astype(jnp.bool_)[None, None, :, :]


def _xavier_uniform(gain: float = 1.0):
    def init(key, shape, dtype=jnp.float32):
        fan_in, fan_out = shape[0], shape[1]
        limit = gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(
            key, shape, dtype, minval=-limit, maxval=limit
        )

    return init


class SelfMultiheadAttn(nn.Module):
    """Drop-in for reference ``SelfMultiheadAttn`` (flax edition).

    Args mirror self_multihead_attn.py:28-38: ``bias`` adds projection
    biases; ``include_norm_add`` pre-layernorms the input and returns
    ``residual + dropout(attn_out)``; ``mask_additive`` marks the
    key_padding_mask as an additive float mask; ``separate_qkv_params``
    stores q/k/v weights separately.  ``impl`` is accepted for API
    compatibility ("fast"/"default" both run the flash kernel).
    """

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    impl: str = "fast"
    separate_qkv_params: bool = False
    mask_additive: bool = False

    def setup(self):
        e = self.embed_dim
        assert e % self.num_heads == 0, (
            "embed_dim must be divisible by num_heads"
        )
        if self.mask_additive:
            assert not self.include_norm_add, (
                "additive mask not supported with layer norm"
            )
        if self.separate_qkv_params:
            self.q_weight = self.param(
                "q_weight", _xavier_uniform(), (e, e))
            self.k_weight = self.param(
                "k_weight", _xavier_uniform(), (e, e))
            self.v_weight = self.param(
                "v_weight", _xavier_uniform(), (e, e))
        else:
            # gain sqrt(2): fused [e, 3e] initialized like an [e, e]
            # matrix (reference reset_parameters rationale)
            self.in_proj_weight = self.param(
                "in_proj_weight", _xavier_uniform(math.sqrt(2.0)),
                (e, 3 * e))
        self.out_proj_weight = self.param(
            "out_proj_weight", _xavier_uniform(), (e, e))
        if self.bias:
            if self.separate_qkv_params:
                self.q_bias = self.param(
                    "q_bias", nn.initializers.zeros, (e,))
                self.k_bias = self.param(
                    "k_bias", nn.initializers.zeros, (e,))
                self.v_bias = self.param(
                    "v_bias", nn.initializers.zeros, (e,))
            else:
                self.in_proj_bias = self.param(
                    "in_proj_bias", nn.initializers.zeros, (3 * e,))
            self.out_proj_bias = self.param(
                "out_proj_bias", nn.initializers.zeros, (e,))
        if self.include_norm_add:
            self.lyr_nrm_gamma_weights = self.param(
                "lyr_nrm_gamma_weights", nn.initializers.ones, (e,))
            self.lyr_nrm_beta_weights = self.param(
                "lyr_nrm_beta_weights", nn.initializers.zeros, (e,))

    def __call__(
        self,
        query: jax.Array,
        key: Optional[jax.Array] = None,
        value: Optional[jax.Array] = None,
        key_padding_mask: Optional[jax.Array] = None,
        need_weights: bool = False,
        attn_mask: Optional[bool] = None,
        is_training: bool = True,
    ):
        """``query``: [tgt_len, batch, embed_dim]; ``key``/``value`` are
        accepted for API parity and must alias query (self-attention).
        ``attn_mask`` is the causal time mask: pass ``True`` (or any
        non-tensor truthy) to mask future timesteps — the reference's
        use_time_mask flag — or an explicit [tgt, tgt] byte/bool tensor
        (1 = masked), which routes to the generic-mask path.
        ``key_padding_mask``: [batch, src_len]; byte/bool (1 = masked)
        or additive float when ``mask_additive``.  Returns
        ``(output, None)`` like the reference fast path (attention
        weights are not materialized — that is the point)."""
        assert key is None or key is query, (
            "SelfMultiheadAttn is self-attention: key must alias query"
        )
        assert value is None or value is query, (
            "SelfMultiheadAttn is self-attention: value must alias query"
        )
        assert not need_weights, (
            "need_weights is unsupported on the fused path (the "
            "reference fast impl returns None as well)"
        )
        t, b, e = query.shape
        h = self.num_heads
        d = e // h

        residual = query
        inputs = query
        if self.include_norm_add:
            inputs = fused_layer_norm(
                inputs, self.lyr_nrm_gamma_weights,
                self.lyr_nrm_beta_weights)

        if self.separate_qkv_params:
            wq, wk, wv = self.q_weight, self.k_weight, self.v_weight
            bq = self.q_bias if self.bias else None
            bk = self.k_bias if self.bias else None
            bv = self.v_bias if self.bias else None
        else:
            wq, wk, wv = jnp.split(self.in_proj_weight, 3, axis=1)
            if self.bias:
                bq, bk, bv = jnp.split(self.in_proj_bias, 3)
            else:
                bq = bk = bv = None

        def proj(x, w, bias_vec):
            y = x @ w
            return y if bias_vec is None else y + bias_vec

        # [t, b, e] -> [b, t, h, d]
        def to_bshd(x):
            return x.reshape(t, b, h, d).transpose(1, 0, 2, 3)

        q = to_bshd(proj(inputs, wq, bq))
        k = to_bshd(proj(inputs, wk, bk))
        v = to_bshd(proj(inputs, wv, bv))

        if key_padding_mask is not None and not self.mask_additive:
            key_padding_mask = key_padding_mask.astype(jnp.bool_)

        dropout_rng = None
        attn_dropout = self.dropout if is_training else 0.0
        if attn_dropout > 0.0:
            dropout_rng = self.make_rng("dropout")

        causal, generic_mask = _resolve_time_mask(attn_mask)
        ctx = flash_attention(
            q, k, v,
            causal=causal,
            mask=generic_mask,
            key_padding_mask=key_padding_mask,
            scale=d ** -0.5,
            dropout_p=attn_dropout,
            dropout_rng=dropout_rng,
        )
        # [b, t, h, d] -> [t, b, e]
        ctx = ctx.transpose(1, 0, 2, 3).reshape(t, b, e)
        out = ctx @ self.out_proj_weight
        if self.bias:
            out = out + self.out_proj_bias

        if self.include_norm_add:
            # dropout-add epilogue (reference jit_dropout_add)
            if is_training and self.dropout > 0.0:
                rng = self.make_rng("dropout")
                keep = jax.random.bernoulli(
                    rng, 1.0 - self.dropout, out.shape)
                out = jnp.where(keep, out / (1.0 - self.dropout), 0.0)
            out = residual + out
        return out, None
