"""RNN-T transducer joint and loss.

Reference: apex/contrib/csrc/transducer/{transducer_joint_kernel.cu,
transducer_loss_kernel.cu} wrapped by apex/contrib/transducer/transducer.py
(``TransducerJoint`` :5, ``TransducerLoss`` :68) — "Sequence Transduction
with Recurrent Neural Networks" (Graves 2012).

TPU-native choices:
- The joint is broadcast-add + optional ReLU/dropout, fused by XLA; the
  reference's ``pack_output`` (variable-length compaction) trades memory
  for dynamic shapes, which XLA cannot compile — the dense layout with a
  validity mask is the TPU equivalent (``joint_mask`` below).
- The loss runs the alpha recurrence over anti-diagonals of the (T, U)
  lattice: each ``lax.scan`` step updates a whole diagonal in parallel
  (T+U-1 sequential steps instead of T·U), the standard TPU lattice
  traversal. Gradients flow through the scan via autodiff (the reference
  hand-writes the beta pass + fused log-softmax backward).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["transducer_joint", "joint_mask", "transducer_loss",
           "pack_joint_output", "unpack_joint",
           "TransducerJoint", "TransducerLoss"]

_NEG = -1e30


def joint_mask(f_len: jax.Array, g_len: jax.Array, T: int, U: int):
    """[B, T, U] validity mask: t < f_len and u <= g_len (the reference
    passes g_len as 'prediction length minus 1', so g_len+1 rows are
    valid — transducer.py:46 docstring)."""
    t = jnp.arange(T)[None, :, None]
    u = jnp.arange(U)[None, None, :]
    return (t < f_len[:, None, None]) & (u <= g_len[:, None, None])


def transducer_joint(
    f: jax.Array,
    g: jax.Array,
    f_len: jax.Array,
    g_len: jax.Array,
    *,
    relu: bool = False,
    dropout_prob: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """f [B,T,H] ⊕ g [B,U,H] → joint [B,T,U,H]; invalid (t,u) cells are
    zeroed (the dense analog of the reference's packed don't-care
    removal)."""
    B, T, H = f.shape
    U = g.shape[1]
    h = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        h = jax.nn.relu(h)
    if dropout_prob > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_prob,
                                    h.shape)
        h = jnp.where(keep, h / (1.0 - dropout_prob), 0.0)
    mask = joint_mask(f_len, g_len, T, U)
    return jnp.where(mask[..., None], h, 0.0).astype(f.dtype)


def pack_joint_output(h: jax.Array, f_len: jax.Array, g_len: jax.Array,
                      max_tokens: int):
    """Compact the dense joint [B, T, U, ...] into packed rows.

    The reference's ``pack_output`` removes the don't-care cells with a
    data-dependent output size (transducer_joint_kernel.cu packed
    layout); XLA needs static shapes, so — like the MoE capacity
    factor — the caller supplies a static ``max_tokens`` capacity.
    Cell (b, t, u) is valid iff ``t < f_len[b]`` and ``u <= g_len[b]``
    (:func:`joint_mask` semantics) and lands at
    ``offsets[b] + t·(g_len[b]+1) + u`` — the reference's batch_offset
    layout.

    Returns ``(packed [max_tokens, ...], offsets [B+1], n_valid [])``;
    slots past ``n_valid`` are zero.  Cells beyond capacity are DROPPED
    (check ``n_valid <= max_tokens``, e.g. with
    ``jax.experimental.checkify`` or a host assert, when capacity is not
    provably sufficient: ``max_tokens >= B·T·U`` never drops).
    """
    B, T, U = h.shape[:3]
    rows_per_b = f_len * (g_len + 1)
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(rows_per_b.astype(jnp.int32))])
    valid = joint_mask(f_len, g_len, T, U)
    t = jnp.arange(T)[None, :, None]
    u = jnp.arange(U)[None, None, :]
    pos = (offsets[:-1][:, None, None]
           + t * (g_len[:, None, None] + 1) + u)
    dest = jnp.where(valid, pos, max_tokens).reshape(-1)
    feat_shape = h.shape[3:]
    flat = h.reshape((B * T * U,) + feat_shape)
    packed = jnp.zeros((max_tokens + 1,) + feat_shape, h.dtype)
    packed = packed.at[dest].set(flat, mode="drop")
    return packed[:max_tokens], offsets, offsets[-1]


def unpack_joint(packed: jax.Array, offsets: jax.Array,
                 f_len: jax.Array, g_len: jax.Array, T: int, U: int,
                 fill: float = 0.0) -> jax.Array:
    """Inverse of :func:`pack_joint_output`: packed rows → dense
    [B, T, U, ...] with invalid cells set to ``fill``."""
    B = offsets.shape[0] - 1
    valid = joint_mask(f_len, g_len, T, U)
    t = jnp.arange(T)[None, :, None]
    u = jnp.arange(U)[None, None, :]
    pos = (offsets[:-1][:, None, None]
           + t * (g_len[:, None, None] + 1) + u)
    idx = jnp.where(valid, pos, 0).reshape(-1)
    dense = packed[idx].reshape((B, T, U) + packed.shape[1:])
    return jnp.where(
        valid.reshape(B, T, U, *([1] * (dense.ndim - 3))), dense,
        jnp.asarray(fill, dense.dtype))


def transducer_loss(
    x: jax.Array,
    label: jax.Array,
    f_len: jax.Array,
    y_len: jax.Array,
    blank_idx: int = 0,
) -> jax.Array:
    """Per-sequence negative log-likelihood [B].

    ``x`` [B, T, U, K] raw joint logits (log-softmax fused here, like the
    reference's fused-softmax-backward path), ``label`` [B, U-1] target
    ids, ``f_len`` [B] encoder lengths, ``y_len`` [B] label lengths
    (so sequence b uses lattice [0..f_len-1] × [0..y_len]).
    """
    B, T, U, K = x.shape
    lsm = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
    blank_lp = lsm[..., blank_idx]                      # [B, T, U]
    # emit_lp[b,t,u] = lsm[b,t,u,label[b,u]] for u < U-1
    lab = jnp.clip(label, 0, K - 1)                     # [B, U-1]
    emit_lp = jnp.take_along_axis(
        lsm[:, :, : U - 1, :],
        lab[:, None, :, None].repeat(T, axis=1), axis=-1)[..., 0]
    emit_lp = jnp.pad(emit_lp, ((0, 0), (0, 0), (0, 1)),
                      constant_values=_NEG)             # [B, T, U]

    u_idx = jnp.arange(U)                                # diag position u

    # vectorized gather helper: value[b, t_of_u, u] for a per-u t index
    def gather_tu(arr, t_of_u):
        # arr [B, T, U], t_of_u [U] → [B, U]
        tc = jnp.clip(t_of_u, 0, T - 1)
        return jnp.take_along_axis(
            arr, jnp.broadcast_to(tc[None, None, :], (B, 1, U)), axis=1
        )[:, 0, :]

    def step(alpha_prev, d):
        # term 1 (advance t): alpha[d-1-u, u] + blank[d-1-u, u]
        t_b = d - 1 - u_idx
        ok_b = (t_b >= 0) & (t_b < T)
        from_blank = jnp.where(
            ok_b[None, :], alpha_prev + gather_tu(blank_lp, t_b), _NEG)
        # term 2 (advance u): alpha[d-u, u-1] + emit[d-u, u-1].
        # Gather per-column j at t = d-1-j, then shift right one column:
        # position u then reads emit_lp[d-1-(u-1), u-1] = emit[d-u, u-1].
        t_e = d - u_idx
        prev_u = jnp.concatenate(
            [jnp.full((B, 1), _NEG), alpha_prev[:, :-1]], axis=1)
        emit_prev = jnp.concatenate(
            [jnp.full((B, 1), _NEG), gather_tu(emit_lp, t_b)[:, :-1]],
            axis=1)
        ok_e = (t_e >= 0) & (t_e < T) & (u_idx >= 1)
        from_emit = jnp.where(ok_e[None, :], prev_u + emit_prev, _NEG)
        alpha_new = jnp.logaddexp(from_blank, from_emit)
        # keep alpha[0,0] = 0 anchored on diagonal 0 only
        return alpha_new, alpha_new

    alpha0 = jnp.full((B, U), _NEG).at[:, 0].set(0.0)
    _, diags = jax.lax.scan(step, alpha0, jnp.arange(1, T + U - 1))
    all_diags = jnp.concatenate([alpha0[None], diags], axis=0)  # [T+U-1,B,U]

    # alpha[f_len-1, y_len] lives on diagonal (f_len-1+y_len) at u=y_len
    b_idx = jnp.arange(B)
    d_fin = f_len - 1 + y_len
    alpha_fin = all_diags[d_fin, b_idx, y_len]
    final_blank = blank_lp[b_idx, f_len - 1, y_len]
    return -(alpha_fin + final_blank)


class TransducerJoint:
    """Reference-API module shim (apex/contrib/transducer/transducer.py:5).

    ``pack_output=True`` needs a static ``max_tokens`` capacity (XLA has
    no data-dependent shapes; this is the capacity-factor contract —
    ``max_tokens = B·T·U`` is always lossless) and returns
    ``(packed, offsets, n_valid)`` instead of the dense joint."""

    def __init__(self, pack_output=False, relu=False, dropout=False,
                 dropout_prob=0.0, max_tokens=None, **_ignored):
        if pack_output and max_tokens is None:
            raise ValueError(
                "pack_output=True requires max_tokens (a static packed "
                "capacity; B*T*U is always enough): XLA cannot compile "
                "the reference's data-dependent packed shape")
        self.pack_output = pack_output
        self.max_tokens = max_tokens
        self.relu = relu
        self.dropout = dropout
        self.dropout_prob = dropout_prob

    def __call__(self, f, g, f_len, g_len, dropout_rng=None):
        h = transducer_joint(
            f, g, f_len, g_len, relu=self.relu,
            dropout_prob=self.dropout_prob if self.dropout else 0.0,
            dropout_rng=dropout_rng)
        if not self.pack_output:
            return h
        return pack_joint_output(h, f_len, g_len, self.max_tokens)


class TransducerLoss:
    """Reference-API module shim (apex/contrib/transducer/transducer.py:68).

    ``packed_input=True`` consumes :class:`TransducerJoint`'s packed
    layout: ``__call__(packed, label, f_len, y_len, offsets,
    max_f_len, max_g_len)``.  The packed rows are scattered back to the
    dense lattice before the anti-diagonal scan — the packing saves
    memory in the joint and whatever runs between joint and loss, not in
    the loss itself (whose lattice is inherently dense)."""

    def __init__(self, packed_input=False, **_ignored):
        self.packed_input = packed_input

    def __call__(self, x, label, f_len, y_len, blank_idx=0, *,
                 offsets=None, max_f_len=None, max_g_len=None):
        if self.packed_input:
            if offsets is None or max_f_len is None or max_g_len is None:
                raise ValueError(
                    "packed_input=True requires offsets (from "
                    "TransducerJoint pack_output) plus static "
                    "max_f_len/max_g_len lattice bounds")
            # recover the packed stride from the offsets themselves
            # (rows_per_b = f_len·(g_len+1)) so this matches whatever
            # g_len convention the joint was packed with
            g_len_packed = ((offsets[1:] - offsets[:-1])
                            // jnp.maximum(f_len, 1)) - 1
            x = unpack_joint(x, offsets, f_len, g_len_packed, max_f_len,
                             max_g_len, fill=0.0)
        return transducer_loss(x, label, f_len, y_len, blank_idx)
