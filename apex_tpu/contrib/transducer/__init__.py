from apex_tpu.contrib.transducer.transducer import (  # noqa: F401
    TransducerJoint,
    TransducerLoss,
    joint_mask,
    transducer_joint,
    transducer_loss,
)
