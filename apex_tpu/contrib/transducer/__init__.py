from apex_tpu.contrib.transducer.transducer import (  # noqa: F401
    TransducerJoint,
    TransducerLoss,
    joint_mask,
    pack_joint_output,
    transducer_joint,
    transducer_loss,
    unpack_joint,
)
