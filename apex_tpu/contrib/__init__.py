"""apex.contrib analog: higher-level / specialized components.

Reference: apex/contrib. The TPU build keeps the namespace:

- ``multihead_attn``  — Self/Encdec MHA modules over the flash kernel
- ``sparsity``        — ASP 2:4 structured sparsity (+ C++ search kernels)
- ``optimizers``      — ZeRO DistributedFusedAdam / DistributedFusedLAMB
- ``bottleneck``      — (Spatial)Bottleneck blocks
- ``peer_memory``     — ppermute halo exchange
- ``conv_bias_relu``  — fused conv epilogues (XLA, HLO-verified)
- ``groupbn``         — NHWC BatchNorm shim over SyncBN (N/A writeup)
- ``transducer`` / ``focal_loss`` / ``index_mul_2d`` / ``xentropy`` /
  ``clip_grad``

The fmha analog lives in ``apex_tpu.ops.flash_attention``; ring
attention (our long-context extension) in
``apex_tpu.parallel.ring_attention``.
"""
