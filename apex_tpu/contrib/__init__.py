"""apex.contrib analog: higher-level / specialized components.

Reference: apex/contrib (fmha, multihead_attn, optimizers, xentropy,
focal_loss, transducer, sparsity, peer_memory, ...). The TPU build keeps
the namespace; fused attention lives in apex_tpu.ops.flash_attention and
ring attention in apex_tpu.parallel.ring_attention.
"""
