"""DistributedFusedAdam — ZeRO-2 sharded-state Adam over the 'dp' axis.

Reference: apex/contrib/optimizers/distributed_fused_adam.py (param flatten
→ fixed-size buckets → optimizer state sharded across DP ranks; overlapped
reduce-scatter grad sync + all-gather param sync; bf16
``store_param_remainders`` packing — :273-470). TPU-native shape: ONE flat
fp32 buffer instead of buckets (the Pallas flat Adam kernel streams it in
one HBM pass), shard_map over 'dp' instead of NCCL process groups, and XLA
collectives instead of hand-overlapped NCCL streams — grad sync is the
SPMD-AD psum, param sync is the all-gather GSPMD inserts when the
'dp'-sharded updated flat buffer is unraveled back into replicated params;
overlap comes from the XLA latency-hiding scheduler.

State per device (ZeRO-2): replicated compute-dtype params + a 1/dp shard
of the fp32 master, m, and v — 12 bytes/param/dp instead of 12 bytes/param.
With ``store_param_remainders`` the fp32 master shard is reconstructed
bit-exactly from the bf16 param shard plus a signed 16-bit mantissa
remainder (reference :461-467), shaving another 2 bytes/param/dp.

Full AMP semantics ride along: dynamic loss scaling, global finite check
(the transformer GradScaler's found-inf allreduce,
apex/transformer/amp/grad_scaler.py:21), skip-on-overflow.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.amp import scaler as scaler_lib
from apex_tpu.amp.policy import _effective, policy_for_opt_level
from apex_tpu.utils.collectives import flag_and


__all__ = ["ZeroTrainState", "make_distributed_adam_train_step",
           "zero_state_specs"]

_LANES = 128


class ZeroTrainState(NamedTuple):
    step: jax.Array                 # i32, replicated
    params: Any                     # compute-dtype pytree, replicated
    master_shard: jax.Array         # f32 [n] sharded | int16 remainders
    m_shard: jax.Array              # f32 [n] sharded over dp
    v_shard: jax.Array              # f32 [n] sharded over dp
    loss_scale_state: Any
    # rank-local error-feedback residual for compressed grad_comm
    # ([ndev, padded_total] f32 sharded over dp); None when off
    comm_residual: Any = None


def _is_float(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def _ravel_floats(tree):
    """Flatten ONLY floating leaves into one f32 vector; non-float leaves
    (step counters, int tables) stay out of the master buffer entirely.

    Returns (flat, unravel) where ``unravel(new_flat, like_tree)`` rebuilds
    the full tree: float leaves from the buffer cast to each like-leaf's
    dtype, non-float leaves taken from ``like_tree`` verbatim."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    fmask = [_is_float(x) for x in leaves]
    shapes = [x.shape for x in leaves]
    sizes = [int(np_prod(x.shape)) if m else 0
             for x, m in zip(leaves, fmask)]
    if any(fmask):
        flat = jnp.concatenate(
            [x.reshape(-1).astype(jnp.float32)
             for x, m in zip(leaves, fmask) if m])
    else:
        flat = jnp.zeros((0,), jnp.float32)

    def unravel(new_flat, like_tree):
        like = jax.tree_util.tree_flatten(like_tree)[0]
        out, off = [], 0
        for x, m, shp, sz in zip(like, fmask, shapes, sizes):
            if m:
                out.append(new_flat[off: off + sz].reshape(shp)
                           .astype(x.dtype))
                off += sz
            else:
                out.append(x)
        return jax.tree_util.tree_unflatten(treedef, out)

    return flat, unravel


def np_prod(shape):
    r = 1
    for d in shape:
        r *= int(d)
    return r


def _split_bits(x32: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """f32 → (truncated bf16 = high 16 bits, int16 = low 16 bits).

    Truncation, not round-to-nearest: the reference kernel does
    ``remainder = full & 0xFFFF; param = bf16(full >> 16)``
    (multi_tensor_distopt_adam_kernel.cu) — and rounding has an unpackable
    tie case (remainder +2^15 does not fit int16)."""
    bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    bf = jax.lax.bitcast_convert_type(
        (bits >> 16).astype(jnp.uint16), jnp.bfloat16)
    rem = jax.lax.bitcast_convert_type(
        (bits & 0xFFFF).astype(jnp.uint16), jnp.int16)
    return bf, rem


def _combine_bits(bf: jax.Array, rem: jax.Array) -> jax.Array:
    hi = jax.lax.bitcast_convert_type(bf, jnp.uint16).astype(jnp.uint32) << 16
    lo = jax.lax.bitcast_convert_type(rem, jnp.uint16).astype(jnp.uint32)
    return jax.lax.bitcast_convert_type(hi | lo, jnp.float32)


def zero_state_specs(state: ZeroTrainState,
                     axis_name: str = "dp") -> ZeroTrainState:
    """Per-leaf :class:`PartitionSpec` tree of a :class:`ZeroTrainState`:
    replicated params/step/scaler, ``P(axis_name)`` for the flat
    master/m/v shards and (when present) the rank-local
    ``comm_residual``.

    This is the shard-extraction contract the checkpoint subsystem
    relies on (ISSUE 11): ``apex_tpu.checkpoint.save_sharded`` walks
    ``addressable_shards`` of exactly these placements, so each rank
    persists only its own 1/dp slice of the optimizer state (and its
    own error-feedback residual row), and restore re-places every
    shard under the same specs — bitwise.  ``step_fn`` builds its
    shard_map in/out specs from the same function, so the checkpoint
    layout can never drift from the training layout."""
    pspec = jax.tree_util.tree_map(lambda _: P(), state.params)
    ls_spec = jax.tree_util.tree_map(
        lambda _: P(), state.loss_scale_state)
    return ZeroTrainState(
        step=P(), params=pspec, master_shard=P(axis_name),
        m_shard=P(axis_name), v_shard=P(axis_name),
        loss_scale_state=ls_spec,
        comm_residual=(P(axis_name) if state.comm_residual is not None
                       else None))


def make_distributed_adam_train_step(
    loss_fn: Callable,
    mesh: Mesh,
    *,
    axis_name: str = "dp",
    lr: float = 1e-3,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    adam_w_mode: bool = True,
    bias_correction: bool = True,
    amp: str = "O2",
    loss_scale=None,
    store_param_remainders: bool = False,
    grad_clip_norm: Optional[float] = None,
    grad_comm=None,
):
    """Build ``(init_fn, step_fn)`` with ZeRO-2 sharded optimizer state.

    ``loss_fn(params, *batch) -> loss`` runs on compute-dtype params.
    ``init_fn(params_f32) -> ZeroTrainState`` (device_put onto ``mesh``:
    params replicated, flat shards split along ``axis_name``).
    ``step_fn(state, *batch) -> (state, metrics)`` — batch sharded on its
    leading dim.

    ``grad_comm`` (``"bf16"`` | ``"int8"`` | ``comm.GradCommConfig``)
    compresses the ZeRO grad sync: gradients are taken w.r.t.
    ``pvary``-ed params (stopping SPMD-AD's fp32 psum) and reduced with
    ``comm.compressed_reduce_scatter`` — quantize → all_to_all →
    local dequant-sum, the scatter half of the EQuARX recipe; the wire
    moves ~1/4 (int8) or 1/2 (bf16) of the fp32 bytes and each rank
    lands exactly its optimizer shard.  No gather phase: the updated
    params already all-gather at compute precision (GSPMD's ZeRO param
    sync).  When the resolved config enables error feedback (int8
    default) the state carries a **full-gradient-sized** fp32 residual
    per rank (``comm_residual`` — 4 bytes/param/rank, deliberately NOT
    ZeRO-sharded because the quantization error is rank-local); pass
    ``GradCommConfig(wire_dtype="int8", error_feedback=False)`` to
    trade that memory for slow compression-error drift.
    """
    policy = policy_for_opt_level(amp)
    comm_cfg = None
    if grad_comm is not None:
        from apex_tpu import comm as comm_lib

        comm_cfg = comm_lib.resolve(grad_comm)
    compressing = comm_cfg is not None and comm_cfg.compresses
    use_ef = compressing and comm_cfg.use_error_feedback
    # uniform compute dtype for the whole flat buffer (the fp32 master
    # shard covers every param, so there is no keep-norm-fp32 split here);
    # _effective realizes fp16 opt levels as bf16 on TPU
    param_dtype = _effective(policy.param_dtype)
    beta1, beta2 = betas
    ndev = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    if loss_scale is None:
        loss_scale = policy.loss_scale    # inherit the opt level's choice
    ls_cfg, ls_state0 = scaler_lib.init_loss_scale(loss_scale)
    if store_param_remainders and param_dtype != jnp.bfloat16:
        raise ValueError(
            "store_param_remainders packs fp32 = bf16 param + 16-bit "
            f"remainder; param dtype is {param_dtype} (use a bf16 "
            "opt level — O2 maps to bf16 on TPU, O5 everywhere)"
        )

    def init_fn(params) -> ZeroTrainState:
        # copy even for same-dtype leaves: aliasing the caller's arrays
        # means step_fn's donate_argnums would delete them out from under
        # the caller (same rationale as amp.frontend init_fn)
        f32 = jax.tree_util.tree_map(
            lambda x: jnp.array(x, jnp.float32, copy=True)
            if _is_float(x) else x, params)
        flat, _ = _ravel_floats(f32)
        n = flat.shape[0]
        shard_n = -(-n // (ndev * _LANES)) * _LANES
        padded = shard_n * ndev
        flat = jnp.pad(flat, (0, padded - n))
        if store_param_remainders:
            # compute params must be the TRUNCATED bf16 (high 16 bits of
            # the master) so reconstruction is exact — see _split_bits
            compute = jax.tree_util.tree_map(
                lambda x: _split_bits(x)[0] if _is_float(x) else x, f32)
            master = _split_bits(flat)[1]
        else:
            compute = jax.tree_util.tree_map(
                lambda x: x.astype(param_dtype) if _is_float(x) else x,
                f32)
            master = flat
        zeros = jnp.zeros((padded,), jnp.float32)
        state = ZeroTrainState(
            step=jnp.zeros((), jnp.int32),
            params=compute,
            master_shard=master,
            m_shard=zeros,
            v_shard=zeros,
            loss_scale_state=ls_state0,
            comm_residual=(jnp.zeros((ndev, padded), jnp.float32)
                           if use_ef else None),
        )
        rep = NamedSharding(mesh, P())
        shard = NamedSharding(mesh, P(axis_name))
        return jax.device_put(state, ZeroTrainState(
            step=rep,
            params=jax.tree_util.tree_map(lambda _: rep, state.params),
            master_shard=shard, m_shard=shard, v_shard=shard,
            loss_scale_state=jax.tree_util.tree_map(
                lambda _: rep, state.loss_scale_state),
            comm_residual=shard if use_ef else None,
        ))

    def shard_step(state: ZeroTrainState, *batch):
        my = jax.lax.axis_index(axis_name)
        shard_n = state.m_shard.shape[0]
        ls_state = state.loss_scale_state

        # grads w.r.t. the replicated compute params; shard_map SPMD-AD
        # psums them — that allreduce IS the ZeRO grad sync
        def scaled_loss(p):
            loss = loss_fn(p, *batch)
            return scaler_lib.scale_loss(loss, ls_state), loss

        # allow_int: non-float leaves (int tables etc.) ride in the tree;
        # their float0 "grads" are skipped by _ravel_floats
        diff_params = state.params
        if compressing:
            from apex_tpu.utils.collectives import pvary

            # shard-varying params stop SPMD-AD's implicit fp32 psum at
            # the grad boundary: the per-shard grads below reach the
            # compressed reduce-scatter uncombined (see amp.frontend)
            diff_params = pvary(state.params, axis_name)
        grads, loss = jax.grad(scaled_loss, has_aux=True,
                               allow_int=True)(diff_params)
        loss = jax.lax.pmean(loss, axis_name)

        g_flat, _ = _ravel_floats(grads)
        total = shard_n * ndev
        g_flat = jnp.pad(g_flat, (0, total - g_flat.shape[0]))
        if compressing:
            from apex_tpu import comm as comm_lib

            # quantized reduce-scatter IS the ZeRO grad sync: each rank
            # receives every peer's wire bytes for its own shard and
            # dequant-sums locally.  Unscale BEFORE compressing so the
            # error-feedback residual lives in loss-scale-free units.
            g_unscaled = g_flat / ls_state.loss_scale
            # finite check on the PRE-quantization grads: int8 clipping
            # could otherwise round non-finite inputs into finite wire
            # values and hide the overflow from the loss scaler
            finite_local = jnp.all(jnp.isfinite(g_unscaled))
            res = (state.comm_residual.reshape(total) if use_ef else None)
            g_local, new_res = comm_lib.compressed_reduce_scatter(
                g_unscaled, axis_name, comm_cfg,
                shard_size=shard_n, residual=res)
            g_local = g_local / ndev
        else:
            new_res = None
            # ZeRO-2: this rank only keeps its shard of the summed grads
            g_local = jax.lax.dynamic_slice(
                g_flat, (my * shard_n,), (shard_n,))
            g_local = g_local / (ndev * ls_state.loss_scale)
            finite_local = jnp.all(jnp.isfinite(g_local))

        finite = flag_and(finite_local, axis_name)

        if grad_clip_norm is not None:
            sq = jax.lax.psum(jnp.sum(g_local * g_local), axis_name)
            g_local = g_local * jnp.minimum(
                1.0, grad_clip_norm / jnp.maximum(jnp.sqrt(sq), 1e-6))

        bf_flat, _ = _ravel_floats(state.params)
        # pad BEFORE slicing: dynamic_slice clamps out-of-bounds starts,
        # which would hand the last shard a shifted window
        bf_flat = jnp.pad(bf_flat, (0, total - bf_flat.shape[0]))
        bf_local = jax.lax.dynamic_slice(bf_flat, (my * shard_n,),
                                         (shard_n,))
        master = (_combine_bits(bf_local.astype(jnp.bfloat16),
                                state.master_shard)
                  if store_param_remainders else state.master_shard)

        step_new = (state.step + 1).astype(jnp.float32)
        bc1 = 1.0 - beta1 ** step_new if bias_correction else jnp.float32(1)
        bc2 = 1.0 - beta2 ** step_new if bias_correction else jnp.float32(1)
        # closed-form XLA flat update on the local shard: the round-5
        # win-or-delete sweep retired the Pallas flat kernel (1.82x XLA
        # at its best block size — BASELINE.md kernel ledger), and XLA
        # fuses this chain into one HBM pass on every backend
        g = g_local if adam_w_mode else g_local + weight_decay * master
        m_new = beta1 * state.m_shard + (1.0 - beta1) * g
        v_new = beta2 * state.v_shard + (1.0 - beta2) * g * g
        u = -lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if adam_w_mode:
            u = u - lr * weight_decay * master
        master_new = master + u

        new_ls, overflow = scaler_lib.update_loss_scale(
            ls_cfg, ls_state, ~finite)

        def pick(new, old):
            return jnp.where(overflow, old, new)

        master_new = pick(master_new, master)
        m_new = pick(m_new, state.m_shard)
        v_new = pick(v_new, state.v_shard)
        if use_ef:
            # overflowed grads poison the residual — keep the old one
            new_res = pick(new_res, state.comm_residual.reshape(total))

        if store_param_remainders:
            bf_new_local, master_store = _split_bits(master_new)
        else:
            # communicate the param sync at compute precision
            bf_new_local = master_new.astype(param_dtype)
            master_store = master_new

        partial = ZeroTrainState(
            step=state.step + jnp.where(overflow, 0, 1),
            params=None,                 # rebuilt outside the shard_map
            master_shard=master_store,
            m_shard=m_new,
            v_shard=v_new,
            loss_scale_state=new_ls,
            comm_residual=(new_res.reshape(state.comm_residual.shape)
                           if use_ef else None),
        )
        metrics = {"loss": loss, "overflow": overflow,
                   "loss_scale": new_ls.loss_scale}
        return partial, bf_new_local, metrics

    def step_fn(state: ZeroTrainState, *batch):
        bf_flat, unravel_bf = _ravel_floats(state.params)
        in_state_spec = zero_state_specs(state, axis_name)
        out_state_spec = in_state_spec._replace(params=None)
        fn = jax.shard_map(
            shard_step, mesh=mesh,
            in_specs=(in_state_spec,) + tuple(P(axis_name) for _ in batch),
            out_specs=(out_state_spec, P(axis_name), {
                "loss": P(), "overflow": P(), "loss_scale": P()}),
        )
        partial, bf_new, metrics = fn(state, *batch)
        # 'dp'-sharded flat buffer → replicated params: GSPMD inserts the
        # ZeRO all-gather here (the reference's overlapped param sync)
        params_new = unravel_bf(bf_new[: bf_flat.shape[0]], state.params)
        return partial._replace(params=params_new), metrics

    # NB: no donate_argnums — donating any input to a jit containing this
    # shard_map raises INVALID_ARGUMENT on the tunneled TPU backend (the
    # same donation works for plain-GSPMD steps); revisit when the backend
    # accepts it, since donation halves peak optimizer-state memory here
    return init_fn, jax.jit(step_fn)
