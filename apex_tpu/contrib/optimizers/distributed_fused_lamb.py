"""DistributedFusedLAMB — ZeRO sharded-state LAMB over the 'dp' axis.

Reference: apex/contrib/optimizers/distributed_fused_lamb.py:24 — flat
buffer → fixed-size block shards across DP ranks, two-phase norm
computation (multi_tensor_l2norm partials + allreduce, then per-layer
trust ratios in lamb stage 2), overlapped reduce-scatter/all-gather.

TPU-native shape (shares the flat-shard design of
``distributed_fused_adam``): ONE fp32 flat buffer sharded over the mesh's
``dp`` axis via shard_map.  LAMB's per-parameter norms over sharded state
— the part the reference spends its two NCCL phases on — become a static
``segment_sum`` over the local shard (parameter boundaries are known at
trace time) followed by one ``psum``: phase 1 = local segment partials,
phase 2 = the cross-shard reduction, exactly the reference's
partial-l2norm + allreduce split but expressed as collectives XLA can
schedule/overlap.

Full AMP semantics ride along (dynamic loss scaling, global finite check,
skip-on-overflow), as in the Adam variant.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.amp import scaler as scaler_lib
from apex_tpu.amp.policy import _effective, policy_for_opt_level
from apex_tpu.utils.collectives import flag_and

from .distributed_fused_adam import _is_float, _ravel_floats, np_prod

__all__ = ["ZeroLambState", "make_distributed_lamb_train_step"]

_LANES = 128


class ZeroLambState(NamedTuple):
    step: jax.Array                 # i32, replicated
    params: Any                     # compute-dtype pytree, replicated
    master_shard: jax.Array         # f32 [n/dp]
    m_shard: jax.Array              # f32 [n/dp]
    v_shard: jax.Array              # f32 [n/dp]
    seg_ids: jax.Array              # i32 [n/dp] param index per slot
    loss_scale_state: Any


def _segment_ids(tree, total: int, n_params_out: int) -> jnp.ndarray:
    """int32 [total]: which float-leaf each flat slot belongs to; padding
    slots get the sentinel id ``n_params_out`` (an extra segment that is
    dropped after the segment_sum)."""
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if _is_float(x)]
    ids = []
    for i, leaf in enumerate(leaves):
        ids.append(jnp.full((np_prod(leaf.shape),), i, jnp.int32))
    ids.append(jnp.full(
        (total - sum(np_prod(x.shape) for x in leaves),),
        n_params_out, jnp.int32))
    return jnp.concatenate(ids)


def make_distributed_lamb_train_step(
    loss_fn: Callable,
    mesh: Mesh,
    *,
    axis_name: str = "dp",
    lr: float = 1e-3,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    bias_correction: bool = True,
    adam_w_mode: bool = True,
    grad_averaging: bool = True,
    max_grad_norm: Optional[float] = 1.0,
    use_nvlamb: bool = False,
    amp: str = "O2",
    loss_scale=None,
):
    """Build ``(init_fn, step_fn)`` with ZeRO sharded LAMB state.

    Semantics match ``apex_tpu.optimizers.fused_lamb`` (which matches the
    reference fused_lamb.py / multi_tensor_lamb.cu):

    - ``max_grad_norm``: grads pre-divided by ``max(gnorm / max, 1)``
      where gnorm is the global grad norm (psum over shards).
    - trust ratio ``||w|| / ||update||`` per parameter tensor; params
      with ``weight_decay == 0`` skip it unless ``use_nvlamb``.
    """
    policy = policy_for_opt_level(amp)
    param_dtype = _effective(policy.param_dtype)
    beta1, beta2 = betas
    ndev = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    if loss_scale is None:
        loss_scale = policy.loss_scale
    ls_cfg, ls_state0 = scaler_lib.init_loss_scale(loss_scale)

    def init_fn(params) -> ZeroLambState:
        f32 = jax.tree_util.tree_map(
            lambda x: jnp.array(x, jnp.float32, copy=True)
            if _is_float(x) else x, params)
        flat, _ = _ravel_floats(f32)
        n = flat.shape[0]
        shard_n = -(-n // (ndev * _LANES)) * _LANES
        total = shard_n * ndev
        flat = jnp.pad(flat, (0, total - n))
        n_params = sum(
            1 for x in jax.tree_util.tree_leaves(params) if _is_float(x))
        compute = jax.tree_util.tree_map(
            lambda x: x.astype(param_dtype) if _is_float(x) else x, f32)
        zeros = jnp.zeros((total,), jnp.float32)
        state = ZeroLambState(
            step=jnp.zeros((), jnp.int32),
            params=compute,
            master_shard=flat,
            m_shard=zeros,
            v_shard=zeros,
            seg_ids=_segment_ids(params, total, n_params),
            loss_scale_state=ls_state0,
        )
        rep = NamedSharding(mesh, P())
        shard = NamedSharding(mesh, P(axis_name))
        return jax.device_put(state, ZeroLambState(
            step=rep,
            params=jax.tree_util.tree_map(lambda _: rep, state.params),
            master_shard=shard, m_shard=shard, v_shard=shard,
            seg_ids=shard,
            loss_scale_state=jax.tree_util.tree_map(
                lambda _: rep, state.loss_scale_state),
        ))

    def shard_step(state: ZeroLambState, *batch):
        my = jax.lax.axis_index(axis_name)
        shard_n = state.m_shard.shape[0]
        ls_state = state.loss_scale_state
        # number of segments: static from the params tree
        n_params = sum(
            1 for x in jax.tree_util.tree_leaves(state.params)
            if _is_float(x))

        def scaled_loss(p):
            loss = loss_fn(p, *batch)
            return scaler_lib.scale_loss(loss, ls_state), loss

        grads, loss = jax.grad(scaled_loss, has_aux=True,
                               allow_int=True)(state.params)
        loss = jax.lax.pmean(loss, axis_name)

        g_flat, _ = _ravel_floats(grads)
        total = shard_n * ndev
        g_flat = jnp.pad(g_flat, (0, total - g_flat.shape[0]))
        g_local = jax.lax.dynamic_slice(g_flat, (my * shard_n,), (shard_n,))
        g_local = g_local / (ndev * ls_state.loss_scale)

        finite = flag_and(jnp.all(jnp.isfinite(g_local)), axis_name)

        # Phase 1a: global grad norm for the pre-division clip
        # (reference _pipeline_step global scale, fused_lamb.py:133-141)
        gsq = jax.lax.psum(jnp.sum(g_local * g_local), axis_name)
        if max_grad_norm is not None and max_grad_norm > 0:
            clip = jnp.maximum(jnp.sqrt(gsq) / max_grad_norm, 1.0)
        else:
            clip = jnp.float32(1.0)
        master = state.master_shard
        sg = g_local / clip
        if not adam_w_mode and weight_decay != 0.0:
            sg = sg + weight_decay * master

        beta3 = (1.0 - beta1) if grad_averaging else 1.0
        step_new = (state.step + 1).astype(jnp.float32)
        bc1 = 1.0 - beta1 ** step_new if bias_correction else jnp.float32(1)
        bc2 = 1.0 - beta2 ** step_new if bias_correction else jnp.float32(1)

        m_new = beta1 * state.m_shard + beta3 * sg
        v_new = beta2 * state.v_shard + (1.0 - beta2) * sg * sg
        u = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
        if adam_w_mode and weight_decay != 0.0:
            u = u + weight_decay * master

        # Phase 1b/2: per-parameter norms — local segment partials then
        # one psum (the reference's partial multi_tensor_l2norm +
        # allreduce two-phase, distributed_fused_lamb.py _pipeline_step)
        w_sq = jax.ops.segment_sum(
            master * master, state.seg_ids, num_segments=n_params + 1)
        u_sq = jax.ops.segment_sum(
            u * u, state.seg_ids, num_segments=n_params + 1)
        w_norm = jnp.sqrt(jax.lax.psum(w_sq[:n_params], axis_name))
        u_norm = jnp.sqrt(jax.lax.psum(u_sq[:n_params], axis_name))
        ratio = jnp.where((w_norm > 0) & (u_norm > 0),
                          w_norm / jnp.maximum(u_norm, 1e-30), 1.0)
        if weight_decay == 0.0 and not use_nvlamb:
            ratio = jnp.ones_like(ratio)
        # padding slots (sentinel segment) get ratio 1
        ratio_full = jnp.concatenate([ratio, jnp.ones((1,), jnp.float32)])
        r_local = ratio_full[state.seg_ids]

        master_new = master - lr * r_local * u

        new_ls, overflow = scaler_lib.update_loss_scale(
            ls_cfg, ls_state, ~finite)

        def pick(new, old):
            return jnp.where(overflow, old, new)

        master_new = pick(master_new, master)
        m_new = pick(m_new, state.m_shard)
        v_new = pick(v_new, state.v_shard)
        bf_new_local = master_new.astype(param_dtype)

        partial = ZeroLambState(
            step=state.step + jnp.where(overflow, 0, 1),
            params=None,
            master_shard=master_new,
            m_shard=m_new,
            v_shard=v_new,
            seg_ids=state.seg_ids,
            loss_scale_state=new_ls,
        )
        metrics = {"loss": loss, "overflow": overflow,
                   "loss_scale": new_ls.loss_scale,
                   "grad_norm": jnp.sqrt(gsq)}
        return partial, bf_new_local, metrics

    def step_fn(state: ZeroLambState, *batch):
        bf_flat, unravel_bf = _ravel_floats(state.params)
        pspec = jax.tree_util.tree_map(lambda _: P(), state.params)
        ls_spec = jax.tree_util.tree_map(
            lambda _: P(), state.loss_scale_state)
        in_state_spec = ZeroLambState(
            step=P(), params=pspec, master_shard=P(axis_name),
            m_shard=P(axis_name), v_shard=P(axis_name),
            seg_ids=P(axis_name), loss_scale_state=ls_spec)
        out_state_spec = in_state_spec._replace(params=None)
        fn = jax.shard_map(
            shard_step, mesh=mesh,
            in_specs=(in_state_spec,) + tuple(P(axis_name) for _ in batch),
            out_specs=(out_state_spec, P(axis_name), {
                "loss": P(), "overflow": P(), "loss_scale": P(),
                "grad_norm": P()}),
        )
        partial, bf_new, metrics = fn(state, *batch)
        # sharded flat buffer → replicated params (GSPMD all-gather)
        params_new = unravel_bf(bf_new[: bf_flat.shape[0]], state.params)
        return partial._replace(params=params_new), metrics

    return init_fn, jax.jit(step_fn)
