from apex_tpu.contrib.optimizers.distributed_fused_adam import (  # noqa: F401
    ZeroTrainState,
    make_distributed_adam_train_step,
)
