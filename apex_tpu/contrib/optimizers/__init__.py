from apex_tpu.contrib.optimizers.distributed_fused_adam import (  # noqa: F401
    ZeroTrainState,
    make_distributed_adam_train_step,
)
from apex_tpu.contrib.optimizers.distributed_fused_lamb import (  # noqa: F401
    ZeroLambState,
    make_distributed_lamb_train_step,
)
