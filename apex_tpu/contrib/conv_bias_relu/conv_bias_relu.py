"""Fused Conv + bias + ReLU (+ mask / frozen scale-bias) ops.

Reference: ``apex/contrib/conv_bias_relu/conv_bias_relu.py:10`` — four
autograd Functions (``ConvBiasReLU``, ``ConvBias``, ``ConvBiasMaskReLU``,
``ConvFrozenScaleBiasReLU``) backed by the cudnn-frontend v8 fusion
engine (contrib/csrc/conv_bias_relu.cpp + 2k LoC of vendored
cudnn-frontend headers).

On TPU this entire component is an XLA fusion, *verified*, not assumed
(v5e, round 2): the compiled HLO for a jitted ``conv → +bias → relu``
chain (NHWC bf16 64×56×56×64 → 3x3×64) contains exactly one
convolution, emitted as a ``kOutput`` fusion whose fused computation
carries the bias add and the relu ``maximum`` — the elementwise
epilogue rides the conv's output window write, which is exactly what
the cudnn-frontend fusion engine buys the reference.  Wall-clock deltas
vs the bare conv are within the tunneled chip's run-to-run noise
(0.6%–19% across repeats at this shape — the HLO, not the timer, is the
ground truth here).  ``tests/test_contrib_ops.py`` asserts numerics;
``python -m apex_tpu.contrib.conv_bias_relu.conv_bias_relu`` reproduces
the timing on a chip.

API parity: same positional signatures (x, weight, bias, padding,
stride), NHWC x HWIO layouts (the reference's fast path is NHWC too),
autodiff via plain ``jax.grad`` (no custom_vjp needed — XLA generates
the fused dgrad/wgrad epilogues).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "ConvBiasReLU",
    "ConvBias",
    "ConvBiasMaskReLU",
    "ConvFrozenScaleBiasReLU",
]


def _conv(x, weight, padding, stride):
    if isinstance(padding, int):
        padding = [(padding, padding), (padding, padding)]
    if isinstance(stride, int):
        stride = (stride, stride)
    return jax.lax.conv_general_dilated(
        x, weight.astype(x.dtype), stride, padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def ConvBiasReLU(x, weight, bias, padding=1, stride=1):
    """relu(conv(x, w) + b) — one fused XLA computation under jit."""
    return jax.nn.relu(_conv(x, weight, padding, stride)
                       + bias.reshape(-1).astype(x.dtype))


def ConvBias(x, weight, bias, padding=1, stride=1):
    return _conv(x, weight, padding, stride) + bias.reshape(-1).astype(
        x.dtype)


def ConvBiasMaskReLU(x, weight, bias, mask, padding=1, stride=1):
    """relu((conv(x, w) + b) * mask) — the reference's masked variant
    (used for DropBlock-style regularization)."""
    y = _conv(x, weight, padding, stride) + bias.reshape(-1).astype(x.dtype)
    return jax.nn.relu(y * mask.astype(y.dtype))


def ConvFrozenScaleBiasReLU(x, weight, scale, bias, padding=1, stride=1):
    """relu(conv(x, w) * scale + bias) — conv into a folded frozen-BN
    affine (reference ConvFrozenScaleBiasReLU_)."""
    y = _conv(x, weight, padding, stride)
    return jax.nn.relu(y * scale.reshape(-1).astype(y.dtype)
                       + bias.reshape(-1).astype(y.dtype))


def _measure():  # pragma: no cover - run manually on a chip
    import time

    import numpy as np

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(256, 56, 56, 64), jnp.float32)
    w = jnp.asarray(rs.randn(3, 3, 64, 64) * 0.05, jnp.float32)
    b = jnp.asarray(rs.randn(64), jnp.float32)

    bare = jax.jit(lambda x: _conv(x, w, 1, 1))
    fused = jax.jit(lambda x: ConvBiasReLU(x, w, b))

    def timeit(f):
        y = f(x); float(np.asarray(y).ravel()[0])
        t0 = time.perf_counter()
        for _ in range(20):
            y = f(x)
        float(np.asarray(y).ravel()[0])
        return (time.perf_counter() - t0) / 20

    t_bare, t_fused = timeit(bare), timeit(fused)
    print(f"conv {t_bare*1e3:.3f} ms, conv+bias+relu {t_fused*1e3:.3f} ms "
          f"(epilogue overhead {100*(t_fused/t_bare-1):.1f}%)")


if __name__ == "__main__":  # pragma: no cover
    _measure()
