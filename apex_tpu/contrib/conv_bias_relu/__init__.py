from .conv_bias_relu import (  # noqa: F401
    ConvBias,
    ConvBiasMaskReLU,
    ConvBiasReLU,
    ConvFrozenScaleBiasReLU,
)
