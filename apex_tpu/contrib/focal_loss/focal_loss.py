"""Fused sigmoid focal loss.

Reference: apex/contrib/csrc/focal_loss/focal_loss_cuda_kernel.cu bound as
``focal_loss_cuda`` and wrapped at apex/contrib/focal_loss/focal_loss.py:6
(``FocalLoss.apply(cls_output, cls_targets_at_level, num_positives_sum,
num_real_classes, alpha, gamma, label_smoothing)``). Parity oracle (their
test): ``torchvision.ops.sigmoid_focal_loss(x, one_hot(y), alpha, gamma,
reduction='sum') / num_positives_sum``.

On TPU the "fusion" is XLA's: the whole expression compiles to one fused
elementwise pass over the logits; no custom kernel needed (the CUDA
version's win was avoiding eager-mode materialization).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["focal_loss", "FocalLoss"]


def focal_loss(
    cls_output: jax.Array,
    cls_targets: jax.Array,
    num_positives_sum: jax.Array,
    num_real_classes: int,
    alpha: float,
    gamma: float,
    label_smoothing: float = 0.0,
) -> jax.Array:
    """Sum-reduced sigmoid focal loss over [N..., K] logits, divided by
    ``num_positives_sum``.

    ``cls_targets`` holds integer class ids in [-2, K): ``-1`` means "no
    positive class" (pure background row, all-negative targets) and ``-2``
    means "ignored match" — zero loss and zero gradient for the whole row
    (kernel:60-67 skips y==-2 entirely). Classes at index
    ≥ ``num_real_classes`` (padding columns) are excluded from the loss.
    """
    x = cls_output.astype(jnp.float32)
    k = x.shape[-1]
    y = jax.nn.one_hot(cls_targets, k, dtype=jnp.float32)

    if label_smoothing > 0.0:
        # The kernel smooths with a constant K=2 (sigmoid/binary smoothing,
        # kernel:35-45): positive target 1-s+s/2, negative target s/2 —
        # NOT 1/num_classes.
        s = label_smoothing
        y_eff = y * (1.0 - s) + s / 2.0
    else:
        y_eff = y

    # bce with logits, numerically stable
    bce = jnp.maximum(x, 0.0) - x * y_eff + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p = jax.nn.sigmoid(x)
    # modulating and alpha factors use the HARD targets (kernel:88-113)
    p_t = p * y + (1.0 - p) * (1.0 - y)
    alpha_t = alpha * y + (1.0 - alpha) * (1.0 - y)
    loss = alpha_t * (1.0 - p_t) ** gamma * bce

    if num_real_classes < k:
        valid = jnp.arange(k) < num_real_classes
        loss = jnp.where(valid, loss, 0.0)

    loss = jnp.where((cls_targets == -2)[..., None], 0.0, loss)

    return jnp.sum(loss) / jnp.asarray(num_positives_sum, jnp.float32)


class FocalLoss:
    """Reference-API shim: ``FocalLoss.apply(...)``
    (apex/contrib/focal_loss/focal_loss.py:6)."""

    apply = staticmethod(focal_loss)
