"""FusedAdam — Adam/AdamW with the whole update as one fused computation.

Reference: apex/optimizers/fused_adam.py (step :127, multi-tensor dispatch
:264-303; kernel csrc/multi_tensor_adam.cu ``AdamFunctor``). Drop-in
semantics:

- ``adam_w_mode=True`` → decoupled weight decay (AdamW); False → L2-style
  decay added to the gradient (classic Adam).
- ``bias_correction`` flag identical to the reference.
- capturable semantics by construction: ``step`` is device-side, lr may be a
  traced scalar or a schedule.
- ``amsgrad`` is rejected exactly like the reference (fused_adam.py raises
  RuntimeError: "amsgrad is not supported").

The update is elementwise over every param; under jit XLA fuses it across
the whole tree (the moral equivalent of one ``multi_tensor_apply<4>`` launch
covering 320 params — csrc/multi_tensor_apply.cuh:44).
``use_flat_buffer=True`` routes through the flattened-buffer update
(``ops.flat_adam`` — pure XLA since the round-5 win-or-delete sweep
retired the Pallas kernel); measured on v5e that is ~30x *slower* for
tree-stored state (ravel/unravel adds 7 HBM copies a step that XLA's
fusion avoids), so leave it off here — the flat path's purpose is the
ZeRO-sharded optimizer whose state is stored flat
(``apex_tpu.contrib.optimizers.distributed_fused_adam``), where no
per-step concat exists.  ``use_pallas`` survives as a deprecated alias
of the flag.
"""

from __future__ import annotations

import warnings
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    GradientTransformation,
    ScheduleOrScalar,
    resolve_lr,
    tree_map_float,
    tree_zeros_like_f32,
    with_norm_telemetry,
)

__all__ = ["FusedAdam", "fused_adam", "AdamState"]


class AdamState(NamedTuple):
    step: jax.Array
    exp_avg: Any
    exp_avg_sq: Any


def fused_adam(
    lr: ScheduleOrScalar = 1e-3,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    adam_w_mode: bool = True,
    bias_correction: bool = True,
    amsgrad: bool = False,
    use_flat_buffer: bool = False,
    norm_telemetry: bool = False,
    use_pallas: Optional[bool] = None,
) -> GradientTransformation:
    """``use_flat_buffer=True`` runs the update over one flattened
    buffer (``ops.flat_adam`` — pure XLA; the Pallas kernel that once
    lived there lost its round-5 win-or-delete gate).  Slower for
    tree-stored state; see the module docstring.  ``use_pallas`` is the
    deprecated pre-round-5 name for the same flag.

    ``norm_telemetry=True`` wraps the transformation with
    ``_common.with_norm_telemetry``: the state additionally carries the
    last step's global grad/update/param norms for host-side recording
    (``record_opt_norms``).  Off by default — it adds full-tree
    reductions to the update."""
    if amsgrad:
        raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
    if use_pallas is not None:
        warnings.warn(
            "fused_adam(use_pallas=...) is deprecated: the flat-buffer "
            "path has been pure XLA since the Pallas kernel was deleted "
            "in round 5 — use use_flat_buffer=", DeprecationWarning,
            stacklevel=2)
        use_flat_buffer = use_pallas
    beta1, beta2 = betas

    def init(params) -> AdamState:
        return AdamState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=tree_zeros_like_f32(params),
            exp_avg_sq=tree_zeros_like_f32(params),
        )

    def update(grads, state: AdamState, params=None):
        if params is None:
            raise ValueError("fused_adam requires params")
        step = state.step + 1
        lr_t = resolve_lr(lr, step)
        if bias_correction:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        if use_flat_buffer:
            from apex_tpu.ops.flat_adam import flat_adam_update

            updates, m, v = flat_adam_update(
                grads, params, state.exp_avg, state.exp_avg_sq,
                lr_t, beta1, beta2, eps, weight_decay, bc1, bc2,
                adam_w_mode,
            )
            return updates, AdamState(step, m, v)

        def adj_grad(g, p):
            g32 = g.astype(jnp.float32)
            if not adam_w_mode and weight_decay != 0.0:
                g32 = g32 + weight_decay * p.astype(jnp.float32)
            return g32

        # Three maps instead of one tuple-valued map; XLA CSE merges the
        # recomputed adj_grad under jit, so this is still one fused update.
        m_tree = tree_map_float(
            lambda g, p, m: beta1 * m + (1.0 - beta1) * adj_grad(g, p),
            grads, params, state.exp_avg,
        )
        v_tree = tree_map_float(
            lambda g, p, v: beta2 * v + (1.0 - beta2) * jnp.square(adj_grad(g, p)),
            grads, params, state.exp_avg_sq,
        )

        def upd_leaf(m, v, p):
            denom = jnp.sqrt(v / bc2) + eps
            upd = -lr_t * (m / bc1) / denom
            if adam_w_mode and weight_decay != 0.0:
                upd = upd - lr_t * weight_decay * p.astype(jnp.float32)
            return upd

        updates = tree_map_float(upd_leaf, m_tree, v_tree, params)
        return updates, AdamState(step, m_tree, v_tree)

    tx = GradientTransformation(init, update)
    return with_norm_telemetry(tx) if norm_telemetry else tx


# Drop-in-named alias: `FusedAdam(lr=...)` reads like the reference ctor.
FusedAdam = fused_adam
