"""FusedSGD — SGD with momentum/dampening/nesterov/weight-decay.

Reference: apex/optimizers/fused_sgd.py (kernel csrc/multi_tensor_sgd_kernel.cu),
which matches torch.optim.SGD semantics:

    d = g + wd * p
    buf = momentum * buf + (1 - dampening) * d        (first step: buf = d)
    update = d + momentum * buf        if nesterov
           = buf                       otherwise
    p -= lr * update
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    GradientTransformation,
    ScheduleOrScalar,
    resolve_lr,
    tree_map_float,
    tree_zeros_like_f32,
)

__all__ = ["FusedSGD", "fused_sgd", "SGDState"]


class SGDState(NamedTuple):
    step: jax.Array
    momentum_buffer: Any


def fused_sgd(
    lr: ScheduleOrScalar = 1e-3,
    momentum: float = 0.0,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
) -> GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError(
            "Nesterov momentum requires a momentum and zero dampening"
        )

    def init(params) -> SGDState:
        return SGDState(
            step=jnp.zeros((), jnp.int32),
            momentum_buffer=tree_zeros_like_f32(params),
        )

    def update(grads, state: SGDState, params=None):
        if params is None:
            raise ValueError("fused_sgd requires params")
        step = state.step + 1
        lr_t = resolve_lr(lr, step)
        first = state.step == 0

        def bufs(g, p, b):
            d = g.astype(jnp.float32)
            if weight_decay != 0.0:
                d = d + weight_decay * p.astype(jnp.float32)
            if momentum == 0.0:
                return d
            # torch keeps buf = d on the very first step (no dampening).
            return jnp.where(
                first, d, momentum * b + (1.0 - dampening) * d
            )

        new_buf = tree_map_float(bufs, grads, params, state.momentum_buffer)

        def upd(g, p, b):
            d = g.astype(jnp.float32)
            if weight_decay != 0.0:
                d = d + weight_decay * p.astype(jnp.float32)
            if momentum == 0.0:
                u = d
            elif nesterov:
                u = d + momentum * b
            else:
                u = b
            return -lr_t * u

        updates = tree_map_float(upd, grads, params, new_buf)
        return updates, SGDState(step, new_buf)

    return GradientTransformation(init, update)


FusedSGD = fused_sgd
