"""FusedAdagrad (reference: apex/optimizers/fused_adagrad.py,
csrc/multi_tensor_adagrad.cu ``AdagradFunctor``):

    h += g^2
    p -= lr * g / (sqrt(h) + eps)          (+ decoupled ``adagrad_w_mode``
    weight decay: p -= lr * wd * p)

The reference kernel applies L2-style weight decay *into the gradient*
(mode 0) or decoupled (mode 1, default 0).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    GradientTransformation,
    ScheduleOrScalar,
    resolve_lr,
    tree_map_float,
    tree_zeros_like_f32,
)

__all__ = ["FusedAdagrad", "fused_adagrad", "AdagradState"]


class AdagradState(NamedTuple):
    step: jax.Array
    sum_sq: Any


def fused_adagrad(
    lr: ScheduleOrScalar = 1e-2,
    eps: float = 1e-10,
    weight_decay: float = 0.0,
    adagrad_w_mode: bool = False,
) -> GradientTransformation:
    def init(params) -> AdagradState:
        return AdagradState(
            step=jnp.zeros((), jnp.int32),
            sum_sq=tree_zeros_like_f32(params),
        )

    def update(grads, state: AdagradState, params=None):
        if params is None:
            raise ValueError("fused_adagrad requires params")
        step = state.step + 1
        lr_t = resolve_lr(lr, step)

        def h_leaf(g, p, h):
            g32 = g.astype(jnp.float32)
            if not adagrad_w_mode and weight_decay != 0.0:
                g32 = g32 + weight_decay * p.astype(jnp.float32)
            return h + jnp.square(g32)

        h_tree = tree_map_float(h_leaf, grads, params, state.sum_sq)

        def upd_leaf(g, p, h):
            g32 = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not adagrad_w_mode and weight_decay != 0.0:
                g32 = g32 + weight_decay * p32
            u = -lr_t * g32 / (jnp.sqrt(h) + eps)
            if adagrad_w_mode and weight_decay != 0.0:
                u = u - lr_t * weight_decay * p32
            return u

        updates = tree_map_float(upd_leaf, grads, params, h_tree)
        return updates, AdagradState(step, h_tree)

    return GradientTransformation(init, update)


FusedAdagrad = fused_adagrad
