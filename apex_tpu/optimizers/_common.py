"""Shared plumbing for the fused optimizers.

The reference optimizers are torch.optim.Optimizer subclasses whose ``step``
groups params by dtype and fires one ``multi_tensor_applier`` launch per
group (e.g. apex/optimizers/fused_adam.py:127,264-303). Under jit the whole
update is one fused XLA computation already, so each optimizer here is an
optax-style ``GradientTransformation``:

    tx = fused_adam(lr=1e-3)
    state = tx.init(params)
    updates, state = tx.update(grads, state, params)
    params = apply_updates(params, updates)      # p + u

"Capturable" mode (CUDA-graph-safe tensor lr/step, fused_adam.py capturable
arg) is the default and only mode: hyperparameters may be Python floats
(baked into the graph) or jax scalars (donated each step), and ``step`` lives
in device memory.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Union

import jax
import jax.numpy as jnp

Scalar = Union[float, jax.Array]

__all__ = [
    "Scalar",
    "GradientTransformation",
    "apply_updates",
    "tree_map_float",
    "tree_zeros_like_f32",
    "global_norm",
    "ScheduleOrScalar",
    "resolve_lr",
    "norm_metrics",
    "NormTelemetryState",
    "with_norm_telemetry",
    "latest_norms",
    "record_opt_norms",
]


class GradientTransformation(NamedTuple):
    """Minimal optax-compatible pair (works anywhere optax transforms do)."""

    init: Callable[[Any], Any]
    update: Callable[..., Any]


def apply_updates(params, updates):
    return jax.tree_util.tree_map(
        lambda p, u: (p + u.astype(p.dtype)) if u is not None else p,
        params,
        updates,
    )


def is_float_leaf(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


_is_float = is_float_leaf


def tree_map_float(fn, *trees):
    """Map over float leaves; pass non-float leaves through unchanged."""
    return jax.tree_util.tree_map(
        lambda x, *rest: fn(x, *rest) if _is_float(x) else x, *trees
    )


def tree_zeros_like_f32(params):
    """fp32 optimizer-state slots regardless of param dtype (the reference
    keeps exp_avg in param dtype, but with master weights those are fp32;
    fp32 slots are strictly more accurate and free on TPU)."""
    return tree_map_float(
        lambda p: jnp.zeros(p.shape, jnp.float32), params
    )


def global_norm(tree) -> jax.Array:
    leaves = [
        x for x in jax.tree_util.tree_leaves(tree) if _is_float(x)
    ]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    )


_NORM_KEYS = ("grad_norm", "update_norm", "param_norm",
              "update_to_param_ratio")


def norm_metrics(grads, updates=None, params=None) -> dict:
    """Global-norm telemetry scalars for a step's metrics dict.

    Returns fp32 device scalars: ``grad_norm`` always; ``update_norm``
    / ``param_norm`` when their trees are given; and
    ``update_to_param_ratio`` (the relative step size, LAMB-trust-ratio
    flavored) when both are.  OFF by default everywhere it is wired
    (``amp.frontend.make_train_step(norm_telemetry=...)``,
    ``fused_adam``/``fused_lamb`` ``norm_telemetry=``): each norm is a
    full-tree reduction the update would not otherwise pay.
    """
    out = {"grad_norm": global_norm(grads)}
    if updates is not None:
        out["update_norm"] = global_norm(updates)
    if params is not None:
        out["param_norm"] = global_norm(params)
    if updates is not None and params is not None:
        out["update_to_param_ratio"] = out["update_norm"] / jnp.maximum(
            out["param_norm"], 1e-12)
    return out


class NormTelemetryState(NamedTuple):
    """Optimizer state wrapper carrying the last update's norms as
    returned aux values — the host-callback-free channel out of jit."""

    inner: Any
    norms: Any


def with_norm_telemetry(tx: GradientTransformation) -> GradientTransformation:
    """Wrap a transformation so every ``update`` also computes
    :func:`norm_metrics` and carries them in the state; read them after
    the step with :func:`latest_norms` / :func:`record_opt_norms`.

    The wrapped ``update`` must receive ``params`` (both fused
    optimizers require it anyway) so the state keeps a fixed pytree
    structure across init/update — donation-safe.
    """

    def init(params):
        zeros = {k: jnp.zeros((), jnp.float32) for k in _NORM_KEYS}
        return NormTelemetryState(tx.init(params), zeros)

    def update(grads, state: NormTelemetryState, params=None):
        updates, inner = tx.update(grads, state.inner, params)
        norms = norm_metrics(grads, updates, params)
        for k in _NORM_KEYS:   # fixed structure even if params was None
            norms.setdefault(k, jnp.zeros((), jnp.float32))
        return updates, NormTelemetryState(inner, norms)

    return GradientTransformation(init, update)


def latest_norms(opt_state):
    """Host copies of the norms a ``with_norm_telemetry`` state carries
    (a plain dict of floats), or None for unwrapped states."""
    if isinstance(opt_state, NormTelemetryState):
        return {k: float(v) for k, v in
                jax.device_get(opt_state.norms).items()}
    return None


def record_opt_norms(opt_state, prefix: str = "optim") -> None:
    """Record :func:`latest_norms` as ``<prefix>.<key>`` gauges.
    No-op when telemetry is disabled or the state is unwrapped."""
    from apex_tpu.observability import metrics as _telemetry

    reg = _telemetry.registry()
    if reg is None:
        return
    norms = latest_norms(opt_state)
    if norms:
        for k, v in norms.items():
            reg.gauge(f"{prefix}.{k}").set(v)


ScheduleOrScalar = Union[float, jax.Array, Callable[[jax.Array], jax.Array]]


def resolve_lr(lr: ScheduleOrScalar, step: jax.Array) -> jax.Array:
    """Accept a constant or an optax-style schedule ``lr(step)``."""
    if callable(lr):
        return jnp.asarray(lr(step), jnp.float32)
    return jnp.asarray(lr, jnp.float32)
