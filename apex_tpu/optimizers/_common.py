"""Shared plumbing for the fused optimizers.

The reference optimizers are torch.optim.Optimizer subclasses whose ``step``
groups params by dtype and fires one ``multi_tensor_applier`` launch per
group (e.g. apex/optimizers/fused_adam.py:127,264-303). Under jit the whole
update is one fused XLA computation already, so each optimizer here is an
optax-style ``GradientTransformation``:

    tx = fused_adam(lr=1e-3)
    state = tx.init(params)
    updates, state = tx.update(grads, state, params)
    params = apply_updates(params, updates)      # p + u

"Capturable" mode (CUDA-graph-safe tensor lr/step, fused_adam.py capturable
arg) is the default and only mode: hyperparameters may be Python floats
(baked into the graph) or jax scalars (donated each step), and ``step`` lives
in device memory.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Union

import jax
import jax.numpy as jnp

Scalar = Union[float, jax.Array]

__all__ = [
    "Scalar",
    "GradientTransformation",
    "apply_updates",
    "tree_map_float",
    "tree_zeros_like_f32",
    "global_norm",
    "ScheduleOrScalar",
    "resolve_lr",
]


class GradientTransformation(NamedTuple):
    """Minimal optax-compatible pair (works anywhere optax transforms do)."""

    init: Callable[[Any], Any]
    update: Callable[..., Any]


def apply_updates(params, updates):
    return jax.tree_util.tree_map(
        lambda p, u: (p + u.astype(p.dtype)) if u is not None else p,
        params,
        updates,
    )


def is_float_leaf(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


_is_float = is_float_leaf


def tree_map_float(fn, *trees):
    """Map over float leaves; pass non-float leaves through unchanged."""
    return jax.tree_util.tree_map(
        lambda x, *rest: fn(x, *rest) if _is_float(x) else x, *trees
    )


def tree_zeros_like_f32(params):
    """fp32 optimizer-state slots regardless of param dtype (the reference
    keeps exp_avg in param dtype, but with master weights those are fp32;
    fp32 slots are strictly more accurate and free on TPU)."""
    return tree_map_float(
        lambda p: jnp.zeros(p.shape, jnp.float32), params
    )


def global_norm(tree) -> jax.Array:
    leaves = [
        x for x in jax.tree_util.tree_leaves(tree) if _is_float(x)
    ]
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    )


ScheduleOrScalar = Union[float, jax.Array, Callable[[jax.Array], jax.Array]]


def resolve_lr(lr: ScheduleOrScalar, step: jax.Array) -> jax.Array:
    """Accept a constant or an optax-style schedule ``lr(step)``."""
    if callable(lr):
        return jnp.asarray(lr(step), jnp.float32)
    return jnp.asarray(lr, jnp.float32)
