"""FusedNovoGrad — NovoGrad with per-tensor second-moment norms.

Reference: apex/optimizers/fused_novograd.py + csrc/multi_tensor_novograd.cu.
The second moment is a *per-tensor scalar*: an EMA of the grad norm (stored
as a norm, not its square — fused_novograd.py:159 comment), blended as
``v = beta2*v + (1-beta2)*||g||`` (multi_tensor_novograd.cu:164) with bias
correction ``sqrt(1-beta2^t)`` (:151). Knobs preserved: ``reg_inside_moment``
(kernel MOMENT_MODE_0 vs 1, :98-113), ``grad_averaging`` (beta3),
``norm_type`` (2 or 0=inf), ``init_zero`` (start EMA at 0 vs first norm so
the first blend is a no-op, fused_novograd.py:162-176).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    GradientTransformation,
    ScheduleOrScalar,
    resolve_lr,
    tree_map_float,
    tree_zeros_like_f32,
)

__all__ = ["FusedNovoGrad", "fused_novograd", "NovoGradState"]


class NovoGradState(NamedTuple):
    step: jax.Array
    exp_avg: Any
    exp_avg_norm: Any   # per-tensor scalar norms


def fused_novograd(
    lr: ScheduleOrScalar = 1e-3,
    betas: Tuple[float, float] = (0.95, 0.98),
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    bias_correction: bool = True,
    reg_inside_moment: bool = False,
    grad_averaging: bool = True,
    norm_type: int = 2,
    init_zero: bool = False,
) -> GradientTransformation:
    if norm_type not in (0, 2):
        raise RuntimeError("FusedNovoGrad only supports l2/inf norm now.")
    beta1, beta2 = betas
    beta3 = (1.0 - beta1) if grad_averaging else 1.0

    def _norm(g32):
        if norm_type == 0:
            return jnp.max(jnp.abs(g32))
        return jnp.sqrt(jnp.sum(jnp.square(g32)))

    def init(params) -> NovoGradState:
        return NovoGradState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=tree_zeros_like_f32(params),
            exp_avg_norm=tree_map_float(
                lambda p: jnp.zeros((), jnp.float32), params
            ),
        )

    def update(grads, state: NovoGradState, params=None):
        if params is None:
            raise ValueError("fused_novograd requires params")
        step = state.step + 1
        lr_t = resolve_lr(lr, step)
        first = state.step == 0
        if bias_correction:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = jnp.sqrt(1.0 - beta2 ** step.astype(jnp.float32))
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def v_leaf(g, v):
            n = _norm(g.astype(jnp.float32))
            if init_zero:
                v_prev = v
            else:
                # init with first-step norm so the first blend is a no-op
                v_prev = jnp.where(first, n, v)
            if norm_type == 2:
                # Reference blends L2 norms in quadrature
                # (multi_tensor_novograd.cu multi_tensor_norm_out_cuda:
                # gn = sqrt(beta2*gn^2 + (1-beta2)*n^2)).
                return jnp.sqrt(
                    beta2 * jnp.square(v_prev) + (1.0 - beta2) * jnp.square(n)
                )
            return beta2 * v_prev + (1.0 - beta2) * n

        v_tree = tree_map_float(v_leaf, grads, state.exp_avg_norm)

        def m_leaf(g, p, m, v):
            g32, p32 = g.astype(jnp.float32), p.astype(jnp.float32)
            if reg_inside_moment:  # MOMENT_MODE_0
                denom = v / bc2 + eps
                d = g32 / denom + weight_decay * p32
                return beta1 * m + beta3 * d
            return beta1 * m + beta3 * g32

        m_tree = tree_map_float(
            m_leaf, grads, params, state.exp_avg, v_tree
        )

        def upd_leaf(m, v, p):
            if reg_inside_moment:
                return -lr_t * (m / bc1)
            denom = v / bc2 + eps
            u = (m / bc1) / denom + weight_decay * p.astype(jnp.float32)
            return -lr_t * u

        updates = tree_map_float(upd_leaf, m_tree, v_tree, params)
        return updates, NovoGradState(step, m_tree, v_tree)

    return GradientTransformation(init, update)


FusedNovoGrad = fused_novograd
