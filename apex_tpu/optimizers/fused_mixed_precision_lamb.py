"""FusedMixedPrecisionLamb.

Reference: apex/optimizers/fused_mixed_precision_lamb.py — LAMB operating on
low-precision model weights with fp32 master copies held inside the
optimizer, fully capturable (tensor lr/step).

In apex_tpu the master-weight machinery is the AMP layer's job
(``amp.make_train_step`` keeps fp32 masters and re-casts model params each
step), so the optimizer itself is exactly :func:`fused_lamb` applied to the
fp32 masters; this module exists for name parity and wires the recommended
pairing::

    tx = FusedMixedPrecisionLamb(lr=1e-3)
    init, step = amp.make_train_step(loss_fn, tx, "O5")   # bf16 + masters
"""

from apex_tpu.optimizers.fused_lamb import fused_lamb

__all__ = ["FusedMixedPrecisionLamb", "fused_mixed_precision_lamb"]

fused_mixed_precision_lamb = fused_lamb
FusedMixedPrecisionLamb = fused_lamb
