"""FusedLARS — layer-wise adaptive rate scaling on top of momentum SGD.

Reference: apex/optimizers/fused_lars.py + csrc/multi_tensor_lars.cu:79-140:

    trust = tc * ||p|| / (||g|| + wd*||p|| + eps)    (1 if either norm is 0)
    scaled_lr = lr * trust                           (plain lr for skipped
                                                      groups, e.g. BN/bias)
    d    = g + wd * p
    mom  = momentum * mom - scaled_lr * d
    p   += momentum * mom - scaled_lr * d            if nesterov
    p   += mom                                       otherwise

The reference marks whole param groups ``is_skipped``; here a
``skip_predicate(path) -> bool`` selects params that bypass the trust ratio
(conventionally biases and norm params).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    GradientTransformation,
    ScheduleOrScalar,
    resolve_lr,
    tree_zeros_like_f32,
)

__all__ = ["FusedLARS", "fused_lars", "LARSState"]


class LARSState(NamedTuple):
    step: jax.Array
    momentum_buffer: Any


def fused_lars(
    lr: ScheduleOrScalar = 1e-2,
    momentum: float = 0.9,
    dampening: float = 0.0,
    weight_decay: float = 0.0,
    nesterov: bool = False,
    trust_coefficient: float = 0.001,
    eps: float = 0.0,
    skip_predicate: Optional[Callable[[tuple], bool]] = None,
) -> GradientTransformation:
    if nesterov and (momentum <= 0 or dampening != 0):
        raise ValueError(
            "Nesterov momentum requires a momentum and zero dampening"
        )

    def init(params) -> LARSState:
        return LARSState(
            step=jnp.zeros((), jnp.int32),
            momentum_buffer=tree_zeros_like_f32(params),
        )

    def update(grads, state: LARSState, params=None):
        if params is None:
            raise ValueError("fused_lars requires params")
        step = state.step + 1
        lr_t = resolve_lr(lr, step)

        def scaled_lr_and_d(path, g, p):
            g32, p32 = g.astype(jnp.float32), p.astype(jnp.float32)
            if skip_predicate is not None and skip_predicate(path):
                scaled_lr = lr_t
            else:
                p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
                g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
                trust = jnp.where(
                    (g_norm > 0.0) & (p_norm > 0.0),
                    trust_coefficient * p_norm
                    / (g_norm + p_norm * weight_decay + eps),
                    1.0,
                )
                scaled_lr = lr_t * trust
            return scaled_lr, g32 + weight_decay * p32

        from apex_tpu.optimizers._common import is_float_leaf as _float

        def mom_leaf(path, g, p, mom):
            if not _float(g):
                return mom
            scaled_lr, d = scaled_lr_and_d(path, g, p)
            return momentum * mom - scaled_lr * d

        new_mom = jax.tree_util.tree_map_with_path(
            mom_leaf, grads, params, state.momentum_buffer
        )

        def upd_leaf(path, g, p, m_new):
            if not _float(g):
                return g
            scaled_lr, d = scaled_lr_and_d(path, g, p)
            if nesterov:
                return momentum * m_new - scaled_lr * d
            return m_new

        updates = jax.tree_util.tree_map_with_path(
            upd_leaf, grads, params, new_mom
        )
        return updates, LARSState(step, new_mom)

    return GradientTransformation(init, update)


FusedLARS = fused_lars
