"""apex_tpu.optimizers — fused optimizers (reference: apex/optimizers/).

All are optax-compatible ``GradientTransformation``s whose whole update fuses
into the surrounding jitted train step; ``FusedAdam`` additionally offers the
flattened-buffer update (``use_flat_buffer=True`` — pure XLA over one flat
vector, the layout the ZeRO-sharded ``distributed_fused_adam`` stores
natively; the Pallas kernel that once backed it was deleted after losing the
round-5 on-chip win-or-delete sweep).
"""

from apex_tpu.optimizers._common import (  # noqa: F401
    GradientTransformation,
    apply_updates,
    global_norm,
)
from apex_tpu.optimizers.fused_adam import AdamState, FusedAdam, fused_adam  # noqa: F401
from apex_tpu.optimizers.fused_adagrad import (  # noqa: F401
    AdagradState,
    FusedAdagrad,
    fused_adagrad,
)
from apex_tpu.optimizers.fused_lamb import FusedLAMB, LambState, fused_lamb  # noqa: F401
from apex_tpu.optimizers.fused_lars import FusedLARS, LARSState, fused_lars  # noqa: F401
from apex_tpu.optimizers.fused_mixed_precision_lamb import (  # noqa: F401
    FusedMixedPrecisionLamb,
    fused_mixed_precision_lamb,
)
from apex_tpu.optimizers.fused_novograd import (  # noqa: F401
    FusedNovoGrad,
    NovoGradState,
    fused_novograd,
)
from apex_tpu.optimizers.fused_sgd import FusedSGD, SGDState, fused_sgd  # noqa: F401
