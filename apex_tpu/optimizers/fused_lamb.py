"""FusedLAMB — layer-wise adaptive moments (LAMB) with global grad clipping.

Reference: apex/optimizers/fused_lamb.py — two-phase update matching
csrc/multi_tensor_lamb.cu: phase 1 computes the global grad norm and the
Adam-style moment update per param; phase 2 rescales each param's update by
the trust ratio ||w|| / ||update||. Semantics preserved:

- ``max_grad_norm``: grads are pre-divided by
  ``max(global_norm / max_grad_norm, 1)`` (fused_lamb.py:133-141).
- ``use_nvlamb``: when False (default), params with ``weight_decay == 0``
  skip the adaptive trust ratio (ratio 1), NVLAMB applies it everywhere
  (fused_lamb.py:54).
- ``bias_correction``, ``adam_w_mode``, ``grad_averaging`` as in the
  reference ctor (fused_lamb.py:67).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    GradientTransformation,
    ScheduleOrScalar,
    global_norm,
    resolve_lr,
    tree_map_float,
    tree_zeros_like_f32,
    with_norm_telemetry,
)

__all__ = ["FusedLAMB", "fused_lamb", "LambState"]


class LambState(NamedTuple):
    step: jax.Array
    exp_avg: Any
    exp_avg_sq: Any


def fused_lamb(
    lr: ScheduleOrScalar = 1e-3,
    betas: Tuple[float, float] = (0.9, 0.999),
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    bias_correction: bool = True,
    adam_w_mode: bool = True,
    grad_averaging: bool = True,
    max_grad_norm: float = 1.0,
    use_nvlamb: bool = False,
    norm_telemetry: bool = False,
) -> GradientTransformation:
    """``norm_telemetry=True``: see ``fused_adam`` — the state carries
    the last step's global norms for ``record_opt_norms``; off by
    default (extra full-tree reductions)."""
    beta1, beta2 = betas

    def init(params) -> LambState:
        return LambState(
            step=jnp.zeros((), jnp.int32),
            exp_avg=tree_zeros_like_f32(params),
            exp_avg_sq=tree_zeros_like_f32(params),
        )

    def update(grads, state: LambState, params=None):
        if params is None:
            raise ValueError("fused_lamb requires params")
        step = state.step + 1
        lr_t = resolve_lr(lr, step)

        # Phase 1a: global grad-norm clip (reference :133-141).
        gnorm = global_norm(grads)
        if max_grad_norm is not None and max_grad_norm > 0:
            clip = jnp.maximum(gnorm / max_grad_norm, 1.0)
        else:
            clip = jnp.asarray(1.0, jnp.float32)

        beta3 = (1.0 - beta1) if grad_averaging else 1.0
        if bias_correction:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def scaled_grad(g, p):
            sg = g.astype(jnp.float32) / clip
            if not adam_w_mode and weight_decay != 0.0:
                # L2 mode (kernel MOMENT_MODE_0, multi_tensor_lamb.cu:123-126):
                # decay*p folds into the scaled gradient before the moments.
                sg = sg + weight_decay * p.astype(jnp.float32)
            return sg

        m_tree = tree_map_float(
            lambda g, p, m: beta1 * m + beta3 * scaled_grad(g, p),
            grads, params, state.exp_avg,
        )
        v_tree = tree_map_float(
            lambda g, p, v: beta2 * v
            + (1.0 - beta2) * jnp.square(scaled_grad(g, p)),
            grads, params, state.exp_avg_sq,
        )

        # Phase 2: per-param trust ratio (kernel lamb_stage_2).
        def upd_leaf(m, v, p):
            p32 = p.astype(jnp.float32)
            u = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
            if adam_w_mode and weight_decay != 0.0:
                u = u + weight_decay * p32
            w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            u_norm = jnp.sqrt(jnp.sum(jnp.square(u)))
            ratio = jnp.where(
                (w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0
            )
            if weight_decay == 0.0 and not use_nvlamb:
                ratio = jnp.asarray(1.0, jnp.float32)
            return -lr_t * ratio * u

        updates = tree_map_float(upd_leaf, m_tree, v_tree, params)
        return updates, LambState(step, m_tree, v_tree)

    tx = GradientTransformation(init, update)
    return with_norm_telemetry(tx) if norm_telemetry else tx


FusedLAMB = fused_lamb
