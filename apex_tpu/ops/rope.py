"""Fused rotary positional embeddings — 4 layouts.

Reference: csrc/megatron/fused_rotary_positional_embedding.{cpp,h} (8 entry
points) wrapped by apex/transformer/functional/fused_rope.py:166,300,424,565.

Rotation is NeoX/Megatron "rotate_half" style with partial rotation: for
rotary dim ``d2 = freqs.shape[-1] <= d``,

    out[..., :d2] = t[..., :d2]·cos(freqs) + rotate_half(t[..., :d2])·sin(freqs)
    out[..., d2:] = t[..., d2:]                       (passthrough)
    rotate_half(x) = concat(-x[..., d2/2:], x[..., :d2/2])

(fused_rotary_positional_embedding.h:35-48). The rotation is orthogonal, so
each backward is the forward with negated angle — expressed here as a
custom VJP. Pure-XLA: the op is elementwise×2 + a lane roll, which XLA fuses
into surrounding matmuls; a Pallas kernel would only add launch overhead.

``transpose_output_memory`` arguments are accepted for signature parity and
ignored (XLA owns memory layout).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = [
    "fused_apply_rotary_pos_emb",
    "fused_apply_rotary_pos_emb_cached",
    "fused_apply_rotary_pos_emb_thd",
    "fused_apply_rotary_pos_emb_2d",
    "fused_apply_rotary_pos_emb_ragged",
]


def _rotate_half(x):
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def _rotate_half_t(x):
    """Adjoint of _rotate_half (its transpose = its inverse = -itself)."""
    half = x.shape[-1] // 2
    return jnp.concatenate([x[..., half:], -x[..., :half]], axis=-1)


def _apply(t, cos, sin):
    """Rotate the first d2 features of t; cos/sin broadcast against t."""
    d2 = cos.shape[-1]
    t32 = t[..., :d2].astype(jnp.float32)
    out = t32 * cos + _rotate_half(t32) * sin
    out = out.astype(t.dtype)
    if d2 < t.shape[-1]:
        out = jnp.concatenate([out, t[..., d2:]], axis=-1)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _rope(t, cos, sin):
    return _apply(t, cos, sin)


def _rope_fwd(t, cos, sin):
    return _apply(t, cos, sin), (cos, sin)


def _rope_bwd(res, dy):
    cos, sin = res
    # True adjoint: dt = dy·cos + rot_halfᵀ(dy·sin). The reference backward
    # kernel (fused_rotary_positional_embedding.h:74-87) reads sin from the
    # *other* half — identical math; this stays correct even when the two
    # freq halves are not duplicates of each other.
    d2 = cos.shape[-1]
    dy32 = dy[..., :d2].astype(jnp.float32)
    dt = dy32 * cos + _rotate_half_t(dy32 * sin)
    dt = dt.astype(dy.dtype)
    if d2 < dy.shape[-1]:
        dt = jnp.concatenate([dt, dy[..., d2:]], axis=-1)
    return dt, None, None


_rope.defvjp(_rope_fwd, _rope_bwd)


def fused_apply_rotary_pos_emb(
    t: jax.Array,
    freqs: jax.Array,
    transpose_output_memory: bool = False,
) -> jax.Array:
    """`sbhd` layout: t [s, b, h, d], freqs [s, 1, 1, d2] (radians).

    Reference fused_rope.py:166 / kernel fwd (fused_rope::fwd)."""
    del transpose_output_memory
    f32 = freqs.astype(jnp.float32)
    return _rope(t, jnp.cos(f32), jnp.sin(f32))


def fused_apply_rotary_pos_emb_cached(
    t: jax.Array,
    cos_: jax.Array,
    sin_: jax.Array,
    transpose_output_memory: bool = False,
) -> jax.Array:
    """`sbhd` layout with precomputed cos/sin [s, 1, 1, d2]
    (reference fused_rope.py:300, kernel fwd_cached)."""
    del transpose_output_memory
    return _rope(t, cos_.astype(jnp.float32), sin_.astype(jnp.float32))


def fused_apply_rotary_pos_emb_thd(
    t: jax.Array,
    cu_seqlens: jax.Array,
    freqs: jax.Array,
) -> jax.Array:
    """`thd` packed-sequence layout: t [T, h, d], cu_seqlens [b+1] int32,
    freqs [max_s, 1, 1, d2] (reference fused_rope.py:424, kernel fwd_thd).

    Token i belongs to the sequence whose range contains i; its rotary
    position is ``i - cu_seqlens[seq(i)]``.
    """
    total = t.shape[0]
    idx = jnp.arange(total, dtype=jnp.int32)
    seg = jnp.searchsorted(cu_seqlens.astype(jnp.int32), idx, side="right") - 1
    pos = idx - jnp.take(cu_seqlens.astype(jnp.int32), seg)
    f32 = freqs.astype(jnp.float32).reshape(freqs.shape[0], -1)   # [max_s,d2]
    cos = jnp.take(jnp.cos(f32), pos, axis=0)[:, None, :]         # [T,1,d2]
    sin = jnp.take(jnp.sin(f32), pos, axis=0)[:, None, :]
    return _rope(t, cos, sin)


def fused_apply_rotary_pos_emb_ragged(
    t: jax.Array,
    cos_: jax.Array,
    sin_: jax.Array,
    positions: jax.Array,
) -> jax.Array:
    """`bshd` layout with per-sequence base positions: t [b, s, h, d],
    cos_/sin_ tables [max_len, d2], positions [b] int32 — token (i, j)
    rotates by angle table row ``positions[i] + j``.

    The ragged-batch inference case (models/generate.py): sequences at
    different absolute offsets decode together, so the rotary row is a
    per-batch gather rather than the uniform slice of the cached
    variant.  ``positions`` of shape ``()`` broadcasts (uniform batch —
    the legacy scalar-pos decode).  Rows are clamped to the table, so a
    finished sequence whose position counter ran past ``max_len`` reads
    a valid (ignored) angle instead of out-of-bounds memory.
    """
    b, s = t.shape[0], t.shape[1]
    pos = jnp.broadcast_to(jnp.asarray(positions, jnp.int32), (b,))
    rows = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
    rows = jnp.clip(rows, 0, cos_.shape[0] - 1)
    cos_g = jnp.take(cos_.astype(jnp.float32), rows, axis=0)[:, :, None, :]
    sin_g = jnp.take(sin_.astype(jnp.float32), rows, axis=0)[:, :, None, :]
    return _rope(t, cos_g, sin_g)


def fused_apply_rotary_pos_emb_2d(
    t: jax.Array,
    img_h: int,
    img_w: int,
    cos_h: jax.Array,
    sin_h: jax.Array,
    cos_w: jax.Array,
    sin_w: jax.Array,
) -> jax.Array:
    """2D (vision) RoPE: t [b, img_h*img_w, h, d]; the first d/2 features
    rotate by the row position (cos_h/sin_h [1, H, 1, d/2]) and the second
    d/2 by the column position (cos_w/sin_w [1, W, 1, d/2])
    (reference fused_rope.py:565, kernel fwd_2d).
    """
    b, s, h, d = t.shape
    if s != img_h * img_w:
        raise ValueError(f"t.shape[1]={s} != img_h*img_w={img_h * img_w}")
    half = d // 2
    t4 = t.reshape(b, img_h, img_w, h, d)
    # tables may be precomputed for a max image size (reference allows
    # H >= img_h / W >= img_w and indexes the first rows)
    ch = cos_h.astype(jnp.float32).reshape(1, -1, 1, half)[:, :img_h]
    sh = sin_h.astype(jnp.float32).reshape(1, -1, 1, half)[:, :img_h]
    cw = cos_w.astype(jnp.float32).reshape(1, -1, 1, half)[:, :img_w]
    sw = sin_w.astype(jnp.float32).reshape(1, -1, 1, half)[:, :img_w]
    ch = ch.reshape(1, img_h, 1, 1, half)
    sh = sh.reshape(1, img_h, 1, 1, half)
    cw = cw.reshape(1, 1, img_w, 1, half)
    sw = sw.reshape(1, 1, img_w, 1, half)
    out_h = _rope(t4[..., :half], ch, sh)
    out_w = _rope(t4[..., half:], cw, sw)
    return jnp.concatenate([out_h, out_w], axis=-1).reshape(b, s, h, d)
