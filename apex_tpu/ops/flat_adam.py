"""Fused Adam over the flattened parameter buffer (pure XLA).

The reference's performance trick is ``multi_tensor_apply``: one kernel
launch updates the entire parameter list (csrc/multi_tensor_adam.cu +
multi_tensor_apply.cuh packs 110 tensor pointers per launch).  The
TPU-native answer turned out to need no hand-written kernel at all:
under ``jit`` XLA fuses the whole flat Adam chain (two moment updates,
the rsqrt, the weight-decay add) into one HBM pass on its own.

A Pallas tile-streaming kernel lived here through round 4
(``adam_kernel_flat``, swept via ``APEX_TPU_ADAM_BLOCK_ROWS``).  The
round-5 on-chip sweep was its win-or-delete gate (BASELINE.md): 88M
fp32, rows=512 → 1.82×, rows=1024 → 1.85× the XLA fused update, and
rows≥2048 failed to compile — so the kernel and its knob were deleted
and every optimizer keeps the XLA flat path.

``adam_kernel_flat`` remains the flat-buffer entry point (the
ZeRO-sharded DistributedFusedAdam layout calls it on raw 1-D shards);
``flat_adam_update`` is the tree-level wrapper kept for the reference's
``multi_tensor_apply``-shaped API surface.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from apex_tpu.utils.registry import register_op

__all__ = ["flat_adam_update", "adam_kernel_flat"]


@functools.partial(jax.jit, static_argnames=("adam_w_mode",))
def adam_kernel_flat(
    g: jax.Array,
    p: jax.Array,
    m: jax.Array,
    v: jax.Array,
    scalars: jax.Array,
    adam_w_mode: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused Adam update on 1-D fp32 buffers.

    ``scalars`` = [lr, beta1, beta2, eps, weight_decay, bc1, bc2] (f32[7]).
    Returns (update, new_m, new_v) with the same length as the inputs.
    XLA fuses the chain into a single pass over HBM (measured round 5:
    4.02 ms for 88M fp32 on v5e — the deleted Pallas kernel's best
    setting took 7.33 ms).
    """
    lr, beta1, beta2, eps, wd, bc1, bc2 = (scalars[i] for i in range(7))
    if not adam_w_mode:
        g = g + wd * p
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    u = -lr * (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps)
    if adam_w_mode:
        u = u - lr * wd * p
    return u, m_new, v_new


def flat_adam_update(
    grads: Any, params: Any, m: Any, v: Any,
    lr, beta1, beta2, eps, weight_decay, bc1, bc2,
    adam_w_mode: bool,
):
    """Tree-level wrapper: ravel → flat update → unravel.

    The three unravel closures share one flat layout, so XLA lowers the
    concat/split to views around a single fused update.
    """
    g_flat, unravel = ravel_pytree(
        jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), grads)
    )
    p_flat, _ = ravel_pytree(
        jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), params)
    )
    m_flat, _ = ravel_pytree(m)
    v_flat, _ = ravel_pytree(v)
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(bc1, jnp.float32),
        jnp.asarray(bc2, jnp.float32),
    ])
    u, m_new, v_new = adam_kernel_flat(
        g_flat, p_flat, m_flat, v_flat, scalars, adam_w_mode=adam_w_mode,
    )
    return unravel(u), unravel(m_new), unravel(v_new)


register_op(
    "fused_adam_update", backend="xla", is_available=lambda: True
)(flat_adam_update)
