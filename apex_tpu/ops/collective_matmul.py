"""Ring collective-matmul — overlapped tensor-parallel collectives.

Megatron-style TP pays a *serialized* collective around every linear:
``gather_from_sequence_parallel_region`` → matmul in ColumnParallelLinear,
and matmul → ``reduce_scatter_to_sequence_parallel_region``/psum in
RowParallelLinear (reference apex/transformer/tensor_parallel/layers.py:429,
:613, mappings.py:223,:245).  While the monolithic collective runs, the MXU
idles; while the matmul runs, the ICI idles.

This module decomposes those pairs into ``ppermute`` ring steps so hop
``t+1``'s transfer is dataflow-independent of hop ``t``'s shard matmul —
XLA's latency-hiding scheduler then runs them concurrently (the classic
TPU "collective matmul"; the same ring structure as
``parallel/ring_attention.py``, applied to the dense TP hot path):

- :func:`all_gather_matmul` — ``all_gather(x) @ w`` as a ring: each hop's
  incoming activation shard is matmul'd immediately while the next shard
  is in flight.  Backward is :func:`matmul_reduce_scatter` for dx plus a
  ring re-gather of ``x`` for dw — no monolithic collective under grad.
- :func:`matmul_reduce_scatter` — ``reduce_scatter(x @ w)`` as a
  partial-product ring with a rotating accumulator: each hop computes only
  the output chunk the traveling accumulator is destined for.  Backward is
  one ring over the output cotangent producing dx chunks and dw together.
- :func:`matmul_all_reduce` — ``psum(x @ w)`` spelled as the ring
  reduce-scatter followed by an all-gather (same wire bytes as the
  monolithic all-reduce; the reduce-scatter half rides the ring overlapped
  with the partial-product matmuls).  Backward sums the output cotangent
  only if it arrives shard-varying (the dual of ``copy_to``'s pvary);
  an invariant cotangent keeps it communication-free like
  ``reduce_from_tensor_model_parallel_region``'s identity backward.
- :func:`ring_all_gather` / :func:`ring_reduce_scatter` — the bare ring
  decompositions (no fused matmul) the sequence-parallel mappings route
  through under ``overlap_comm``.

Rings are **bidirectional** for ≥3 shards: the forward-direction buffer
carries ⌈(n−1)/2⌉ hops and the backward buffer the rest, so both ICI
directions are busy and wall-clock latency halves while total hop count
stays n−1.

All functions run on *local shards inside* ``jax.shard_map`` (or pmap)
with ``axis_name`` bound.  The ``overlap_*``/``gspmd_*`` helpers wrap them
in a shard_map island for use from GSPMD-annotated code (the pattern of
``transformer_lm._cp_core_attention``), returning ``None`` whenever the
ring path does not apply (no mesh, axis absent or size 1, indivisible
dims) so callers fall back to the monolithic path.

Trace-time telemetry (PR-1 registry): every ring loop counts
``collectives.ring.calls`` (+1), ``collectives.ring.hops`` (+n−1) and
``collectives.ring.bytes`` (+(n−1) × per-hop message bytes) — by
construction ``hops == (tp−1) × calls`` on a fixed-tp program, the
invariant the dryrun gate asserts.

The ring-only contract is additionally enforced structurally: the
``static_audit`` dryrun phase traces these paths under an active
:func:`overlap_scope` and walks the jaxpr
(``analysis/jaxpr_audit.py``) — any monolithic
``all_gather``/``psum``/``all_to_all`` equation inside the overlap
region fails CI, so a fallback path silently engaging under the scope
cannot ship.
"""

from __future__ import annotations

import contextlib
import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.observability import metrics as _telemetry
from apex_tpu.utils.collectives import (
    match_vma,
    ppermute as _counted_ppermute,
    pvary as _pvary,
    vma_of,
)

__all__ = [
    "all_gather_matmul",
    "matmul_reduce_scatter",
    "matmul_all_reduce",
    "ring_all_gather",
    "ring_reduce_scatter",
    "overlap_scope",
    "overlap_enabled",
    "sequence_parallel_matmul",
    "gspmd_row_parallel_matmul",
]


# ---------------------------------------------------------------------------
# overlap_comm tri-state resolution
# ---------------------------------------------------------------------------

# Default for overlap_comm=None call sites; overlap_scope pushes overrides.
# amp.frontend.make_train_step(overlap_comm=...) traces the loss under a
# scope so TP contexts built with the tri-state default inherit the
# train-step's choice without re-plumbing every layer.
_SCOPE = [False]


@contextlib.contextmanager
def overlap_scope(enable: bool = True):
    """Set the default for ``overlap_comm=None`` call sites within the
    ``with`` block (trace-time: affects functions traced inside it)."""
    _SCOPE.append(bool(enable))
    try:
        yield
    finally:
        _SCOPE.pop()


def overlap_enabled(flag: Optional[bool] = None) -> bool:
    """Resolve an ``overlap_comm`` tri-state: an explicit bool wins;
    ``None`` reads the innermost :func:`overlap_scope` (default off)."""
    return _SCOPE[-1] if flag is None else bool(flag)


# ---------------------------------------------------------------------------
# ring plumbing
# ---------------------------------------------------------------------------


def _axis_size(axis_name) -> int:
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)   # folds to a python int pre-0.9


def _note_ring(n: int, msg_nbytes: int) -> None:
    """Trace-time ring accounting: one call, n−1 hops, (n−1)·msg bytes."""
    reg = _telemetry.registry()
    if reg is None:
        return
    reg.counter("collectives.ring.calls").inc()
    reg.counter("collectives.ring.hops").inc(n - 1)
    reg.counter("collectives.ring.bytes").inc((n - 1) * int(msg_nbytes))


def _nbytes(x) -> int:
    return int(math.prod(x.shape or ())) * x.dtype.itemsize


def _perms(axis_name, n):
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    return fwd, bwd


def _split_hops(n: int):
    """Bidirectional hop split: a fwd + b bwd hops, a+b = n−1, a ≥ b."""
    a = -(-(n - 1) // 2)
    return a, (n - 1) - a


def _zeros_like_vma(shape, dtype, *refs):
    axes = set()
    for r in refs:
        axes |= set(vma_of(r))
    return match_vma(jnp.zeros(shape, dtype), tuple(sorted(axes)))


def _mm(x, w):
    """x [..., k] @ w [k, p] with fp32 accumulation (fp32 output)."""
    return jax.lax.dot_general(
        x, w, dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _mm_grad_w(xc, gc):
    """dw [k, p] = Σ over every non-contracted dim of x [..., k] ⊗
    g [..., p] (fp32 accumulation)."""
    dims = tuple(range(xc.ndim - 1))
    return jax.lax.dot_general(
        xc, gc, dimension_numbers=((dims, dims), ((), ())),
        preferred_element_type=jnp.float32)


def _ring_visit(x, axis_name, visit):
    """Bidirectional all-gather ring over ``x``'s shards: call
    ``visit(src_rank, shard)`` once per rank's shard (``src_rank`` is a
    traced index; the local shard is visited first, at hop 0).  n−1 hops;
    hop t+1's ppermute depends only on the buffer, not on ``visit``'s
    consumption of it, so transfer t+1 overlaps compute t."""
    n = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    x = _pvary(x, axis_name)
    visit(my, x)
    if n == 1:
        _note_ring(n, _nbytes(x))
        return
    fwd, bwd = _perms(axis_name, n)
    a, b = _split_hops(n)
    xf = x
    for t in range(1, a + 1):
        xf = _counted_ppermute(xf, axis_name, fwd)
        visit((my - t) % n, xf)
    xb = x
    for t in range(1, b + 1):
        xb = _counted_ppermute(xb, axis_name, bwd)
        visit((my + t) % n, xb)
    _note_ring(n, _nbytes(x))


def _ring_scatter_sum(axis_name, n, chunk_shape, dtype, part, *vma_refs):
    """Bidirectional reduce-scatter ring: ``part(d)`` computes this
    rank's fp32 contribution to destination chunk ``d`` (traced index);
    returns this rank's fully-summed chunk.  Two accumulators travel in
    opposite directions and meet at the destination after n−1 total
    hops; each hop's ``part`` for the next destination is independent of
    the in-flight accumulator, so compute overlaps transfer."""
    my = jax.lax.axis_index(axis_name)
    if n == 1:
        out = part(my)
        _note_ring(n, _nbytes(out))
        return out
    fwd, bwd = _perms(axis_name, n)
    a, b = _split_hops(n)
    acc_f = _zeros_like_vma(chunk_shape, dtype, *vma_refs)
    for t in range(a):
        acc_f = acc_f + part((my + a - t) % n)
        acc_f = _counted_ppermute(acc_f, axis_name, fwd)
    out = acc_f
    if b:
        acc_b = _zeros_like_vma(chunk_shape, dtype, *vma_refs)
        for t in range(b):
            acc_b = acc_b + part((my - b + t) % n)
            acc_b = _counted_ppermute(acc_b, axis_name, bwd)
        out = out + acc_b
    out = out + part(my)
    _note_ring(n, int(math.prod(chunk_shape)) * jnp.dtype(dtype).itemsize)
    return out


def _check_dims(x, w, dim, what):
    if w.ndim != 2:
        raise ValueError(f"{what}: w must be 2-D [k, p], got {w.shape}")
    if x.ndim < 2:
        raise ValueError(f"{what}: x must be at least 2-D, got {x.shape}")
    if x.shape[-1] != w.shape[0]:
        raise ValueError(
            f"{what}: contraction mismatch — x [..., {x.shape[-1]}] vs "
            f"w [{w.shape[0]}, ...]")
    if not (0 <= dim < x.ndim - 1):
        raise ValueError(
            f"{what}: ring dim {dim} must be a non-contracted dim of x "
            f"(ndim {x.ndim})")


# ---------------------------------------------------------------------------
# all_gather_matmul
# ---------------------------------------------------------------------------


def _agmm_impl(x, w, axis_name, gather_dim, out_dtype):
    n = _axis_size(axis_name)
    m = x.shape[gather_dim]
    out_shape = (x.shape[:gather_dim] + (n * m,)
                 + x.shape[gather_dim + 1:-1] + (w.shape[1],))
    y = _zeros_like_vma(out_shape, jnp.float32, x, w)
    box = [y]

    def visit(src, shard):
        box[0] = jax.lax.dynamic_update_slice_in_dim(
            box[0], _mm(shard, w), src * m, axis=gather_dim)

    _ring_visit(x, axis_name, visit)
    return box[0].astype(out_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _agmm(x, w, axis_name, gather_dim):
    return _agmm_impl(x, w, axis_name, gather_dim,
                      jnp.result_type(x, w))


def _agmm_fwd(x, w, axis_name, gather_dim):
    return _agmm(x, w, axis_name, gather_dim), (x, w)


def _agmm_bwd(axis_name, gather_dim, res, g):
    x, w = res
    n = _axis_size(axis_name)
    m = x.shape[gather_dim]
    # dx = reduce_scatter(g @ w^T) along gather_dim — the dual ring
    dx = _mmrs_impl(g, w.T.astype(g.dtype), axis_name, gather_dim,
                    x.dtype)
    # dw = gather(x)^T @ g: re-ring x, consuming each shard against its
    # rows of g the hop it lands (never materializing the gathered x)
    dw_box = [_zeros_like_vma(w.shape, jnp.float32, x, g)]

    def visit(src, shard):
        gc = jax.lax.dynamic_slice_in_dim(g, src * m, m, axis=gather_dim)
        dw_box[0] = dw_box[0] + _mm_grad_w(shard, gc)

    _ring_visit(x, axis_name, visit)
    return dx, dw_box[0].astype(w.dtype)


_agmm.defvjp(_agmm_fwd, _agmm_bwd)


def all_gather_matmul(x: jax.Array, w: jax.Array, axis_name: str, *,
                      gather_dim: int = 0) -> jax.Array:
    """``all_gather(x, dim=gather_dim) @ w`` as an overlapped ring.

    ``x`` is this rank's activation shard (sequence-parallel input of a
    column-parallel linear, [s/tp, ..., k]); ``w`` this rank's column
    shard [k, p/tp].  Each hop's incoming shard is matmul'd into its rows
    of the gathered output while the next transfer is in flight.  Output
    [s, ..., p/tp] in ``result_type(x, w)`` with fp32 accumulation.

    Backward: dx via :func:`matmul_reduce_scatter` (the transpose pair),
    dw via a ring re-gather of ``x`` — both n−1-hop rings, no monolithic
    collective under grad.  Call inside ``shard_map`` with ``axis_name``
    bound.
    """
    _check_dims(x, w, gather_dim, "all_gather_matmul")
    return _agmm(x, w, axis_name, gather_dim)


# ---------------------------------------------------------------------------
# matmul_reduce_scatter
# ---------------------------------------------------------------------------


def _mmrs_impl(x, w, axis_name, scatter_dim, out_dtype):
    n = _axis_size(axis_name)
    M = x.shape[scatter_dim]
    if M % n:
        raise ValueError(
            f"matmul_reduce_scatter: dim {scatter_dim} of x ({M}) not "
            f"divisible by the '{axis_name}' axis size {n}")
    mc = M // n
    x = _pvary(x, axis_name)
    chunk_shape = (x.shape[:scatter_dim] + (mc,)
                   + x.shape[scatter_dim + 1:-1] + (w.shape[1],))

    def part(d):
        xc = jax.lax.dynamic_slice_in_dim(x, d * mc, mc, axis=scatter_dim)
        return _mm(xc, w)

    out = _ring_scatter_sum(axis_name, n, chunk_shape, jnp.float32, part,
                            x, w)
    return out.astype(out_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _mmrs(x, w, axis_name, scatter_dim):
    return _mmrs_impl(x, w, axis_name, scatter_dim, jnp.result_type(x, w))


def _mmrs_fwd(x, w, axis_name, scatter_dim):
    return _mmrs(x, w, axis_name, scatter_dim), (x, w)


def _mmrs_bwd(axis_name, scatter_dim, res, g):
    """ONE ring over the scattered cotangent yields both grads: as chunk
    ``c`` of g lands, dx rows c (= g_c @ w^T) are written and x's rows c
    contribute x_c^T @ g_c to dw — the all-gather-matmul dual."""
    x, w = res
    mc = g.shape[scatter_dim]
    wT = w.T.astype(g.dtype)
    dx_box = [_zeros_like_vma(x.shape, jnp.float32, x, g)]
    dw_box = [_zeros_like_vma(w.shape, jnp.float32, x, g)]

    def visit(src, gc):
        dx_box[0] = jax.lax.dynamic_update_slice_in_dim(
            dx_box[0], _mm(gc, wT), src * mc, axis=scatter_dim)
        xc = jax.lax.dynamic_slice_in_dim(x, src * mc, mc,
                                          axis=scatter_dim)
        dw_box[0] = dw_box[0] + _mm_grad_w(xc, gc)

    _ring_visit(g, axis_name, visit)
    return dx_box[0].astype(x.dtype), dw_box[0].astype(w.dtype)


_mmrs.defvjp(_mmrs_fwd, _mmrs_bwd)


def matmul_reduce_scatter(x: jax.Array, w: jax.Array, axis_name: str, *,
                          scatter_dim: int = 0) -> jax.Array:
    """``reduce_scatter(x @ w, dim=scatter_dim)`` as an overlapped ring.

    ``x`` is this rank's full-length input with the contraction dim
    locally sharded ([s, ..., k/tp] of a row-parallel linear); ``w`` the
    row shard [k/tp, p].  A rotating accumulator visits every rank; each
    hop computes only the partial-product chunk the accumulator is
    destined for, so the next transfer overlaps the current chunk matmul.
    Output [s/tp, ..., p]: this rank's fully-summed chunk.

    Backward is a single ring over the output cotangent producing dx
    chunks and dw together (see :func:`all_gather_matmul` — the two are
    each other's transpose).  Call inside ``shard_map``.
    """
    _check_dims(x, w, scatter_dim, "matmul_reduce_scatter")
    return _mmrs(x, w, axis_name, scatter_dim)


# ---------------------------------------------------------------------------
# matmul_all_reduce
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _mmar(x, w, axis_name, scatter_dim):
    from apex_tpu.utils.collectives import all_gather as _counted_ag

    y = _mmrs_impl(x, w, axis_name, scatter_dim, jnp.result_type(x, w))
    return _counted_ag(y, axis_name, axis=scatter_dim, tiled=True)


def _mmar_fwd(x, w, axis_name, scatter_dim):
    return _mmar(x, w, axis_name, scatter_dim), (x, w)


def _mmar_bwd(axis_name, scatter_dim, res, g):
    # The replicated-valued output is consumed per-shard, so a cotangent
    # that arrives still shard-varying is only this rank's contribution:
    # the true dy is the psum of the per-rank cotangents — the same sum
    # the monolithic path pays at copy_to's pvary transpose.  An
    # axis-invariant cotangent (already the total, e.g. an out_specs-
    # replicated consumer) skips it, keeping the backward
    # communication-free like reduce_from_tensor_model_parallel_region's
    # identity transpose; grad_sum makes exactly that distinction.
    from apex_tpu.utils.collectives import grad_sum

    x, w = res
    g = _pvary(grad_sum(g, axis_name), axis_name)
    dx = _mm(g, w.T.astype(g.dtype)).astype(x.dtype)
    dw = _mm_grad_w(x, g).astype(w.dtype)
    return dx, dw


_mmar.defvjp(_mmar_fwd, _mmar_bwd)


def matmul_all_reduce(x: jax.Array, w: jax.Array, axis_name: str, *,
                      scatter_dim: int = 0) -> jax.Array:
    """``psum(x @ w)`` as ring reduce-scatter + all-gather.

    Same wire bytes as the monolithic all-reduce, but the reduce-scatter
    half rides the ring overlapped with the partial-product matmul
    chunks.  ``scatter_dim`` names the dim the intermediate scatter
    tiles over (must be divisible by the axis size).  Backward psums the
    output cotangent only when it arrives shard-varying (the per-rank
    consumption of a replicated value — the same sum the monolithic
    path pays at ``copy_to_tensor_model_parallel_region``'s transpose);
    an axis-invariant cotangent is used as-is, communication-free.
    """
    _check_dims(x, w, scatter_dim, "matmul_all_reduce")
    return _mmar(x, w, axis_name, scatter_dim)


# ---------------------------------------------------------------------------
# bare ring collectives (the sequence-parallel mapping decompositions)
# ---------------------------------------------------------------------------


def ring_all_gather(x: jax.Array, axis_name: str, *,
                    dim: int = 0) -> jax.Array:
    """``all_gather(x, dim)`` decomposed into n−1 ``ppermute`` hops.

    Each hop's chunk is placed as it lands, so downstream consumers of
    early rows can start before the last hop arrives (the scheduler's
    hook for overlapping the gather with neighboring compute).  Plain
    jax autodiff transposes the ring into a ring (reversed ppermutes),
    so no custom VJP is needed.
    """
    m = x.shape[dim]
    n = _axis_size(axis_name)
    out_shape = x.shape[:dim] + (n * m,) + x.shape[dim + 1:]
    box = [_zeros_like_vma(out_shape, x.dtype, x)]

    def visit(src, shard):
        box[0] = jax.lax.dynamic_update_slice_in_dim(
            box[0], shard, src * m, axis=dim)

    _ring_visit(x, axis_name, visit)
    return box[0]


def ring_reduce_scatter(x: jax.Array, axis_name: str, *,
                        dim: int = 0) -> jax.Array:
    """``psum_scatter(x, dim, tiled=True)`` decomposed into n−1
    ``ppermute`` hops with a rotating accumulator (sum semantics)."""
    n = _axis_size(axis_name)
    M = x.shape[dim]
    if M % n:
        raise ValueError(
            f"ring_reduce_scatter: dim {dim} of x ({M}) not divisible "
            f"by the '{axis_name}' axis size {n}")
    mc = M // n
    x = _pvary(x, axis_name)
    chunk_shape = x.shape[:dim] + (mc,) + x.shape[dim + 1:]

    def part(d):
        return jax.lax.dynamic_slice_in_dim(x, d * mc, mc, axis=dim)

    return _ring_scatter_sum(axis_name, n, chunk_shape, x.dtype, part, x)


# ---------------------------------------------------------------------------
# GSPMD shard_map islands (the _cp_core_attention pattern)
# ---------------------------------------------------------------------------


def _abstract_mesh():
    try:
        mesh = jax.sharding.get_abstract_mesh()
    except AttributeError:   # jax < 0.9
        return None
    if mesh is None or mesh.empty:
        return None
    return mesh


def _mesh_axis(mesh, axis_name):
    """Axis size when present on the mesh, else 0."""
    if axis_name is None or axis_name not in mesh.axis_names:
        return 0
    return int(mesh.shape[axis_name])


def sequence_parallel_matmul(x: jax.Array, w: jax.Array, *,
                             mode: str, axis_name: str = "tp",
                             dim: int = 0,
                             enable: Optional[bool] = None):
    """Shard_map island for the GSPMD Column/Row parallel flax layers.

    ``mode='gather'``: ``x`` sequence-sharded over ``axis_name`` at
    ``dim``, ``w`` column-sharded on its last dim → ring
    :func:`all_gather_matmul`; output carries the full sequence with the
    last dim still tp-sharded.  ``mode='scatter'``: ``x`` with its last
    dim tp-sharded, ``w`` row-sharded on dim 0 → ring
    :func:`matmul_reduce_scatter`; output sequence-scattered over
    ``axis_name`` at ``dim`` (constrain it afterwards to re-gather for
    non-sequence-parallel semantics — XLA then overlaps that all-gather
    with downstream compute).

    Returns ``None`` when the ring path does not apply (overlap
    disabled, no active mesh, axis absent or size 1, indivisible dims):
    the caller falls back to the monolithic collective.
    """
    if mode not in ("gather", "scatter"):
        raise ValueError(f"mode must be 'gather' or 'scatter', got {mode!r}")
    if not overlap_enabled(enable):
        return None
    mesh = _abstract_mesh()
    if mesh is None:
        return None
    n = _mesh_axis(mesh, axis_name)
    if n < 2:
        return None
    rest = [None] * (x.ndim - 1)
    if mode == "gather":
        if x.shape[dim] % n or w.shape[1] % n:
            return None
        x_spec = P(*([None] * dim + [axis_name] + rest[dim:]))
        w_spec = P(None, axis_name)
        out_spec = P(*([None] * (x.ndim - 1) + [axis_name]))
        fn = functools.partial(all_gather_matmul, axis_name=axis_name,
                               gather_dim=dim)
    elif mode == "scatter":
        if x.shape[dim] % n or x.shape[-1] % n or w.shape[0] % n:
            return None
        x_spec = P(*(rest + [axis_name]))
        w_spec = P(axis_name, None)
        out_spec = P(*([None] * dim + [axis_name]
                       + [None] * (x.ndim - 1 - dim)))
        fn = functools.partial(matmul_reduce_scatter, axis_name=axis_name,
                               scatter_dim=dim)
    f = jax.shard_map(fn, mesh=mesh, in_specs=(x_spec, w_spec),
                      out_specs=out_spec)
    return f(x, w)


def gspmd_row_parallel_matmul(x: jax.Array, w: jax.Array, *,
                              tp_axis: str = "tp",
                              batch_axis: str = "dp",
                              seq_axis: Optional[str] = None,
                              enable: Optional[bool] = None):
    """Overlapped row-parallel matmul for the GSPMD model forward.

    ``x`` [b, s, k] with k tp-sharded (attention/MLP output partials),
    ``w`` [k, h] row-sharded: the island runs the ring
    :func:`matmul_reduce_scatter` over ``tp_axis`` scattering the local
    sequence dim, and returns the output sequence-sharded over
    ``(seq_axis, tp_axis)`` — the caller's hidden-state constraint then
    re-gathers over tp lazily (overlappable), replacing the monolithic
    tp all-reduce XLA would otherwise serialize after the matmul.

    Returns ``None`` when inapplicable (overlap disabled, no mesh, tp
    absent/1, indivisible batch/seq/contraction dims) so callers fall
    back to the annotated monolithic path.
    """
    if not overlap_enabled(enable) or x.ndim != 3 or w.ndim != 2:
        return None
    mesh = _abstract_mesh()
    if mesh is None:
        return None
    tp = _mesh_axis(mesh, tp_axis)
    if tp < 2:
        return None
    dp = max(_mesh_axis(mesh, batch_axis), 1)
    sp = max(_mesh_axis(mesh, seq_axis), 1)
    b, s, k = x.shape
    if b % dp or s % (sp * tp) or k % tp or k != w.shape[0]:
        return None
    bspec = batch_axis if dp > 1 or batch_axis in mesh.axis_names else None
    sspec = seq_axis if (seq_axis and seq_axis in mesh.axis_names) else None
    seq_out = (sspec, tp_axis) if sspec else tp_axis
    f = jax.shard_map(
        functools.partial(matmul_reduce_scatter, axis_name=tp_axis,
                          scatter_dim=1),
        mesh=mesh,
        in_specs=(P(bspec, sspec, tp_axis), P(tp_axis, None)),
        out_specs=P(bspec, seq_out, None))
    return f(x, w)
