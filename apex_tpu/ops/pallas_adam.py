"""Single-pass Pallas Adam over the flattened parameter buffer.

The reference's performance trick is ``multi_tensor_apply``: one kernel
launch updates the entire parameter list (csrc/multi_tensor_adam.cu +
multi_tensor_apply.cuh packs 110 tensor pointers per launch). The TPU-native
equivalent runs one Pallas kernel over a single flat fp32 buffer: each grid
step streams a (block × 128) tile of g/p/m/v through VMEM and writes the
update and both new moments — one HBM pass for the whole model.

Use ``adam_kernel_flat`` directly when optimizer state is *stored* flat
(the ZeRO-sharded DistributedFusedAdam path). The tree-level wrapper
``flat_adam_update`` ravels per step and is measured ~30x slower on v5e
than letting XLA fuse the tree update (the concat/split costs 7 extra HBM
copies); it exists for API completeness and kernel testing.

Falls back to interpret mode off-TPU (used by tests).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.flatten_util import ravel_pytree

from apex_tpu.utils.registry import on_tpu, register_op
from apex_tpu.ops._pallas_utils import out_struct

__all__ = ["flat_adam_update", "adam_kernel_flat"]

import os

_LANES = 128
# (rows, 128) f32 tile per operand in VMEM; 7 blocked operands double-
# buffered = 14 tiles live, so 1024 rows = 512 KiB/tile = 7 MiB total
# (fits v5e's 16 MiB).  APEX_TPU_ADAM_BLOCK_ROWS overrides (read at
# trace time so on-chip sweeps can vary it; VERDICT r3 #4: the flat
# kernel measured 2.01x XLA at 512 rows — suspected per-grid-step
# overhead at the small tile).
_BLOCK_ROWS = 1024


def _block_rows() -> int:
    return int(os.environ.get("APEX_TPU_ADAM_BLOCK_ROWS", _BLOCK_ROWS))


def _adam_body(adam_w_mode, s_ref, g_ref, p_ref, m_ref, v_ref,
               u_out, m_out, v_out):
    lr = s_ref[0]
    beta1 = s_ref[1]
    beta2 = s_ref[2]
    eps = s_ref[3]
    wd = s_ref[4]
    bc1 = s_ref[5]
    bc2 = s_ref[6]

    g = g_ref[:]
    p = p_ref[:]
    if not adam_w_mode:
        g = g + wd * p
    m = beta1 * m_ref[:] + (1.0 - beta1) * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    denom = jnp.sqrt(v / bc2) + eps
    u = -lr * (m / bc1) / denom
    if adam_w_mode:
        u = u - lr * wd * p
    u_out[:] = u
    m_out[:] = m
    v_out[:] = v


@functools.partial(jax.jit, static_argnames=("adam_w_mode", "interpret"))
def adam_kernel_flat(
    g: jax.Array,
    p: jax.Array,
    m: jax.Array,
    v: jax.Array,
    scalars: jax.Array,
    adam_w_mode: bool = True,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Run the fused update on 1-D fp32 buffers (padded internally).

    ``scalars`` = [lr, beta1, beta2, eps, weight_decay, bc1, bc2] (f32[7]).
    Returns (update, new_m, new_v) with the same length as the inputs.
    """
    from jax.experimental.pallas import tpu as pltpu

    n = g.shape[0]
    rows = max(pl.cdiv(n, _LANES), 1)
    padded = rows * _LANES
    pad = padded - n

    def to2d(x):
        return jnp.pad(x, (0, pad)).reshape(rows, _LANES)

    g2, p2, m2, v2 = to2d(g), to2d(p), to2d(m), to2d(v)
    block = min(_block_rows(), rows)
    grid = (pl.cdiv(rows, block),)

    tile = pl.BlockSpec(
        (block, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    out_shape = out_struct((rows, _LANES), jnp.float32, g2)
    u2, m2n, v2n = pl.pallas_call(
        functools.partial(_adam_body, adam_w_mode),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # scalars
            tile, tile, tile, tile,
        ],
        out_specs=(tile, tile, tile),
        out_shape=(out_shape, out_shape, out_shape),
        interpret=interpret,
    )(scalars, g2, p2, m2, v2)

    def back(x):
        return x.reshape(padded)[:n]

    return back(u2), back(m2n), back(v2n)


def flat_adam_update(
    grads: Any, params: Any, m: Any, v: Any,
    lr, beta1, beta2, eps, weight_decay, bc1, bc2,
    adam_w_mode: bool,
):
    """Tree-level wrapper: ravel → kernel → unravel.

    The three unravel closures share one flat layout, so XLA lowers the
    concat/split to views around a single fused kernel.
    """
    g_flat, unravel = ravel_pytree(
        jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), grads)
    )
    p_flat, _ = ravel_pytree(
        jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), params)
    )
    m_flat, _ = ravel_pytree(m)
    v_flat, _ = ravel_pytree(v)
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(beta1, jnp.float32),
        jnp.asarray(beta2, jnp.float32),
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        jnp.asarray(bc1, jnp.float32),
        jnp.asarray(bc2, jnp.float32),
    ])
    u, m_new, v_new = adam_kernel_flat(
        g_flat, p_flat, m_flat, v_flat, scalars,
        adam_w_mode=adam_w_mode, interpret=not on_tpu(),
    )
    return unravel(u), unravel(m_new), unravel(v_new)


# Available everywhere: the wrapper itself switches to interpret mode
# off-TPU, so the default pallas availability gate would under-report.
register_op(
    "fused_adam_update", backend="pallas", is_available=lambda: True
)(flat_adam_update)
