"""Ragged paged attention — the decode kernel of the paged KV cache.

The paged serving cache (ISSUE 6) stores K/V in a global pool of
fixed-size blocks ``[num_blocks, block_size, kv_groups, dh]``; each
request owns an ordered *block table* of pool indices instead of a
contiguous ``max_len`` stripe.  Decode attention then has to gather a
request's blocks before it can score them — and materializing that
gather (``pool[tables]`` → ``[b, max_blocks·block_size, g, dh]``) is
exactly the HBM round-trip "LLM Inference Acceleration via Efficient
Operation Fusion" (PAPERS.md) warns against.  Following "Ragged Paged
Attention: A High-Performance and Flexible LLM Inference Kernel for
TPU" (PAPERS.md), the Pallas kernel fuses the gather into the attention
loop: the block table rides in SMEM via scalar prefetch and the
*BlockSpec index map* dereferences it, so each grid step DMAs one
physical block straight into VMEM and folds it into an online-softmax
accumulator — the gathered K/V never exists as a tensor.

Ragged lengths are handled in-kernel: every sequence carries its own
live length, whole blocks past it are skipped (their FLOPs and their
accumulator contribution), and the tail block is masked per-position.
GQA folds the query heads as ``[groups, rep]`` against the group-width
pool exactly like the dense decode path — repeated K/V is never
materialized.

Routing mirrors the rest of ``apex_tpu.ops`` (flash_attention's
gate specialized to the decode shape): the fused kernel runs on TPU
(or under ``APEX_TPU_PALLAS_INTERPRET=1``, the 8-virtual-device CI
path); everywhere else the XLA gather-based :func:`paged_attention_
reference` — always available, numerics oracle for the parity tests —
executes instead.  ``APEX_TPU_PAGED_ATTENTION=kernel|reference|auto``
overrides, and the ``backend=`` argument pins a path explicitly
(the kernel parity suite compares the two).

Layout contract (shared with ``serving/paged_cache.py``):

- ``q``            ``[b, num_heads, dh]`` — ONE query token per sequence
  (sq=1, the decode shape);
- ``k_pool/v_pool````[num_blocks, block_size, kv_groups, dh]``;
- ``block_tables`` ``[b, max_blocks]`` int32 — entries ``>= num_blocks``
  are unmapped sentinels (reads clamp + mask, so a short table tail or
  a released lane is safe);
- ``lengths``      ``[b]`` int32 — live tokens per sequence (the query
  token included): position ``t`` is visible iff ``t < lengths[i]``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._pallas_utils import LANES as _LANES
from apex_tpu.utils.registry import on_tpu

__all__ = ["ragged_paged_attention", "paged_attention_reference"]

_NEG_INF = -1e30


def _check_paged_shapes(q, k_pool, v_pool, block_tables, lengths,
                        k_scale=None, v_scale=None):
    if q.ndim != 3:
        raise ValueError(
            f"expected q [b, num_heads, dh] (one decode token per "
            f"sequence), got {q.shape}")
    if k_pool.ndim != 4 or v_pool.shape != k_pool.shape:
        raise ValueError(
            f"expected k/v pools [num_blocks, block_size, kv_groups, "
            f"dh], got k {k_pool.shape} v {v_pool.shape}")
    b, nh, dh = q.shape
    if k_pool.shape[-1] != dh:
        raise ValueError(
            f"head dim mismatch: q has {dh}, pool has {k_pool.shape[-1]}")
    g = k_pool.shape[2]
    if nh % g:
        raise ValueError(
            f"query heads ({nh}) must be a multiple of the pool's "
            f"kv group count ({g})")
    if block_tables.ndim != 2 or block_tables.shape[0] != b:
        raise ValueError(
            f"expected block_tables [b={b}, max_blocks], got "
            f"{block_tables.shape}")
    if lengths.shape != (b,):
        raise ValueError(
            f"expected lengths [b={b}], got {lengths.shape}")
    quant = jnp.dtype(k_pool.dtype) == jnp.int8
    if quant and (k_scale is None or v_scale is None):
        raise ValueError(
            "int8 pools need k_scale/v_scale [num_blocks, block_size, "
            "kv_groups] (the block-scaled at-rest form of "
            "serving/paged_cache.py) — refusing to treat raw int8 as "
            "attention values")
    if not quant and (k_scale is not None or v_scale is not None):
        raise ValueError(
            f"k_scale/v_scale only apply to int8 pools, got pool dtype "
            f"{k_pool.dtype}")
    if quant:
        want = k_pool.shape[:3]
        if k_scale.shape != want or v_scale.shape != want:
            raise ValueError(
                f"expected scales {want}, got k {k_scale.shape} "
                f"v {v_scale.shape}")


def paged_attention_reference(q, k_pool, v_pool, block_tables, lengths,
                              *, scale: Optional[float] = None,
                              k_scale=None, v_scale=None):
    """XLA composition: gather the listed blocks, then run the dense
    masked decode attention over them.

    This is the materialized-gather path the fused kernel exists to
    avoid (``pool[tables]`` builds the full ``[b, max_blocks·bs, g,
    dh]`` view in HBM every step) — kept as the always-available
    fallback and the numerics oracle of the parity suite, the same
    role ``mha_reference`` plays for the flash kernel.

    int8 pools (``k_scale``/``v_scale`` given): the gather also pulls
    each block's per-(token, group) scales and dequantizes before the
    math — the matching gather+dequant oracle of the in-kernel
    dequantizing path."""
    _check_paged_shapes(q, k_pool, v_pool, block_tables, lengths,
                        k_scale, v_scale)
    b, nh, dh = q.shape
    nb, bs, g, _ = k_pool.shape
    mb = block_tables.shape[1]
    scale = (1.0 / dh ** 0.5) if scale is None else float(scale)
    # unmapped sentinel entries clamp to block 0; their positions are
    # >= lengths by contract, so the mask below hides the garbage
    tbl = jnp.minimum(block_tables.astype(jnp.int32), nb - 1)
    k = k_pool[tbl].reshape(b, mb * bs, g, dh)
    v = v_pool[tbl].reshape(b, mb * bs, g, dh)
    if k_scale is not None:
        sk = k_scale[tbl].reshape(b, mb * bs, g)
        sv = v_scale[tbl].reshape(b, mb * bs, g)
        k = k.astype(jnp.float32) * sk[..., None]
        v = v.astype(jnp.float32) * sv[..., None]
    rep = nh // g
    qg = q.reshape(b, g, rep, dh)
    s = jnp.einsum("bgrd,btgd->bgrt", qg.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    live = (jnp.arange(mb * bs)[None] <
            lengths.astype(jnp.int32)[:, None])[:, None, None, :]
    s = jnp.where(live, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgrt,btgd->bgrd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o.reshape(b, nh, dh).astype(q.dtype)


# ---------------------------------------------------------------------------
# Fused Pallas kernel.
# ---------------------------------------------------------------------------


def _paged_kernel(scale, bs, g, rep, quant, *refs):
    """Grid (b, max_blocks): sequence-major, one physical K/V block per
    step, online softmax across the block steps.  The block table and
    lengths ride in SMEM (scalar prefetch); the BlockSpec index maps
    already dereferenced the table, so ``k_ref``/``v_ref`` hold the
    right physical block — the fused-gather property.

    ``quant``: the pool is block-scaled int8 and two extra refs carry
    the step's per-(token, group) scale blocks (dereferenced through
    the SAME table index map as the payload), so dequantization is one
    VMEM-resident multiply per block — the float K/V never exists in
    HBM, which is the whole at-rest win."""
    if quant:
        (tbl_ref, len_ref, q_ref, k_ref, ks_ref, v_ref, vs_ref, o_ref,
         m_s, l_s, acc) = refs
    else:
        (tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
         m_s, l_s, acc) = refs
        ks_ref = vs_ref = None
    i, j = pl.program_id(0), pl.program_id(1)
    nh = g * rep

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    length = len_ref[i]

    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [nh, dh]
        k = k_ref[0].astype(jnp.float32)          # [bs, g, dh]
        if quant:
            k = k * ks_ref[0][..., None]          # [bs, g, 1] scales
        qg = q.reshape(g, rep, q.shape[-1])
        # batched over the group axis: [g, rep, dh] x [bs, g, dh]
        # -> [g, rep, bs]; the rep query heads of a group share its
        # single pool-resident K/V block (GQA without repeat)
        s = jax.lax.dot_general(
            qg, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale
        s = s.reshape(nh, bs)
        col = j * bs + jax.lax.broadcasted_iota(jnp.int32, (nh, bs), 1)
        s = jnp.where(col < length, s, _NEG_INF)

        m_prev = m_s[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # rows still fully masked (possible only while length == 0):
        # keep the accumulator at exact zero instead of exp(NaN)
        p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
        alpha = jnp.where(m_new > _NEG_INF / 2, alpha, 0.0)
        l_s[:] = l_s[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)          # [bs, g, dh]
        if quant:
            v = v * vs_ref[0][..., None]
        pg = p.reshape(g, rep, bs)
        ctx = jax.lax.dot_general(
            pg, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)   # [g, rep, dh]
        acc[:] = acc[:] * alpha + ctx.reshape(nh, v.shape[-1])
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)

    # ragged skip: a block whose first position is past the sequence's
    # live length contributes nothing — skip its FLOPs entirely (the
    # DMA for it was clamped to a valid block by the index map)
    pl.when(j * bs < length)(_compute)

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)


def _paged_pallas(q, k_pool, v_pool, block_tables, lengths, scale,
                  interpret, k_scale=None, v_scale=None):
    from jax.experimental.pallas import tpu as pltpu

    b, nh, dh = q.shape
    nb, bs, g, _ = k_pool.shape
    mb = block_tables.shape[1]
    rep = nh // g
    quant = k_scale is not None
    # the index map runs for EVERY grid step, skipped blocks included:
    # clamp unmapped sentinels to a valid pool index here (host-side,
    # once) so the DMA source is always in range — the kernel's ragged
    # skip / tail mask keeps the clamped garbage out of the math
    tbl = jnp.minimum(block_tables.astype(jnp.int32), nb - 1)
    lens = lengths.astype(jnp.int32)

    kv_spec = pl.BlockSpec(
        (1, bs, g, dh),
        lambda i, j, tbl_ref, len_ref: (tbl_ref[i, j], 0, 0, 0))
    # the scale pool dereferences through the SAME table entry, so each
    # step's DMA brings the block's payload AND its scales — the
    # gather+dequant is fused exactly like the gather itself
    sc_spec = pl.BlockSpec(
        (1, bs, g),
        lambda i, j, tbl_ref, len_ref: (tbl_ref[i, j], 0, 0))
    in_specs = [
        pl.BlockSpec((1, nh, dh),
                     lambda i, j, tbl_ref, len_ref: (i, 0, 0)),
        kv_spec,
    ]
    inputs = [q, k_pool]
    if quant:
        in_specs.append(sc_spec)
        inputs.append(k_scale)
    in_specs.append(kv_spec)
    inputs.append(v_pool)
    if quant:
        in_specs.append(sc_spec)
        inputs.append(v_scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, nh, dh), lambda i, j, tbl_ref, len_ref: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, _LANES), jnp.float32),   # running max
            pltpu.VMEM((nh, _LANES), jnp.float32),   # running normalizer
            pltpu.VMEM((nh, dh), jnp.float32),       # output accumulator
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, scale, bs, g, rep, quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nh, dh), q.dtype),
        interpret=interpret,
    )(tbl, lens, *inputs)


def _route(backend: Optional[str]) -> str:
    if backend is None:
        backend = os.environ.get("APEX_TPU_PAGED_ATTENTION", "auto")
    if backend not in ("auto", "kernel", "reference"):
        raise ValueError(
            f"paged attention backend={backend!r}: expected "
            "auto|kernel|reference")
    if backend == "auto":
        interp = os.environ.get("APEX_TPU_PALLAS_INTERPRET", "0") == "1"
        backend = "kernel" if (on_tpu() or interp) else "reference"
    return backend


def ragged_paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    lengths: jax.Array,
    *,
    scale: Optional[float] = None,
    backend: Optional[str] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """One decode token per sequence attends over its paged KV blocks.

    ``q`` ``[b, num_heads, dh]``, pools ``[num_blocks, block_size,
    kv_groups, dh]``, ``block_tables`` ``[b, max_blocks]`` (entries
    ``>= num_blocks`` are unmapped), ``lengths`` ``[b]`` live token
    counts → context ``[b, num_heads, dh]``.

    int8 pools (ISSUE 14): pass the pool's per-(token, group) fp32
    scales as ``k_scale``/``v_scale`` ``[num_blocks, block_size,
    kv_groups]`` — the kernel dequantizes each block in VMEM right
    after its table-dereferenced DMA (the float K/V never exists in
    HBM), the reference runs the matching gather+dequant.

    ``backend``: ``None`` routes automatically (fused Pallas kernel on
    TPU or under ``APEX_TPU_PALLAS_INTERPRET=1``; XLA gather reference
    otherwise; ``APEX_TPU_PAGED_ATTENTION`` overrides), ``"kernel"`` /
    ``"reference"`` pin a path — the parity suite compares the two.

    Inference-only by design (no custom VJP): nothing differentiates
    through the serving decode step, and keeping the kernel
    forward-only keeps its VMEM budget at one block.
    """
    _check_paged_shapes(q, k_pool, v_pool, block_tables, lengths,
                        k_scale, v_scale)
    dh = q.shape[-1]
    scale = (1.0 / dh ** 0.5) if scale is None else float(scale)
    if _route(backend) == "reference":
        return paged_attention_reference(
            q, k_pool, v_pool, block_tables, lengths, scale=scale,
            k_scale=k_scale, v_scale=v_scale)
    return _paged_pallas(q, k_pool, v_pool, block_tables, lengths,
                         scale, interpret=not on_tpu(),
                         k_scale=k_scale, v_scale=v_scale)
