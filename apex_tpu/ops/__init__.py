"""apex_tpu.ops — the fused op library (Pallas TPU kernels + XLA references).

Reference equivalents live in csrc/ and apex/contrib/csrc/ (see SURVEY.md
§2.2-2.3). Every op has a pure-jnp/lax implementation (always available,
XLA-fused) and, where profitable, a Pallas TPU kernel behind the op registry.
"""

from apex_tpu.ops.dense import (  # noqa: F401
    fused_dense_function,
    fused_dense_gelu_dense_function,
)
from apex_tpu.ops.layer_norm import (  # noqa: F401
    fused_layer_norm,
    fused_rms_norm,
)
from apex_tpu.ops.flat_adam import flat_adam_update  # noqa: F401
from apex_tpu.ops.collective_matmul import (  # noqa: F401
    all_gather_matmul,
    matmul_all_reduce,
    matmul_reduce_scatter,
    ring_all_gather,
    ring_reduce_scatter,
)
from apex_tpu.ops.rope import (  # noqa: F401
    fused_apply_rotary_pos_emb,
    fused_apply_rotary_pos_emb_2d,
    fused_apply_rotary_pos_emb_cached,
    fused_apply_rotary_pos_emb_ragged,
    fused_apply_rotary_pos_emb_thd,
)
from apex_tpu.ops.paged_attention import (  # noqa: F401
    paged_attention_reference,
    ragged_paged_attention,
)
from apex_tpu.ops.softmax import (  # noqa: F401
    generic_scaled_masked_softmax,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.ops.swiglu import (  # noqa: F401
    fused_bias_swiglu,
    fused_bias_swiglu_paired,
)
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss  # noqa: F401
