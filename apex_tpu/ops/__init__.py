"""apex_tpu.ops — the fused op library (Pallas TPU kernels + XLA references).

Reference equivalents live in csrc/ and apex/contrib/csrc/ (see SURVEY.md
§2.2-2.3). Every op has a pure-jnp/lax implementation (always available,
XLA-fused) and, where profitable, a Pallas TPU kernel behind the op registry.
"""

from apex_tpu.ops.pallas_adam import flat_adam_update  # noqa: F401
