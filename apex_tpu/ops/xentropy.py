"""Fused softmax-cross-entropy with label smoothing.

Reference: apex/contrib/csrc/xentropy/xentropy_kernel.cu bound as
``xentropy_cuda``, wrapped by
apex/contrib/xentropy/softmax_xentropy.py (``SoftmaxCrossEntropyLoss``).
The fusion win the reference targets — not materializing the softmax and
saving only ``max + log Σ exp`` for backward — is the same here: forward
saves the scalar ``max_log_sum_exp`` per row, backward reconstructs the
softmax from logits in one fused pass.

Per-row semantics (xentropy_kernel.cu:431-436, 448-452):

    lse      = max(x) + log Σ exp(x - max)
    loss     = (lse - mean(x)) · smoothing + (lse - x[label]) · (1-smoothing)
    loss     = 0                         where label == padding_idx
    dx_j     = g · (softmax_j - smoothing/K - (1-smoothing)·1[j==label])
    dx       = 0                         where label == padding_idx
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["SoftmaxCrossEntropyLoss", "softmax_cross_entropy_loss"]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _xentropy(logits, labels, smoothing, padding_idx):
    loss, _ = _fwd_math(logits, labels, smoothing, padding_idx)
    return loss


def _fwd_math(logits, labels, smoothing, padding_idx):
    x = logits.astype(jnp.float32)
    m = jnp.max(x, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m[..., None]), axis=-1))
    picked = jnp.take_along_axis(
        x, labels[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    loss = (lse - jnp.mean(x, axis=-1)) * smoothing + (lse - picked) * (
        1.0 - smoothing
    )
    if padding_idx is not None:
        loss = jnp.where(labels == padding_idx, 0.0, loss)
    return loss, lse


def _xentropy_fwd(logits, labels, smoothing, padding_idx):
    loss, lse = _fwd_math(logits, labels, smoothing, padding_idx)
    return loss, (logits, labels, lse)


def _xentropy_bwd(smoothing, padding_idx, res, g):
    logits, labels, lse = res
    x = logits.astype(jnp.float32)
    classes = x.shape[-1]
    probs = jnp.exp(x - lse[..., None])
    onehot = jax.nn.one_hot(labels, classes, dtype=jnp.float32)
    dx = probs - smoothing / classes - (1.0 - smoothing) * onehot
    g32 = g.astype(jnp.float32)
    if padding_idx is not None:
        g32 = jnp.where(labels == padding_idx, 0.0, g32)
    dx = dx * g32[..., None]
    return dx.astype(logits.dtype), None


_xentropy.defvjp(_xentropy_fwd, _xentropy_bwd)


def softmax_cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    smoothing: float = 0.0,
    padding_idx: int = 0,
    half_to_float: bool = False,
) -> jax.Array:
    """Per-row losses (reference softmax_xentropy.py:6 signature).

    ``half_to_float`` is accepted for parity; losses are always fp32.
    """
    del half_to_float
    return _xentropy(logits, labels, float(smoothing), padding_idx)


# Reference exposes a Function-object with .apply; the callable is enough.
SoftmaxCrossEntropyLoss = softmax_cross_entropy_loss
