"""Flash attention — the TPU answer to the reference's fused attention stack.

Reference parity targets: apex/contrib/csrc/fmha (seqlen<=512 BERT fwd/bwd,
varlen via cu_seqlens — fmha_api.cpp:358) and apex/contrib/csrc/
multihead_attn (pre-flash fused MHA with softmax/dropout epilogues). Instead
of porting those CUDA tilings we implement one FlashAttention-2 style
blockwise kernel set in Pallas: O(sq·d) memory, online softmax, fused causal
/ key-padding masking and attention dropout, fp32 accumulation on the MXU.
It also serves as the compute core of the ring-attention context-parallel
path (the reference has no long-context story; SURVEY.md §5).

Layout: [batch, seq, heads, head_dim] (the model's native BSND). The kernel
grid runs (batch*heads, q-blocks, kv-blocks) with kv innermost; VMEM scratch
carries the running max / normalizer / accumulator across kv steps.

Variants:
- ``causal=True`` — upper-triangular mask generated from iota in-kernel.
- ``key_padding_mask`` [b, sk] — bool (True = masked) or additive float
  (the reference's ``mask_additive`` MHA mode) — fused in-kernel as an
  additive score term.
- ``dropout_p`` — attention dropout fused in-kernel. The keep mask is a
  counter-based hash of (seed, batch·head, query row, key col) — the
  Philox-counter analog of the reference's in-kernel dropout
  (contrib/csrc/multihead_attn/philox.cuh): stateless, order-independent,
  so the forward and both backward kernels regenerate identical bits for
  every tile with no O(s²) residual.  Dropout is applied to the
  *unnormalized* probabilities feeding the accumulator while the softmax
  normalizer accumulates the un-dropped weights, which equals dropping
  the normalized probabilities.
- generic additive ``bias`` or full boolean ``mask`` — routed to the XLA
  composition (rare paths in the reference too).

Backward: custom_vjp with the standard two-kernel scheme — dq accumulates
over kv blocks, dk/dv over q blocks, both recomputing the probabilities
from the saved logsumexp (no O(s²) residuals).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from apex_tpu.ops._pallas_utils import LANES as _LANES, out_struct
from apex_tpu.utils.registry import on_tpu

__all__ = ["flash_attention", "flash_attention_packed", "mha_reference",
           "segment_ids_from_cu_seqlens"]

_NEG_INF = -1e30


def _unify_vma(*arrays):
    """Promote every (non-None) array to the union of the group's varying
    manual axes (jax 0.9 shard_map vma typing).  A Pallas call with
    mixed-vma operands — e.g. a closure-constant mask next to a
    pp-varying activation inside a shard_map pipeline stage — fails the
    dynamic_slice vma check in the interpreter/lowering; unifying here
    makes the kernel's type uniform.  No-op outside shard_map."""
    vmas = []
    for a in arrays:
        if a is None:
            continue
        vmas.append(set(getattr(jax.typeof(a), "vma", ()) or ()))
    union = set().union(*vmas) if vmas else set()
    if not union:
        return arrays
    from apex_tpu.utils.collectives import match_vma

    return tuple(None if a is None else match_vma(a, tuple(union))
                 for a in arrays)


def _pad_to(x, size, axis):
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# ---------------------------------------------------------------------------
# In-kernel dropout PRNG: counter-based hash (Philox-counter analog).
#
# pltpu.prng_* is hardware-only (no CPU interpret lowering), so the keep
# mask is a murmur3-style integer hash over global (seed, bh, row, col)
# coordinates — bit-identical on TPU and in CPU interpret mode, and
# trivially order-independent across the three kernels.
# ---------------------------------------------------------------------------


def _u32(x):
    return jnp.uint32(x)


def _keep_mask(seed, bh, q_start, k_start, shape, keep_prob):
    """Boolean keep mask for a (block_q, block_k) tile."""
    row = (
        q_start
        + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    ).astype(jnp.uint32)
    col = (
        k_start
        + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    ).astype(jnp.uint32)
    h = seed.astype(jnp.uint32) + bh.astype(jnp.uint32) * _u32(0x9E3779B1)
    h = h ^ (row * _u32(0x85EBCA77))
    h = h ^ (h >> 16)
    h = h * _u32(0x7FEB352D)
    h = h ^ (col * _u32(0xC2B2AE3D))
    h = h ^ (h >> 16)
    h = h * _u32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * _u32(0xC2B2AE35)
    h = h ^ (h >> 16)
    threshold = min(int(round(keep_prob * 4294967296.0)), 4294967295)
    return h < _u32(threshold)


# ---------------------------------------------------------------------------
# Reference XLA path (also the fallback for generic bias / mask).
# ---------------------------------------------------------------------------


def mha_reference(q, k, v, *, causal=False, key_padding_mask=None,
                  mask=None, bias=None, scale=None, dropout_p=0.0,
                  dropout_rng=None, segment_ids=None):
    """Materialized softmax(QK^T)V in fp32 — numerics oracle for the kernel
    and the execution path for variants the kernel doesn't fuse.

    Accepts grouped K/V (fewer heads than Q, GQA/MQA): the group heads
    are broadcast up to the query heads, the semantics the fused kernel
    implements via its index maps without materializing the repeat."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    b, sq, n, d = q.shape
    sk = k.shape[1]
    scale = (1.0 / d ** 0.5) if scale is None else scale
    s = jnp.einsum("bsnd,btnd->bnst", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if mask is not None:
        s = jnp.where(mask, _NEG_INF, s)
    if segment_ids is not None:
        # a (seg_q, seg_k) pair supports rectangular (cross-attention)
        # grids; a single [b, s] array is the packed self-attention case
        if isinstance(segment_ids, tuple):
            seg_q, seg_k = (x.astype(jnp.int32) for x in segment_ids)
        else:
            seg_q = seg_k = segment_ids.astype(jnp.int32)
        blocked = (seg_q[:, None, :, None] != seg_k[:, None, None, :]) | (
            seg_k < 0)[:, None, None, :]
        s = jnp.where(blocked, _NEG_INF, s)
    if key_padding_mask is not None:
        if key_padding_mask.dtype == jnp.bool_:
            s = jnp.where(key_padding_mask[:, None, None, :], _NEG_INF, s)
        else:  # additive float mask (reference mask_additive mode)
            s = s + key_padding_mask[:, None, None, :].astype(jnp.float32)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((col > row)[None, None], _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    # fully-blocked rows (e.g. padding queries under segment_ids, or an
    # all-masked key row): softmax of a constant -1e30 row is uniform —
    # zero it to match the kernel's l==0 sentinel (no value/grad leaks
    # across segments through pad slots)
    any_open = jnp.max(s, axis=-1, keepdims=True) > _NEG_INF / 2
    p = jnp.where(any_open, p, 0.0)
    if dropout_p > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bnst,btnd->bsnd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# ---------------------------------------------------------------------------
# Forward kernel.
# ---------------------------------------------------------------------------


def _fwd_kernel(scale, causal, sk_real, block_q, block_k, has_kpm,
                has_seg, dropout_p, *refs):
    if dropout_p > 0.0:
        seed_ref, refs = refs[0], refs[1:]
    if has_seg:
        qseg_ref, kseg_ref, refs = refs[0], refs[1], refs[2:]
    if has_kpm:
        q_ref, k_ref, v_ref, kpm_ref, o_ref, lse_ref, acc, m_s, l_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s = refs
    bh, qi, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    q_start = qi * block_q
    k_start = kj * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if has_kpm:
            s = s + kpm_ref[0]  # additive [1, block_k] broadcast

        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        pred = col < sk_real                       # kv tail padding
        if causal:
            row = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            pred &= col <= row
        if has_seg:
            # packed multi-sequence rows: attend within a segment only
            # (negative ids = padding slots, matching nothing)
            qseg = qseg_ref[0].reshape(block_q, 1)
            kseg = kseg_ref[0].reshape(1, block_k)
            pred &= (qseg == kseg) & (kseg >= 0)
        s = jnp.where(pred, s, _NEG_INF)

        m_prev = m_s[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # fully-masked-so-far rows: m_new == -inf ⇒ exp(NaN) guards
        p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
        alpha = jnp.where(m_new > _NEG_INF / 2, alpha, 0.0)

        l_s[:] = l_s[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_p > 0.0:
            keep = _keep_mask(seed_ref[0], bh, q_start, k_start,
                              (block_q, block_k), 1.0 - dropout_p)
            p_acc = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
        else:
            p_acc = p
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p_acc.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)

    run = None
    if causal:
        # whole kv block above the diagonal → skip its FLOPs
        run = k_start <= q_start + block_q - 1
    if has_seg:
        # block-sparse skip of fully-disjoint tiles: if any q/k segment
        # ids match, the id ranges overlap — so disjoint ranges are a
        # safe (conservative) skip regardless of id ordering
        qseg = qseg_ref[0]
        kseg = kseg_ref[0]
        overlap = (jnp.min(kseg) <= jnp.max(qseg)) & (
            jnp.max(kseg) >= jnp.min(qseg))
        run = overlap if run is None else (run & overlap)
    if run is None:
        _compute()
    else:
        pl.when(run)(_compute)

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)
        # logsumexp (fully-masked rows get -inf-ish sentinel)
        lse = m_s[:, :1] + jnp.log(safe_l)
        lse_ref[0] = jnp.broadcast_to(
            jnp.where(l == 0.0, _NEG_INF, lse), lse_ref.shape[1:])


def _kv_of(bq_flat, n, g):
    """Flat kv-head row for flat q-head row ``bq_flat`` under GQA: the
    [b, s, heads, d] → [b*heads, s, d] flattening is batch-major, so
    batch = bq // n and the q head's group is (bq % n) // (n // g)."""
    return (bq_flat // n) * g + (bq_flat % n) // (n // g)


def _fwd_pallas(q3, k3, v3, kpm, seg, seed, scale, causal, sk_real,
                block_q, block_k, dropout_p, interpret, out_dtype=None,
                gqa=None):
    from jax.experimental.pallas import tpu as pltpu

    bh, sqp, d = q3.shape
    skp = k3.shape[1]
    grid = (bh, sqp // block_q, skp // block_k)

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    if gqa is not None:
        # grouped K/V (GQA): the index map broadcasts each group head to
        # its rep query heads — the repeated tensor never exists in HBM
        n, g = gqa
        k_spec = pl.BlockSpec(
            (1, block_k, d),
            lambda b, i, j, n=n, g=g: (_kv_of(b, n, g), j, 0),
            memory_space=pltpu.VMEM)
    else:
        k_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                              memory_space=pltpu.VMEM)
    in_specs = []
    args = []
    if dropout_p > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    if seg is not None:
        # (seg_q, seg_k): [b, sqp]/[b, skp] int32, indexed by batch
        heads = bh // seg[0].shape[0]
        in_specs.append(pl.BlockSpec(
            (1, block_q), lambda b, i, j, h=heads: (b // h, i),
            memory_space=pltpu.VMEM))
        args.append(seg[0])
        in_specs.append(pl.BlockSpec(
            (1, block_k), lambda b, i, j, h=heads: (b // h, j),
            memory_space=pltpu.VMEM))
        args.append(seg[1])
    in_specs += [q_spec, k_spec, k_spec]
    args += [q3, k3, v3]
    if kpm is not None:
        # [b, 1, skp] additive f32, indexed by batch = bh // heads
        heads = bh // kpm.shape[0]
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k),
            lambda b, i, j, h=heads: (b // h, 0, j),
            memory_space=pltpu.VMEM))
        args.append(kpm)

    out_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        out_struct((bh, sqp, d), out_dtype or q3.dtype, q3),
        out_struct((bh, sqp, _LANES), jnp.float32, q3),
    ]
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale, causal, sk_real,
                          block_q, block_k, kpm is not None,
                          seg is not None, dropout_p),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return o, lse[:, :, 0]


# ---------------------------------------------------------------------------
# Backward kernels.
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(scale, causal, sk_real, block_q, block_k, has_kpm,
                   has_seg, dropout_p, *refs):
    if dropout_p > 0.0:
        seed_ref, refs = refs[0], refs[1:]
    if has_seg:
        qseg_ref, kseg_ref, refs = refs[0], refs[1], refs[2:]
    if has_kpm:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kpm_ref,
         dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
    bh, qi, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start, k_start = qi * block_q, kj * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if has_kpm:
            s = s + kpm_ref[0]
        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        pred = col < sk_real
        if causal:
            row = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            pred &= col <= row
        if has_seg:
            qseg = qseg_ref[0].reshape(block_q, 1)
            kseg = kseg_ref[0].reshape(1, block_k)
            pred &= (qseg == kseg) & (kseg >= 0)
        lse = lse_ref[0][:, :1]
        # fully-masked rows carry the -inf lse sentinel: s - lse would be
        # ~0 there (additive -1e30 mask == -1e30 sentinel), not -inf —
        # zero them explicitly or pad keys receive garbage gradients
        pred &= lse > _NEG_INF / 2
        p = jnp.where(pred, jnp.exp(s - lse), 0.0)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            keep = _keep_mask(seed_ref[0], bh, q_start, k_start,
                              (block_q, block_k), 1.0 - dropout_p)
            dp = jnp.where(keep, dp / (1.0 - dropout_p), 0.0)
        delta = delta_ref[0][:, :1]
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    run = None
    if causal:
        run = k_start <= q_start + block_q - 1
    if has_seg:
        qs, ks = qseg_ref[0], kseg_ref[0]
        overlap = (jnp.min(ks) <= jnp.max(qs)) & (
            jnp.max(ks) >= jnp.min(qs))
        run = overlap if run is None else (run & overlap)
    if run is None:
        _compute()
    else:
        pl.when(run)(_compute)

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(scale, causal, sq_real, sk_real, block_q, block_k,
                    has_kpm, has_seg, dropout_p, gqa, *refs):
    if dropout_p > 0.0:
        seed_ref, refs = refs[0], refs[1:]
    if has_seg:
        qseg_ref, kseg_ref, refs = refs[0], refs[1], refs[2:]
    if has_kpm:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kpm_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    if gqa is not None:
        # grid (b*g, kv, rep, q): one dk/dv row accumulates all rep query
        # heads of its group; bh reconstructs the flat q-head row so the
        # dropout hash matches the forward bit-for-bit
        n, g = gqa
        rep = n // g
        bkv, kj = pl.program_id(0), pl.program_id(1)
        r, qi = pl.program_id(2), pl.program_id(3)
        bh = (bkv // g) * n + (bkv % g) * rep + r
        first = (r == 0) & (qi == 0)
        last = (r == rep - 1) & (qi == pl.num_programs(3) - 1)
    else:
        bh, kj, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
        first = qi == 0
        last = qi == pl.num_programs(2) - 1

    @pl.when(first)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start, k_start = qi * block_q, kj * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if has_kpm:
            s = s + kpm_ref[0]
        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        row = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        pred = (col < sk_real) & (row < sq_real)
        if causal:
            pred &= col <= row
        if has_seg:
            qseg = qseg_ref[0].reshape(block_q, 1)
            kseg = kseg_ref[0].reshape(1, block_k)
            pred &= (qseg == kseg) & (kseg >= 0)
        lse = lse_ref[0][:, :1]
        # see _bwd_dq_kernel: zero fully-masked rows (lse sentinel)
        pred &= lse > _NEG_INF / 2
        p = jnp.where(pred, jnp.exp(s - lse), 0.0)
        do = do_ref[0].astype(jnp.float32)
        if dropout_p > 0.0:
            keep = _keep_mask(seed_ref[0], bh, q_start, k_start,
                              (block_q, block_k), 1.0 - dropout_p)
            inv = 1.0 / (1.0 - dropout_p)
            p_acc = jnp.where(keep, p * inv, 0.0)
        else:
            p_acc = p
        dv_acc[:] += jax.lax.dot_general(
            p_acc, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p > 0.0:
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_p)), 0.0)
        delta = delta_ref[0][:, :1]
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    run = None
    if causal:
        run = k_start <= q_start + block_q - 1
    if has_seg:
        qs, ks = qseg_ref[0], kseg_ref[0]
        overlap = (jnp.min(ks) <= jnp.max(qs)) & (
            jnp.max(ks) >= jnp.min(qs))
        run = overlap if run is None else (run & overlap)
    if run is None:
        _compute()
    else:
        pl.when(run)(_compute)

    @pl.when(last)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(scale, causal, sq_real, sk_real, block_q, skp,
                      has_kpm, has_seg, dropout_p, gqa, *refs):
    """Single-pass backward for short key sequences: K/V stay fully
    VMEM-resident, the probability tile is computed ONCE, and dq/dk/dv
    all fall out of the same pass — where the split dq + dkv kernels
    recompute p twice and traverse HBM twice.  This is the class the
    reference serves with its small-seqlen fmha variants
    (fmha_api.cpp:358 `_nl` kernels); VERDICT r3 #4."""
    if dropout_p > 0.0:
        seed_ref, refs = refs[0], refs[1:]
    if has_seg:
        qseg_ref, kseg_ref, refs = refs[0], refs[1], refs[2:]
    if has_kpm:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kpm_ref,
         dq_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dk_ref, dv_ref, dk_acc, dv_acc) = refs
    bh, qi = pl.program_id(0), pl.program_id(1)
    if gqa is None:
        first = qi == 0
        last = qi == pl.num_programs(1) - 1
    else:
        # grouped K/V: the grid still walks q-head rows (batch-major, so
        # a group's rep heads are consecutive in bh) while the dk/dv
        # output block is the group row — init on the group's first
        # (head, q-block) step, flush on its last
        n, g = gqa
        rep = n // g
        r = (bh % n) % rep
        first = (r == 0) & (qi == 0)
        last = (r == rep - 1) & (qi == pl.num_programs(1) - 1)

    @pl.when(first)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if has_kpm:
        s = s + kpm_ref[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (block_q, skp), 1)
    row = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, skp), 0)
    pred = (col < sk_real) & (row < sq_real)
    if causal:
        pred &= col <= row
    if has_seg:
        qseg = qseg_ref[0].reshape(block_q, 1)
        kseg = kseg_ref[0].reshape(1, skp)
        pred &= (qseg == kseg) & (kseg >= 0)
    lse = lse_ref[0][:, :1]
    # see _bwd_dq_kernel: zero fully-masked rows (lse sentinel)
    pred &= lse > _NEG_INF / 2
    p = jnp.where(pred, jnp.exp(s - lse), 0.0)
    do = do_ref[0].astype(jnp.float32)
    if dropout_p > 0.0:
        keep = _keep_mask(seed_ref[0], bh, q_start, 0,
                          (block_q, skp), 1.0 - dropout_p)
        inv = 1.0 / (1.0 - dropout_p)
        p_acc = jnp.where(keep, p * inv, 0.0)
    else:
        p_acc = p
    dv_acc[:] += jax.lax.dot_general(
        p_acc, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if dropout_p > 0.0:
        dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_p)), 0.0)
    delta = delta_ref[0][:, :1]
    ds = p * (dp - delta) * scale
    dq_ref[0] = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_acc[:] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(last)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_pallas_fused(q3, k3, v3, do3, lse, delta, kpm, seg, seed, scale,
                      causal, sq_real, sk_real, block_q, dropout_p,
                      interpret, out_dtype=None, gqa=None):
    """Driver for :func:`_bwd_fused_kernel` — grid (bh, q-blocks), K/V
    full-width per group (call only when the padded key length fits
    VMEM).  Under ``gqa`` the k/v (and dk/dv) rows are group-width; the
    group's rep consecutive q-head rows accumulate into one output
    block, which stays resident across their grid steps."""
    from jax.experimental.pallas import tpu as pltpu

    bh, sqp, d = q3.shape
    skp = k3.shape[1]
    lse3 = jnp.broadcast_to(lse[:, :, None], (bh, sqp, _LANES))
    delta3 = jnp.broadcast_to(delta[:, :, None], (bh, sqp, _LANES))
    qmap = lambda b, i: (b, i, 0)
    if gqa is not None:
        n, g = gqa
        kmap = lambda b, i, n=n, g=g: (_kv_of(b, n, g), 0, 0)
    else:
        kmap = lambda b, i: (b, 0, 0)
    qspec = pl.BlockSpec((1, block_q, d), qmap, memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, skp, d), kmap, memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((1, block_q, _LANES), qmap,
                           memory_space=pltpu.VMEM)
    in_specs = []
    args = []
    if dropout_p > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    if seg is not None:
        heads = bh // seg[0].shape[0]
        in_specs.append(pl.BlockSpec(
            (1, block_q), lambda b, i, h=heads: (b // h, i),
            memory_space=pltpu.VMEM))
        args.append(seg[0])
        in_specs.append(pl.BlockSpec(
            (1, skp), lambda b, i, h=heads: (b // h, 0),
            memory_space=pltpu.VMEM))
        args.append(seg[1])
    in_specs += [qspec, kspec, kspec, qspec, rowspec, rowspec]
    args += [q3, k3, v3, do3, lse3, delta3]
    if kpm is not None:
        heads = bh // kpm.shape[0]
        in_specs.append(pl.BlockSpec(
            (1, 1, skp), lambda b, i, h=heads: (b // h, 0, 0),
            memory_space=pltpu.VMEM))
        args.append(kpm)
    nkv = k3.shape[0]
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale, causal, sq_real,
                          sk_real, block_q, skp, kpm is not None,
                          seg is not None, dropout_p, gqa),
        grid=(bh, sqp // block_q),
        in_specs=in_specs,
        out_specs=[qspec, kspec, kspec],
        out_shape=[out_struct((bh, sqp, d), out_dtype or q3.dtype, q3),
                   out_struct((nkv, skp, d), out_dtype or k3.dtype, k3),
                   out_struct((nkv, skp, d), out_dtype or v3.dtype, k3)],
        scratch_shapes=[pltpu.VMEM((skp, d), jnp.float32),
                        pltpu.VMEM((skp, d), jnp.float32)],
        interpret=interpret,
    )(*args)
    return dq, dk, dv


def _bwd_pallas(q3, k3, v3, do3, lse, delta, kpm, seg, seed, scale,
                causal, sq_real, sk_real, block_q, block_k, dropout_p,
                interpret, out_dtype=None, gqa=None):
    from jax.experimental.pallas import tpu as pltpu

    bh, sqp, d = q3.shape
    skp = k3.shape[1]
    lse3 = jnp.broadcast_to(lse[:, :, None], (bh, sqp, _LANES))
    delta3 = jnp.broadcast_to(delta[:, :, None], (bh, sqp, _LANES))

    def qspec(f):
        return pl.BlockSpec((1, block_q, d), f, memory_space=pltpu.VMEM)

    def kspec(f):
        return pl.BlockSpec((1, block_k, d), f, memory_space=pltpu.VMEM)

    def rowspec(f):
        return pl.BlockSpec((1, block_q, _LANES), f,
                            memory_space=pltpu.VMEM)

    if gqa is not None:
        n, g = gqa   # bound once for both the dq and dkv sections

    # --- dq: grid (bh, q, kv) ------------------------------------------
    qmap = lambda b, i, j: (b, i, 0)
    if gqa is not None:
        kmap = lambda b, i, j, n=n, g=g: (_kv_of(b, n, g), j, 0)
    else:
        kmap = lambda b, i, j: (b, j, 0)
    in_specs = []
    args = []
    if dropout_p > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    if seg is not None:
        heads = bh // seg[0].shape[0]
        in_specs.append(pl.BlockSpec(
            (1, block_q), lambda b, i, j, h=heads: (b // h, i),
            memory_space=pltpu.VMEM))
        args.append(seg[0])
        in_specs.append(pl.BlockSpec(
            (1, block_k), lambda b, i, j, h=heads: (b // h, j),
            memory_space=pltpu.VMEM))
        args.append(seg[1])
    in_specs += [qspec(qmap), kspec(kmap), kspec(kmap), qspec(qmap),
                 rowspec(qmap), rowspec(qmap)]
    args += [q3, k3, v3, do3, lse3, delta3]
    if kpm is not None:
        heads = bh // kpm.shape[0]
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda b, i, j, h=heads: (b // h, 0, j),
            memory_space=pltpu.VMEM))
        args.append(kpm)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale, causal, sk_real,
                          block_q, block_k, kpm is not None,
                          seg is not None, dropout_p),
        grid=(bh, sqp // block_q, skp // block_k),
        in_specs=in_specs,
        out_specs=qspec(qmap),
        out_shape=out_struct((bh, sqp, d), out_dtype or q3.dtype, q3),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*args)

    # --- dk/dv ---------------------------------------------------------
    # Classic: grid (bh, kv, q), one q-head per dk/dv row.  GQA: grid
    # (b*g, kv, rep, q) — the rep query heads of a group are a grid dim
    # OUTSIDE the q-block dim, so the (b*g)-row dk/dv output block stays
    # fixed across (rep × q-blocks) consecutive steps while the kernel
    # accumulates all of the group's query heads into it; the repeated
    # dk/dv tensor (and the jnp.repeat forward tensor whose autodiff
    # would sum it) never exists in HBM.
    if gqa is not None:
        rep = n // g
        qmap2 = lambda b, j, r, i, n=n, g=g, rp=rep: (
            (b // g) * n + (b % g) * rp + r, i, 0)
        kmap2 = lambda b, j, r, i: (b, j, 0)
        grid2 = (k3.shape[0], skp // block_k, rep, sqp // block_q)
        seg_qmap = lambda b, j, r, i, g=g: (b // g, i)
        seg_kmap = lambda b, j, r, i, g=g: (b // g, j)
        kpm_map = lambda b, j, r, i, g=g: (b // g, 0, j)
    else:
        qmap2 = lambda b, j, i: (b, i, 0)
        kmap2 = lambda b, j, i: (b, j, 0)
        grid2 = (bh, skp // block_k, sqp // block_q)
        heads_s = bh // seg[0].shape[0] if seg is not None else 1
        seg_qmap = lambda b, j, i, h=heads_s: (b // h, i)
        seg_kmap = lambda b, j, i, h=heads_s: (b // h, j)
        heads_m = bh // kpm.shape[0] if kpm is not None else 1
        kpm_map = lambda b, j, i, h=heads_m: (b // h, 0, j)
    in_specs = []
    args = []
    if dropout_p > 0.0:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(seed)
    if seg is not None:
        in_specs.append(pl.BlockSpec(
            (1, block_q), seg_qmap, memory_space=pltpu.VMEM))
        args.append(seg[0])
        in_specs.append(pl.BlockSpec(
            (1, block_k), seg_kmap, memory_space=pltpu.VMEM))
        args.append(seg[1])
    in_specs += [qspec(qmap2), kspec(kmap2), kspec(kmap2), qspec(qmap2),
                 rowspec(qmap2), rowspec(qmap2)]
    args += [q3, k3, v3, do3, lse3, delta3]
    if kpm is not None:
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k), kpm_map, memory_space=pltpu.VMEM))
        args.append(kpm)
    nkv = k3.shape[0]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale, causal, sq_real,
                          sk_real, block_q, block_k, kpm is not None,
                          seg is not None, dropout_p, gqa),
        grid=grid2,
        in_specs=in_specs,
        out_specs=[kspec(kmap2), kspec(kmap2)],
        out_shape=[out_struct((nkv, skp, d), out_dtype or k3.dtype, k3),
                   out_struct((nkv, skp, d), out_dtype or v3.dtype, k3)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(*args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper.
# ---------------------------------------------------------------------------


def _to_bh(x):
    """[b, s, n, d] → [b*n, s, d]."""
    b, s, n, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * n, s, d)


def _from_bh(x3, b, n):
    bh, s, d = x3.shape
    return x3.reshape(b, n, s, d).transpose(0, 2, 1, 3)


def _blocks(sq, sk):
    """Block sizes tuned on v5e (round-3 sweep, BASELINE.md kernel
    ledger): at sk>=1024 the 1024x1024 score tile amortizes per-grid-step
    overhead and beats the old 256x512 default ~1.5x (fwd s1024 causal:
    946us vs 1494us; s2048: 644us vs 964us); short sequences keep the
    small tiles (256x512 best at s512).  1024x2048 fails to compile
    (VMEM), so 1024 caps both dims."""
    bq = min(1024 if sq >= 1024 else 256, pl.cdiv(sq, _LANES) * _LANES)
    bk = min(1024 if sk >= 1024 else 512, pl.cdiv(sk, _LANES) * _LANES)
    return bq, bk


def _seg_pads(seg, sqp, skp):
    """[b, sq] int32 segment ids → padded (q_view, k_view), pad id −2
    (matches nothing; negative ids are always-masked keys)."""
    if seg is None:
        return None
    seg = seg.astype(jnp.int32)
    segq = _pad_to(seg + 0, sqp, 1) + jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (1, sqp), 1) >= seg.shape[1],
        jnp.int32(-2), 0)
    segk = _pad_to(seg + 0, skp, 1) + jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (1, skp), 1) >= seg.shape[1],
        jnp.int32(-2), 0)
    return segq, segk


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _flash(q, k, v, kpm, seg, seed, causal, scale, dropout_p):
    o, _ = _flash_fwd(q, k, v, kpm, seg, seed, causal, scale, dropout_p)
    return o


def _flash_fwd(q, k, v, kpm, seg, seed, causal, scale, dropout_p):
    b, sq, n, d = q.shape
    sk = k.shape[1]
    g = k.shape[2]
    gqa = (n, g) if g != n else None
    block_q, block_k = _blocks(sq, sk)
    sqp = pl.cdiv(sq, block_q) * block_q
    skp = pl.cdiv(sk, block_k) * block_k
    q3 = _pad_to(_to_bh(q), sqp, 1)
    k3 = _pad_to(_to_bh(k), skp, 1)
    v3 = _pad_to(_to_bh(v), skp, 1)
    kpm3 = (None if kpm is None
            else _pad_to(kpm.astype(jnp.float32)[:, None, :], skp, 2))
    seg3 = _seg_pads(seg, sqp, skp)
    q3, k3, v3, kpm3, seg3q, seg3k, seed = _unify_vma(
        q3, k3, v3, kpm3,
        None if seg3 is None else seg3[0],
        None if seg3 is None else seg3[1], seed)
    seg3 = None if seg3 is None else (seg3q, seg3k)
    o3, lse = _fwd_pallas(q3, k3, v3, kpm3, seg3, seed, scale, causal,
                          sk, block_q, block_k, dropout_p,
                          interpret=not on_tpu(), gqa=gqa)
    o = _from_bh(o3, b, n)[:, :sq]
    return o, (q, k, v, kpm, seg, seed, o, lse)


def _flash_bwd(causal, scale, dropout_p, res, do):
    q, k, v, kpm, seg, seed, o, lse = res
    b, sq, n, d = q.shape
    sk = k.shape[1]
    g = k.shape[2]
    gqa = (n, g) if g != n else None
    block_q, block_k = _blocks(sq, sk)
    sqp = pl.cdiv(sq, block_q) * block_q
    skp = pl.cdiv(sk, block_k) * block_k
    q3 = _pad_to(_to_bh(q), sqp, 1)
    k3 = _pad_to(_to_bh(k), skp, 1)
    v3 = _pad_to(_to_bh(v), skp, 1)
    do3 = _pad_to(_to_bh(do), sqp, 1)
    o3 = _pad_to(_to_bh(o), sqp, 1)
    lse3 = _pad_to(lse, sqp, 1)
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)
    kpm3 = (None if kpm is None
            else _pad_to(kpm.astype(jnp.float32)[:, None, :], skp, 2))
    seg3 = _seg_pads(seg, sqp, skp)
    q3, k3, v3, do3, lse3, delta, kpm3, seg3q, seg3k, seed = _unify_vma(
        q3, k3, v3, do3, lse3, delta, kpm3,
        None if seg3 is None else seg3[0],
        None if seg3 is None else seg3[1], seed)
    seg3 = None if seg3 is None else (seg3q, seg3k)
    mode = os.environ.get("APEX_TPU_FLASH_BWD", "auto")
    if mode not in ("auto", "fused", "split"):
        raise ValueError(
            f"APEX_TPU_FLASH_BWD={mode!r}: expected auto|fused|split")
    # auto routes the short-key class (sk<=512) to the fused single-pass
    # backward: the round-5 on-chip sweep (first silicon after the
    # round-3/4 outage) measured fused beating the split pair at every
    # swept q-block for s512 — causal 531.7us vs 708.0us, non-causal
    # 569.0us vs 821.6us at bq=512 (tools/sweep_r4.py, SWEEP log
    # 2026-07-31) — and improving monotonically with bq.  Above 512 the
    # split pair keeps the s1024/s2048 wins from the round-3 retune
    # until tools/sweep_r5.py measures the fused kernel there.
    fused_max = int(os.environ.get("APEX_TPU_FLASH_BWD_FUSED_MAX", "512"))
    if mode == "fused" or (mode == "auto" and skp <= fused_max):
        # short-key class (BERT s512 etc.): K/V fit VMEM whole — one
        # pass computes p once and emits dq/dk/dv together, vs the
        # split kernels' two passes with p recomputed in each.  q-block
        # default 512: the round-5 sweep improved monotonically with bq
        # (128: 671us, 256: 581us, 512: 532us at s512 causal)
        env_bq = os.environ.get("APEX_TPU_FLASH_FUSED_BQ")
        fused_bq = min(int(env_bq) if env_bq else 512, sqp)
        if sqp % fused_bq:
            if env_bq:
                raise ValueError(
                    f"APEX_TPU_FLASH_FUSED_BQ={fused_bq} must divide the "
                    f"padded query length {sqp} (floor-division grids "
                    "would silently drop tail q-rows)")
            fused_bq = block_q   # always divides sqp (it set the padding)
        dq3, dk3, dv3 = _bwd_pallas_fused(
            q3, k3, v3, do3, lse3, delta, kpm3, seg3, seed, scale,
            causal, sq, sk, fused_bq, dropout_p,
            interpret=not on_tpu(), gqa=gqa)
    else:
        dq3, dk3, dv3 = _bwd_pallas(
            q3, k3, v3, do3, lse3, delta, kpm3, seg3, seed, scale,
            causal, sq, sk, block_q, block_k, dropout_p,
            interpret=not on_tpu(), gqa=gqa)
    dq = _from_bh(dq3, b, n)[:, :sq]
    dk = _from_bh(dk3, b, g)[:, :sk]
    dv = _from_bh(dv3, b, g)[:, :sk]
    # The kernel treats the (float) mask as a constant: the wrapper
    # stop-gradients it, so a zero cotangent is the user-visible truth.
    # Learned additive masks/biases belong on the differentiable XLA
    # ``bias`` path.
    dkpm = None if kpm is None else jnp.zeros_like(kpm)
    dseg = None if seg is None else np.zeros(seg.shape, jax.dtypes.float0)
    dseed = np.zeros(seed.shape, jax.dtypes.float0)
    return dq, dk, dv, dkpm, dseg, dseed


_flash.defvjp(_flash_fwd, _flash_bwd)


def _seed_from_rng(dropout_rng) -> jax.Array:
    """Collapse a PRNG key (typed or raw uint32 pair) to an int32 seed."""
    data = jax.random.key_data(dropout_rng).reshape(-1)
    seed = data[-1]
    if data.shape[0] > 1:
        seed = seed ^ (data[-2] * jnp.uint32(0x9E3779B1))
    return seed.astype(jnp.int32).reshape(1)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    key_padding_mask: Optional[jax.Array] = None,
    mask: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    dropout_p: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Memory-efficient attention over [b, s, n, d] tensors.

    The Pallas blockwise kernel handles ``causal``, ``key_padding_mask``
    ([b, sk] bool True = masked, or additive float — the reference's
    ``mask_additive`` MHA mode), ``segment_ids`` ([b, s] int32 — packed
    multi-sequence rows attend within their own segment only, with a
    block-sparse skip of fully-disjoint tiles; negative ids mark padding
    slots.  This is the cu_seqlens varlen mode of the reference fmha,
    fmha_api.cpp:358 — see :func:`flash_attention_packed` for the
    cu_seqlens-shaped wrapper) and attention ``dropout`` (fused
    in-kernel, O(sq·d) memory — reference multihead_attn philox.cuh
    analog).  A generic boolean ``mask`` or additive ``bias`` falls back
    to the fused-softmax XLA composition.
    """
    if q.ndim != 4:
        raise ValueError(f"expected [b, s, n, d], got {q.shape}")
    if v.shape[2] != k.shape[2]:
        raise ValueError(
            f"K/V head counts differ: k has {k.shape[2]}, "
            f"v has {v.shape[2]}")
    if k.shape[2] != q.shape[2]:
        # grouped K/V (GQA/MQA): each of the g kv heads serves
        # n//g query heads via kernel index maps — the repeated
        # [b, s, n, d] K/V never materializes in HBM
        if q.shape[2] % k.shape[2]:
            raise ValueError(
                f"query heads ({q.shape[2]}) must be a multiple of the "
                f"K/V group count ({k.shape[2]})")
    seg_pair = isinstance(segment_ids, tuple)
    if segment_ids is not None and not seg_pair and (
            q.shape[1] != k.shape[1]):
        raise ValueError(
            "a single segment_ids array requires sq == sk (packed "
            "self-attention rows); pass a (seg_q, seg_k) pair for "
            "cross-attention shapes (runs on the XLA path)")
    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else float(scale)
    # per-side segment ids are beyond the fused kernel (it walks one
    # packed diagonal) — the XLA composition handles them exactly
    generic = mask is not None or bias is not None or seg_pair
    # Off-TPU inside shard_map (vma non-empty): the Pallas HLO
    # interpreter's internal while-loop cannot carry mixed varying-axes
    # buffers (jax 0.9 check) — run the XLA composition instead.  On
    # real TPU the kernel runs under shard_map as normal (same choice as
    # distributed_fused_adam's CPU path).
    if not on_tpu() and getattr(jax.typeof(q), "vma", ()):
        generic = True
    if generic:
        return mha_reference(
            q, k, v, causal=causal, key_padding_mask=key_padding_mask,
            mask=mask, bias=bias, scale=scale, dropout_p=dropout_p,
            dropout_rng=dropout_rng, segment_ids=segment_ids)
    kpm = key_padding_mask
    if kpm is not None:
        if kpm.dtype == jnp.bool_:
            kpm = jnp.where(kpm, jnp.float32(_NEG_INF), jnp.float32(0.0))
        # the fused kernel does not differentiate the mask — learned
        # additive masks must use ``bias`` (XLA path) instead
        kpm = jax.lax.stop_gradient(kpm)
    seg = (None if segment_ids is None
           else jax.lax.stop_gradient(segment_ids.astype(jnp.int32)))
    use_dropout = dropout_p > 0.0 and dropout_rng is not None
    seed = (_seed_from_rng(dropout_rng) if use_dropout
            else jnp.zeros((1,), jnp.int32))
    return _flash(q, k, v, kpm, seg, seed, causal, scale,
                  float(dropout_p) if use_dropout else 0.0)


def segment_ids_from_cu_seqlens(cu_seqlens: jax.Array,
                                total: int) -> jax.Array:
    """[b+1] cumulative sequence starts → [total] int32 segment ids
    (the reference varlen descriptor, fmha_api.cpp:358).  Positions at or
    beyond ``cu_seqlens[-1]`` get id −1 (padding: masked as keys)."""
    pos = jnp.arange(total, dtype=jnp.int32)
    seg = jnp.searchsorted(cu_seqlens.astype(jnp.int32), pos,
                           side="right").astype(jnp.int32) - 1
    n_seq = cu_seqlens.shape[0] - 1
    return jnp.where(seg >= n_seq, -1, seg)


def flash_attention_packed(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    cu_seqlens: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    dropout_p: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Varlen (THD) attention over ``[total, n, d]`` packed tensors.

    The reference fmha's defining mode: multiple sequences packed into
    one row with ``cu_seqlens`` boundaries and zero padding compute
    (apex/contrib/fmha/fmha.py:33-60, fmha_api.cpp:358).  Pairs with
    :func:`apex_tpu.ops.rope.fused_apply_rotary_pos_emb_thd` (same
    cu_seqlens layout).  Internally runs the segment-id kernel on a
    [1, total, n, d] view; cross-segment tiles are skipped blockwise.

    Self-attention only (one ``cu_seqlens`` describes both sides, the
    layout of the reference's ``FMHAFun``); for rectangular cross-
    attention grids call :func:`flash_attention` with a
    ``(seg_q, seg_k)`` pair, which runs the XLA composition.
    """
    if q.ndim != 3:
        raise ValueError(f"expected packed [total, n, d], got {q.shape}")
    total = q.shape[0]
    seg = segment_ids_from_cu_seqlens(cu_seqlens, total)
    out = flash_attention(
        q[None], k[None], v[None], causal=causal,
        segment_ids=seg[None], scale=scale, dropout_p=dropout_p,
        dropout_rng=dropout_rng)
    return out[0]
