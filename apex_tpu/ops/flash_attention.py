"""Flash attention — the TPU answer to the reference's fused attention stack.

Reference parity targets: apex/contrib/csrc/fmha (seqlen<=512 BERT fwd/bwd,
varlen via cu_seqlens — fmha_api.cpp:358) and apex/contrib/csrc/
multihead_attn (pre-flash fused MHA with softmax/dropout epilogues). Instead
of porting those CUDA tilings we implement one FlashAttention-2 style
blockwise kernel set in Pallas: O(sq·d) memory, online softmax, fused causal
/ key-padding masking, fp32 accumulation on the MXU. It also serves as the
compute core of the ring-attention context-parallel path (the reference has
no long-context story; SURVEY.md §5).

Layout: [batch, seq, heads, head_dim] (the model's native BSND). The kernel
grid runs (batch*heads, q-blocks, kv-blocks) with kv innermost; VMEM scratch
carries the running max / normalizer / accumulator across kv steps.

Variants:
- ``causal=True`` — upper-triangular mask generated from iota in-kernel.
- ``key_padding_mask`` [b, sk] bool (True = masked) — fused in-kernel.
- generic additive ``bias`` or full boolean ``mask``, or dropout — routed to
  the XLA composition (these are rare paths in the reference too; its fmha
  supports only varlen+causal-free BERT shapes).

Backward: custom_vjp with the standard two-kernel scheme — dq accumulates
over kv blocks, dk/dv over q blocks, both recomputing the probabilities
from the saved logsumexp (no O(s²) residuals).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from apex_tpu.ops._pallas_utils import LANES as _LANES, out_struct
from apex_tpu.utils.registry import on_tpu

__all__ = ["flash_attention", "mha_reference"]

_NEG_INF = -1e30


def _pad_to(x, size, axis):
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# ---------------------------------------------------------------------------
# Reference XLA path (also the fallback for bias / generic mask / dropout).
# ---------------------------------------------------------------------------


def mha_reference(q, k, v, *, causal=False, key_padding_mask=None,
                  mask=None, bias=None, scale=None, dropout_p=0.0,
                  dropout_rng=None):
    """Materialized softmax(QK^T)V in fp32 — numerics oracle for the kernel
    and the execution path for variants the kernel doesn't fuse."""
    b, sq, n, d = q.shape
    sk = k.shape[1]
    scale = (1.0 / d ** 0.5) if scale is None else scale
    s = jnp.einsum("bsnd,btnd->bnst", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if mask is not None:
        s = jnp.where(mask, _NEG_INF, s)
    if key_padding_mask is not None:
        s = jnp.where(key_padding_mask[:, None, None, :], _NEG_INF, s)
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((col > row)[None, None], _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_p > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bnst,btnd->bsnd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


# ---------------------------------------------------------------------------
# Forward kernel.
# ---------------------------------------------------------------------------


def _fwd_kernel(scale, causal, sk_real, block_q, block_k, has_kpm,
                *refs):
    if has_kpm:
        q_ref, k_ref, v_ref, kpm_ref, o_ref, lse_ref, acc, m_s, l_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s, l_s = refs
    qi, kj = pl.program_id(1), pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    q_start = qi * block_q
    k_start = kj * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        pred = col < sk_real                       # kv tail padding
        if has_kpm:
            pred &= kpm_ref[0] == 0
        if causal:
            row = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            pred &= col <= row
        s = jnp.where(pred, s, _NEG_INF)

        m_prev = m_s[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # fully-masked-so-far rows: m_new == -inf ⇒ exp(NaN) guards
        p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
        alpha = jnp.where(m_new > _NEG_INF / 2, alpha, 0.0)

        l_s[:] = l_s[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)

    if causal:
        # whole kv block above the diagonal → skip its FLOPs
        pl.when(k_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)
        # logsumexp (fully-masked rows get -inf-ish sentinel)
        lse = m_s[:, :1] + jnp.log(safe_l)
        lse_ref[0] = jnp.broadcast_to(
            jnp.where(l == 0.0, _NEG_INF, lse), lse_ref.shape[1:])


def _fwd_pallas(q3, k3, v3, kpm, scale, causal, sk_real,
                block_q, block_k, interpret, out_dtype=None):
    from jax.experimental.pallas import tpu as pltpu

    bh, sqp, d = q3.shape
    skp = k3.shape[1]
    grid = (bh, sqp // block_q, skp // block_k)

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0),
                          memory_space=pltpu.VMEM)
    in_specs = [q_spec, k_spec, k_spec]
    args = [q3, k3, v3]
    if kpm is not None:
        # [b, 1, skp] int32, indexed by batch = bh // heads
        heads = bh // kpm.shape[0]
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k),
            lambda b, i, j, h=heads: (b // h, 0, j),
            memory_space=pltpu.VMEM))
        args.append(kpm)

    out_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
    ]
    out_shape = [
        out_struct((bh, sqp, d), out_dtype or q3.dtype, q3),
        out_struct((bh, sqp, _LANES), jnp.float32, q3),
    ]
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale, causal, sk_real,
                          block_q, block_k, kpm is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return o, lse[:, :, 0]


# ---------------------------------------------------------------------------
# Backward kernels.
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(scale, causal, sk_real, block_q, block_k, has_kpm, *refs):
    if has_kpm:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kpm_ref,
         dq_ref, dq_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dq_acc) = refs
    qi, kj = pl.program_id(1), pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start, k_start = qi * block_q, kj * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        pred = col < sk_real
        if has_kpm:
            pred &= kpm_ref[0] == 0
        if causal:
            row = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            pred &= col <= row
        lse = lse_ref[0][:, :1]
        p = jnp.where(pred, jnp.exp(s - lse), 0.0)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        delta = delta_ref[0][:, :1]
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(k_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(scale, causal, sq_real, sk_real, block_q, block_k,
                    has_kpm, *refs):
    if has_kpm:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, kpm_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    kj, qi = pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start, k_start = qi * block_q, kj * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        row = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        pred = (col < sk_real) & (row < sq_real)
        if has_kpm:
            pred &= kpm_ref[0] == 0
        if causal:
            pred &= col <= row
        lse = lse_ref[0][:, :1]
        p = jnp.where(pred, jnp.exp(s - lse), 0.0)
        do = do_ref[0].astype(jnp.float32)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        delta = delta_ref[0][:, :1]
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(k_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_pallas(q3, k3, v3, do3, lse, delta, kpm, scale, causal,
                sq_real, sk_real, block_q, block_k, interpret,
                out_dtype=None):
    from jax.experimental.pallas import tpu as pltpu

    bh, sqp, d = q3.shape
    skp = k3.shape[1]
    lse3 = jnp.broadcast_to(lse[:, :, None], (bh, sqp, _LANES))
    delta3 = jnp.broadcast_to(delta[:, :, None], (bh, sqp, _LANES))

    def qspec(f):
        return pl.BlockSpec((1, block_q, d), f, memory_space=pltpu.VMEM)

    def kspec(f):
        return pl.BlockSpec((1, block_k, d), f, memory_space=pltpu.VMEM)

    def rowspec(f):
        return pl.BlockSpec((1, block_q, _LANES), f,
                            memory_space=pltpu.VMEM)

    # --- dq: grid (bh, q, kv) ------------------------------------------
    qmap = lambda b, i, j: (b, i, 0)
    kmap = lambda b, i, j: (b, j, 0)
    in_specs = [qspec(qmap), kspec(kmap), kspec(kmap), qspec(qmap),
                rowspec(qmap), rowspec(qmap)]
    args = [q3, k3, v3, do3, lse3, delta3]
    if kpm is not None:
        heads = bh // kpm.shape[0]
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda b, i, j, h=heads: (b // h, 0, j),
            memory_space=pltpu.VMEM))
        args.append(kpm)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale, causal, sk_real,
                          block_q, block_k, kpm is not None),
        grid=(bh, sqp // block_q, skp // block_k),
        in_specs=in_specs,
        out_specs=qspec(qmap),
        out_shape=out_struct((bh, sqp, d), out_dtype or q3.dtype, q3),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*args)

    # --- dk/dv: grid (bh, kv, q) ---------------------------------------
    qmap2 = lambda b, j, i: (b, i, 0)
    kmap2 = lambda b, j, i: (b, j, 0)
    in_specs = [qspec(qmap2), kspec(kmap2), kspec(kmap2), qspec(qmap2),
                rowspec(qmap2), rowspec(qmap2)]
    args = [q3, k3, v3, do3, lse3, delta3]
    if kpm is not None:
        heads = bh // kpm.shape[0]
        in_specs.append(pl.BlockSpec(
            (1, 1, block_k), lambda b, j, i, h=heads: (b // h, 0, j),
            memory_space=pltpu.VMEM))
        args.append(kpm)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale, causal, sq_real,
                          sk_real, block_q, block_k, kpm is not None),
        grid=(bh, skp // block_k, sqp // block_q),
        in_specs=in_specs,
        out_specs=[kspec(kmap2), kspec(kmap2)],
        out_shape=[out_struct((bh, skp, d), out_dtype or k3.dtype, k3),
                   out_struct((bh, skp, d), out_dtype or v3.dtype, k3)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(*args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper.
# ---------------------------------------------------------------------------


def _to_bh(x):
    """[b, s, n, d] → [b*n, s, d]."""
    b, s, n, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * n, s, d)


def _from_bh(x3, b, n):
    bh, s, d = x3.shape
    return x3.reshape(b, n, s, d).transpose(0, 2, 1, 3)


def _blocks(sq, sk):
    bq = min(256, pl.cdiv(sq, _LANES) * _LANES)
    bk = min(512, pl.cdiv(sk, _LANES) * _LANES)
    return bq, bk


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, kpm, causal, scale):
    o, _ = _flash_fwd(q, k, v, kpm, causal, scale)
    return o


def _flash_fwd(q, k, v, kpm, causal, scale):
    b, sq, n, d = q.shape
    sk = k.shape[1]
    block_q, block_k = _blocks(sq, sk)
    sqp = pl.cdiv(sq, block_q) * block_q
    skp = pl.cdiv(sk, block_k) * block_k
    q3 = _pad_to(_to_bh(q), sqp, 1)
    k3 = _pad_to(_to_bh(k), skp, 1)
    v3 = _pad_to(_to_bh(v), skp, 1)
    kpm3 = (None if kpm is None
            else _pad_to(kpm.astype(jnp.int32)[:, None, :], skp, 2))
    o3, lse = _fwd_pallas(q3, k3, v3, kpm3, scale, causal, sk,
                          block_q, block_k, interpret=not on_tpu())
    o = _from_bh(o3, b, n)[:, :sq]
    return o, (q, k, v, kpm, o, lse)


def _flash_bwd(causal, scale, res, do):
    q, k, v, kpm, o, lse = res
    b, sq, n, d = q.shape
    sk = k.shape[1]
    block_q, block_k = _blocks(sq, sk)
    sqp = pl.cdiv(sq, block_q) * block_q
    skp = pl.cdiv(sk, block_k) * block_k
    q3 = _pad_to(_to_bh(q), sqp, 1)
    k3 = _pad_to(_to_bh(k), skp, 1)
    v3 = _pad_to(_to_bh(v), skp, 1)
    do3 = _pad_to(_to_bh(do), sqp, 1)
    o3 = _pad_to(_to_bh(o), sqp, 1)
    lse3 = _pad_to(lse, sqp, 1)
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)
    kpm3 = (None if kpm is None
            else _pad_to(kpm.astype(jnp.int32)[:, None, :], skp, 2))
    dq3, dk3, dv3 = _bwd_pallas(
        q3, k3, v3, do3, lse3, delta, kpm3, scale, causal, sq, sk,
        block_q, block_k, interpret=not on_tpu())
    dq = _from_bh(dq3, b, n)[:, :sq]
    dk = _from_bh(dk3, b, n)[:, :sk]
    dv = _from_bh(dv3, b, n)[:, :sk]
    # bool mask has no tangent space — float0 cotangent
    dkpm = (None if kpm is None
            else np.zeros(kpm.shape, jax.dtypes.float0))
    return dq, dk, dv, dkpm


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    key_padding_mask: Optional[jax.Array] = None,
    mask: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    dropout_p: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Memory-efficient attention over [b, s, n, d] tensors.

    The Pallas blockwise kernel handles ``causal`` and ``key_padding_mask``
    ([b, sk] bool, True = masked — the cu_seqlens analog of reference
    fmha_api.cpp:358). A generic boolean ``mask``, additive ``bias``, or
    attention ``dropout`` falls back to the fused-softmax XLA composition
    (reference fast_multihead_attn territory).
    """
    if q.ndim != 4:
        raise ValueError(f"expected [b, s, n, d], got {q.shape}")
    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else float(scale)
    generic = (mask is not None or bias is not None
               or (dropout_p > 0.0 and dropout_rng is not None))
    if generic:
        return mha_reference(
            q, k, v, causal=causal, key_padding_mask=key_padding_mask,
            mask=mask, bias=bias, scale=scale, dropout_p=dropout_p,
            dropout_rng=dropout_rng)
    return _flash(q, k, v, key_padding_mask, causal, scale)
