"""Fused bias + SwiGLU.

Reference: csrc/megatron/fused_bias_swiglu.cpp (fwd/bwd) — given
``y = x + bias`` with ``y = [y1 ‖ y2]`` split on the last dim,

    out = silu(y1) · y2,   silu(z) = z·sigmoid(z)

Backward (derived, matches fused_bias_swiglu.cu):
    dsilu(z) = sigmoid(z)·(1 + z·(1-sigmoid(z)))
    dy1 = g · y2 · dsilu(y1);  dy2 = g · silu(y1);  dbias = Σ dy

Elementwise throughout — XLA fuses it into the surrounding GEMMs; custom VJP
avoids saving silu activations (recomputes from x+bias like the reference).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["fused_bias_swiglu", "fused_bias_swiglu_paired", "bias_swiglu_ref"]


def _silu(z):
    return z * jax.nn.sigmoid(z)


def bias_swiglu_ref(x, bias=None):
    y = x.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    y1, y2 = jnp.split(y, 2, axis=-1)
    return (_silu(y1) * y2).astype(x.dtype)


@jax.custom_vjp
def _bias_swiglu(x, bias):
    return bias_swiglu_ref(x, bias)


def _fwd(x, bias):
    return bias_swiglu_ref(x, bias), (x, bias)


def _bwd(res, g):
    x, bias = res
    y = x.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    y1, y2 = jnp.split(y, 2, axis=-1)
    g32 = g.astype(jnp.float32)
    sig = jax.nn.sigmoid(y1)
    dsilu = sig * (1.0 + y1 * (1.0 - sig))
    dy1 = g32 * y2 * dsilu
    dy2 = g32 * _silu(y1)
    dx = jnp.concatenate([dy1, dy2], axis=-1)
    dbias = None
    if bias is not None:
        reduce_axes = tuple(range(dx.ndim - 1))
        dbias = jnp.sum(dx, axis=reduce_axes).astype(bias.dtype)
    return dx.astype(x.dtype), dbias


_bias_swiglu.defvjp(_fwd, _bwd)


def fused_bias_swiglu(x: jax.Array, bias: Optional[jax.Array] = None):
    """SwiGLU over the (even) last dim of ``x + bias``
    (reference fused_bias_swiglu.cpp:9-10)."""
    if x.shape[-1] % 2 != 0:
        raise ValueError("fused_bias_swiglu needs an even last dimension")
    return _bias_swiglu(x, bias)


@jax.custom_vjp
def _bias_swiglu_paired(y, bias):
    yf = y.astype(jnp.float32)
    if bias is not None:
        yf = yf + bias.astype(jnp.float32)
    return (_silu(yf[..., 0, :]) * yf[..., 1, :]).astype(y.dtype)


def _paired_fwd(y, bias):
    return _bias_swiglu_paired(y, bias), (y, bias)


def _paired_bwd(res, g):
    y, bias = res
    yf = y.astype(jnp.float32)
    if bias is not None:
        yf = yf + bias.astype(jnp.float32)
    y1 = yf[..., 0, :]
    y2 = yf[..., 1, :]
    g32 = g.astype(jnp.float32)
    sig = jax.nn.sigmoid(y1)
    dsilu = sig * (1.0 + y1 * (1.0 - sig))
    dy = jnp.stack([g32 * y2 * dsilu, g32 * _silu(y1)], axis=-2)
    dbias = None
    if bias is not None:
        reduce_axes = tuple(range(dy.ndim - bias.ndim))
        dbias = jnp.sum(dy, axis=reduce_axes).astype(bias.dtype)
    return dy.astype(y.dtype), dbias


_bias_swiglu_paired.defvjp(_paired_fwd, _paired_bwd)


def fused_bias_swiglu_paired(y: jax.Array,
                             bias: Optional[jax.Array] = None) -> jax.Array:
    """SwiGLU on the paired layout ``[..., 2, f]`` — gate at index 0, up at
    index 1 on the second-to-last dim.

    Tensor-parallel-safe variant of :func:`fused_bias_swiglu`: sharding the
    trailing ``f`` dim keeps each shard a (gate, up) pair, whereas sharding
    the concatenated ``[..., 2f]`` layout splits gate columns across ranks.
    Same math as the reference kernel (fused_bias_swiglu.cu), recompute-in-
    backward like the concat variant.
    """
    if y.shape[-2] != 2:
        raise ValueError("paired layout requires shape [..., 2, f]")
    return _bias_swiglu_paired(y, bias)
