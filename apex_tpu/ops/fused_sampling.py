"""Fused sampling — logits → temperature → top-k/top-p → sample, one op.

The decode hot path pays a chain of separate sampling ops per token
(temperature scale → ``lax.top_k``/sort → cumulative-sum nucleus mask →
``jax.random.categorical``), each a full ``[b, vocab]`` HBM round trip.
Following "LLM Inference Acceleration via Efficient Operation Fusion"
(PAPERS.md, ROADMAP item 2), :func:`fused_sample` collapses the chain
into ONE kernel over ``[b, vocab]``: each grid step owns a row, applies
the vocab limit, scales by that row's temperature, resolves the top-k
and nucleus cutoffs by in-register bisection (no sort, no materialized
sorted copy), and draws the token by Gumbel-max over the filtered
logits — the row is read from HBM once and the only write is one token
id.

Two execution paths, routed like ``flash_attention`` /
``paged_attention``:

- **reference** (always available, the numerics oracle): the exact
  ``sample_logits`` op sequence — *bit-identical* to the historical
  sampler given the same PRNG key, which is what lets
  ``models.generate.sample_logits`` become a thin wrapper without
  perturbing any seeded test;
- **kernel**: the fused Pallas kernel.  Its filter cutoffs converge to
  the same values (bisection over row values is exact at fp32
  resolution), but the Gumbel draw uses an in-kernel counter-based
  generator (seeded from the caller's key), so kernel-path parity is
  *distributional* (χ² in tests/test_fused_sampling.py) while greedy
  rows are exact.

``APEX_TPU_FUSED_SAMPLING=kernel|reference|auto`` overrides the route
(malformed values warn by name and fall back to ``auto``, the env
convention of ``utils/probe.py``); an explicit ``backend=`` argument
raises on malformed values like the paged-attention gate.  ``auto``
picks the kernel on TPU or under ``APEX_TPU_PALLAS_INTERPRET=1`` (the
8-virtual-device CI path) and the reference elsewhere.

``temperature`` may be a per-sequence ``[b]`` vector (traced — the
serving engine's mixed-temperature contract): rows at temperature 0
take the argmax, the rest sample at temperature 1 over their pre-scaled
logits, exactly the engine's historical ``_mixed_sample`` composition.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._pallas_utils import LANES as _LANES
from apex_tpu.utils.registry import on_tpu

__all__ = ["fused_sample", "filter_logits", "sample_reference",
           "apply_token_mask"]

_NEG_INF = -1e30
# bisection trip count: each iteration halves the value interval, so 64
# collapses any fp32 row range below one ulp — the cutoff the loop
# converges to IS the row's k-th value / nucleus boundary exactly
_BISECT_ITERS = 64


def filter_logits(logits, *, top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
    """Apply the top-k / nucleus cutoffs to ``logits`` ``[b, v]``
    (already temperature-scaled), returning filtered logits with
    dropped tokens at ``-1e30`` — the exact op sequence the historical
    ``sample_logits`` used, factored out so the fused reference path,
    the thin ``sample_logits`` wrapper, and speculative decoding's
    rejection-sampling distributions all share ONE implementation.

    Without ``top_p`` the top-k cutoff uses ``jax.lax.top_k``
    (O(v·log k)) instead of a full descending sort; the single-sort
    path survives only where the nucleus mass genuinely needs the
    sorted cumulative sum."""
    if top_p is None:
        if top_k is not None:
            kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits < kth, _NEG_INF, logits)
        return logits
    # one descending sort serves both cutoffs (the nucleus mass below
    # needs the sorted cumulative sum anyway)
    sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
    if top_k is not None:
        kth = sorted_l[:, top_k - 1][:, None]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
        # reflect the cutoff in sorted space so the nucleus mass
        # below is computed over the top_k-filtered distribution
        rank = jnp.arange(sorted_l.shape[-1])[None]
        sorted_l = jnp.where(rank >= top_k, _NEG_INF, sorted_l)
    # nucleus: drop tokens outside the smallest prob-sorted prefix
    # reaching mass top_p; n_keep clamps to 1 so the head token always
    # stays (top_p<=0 means near-greedy, not a silent no-op)
    probs = jax.nn.softmax(sorted_l, axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (csum - probs) < top_p
    n_keep = jnp.maximum(jnp.sum(keep_sorted, axis=-1), 1)
    cutoff = jnp.take_along_axis(sorted_l, (n_keep - 1)[:, None], axis=-1)
    return jnp.where(logits < cutoff, _NEG_INF, logits)


def _mask_vocab(logits, vocab_limit):
    if vocab_limit is None:
        return logits
    over = jnp.arange(logits.shape[-1]) >= vocab_limit
    return jnp.where(over[None], _NEG_INF, logits)


def apply_token_mask(logits, token_mask):
    """Constrained decoding (ISSUE 20): zero out disallowed tokens
    BEFORE any temperature/top-k/top-p work.  ``token_mask`` is a bool
    ``[v]`` (one constraint for the whole batch) or ``[b, v]``
    (per-row, the serving engine's per-request JSON-mode masks), True =
    allowed.  Masking ahead of the filters is what keeps the filtered
    distribution a proper renormalization of the allowed set — masking
    after top-k could leave fewer than k live tokens of the ALLOWED
    set and silently sharpen the draw."""
    if token_mask is None:
        return logits
    mask = token_mask
    if mask.ndim == 1:
        mask = mask[None]
    return jnp.where(mask, logits, _NEG_INF)


def sample_reference(logits, key, *, temperature=0.0,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     vocab_limit: Optional[int] = None,
                     token_mask=None):
    """The XLA composition (numerics oracle): bit-identical to the
    historical ``sample_logits`` for a scalar ``temperature`` and to
    the serving engine's mixed-temperature sampler for a ``[b]``
    vector, given the same key (and, with ``token_mask=None``, to the
    pre-constrained-decoding sampler exactly)."""
    logits = apply_token_mask(_mask_vocab(logits, vocab_limit),
                              token_mask)
    if not (hasattr(temperature, "ndim") and temperature.ndim):
        # static scalar: greedy short-circuits ALL filtering work — the
        # cutoffs cannot change the argmax (tests pin the equivalence)
        if float(temperature) == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = filter_logits(logits / float(temperature),
                               top_k=top_k, top_p=top_p)
        return jax.random.categorical(key, scaled).astype(jnp.int32)
    # per-sequence [b] temperatures (traced): greedy rows take the
    # argmax, the rest sample at temperature 1 over pre-scaled logits —
    # one traced vector, no recompile per request mix
    temps = temperature.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = filter_logits(logits / jnp.maximum(temps, 1e-6)[:, None],
                           top_k=top_k, top_p=top_p)
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Fused Pallas kernel.
# ---------------------------------------------------------------------------


def _uniform_bits(col_u32, row, s0, s1):
    """Counter-based per-(row, column) uniform draw in (0, 1): a
    murmur3-style finalizer over (column, row, key words).  Chosen over
    ``pltpu.prng_*`` because it lowers identically on hardware AND the
    interpret path (the CI route), and it is a pure function of the
    caller's PRNG key — same key, same draw."""
    x = col_u32 ^ (s0 + row.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    x = x + s1
    x = x * jnp.uint32(0x27D4EB2F)
    x = x ^ (x >> 15)
    # 24 high bits -> exact multiples of 2^-24 in [0, 1 - 2^-24] (every
    # such multiple is fp32-representable, so u can never round UP to
    # 1.0 and blow the double log into +inf); clamp the bottom so it
    # never sees exactly 0 either
    u = (x >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
    return jnp.maximum(u, 1.0 / (1 << 24))


def _sampling_kernel(top_k, top_p, n_valid, *refs):
    """Grid (b,): one row per step.  The row is read once; the filters
    resolve their cutoffs by value-space bisection (64 halvings of the
    row's own range collapse below one fp32 ulp, so the converged bound
    IS the k-th value / nucleus boundary), and the draw is Gumbel-max —
    no sort, no second HBM pass, one int32 out."""
    seed_ref, temp_ref, x_ref, o_ref = refs
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)                    # (1, V)
    V = x.shape[1]
    col = jax.lax.broadcasted_iota(jnp.int32, (1, V), 1)
    valid = col < n_valid          # vocab limit + lane padding together
    x = jnp.where(valid, x, _NEG_INF)

    # greedy argmax (also the nucleus filter's forced-keep head token)
    m = jnp.max(x)
    greedy = jnp.min(jnp.where((x == m) & valid, col, V))

    temp = temp_ref[i]
    y = jnp.where(valid, x / jnp.maximum(temp, 1e-6), _NEG_INF)

    if top_k is not None and top_k < n_valid:
        # k-th largest by bisection: the largest t with
        # count(y >= t) >= k is exactly the k-th value.  The range must
        # span only LIVE entries (the nucleus branch's discipline): a
        # token mask leaves -1e30 holes inside the vocab window, and a
        # range that wide turns 64 halvings into a useless resolution —
        # the cutoff would never resolve between finite logits and the
        # filter silently keeps the whole allowed set
        hi0 = jnp.max(y)
        lo0 = jnp.min(jnp.where(y > _NEG_INF / 2, y, hi0))

        def kth_body(_, carry):
            lo, hi = carry
            mid = 0.5 * (lo + hi)
            cnt = jnp.sum((y >= mid).astype(jnp.int32))
            ok = cnt >= top_k
            return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

        kth, _ = jax.lax.fori_loop(0, _BISECT_ITERS, kth_body, (lo0, hi0))
        y = jnp.where(y < kth, _NEG_INF, y)

    if top_p is not None:
        # nucleus boundary by bisection on UNNORMALIZED mass: drop v
        # iff the mass strictly above it reaches top_p — the same keep
        # set as the sorted-prefix form (ties at the cutoff included)
        m2 = jnp.max(y)
        live = y > _NEG_INF / 2
        e = jnp.where(live, jnp.exp(y - m2), 0.0)
        target = jnp.float32(top_p) * jnp.sum(e)
        # the bisection range must span only LIVE entries: a prior
        # top-k filter left -1e30 holes inside the vocab window, and a
        # range that wide turns 64 halvings into a useless resolution
        lo0 = jnp.min(jnp.where(live, y, m2)) - 1.0

        def nuc_body(_, carry):
            lo, hi = carry
            mid = 0.5 * (lo + hi)
            mass = jnp.sum(jnp.where(y > mid, e, 0.0))
            ok = mass >= target
            return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid)

        theta, _ = jax.lax.fori_loop(0, _BISECT_ITERS, nuc_body, (lo0, m2))
        y = jnp.where((y > theta) | (col == greedy), y, _NEG_INF)

    u = _uniform_bits(col.astype(jnp.uint32), i,
                      seed_ref[0].astype(jnp.uint32),
                      seed_ref[1].astype(jnp.uint32))
    z = y + (-jnp.log(-jnp.log(u)))                       # Gumbel-max
    ms = jnp.max(z)
    sampled = jnp.min(jnp.where(z == ms, col, V))
    out = jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)
    o_ref[...] = jnp.full((1, _LANES), out, jnp.int32)


def _key_words(key) -> jax.Array:
    """Two int32 words from a PRNG key (typed or raw uint32 pair)."""
    data = key
    if not jnp.issubdtype(jnp.result_type(key), jnp.integer):
        data = jax.random.key_data(key)
    data = data.reshape(-1)
    words = jnp.stack([data[0], data[-1]]).astype(jnp.uint32)
    return jax.lax.bitcast_convert_type(words, jnp.int32)


def _fused_pallas(logits, key, temps, top_k, top_p, vocab_limit,
                  interpret):
    b, v = logits.shape
    n_valid = v if vocab_limit is None else min(int(vocab_limit), v)
    pad = (-v) % _LANES
    if pad:
        logits = jnp.pad(logits, ((0, 0), (0, pad)),
                         constant_values=_NEG_INF)
    top_k = None if top_k is None else min(int(top_k), n_valid)
    call = pl.pallas_call(
        functools.partial(_sampling_kernel, top_k, top_p, n_valid),
        grid_spec=_grid_spec(b, logits.shape[1]),
        out_shape=jax.ShapeDtypeStruct((b, _LANES), jnp.int32),
        interpret=interpret,
    )
    out = call(_key_words(key), temps.astype(jnp.float32), logits)
    return out[:, 0]


def _grid_spec(b, v_padded):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[pl.BlockSpec(
            (1, v_padded), lambda i, seed_ref, temp_ref: (i, 0))],
        out_specs=pl.BlockSpec(
            (1, _LANES), lambda i, seed_ref, temp_ref: (i, 0)),
    )


def _route(backend: Optional[str]) -> str:
    if backend is None:
        backend = os.environ.get("APEX_TPU_FUSED_SAMPLING", "auto")
        if backend not in ("auto", "kernel", "reference"):
            # env values warn BY NAME and fall back (utils/probe.py
            # convention): a typo'd deployment var must not take the
            # whole decode path down
            from apex_tpu.utils.logging import get_logger

            get_logger("ops").warning(
                "APEX_TPU_FUSED_SAMPLING=%r is not one of "
                "auto|kernel|reference; falling back to auto", backend)
            backend = "auto"
    elif backend not in ("auto", "kernel", "reference"):
        raise ValueError(
            f"fused sampling backend={backend!r}: expected "
            "auto|kernel|reference")
    if backend == "auto":
        interp = os.environ.get("APEX_TPU_PALLAS_INTERPRET", "0") == "1"
        backend = "kernel" if (on_tpu() or interp) else "reference"
    return backend


def fused_sample(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature=0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    vocab_limit: Optional[int] = None,
    token_mask=None,
    backend: Optional[str] = None,
) -> jax.Array:
    """Sample next tokens ``[b]`` from ``logits`` ``[b, v]`` with the
    whole temperature → top-k → top-p → draw chain fused into one op.

    ``temperature``: a static float (0 = greedy, every filter skipped —
    the cutoffs cannot change the argmax) or a traced ``[b]`` vector of
    per-sequence temperatures (rows at 0 are greedy).  ``top_k`` /
    ``top_p`` / ``vocab_limit`` are static.  ``backend``: ``None``
    routes automatically (fused Pallas kernel on TPU or under
    ``APEX_TPU_PALLAS_INTERPRET=1``; XLA reference otherwise;
    ``APEX_TPU_FUSED_SAMPLING`` overrides, malformed values warn by
    name), ``"kernel"`` / ``"reference"`` pin a path — the parity
    suite compares the two.

    Distribution contract: the reference path is bit-identical to the
    historical ``sample_logits`` given the same key; the kernel path
    selects the same support (greedy rows exactly) but draws through an
    in-kernel counter-based generator, so its parity is distributional
    (χ² — tests/test_fused_sampling.py).

    ``token_mask``: optional bool ``[v]`` / ``[b, v]`` allowed-token
    mask (constrained decoding, e.g. a JSON-mode token set), applied
    before every filter on BOTH paths — the kernel sees pre-masked
    logits, so its bisection cutoffs resolve over the allowed set."""
    if top_k is not None and top_k < 1:
        raise ValueError(
            f"top_k={top_k}: pass None (not 0) to disable the cutoff")
    logits = apply_token_mask(logits, token_mask)
    static_temp = not (hasattr(temperature, "ndim")
                      and getattr(temperature, "ndim", 0))
    if static_temp and float(temperature) < 0:
        raise ValueError(
            f"temperature={temperature}: negative temperatures would "
            "silently invert the distribution; pass 0 for greedy or a "
            "positive value")
    if _route(backend) == "reference":
        return sample_reference(logits, key, temperature=temperature,
                                top_k=top_k, top_p=top_p,
                                vocab_limit=vocab_limit)
    if static_temp and float(temperature) == 0.0:
        # pure argmax — not worth a kernel launch, and it keeps greedy
        # bit-identical across every backend
        return jnp.argmax(_mask_vocab(logits, vocab_limit),
                          axis=-1).astype(jnp.int32)
    temps = (jnp.full((logits.shape[0],), float(temperature), jnp.float32)
             if static_temp else temperature.astype(jnp.float32))
    return _fused_pallas(logits, key, temps, top_k, top_p, vocab_limit,
                         interpret=not on_tpu())
