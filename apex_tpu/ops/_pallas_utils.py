"""Shared helpers for Pallas row-kernel wrappers."""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.utils.registry import on_tpu

LANES = 128

__all__ = ["LANES", "pallas_ok", "pad_rows", "out_struct"]


def out_struct(shape, dtype, like) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct for a pallas_call output, propagating the mesh-axis
    variance (vma) of ``like`` — required when the kernel runs inside a
    ``jax.shard_map`` with its default ``check_vma=True``."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def pallas_ok(op_name: str, last_dim: int, dtype) -> bool:
    """Common gate: on TPU (or forced interpret), lane-aligned last dim,
    supported dtype, and not disabled via APEX_TPU_DISABLE_<OP>=1."""
    if os.environ.get(f"APEX_TPU_DISABLE_{op_name.upper()}", "0") == "1":
        return False
    interp = os.environ.get("APEX_TPU_PALLAS_INTERPRET", "0") == "1"
    return (
        (on_tpu() or interp)
        and last_dim % LANES == 0
        and dtype in (jnp.float32, jnp.bfloat16, jnp.float16)
    )


def pad_rows(x2, block_rows: int):
    """Zero-pad dim 0 to a multiple of block_rows; returns (padded, rows).

    Padding rows are zeros: reductions over rows (dγ/dβ-style accumulators)
    see zero contributions, and per-row outputs are sliced off by callers.
    """
    rows = x2.shape[0]
    padded = pl.cdiv(rows, block_rows) * block_rows
    if padded == rows:
        return x2, rows
    pad_width = [(0, padded - rows)] + [(0, 0)] * (x2.ndim - 1)
    return jnp.pad(x2, pad_width), rows
