"""Fused decode-layer step: rope + paged attention + output projection.

After PR 14/15 the serving tier schedules well, but the per-token step
itself is still inter-op bound: every decode layer launches rope → the
paged-attention kernel → the output projection as separate XLA ops with
HLO glue between them — exactly the residual cost "LLM Inference
Acceleration via Efficient Operation Fusion" (PAPERS.md) identifies.
This module fuses the three into ONE Pallas kernel with one VMEM
residency (ROADMAP item 4's kernel half):

- the query token's rotary embedding is applied in-kernel at the first
  block step (per-sequence angle rows ride a tiny ``[b, d2]`` input;
  the rotated query parks in a VMEM scratch reused by every block
  step), matching :func:`apex_tpu.ops.rope.fused_apply_rotary_pos_emb_
  ragged`'s partial-rotation NeoX math — including its round-trip to
  the compute dtype, so the fused path sees the bits the unfused path
  feeds its attention;
- attention over the paged KV pool runs the exact online-softmax loop
  of :mod:`apex_tpu.ops.paged_attention` — block table dereferenced by
  the BlockSpec index map via scalar prefetch (the fused-gather
  property), ragged skip of dead blocks, per-position tail mask, GQA/
  MQA head folding, and in-VMEM int8 dequantization of block-scaled
  pools (ISSUE 14's ``cache_wire="int8"``);
- the output projection (``ctx @ W_proj``) runs at the finalize step
  off the still-resident f32 accumulator — the context vector never
  round-trips through HBM between attention and projection.

``decode_layer_reference`` is the XLA composition (rope → :func:`~apex_
tpu.ops.paged_attention.ragged_paged_attention` → matmul), numerically
the exact op sequence ``models/generate._layer_decode_paged`` ran
before this op existed — the always-available fallback and the parity
oracle.  ``APEX_TPU_DECODE_FUSED=kernel|reference|auto`` routes exactly
like flash/paged/grouped (auto → kernel on TPU or under
``APEX_TPU_PALLAS_INTERPRET=1``), and ``backend=`` pins a path.

VMEM budget note: the projection weight is held fully resident
(``nh·dh·h_out`` elements) next to one K/V block — the decode-layer
shapes this repo serves fit comfortably, but a multi-MB projection
slab should stay on the unfused path (quantized int8 weight slabs
already do: ``models/generate`` routes them to the reference
composition, where ``ops/dense.dense_quantized`` owns the tiling).

Layout contract (shared with :mod:`apex_tpu.ops.paged_attention`):
``q`` ``[b, num_heads, dh]`` PRE-rope, pools ``[num_blocks,
block_size, kv_groups, dh]``, ``block_tables`` ``[b, max_blocks]``
(entries ``>= num_blocks`` unmapped), ``lengths`` ``[b]`` live tokens
(query included), ``w_proj`` ``[num_heads·dh, h_out]`` float,
``rope_cos``/``rope_sin`` ``[b, d2]`` per-sequence angle rows (``None``
= no rotary, e.g. learned positions) → output ``[b, h_out]`` in
``q.dtype``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._pallas_utils import LANES as _LANES
from apex_tpu.ops.paged_attention import (
    _check_paged_shapes, ragged_paged_attention)
from apex_tpu.ops.rope import _rope
from apex_tpu.utils.registry import on_tpu

__all__ = ["fused_decode_layer", "decode_layer_reference",
           "route_decode_fused"]

_NEG_INF = -1e30


def _check_fused_shapes(q, w_proj, rope_cos, rope_sin):
    if isinstance(w_proj, dict):
        raise ValueError(
            "w_proj is a quantized weight slab; the fused decode layer "
            "takes plain float projection kernels only — route "
            "quantized projections through the reference composition "
            "(ops/dense.dense_quantized owns their tiling)")
    b, nh, dh = q.shape
    if w_proj.ndim != 2 or w_proj.shape[0] != nh * dh:
        raise ValueError(
            f"expected w_proj [num_heads*dh={nh * dh}, h_out], got "
            f"{w_proj.shape}")
    if (rope_cos is None) != (rope_sin is None):
        raise ValueError("pass rope_cos and rope_sin together or not "
                         "at all")
    if rope_cos is not None:
        d2 = rope_cos.shape[-1]
        if rope_cos.shape != (b, d2) or rope_sin.shape != (b, d2):
            raise ValueError(
                f"expected per-sequence rope rows [b={b}, d2], got cos "
                f"{rope_cos.shape} sin {rope_sin.shape}")
        if d2 > dh or d2 % 2:
            raise ValueError(
                f"rotary dim d2={d2} must be even and <= head dim "
                f"{dh}")


def route_decode_fused(backend: Optional[str]) -> str:
    """Resolve the fused-decode-layer route: ``APEX_TPU_DECODE_FUSED=
    kernel|reference|auto`` overrides, auto picks the kernel on TPU /
    under ``APEX_TPU_PALLAS_INTERPRET=1`` — the flash/paged/grouped
    pattern.  Exposed so ``models/generate`` can resolve the route ONCE
    at the Python level and thread it through its jit static args (a
    trace-time env read would pin the first call's route into every
    cached trace)."""
    if backend is None:
        backend = os.environ.get("APEX_TPU_DECODE_FUSED", "auto")
    if backend not in ("auto", "kernel", "reference"):
        raise ValueError(
            f"fused decode backend={backend!r} (APEX_TPU_DECODE_FUSED): "
            "expected auto|kernel|reference")
    if backend == "auto":
        interp = os.environ.get("APEX_TPU_PALLAS_INTERPRET", "0") == "1"
        backend = "kernel" if (on_tpu() or interp) else "reference"
    return backend


def decode_layer_reference(q, k_pool, v_pool, block_tables, lengths,
                           w_proj, *, rope_cos=None, rope_sin=None,
                           scale: Optional[float] = None,
                           k_scale=None, v_scale=None,
                           attention_backend: Optional[str] = None):
    """XLA composition of the three fused stages — numerically the
    exact op sequence the unfused decode layer runs (rope's f32 math +
    dtype round-trip, :func:`ragged_paged_attention` with its own
    routing still honored via ``attention_backend``, then the plain
    ``ctx @ W.astype(dtype)`` matmul of ``ops/dense.quantized_matmul``'s
    float path).  The parity oracle and the always-available fallback."""
    _check_paged_shapes(q, k_pool, v_pool, block_tables, lengths,
                        k_scale, v_scale)
    _check_fused_shapes(q, w_proj, rope_cos, rope_sin)
    b = q.shape[0]
    if rope_cos is not None:
        # same math (and the same [b, s=1, h, d] shapes) as
        # fused_apply_rotary_pos_emb_ragged with the rows pre-gathered
        q = _rope(q[:, None],
                  rope_cos.astype(jnp.float32)[:, None, None, :],
                  rope_sin.astype(jnp.float32)[:, None, None, :])[:, 0]
    ctx = ragged_paged_attention(
        q, k_pool, v_pool, block_tables, lengths, scale=scale,
        backend=attention_backend, k_scale=k_scale, v_scale=v_scale)
    # the historical projection site: [b, 1, nh*dh] @ W in the compute
    # dtype (ops/dense.quantized_matmul's plain-array path)
    ctx_flat = ctx.astype(q.dtype).reshape(b, 1, -1)
    return (ctx_flat @ w_proj.astype(q.dtype))[:, 0]


# ---------------------------------------------------------------------------
# Fused Pallas kernel.
# ---------------------------------------------------------------------------


def _fused_kernel(scale, bs, g, rep, d2, quant, has_rope, *refs):
    """Grid (b, max_blocks), sequence-major like ``_paged_kernel``; one
    physical K/V block per step, online softmax across the block steps,
    plus two fused edges: the query ropes ONCE at ``j == 0`` (parked in
    a VMEM scratch every block step reuses) and the output projection
    runs at the last block step off the f32 accumulator — between rope
    and projection nothing leaves VMEM."""
    it = iter(refs)
    tbl_ref, len_ref = next(it), next(it)
    q_ref = next(it)
    cos_ref = sin_ref = None
    if has_rope:
        cos_ref, sin_ref = next(it), next(it)
    k_ref = next(it)
    ks_ref = next(it) if quant else None
    v_ref = next(it)
    vs_ref = next(it) if quant else None
    w_ref = next(it)
    o_ref = next(it)
    m_s, l_s, acc, qr = next(it), next(it), next(it), next(it)
    del it
    i, j = pl.program_id(0), pl.program_id(1)
    nh = g * rep
    dh = qr.shape[-1]

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, _NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        q = q_ref[0].astype(jnp.float32)          # [nh, dh]
        if has_rope:
            cos = cos_ref[0]                      # [d2] (f32 input)
            sin = sin_ref[0]
            t32 = q[:, :d2]
            half = d2 // 2
            rot = jnp.concatenate([-t32[:, half:], t32[:, :half]],
                                  axis=-1)
            rq = t32 * cos[None, :] + rot * sin[None, :]
            if d2 < dh:
                rq = jnp.concatenate([rq, q[:, d2:]], axis=-1)
            # the unfused path rounds the roped query to the compute
            # dtype before attention casts it back up — replay that
            # round-trip so both paths score identical query bits
            q = rq.astype(o_ref.dtype).astype(jnp.float32)
        qr[:] = q

    length = len_ref[i]

    def _compute():
        q = qr[:]                                 # [nh, dh] f32
        k = k_ref[0].astype(jnp.float32)          # [bs, g, dh]
        if quant:
            k = k * ks_ref[0][..., None]
        qg = q.reshape(g, rep, dh)
        s = jax.lax.dot_general(
            qg, k, (((2,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * scale
        s = s.reshape(nh, bs)
        col = j * bs + jax.lax.broadcasted_iota(jnp.int32, (nh, bs), 1)
        s = jnp.where(col < length, s, _NEG_INF)

        m_prev = m_s[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(m_new > _NEG_INF / 2, p, 0.0)
        alpha = jnp.where(m_new > _NEG_INF / 2, alpha, 0.0)
        l_s[:] = l_s[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0].astype(jnp.float32)          # [bs, g, dh]
        if quant:
            v = v * vs_ref[0][..., None]
        pg = p.reshape(g, rep, bs)
        ctx = jax.lax.dot_general(
            pg, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)   # [g, rep, dh]
        acc[:] = acc[:] * alpha + ctx.reshape(nh, dh)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)

    pl.when(j * bs < length)(_compute)

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        ctx = acc[:] / safe_l                     # [nh, dh] f32
        # replay the unfused path's dtype edges (ctx and W both pass
        # through the compute dtype at the historical matmul site)
        ctx = ctx.astype(o_ref.dtype).astype(jnp.float32)
        w = w_ref[:].astype(o_ref.dtype).astype(jnp.float32)
        # per-head [1, dh] @ [dh, h_out] batched over heads, summed —
        # the flat [1, nh*dh] GEMM without reshaping the accumulator
        out = jax.lax.dot_general(
            ctx, w, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)   # [nh, h_out]
        o_ref[0] = jnp.sum(out, axis=0).astype(o_ref.dtype)


def _fused_pallas(q, k_pool, v_pool, block_tables, lengths, w_proj,
                  rope_cos, rope_sin, scale, interpret,
                  k_scale=None, v_scale=None):
    from jax.experimental.pallas import tpu as pltpu

    b, nh, dh = q.shape
    nb, bs, g, _ = k_pool.shape
    mb = block_tables.shape[1]
    rep = nh // g
    h_out = w_proj.shape[1]
    quant = k_scale is not None
    has_rope = rope_cos is not None
    d2 = rope_cos.shape[-1] if has_rope else 0
    # clamp unmapped sentinels once host-side: the index map runs for
    # EVERY grid step (skipped blocks included) and its DMA source must
    # stay in range — the in-kernel ragged skip / tail mask keeps the
    # clamped garbage out of the math
    tbl = jnp.minimum(block_tables.astype(jnp.int32), nb - 1)
    lens = lengths.astype(jnp.int32)

    kv_spec = pl.BlockSpec(
        (1, bs, g, dh),
        lambda i, j, tbl_ref, len_ref: (tbl_ref[i, j], 0, 0, 0))
    sc_spec = pl.BlockSpec(
        (1, bs, g),
        lambda i, j, tbl_ref, len_ref: (tbl_ref[i, j], 0, 0))
    row_spec = pl.BlockSpec(
        (1, d2), lambda i, j, tbl_ref, len_ref: (i, 0))
    in_specs = [
        pl.BlockSpec((1, nh, dh),
                     lambda i, j, tbl_ref, len_ref: (i, 0, 0)),
    ]
    inputs = [q]
    if has_rope:
        in_specs.extend([row_spec, row_spec])
        inputs.extend([rope_cos.astype(jnp.float32),
                       rope_sin.astype(jnp.float32)])
    in_specs.append(kv_spec)
    inputs.append(k_pool)
    if quant:
        in_specs.append(sc_spec)
        inputs.append(k_scale)
    in_specs.append(kv_spec)
    inputs.append(v_pool)
    if quant:
        in_specs.append(sc_spec)
        inputs.append(v_scale)
    # the projection weight: one constant-index block — fetched once,
    # resident across the whole grid (the single-VMEM-residency claim)
    in_specs.append(pl.BlockSpec(
        (nh, dh, h_out), lambda i, j, tbl_ref, len_ref: (0, 0, 0)))
    inputs.append(w_proj.reshape(nh, dh, h_out))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, h_out), lambda i, j, tbl_ref, len_ref: (i, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, _LANES), jnp.float32),   # running max
            pltpu.VMEM((nh, _LANES), jnp.float32),   # running normalizer
            pltpu.VMEM((nh, dh), jnp.float32),       # output accumulator
            pltpu.VMEM((nh, dh), jnp.float32),       # roped query
        ],
    )
    return pl.pallas_call(
        functools.partial(_fused_kernel, scale, bs, g, rep, d2, quant,
                          has_rope),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h_out), q.dtype),
        interpret=interpret,
    )(tbl, lens, *inputs)


def fused_decode_layer(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_tables: jax.Array,
    lengths: jax.Array,
    w_proj: jax.Array,
    *,
    rope_cos: Optional[jax.Array] = None,
    rope_sin: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    backend: Optional[str] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
) -> jax.Array:
    """One decode token per sequence: rope the query in-kernel, attend
    over its paged KV blocks, and project the context — fused into one
    kernel launch with one VMEM residency (see module doc).

    ``q`` ``[b, num_heads, dh]`` PRE-rope; ``rope_cos``/``rope_sin``
    ``[b, d2]`` per-sequence angle-table rows (the caller gathers row
    ``pos[i]``, clamped — ``None`` skips rotation, the learned-position
    configs); pools / ``block_tables`` / ``lengths`` exactly as
    :func:`~apex_tpu.ops.paged_attention.ragged_paged_attention`
    (int8 pools pass ``k_scale``/``v_scale``); ``w_proj``
    ``[num_heads*dh, h_out]`` plain float → ``[b, h_out]`` in
    ``q.dtype`` (projection bias, residual and MLP stay with the
    caller — they are cheap elementwise/GEMM ops XLA already fuses).

    ``backend``: ``None`` routes via ``APEX_TPU_DECODE_FUSED``
    (auto → kernel on TPU or under ``APEX_TPU_PALLAS_INTERPRET=1``,
    reference otherwise); ``"kernel"`` / ``"reference"`` pin a path —
    the parity suite (tests/test_decode_fused.py) compares the two.

    Inference-only by design (no custom VJP), like the paged-attention
    kernel it extends.
    """
    _check_paged_shapes(q, k_pool, v_pool, block_tables, lengths,
                        k_scale, v_scale)
    _check_fused_shapes(q, w_proj, rope_cos, rope_sin)
    dh = q.shape[-1]
    scale = (1.0 / dh ** 0.5) if scale is None else float(scale)
    if route_decode_fused(backend) == "reference":
        return decode_layer_reference(
            q, k_pool, v_pool, block_tables, lengths, w_proj,
            rope_cos=rope_cos, rope_sin=rope_sin, scale=scale,
            k_scale=k_scale, v_scale=v_scale)
    return _fused_pallas(q, k_pool, v_pool, block_tables, lengths,
                         w_proj, rope_cos, rope_sin, scale,
                         interpret=not on_tpu(),
                         k_scale=k_scale, v_scale=v_scale)
