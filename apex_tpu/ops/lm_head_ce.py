"""Fused LM-head matmul + softmax cross-entropy, chunked over tokens.

The reference computes ``parallel_lm_logits`` then cross-entropy as two
stages (standalone_transformer_lm.py:1130, :1547), materializing the
full [tokens, vocab] logits.  At GPT-2 bench shape that tensor is
b16·s1024·v50304 fp32 = 3.2 GB — written by the head matmul, read by the
loss, read again by its backward.  On a v5e (819 GB/s) that round
tripping alone costs ~12 ms/step, and the buffer dominates peak memory.

This op fuses the two and *chunks over tokens*: the forward computes
each chunk's logits on the fly, reduces them to the per-token
``(lse, picked, mean)`` scalars the loss needs, and throws the chunk
away; the backward recomputes each chunk's logits from the saved lse
(one extra chunk matmul) and immediately contracts them into ``dhidden``
and the ``dhead`` accumulator.  Peak extra memory is O(chunk · vocab)
instead of O(tokens · vocab); the full logits never touch HBM.

Same per-row semantics as :mod:`apex_tpu.ops.xentropy`
(xentropy_kernel.cu:431-452), with the head matmul folded in.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["lm_head_cross_entropy"]


def _chunks(n: int, chunk: int) -> int:
    return (n + chunk - 1) // chunk


def _pad_rows(x, n_pad):
    if n_pad == 0:
        return x
    return jnp.concatenate(
        [x, jnp.zeros((n_pad,) + x.shape[1:], x.dtype)], axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_ce(hidden, head, labels, smoothing, chunk):
    losses, _ = _fwd_math(hidden, head, labels, smoothing, chunk)
    return losses


def _fwd_math(hidden, head, labels, smoothing, chunk):
    """Per-token losses [N] plus the lse residual [N]."""
    n, h = hidden.shape
    v = head.shape[0]
    nc = _chunks(n, chunk)
    n_pad = nc * chunk - n
    hid = _pad_rows(hidden, n_pad).reshape(nc, chunk, h)
    lab = _pad_rows(labels.astype(jnp.int32), n_pad).reshape(nc, chunk)

    def one(carry, inp):
        hc, lc = inp
        logits = jnp.einsum(
            "ch,vh->cv", hc, head.astype(hc.dtype),
            preferred_element_type=jnp.float32)
        m = jnp.max(logits, axis=-1)
        lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
        picked = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
        loss = (lse - picked) * (1.0 - smoothing)
        if smoothing:
            loss = loss + (lse - jnp.mean(logits, axis=-1)) * smoothing
        return carry, (loss, lse)

    _, (losses, lses) = jax.lax.scan(one, (), (hid, lab))
    return losses.reshape(-1)[:n], lses.reshape(-1)[:n]


def _fused_ce_fwd(hidden, head, labels, smoothing, chunk):
    losses, lses = _fwd_math(hidden, head, labels, smoothing, chunk)
    return losses, (hidden, head, labels, lses)


def _fused_ce_bwd(smoothing, chunk, res, g):
    hidden, head, labels, lses = res
    n, h = hidden.shape
    v = head.shape[0]
    nc = _chunks(n, chunk)
    n_pad = nc * chunk - n
    hid = _pad_rows(hidden, n_pad).reshape(nc, chunk, h)
    lab = _pad_rows(labels.astype(jnp.int32), n_pad).reshape(nc, chunk)
    lse = _pad_rows(lses, n_pad).reshape(nc, chunk)
    # padded rows must contribute nothing to dhead
    gv = _pad_rows(g.astype(jnp.float32), n_pad).reshape(nc, chunk)

    head_f = head.astype(hidden.dtype)

    def one(dhead_acc, inp):
        hc, lc, lsec, gc = inp
        logits = jnp.einsum(
            "ch,vh->cv", hc, head_f,
            preferred_element_type=jnp.float32)
        probs = jnp.exp(logits - lsec[:, None])
        onehot = jax.nn.one_hot(lc, v, dtype=jnp.float32)
        dlogits = probs - smoothing / v - (1.0 - smoothing) * onehot
        dlogits = (dlogits * gc[:, None]).astype(hc.dtype)
        dh = jnp.einsum("cv,vh->ch", dlogits, head_f,
                        preferred_element_type=jnp.float32)
        dhead_acc = dhead_acc + jnp.einsum(
            "cv,ch->vh", dlogits, hc, preferred_element_type=jnp.float32)
        return dhead_acc, dh

    dhead, dhs = jax.lax.scan(
        one, jnp.zeros((v, h), jnp.float32), (hid, lab, lse, gv))
    dhidden = dhs.reshape(nc * chunk, h)[:n].astype(hidden.dtype)
    return dhidden, dhead.astype(head.dtype), None


_fused_ce.defvjp(_fused_ce_fwd, _fused_ce_bwd)


def lm_head_cross_entropy(
    hidden: jax.Array,
    head: jax.Array,
    labels: jax.Array,
    *,
    smoothing: float = 0.0,
    chunk: int = 2048,
    ignore_index: Optional[int] = None,
) -> jax.Array:
    """Per-token CE of ``softmax(hidden @ head.T)`` without materializing
    the [tokens, vocab] logits (see module docstring).

    ``hidden`` [N, h] (or [..., h] — leading dims flattened), ``head``
    [v, h], ``labels`` int [N].  Rows whose label equals ``ignore_index``
    get loss 0 (and zero gradients), matching the fused xentropy op's
    ``padding_idx`` semantics.
    """
    lead = hidden.shape[:-1]
    hidden2 = hidden.reshape(-1, hidden.shape[-1])
    labels2 = labels.reshape(-1)
    if ignore_index is not None:
        valid = labels2 != ignore_index
        labels2 = jnp.where(valid, labels2, 0)
    losses = _fused_ce(hidden2, head, labels2, float(smoothing),
                       int(chunk))
    if ignore_index is not None:
        losses = jnp.where(valid, losses, 0.0)
    return losses.reshape(lead)
