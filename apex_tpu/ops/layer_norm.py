"""Fused LayerNorm / RMSNorm with custom VJP and Pallas TPU kernels.

Reference: csrc/layer_norm_cuda.cpp + layer_norm_cuda_kernel.cu (Welford
row reduction, 10 entry points: LN/RMS × affine/plain × fwd/bwd, mixed-dtype
"Megatron" variants, memory-efficient mode that saves the *output* instead of
the input and reconstructs x in backward), wrapped by
apex/normalization/fused_layer_norm.py.

TPU design: a row-parallel Pallas kernel — each grid step normalizes a
(block × hidden) tile held in VMEM; mean/rstd are saved as residuals. The
backward kernel recomputes x̂ and accumulates dγ/dβ across row blocks in a
revisited output tile (the TPU analog of the reference's two-pass part-grad
reduction). Falls back to a pure-XLA composition when the hidden size isn't
lane-aligned or we're off TPU (XLA fuses that composition well; the Pallas
path wins by keeping the row statistics in VMEM and fusing the affine
epilogue).

Norm semantics match torch.nn.functional.layer_norm /
the reference's RMSNorm (no mean subtraction, rsqrt(E[x²]+eps)).
Mixed-dtype: stats and affine math always run in fp32; output dtype equals
input dtype, params may be fp32 while inputs are bf16 (the Megatron
``MixedFused*`` contract, fused_layer_norm.py:553+).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._pallas_utils import out_struct
from apex_tpu.utils.registry import on_tpu

__all__ = [
    "fused_layer_norm",
    "fused_rms_norm",
    "layer_norm_ref",
    "rms_norm_ref",
]

_LANES = 128


def _rows_block(hidden: int, n_bufs: int) -> int:
    """Pick a row-block size that keeps ~n_bufs (block, hidden) fp32 tiles
    within a few MB of VMEM."""
    budget = 6 * 1024 * 1024 // n_bufs
    rows = max(8, budget // (hidden * 4))
    rows = 1 << (rows.bit_length() - 1)  # floor to pow2
    return min(512, rows)


# ----------------------------------------------------------------------------
# Pure-XLA reference implementations (always available; fp32 math).
# ----------------------------------------------------------------------------


def layer_norm_ref(x, weight=None, bias=None, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm_ref(x, weight=None, eps=1e-5):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(ms + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(x.dtype)


# ----------------------------------------------------------------------------
# Pallas kernels. x is viewed as (rows, hidden).
# ----------------------------------------------------------------------------


def _ln_fwd_kernel(rms: bool, affine: bool, has_bias: bool, eps: float,
                   *refs):
    if affine:
        if has_bias:
            x_ref, w_ref, b_ref, y_ref, mu_ref, rs_ref = refs
        else:
            x_ref, w_ref, y_ref, mu_ref, rs_ref = refs
    else:
        x_ref, y_ref, mu_ref, rs_ref = refs
    x = x_ref[:].astype(jnp.float32)
    if rms:
        mu = jnp.zeros((x.shape[0], 1), jnp.float32)
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    else:
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    rs = jax.lax.rsqrt(var + eps)
    xhat = (x - mu) * rs
    y = xhat
    if affine:
        y = y * w_ref[:].astype(jnp.float32)
        if has_bias:
            y = y + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mu_ref[:] = mu
    rs_ref[:] = rs


def _ln_bwd_kernel(rms: bool, affine: bool, has_bias: bool, *refs):
    """dx plus dγ/dβ accumulated into one revisited (1, hidden) tile.

    A round-4 "split partials" variant wrote per-block dγ/dβ rows for a
    trailing XLA sum instead; it was deleted in round 5 — Mosaic rejects
    its (1, hidden) output block over a (n_blocks, hidden) array (last
    two block dims must be (8k, 128k) or equal the array's), and the
    revisit kernel it was meant to replace *wins* on silicon anyway
    (fwd+bwd 16384x768 bf16: 108.8us vs the XLA chain's 150.1us, round-5
    sweep)."""
    if affine:
        if has_bias:
            (dy_ref, x_ref, w_ref, mu_ref, rs_ref,
             dx_ref, dw_ref, db_ref) = refs
        else:
            dy_ref, x_ref, w_ref, mu_ref, rs_ref, dx_ref, dw_ref = refs
    else:
        dy_ref, x_ref, mu_ref, rs_ref, dx_ref = refs

    dy = dy_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    mu = mu_ref[:]
    rs = rs_ref[:]
    xhat = (x - mu) * rs
    if affine:
        wdy = dy * w_ref[:].astype(jnp.float32)
    else:
        wdy = dy
    h = x.shape[-1]
    c1 = jnp.sum(wdy, axis=-1, keepdims=True) / h
    c2 = jnp.sum(wdy * xhat, axis=-1, keepdims=True) / h
    if rms:
        dx = (wdy - xhat * c2) * rs
    else:
        dx = (wdy - c1 - xhat * c2) * rs
    dx_ref[:] = dx.astype(dx_ref.dtype)

    if affine:
        first = pl.program_id(0) == 0

        @pl.when(first)
        def _init():
            dw_ref[:] = jnp.zeros_like(dw_ref)
            if has_bias:
                db_ref[:] = jnp.zeros_like(db_ref)

        dw_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
        if has_bias:
            db_ref[:] += jnp.sum(dy, axis=0, keepdims=True)


def _pallas_ok(hidden: int, dtype) -> bool:
    import os

    from apex_tpu.ops._pallas_utils import pallas_ok

    if not pallas_ok("fused_layer_norm", hidden, dtype):
        return False
    # Measured on v5e (bench_kernels.py round 3): the Pallas forward wins
    # for 16-bit inputs (bf16 16384x768: 36us vs 78us) but loses at fp32
    # (74us vs 49us — fp32 doubles the VMEM tile traffic while XLA fuses
    # the fp32 chain).  Interpret mode keeps every dtype for test
    # coverage.
    if os.environ.get("APEX_TPU_PALLAS_INTERPRET", "0") == "1":
        return True
    return dtype in (jnp.bfloat16, jnp.float16)


def _pad_rows(x2, br):
    from apex_tpu.ops._pallas_utils import pad_rows

    return pad_rows(x2, br)


def _ln_fwd_pallas(x2, weight, bias, eps, rms):
    from jax.experimental.pallas import tpu as pltpu

    hidden = x2.shape[1]
    affine = weight is not None
    has_bias = bias is not None
    n_bufs = 3 + (1 if affine else 0) + (1 if has_bias else 0)
    br = _rows_block(hidden, n_bufs)
    x2, rows = _pad_rows(x2, br)
    prows = x2.shape[0]
    grid = (prows // br,)
    row_tile = pl.BlockSpec((br, hidden), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    stat_tile = pl.BlockSpec((br, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    param_tile = pl.BlockSpec((1, hidden), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    in_specs = [row_tile]
    args = [x2]
    if affine:
        in_specs.append(param_tile)
        args.append(weight.reshape(1, hidden))
        if has_bias:
            in_specs.append(param_tile)
            args.append(bias.reshape(1, hidden))
    y, mu, rs = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, rms, affine, has_bias, eps),
        grid=grid,
        in_specs=in_specs,
        out_specs=(row_tile, stat_tile, stat_tile),
        out_shape=(
            out_struct((prows, hidden), x2.dtype, x2),
            out_struct((prows, 1), jnp.float32, x2),
            out_struct((prows, 1), jnp.float32, x2),
        ),
        interpret=not on_tpu(),
    )(*args)
    return y[:rows], mu[:rows], rs[:rows]


def _ln_bwd_pallas(dy2, x2, weight, mu, rs, rms, has_bias):
    from jax.experimental.pallas import tpu as pltpu

    hidden = x2.shape[1]
    affine = weight is not None
    n_bufs = 5 + (3 if affine else 0)
    br = _rows_block(hidden, n_bufs)
    dy2, rows = _pad_rows(dy2, br)
    x2, _ = _pad_rows(x2, br)
    mu, _ = _pad_rows(mu, br)
    # rs is zero-padded like everything else; padded rows are safe because
    # dy there is zero too (dx = 0·rs = 0, dγ/dβ partial sums get zeros)
    # and the per-row outputs are sliced off below.
    rs, _ = _pad_rows(rs, br)
    prows = x2.shape[0]
    grid = (prows // br,)
    row_tile = pl.BlockSpec((br, hidden), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    stat_tile = pl.BlockSpec((br, 1), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    param_tile = pl.BlockSpec((1, hidden), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
    acc_tile = pl.BlockSpec((1, hidden), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)

    in_specs = [row_tile, row_tile]
    args = [dy2, x2]
    if affine:
        in_specs.append(param_tile)
        args.append(weight.reshape(1, hidden))
    in_specs += [stat_tile, stat_tile]
    args += [mu, rs]

    acc_rows = 1
    out_specs = [row_tile]
    out_shape = [out_struct((prows, hidden), x2.dtype, x2)]
    if affine:
        out_specs.append(acc_tile)
        out_shape.append(out_struct((acc_rows, hidden), jnp.float32, x2))
        if has_bias:
            out_specs.append(acc_tile)
            out_shape.append(
                out_struct((acc_rows, hidden), jnp.float32, x2))

    outs = pl.pallas_call(
        functools.partial(_ln_bwd_kernel, rms, affine, has_bias),
        grid=grid,
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        interpret=not on_tpu(),
    )(*args)
    if not affine:
        dx = outs[0] if isinstance(outs, (tuple, list)) else outs
        return dx[:rows], None, None

    def red(t):
        return t.reshape(-1)

    if has_bias:
        dx, dw, db = outs
        return dx[:rows], red(dw), red(db)
    dx, dw = outs
    return dx[:rows], red(dw), None


# ----------------------------------------------------------------------------
# custom_vjp wrappers
# ----------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _norm(x, weight, bias, eps, rms, memory_efficient):
    rows = 1
    for d in x.shape[:-1]:
        rows *= d
    hidden = x.shape[-1]
    if _pallas_ok(hidden, x.dtype):
        y, _, _ = _ln_fwd_pallas(
            x.reshape(rows, hidden), weight, bias, eps, rms
        )
        return y.reshape(x.shape)
    if rms:
        return rms_norm_ref(x, weight, eps)
    return layer_norm_ref(x, weight, bias, eps)


def _norm_fwd(x, weight, bias, eps, rms, memory_efficient):
    shape = x.shape
    hidden = shape[-1]
    rows = x.size // hidden
    x2 = x.reshape(rows, hidden)
    if _pallas_ok(hidden, x.dtype):
        y2, mu, rs = _ln_fwd_pallas(x2, weight, bias, eps, rms)
    else:
        x32 = x2.astype(jnp.float32)
        if rms:
            mu = jnp.zeros((rows, 1), jnp.float32)
            var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        else:
            mu = jnp.mean(x32, axis=-1, keepdims=True)
            var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
        rs = jax.lax.rsqrt(var + eps)
        y32 = (x32 - mu) * rs
        if weight is not None:
            y32 = y32 * weight.astype(jnp.float32)
            if bias is not None:
                y32 = y32 + bias.astype(jnp.float32)
        y2 = y32.astype(x.dtype)
    # memory_efficient mode (reference layer_norm_cuda.cpp "mem eff" entry
    # points): save y instead of x; x is reconstructed in backward.
    saved_x = None if memory_efficient else x2
    saved_y = y2 if memory_efficient else None
    return y2.reshape(shape), (saved_x, saved_y, weight, bias, mu, rs, shape)


def _ln_bwd_mode(hidden, dtype) -> Optional[str]:
    """Backward backend gate. Measured on v5e, round-5 sweep (first chip
    contact after the round-3/4 outage): the Pallas revisit kernel WINS
    the full fwd+bwd chain — 16384x768 bf16: 108.8us vs 150.1us for the
    pallas-fwd/XLA-bwd mix (ratio 0.725) — reversing the round-3 reading
    (143us vs 93us) that had demoted it.  The kernel is unchanged since
    round 3, so the flip is environmental (the tunnel/toolchain behind
    the chip was rebuilt during the two-round outage); sweep_r4
    re-measures both sides every campaign, so a flip back would be
    caught.  Default is therefore
    the Pallas backward wherever the Pallas forward is eligible;
    ``APEX_TPU_LN_BWD=xla`` opts back into the XLA composition (and is
    what sweep_r4 measures against)."""
    import os

    mode = os.environ.get("APEX_TPU_LN_BWD")
    if mode == "xla":
        return None
    if mode not in (None, "", "pallas"):
        raise ValueError(
            f"APEX_TPU_LN_BWD={mode!r}: expected pallas|xla (the round-4 "
            "pallas_split variant was deleted in round 5 — Mosaic rejects "
            "its partials block spec and the revisit kernel wins on chip)")
    if _pallas_ok(hidden, dtype):
        return "pallas"
    return None


def _norm_bwd(eps, rms, memory_efficient, res, dy):
    saved_x, saved_y, weight, bias, mu, rs, shape = res
    hidden = shape[-1]
    rows = dy.size // hidden
    dy2 = dy.reshape(rows, hidden)
    if memory_efficient:
        # Reconstruct x̂ (and x) from y: y = x̂*w + b  ⇒  x̂ = (y - b)/w.
        y32 = saved_y.astype(jnp.float32)
        if weight is not None:
            w32 = weight.astype(jnp.float32)
            # guard zero gammas exactly like the reference's
            # clamp_by_magnitude (layer_norm_cuda_kernel.cu:540)
            w32 = jnp.sign(w32) * jnp.maximum(jnp.abs(w32), eps) + jnp.where(
                w32 == 0.0, eps, 0.0
            )
            if bias is not None:
                y32 = y32 - bias.astype(jnp.float32)
            xhat = y32 / w32
        else:
            xhat = y32
        x2 = (xhat / rs + mu).astype(dy.dtype)
    else:
        x2 = saved_x

    bwd_mode = _ln_bwd_mode(hidden, x2.dtype)
    if bwd_mode is not None:
        dx, dw, db = _ln_bwd_pallas(
            dy2, x2, weight, mu, rs, rms, bias is not None
        )
    else:
        dy32 = dy2.astype(jnp.float32)
        x32 = x2.astype(jnp.float32)
        xhat = (x32 - mu) * rs
        wdy = dy32 if weight is None else dy32 * weight.astype(jnp.float32)
        c1 = jnp.mean(wdy, axis=-1, keepdims=True)
        c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
        if rms:
            dx = (wdy - xhat * c2) * rs
        else:
            dx = (wdy - c1 - xhat * c2) * rs
        dx = dx.astype(dy.dtype)
        dw = jnp.sum(dy32 * xhat, axis=0) if weight is not None else None
        db = jnp.sum(dy32, axis=0) if bias is not None else None

    dxr = dx.reshape(shape)
    dwr = None if weight is None else dw.astype(weight.dtype)
    dbr = None if bias is None else db.astype(bias.dtype)
    return (dxr, dwr, dbr)


_norm.defvjp(_norm_fwd, _norm_bwd)


def fused_layer_norm(
    x: jax.Array,
    weight: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    eps: float = 1e-5,
    memory_efficient: bool = False,
) -> jax.Array:
    """LayerNorm over the last dimension (affine when weight/bias given).

    Equivalent surface to ``fused_layer_norm_cuda``'s forward entry points
    (csrc/layer_norm_cuda.cpp:446-458) + autograd
    (apex/normalization/fused_layer_norm.py:38+).
    """
    return _norm(x, weight, bias, eps, False, memory_efficient)


def fused_rms_norm(
    x: jax.Array,
    weight: Optional[jax.Array] = None,
    eps: float = 1e-5,
    memory_efficient: bool = False,
) -> jax.Array:
    """RMSNorm over the last dimension (reference ``FusedRMSNorm``,
    fused_layer_norm.py:347+)."""
    return _norm(x, weight, None, eps, True, memory_efficient)
