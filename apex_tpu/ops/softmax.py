"""Scaled (masked / causal / generic) softmax family.

Reference: csrc/megatron/scaled_masked_softmax.h warp-softmax templates bound
as four modules — ``scaled_softmax_cuda``, ``scaled_masked_softmax_cuda``,
``scaled_upper_triang_masked_softmax_cuda``,
``generic_scaled_masked_softmax_cuda`` (SURVEY.md §2.2) — wrapped by
``FusedScaleMaskSoftmax`` (apex/transformer/functional/fused_softmax.py).

Semantics preserved:
- input is multiplied by ``scale`` *before* the mask/softmax,
- ``mask`` is boolean with True = masked-out (filled with -10000.0 like the
  reference kernels), broadcastable against the input,
- the causal variant requires square (sq == sk) inputs
  (fused_softmax.py:214 assert),
- backward is ``(dy - Σ dy·y) · y · scale`` through a custom VJP (the
  reference saves softmax_results for backward; so do we).

On TPU the forward runs as a Pallas row kernel that fuses scale + mask +
stable softmax in one VMEM pass — the causal mask is generated from iota
inside the kernel, never materialized in HBM. Off-TPU (or lane-misaligned)
the pure-XLA composition is used; softmax math is fp32 throughout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._pallas_utils import (
    out_struct,
    pad_rows,
    pallas_ok,
)
from apex_tpu.utils.registry import on_tpu

__all__ = [
    "scaled_softmax",
    "scaled_masked_softmax",
    "scaled_upper_triang_masked_softmax",
    "generic_scaled_masked_softmax",
]

_MASK_FILL = -10000.0


# --------------------------------------------------------------------------
# XLA reference paths (fp32 math).
# --------------------------------------------------------------------------


def _softmax_fwd_ref(x, scale, mask=None, causal=False):
    x32 = x.astype(jnp.float32) * scale
    if mask is not None:
        x32 = jnp.where(mask, _MASK_FILL, x32)
    if causal:
        sq, sk = x.shape[-2], x.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        x32 = jnp.where(col > row, _MASK_FILL, x32)
    y = jax.nn.softmax(x32, axis=-1)
    # Fully-masked rows emit zeros, matching the reference kernels'
    # scale_value=0 when a row's max is the mask fill
    # (scaled_masked_softmax.h:304, generic_scaled_masked_softmax.h:288).
    if mask is not None or causal:
        all_masked = jnp.max(x32, axis=-1, keepdims=True) <= _MASK_FILL
        y = jnp.where(all_masked, 0.0, y)
    return y.astype(x.dtype)


# --------------------------------------------------------------------------
# Pallas forward kernels: x viewed as (rows, sk).
# --------------------------------------------------------------------------


def _softmax_kernel(scale, causal, sq, has_mask, *refs):
    if has_mask:
        x_ref, m_ref, y_ref = refs
    else:
        x_ref, y_ref = refs
    x = x_ref[:].astype(jnp.float32) * scale
    if has_mask:
        x = jnp.where(m_ref[:] != 0, _MASK_FILL, x)
    if causal:
        br, sk = x.shape
        base = pl.program_id(0) * br
        row_in_block = jax.lax.broadcasted_iota(jnp.int32, (br, sk), 0)
        q_pos = (base + row_in_block) % sq
        col = jax.lax.broadcasted_iota(jnp.int32, (br, sk), 1)
        x = jnp.where(col > q_pos, _MASK_FILL, x)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    y = e / jnp.sum(e, axis=-1, keepdims=True)
    if has_mask or causal:
        # fully-masked rows → zeros (reference scale_value=0 semantics)
        y = jnp.where(m <= _MASK_FILL, 0.0, y)
    y_ref[:] = y.astype(y_ref.dtype)


def _pallas_ok(sk: int, dtype) -> bool:
    return pallas_ok("fused_softmax", sk, dtype)


def _softmax_fwd_pallas(x, scale, mask, causal):
    from jax.experimental.pallas import tpu as pltpu

    shape = x.shape
    sk = shape[-1]
    sq = shape[-2]
    rows = x.size // sk
    # The causal q-position of a row is (global_row % sq) regardless of the
    # block size, so any row blocking works.
    br = max(8, min(512, (4 * 1024 * 1024 // 3) // (sk * 4)) // 8 * 8)
    x2, _ = pad_rows(x.reshape(rows, sk), br)
    padded_rows = x2.shape[0]
    grid = (padded_rows // br,)
    row_tile = pl.BlockSpec((br, sk), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [row_tile]
    args = [x2]
    if mask is not None:
        # dispatcher guarantees mask.shape == x.shape here (broadcast masks
        # take the XLA path, which reads them with broadcast strides)
        m2, _ = pad_rows(mask.reshape(rows, sk).astype(jnp.int32), br)
        in_specs.append(row_tile)
        args.append(m2)
    y = pl.pallas_call(
        functools.partial(
            _softmax_kernel, scale, causal, sq, mask is not None
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=row_tile,
        out_shape=out_struct((padded_rows, sk), x.dtype, x2),
        interpret=not on_tpu(),
    )(*args)
    return y[:rows].reshape(shape)


# --------------------------------------------------------------------------
# custom_vjp
# --------------------------------------------------------------------------


def _use_pallas(x, mask, causal):
    # Broadcast masks (e.g. (B,1,sq,sk) vs (B,H,sq,sk)) would have to be
    # materialized at full size in HBM for the kernel; XLA reads them with
    # broadcast strides instead, so route those to the reference path.
    if mask is not None and mask.shape != x.shape:
        return False
    # Measured crossover on v5e (bench_kernels.py, round 3): the Pallas
    # row kernel wins at sk<=512 (causal fwd 32x16x512x512: 0.65x) but
    # loses to the XLA composition at sk=1024 (1.19x fwd) — the larger
    # rows blow past the VMEM-friendly tile and XLA's fusion with the
    # surrounding matmuls dominates.  APEX_TPU_SOFTMAX=pallas forces the
    # kernel at any size.
    import os

    if (x.shape[-1] > 512
            and os.environ.get("APEX_TPU_SOFTMAX") != "pallas"):
        return False
    return _pallas_ok(x.shape[-1], x.dtype) and (
        not causal or x.shape[-2] == x.shape[-1]
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _scaled_softmax(x, mask, scale, causal):
    if _use_pallas(x, mask, causal):
        return _softmax_fwd_pallas(x, scale, mask, causal)
    return _softmax_fwd_ref(x, scale, mask, causal)


def _scaled_softmax_fwd(x, mask, scale, causal):
    # Under differentiation the XLA composition wins outright: the bwd is
    # pure elementwise+reduce that XLA fuses across the fwd/bwd boundary,
    # and an opaque Pallas fwd call in the middle forces the y tensor
    # through HBM twice (measured 1.96x the XLA chain at 512^2 causal —
    # BASELINE.md round-3 ledger; VERDICT r3 #4).  The Pallas row kernel
    # stays the primal (fwd-only) path, where it measures 0.65x.
    # APEX_TPU_SOFTMAX=pallas forces the kernel here too.
    import os

    if (os.environ.get("APEX_TPU_SOFTMAX") == "pallas"
            and _use_pallas(x, mask, causal)):
        y = _softmax_fwd_pallas(x, scale, mask, causal)
    else:
        y = _softmax_fwd_ref(x, scale, mask, causal)
    return y, y


def _scaled_softmax_bwd(scale, causal, y, dy):
    y32 = y.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    inner = dy32 - jnp.sum(dy32 * y32, axis=-1, keepdims=True)
    dx = (inner * y32 * scale).astype(dy.dtype)
    return (dx, None)


_scaled_softmax.defvjp(_scaled_softmax_fwd, _scaled_softmax_bwd)


def scaled_softmax(x: jax.Array, scale: float = 1.0) -> jax.Array:
    """softmax(x*scale) — reference ``scaled_softmax_cuda`` (seq-len ≤16k
    warp kernel; here any length)."""
    return _scaled_softmax(x, None, float(scale), False)


def scaled_masked_softmax(
    x: jax.Array, mask: Optional[jax.Array], scale: float = 1.0
) -> jax.Array:
    """softmax(mask_fill(x*scale)) — reference ``scaled_masked_softmax_cuda``.

    ``mask`` boolean, True = masked (filled with -10000), broadcastable
    (typically (B, 1, sq, sk) against (B, H, sq, sk))."""
    if mask is None:
        return scaled_softmax(x, scale)
    return _scaled_softmax(x, mask, float(scale), False)


def scaled_upper_triang_masked_softmax(
    x: jax.Array, scale: float = 1.0
) -> jax.Array:
    """Causal softmax — reference
    ``scaled_upper_triang_masked_softmax_cuda`` (requires sq == sk)."""
    if x.shape[-1] != x.shape[-2]:
        raise ValueError(
            "scaled_upper_triang_masked_softmax requires square inputs "
            f"(got {x.shape[-2]}x{x.shape[-1]}); use scaled_masked_softmax "
            "with an explicit mask for rectangular attention."
        )
    return _scaled_softmax(x, None, float(scale), True)


def generic_scaled_masked_softmax(
    x: jax.Array, mask: Optional[jax.Array], scale: float = 1.0
) -> jax.Array:
    """Arbitrary-broadcast masked softmax — reference
    ``generic_scaled_masked_softmax_cuda`` (no pow-2/seq-len limits)."""
    return scaled_masked_softmax(x, mask, scale)
