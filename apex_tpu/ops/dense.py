"""Fused dense (GEMM + bias [+ GELU + GEMM]) building blocks.

Reference: csrc/fused_dense_cuda.cu drives cublasLt epilogue fusion
(GEMM+bias, GEMM+bias+GELU with saved pre-GELU, and the bgradb/dgelu
backward epilogues), wrapped by apex/fused_dense/fused_dense.py
(``FusedDense`` :8, ``FusedDenseGeluDense`` :102) and apex/mlp (whole MLP in
two native calls, mlp.py:11,33).

On TPU, XLA performs exactly these epilogue fusions automatically: a
``dot_general`` followed by bias-add/GELU lowers to one MXU op with a fused
epilogue, and the wgrad/dgrad GEMMs fuse their epilogues in backward. So the
functions below are thin, *correct-by-construction* compositions — they
exist to give reference users the same call surface, keep the math in
``preferred_element_type=float32`` (the MXU accumulates fp32), and anchor
the numerics tests. The custom kernel layer the reference needs does not
earn its keep here; profiling on v5e shows XLA emits single fused kernels
for these shapes (coverage: tests/test_rope_swiglu_xentropy.py:228).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["fused_dense_function", "fused_dense_gelu_dense_function"]


def _matmul(x, w):
    return jax.lax.dot_general(
        x, w,
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def fused_dense_function(
    x: jax.Array, weight: jax.Array, bias: Optional[jax.Array] = None
) -> jax.Array:
    """y = x @ W + b with fp32 accumulation; W is [in, out].

    (reference fused_dense_function, apex/fused_dense/fused_dense.py:64 —
    note the reference stores torch-convention [out, in]; pass W.T
    equivalents when porting weights.)
    """
    y = _matmul(x, weight)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y.astype(x.dtype)


def fused_dense_gelu_dense_function(
    x: jax.Array,
    weight1: jax.Array,
    bias1: Optional[jax.Array],
    weight2: jax.Array,
    bias2: Optional[jax.Array] = None,
) -> jax.Array:
    """y = GELU(x @ W1 + b1) @ W2 + b2 (reference fused_dense.py:102;
    cublasLt GELU_AUX epilogue ≙ XLA fusing the gelu into the first GEMM)."""
    h = fused_dense_function(x, weight1, bias1)
    h = jax.nn.gelu(h.astype(jnp.float32), approximate=False)
    return fused_dense_function(h.astype(x.dtype), weight2, bias2)
