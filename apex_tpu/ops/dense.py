"""Fused dense (GEMM + bias [+ GELU + GEMM]) building blocks — plus the
weight-only int8 quantized matmul path (ISSUE 14).

Reference: csrc/fused_dense_cuda.cu drives cublasLt epilogue fusion
(GEMM+bias, GEMM+bias+GELU with saved pre-GELU, and the bgradb/dgelu
backward epilogues), wrapped by apex/fused_dense/fused_dense.py
(``FusedDense`` :8, ``FusedDenseGeluDense`` :102) and apex/mlp (whole MLP in
two native calls, mlp.py:11,33).

On TPU, XLA performs exactly these epilogue fusions automatically: a
``dot_general`` followed by bias-add/GELU lowers to one MXU op with a fused
epilogue, and the wgrad/dgrad GEMMs fuse their epilogues in backward. So the
functions below are thin, *correct-by-construction* compositions — they
exist to give reference users the same call surface, keep the math in
``preferred_element_type=float32`` (the MXU accumulates fp32), and anchor
the numerics tests. The custom kernel layer the reference needs does not
earn its keep here; profiling on v5e shows XLA emits single fused kernels
for these shapes (coverage: tests/test_rope_swiglu_xentropy.py:228).

**Weight-only quantization** (the serving half of ISSUE 14): decode is
HBM-bandwidth-bound — every generated token re-reads the whole weight
set, so the bytes the weights occupy set tokens/s, not the FLOPs.
:func:`quantize_weight` converts a ``[in, *out]`` kernel to symmetric
int8 with one fp32 scale per ``(in-block, output column)`` (block-scaled
along the contraction axis — the EQuARX neighborhood-scaling design of
``comm/quantize``, applied to weights at rest), and
:func:`dense_quantized` runs ``x @ W`` off the int8 slab: a Pallas
kernel whose k-grid IS the quantization blocking, so each inner-loop
step dequantizes its ``[kb, out]`` tile in VMEM (one multiply by the
tile's scale row after the int8 dot) — the fp32 weights never exist in
HBM and the per-token weight read drops to ~1/4 (fp32) or ~1/2 (bf16)
of the raw bytes.  The XLA reference path dequantizes whole slabs (the
parity oracle); ``APEX_TPU_QUANT_MATMUL=kernel|reference|auto`` routes
like every other op here.  ``custom_vjp`` keeps the backward in high
precision: ``dx`` is computed against the fp32-dequantized weights, the
frozen wire/scales get zero cotangents (weight-only quantization is a
serving conversion — nothing trains through it).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = ["QUANT_BLOCK", "dense_quantized", "dequantize_weight",
           "fused_dense_function", "fused_dense_gelu_dense_function",
           "is_quantized", "pick_quant_block", "quantize_weight",
           "quantized_matmul", "route_quant_backend"]


def _matmul(x, w):
    return jax.lax.dot_general(
        x, w,
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def fused_dense_function(
    x: jax.Array, weight: jax.Array, bias: Optional[jax.Array] = None
) -> jax.Array:
    """y = x @ W + b with fp32 accumulation; W is [in, out].

    (reference fused_dense_function, apex/fused_dense/fused_dense.py:64 —
    note the reference stores torch-convention [out, in]; pass W.T
    equivalents when porting weights.)
    """
    y = _matmul(x, weight)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y.astype(x.dtype)


def fused_dense_gelu_dense_function(
    x: jax.Array,
    weight1: jax.Array,
    bias1: Optional[jax.Array],
    weight2: jax.Array,
    bias2: Optional[jax.Array] = None,
) -> jax.Array:
    """y = GELU(x @ W1 + b1) @ W2 + b2 (reference fused_dense.py:102;
    cublasLt GELU_AUX epilogue ≙ XLA fusing the gelu into the first GEMM)."""
    h = fused_dense_function(x, weight1, bias1)
    h = jax.nn.gelu(h.astype(jnp.float32), approximate=False)
    return fused_dense_function(h.astype(x.dtype), weight2, bias2)


# ---------------------------------------------------------------------------
# Weight-only int8 quantization (ISSUE 14)
# ---------------------------------------------------------------------------

QUANT_BLOCK = 128        # contraction-axis quantization block (= the
_INT8_MAX = 127.0        # kernel's k tile, so dequant IS the inner loop)


def pick_quant_block(in_dim: int, block: Optional[int] = None) -> int:
    """Largest divisor of ``in_dim`` that is ``<= block`` — the
    quantization block must tile the contraction axis exactly (the
    kernel's k grid walks whole blocks; zero-padding weights would
    change the matmul's reduction shape)."""
    block = QUANT_BLOCK if block is None else int(block)
    if block < 1:
        raise ValueError(f"block={block} must be positive")
    want = min(block, in_dim)
    for b in range(want, 0, -1):
        if in_dim % b == 0:
            return b
    return 1


def is_quantized(leaf) -> bool:
    """True for a quantized-weight leaf (the dict form
    :func:`quantize_weight` emits; model code branches on this at every
    matmul site — ``models/quantized.quantize_params`` produces trees
    whose kernels are these dicts)."""
    return isinstance(leaf, dict) and "wire" in leaf and "scale" in leaf


def quantize_weight(w, block: Optional[int] = None) -> dict:
    """Symmetric round-to-nearest int8 along the CONTRACTION axis
    (axis 0): ``w`` ``[in, *out]`` float → ``{"wire": int8 [in, *out],
    "scale": fp32 [in/kb, *out]}`` with one scale per (k-block, output
    column) — ``kb = pick_quant_block(in, block)``.  All-zero columns
    get scale 1 (exact round-trip); a NaN weight poisons its scale
    rather than laundering into finite int8 (same contract as
    ``comm/quantize``).  The block is recoverable from the shapes
    (``in // scale.shape[0]``), so the dict stays a pure array pytree —
    it scans, donates, and shards like the float kernel it replaces."""
    w = jnp.asarray(w)
    if w.ndim < 2:
        raise ValueError(
            f"quantize_weight expects [in, *out] kernels, got {w.shape}")
    in_dim = w.shape[0]
    kb = pick_quant_block(in_dim, block)
    if kb <= 4 and in_dim > kb:
        # a prime-ish in_dim forced a tiny divisor: at 4/kb >= 1
        # scale-bytes per element the "quantized" slab is no smaller
        # than bf16 — the conversion would silently inflate the bytes
        # it exists to halve
        import warnings

        warnings.warn(
            f"quantize_weight: in_dim {in_dim} has no block divisor "
            f"<= {block or QUANT_BLOCK} larger than {kb}; at "
            f"{4 / kb:.1f} scale bytes/element the int8 form saves "
            "nothing over bf16 — pad the kernel or keep it float",
            stacklevel=2)
    out_shape = w.shape[1:]
    wf = w.astype(jnp.float32).reshape((in_dim // kb, kb) + out_shape)
    amax = jnp.max(jnp.abs(wf), axis=1)
    scale = jnp.where(amax == 0, 1.0, amax / _INT8_MAX)
    q = jnp.round(wf / scale[:, None])
    wire = jnp.clip(q, -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return {"wire": wire.reshape(w.shape), "scale": scale}


def _quant_block_of(wire, scale) -> int:
    in_dim, nkb = wire.shape[0], scale.shape[0]
    if nkb < 1 or in_dim % nkb:
        raise ValueError(
            f"scale blocks ({nkb}) do not tile the contraction axis "
            f"({in_dim})")
    if wire.shape[1:] != scale.shape[1:]:
        raise ValueError(
            f"wire {wire.shape} / scale {scale.shape}: output axes "
            "must match")
    return in_dim // nkb


def dequantize_weight(wire, scale):
    """fp32 weights from a quantized slab (the backward path and the
    reference route; also the ``dequantize_params`` fake-quant oracle)."""
    kb = _quant_block_of(wire, scale)
    nkb = scale.shape[0]
    wf = wire.astype(jnp.float32).reshape((nkb, kb) + wire.shape[1:])
    return (wf * scale[:, None]).reshape(wire.shape)


# -- routing (the flash/paged/grouped pattern) ------------------------------


def route_quant_backend(backend: Optional[str]) -> str:
    """Resolve the quantized-matmul route (shared by the dense path
    here and the grouped slab path in ``ops/grouped_matmul.py``):
    ``APEX_TPU_QUANT_MATMUL=kernel|reference|auto`` overrides, auto
    picks the kernel on TPU / under ``APEX_TPU_PALLAS_INTERPRET=1``."""
    from apex_tpu.utils.registry import on_tpu

    if backend is None:
        backend = os.environ.get("APEX_TPU_QUANT_MATMUL", "auto")
    if backend not in ("auto", "kernel", "reference"):
        raise ValueError(
            f"quantized matmul backend={backend!r}: expected "
            "auto|kernel|reference")
    if backend == "auto":
        interp = os.environ.get("APEX_TPU_PALLAS_INTERPRET", "0") == "1"
        backend = "kernel" if (on_tpu() or interp) else "reference"
    return backend


# -- Pallas kernel ----------------------------------------------------------

_ROW_BLOCK = 128


def _dq_kernel(n_rows, bm, *refs):
    """Grid (row-block, k-block): the k grid dimension IS the
    quantization blocking, so each step's weight tile ``[kb, p]``
    dequantizes with ONE multiply by its scale row right after the
    int8 dot — the inner-loop dequant the at-rest format exists for
    (the scale is constant over the tile's k span, so it commutes with
    the in-tile reduction: ``dot(x, q)·s == dot(x, q·s)``)."""
    x_ref, w_ref, s_ref, o_ref, acc = refs
    i, s = pl.program_id(0), pl.program_id(1)
    rows = i * bm + jax.lax.broadcasted_iota(jnp.int32, (bm, 1), 0)
    xm = jnp.where(rows < n_rows, x_ref[:].astype(jnp.float32), 0.0)
    part = jax.lax.dot(xm, w_ref[:].astype(jnp.float32),
                       preferred_element_type=jnp.float32) * s_ref[:]

    @pl.when(s == 0)
    def _init():
        acc[:] = part

    @pl.when(s > 0)
    def _accum():
        acc[:] = acc[:] + part

    o_ref[:] = acc[:].astype(o_ref.dtype)


def _dq_pallas(x, wire, scale, kb, interpret):
    from jax.experimental.pallas import tpu as pltpu

    n, k = x.shape
    p = wire.shape[1]
    nkb = scale.shape[0]
    bm = _ROW_BLOCK if n >= _ROW_BLOCK else max(8, 8 * pl.cdiv(n, 8))
    grid = (pl.cdiv(n, bm), nkb)
    return pl.pallas_call(
        functools.partial(_dq_kernel, n, bm),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, kb), lambda i, s: (i, s)),
            pl.BlockSpec((kb, p), lambda i, s: (s, 0)),
            pl.BlockSpec((1, p), lambda i, s: (s, 0)),
        ],
        out_specs=pl.BlockSpec((bm, p), lambda i, s: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, p), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, p), jnp.float32)],
        interpret=interpret,
    )(x, wire, scale)


def _dq_impl(x2, wire2, scale2, kb, backend):
    from apex_tpu.utils.registry import on_tpu

    if x2.shape[0] == 0:
        return jnp.zeros((0, wire2.shape[1]), x2.dtype)
    if route_quant_backend(backend) == "reference":
        deq = dequantize_weight(wire2, scale2)
        out = jax.lax.dot(x2.astype(jnp.float32), deq,
                          preferred_element_type=jnp.float32)
        return out.astype(x2.dtype)
    return _dq_pallas(x2, wire2, scale2, kb, interpret=not on_tpu())


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _dqmm(x2, wire2, scale2, kb, backend, x_dtype):
    return _dq_impl(x2, wire2, scale2, kb, backend)


def _dqmm_fwd(x2, wire2, scale2, kb, backend, x_dtype):
    return _dqmm(x2, wire2, scale2, kb, backend, x_dtype), (wire2,
                                                            scale2)


def _dqmm_bwd(kb, backend, x_dtype, res, g):
    # high-precision backward: dx against the fp32-dequantized weights
    # (no re-quantization error enters the cotangent); the wire is
    # integer (float0 tangent) and the scales are FROZEN serving
    # constants — zero cotangent by contract, documented at
    # quantize_weight
    wire2, scale2 = res
    deq = dequantize_weight(wire2, scale2)
    dx = jax.lax.dot(g.astype(jnp.float32), deq.T,
                     preferred_element_type=jnp.float32).astype(x_dtype)
    return (dx, np.zeros(wire2.shape, jax.dtypes.float0),
            jnp.zeros_like(scale2))


_dqmm.defvjp(_dqmm_fwd, _dqmm_bwd)


def dense_quantized(x, wire, scale, *, backend: Optional[str] = None):
    """``x [..., in] @ W`` off a pre-quantized weight slab → ``[...,
    *out]`` in ``x.dtype`` (fp32 accumulation; trailing weight axes are
    flattened for the GEMM and restored on the output, so the swiglu
    paired ``[h, 2, f]`` kernel works unchanged).

    ``wire`` int8 ``[in, *out]`` + ``scale`` fp32 ``[in/kb, *out]``
    from :func:`quantize_weight`.  ``backend`` routes like every other
    op (``APEX_TPU_QUANT_MATMUL``): the Pallas kernel dequantizes each
    ``[kb, out]`` tile in its inner loop; the reference dequantizes the
    whole slab in XLA — the parity oracle, and exactly what a
    fake-quantized float model computes (the dequantize-then-generate
    pin in tests/test_quantized_matmul.py)."""
    wire = jnp.asarray(wire)
    scale = jnp.asarray(scale)
    kb = _quant_block_of(wire, scale)
    in_dim = wire.shape[0]
    if x.shape[-1] != in_dim:
        raise ValueError(
            f"contraction mismatch: x [..., {x.shape[-1]}] vs wire "
            f"[{in_dim}, ...]")
    out_shape = wire.shape[1:]
    p = 1
    for d in out_shape:
        p *= d
    x2 = x.reshape(-1, in_dim)
    out = _dqmm(x2, wire.reshape(in_dim, p),
                scale.reshape(scale.shape[0], p), kb, backend,
                jnp.dtype(x.dtype).name)
    return out.reshape(x.shape[:-1] + out_shape)


def quantized_matmul(x, leaf, *, backend: Optional[str] = None):
    """The one matmul-site helper: ``leaf`` is either a plain kernel
    array (cast to ``x.dtype`` and multiplied exactly as the historical
    sites did — byte-identical to the pre-quantization code path) or a
    quantized dict, in which case the int8 slab path runs."""
    if is_quantized(leaf):
        return dense_quantized(x, leaf["wire"], leaf["scale"],
                               backend=backend)
    return x @ leaf.astype(x.dtype)
