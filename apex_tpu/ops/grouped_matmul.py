"""Grouped (segment) matmul — the ragged expert-FFN compute primitive.

Capacity-free MoE routing (transformer/moe.py ``routing='ragged'``) sorts
tokens by expert and hands each expert a *ragged* ``[tokens, k]`` segment;
the FFN is then ``out[r] = x[r] @ w[group(r)]`` with segment boundaries in
an offsets vector — no pad-to-capacity slots, no dropped tokens (the
megablocks formulation, arXiv:2211.15841, on TPU).

Two implementations behind one route (the flash/paged-attention pattern):

- **kernel** — a Pallas kernel whose grid walks (row-block, group)
  intersection steps.  The per-step block/group ids, first-visit flags and
  the group offsets ride in SMEM via scalar prefetch, so the weight
  BlockSpec index map dereferences the right expert's ``[k, p]`` slab per
  step and a row block shared by two experts is visited once per expert
  with row masks — compute is proportional to ``N·k·p`` + one partial
  block per boundary, never ``G·N·k·p``.
- **reference** — the XLA segment-sum form: one masked matmul per group
  (``G`` dense matmuls), trivially correct and differentiable; the parity
  oracle and the CPU path.

``APEX_TPU_GROUPED_MATMUL=kernel|reference|auto`` overrides the route;
``auto`` picks the kernel on TPU (or under ``APEX_TPU_PALLAS_INTERPRET=1``)
and the reference elsewhere.

``offsets`` may describe a *window*: ``offsets[0] > 0`` / ``offsets[-1] <
N`` leave the rows outside ``[offsets[0], offsets[-1])`` exactly zero in
the output (the expert-parallel ring path computes only its local experts'
window of a remote rank's token array this way).  Offsets may be traced
values — all metadata is built with jnp and static shapes.

Backward: ``dx = grouped_matmul(g, w.swapaxes(1, 2), offsets)`` (the same
routed primitive — kernel backward stays a kernel) and ``dw[e] =
x_seg(e)^T @ g_seg(e)`` as masked segment outer products (XLA on both
routes; its access pattern is weight-stationary, not token-stationary, and
the G small ``[k, N]·[N, p]`` products fuse well).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from apex_tpu.ops._pallas_utils import out_struct
from apex_tpu.utils.registry import on_tpu

__all__ = ["grouped_matmul", "grouped_matmul_quantized",
           "grouped_matmul_reference", "group_ids",
           "quantize_group_weights"]


def group_ids(offsets: jax.Array, n_rows: int, n_groups: int) -> jax.Array:
    """Group index per row: ``[n_rows]`` int32 in ``[0, n_groups]`` where
    rows outside the ``[offsets[0], offsets[-1])`` window get the
    sentinel ``n_groups`` (callers gather per-row biases through a
    zero-padded table so sentinel rows stay exactly zero)."""
    r = jnp.arange(n_rows, dtype=jnp.int32)
    off = offsets.astype(jnp.int32)
    g = jnp.searchsorted(off, r, side="right").astype(jnp.int32) - 1
    valid = (r >= off[0]) & (r < off[-1])
    return jnp.where(valid, jnp.clip(g, 0, n_groups - 1), n_groups)


def _check(x, w, offsets):
    if x.ndim != 2 or w.ndim != 3 or offsets.ndim != 1:
        raise ValueError(
            f"grouped_matmul: expected x [N, k], w [G, k, p], offsets "
            f"[G+1]; got {x.shape}, {w.shape}, {offsets.shape}")
    if w.shape[0] + 1 != offsets.shape[0]:
        raise ValueError(
            f"grouped_matmul: offsets length {offsets.shape[0]} != "
            f"G + 1 = {w.shape[0] + 1}")
    if x.shape[1] != w.shape[1]:
        raise ValueError(
            f"grouped_matmul: contraction mismatch — x [..., {x.shape[1]}]"
            f" vs w [., {w.shape[1]}, .]")


def grouped_matmul_reference(x: jax.Array, w: jax.Array,
                             offsets: jax.Array) -> jax.Array:
    """Segment-sum reference: ``out[r] = x[r] @ w[g]`` for rows in group
    ``g``'s ``[offsets[g], offsets[g+1])`` span, zero outside every
    span — one masked dense matmul per group."""
    _check(x, w, offsets)
    n = x.shape[0]
    off = offsets.astype(jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)
    out = jnp.zeros((n, w.shape[-1]), jnp.float32)
    for g in range(w.shape[0]):
        mask = ((rows >= off[g]) & (rows < off[g + 1]))[:, None]
        xg = jnp.where(mask, x.astype(jnp.float32), 0.0)
        out = out + jnp.where(
            mask,
            jax.lax.dot(xg, w[g].astype(jnp.float32),
                        preferred_element_type=jnp.float32),
            0.0)
    return out.astype(jnp.result_type(x, w))


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

_BLOCK_ROWS = 128


def _gmm_kernel(bm, n_rows, quant, *refs):
    """One grid step = one (row-block, group) intersection.  Consecutive
    steps share a row block (the f32 accumulator stays VMEM-resident);
    the first visit of a block overwrites, later visits add.  Rows
    outside the step's group span are zeroed *on the input side*, so a
    block straddling two groups gets each row exactly its own expert's
    product.

    ``quant`` (ISSUE 14): the expert slab is pre-quantized int8 and an
    extra ref carries its per-(k-block, column) scales (dereferenced by
    the same group index map) — the slab dequantizes in VMEM right
    before the dot, so the HBM read of the weights is the int8 bytes."""
    if quant:
        (blk_ref, grp_ref, fst_ref, off_ref, nst_ref,
         x_ref, w_ref, s_ref, out_ref, acc) = refs
    else:
        (blk_ref, grp_ref, fst_ref, off_ref, nst_ref,
         x_ref, w_ref, out_ref, acc) = refs
        s_ref = None
    s = pl.program_id(0)
    g = grp_ref[s]
    start = off_ref[g]
    end = off_ref[g + 1]
    rows = blk_ref[s] * bm + jax.lax.broadcasted_iota(
        jnp.int32, (bm, 1), 0)
    # padded trailing steps (s >= the actual intersection count) must
    # contribute nothing; their block id aliases the last real block
    live = (rows >= start) & (rows < end) & (rows < n_rows) \
        & (s < nst_ref[0])
    xm = jnp.where(live, x_ref[:].astype(jnp.float32), 0.0)
    w = w_ref[0].astype(jnp.float32)
    if quant:
        k, p = w.shape
        nkb = s_ref.shape[1]
        w = (w.reshape(nkb, k // nkb, p)
             * s_ref[0][:, None, :]).reshape(k, p)
    part = jax.lax.dot(xm, w, preferred_element_type=jnp.float32)

    @pl.when(fst_ref[s] == 1)
    def _init():
        acc[:] = part

    @pl.when(fst_ref[s] == 0)
    def _accum():
        acc[:] = acc[:] + part

    out_ref[:] = acc[:].astype(out_ref.dtype)


def _step_metadata(offsets, n_rows, n_groups, bm):
    """Static-shape (row-block, group) walk: for each of the
    ``B = ceil(N/bm)`` row blocks, one step per group intersecting it
    (≥ 1 — empty blocks get one masked step so every output block is
    initialized).  Total real steps ≤ B + G, the static bound the grid
    uses; trailing padding repeats the last block with a dead mask.
    Built entirely from jnp so traced offsets work."""
    nb = pl.cdiv(n_rows, bm)
    n_steps = nb + n_groups
    off = offsets.astype(jnp.int32)
    blocks = jnp.arange(nb, dtype=jnp.int32)

    def row_group(r):
        g = jnp.searchsorted(off, r, side="right").astype(jnp.int32) - 1
        return jnp.clip(g, 0, n_groups - 1)

    g_first = row_group(blocks * bm)
    g_last = row_group(jnp.minimum((blocks + 1) * bm - 1, n_rows - 1))
    per_block = g_last - g_first + 1                       # [B], >= 1
    cum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                           jnp.cumsum(per_block, dtype=jnp.int32)])
    total = cum[-1]
    step_block = jnp.clip(
        jnp.repeat(blocks, per_block, total_repeat_length=n_steps),
        0, nb - 1).astype(jnp.int32)
    within = jnp.arange(n_steps, dtype=jnp.int32) - cum[step_block]
    step_group = jnp.clip(g_first[step_block] + within,
                          0, n_groups - 1).astype(jnp.int32)
    first = jnp.concatenate([
        jnp.ones(1, jnp.int32),
        (step_block[1:] != step_block[:-1]).astype(jnp.int32)])
    return step_block, step_group, first, total.reshape(1)


def _gmm_pallas(x, w, offsets, interpret, scale=None):
    from jax.experimental.pallas import tpu as pltpu

    n, k = x.shape
    g_n, _, p = w.shape
    bm = _BLOCK_ROWS if n >= _BLOCK_ROWS else max(
        8, 8 * pl.cdiv(n, 8))
    blk, grp, fst, nst = _step_metadata(offsets, n, g_n, bm)
    n_steps = int(blk.shape[0])
    out_dtype = x.dtype if scale is not None else jnp.result_type(x, w)
    in_specs = [
        pl.BlockSpec((bm, k),
                     lambda s, blk, grp, fst, off, nst: (blk[s], 0)),
        pl.BlockSpec((1, k, p),
                     lambda s, blk, grp, fst, off, nst:
                     (grp[s], 0, 0)),
    ]
    inputs = [x, w]
    if scale is not None:
        # the scale slab dereferences through the SAME per-step group
        # id, so the weight tile and its scales arrive together
        nkb = scale.shape[1]
        in_specs.append(pl.BlockSpec(
            (1, nkb, p),
            lambda s, blk, grp, fst, off, nst: (grp[s], 0, 0)))
        inputs.append(scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(n_steps,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (bm, p), lambda s, blk, grp, fst, off, nst: (blk[s], 0)),
        scratch_shapes=[pltpu.VMEM((bm, p), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_gmm_kernel, bm, n, scale is not None),
        grid_spec=grid_spec,
        out_shape=out_struct((n, p), out_dtype, x),
        interpret=interpret,
    )(blk, grp, fst, offsets.astype(jnp.int32), nst, *inputs)


# ---------------------------------------------------------------------------
# routing + VJP
# ---------------------------------------------------------------------------


def _route(backend: Optional[str]) -> str:
    if backend is None:
        backend = os.environ.get("APEX_TPU_GROUPED_MATMUL", "auto")
    if backend not in ("auto", "kernel", "reference"):
        raise ValueError(
            f"grouped_matmul backend={backend!r}: expected "
            "auto|kernel|reference")
    if backend == "auto":
        interp = os.environ.get("APEX_TPU_PALLAS_INTERPRET", "0") == "1"
        backend = "kernel" if (on_tpu() or interp) else "reference"
    return backend


def _gmm_impl(x, w, offsets, backend):
    if x.shape[0] == 0:
        return jnp.zeros((0, w.shape[-1]), jnp.result_type(x, w))
    if _route(backend) == "reference":
        return grouped_matmul_reference(x, w, offsets)
    return _gmm_pallas(x, w, offsets, interpret=not on_tpu())


def _grouped_dw(x, g, offsets):
    """``dw[e] = x_seg(e)^T @ g_seg(e)`` via masked segment outer
    products (fp32 accumulation); weight-stationary, shared by both
    routes."""
    n = x.shape[0]
    off = offsets.astype(jnp.int32)
    rows = jnp.arange(n, dtype=jnp.int32)
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    parts = []
    for e in range(off.shape[0] - 1):
        mask = ((rows >= off[e]) & (rows < off[e + 1]))[:, None]
        parts.append(jax.lax.dot(
            jnp.where(mask, xf, 0.0).T, gf,
            preferred_element_type=jnp.float32))
    return jnp.stack(parts)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _gmm(x, w, offsets, backend):
    return _gmm_impl(x, w, offsets, backend)


def _gmm_fwd(x, w, offsets, backend):
    return _gmm(x, w, offsets, backend), (x, w, offsets)


def _gmm_bwd(backend, res, g):
    x, w, offsets = res
    dx = _gmm_impl(g, w.swapaxes(1, 2).astype(g.dtype), offsets,
                   backend).astype(x.dtype)
    dw = _grouped_dw(x, g, offsets).astype(w.dtype)
    d_off = np.zeros(offsets.shape, jax.dtypes.float0)
    return dx, dw, d_off


_gmm.defvjp(_gmm_fwd, _gmm_bwd)


def grouped_matmul(x: jax.Array, w: jax.Array, offsets: jax.Array, *,
                   backend: Optional[str] = None) -> jax.Array:
    """``out[r] = x[r] @ w[g]`` for rows ``r`` in group ``g``'s span
    ``[offsets[g], offsets[g+1])``; rows outside every span (including
    outside a window — ``offsets[0] > 0`` / ``offsets[-1] < N``) come
    back exactly zero.

    ``x`` ``[N, k]`` sorted by group, ``w`` ``[G, k, p]`` stacked group
    weights, ``offsets`` ``[G+1]`` non-decreasing int (traced values
    fine).  fp32 accumulation, output in ``result_type(x, w)``.

    ``backend``: ``None`` routes automatically (Pallas kernel on TPU or
    under ``APEX_TPU_PALLAS_INTERPRET=1``; XLA segment-sum reference
    otherwise; ``APEX_TPU_GROUPED_MATMUL`` overrides), ``"kernel"`` /
    ``"reference"`` pin a path — the parity suite compares the two.

    Differentiable: ``dx`` re-enters the routed primitive with the
    weights transposed (kernel backward stays a kernel), ``dw`` runs as
    masked segment outer products.
    """
    _check(x, w, offsets)
    return _gmm(x, w, offsets, backend)


# ---------------------------------------------------------------------------
# Weight-only int8 quantized slab path (ISSUE 14)
# ---------------------------------------------------------------------------


def quantize_group_weights(w, block: Optional[int] = None) -> dict:
    """Pre-quantize an expert weight slab ``[G, k, p]`` → ``{"wire":
    int8 [G, k, p], "scale": fp32 [G, k/kb, p]}`` — per-expert exactly
    :func:`~apex_tpu.ops.dense.quantize_weight` vmapped over the
    expert axis, so the dense and grouped slab forms share ONE
    quantization definition (one fp32 scale per (k-block, output
    column); the block is recoverable from the shapes, so the dict
    stays a pure array pytree)."""
    from apex_tpu.ops.dense import quantize_weight

    w = jnp.asarray(w)
    if w.ndim != 3:
        raise ValueError(
            f"quantize_group_weights expects [G, k, p] slabs, got "
            f"{w.shape}")
    return jax.vmap(lambda we: quantize_weight(we, block))(w)


def _check_group_slab(wire, scale) -> None:
    g_n, k, p = wire.shape
    if (scale.ndim != 3 or scale.shape[0] != g_n
            or scale.shape[2] != p or not scale.shape[1]
            or k % scale.shape[1]):
        raise ValueError(
            f"scale {scale.shape} does not tile slab {wire.shape}")


def _dequantize_group(wire, scale):
    from apex_tpu.ops.dense import dequantize_weight

    _check_group_slab(wire, scale)
    return jax.vmap(dequantize_weight)(wire, scale)


def _gmmq_impl(x, wire, scale, offsets, backend):
    from apex_tpu.ops.dense import route_quant_backend

    if x.shape[0] == 0:
        return jnp.zeros((0, wire.shape[-1]), x.dtype)
    if route_quant_backend(backend) == "reference":
        return grouped_matmul_reference(
            x, _dequantize_group(wire, scale), offsets).astype(x.dtype)
    return _gmm_pallas(x, wire, offsets, interpret=not on_tpu(),
                       scale=scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _gmmq(x, wire, scale, offsets, backend, x_dtype):
    return _gmmq_impl(x, wire, scale, offsets, backend)


def _gmmq_fwd(x, wire, scale, offsets, backend, x_dtype):
    return _gmmq(x, wire, scale, offsets, backend, x_dtype), (
        wire, scale, offsets)


def _gmmq_bwd(backend, x_dtype, res, g):
    # high-precision backward: dx runs the ROUTED float primitive over
    # the fp32-dequantized slab (transposed), so no requantization
    # error enters the cotangent; the frozen wire gets a float0
    # cotangent (int8) and the scales zeros — serving constants, the
    # same contract as ops/dense.quantize_weight
    wire, scale, offsets = res
    deq = _dequantize_group(wire, scale)
    dx = _gmm_impl(g.astype(jnp.float32), deq.swapaxes(1, 2), offsets,
                   backend).astype(x_dtype)
    d_off = np.zeros(offsets.shape, jax.dtypes.float0)
    return (dx, np.zeros(wire.shape, jax.dtypes.float0),
            jnp.zeros_like(scale), d_off)


_gmmq.defvjp(_gmmq_fwd, _gmmq_bwd)


def grouped_matmul_quantized(x: jax.Array, wire: jax.Array,
                             scale: jax.Array, offsets: jax.Array, *,
                             backend: Optional[str] = None) -> jax.Array:
    """:func:`grouped_matmul` off a pre-quantized expert slab
    (:func:`quantize_group_weights`): ``out[r] = x[r] @ deq(w[g])`` for
    rows in group ``g``'s span, rows outside every span exactly zero,
    output in ``x.dtype`` with fp32 accumulation.

    The kernel route extends the float grouped kernel: the per-step
    group index also dereferences the slab's scale rows, and each
    step's ``[k, p]`` expert tile dequantizes in VMEM before its dot —
    the HBM weight read per step is the int8 bytes, which is the
    decode-bandwidth win.  ``APEX_TPU_QUANT_MATMUL`` routes (shared
    with ``ops/dense.dense_quantized``); the XLA reference dequantizes
    the whole slab — the parity oracle.  Backward stays high-precision
    (``dx`` against fp32 dequantized weights; wire/scales frozen)."""
    if x.ndim != 2 or wire.ndim != 3 or offsets.ndim != 1:
        raise ValueError(
            f"grouped_matmul_quantized: expected x [N, k], wire "
            f"[G, k, p], offsets [G+1]; got {x.shape}, {wire.shape}, "
            f"{offsets.shape}")
    if wire.shape[0] + 1 != offsets.shape[0]:
        raise ValueError(
            f"grouped_matmul_quantized: offsets length "
            f"{offsets.shape[0]} != G + 1 = {wire.shape[0] + 1}")
    if x.shape[1] != wire.shape[1]:
        raise ValueError(
            f"grouped_matmul_quantized: contraction mismatch — x "
            f"[..., {x.shape[1]}] vs wire [., {wire.shape[1]}, .]")
    _check_group_slab(wire, scale)
    return _gmmq(x, wire, scale, offsets, backend,
                 jnp.dtype(x.dtype).name)
