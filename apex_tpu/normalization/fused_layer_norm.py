"""Module wrappers for the fused norms.

Reference: apex/normalization/fused_layer_norm.py —
``FusedLayerNorm``/``FusedRMSNorm`` (:195, :347) and the ``Mixed*`` variants
(:553+) where params stay fp32 while activations are low-precision (the
Megatron contract). As flax.linen modules the "mixed" behavior is the
default — params are created fp32 and the op computes in fp32 — so the
``Mixed*`` names are aliases kept for API parity.
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.layer_norm import fused_layer_norm, fused_rms_norm

__all__ = [
    "FusedLayerNorm",
    "FusedRMSNorm",
    "MixedFusedLayerNorm",
    "MixedFusedRMSNorm",
]


def _last_dim(shape: Union[int, Sequence[int]]) -> int:
    if isinstance(shape, int):
        return shape
    if len(shape) != 1:
        raise NotImplementedError(
            "apex_tpu norms normalize over the last dimension; pass "
            "normalized_shape as an int (multi-dim normalized_shape from the "
            "reference maps to flattening those dims first)."
        )
    return int(shape[0])


class FusedLayerNorm(nn.Module):
    """Drop-in for reference ``apex.normalization.FusedLayerNorm``."""

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        hidden = _last_dim(self.normalized_shape)
        if x.shape[-1] != hidden:
            raise ValueError(
                f"input last dim {x.shape[-1]} != normalized_shape {hidden}"
            )
        if self.elementwise_affine:
            weight = self.param("scale", nn.initializers.ones, (hidden,),
                                jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (hidden,),
                              jnp.float32)
        else:
            weight = bias = None
        return fused_layer_norm(
            x, weight, bias, self.eps, self.memory_efficient
        )


class FusedRMSNorm(nn.Module):
    """Drop-in for reference ``apex.normalization.FusedRMSNorm``."""

    normalized_shape: Union[int, Sequence[int]]
    eps: float = 1e-5
    elementwise_affine: bool = True
    memory_efficient: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        hidden = _last_dim(self.normalized_shape)
        if x.shape[-1] != hidden:
            raise ValueError(
                f"input last dim {x.shape[-1]} != normalized_shape {hidden}"
            )
        weight = (
            self.param("scale", nn.initializers.ones, (hidden,), jnp.float32)
            if self.elementwise_affine
            else None
        )
        return fused_rms_norm(x, weight, self.eps, self.memory_efficient)


# Params are already kept fp32 regardless of activation dtype, which is
# exactly what the reference's Mixed* variants add.
MixedFusedLayerNorm = FusedLayerNorm
MixedFusedRMSNorm = FusedRMSNorm
