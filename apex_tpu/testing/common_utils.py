"""Test skip decorators.

Reference: ``apex/testing/common_utils.py:12-33`` — env-driven
``skipIfRocm`` / ``skipFlakyTest``.  The platform split here is
CPU-mesh vs real-TPU instead of CUDA vs ROCm:

- ``skipIfNoTPU`` — test needs a real chip (non-interpret Pallas);
  prefer the ``tpu`` pytest marker (pyproject) for whole files.
- ``skipIfTPU`` — test only makes sense on the CPU mesh.
- ``skipFlakyTest`` — honored when ``APEX_TPU_SKIP_FLAKY_TEST=1``
  (reference APEX_SKIP_FLAKY_TEST).
"""

from __future__ import annotations

import os

import pytest

__all__ = ["skipIfNoTPU", "skipIfTPU", "skipFlakyTest"]


def _on_tpu() -> bool:
    import jax

    return any(d.platform == "tpu" for d in jax.devices())


def skipIfNoTPU(fn):
    return pytest.mark.skipif(
        not _on_tpu(), reason="test requires a real TPU chip")(fn)


def skipIfTPU(fn):
    return pytest.mark.skipif(
        _on_tpu(), reason="test only runs on the CPU mesh")(fn)


def skipFlakyTest(fn):
    return pytest.mark.skipif(
        os.environ.get("APEX_TPU_SKIP_FLAKY_TEST") == "1",
        reason="flaky test skipped via APEX_TPU_SKIP_FLAKY_TEST")(fn)
