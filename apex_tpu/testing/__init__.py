from .common_utils import (  # noqa: F401
    skipFlakyTest,
    skipIfNoTPU,
    skipIfTPU,
)
