"""Killable out-of-process JAX backend probes.

On a dead axon tunnel ``jax.devices()`` hangs inside C++ where Python
signal handlers never fire, so any code that must *decide* whether a
backend is reachable (bench.py's skip path, the dryrun gate's
virtual-CPU fallback) probes in a subprocess with a kill timeout
instead of initializing its own backend.  One helper serves both so the
timeout/parse/error-surfacing recipe cannot drift between callers.

Outage economics (VERDICT r4 #7): every gate used to pay its own full
timeout on a dead tunnel (120s dryrun + 150s bench per driver run).
Two levers fix that: the default timeout drops to 45s (a healthy TPU
init answers in a few seconds; only a hang rides the timeout out), and
results are cached in a temp file for a short TTL so the second gate of
the same driver invocation reuses the first one's verdict instead of
re-hanging.  ``APEX_TPU_PROBE_CACHE_TTL=0`` disables the cache (the
unit tests do); the TTL stays under the probe cron's period so a
returning tunnel is never masked for long.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Optional

__all__ = ["probe_jax", "probe_backend_info", "resolve_timeout"]

# uid-suffixed: /tmp is world-shared, and a fixed name would (a) break
# the cache for the second user on a host (0600 file, silent open
# failures) and (b) let any user pre-seed verdicts other users trust
_CACHE_PATH = os.path.join(
    tempfile.gettempdir(),
    f"apex_tpu_probe_cache_{os.getuid() if hasattr(os, 'getuid') else 0}"
    ".json")
_MISS = object()


def resolve_timeout(timeout_s: Optional[int], default: int = 45) -> int:
    """The probe timeout actually used: ``APEX_TPU_PROBE_TIMEOUT`` (an
    operator knob — BENCH_r05 lost every row to a hard-coded 45s on a
    slow-to-answer tunnel) overrides any caller value; else the caller's
    explicit ``timeout_s``; else ``default``.  Malformed env values warn
    by name and are ignored."""
    raw = os.environ.get("APEX_TPU_PROBE_TIMEOUT")
    if raw:
        try:
            val = int(float(raw))
            if val > 0:
                return val
            raise ValueError
        except ValueError:
            print(f"[probe] ignoring malformed APEX_TPU_PROBE_TIMEOUT="
                  f"{raw!r} (want a positive number of seconds)",
                  flush=True)
    return default if timeout_s is None else int(timeout_s)


def _cache_ttl() -> float:
    try:
        return float(os.environ.get("APEX_TPU_PROBE_CACHE_TTL", "270"))
    except ValueError:
        return 0.0


def _cache_get(expr: str, validate=None):
    ttl = _cache_ttl()
    if ttl <= 0:
        return _MISS
    try:
        with open(_CACHE_PATH) as f:
            data = json.load(f)
        entry = data.get(expr) if isinstance(data, dict) else None
        if (isinstance(entry, dict)
                and isinstance(entry.get("t"), (int, float))
                and isinstance(entry.get("val"), (str, type(None)))
                and time.time() - entry["t"] <= ttl):
            val = entry["val"]   # may be None: a cached outage verdict
            if (isinstance(val, str) and validate is not None
                    and not validate(val)):
                # corrupted/foreign entry: a value the caller cannot
                # parse must read as a cache MISS (re-probe), not wedge
                # the gates on garbage for a whole TTL
                return _MISS
            return val
    except (OSError, ValueError, KeyError, TypeError):
        pass
    return _MISS


def _cache_put(expr: str, val: Optional[str]) -> None:
    if _cache_ttl() <= 0:
        return
    try:
        try:
            with open(_CACHE_PATH) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}
        if not isinstance(data, dict):
            data = {}
        data[expr] = {"t": time.time(), "val": val}
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(_CACHE_PATH))
        with os.fdopen(fd, "w") as f:
            json.dump(data, f)
        os.replace(tmp, _CACHE_PATH)
    except OSError:
        pass   # cache is best-effort; the probe result is already known


def probe_jax(expr: str, timeout_s: Optional[int] = None,
              label: str = "jax backend probe",
              validate=None) -> Optional[str]:
    """Evaluate ``expr`` (a Python expression over an imported ``jax``)
    in a subprocess; return its str() result, or None on failure.

    ``timeout_s=None`` resolves to 45s; ``APEX_TPU_PROBE_TIMEOUT``
    overrides either (see :func:`resolve_timeout`), and the chosen value
    is printed in the probe log line so a skipped-row post-mortem can
    see which timeout actually applied.

    Failures (timeout, crash) print the child's tail of stderr with the
    ``label`` so a healthy-host misconfiguration does not silently read
    as an outage.  Results (including failures) are shared across
    processes for a short TTL via a temp-file cache — see the module
    docstring.

    ``validate``: optional predicate on the result string.  A *cached*
    value failing it is treated as a miss (re-probe, don't trust a
    corrupted cache file); a *fresh* value failing it is treated as a
    probe failure (printed, cached as None)."""
    timeout_s = resolve_timeout(timeout_s)
    cached = _cache_get(expr, validate)
    if cached is not _MISS:
        print(f"[{label}] using cached probe result "
              f"(APEX_TPU_PROBE_CACHE_TTL={_cache_ttl():g}s): "
              f"{cached!r}", flush=True)
        return cached
    env_src = (" (from APEX_TPU_PROBE_TIMEOUT)"
               if os.environ.get("APEX_TPU_PROBE_TIMEOUT") else "")
    print(f"[{label}] probing backend, timeout {timeout_s}s{env_src}",
          flush=True)
    code = f"import jax; print('PROBE=' + str({expr}))"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"[{label}] timed out after {timeout_s}s "
              "(backend unreachable)", flush=True)
        _cache_put(expr, None)
        return None
    for line in out.stdout.splitlines():
        if line.startswith("PROBE="):
            val = line.split("=", 1)[1]
            if validate is not None and not validate(val):
                print(f"[{label}] unparseable probe result {val!r}; "
                      "treating as unreachable", flush=True)
                _cache_put(expr, None)
                return None
            _cache_put(expr, val)
            return val
    tail = (out.stderr or out.stdout).strip()[-400:]
    print(f"[{label}] failed rc={out.returncode}: {tail}", flush=True)
    _cache_put(expr, None)
    return None


def _parse_backend_info(val: str):
    """Parse ``platform:count`` or return None for anything else —
    empty counts (``"cpu:"``), non-numeric counts, colon-less strings."""
    platform, sep, count = val.partition(":")
    if not sep or not platform or not (count.isascii() and count.isdigit()):
        return None
    return platform, int(count)


def probe_backend_info(timeout_s: Optional[int] = None,
                       label: str = "backend probe"):
    """(platform, device_count) via ONE probed expression, or None.
    ``timeout_s`` resolves through :func:`resolve_timeout`
    (``APEX_TPU_PROBE_TIMEOUT`` wins, then the caller value, then 45s).

    Both gates (bench.py backend check, dryrun device count) call this
    so a single cached verdict serves the whole driver invocation — two
    distinct expressions would each pay the outage timeout.  Malformed
    values (a corrupted cache entry like ``"cpu:"``) are rejected at the
    cache layer (re-probe) and, on a fresh probe, degrade to None
    instead of crashing the gates."""
    got = probe_jax("jax.devices()[0].platform + ':' + str(len("
                    "jax.devices()))", timeout_s, label=label,
                    validate=lambda v: _parse_backend_info(v) is not None)
    if got is None:
        return None
    parsed = _parse_backend_info(got)
    if parsed is None:   # unreachable given validate=; belt and braces
        print(f"[{label}] unparseable probe result {got!r}; "
              "treating as unreachable", flush=True)
        return None
    return parsed
