"""Killable out-of-process JAX backend probes.

On a dead axon tunnel ``jax.devices()`` hangs inside C++ where Python
signal handlers never fire, so any code that must *decide* whether a
backend is reachable (bench.py's skip path, the dryrun gate's
virtual-CPU fallback) probes in a subprocess with a kill timeout
instead of initializing its own backend.  One helper serves both so the
timeout/parse/error-surfacing recipe cannot drift between callers.
"""

from __future__ import annotations

import subprocess
import sys
from typing import Optional

__all__ = ["probe_jax"]


def probe_jax(expr: str, timeout_s: int = 120,
              label: str = "jax backend probe") -> Optional[str]:
    """Evaluate ``expr`` (a Python expression over an imported ``jax``)
    in a subprocess; return its str() result, or None on failure.

    Failures (timeout, crash) print the child's tail of stderr with the
    ``label`` so a healthy-host misconfiguration does not silently read
    as an outage."""
    code = f"import jax; print('PROBE=' + str({expr}))"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        print(f"[{label}] timed out after {timeout_s}s "
              "(backend unreachable)", flush=True)
        return None
    for line in out.stdout.splitlines():
        if line.startswith("PROBE="):
            return line.split("=", 1)[1]
    tail = (out.stderr or out.stdout).strip()[-400:]
    print(f"[{label}] failed rc={out.returncode}: {tail}", flush=True)
    return None
