"""Checkpoint/resume for AMP train states.

Reference mechanisms (SURVEY.md §5): (1) ``amp.state_dict()`` serializing
every LossScaler (README.md:60-97 workflow); (2) optimizer state re-cast on
load (_initialize.py:205-207); (3) cluster-requeue via ADLR AutoResume
(pipeline_parallel/utils.py:142). The TPU-idiomatic equivalent is orbax:
one ``save``/``restore`` pair over the whole TrainState pytree (params,
masters, optimizer moments, loss-scale state, step), sharded arrays
restored to their original shardings.

``AutoResume`` mirrors the ADLR hook shape (init / termination request /
requeue) as a plain polling stub so Megatron-style loops port unchanged.

``async_saver`` goes beyond the reference (whose checkpointing blocks
the train loop): orbax's async machinery snapshots device arrays to
host, returns, and writes to disk on a background thread — the step
loop keeps training while the previous checkpoint persists.

NOTE (ISSUE 11): the production fault-tolerance path is
:mod:`apex_tpu.checkpoint` — per-process shard files with an
atomically committed manifest and content digests, donation-safe
async saves with overlap telemetry, bitwise restore validation, and
detector-driven rollback + LR re-warm (``RecoveryManager``).  This
module remains the thin orbax-compatible surface for users who
already run orbax everywhere.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

__all__ = ["save_checkpoint", "restore_checkpoint", "latest_step",
           "async_saver", "AsyncSaver", "AutoResume"]


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(directory: str, step: int, state: Any) -> str:
    """Write ``state`` (any pytree of arrays) to ``directory/step_N``."""
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    ckptr = _ckptr()
    ckptr.save(path, state, force=True)
    ckptr.wait_until_finished()
    return path


class AsyncSaver:
    """Non-blocking checkpoint writes: ``save`` snapshots to host and
    returns; the disk write runs on orbax's background thread.  At most
    one save is in flight — a new ``save`` first waits for the previous
    write (so the loop can never queue unbounded host memory), and
    ``wait`` / context-manager exit block until everything is durable.

    Use :func:`async_saver` to construct; ``save_checkpoint`` remains
    the synchronous one-shot API.
    """

    def __init__(self):
        import orbax.checkpoint as ocp

        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, directory: str, step: int, state: Any) -> str:
        self._ckptr.wait_until_finished()   # bound in-flight saves to 1
        path = os.path.join(os.path.abspath(directory), f"step_{step}")
        self._ckptr.save(path, args=_standard_save_args(state),
                         force=True)
        return path

    def wait(self):
        self._ckptr.wait_until_finished()

    def close(self):
        self._ckptr.wait_until_finished()
        self._ckptr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _standard_save_args(state):
    import orbax.checkpoint as ocp

    return ocp.args.StandardSave(state)


def async_saver() -> AsyncSaver:
    """A reusable non-blocking saver for the training loop::

        with async_saver() as saver:
            for step in range(n):
                state, metrics = train_step(state, batch)
                if step % ckpt_every == 0:
                    saver.save(ckpt_dir, step, state)
        # exit blocks until the last write is durable
    """
    return AsyncSaver()


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = [int(d.split("_", 1)[1]) for d in os.listdir(directory)
             if d.startswith("step_") and d.split("_", 1)[1].isdigit()]
    return max(steps) if steps else None


def restore_checkpoint(directory: str, state_like: Any,
                       step: Optional[int] = None) -> Any:
    """Restore into the structure/shardings of ``state_like`` (pass the
    freshly-initialized state; dtypes, shapes, and shardings are taken
    from it — the reference's load-then-recast trick,
    _initialize.py:205-207, is implicit)."""
    step = latest_step(directory) if step is None else step
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {directory}")
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array) else x,
        state_like,
    )
    return _ckptr().restore(path, abstract)


class AutoResume:
    """ADLR AutoResume-shaped hook (reference testing/global_vars.py:156):
    a scheduler writes ``termination_file`` to request
    checkpoint-and-requeue; the training loop polls ``termination_requested``
    and calls ``request_resume`` after saving."""

    def __init__(self, termination_file: Optional[str] = None):
        self.termination_file = termination_file or os.environ.get(
            "APEX_TPU_TERMINATION_FILE", "")

    def init(self):
        return self

    def termination_requested(self) -> bool:
        return bool(self.termination_file) and os.path.exists(
            self.termination_file)

    def request_resume(self):
        if self.termination_file and os.path.exists(self.termination_file):
            os.unlink(self.termination_file)
