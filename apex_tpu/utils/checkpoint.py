"""Checkpoint/resume for AMP train states.

Reference mechanisms (SURVEY.md §5): (1) ``amp.state_dict()`` serializing
every LossScaler (README.md:60-97 workflow); (2) optimizer state re-cast on
load (_initialize.py:205-207); (3) cluster-requeue via ADLR AutoResume
(pipeline_parallel/utils.py:142). The TPU-idiomatic equivalent is orbax:
one ``save``/``restore`` pair over the whole TrainState pytree (params,
masters, optimizer moments, loss-scale state, step), sharded arrays
restored to their original shardings.

``AutoResume`` mirrors the ADLR hook shape (init / termination request /
requeue) as a plain polling stub so Megatron-style loops port unchanged.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

__all__ = ["save_checkpoint", "restore_checkpoint", "latest_step",
           "AutoResume"]


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_checkpoint(directory: str, step: int, state: Any) -> str:
    """Write ``state`` (any pytree of arrays) to ``directory/step_N``."""
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    ckptr = _ckptr()
    ckptr.save(path, state, force=True)
    ckptr.wait_until_finished()
    return path


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = [int(d.split("_", 1)[1]) for d in os.listdir(directory)
             if d.startswith("step_") and d.split("_", 1)[1].isdigit()]
    return max(steps) if steps else None


def restore_checkpoint(directory: str, state_like: Any,
                       step: Optional[int] = None) -> Any:
    """Restore into the structure/shardings of ``state_like`` (pass the
    freshly-initialized state; dtypes, shapes, and shardings are taken
    from it — the reference's load-then-recast trick,
    _initialize.py:205-207, is implicit)."""
    step = latest_step(directory) if step is None else step
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {directory}")
    path = os.path.join(os.path.abspath(directory), f"step_{step}")
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, jax.Array) else x,
        state_like,
    )
    return _ckptr().restore(path, abstract)


class AutoResume:
    """ADLR AutoResume-shaped hook (reference testing/global_vars.py:156):
    a scheduler writes ``termination_file`` to request
    checkpoint-and-requeue; the training loop polls ``termination_requested``
    and calls ``request_resume`` after saving."""

    def __init__(self, termination_file: Optional[str] = None):
        self.termination_file = termination_file or os.environ.get(
            "APEX_TPU_TERMINATION_FILE", "")

    def init(self):
        return self

    def termination_requested(self) -> bool:
        return bool(self.termination_file) and os.path.exists(
            self.termination_file)

    def request_resume(self):
        if self.termination_file and os.path.exists(self.termination_file):
            os.unlink(self.termination_file)
