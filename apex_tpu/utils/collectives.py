"""Collective helpers aware of SPMD autodiff semantics.

Under ``jax.shard_map`` with varying-axes tracking (jax ≥0.9), gradients
taken w.r.t. *replicated* (axis-invariant) parameters are ALREADY summed
over the mapped axis — the transpose of the implicit broadcast inserts the
psum. A DDP layer that blindly psums again double-counts (verified on the
8-device mesh: explicit psum after jax.grad yields 8× gradients).

These helpers consult ``jax.typeof(x).vma`` (the set of mesh axes a value
varies over) to apply a collective only when the value is still
shard-varying, and a plain division when SPMD-AD has pre-summed.

The ``collectives.*`` counters these helpers book are load-bearing
beyond dashboards: the Tier-B jaxpr auditor
(``apex_tpu/analysis/jaxpr_audit.py``, gated by the ``static_audit``
dryrun phase) diffs them against a census of the collective equations
that actually landed in each entry point's jaxpr — a collective
emitted around these wrappers shows up as accounting drift and fails
CI.  New comm paths must route through this module (or the
ring/compressed wrappers built on it), not bind ``jax.lax``
collectives directly.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from apex_tpu.observability import metrics as _telemetry

__all__ = [
    "is_varying",
    "grad_mean",
    "grad_sum",
    "flag_and",
    "flag_or",
    "match_vma",
    "pvary",
    "vma_of",
    "all_gather",
    "all_to_all",
    "ppermute",
    "psum_scatter",
]


def _note_collective(kind: str, x) -> None:
    """Count a collective about to be emitted: ``collectives.<kind>.calls``
    and ``collectives.<kind>.bytes`` (abstract shape x itemsize).

    Trace-time accounting — these helpers run while the enclosing
    jit/shard_map traces, so counts are per collective *emitted into
    the compiled program* (once per trace), not per executed step;
    host-callback-free by construction.  One enabled() check when
    telemetry is off.
    """
    reg = _telemetry.registry()
    if reg is None:
        return
    dtype = getattr(x, "dtype", None)
    nbytes = 0
    if dtype is not None:
        nbytes = int(math.prod(getattr(x, "shape", ()) or ())
                     ) * dtype.itemsize
    reg.counter(f"collectives.{kind}.calls").inc()
    reg.counter(f"collectives.{kind}.bytes").inc(nbytes)


def pvary(tree, axis_name: str):
    """Type values as varying over ``axis_name`` (jax≥0.9 vma typing).

    No-op for leaves already varying or outside a mapped context (used
    by the TP mappings and the pipeline scan carries, where the target
    is one known axis).  When the target is a *set* of axes derived from
    another value, use :func:`match_vma` + :func:`vma_of` instead.
    """

    def leaf(v):
        if is_varying(v, axis_name):
            return v
        try:
            return jax.lax.pcast(v, axis_name, to="varying")
        except NameError:
            # axis not bound (outside shard_map) — nothing to type
            return v

    return jax.tree_util.tree_map(leaf, tree)


def vma_of(x) -> tuple:
    """The manual axes ``x`` is typed as varying over (empty outside
    shard_map / for untyped tracers)."""
    return tuple(getattr(jax.typeof(x), "vma", ()) or ())


def match_vma(tree, axes):
    """Promote every leaf to vary over each of ``axes`` it doesn't
    already — the one home for the pcast-to-varying dance when a target
    vma set is known (fresh constants entering a lax.switch/scan next to
    shard_map-varying operands, Pallas calls with mixed-vma inputs)."""
    axes = tuple(axes)
    if not axes:
        return tree

    def leaf(v):
        have = set(vma_of(v))
        missing = tuple(a for a in axes if a not in have)
        return jax.lax.pcast(v, missing, to="varying") if missing else v

    return jax.tree_util.tree_map(leaf, tree)


def is_varying(x, axis_name: str) -> bool:
    """True if ``x`` still differs across shards of ``axis_name``."""
    try:
        return axis_name in jax.typeof(x).vma
    except AttributeError:
        # Outside shard_map / older tracer: assume varying (legacy pmap
        # semantics) — callers get an explicit collective.
        return True


def grad_sum(tree: Any, axis_name: str) -> Any:
    """Sum grads over the axis (no-op when SPMD-AD already summed)."""

    def red(g):
        if not hasattr(g, "dtype") or not jnp.issubdtype(g.dtype, jnp.inexact):
            return g
        if is_varying(g, axis_name):
            _note_collective("psum", g)
            return jax.lax.psum(g, axis_name)
        return g

    return jax.tree_util.tree_map(red, tree)


def grad_mean(tree: Any, axis_name: str) -> Any:
    """Average grads over the axis, whether or not they were pre-summed."""
    n = jax.lax.axis_size(axis_name)

    def red(g):
        if not hasattr(g, "dtype") or not jnp.issubdtype(g.dtype, jnp.inexact):
            return g
        if is_varying(g, axis_name):
            _note_collective("pmean", g)
            return jax.lax.pmean(g, axis_name)
        return g / n

    return jax.tree_util.tree_map(red, tree)


def flag_and(flag, axis_name: str):
    """AND a boolean flag across shards (found-inf combining)."""
    if is_varying(flag, axis_name):
        _note_collective("pmin", flag)
        return jax.lax.pmin(flag.astype(jnp.int32), axis_name) > 0
    return flag


def flag_or(flag, axis_name: str):
    if is_varying(flag, axis_name):
        _note_collective("pmax", flag)
        return jax.lax.pmax(flag.astype(jnp.int32), axis_name) > 0
    return flag


# ---- counted pass-throughs for the non-psum collective family -------------
# The psum/pmean/pmin/pmax helpers above count themselves; everything the
# comm/ and ring paths emit (all_gather, all_to_all, ppermute,
# psum_scatter) was invisible to collectives.* until these wrappers.
# ``bytes`` counts what THIS rank puts on the wire per emitted collective:
# the full local operand (trace-time accounting, like _note_collective).


def all_gather(x, axis_name: str, *, axis: int = 0, tiled: bool = False):
    """Counted ``jax.lax.all_gather`` → ``collectives.all_gather.*``."""
    _note_collective("all_gather", x)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int, *,
               tiled: bool = False):
    """Counted ``jax.lax.all_to_all`` → ``collectives.all_to_all.*``."""
    _note_collective("all_to_all", x)
    return jax.lax.all_to_all(x, axis_name, split_axis, concat_axis,
                              tiled=tiled)


def ppermute(x, axis_name: str, perm):
    """Counted ``jax.lax.ppermute`` → ``collectives.ppermute.*``."""
    _note_collective("ppermute", x)
    return jax.lax.ppermute(x, axis_name, perm)


def psum_scatter(x, axis_name: str, *, scatter_dimension: int = 0,
                 tiled: bool = False):
    """Counted ``jax.lax.psum_scatter`` → ``collectives.psum_scatter.*``."""
    _note_collective("psum_scatter", x)
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=tiled)
