"""Rank-annotated logging.

TPU-native analog of the reference's library-root logger with
``RankInfoFormatter`` (reference apex/__init__.py:27-39) and the transformer
log utilities (reference apex/transformer/log_util.py). Rank info comes from
``jax.process_index`` instead of torch.distributed, and — when a mesh-based
model-parallel state is initialized — from
``apex_tpu.transformer.parallel_state.get_rank_info``.
"""

from __future__ import annotations

import logging
import sys

_LOGGER_NAME = "apex_tpu"


class RankInfoFormatter(logging.Formatter):
    """Prepends (host rank / mp rank info) to every record when available."""

    def format(self, record):
        rank_info = ""
        try:
            import jax

            # Cheap: process_index does not touch devices.
            rank_info = f"[host {jax.process_index()}/{jax.process_count()}]"
        except Exception:
            pass
        try:
            from apex_tpu.transformer import parallel_state

            if parallel_state.model_parallel_is_initialized():
                rank_info += str(parallel_state.get_rank_info())
        except Exception:
            pass
        record.rank_info = rank_info
        return super().format(record)


def _build_root_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            RankInfoFormatter(
                "%(asctime)s %(levelname)s %(rank_info)s %(name)s: %(message)s"
            )
        )
        logger.addHandler(handler)
        logger.setLevel(logging.WARNING)
        logger.propagate = False
    return logger


_ROOT = _build_root_logger()


def get_logger(name: str | None = None) -> logging.Logger:
    if name is None:
        return _ROOT
    return _ROOT.getChild(name)


def set_logging_level(level) -> None:
    """reference apex/transformer/log_util.py:set_logging_level analog."""
    _ROOT.setLevel(level)


def print_rank_0(message: str) -> None:
    """Print only on process 0 (reference pipeline_parallel/utils.py:159).

    Guarded the way ``RankInfoFormatter.format`` already is: with no
    reachable JAX backend (``jax.process_index`` raising mid-init or on
    a dead tunnel) this degrades to printing instead of raising from
    inside a log call.
    """
    try:
        import jax

        rank = jax.process_index()
    except Exception:
        rank = 0
    if rank == 0:
        print(message, flush=True)
