from apex_tpu.utils.logging import get_logger, set_logging_level  # noqa: F401
from apex_tpu.utils.registry import (  # noqa: F401
    OpImpl,
    OpRegistry,
    get_op,
    registry,
    register_op,
)
