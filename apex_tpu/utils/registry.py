"""Op registry — the TPU analog of the reference's extension build/loader layer.

The reference discovers 31 per-op builders into an ``ALL_OPS`` dict
(op_builder/all_ops.py:87), JIT-compiles CUDA/HIP on first use with capability
checks (op_builder/builder.py:527,614-660), and routes ``import amp_C``-style
modules through lazy shims. On TPU no ninja/nvcc step exists — Pallas kernels
and XLA graphs compile through jit — so the layer collapses into this registry:

- named ops, each with one or more *implementations* per backend
  (``pallas`` — Mosaic TPU kernel; ``xla`` — pure jnp/lax composition that XLA
  fuses; ``ref`` — unfused numpy-like reference used in tests),
- capability predicates per implementation (platform, dtype, shape
  constraints) replacing compute-capability probing,
- environment overrides (``APEX_TPU_BACKEND``, ``APEX_TPU_DISABLE_<OP>``)
  replacing the reference's ``APEX_BUILD_<OP>`` gates (setup.py:166-181),
- the jax persistent compilation cache standing in for the AOT build cache.

Usage::

    @register_op("fused_layer_norm", backend="pallas",
                 is_available=lambda: default_backend() == "tpu")
    def _ln_pallas(...): ...

    @register_op("fused_layer_norm", backend="xla")
    def _ln_xla(...): ...

    fn = get_op("fused_layer_norm")   # best available implementation
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Callable, Dict, List, Optional

__all__ = [
    "OpImpl",
    "OpRegistry",
    "registry",
    "register_op",
    "get_op",
    "available_ops",
    "default_backend",
]

# Preference order when the user does not force a backend.
_BACKEND_PRIORITY = {"pallas": 0, "xla": 1, "ref": 2}


@functools.lru_cache(maxsize=None)
def default_backend() -> str:
    """The active jax platform ('tpu', 'cpu', 'gpu')."""
    import jax

    return jax.default_backend()


def on_tpu() -> bool:
    return default_backend() == "tpu"


@dataclasses.dataclass
class OpImpl:
    name: str
    backend: str
    fn: Callable
    is_available: Callable[[], bool]

    def available(self) -> bool:
        if os.environ.get(f"APEX_TPU_DISABLE_{self.name.upper()}", "0") == "1":
            return False
        try:
            return bool(self.is_available())
        except Exception:
            return False


class OpRegistry:
    def __init__(self) -> None:
        self._ops: Dict[str, List[OpImpl]] = {}

    def register(
        self,
        name: str,
        backend: str,
        fn: Callable,
        is_available: Optional[Callable[[], bool]] = None,
    ) -> None:
        if backend not in _BACKEND_PRIORITY:
            raise ValueError(
                f"unknown backend {backend!r}; expected one of "
                f"{sorted(_BACKEND_PRIORITY)}"
            )
        if is_available is None:
            # Pallas kernels need a real TPU unless interpret mode is forced.
            if backend == "pallas":
                is_available = lambda: (  # noqa: E731
                    on_tpu()
                    or os.environ.get("APEX_TPU_PALLAS_INTERPRET", "0") == "1"
                )
            else:
                is_available = lambda: True  # noqa: E731
        impls = self._ops.setdefault(name, [])
        impls[:] = [i for i in impls if i.backend != backend]
        impls.append(OpImpl(name, backend, fn, is_available))
        impls.sort(key=lambda i: _BACKEND_PRIORITY[i.backend])

    def get(self, name: str, backend: Optional[str] = None) -> Callable:
        """Resolve the best available implementation of ``name``.

        ``backend`` (or the ``APEX_TPU_BACKEND`` env var) forces a specific
        implementation; otherwise the highest-priority available one wins.
        """
        if name not in self._ops:
            raise KeyError(
                f"op {name!r} is not registered; known ops: "
                f"{sorted(self._ops)}"
            )
        forced = backend or os.environ.get("APEX_TPU_BACKEND") or None
        for impl in self._ops[name]:
            if forced is not None and impl.backend != forced:
                continue
            if impl.available():
                return impl.fn
        raise RuntimeError(
            f"no available implementation for op {name!r}"
            + (f" with backend={forced!r}" if forced else "")
            + f"; registered: {[i.backend for i in self._ops[name]]}"
        )

    def impls(self, name: str) -> List[OpImpl]:
        return list(self._ops.get(name, []))

    def names(self) -> List[str]:
        return sorted(self._ops)


registry = OpRegistry()


def register_op(
    name: str,
    backend: str = "xla",
    is_available: Optional[Callable[[], bool]] = None,
):
    """Decorator form of ``registry.register``."""

    def deco(fn: Callable) -> Callable:
        registry.register(name, backend, fn, is_available)
        return fn

    return deco


def get_op(name: str, backend: Optional[str] = None) -> Callable:
    return registry.get(name, backend)


def available_ops() -> Dict[str, List[str]]:
    """Report, per op, which backends are currently usable.

    Plays the role of the reference's installed-ops report
    (apex/git_version_info.py:11-27).
    """
    return {
        name: [i.backend for i in registry.impls(name) if i.available()]
        for name in registry.names()
    }
