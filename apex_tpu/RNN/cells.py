"""Recurrent cells (RNN/LSTM/GRU/mLSTM).

Reference: apex/RNN/RNNBackend.py (``RNNCell`` :232 — a generic cell with
``gate_multiplier`` × hidden gates and a nonlinearity; LSTMCell/GRUCell in
cells.py; mLSTM from "Multiplicative LSTM for sequence modelling",
Krause et al. 2016 — apex/RNN/models.py:19). The reference marks the whole
package "under construction" (apex/RNN/README.md:1); this port completes
the same surface functionally: pure cell functions + init, composed by
``runner.run_rnn`` with lax.scan.

Gate layouts follow torch convention (i, f, g, o for LSTM; r, z, n for
GRU) so ported weights drop in.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "init_cell_params",
    "rnn_relu_cell",
    "rnn_tanh_cell",
    "lstm_cell",
    "gru_cell",
    "mlstm_cell",
]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3, "mlstm": 4}


def init_cell_params(rng: jax.Array, cell: str, input_size: int,
                     hidden_size: int, dtype=jnp.float32) -> dict:
    """Uniform(-1/sqrt(h), 1/sqrt(h)) like torch RNN init."""
    g = _GATES[cell]
    k = 1.0 / hidden_size ** 0.5
    ks = jax.random.split(rng, 6)

    def u(key, shape):
        return jax.random.uniform(key, shape, dtype, -k, k)

    p = {
        "w_ih": u(ks[0], (input_size, g * hidden_size)),
        "w_hh": u(ks[1], (hidden_size, g * hidden_size)),
        "b_ih": u(ks[2], (g * hidden_size,)),
        "b_hh": u(ks[3], (g * hidden_size,)),
    }
    if cell == "mlstm":
        # multiplicative intermediate state m = (x W_mx) ⊙ (h W_mh)
        p["w_mx"] = u(ks[4], (input_size, hidden_size))
        p["w_mh"] = u(ks[5], (hidden_size, hidden_size))
    return p


def _gates(p, x, h):
    return (x @ p["w_ih"] + p["b_ih"]) + (h @ p["w_hh"] + p["b_hh"])


def rnn_relu_cell(p, state, x):
    h = jax.nn.relu(_gates(p, x, state[0]))
    return (h,), h


def rnn_tanh_cell(p, state, x):
    h = jnp.tanh(_gates(p, x, state[0]))
    return (h,), h


def lstm_cell(p, state, x):
    h, c = state
    i, f, g, o = jnp.split(_gates(p, x, h), 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    c = f * c + i * jnp.tanh(g)
    h = o * jnp.tanh(c)
    return (h, c), h


def gru_cell(p, state, x):
    h = state[0]
    xg = x @ p["w_ih"] + p["b_ih"]
    hg = h @ p["w_hh"] + p["b_hh"]
    xr, xz, xn = jnp.split(xg, 3, axis=-1)
    hr, hz, hn = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    h = (1.0 - z) * n + z * h
    return (h,), h


def mlstm_cell(p, state, x):
    """Multiplicative LSTM (reference mLSTMRNNCell, RNNBackend.py +
    models.py:19): the hidden fed to the gates is the multiplicative
    state m = (x W_mx) ⊙ (h W_mh)."""
    h, c = state
    m = (x @ p["w_mx"]) * (h @ p["w_mh"])
    i, f, g, o = jnp.split(
        (x @ p["w_ih"] + p["b_ih"]) + (m @ p["w_hh"] + p["b_hh"]), 4,
        axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    c = f * c + i * jnp.tanh(g)
    h = o * jnp.tanh(c)
    return (h, c), h


CELLS = {
    "rnn_relu": rnn_relu_cell,
    "rnn_tanh": rnn_tanh_cell,
    "lstm": lstm_cell,
    "gru": gru_cell,
    "mlstm": mlstm_cell,
}


def zero_state(cell: str, batch: int, hidden: int, dtype) -> Tuple:
    h = jnp.zeros((batch, hidden), dtype)
    if cell in ("lstm", "mlstm"):
        return (h, jnp.zeros((batch, hidden), dtype))
    return (h,)
