"""RNN model factories.

Reference: apex/RNN/models.py (``RNN`` :47 dispatching on nonlinearity,
``LSTM`` :19, ``GRU`` :26, ``mLSTM`` :33). Factories return a lightweight
module holding params + config, callable on [T, B, D] sequences.
"""

from __future__ import annotations

from typing import Optional

import jax

from apex_tpu.RNN.runner import init_rnn_params, run_rnn

__all__ = ["RNN", "LSTM", "GRU", "mLSTM"]


class _RNNModule:
    def __init__(self, cell: str, input_size: int, hidden_size: int,
                 num_layers: int = 1, bidirectional: bool = False,
                 dropout: float = 0.0):
        self.cell = cell
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirectional = bidirectional
        self.dropout = dropout

    def init(self, rng: jax.Array, dtype=None):
        import jax.numpy as jnp

        return init_rnn_params(
            rng, self.cell, self.input_size, self.hidden_size,
            self.num_layers, self.bidirectional, dtype or jnp.float32)

    def __call__(self, params, x, *, initial_states=None,
                 dropout_rng: Optional[jax.Array] = None):
        return run_rnn(
            params, x, self.cell, bidirectional=self.bidirectional,
            dropout=self.dropout, dropout_rng=dropout_rng,
            initial_states=initial_states)


def RNN(input_size, hidden_size, num_layers=1, nonlinearity="tanh",
        bidirectional=False, dropout=0.0) -> _RNNModule:
    """reference models.py:47 — nonlinearity 'tanh' | 'relu'."""
    cell = {"tanh": "rnn_tanh", "relu": "rnn_relu"}[nonlinearity]
    return _RNNModule(cell, input_size, hidden_size, num_layers,
                      bidirectional, dropout)


def LSTM(input_size, hidden_size, num_layers=1, bidirectional=False,
         dropout=0.0) -> _RNNModule:
    return _RNNModule("lstm", input_size, hidden_size, num_layers,
                      bidirectional, dropout)


def GRU(input_size, hidden_size, num_layers=1, bidirectional=False,
        dropout=0.0) -> _RNNModule:
    return _RNNModule("gru", input_size, hidden_size, num_layers,
                      bidirectional, dropout)


def mLSTM(input_size, hidden_size, num_layers=1, dropout=0.0) -> _RNNModule:
    """Multiplicative LSTM (reference models.py:33; no bidirectional
    variant upstream either)."""
    return _RNNModule("mlstm", input_size, hidden_size, num_layers,
                      False, dropout)
