from apex_tpu.RNN.models import GRU, LSTM, RNN, mLSTM  # noqa: F401
from apex_tpu.RNN.cells import (  # noqa: F401
    gru_cell,
    init_cell_params,
    lstm_cell,
    mlstm_cell,
    rnn_relu_cell,
    rnn_tanh_cell,
)
from apex_tpu.RNN.runner import run_rnn  # noqa: F401
