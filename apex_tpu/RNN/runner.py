"""Stacked / bidirectional RNN runner.

Reference: apex/RNN/RNNBackend.py — ``stackedRNN`` :90 (layer stack with
inter-layer dropout), ``bidirectionalRNN`` :25 (fwd + reversed-bwd concat).
Here one function drives any cell with ``lax.scan`` over time (the
compiler-friendly control flow the reference's Python loop over timesteps
can't give XLA).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.RNN.cells import CELLS, init_cell_params, zero_state

__all__ = ["init_rnn_params", "run_rnn"]


def init_rnn_params(rng, cell: str, input_size: int, hidden_size: int,
                    num_layers: int = 1, bidirectional: bool = False,
                    dtype=jnp.float32) -> list:
    dirs = 2 if bidirectional else 1
    layers = []
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else hidden_size * dirs
        per_dir = []
        for _ in range(dirs):
            rng, k = jax.random.split(rng)
            per_dir.append(init_cell_params(k, cell, in_sz, hidden_size,
                                            dtype))
        layers.append(per_dir)
    return layers


def run_rnn(
    params: list,
    x: jax.Array,
    cell: str = "lstm",
    *,
    bidirectional: bool = False,
    dropout: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    initial_states: Optional[list] = None,
):
    """x [T, B, D] → (outputs [T, B, H·dirs], final_states).

    Layout matches the reference (seq-first, RNNBackend.py:107).
    ``initial_states[layer][direction]`` defaults to zeros.
    """
    cell_fn = CELLS[cell]
    T, B, _ = x.shape
    hidden = params[0][0]["w_hh"].shape[0]
    finals = []

    def scan_dir(p, seq, state0):
        def step(state, xt):
            return cell_fn(p, state, xt)

        return jax.lax.scan(step, state0, seq)

    h = x
    for li, layer in enumerate(params):
        outs = []
        layer_finals = []
        for di, p in enumerate(layer):
            seq = h if di == 0 else jnp.flip(h, axis=0)
            s0 = (initial_states[li][di] if initial_states is not None
                  else zero_state(cell, B, hidden, h.dtype))
            final, ys = scan_dir(p, seq, s0)
            if di == 1:
                ys = jnp.flip(ys, axis=0)
            outs.append(ys)
            layer_finals.append(final)
        h = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=-1)
        finals.append(layer_finals)
        if dropout > 0.0 and dropout_rng is not None \
                and li < len(params) - 1:
            dropout_rng, k = jax.random.split(dropout_rng)
            keep = jax.random.bernoulli(k, 1.0 - dropout, h.shape)
            h = jnp.where(keep, h / (1.0 - dropout), 0.0)
    return h, finals
