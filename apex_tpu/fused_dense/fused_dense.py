"""FusedDense / FusedDenseGeluDense modules
(reference apex/fused_dense/fused_dense.py:8,102)."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.dense import (  # noqa: F401  (re-exported API surface)
    fused_dense_function,
    fused_dense_gelu_dense_function,
)

__all__ = [
    "FusedDense",
    "FusedDenseGeluDense",
    "fused_dense_function",
    "fused_dense_gelu_dense_function",
]


class FusedDense(nn.Module):
    """Linear + bias in one fused op (reference FusedDense)."""

    in_features: int
    out_features: int
    bias: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (self.in_features, self.out_features),
            jnp.float32,
        )
        b = (
            self.param("bias", nn.initializers.zeros,
                       (self.out_features,), jnp.float32)
            if self.bias
            else None
        )
        return fused_dense_function(
            x, kernel.astype(x.dtype), None if b is None else b
        )


class FusedDenseGeluDense(nn.Module):
    """Linear+bias+GELU+Linear+bias (reference FusedDenseGeluDense)."""

    in_features: int
    intermediate_features: int
    out_features: int
    bias: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        k1 = self.param(
            "kernel1", nn.initializers.lecun_normal(),
            (self.in_features, self.intermediate_features), jnp.float32,
        )
        k2 = self.param(
            "kernel2", nn.initializers.lecun_normal(),
            (self.intermediate_features, self.out_features), jnp.float32,
        )
        b1 = b2 = None
        if self.bias:
            b1 = self.param("bias1", nn.initializers.zeros,
                            (self.intermediate_features,), jnp.float32)
            b2 = self.param("bias2", nn.initializers.zeros,
                            (self.out_features,), jnp.float32)
        return fused_dense_gelu_dense_function(
            x, k1.astype(x.dtype), b1, k2.astype(x.dtype), b2
        )
