"""GPT model wiring: GSPMD train step + shard_map pipeline stages.

Reference analogs: apex/transformer/testing/standalone_gpt.py (``GPTModel``
:45, ``gpt_model_provider`` :33) and the minimal train loops in
tests/L0/run_transformer/run_gpt_minimal_test.py. Two composition modes:

- :func:`make_gpt_train_step` — GSPMD: one jitted AMP train step over a
  ('pp','dp','sp','tp') mesh; dp+tp+sp come from sharding annotations
  (pp stays 1 on this path).
- :func:`make_gpt_pipeline_stage` / :func:`stack_pipeline_params` — the
  shard_map path: the decoder is cut into ``pp`` stages driven by the
  differentiable-scan schedules (pipeline_parallel/schedules.py), tensor
  parallelism via the manual mapping collectives inside each stage.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.amp.frontend import make_train_step
from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.transformer_lm import (
    apply_norm,
    gpt_loss,
    gpt_param_specs,
    gspmd_ctx,
    init_gpt_params,
    lm_cross_entropy,
    manual_ctx,
    single_device_ctx,
    transformer_backbone,
    embed_tokens,
    lm_head_logits,
)

__all__ = [
    "make_gpt_train_step",
    "make_gpt_pipeline_stage",
    "stack_pipeline_params",
    "stack_pipeline_params_vpp",
    "make_gpt_vpp_stage",
    "pipeline_packet",
    "gpt_pipeline_loss_and_grads",
    "gpt_vpp_loss_and_grads",
]


def make_gpt_train_step(
    cfg: TransformerConfig,
    optimizer: Any,
    policy_or_amp="O2",
    mesh: Optional[Mesh] = None,
    *,
    seq_axis: Optional[str] = None,
    context_parallel: Union[bool, str] = False,
    grad_postprocess: Optional[Callable] = None,
    fsdp: bool = False,
    norm_telemetry: bool = False,
    overlap_comm: Optional[bool] = None,
):
    """GSPMD data/tensor/sequence-parallel AMP train step.

    Returns ``(init_fn, step_fn)``; both are jitted against ``mesh`` when
    given. ``init_fn(rng)`` places params per :func:`gpt_param_specs`;
    ``step_fn(state, tokens, labels)`` is the full O2-style AMP step
    (scale → grad → unscale+finite-check → fused update → skip-on-overflow)
    with gradient mean over 'dp' handled by GSPMD sharding propagation.

    ``fsdp=True`` (ZeRO-3) additionally shards every parameter — and,
    through the state pytree, its fp32 master and optimizer moments —
    over the 'dp' axis on top of the tp specs (parallel/fsdp.py
    ``fsdp_augment_specs``); GSPMD inserts the per-layer all-gathers and
    backward reduce-scatters.  Beyond the reference: apex stops at
    ZeRO-2 (DistributedFusedAdam's optimizer-state sharding).

    Batch signature grows with the config: ``attn_mask_type='padding'``
    appends an ``attention_mask`` (True = masked) element, dropout appends
    a PRNG key — ``step(state, tokens, labels[, mask][, rng])``.

    ``context_parallel`` (requires ``seq_axis``) keeps core attention
    sequence-sharded — the long-context mode.  ``True``/``"ring"``
    selects ring attention (per-device attention memory O(s_local));
    ``"ulysses"`` selects all-to-all head re-sharding (one
    full-sequence flash call per head group; needs heads divisible by
    the axis size).  Both cover the flagship patterns only:
    ``attn_mask_type='padding'`` and ``attention_dropout > 0`` are
    rejected up front (they would silently fall back to the gathered
    path and OOM at exactly the lengths the flag exists for);
    ``hidden_dropout`` is fine.

    ``overlap_comm=True`` routes the tensor-parallel row-parallel exits
    (attention proj, MLP fc2) through the ring collective-matmul
    (``ops/collective_matmul``): the tp reduction is decomposed into
    ppermute hops overlapped with per-shard matmul chunks instead of one
    serialized all-reduce after the matmul.  Default ``None`` keeps the
    monolithic collectives unless an enclosing
    ``collective_matmul.overlap_scope`` turns the ring on.

    MoE configs (``cfg.num_experts``) additionally honor
    ``cfg.moe_routing``/``cfg.moe_comm``: ``moe_routing='ragged'`` makes
    every expert layer capacity-free (no dropped tokens, no pad slots)
    with its EP dispatch/combine running explicitly through the counted
    ``all_to_all`` wrappers at ``moe_comm`` wire precision — and the
    same ``overlap_comm`` scope that rings the TP exits also rings the
    expert dispatch/combine (per-hop expert compute inside the ring).
    """
    if context_parallel:
        if cfg.attn_mask_type == "padding":
            raise ValueError(
                "context_parallel does not support "
                "attn_mask_type='padding': the ring kernels have no "
                "sharded-mask path, so masked configs would silently "
                "gather K/V (O(s_global) memory). Pack sequences with "
                "segment-free causal rows instead.")
        if cfg.attention_dropout > 0:
            raise ValueError(
                "context_parallel does not support attention_dropout "
                "> 0 (the sequence-sharded attention paths run without "
                "in-kernel dropout); set attention_dropout=0 — "
                "hidden_dropout is unaffected.")
        if context_parallel == "ulysses" and mesh is not None:
            axes = dict(zip(mesh.axis_names, mesh.devices.shape))
            sp_size = axes.get(seq_axis, 1) if seq_axis else 1
            tp_size = axes.get("tp", 1)
            heads = cfg.num_attention_heads
            if heads % tp_size or (heads // tp_size) % sp_size:
                raise ValueError(
                    f"context_parallel='ulysses' needs num_attention_"
                    f"heads ({heads}) divisible by tp ({tp_size}) and "
                    f"the per-tp-rank heads ({heads // max(tp_size, 1)}) "
                    f"divisible by the '{seq_axis}' axis size "
                    f"({sp_size}); use context_parallel='ring' for "
                    "head counts that don't factor.")
    ctx = (gspmd_ctx(seq_axis=seq_axis,
                     context_parallel=context_parallel,
                     overlap_comm=overlap_comm)
           if mesh is not None else None)
    has_dropout = (cfg.hidden_dropout > 0 or cfg.attention_dropout > 0
                   or cfg.drop_path_rate > 0)
    has_mask = cfg.attn_mask_type == "padding"

    def loss_fn(params, tokens, labels, *rest):
        rest = list(rest)
        mask = rest.pop(0) if has_mask else None
        rng = rest.pop(0) if has_dropout else None
        return gpt_loss(params, tokens, labels, cfg, ctx,
                        attention_mask=mask, dropout_rng=rng)

    init_fn, step_fn = make_train_step(
        loss_fn, optimizer, policy_or_amp,
        grad_postprocess=grad_postprocess,
        norm_telemetry=norm_telemetry,
        overlap_comm=overlap_comm,
    )

    def init(rng):
        params = init_gpt_params(rng, cfg)
        if mesh is not None:
            specs = gpt_param_specs(cfg)
            if fsdp:
                from apex_tpu.parallel.fsdp import fsdp_augment_specs

                axes = dict(zip(mesh.axis_names, mesh.devices.shape))
                if "dp" not in axes:
                    raise ValueError(
                        "make_gpt_train_step(fsdp=True) shards master "
                        "params over the 'dp' mesh axis, but this mesh "
                        f"has axes {tuple(mesh.axis_names)}; add a 'dp' "
                        "axis (e.g. create_mesh(dp=N)).")
                ndev = axes["dp"]
                specs = fsdp_augment_specs(specs, params, ndev)
            params = jax.device_put(
                params,
                jax.tree_util.tree_map(
                    lambda s: NamedSharding(mesh, s), specs,
                    is_leaf=lambda x: isinstance(x, P),
                ),
            )
            state = init_fn(params)
            if fsdp:
                # The optimizer moments and fp32 masters are created as
                # fresh (replicated) arrays.  Every state subtree that
                # mirrors the params structure (masters, bf16 copies,
                # each Adam moment tree) is re-placed on the params'
                # shardings — matched by tree structure, not by array
                # shape, so equal-shape params with different specs
                # cannot collide.
                shardings = jax.tree_util.tree_map(
                    lambda s: NamedSharding(mesh, s), specs,
                    is_leaf=lambda x: isinstance(x, P))
                pstruct = jax.tree_util.tree_structure(params)

                def matches(sub):
                    try:
                        return (jax.tree_util.tree_structure(sub)
                                == pstruct)
                    except Exception:
                        return False

                replicated = NamedSharding(mesh, P())

                def place(sub):
                    if matches(sub):
                        return jax.device_put(sub, shardings)
                    if isinstance(sub, jax.Array) and sub.ndim == 0:
                        # scalar state ONLY (step counter, loss scale):
                        # explicitly mesh-replicated, so checkpoint
                        # restore cannot pin it to one device while the
                        # masters span the mesh.  Non-scalar arrays in
                        # exotic optimizer-state structures are left
                        # alone — force-replicating a param-sized moment
                        # buffer would silently defeat ZeRO-3.
                        return jax.device_put(sub, replicated)
                    return sub

                state = jax.tree_util.tree_map(
                    place, state, is_leaf=matches)
            return state
        return init_fn(params)

    if mesh is None:
        return init, jax.jit(step_fn, donate_argnums=0)

    batch_sharding = NamedSharding(mesh, P("dp", seq_axis))
    shardings = (None, batch_sharding, batch_sharding)
    if has_mask:
        # (b, 1, sq, sk) or (b, sq, sk) boolean padding mask
        shardings = shardings + (NamedSharding(mesh, P("dp")),)
    if has_dropout:
        shardings = shardings + (NamedSharding(mesh, P()),)
    jstep = jax.jit(step_fn, in_shardings=shardings, donate_argnums=0)

    def step(state, *batch):
        # the mesh context activates the model's with_sharding_constraint
        # annotations (bare PartitionSpecs need an ambient mesh)
        with jax.set_mesh(mesh):
            return jstep(state, *batch)

    return init, step


# ---------------------------------------------------------------------------
# shard_map pipeline path
# ---------------------------------------------------------------------------


def pipeline_packet(tokens_mb: jax.Array, labels_mb: jax.Array,
                    cfg: TransformerConfig, *,
                    attention_mask_mb: Optional[jax.Array] = None,
                    dropout_seeds: Optional[jax.Array] = None) -> dict:
    """The activation packet ppermuted between stages.

    The schedules require one uniform pytree for injection and transfer
    (schedules.py ``pipeline_forward``), so token/label ids ride alongside
    the hidden activation and the last stage banks its per-microbatch loss
    in the ``loss`` slot. [n_micro, mb, s] token arrays → packets of
    hidden [mb, s, h].

    ``attention_mask_mb`` ([n_micro, mb, s] bool, True = masked key) rides
    in the packet when the model needs padding masks
    (cfg.attn_mask_type == 'padding' — BERT-style).  ``dropout_seeds``
    ([n_micro] int32) seeds per-microbatch dropout; each stage folds its
    own pp index in so no two (stage, microbatch) pairs share a stream —
    the pipeline analog of the reference's per-region RNG tracker
    (tensor_parallel/random.py CudaRNGStatesTracker).
    """
    mb, s = tokens_mb.shape[-2], tokens_mb.shape[-1]
    packet = {
        "hidden": jnp.zeros((*tokens_mb.shape[:-2], mb, s, cfg.hidden_size),
                            cfg.compute_dtype),
        "tokens": tokens_mb,
        "labels": labels_mb,
        "loss": jnp.zeros(tokens_mb.shape[:-2], jnp.float32),
    }
    if cfg.num_experts:
        # running MoE load-balance aux: every stage adds its layers'
        # contribution as the packet rides the pipeline; the last stage
        # folds it into the loss (gpt_loss semantics)
        packet["aux"] = jnp.zeros(tokens_mb.shape[:-2], jnp.float32)
    if attention_mask_mb is not None:
        packet["attention_mask"] = attention_mask_mb
    if dropout_seeds is not None:
        packet["dropout_seed"] = dropout_seeds.astype(jnp.int32)
    return packet


def stack_pipeline_params(params: dict, cfg: TransformerConfig,
                          n_stages: int) -> dict:
    """Cut the layer stack into ``n_stages`` chunks with a leading pp axis.

    Embedding / final-LN / head stay unstacked (replicated across pp via
    ``in_specs=P()``; shard_map's AD psums their grads, and only the stages
    that consume them contribute non-zeros — the reference ties embeddings
    with an explicit embedding-group allreduce instead,
    standalone_transformer_lm.py:49 ``MegatronModule.word_embeddings_weight``).
    """
    L = cfg.num_layers
    if L % n_stages:
        raise ValueError(f"num_layers {L} not divisible by pp {n_stages}")
    per = L // n_stages
    layers = jax.tree_util.tree_map(
        lambda v: v.reshape((n_stages, per) + v.shape[1:]), params["layers"])
    out = dict(params)
    out["layers"] = layers
    return out


def gpt_pipeline_loss_and_grads(
    stage_fn: Callable,
    stacked_params: dict,
    packets: dict,
    *,
    n_micro: int,
    pp_axis: str = "pp",
    remat: bool = True,
):
    """Run the 1F1B scan schedule on GPT stage params; call inside shard_map.

    Non-layer params (embedding, final LN, LM head) are replicated across
    'pp'; they are marked pp-varying for the scan schedule's carry typing
    and their gradients psum'd afterwards — the explicit form of the
    reference's embedding-group allreduce
    (apex/transformer/parallel_state.py:184-310 _EMBEDDING_GROUP;
    standalone_transformer_lm.py:49 shared word_embeddings_weight).
    """
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_without_interleaving,
    )
    from apex_tpu.utils.collectives import pvary

    varying = pvary(stacked_params, pp_axis)
    loss, grads = forward_backward_pipelining_without_interleaving(
        stage_fn, packets, varying,
        n_micro=n_micro,
        loss_fn=lambda out, _mb: out["loss"],
        axis=pp_axis,
        remat=remat,
    )
    grads = {
        k: (v if k == "layers"
            else jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, pp_axis), v))
        for k, v in grads.items()
    }
    return loss, grads


def make_gpt_pipeline_stage(cfg: TransformerConfig, n_stages: int,
                            tp: int = 1, *, pp_axis: str = "pp",
                            tp_axis: str = "tp") -> Callable:
    """Build ``stage_fn(stage_params, packet) -> packet`` for the scan
    schedules (reference forward_step, schedules/common.py:253).

    Every device runs the same program; stage behavior is selected by
    ``lax.axis_index(pp_axis)``: stage 0 embeds tokens, inner stages
    transform the hidden, the last stage applies the final norm + LM head
    and writes the per-microbatch loss into the packet. TP inside a stage
    uses the manual mapping collectives over ``tp_axis``.

    Dropout keys and padding masks ride in the packet (see
    :func:`pipeline_packet`); the LM head + CE run under ``lax.cond`` so
    only the last stage pays their FLOPs — safe because all members of a
    tp group share one pp index, so the vocab-parallel collectives inside
    the branch cannot diverge across a tp group.

    MoE configs compose with the pipeline since round 3: each stage runs
    its experts *locally* (replicated within the stage — the packet
    threads the running load-balance aux loss to the last stage, which
    folds it into the CE like ``gpt_loss``).  Sharding experts over an
    'ep' mesh axis *inside* shard_map would need hand-written
    all-to-alls; that combination stays on the GSPMD path
    (``make_gpt_train_step`` over a mesh with an 'ep' axis), where the
    partitioner inserts them from the annotations.
    """
    if cfg.num_experts:
        import dataclasses

        cfg = dataclasses.replace(cfg, moe_ep_axis=None)
    ctx = manual_ctx(tp, tp_axis) if tp > 1 else single_device_ctx()

    def stage_fn(sp: dict, packet: dict) -> dict:
        my = jax.lax.axis_index(pp_axis)
        first = my == 0
        last = my == n_stages - 1
        cd = cfg.compute_dtype
        tokens, labels = packet["tokens"], packet["labels"]
        mask = packet.get("attention_mask")
        seed = packet.get("dropout_seed")
        if cfg.attn_mask_type == "padding" and mask is None:
            raise ValueError(
                "attn_mask_type='padding' needs the key-padding mask in "
                "the packet: pipeline_packet(..., attention_mask_mb=...)"
            )
        if (cfg.hidden_dropout > 0 or cfg.attention_dropout > 0
                or cfg.drop_path_rate > 0) and seed is None:
            raise ValueError(
                "dropout is enabled but the packet carries no "
                "dropout_seed: pipeline_packet(..., dropout_seeds=...) "
                "(silently training without dropout would diverge from "
                "the configured model)"
            )
        rng = None
        if seed is not None and (
                cfg.hidden_dropout > 0 or cfg.attention_dropout > 0
                or cfg.drop_path_rate > 0):
            # distinct stream per (stage, microbatch): the seed is
            # per-microbatch, each stage folds in its pp index (attention
            # additionally folds the tp index in — see _attention)
            rng = jax.random.fold_in(jax.random.PRNGKey(seed), my)

        # first stage only (same lax.cond treatment as the head: under
        # manual TP the vocab-parallel embed carries a psum, and all tp
        # peers share one pp index, so branches cannot diverge).  Both
        # branches pvary'd so their varying-axes types unify.
        from apex_tpu.utils.collectives import pvary as _pvary

        h = jax.lax.cond(
            first,
            lambda: _pvary(
                embed_tokens(sp["embedding"], tokens, cfg, ctx
                             ).astype(packet["hidden"].dtype), pp_axis),
            lambda: _pvary(packet["hidden"], pp_axis))

        # this stage's layer chunk: local leading pp dim of size 1
        layers = jax.tree_util.tree_map(lambda v: v[0], sp["layers"])
        h, aux_local = transformer_backbone(
            {"layers": layers}, h, cfg, ctx, attention_mask=mask,
            dropout_rng=rng, apply_final_norm=False, with_aux=True)
        aux = None
        if cfg.num_experts:
            aux = _pvary(packet["aux"], pp_axis) + aux_local

        def head_and_ce(h_in):
            h_final = apply_norm(cfg, h_in, sp["final_ln"]["scale"],
                                 sp["final_ln"]["bias"])
            logits = lm_head_logits(sp, h_final, cfg)
            ce = lm_cross_entropy(logits, labels, ctx)
            if cfg.num_experts:
                # fold the accumulated load-balance term in exactly like
                # gpt_loss (mean over layers)
                ce = ce + cfg.moe_aux_loss_coeff * aux / cfg.num_layers
            return ce

        # last stage only: the v/12h-per-stage FLOP tax of running the
        # head everywhere (round-1 design) is gone.  The false branch's
        # zero must carry the same varying-axes type as the head output
        # (pp-varying), hence the pvary.
        loss = jax.lax.cond(
            last, head_and_ce,
            lambda _h: _pvary(jnp.float32(0.0), pp_axis), h)

        out = {
            "hidden": h.astype(cd),
            "tokens": tokens,
            "labels": labels,
            "loss": loss,
        }
        if aux is not None:
            out["aux"] = aux
        if mask is not None:
            out["attention_mask"] = mask
        if seed is not None:
            out["dropout_seed"] = seed
        return out

    return stage_fn


# ---------------------------------------------------------------------------
# interleaved virtual-pipeline (vpp) path
# ---------------------------------------------------------------------------


def stack_pipeline_params_vpp(params: dict, cfg: TransformerConfig,
                              n_stages: int, vpp: int) -> dict:
    """Cut the layer stack into ``n_stages * vpp`` chunks stacked
    [vpp, pp, layers_per_chunk, ...] (chunk c = j*pp + d lives on device
    d slot j — the interleaved schedule's placement,
    reference fwd_bwd_pipelining_with_interleaving.py:26 / build_model
    virtual chunks, schedules/common.py:30).

    A ``chunk_id`` leaf rides along so the stage can tell which global
    chunk it is holding (the schedule slices slot j and shard_map shards
    device d; the value that arrives is exactly ``j*pp + d``).
    """
    L = cfg.num_layers
    n_chunks = n_stages * vpp
    if L % n_chunks:
        raise ValueError(
            f"num_layers {L} not divisible by pp*vpp = {n_chunks}")
    per = L // n_chunks
    layers = jax.tree_util.tree_map(
        lambda v: v.reshape((vpp, n_stages, per) + v.shape[1:]),
        params["layers"])
    # the interleaved schedule slices slot j from EVERY leaf, so the
    # replicated (embedding / final-LN / head) params get a broadcast
    # leading vpp dim (lazy under jit — no real copy)
    out = {
        k: jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a, (vpp,) + a.shape), v)
        for k, v in params.items() if k != "layers"
    }
    out["layers"] = layers
    # float32 so the leaf is differentiable-typed (its grad is zero);
    # value_and_grad in the schedule rejects integer params
    out["chunk_id"] = jnp.arange(n_chunks, dtype=jnp.float32).reshape(
        vpp, n_stages)
    return out


def make_gpt_vpp_stage(cfg: TransformerConfig, n_stages: int, vpp: int,
                       tp: int = 1, *, tp_axis: str = "tp") -> Callable:
    """Chunk-apply function for the interleaved schedule:
    ``stage_fn(chunk_params, packet) -> packet``.

    Chunk identity comes from the ``chunk_id`` leaf (global chunk
    ``c = j*pp + my``): chunk 0 embeds, chunk ``pp*vpp - 1`` runs the
    final norm + LM head + CE — both under ``lax.cond`` so only the
    owning chunk pays the FLOPs (same argument as
    :func:`make_gpt_pipeline_stage`).
    """
    from apex_tpu.utils.collectives import pvary as _pvary

    if cfg.num_experts:
        # experts run locally per chunk; aux rides the packet — see
        # make_gpt_pipeline_stage (EP×PP sharded routing is GSPMD-only)
        import dataclasses

        cfg = dataclasses.replace(cfg, moe_ep_axis=None)
    ctx = manual_ctx(tp, tp_axis) if tp > 1 else single_device_ctx()
    n_chunks = n_stages * vpp
    pp_axis = "pp"

    def stage_fn(sp: dict, packet: dict) -> dict:
        cid = sp["chunk_id"][0] if sp["chunk_id"].ndim else sp["chunk_id"]
        first = cid == 0
        last = cid == n_chunks - 1
        cd = cfg.compute_dtype
        tokens, labels = packet["tokens"], packet["labels"]
        mask = packet.get("attention_mask")
        seed = packet.get("dropout_seed")
        rng = None
        if seed is not None and (
                cfg.hidden_dropout > 0 or cfg.attention_dropout > 0
                or cfg.drop_path_rate > 0):
            rng = jax.random.fold_in(jax.random.PRNGKey(seed),
                                     cid.astype(jnp.int32))

        h = jax.lax.cond(
            first,
            lambda: _pvary(
                embed_tokens(sp["embedding"], tokens, cfg, ctx
                             ).astype(packet["hidden"].dtype), pp_axis),
            lambda: _pvary(packet["hidden"], pp_axis))

        # this chunk's layer slice: leading dims already sliced down to
        # the local (per-chunk) stack by the schedule + shard_map
        layers = jax.tree_util.tree_map(lambda v: v[0], sp["layers"])
        h, aux_local = transformer_backbone(
            {"layers": layers}, h, cfg, ctx, attention_mask=mask,
            dropout_rng=rng, apply_final_norm=False, with_aux=True)
        aux = None
        if cfg.num_experts:
            aux = _pvary(packet["aux"], pp_axis) + aux_local

        def head_and_ce(h_in):
            h_final = apply_norm(cfg, h_in, sp["final_ln"]["scale"],
                                 sp["final_ln"]["bias"])
            logits = lm_head_logits(sp, h_final, cfg)
            ce = lm_cross_entropy(logits, labels, ctx)
            if cfg.num_experts:
                ce = ce + cfg.moe_aux_loss_coeff * aux / cfg.num_layers
            return ce

        loss = jax.lax.cond(
            last, head_and_ce,
            lambda _h: _pvary(jnp.float32(0.0), pp_axis), h)

        out = {
            "hidden": h.astype(cd),
            "tokens": tokens,
            "labels": labels,
            "loss": loss,
        }
        if aux is not None:
            out["aux"] = aux
        if mask is not None:
            out["attention_mask"] = mask
        if seed is not None:
            out["dropout_seed"] = seed
        return out

    return stage_fn


def gpt_vpp_loss_and_grads(
    stage_fn: Callable,
    stacked_params: dict,
    packets: dict,
    *,
    n_micro: int,
    vpp: int,
    pp_axis: str = "pp",
    remat: bool = True,
):
    """Interleaved-schedule loss+grads for GPT; call inside shard_map.

    Same grad handling as :func:`gpt_pipeline_loss_and_grads`: layer
    grads are per-chunk exact, the replicated embedding/head/final-LN
    grads are psum'd over 'pp' (embedding-group allreduce analog)."""
    from apex_tpu.transformer.pipeline_parallel.schedules import (
        forward_backward_pipelining_with_interleaving,
    )
    from apex_tpu.utils.collectives import pvary

    varying = pvary(stacked_params, pp_axis)
    loss, grads = forward_backward_pipelining_with_interleaving(
        stage_fn, packets, varying,
        n_micro=n_micro,
        num_model_chunks=vpp,
        loss_fn=lambda out, _mb: out["loss"],
        axis=pp_axis,
        remat=remat,
    )
    # layers: exact per-chunk grads, stacked.  Replicated params: sum the
    # per-slot contributions (vpp dim) then psum over pp (the embedding-
    # group allreduce analog).  chunk_id is a constant — dropped.
    out = {}
    for k, v in grads.items():
        if k == "layers":
            out[k] = v
        elif k == "chunk_id":
            continue
        else:
            out[k] = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(jnp.sum(g, axis=0), pp_axis), v)
    return loss, out
