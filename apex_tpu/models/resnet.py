"""ResNet family (RN18/34/50/101/152) — the reference's flagship CNN config.

Reference: examples/imagenet/main_amp.py (torchvision resnet50 under
amp.initialize O2 + apex.parallel.DistributedDataParallel + optional
convert_syncbn_model) — the L1 correctness baseline and BASELINE.json's
headline metric ('ImageNet RN50 imgs/sec/chip, AMP O2 + DDP'). TPU-native
choices: NHWC layout end-to-end (channels ride the 128-lane minor dim;
reference groupbn's NHWC is the default here), bf16 compute with fp32
normalization statistics, SyncBatchNorm semantics (apex
convert_syncbn_model analog): under GSPMD (jit over a mesh) leave
``axis_name=None`` — ``jnp.mean`` over the dp-sharded batch axis already
computes GLOBAL statistics, XLA inserts the collective; set ``axis_name``
only inside shard_map/pmap where the explicit ``pmean`` is needed.
ResNet-v1.5 downsampling (stride on the 3x3, torchvision semantics).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from apex_tpu.amp.frontend import make_train_step
from apex_tpu.parallel.mesh import replicate, shard_batch
from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm

__all__ = [
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "make_resnet_train_step",
    "space_to_depth",
    "stem_kernel_to_space_to_depth",
]


class BasicBlock(nn.Module):
    filters: int
    stride: int = 1
    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16
    expansion: int = 1

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(SyncBatchNorm, axis_name=self.axis_name)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.stride, self.stride),
                 padding=[(1, 1), (1, 1)], name="conv1")(x)
        y = bn(self.filters, fuse_relu=True, name="bn1")(
            y, use_running_average=not train)
        y = conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)],
                 name="conv2")(y)
        y = bn(self.filters, name="bn2")(y, use_running_average=not train)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1),
                            strides=(self.stride, self.stride),
                            name="downsample_conv")(x)
            residual = bn(self.filters, name="downsample_bn")(
                residual, use_running_average=not train)
        return jax.nn.relu(y + residual.astype(y.dtype))


class Bottleneck(nn.Module):
    """v1.5 bottleneck: 1x1 → 3x3(stride) → 1x1x4 (torchvision layout,
    the reference example's model and contrib.bottleneck's block shape)."""

    filters: int
    stride: int = 1
    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool):
        bn = partial(SyncBatchNorm, axis_name=self.axis_name)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        out_ch = self.filters * self.expansion
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = bn(self.filters, fuse_relu=True, name="bn1")(
            y, use_running_average=not train)
        y = conv(self.filters, (3, 3), strides=(self.stride, self.stride),
                 padding=[(1, 1), (1, 1)], name="conv2")(y)
        y = bn(self.filters, fuse_relu=True, name="bn2")(
            y, use_running_average=not train)
        y = conv(out_ch, (1, 1), name="conv3")(y)
        y = bn(out_ch, name="bn3")(y, use_running_average=not train)
        if residual.shape != y.shape:
            residual = conv(out_ch, (1, 1),
                            strides=(self.stride, self.stride),
                            name="downsample_conv")(x)
            residual = bn(out_ch, name="downsample_bn")(
                residual, use_running_average=not train)
        return jax.nn.relu(y + residual.astype(y.dtype))


def space_to_depth(x: jax.Array) -> jax.Array:
    """NHWC 2x2 space-to-depth: [n,H,W,C] → [n,H/2,W/2,4C] with
    ``out[..., (di*2+dj)*C + c] = x[n, 2i+di, 2j+dj, c]``."""
    n, H, W, C = x.shape
    xs = x.reshape(n, H // 2, 2, W // 2, 2, C).transpose(0, 1, 3, 2, 4, 5)
    return xs.reshape(n, H // 2, W // 2, 4 * C)


def stem_kernel_to_space_to_depth(w7: jax.Array) -> jax.Array:
    """Convert a (7,7,C,F) stride-2 stem kernel to its exactly-equivalent
    (4,4,4C,F) space-to-depth kernel (zero-pad to 8x8 at the top-left,
    then interleave the 2x2 phases into channels — the MLPerf ResNet TPU
    stem transform).  Used with stride (1,1) and padding [(2,1),(2,1)]
    on the space-to-depth input; tested bit-close vs the 7x7 stem."""
    C, F = w7.shape[2], w7.shape[3]
    w8 = jnp.pad(w7, ((1, 0), (1, 0), (0, 0), (0, 0)))
    w8r = w8.reshape(4, 2, 4, 2, C, F).transpose(0, 2, 1, 3, 4, 5)
    return w8r.reshape(4, 4, 4 * C, F)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Any
    num_classes: int = 1000
    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16
    # MLPerf-style TPU stem: 2x2 space-to-depth on the input + an
    # equivalent 4x4x12 conv — the 7x7x3 stem's 3 input channels waste
    # the 128-wide MXU lanes; 12 channels at a quarter the spatial size
    # do the same math with far better tiling.
    space_to_depth_stem: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        bn = partial(SyncBatchNorm, axis_name=self.axis_name)
        x = x.astype(self.dtype)
        if self.space_to_depth_stem:
            x = space_to_depth(x)
            x = nn.Conv(64, (4, 4), strides=(1, 1),
                        padding=[(2, 1), (2, 1)], use_bias=False,
                        dtype=self.dtype, name="conv1")(x)
        else:
            x = nn.Conv(64, (7, 7), strides=(2, 2),
                        padding=[(3, 3), (3, 3)], use_bias=False,
                        dtype=self.dtype, name="conv1")(x)
        x = bn(64, fuse_relu=True, name="bn1")(
            x, use_running_average=not train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                stride = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=64 * 2 ** i, stride=stride,
                    axis_name=self.axis_name, dtype=self.dtype,
                    name=f"layer{i + 1}_{j}")(x, train=train)
        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        # classifier in fp32 (reference O2 keeps the loss path fp32)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)
        return x


def resnet18(**kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock, **kw)


def resnet34(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock, **kw)


def resnet50(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck, **kw)


def resnet101(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 23, 3], block_cls=Bottleneck, **kw)


def resnet152(**kw) -> ResNet:
    return ResNet(stage_sizes=[3, 8, 36, 3], block_cls=Bottleneck, **kw)


def make_resnet_train_step(
    model: ResNet,
    optimizer: Any,
    policy_or_amp="O2",
    mesh: Optional[Mesh] = None,
    *,
    image_shape: Tuple[int, int, int] = (224, 224, 3),
):
    """AMP train step for the imagenet config (examples/imagenet/main_amp.py
    hot loop, SURVEY.md §3.2 — here one jitted step: SyncBN stats pmean'd
    by GSPMD, grads mean'd over 'dp' via sharding propagation, fused
    optimizer update, dynamic loss scale with skip-step).

    Returns ``(init_fn, step_fn)``:
      ``init_fn(rng) -> (train_state, batch_stats)``;
      ``step_fn(train_state, batch_stats, images, labels)
          -> (train_state, batch_stats, metrics)`` — images NHWC.
    """

    def loss_fn(params, batch_stats, images, labels):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            images, train=True, mutable=["batch_stats"])
        one_hot = jax.nn.one_hot(labels, logits.shape[-1],
                                 dtype=jnp.float32)
        loss = -jnp.mean(
            jnp.sum(jax.nn.log_softmax(logits.astype(jnp.float32))
                    * one_hot, axis=-1))
        return loss, mutated["batch_stats"]

    init_amp, step_amp = make_train_step(
        loss_fn, optimizer, policy_or_amp, has_aux=True)

    def init(rng):
        variables = model.init(
            rng, jnp.zeros((1, *image_shape), jnp.float32), train=False)
        state = init_amp(variables["params"])
        stats = variables["batch_stats"]
        if mesh is not None:
            state = jax.device_put(state, replicate(mesh))
            stats = jax.device_put(stats, replicate(mesh))
        return state, stats

    def raw_step(state, stats, images, labels):
        state, metrics = step_amp(state, stats, images, labels)
        new_stats = metrics.pop("aux")
        return state, new_stats, metrics

    if mesh is None:
        return init, jax.jit(raw_step, donate_argnums=(0, 1))

    batch_sharding = shard_batch(mesh)
    jstep = jax.jit(
        raw_step,
        in_shardings=(None, None, batch_sharding, batch_sharding),
        donate_argnums=(0, 1),
    )

    def step(state, stats, images, labels):
        with jax.set_mesh(mesh):
            return jstep(state, stats, images, labels)

    return init, step
