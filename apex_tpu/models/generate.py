"""Autoregressive GPT decoding with a KV cache.

Beyond the reference: apex is a training-acceleration library with no
generation runtime (its GPT exists for scaling tests,
standalone_gpt.py), but a complete framework needs the inference half of
the model family.  TPU-native design:

- the whole decode loop is ONE ``lax.scan`` under jit (no per-token
  dispatch); static shapes throughout — the cache is pre-allocated at
  ``max_len`` and masked by position;
- the per-step attention is dense over the cache (sq=1 never benefits
  from the flash kernel's tiling) with fp32 accumulation on the MXU;
- parameters are the exact training pytree (init_gpt_params /
  tools/import_hf.py), so a trained or imported model generates without
  conversion; numerics follow transformer_lm.py layer-for-layer
  (pre-LN or the post-LN-residual flag, gelu/gelu_tanh/swiglu FFNs,
  learned or rope positions).

Teacher-forcing parity with ``gpt_forward`` is tested to float
tolerance (tests/test_generate.py), which pins the cached attention
against the training forward.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.transformer_lm import (
    apply_norm, lm_head_weight, rope_cos_sin)

__all__ = ["init_kv_cache", "decode_step", "generate"]


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int):
    """[L, b, max_len, kv_groups, dh] k/v buffers + position counter.

    Under GQA the cache holds only the group heads — the persistent
    per-token memory shrinks by num_attention_heads/num_query_groups
    (the principal GQA/MQA serving win, arXiv:2305.13245)."""
    nh = cfg.kv_groups
    dh = cfg.kv_channels
    shape = (cfg.num_layers, batch, max_len, nh, dh)
    return {
        "k": jnp.zeros(shape, cfg.compute_dtype),
        "v": jnp.zeros(shape, cfg.compute_dtype),
        "pos": jnp.zeros((), jnp.int32),
    }


def _layer_decode(cfg, lp, x, cache_k, cache_v, pos, rope):
    """One layer, one token: x [b, 1, h] + cache slice [b, T, nh, dh]."""
    b = x.shape[0]
    nh = cfg.num_attention_heads
    dh = cfg.kv_channels

    h = apply_norm(cfg, x, lp["ln1_scale"], lp["ln1_bias"])
    qkv = h @ lp["qkv_kernel"].astype(x.dtype) + lp["qkv_bias"].astype(
        x.dtype)
    if cfg.is_gqa:
        from apex_tpu.models.transformer_lm import split_qkv_gqa
        q, k, v = split_qkv_gqa(cfg, qkv, b, 1, nh)
    else:
        qkv = qkv.reshape(b, 1, nh, 3 * dh)
        q, k, v = jnp.split(qkv, 3, axis=-1)
    if rope is not None:
        cos, sin = rope          # [max_len, d]
        cos_t = jax.lax.dynamic_slice_in_dim(cos, pos, 1)[None, :, None]
        sin_t = jax.lax.dynamic_slice_in_dim(sin, pos, 1)[None, :, None]
        from apex_tpu.ops.rope import fused_apply_rotary_pos_emb_cached

        q = fused_apply_rotary_pos_emb_cached(q, cos_t, sin_t)
        k = fused_apply_rotary_pos_emb_cached(k, cos_t, sin_t)

    cache_k = jax.lax.dynamic_update_slice_in_dim(
        cache_k, k.astype(cache_k.dtype), pos, axis=1)
    cache_v = jax.lax.dynamic_update_slice_in_dim(
        cache_v, v.astype(cache_v.dtype), pos, axis=1)

    # dense attention over the (masked) cache; under GQA the query
    # heads fold as [groups, rep] against the group-width cache — no
    # repeated K/V is ever materialized
    scale = 1.0 / dh ** 0.5
    g = cfg.kv_groups
    rep = nh // g
    qg = q.reshape(b, 1, g, rep, dh)
    s = jnp.einsum("bqgrd,btgd->bgrqt", qg, cache_k,
                   preferred_element_type=jnp.float32) * scale
    t_idx = jnp.arange(cache_k.shape[1])
    s = jnp.where((t_idx <= pos)[None, None, None, None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    ctxv = jnp.einsum("bgrqt,btgd->bqgrd", p.astype(cache_v.dtype),
                      cache_v,
                      preferred_element_type=jnp.float32).astype(x.dtype)
    a = ctxv.reshape(b, 1, nh * dh) @ lp["proj_kernel"].astype(x.dtype)
    a = a + lp["proj_bias"].astype(x.dtype)

    res = h if cfg.apply_residual_connection_post_layernorm else x
    x = res + a
    h = apply_norm(cfg, x, lp["ln2_scale"], lp["ln2_bias"])
    from apex_tpu.models.transformer_lm import _mlp, single_device_ctx

    m = _mlp(cfg, lp, h, single_device_ctx())
    res = h if cfg.apply_residual_connection_post_layernorm else x
    return res + m, cache_k, cache_v


def decode_step(params: dict, token: jax.Array, cache: dict,
                cfg: TransformerConfig):
    """One decoding step: token [b] int32 at position ``cache['pos']`` →
    (logits [b, v], updated cache)."""
    if cfg.num_experts:
        raise ValueError(
            "KV-cache decoding does not support MoE configs yet")
    if cfg.attn_mask_type != "causal":
        raise ValueError(
            "KV-cache decoding is causal by construction; "
            f"attn_mask_type={cfg.attn_mask_type!r} would silently "
            "decode with the wrong mask")
    cd = cfg.compute_dtype
    pos = cache["pos"]
    x = jnp.take(params["embedding"]["word"].astype(cd), token,
                 axis=0)[:, None]
    if cfg.position_embedding_type == "learned":
        pe = jax.lax.dynamic_slice_in_dim(
            params["embedding"]["position"], pos, 1)
        x = x + pe.astype(cd)[None]
    rope = None
    if cfg.position_embedding_type == "rope":
        rope = rope_cos_sin(cache["k"].shape[2], cfg.kv_channels)

    # one compiled layer body scanned over the stacked layer params
    # (transformer_backbone's shape — compile time constant in depth)
    def body(x, layer_in):
        lp, ck, cv = layer_in
        x, ck, cv = _layer_decode(cfg, lp, x, ck, cv, pos, rope)
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))

    x = apply_norm(cfg, x, params["final_ln"]["scale"],
                   params["final_ln"]["bias"])
    logits = jnp.einsum(
        "bsh,vh->bsv", x, lm_head_weight(params, cfg).astype(cd),
        preferred_element_type=jnp.float32)[:, 0]
    cache = {"k": new_k, "v": new_v, "pos": pos + 1}
    return logits, cache


@functools.partial(jax.jit, static_argnames=(
    "cfg", "max_new_tokens", "temperature", "top_k", "vocab_limit"))
def generate(
    params: dict,
    prompt: jax.Array,
    cfg: TransformerConfig,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
    vocab_limit: Optional[int] = None,
) -> jax.Array:
    """Decode ``max_new_tokens`` past ``prompt`` [b, s] → [b, s+new].

    ``temperature=0`` is greedy; otherwise softmax sampling with an
    optional ``top_k`` cutoff and/or nucleus ``top_p`` cutoff (keep the
    smallest prefix of probability-sorted tokens whose mass reaches
    ``top_p``; both given = intersection, top_k first).  The prompt is
    consumed through the same cached step (prefill == decode path, so
    the parity test covers both).

    ``vocab_limit`` masks logits at and beyond that id — REQUIRED
    knowledge for padded vocab tables (tools/import_hf.py pads GPT-2's
    50257 to 50304; the zero-logit pad ids would otherwise be sampleable
    and can even win argmax when all real logits are negative).
    """
    b, s = prompt.shape
    total = s + max_new_tokens
    if (cfg.position_embedding_type == "learned"
            and total > cfg.max_position_embeddings):
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_position_embeddings ({cfg.max_position_embeddings}); "
            "the learned position lookup would silently clamp")
    if top_k is not None and top_k < 1:
        raise ValueError(
            f"top_k={top_k}: pass None (not 0) to disable the cutoff — "
            "a zero-width cutoff would silently break the nucleus mask")
    cache = init_kv_cache(cfg, b, total)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def pick(logits, key):
        if vocab_limit is not None:
            over = jnp.arange(logits.shape[-1]) >= vocab_limit
            logits = jnp.where(over[None], -1e30, logits)
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits / temperature
        if top_k is not None or top_p is not None:
            # one descending sort serves both cutoffs (pick() runs every
            # scan step; a second O(v log v) sort per token is real money
            # at GPT-2's 50k vocab)
            sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        if top_k is not None:
            kth = sorted_l[:, top_k - 1][:, None]
            logits = jnp.where(logits < kth, -1e30, logits)
            # reflect the cutoff in sorted space so the nucleus mass
            # below is computed over the top_k-filtered distribution
            pos = jnp.arange(sorted_l.shape[-1])[None]
            sorted_l = jnp.where(pos >= top_k, -1e30, sorted_l)
        if top_p is not None:
            # nucleus: drop tokens outside the smallest prob-sorted
            # prefix reaching mass top_p; n_keep clamps to 1 so the
            # head token always stays (top_p<=0 means near-greedy, not
            # a silent no-op)
            probs = jax.nn.softmax(sorted_l, axis=-1)
            csum = jnp.cumsum(probs, axis=-1)
            keep_sorted = (csum - probs) < top_p
            n_keep = jnp.maximum(jnp.sum(keep_sorted, axis=-1), 1)
            cutoff = jnp.take_along_axis(
                sorted_l, (n_keep - 1)[:, None], axis=-1)
            logits = jnp.where(logits < cutoff, -1e30, logits)
        return jax.random.categorical(key, logits).astype(jnp.int32)

    def body(carry, i):
        cache, tokens, key = carry
        token = jax.lax.dynamic_index_in_dim(
            tokens, i, axis=1, keepdims=False)
        logits, cache = decode_step(params, token, cache, cfg)
        key, sub = jax.random.split(key)
        nxt = pick(logits, sub)
        # only write past the prompt (positions < s-1 feed the prefill)
        write_at = i + 1
        keep = write_at >= s
        cur = jax.lax.dynamic_index_in_dim(
            tokens, jnp.minimum(write_at, total - 1), axis=1,
            keepdims=False)
        out = jnp.where(keep, nxt, cur)
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, out[:, None], jnp.minimum(write_at, total - 1),
            axis=1)
        return (cache, tokens, key), None

    tokens = jnp.concatenate(
        [prompt, jnp.zeros((b, max_new_tokens), prompt.dtype)], axis=1)
    (cache, tokens, _), _ = jax.lax.scan(
        body, (cache, tokens, rng), jnp.arange(total - 1))
    return tokens
