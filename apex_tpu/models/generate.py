"""Autoregressive GPT inference: batched flash prefill + ragged decode.

Beyond the reference: apex is a training-acceleration library with no
generation runtime (its GPT exists for scaling tests,
standalone_gpt.py), but a complete framework needs the inference half of
the model family.  TPU-native design (ISSUE 3):

- **prefill/decode split** — :func:`prefill` runs the full-sequence
  training forward (the same ``ops/flash_attention.py`` causal kernel
  the train step uses) and writes the whole KV cache in ONE batched
  pass, so a 512-token prompt costs one forward instead of 512
  sequential decode steps; :func:`decode_step` then extends one token
  per call with dense attention over the cache (sq=1 never benefits
  from the flash kernel's tiling) with fp32 accumulation on the MXU;
- **ragged batching** — the cache position is a ``[b]`` int32 vector,
  so prompts of different lengths batch together left-aligned without
  padding every sequence to the longest: per-sequence attention masks,
  per-sequence rotary offsets (``ops.rope.fused_apply_rotary_pos_emb_
  ragged``) and per-sequence EOS done-flags; the outer decode is a
  ``lax.while_loop`` that exits when every sequence has finished
  instead of always scanning ``max_new_tokens``;
- static shapes throughout — the cache is pre-allocated at ``max_len``
  and masked by position, the one compiled decode body serves every
  step;
- **two cache layouts** (ISSUE 6) — ``cache_layout="contiguous"`` is
  the original per-sequence ``[b, max_len]`` stripe;
  ``cache_layout="paged"`` stores K/V in a global pool of fixed-size
  blocks addressed through per-sequence block tables
  (``serving/paged_cache.py``), with decode attention running the
  fused ragged-paged kernel (``ops/paged_attention.py``).  Both
  layouts decode token-identically (tests/test_generate_paged.py);
  the paged one is what lets the serving engine commit HBM per
  allocated block instead of per ``max_slots × max_len``;
- parameters are the exact training pytree (init_gpt_params /
  tools/import_hf.py), so a trained or imported model generates without
  conversion; numerics follow transformer_lm.py layer-for-layer
  (pre-LN or the post-LN-residual flag, gelu/gelu_tanh/swiglu FFNs,
  learned or rope positions, MHA or grouped-query K/V).

Teacher-forcing parity with ``gpt_forward`` is tested to float
tolerance and prefill-vs-stepwise cache equivalence is pinned exactly
(tests/test_generate.py).  The slot-based continuous-batching engine in
``apex_tpu/serving`` builds on these three primitives.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.transformer_lm import (
    apply_norm, lm_head_weight, rope_cos_sin)
from apex_tpu.observability import metrics as _telemetry
from apex_tpu.ops.fused_sampling import fused_sample

__all__ = ["init_kv_cache", "decode_step", "decode_verify", "prefill",
           "prefill_chunked", "generate", "sample_logits", "extract_kv",
           "inject_kv"]


DEFAULT_BLOCK_SIZE = 16


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int,
                  cache_dtype=None, *, cache_layout: str = "contiguous",
                  block_size: int = DEFAULT_BLOCK_SIZE,
                  cache_wire=None):
    """KV cache for ``batch`` sequences of up to ``max_len`` tokens.

    ``cache_layout="contiguous"`` (default): ``[L, b, max_len,
    kv_groups, dh]`` k/v buffers + ``[b]`` positions — every sequence
    owns a max-length stripe.

    ``cache_layout="paged"``: a global block pool ``[L, num_blocks,
    block_size, kv_groups, dh]`` plus per-sequence ``block_tables``
    ``[b, ceil(max_len/block_size)]``.  Here the tables are filled
    linearly (sequence ``i`` owns blocks ``[i·mb, (i+1)·mb)``) — the
    static one-shot form :func:`generate` uses; the serving engine
    allocates tables dynamically through
    :class:`~apex_tpu.serving.paged_cache.BlockManager` instead, which
    is where the pool layout actually pays (HBM per allocated block,
    prefix sharing, preemption).

    Under GQA the cache holds only the group heads — the persistent
    per-token memory shrinks by num_attention_heads/num_query_groups
    (the principal GQA/MQA serving win, arXiv:2305.13245).

    ``cache_dtype`` overrides the buffer dtype (default
    ``cfg.compute_dtype``) so a serving deployment can hold bf16 caches
    under an fp32 compute config — decode casts at the attention einsum
    as it already does for the compute dtype.

    ``pos`` is per-sequence: sequence ``i``'s next token lands at
    ``pos[i]`` and its attention sees ``t <= pos[i]``, which is what
    lets ragged prompts share one batch.

    ``cache_wire="int8"`` (ISSUE 14, paged layout only) stores the
    pool at rest as block-scaled int8 — K/V quantize per (token, kv
    group) at every write edge and the paged-attention kernel
    dequantizes in-VMEM; the dict carries the parallel
    ``k_scale``/``v_scale`` pools.  ~0.53x a bf16 pool's resident
    bytes (``1 + 4/dh`` bytes/element).
    """
    dt = cfg.compute_dtype if cache_dtype is None else cache_dtype
    nh = cfg.kv_groups
    dh = cfg.kv_channels
    if cache_layout == "contiguous":
        if cache_wire not in (None, "native"):
            raise ValueError(
                f"cache_wire={cache_wire!r} is a paged-pool form; the "
                "contiguous stripe layout stores the cache dtype only")
        shape = (cfg.num_layers, batch, max_len, nh, dh)
        return {
            "k": jnp.zeros(shape, dt),
            "v": jnp.zeros(shape, dt),
            "pos": jnp.zeros((batch,), jnp.int32),
        }
    if cache_layout != "paged":
        raise ValueError(
            f"cache_layout={cache_layout!r}: expected 'contiguous' or "
            "'paged'")
    from apex_tpu.serving.paged_cache import blocks_for, init_paged_pool

    mb = blocks_for(max_len, block_size)
    pool = init_paged_pool(cfg, batch * mb, block_size,
                           cache_dtype=cache_dtype,
                           cache_wire=cache_wire)
    tables = (jnp.arange(batch, dtype=jnp.int32)[:, None] * mb
              + jnp.arange(mb, dtype=jnp.int32)[None])
    pool["pos"] = jnp.zeros((batch,), jnp.int32)
    pool["block_tables"] = tables
    return pool


def extract_kv(cache: dict, length: int, *, row: int = 0):
    """Pull sequence ``row``'s first ``length`` tokens of K/V out of a
    cache in EITHER layout → ``(k, v)`` of shape
    ``[L, length, kv_groups, dh]`` (device arrays; ``np.asarray`` them
    to cross a process boundary).

    This is the model-path half of the cluster KV handoff (ISSUE 9): a
    prefill worker extracts the freshly written prompt K/V and ships it
    to a decode pool.  Paged caches dereference the row's block table
    (only the blocks the table names are touched — token order, not
    pool order); contiguous caches slice the row's stripe.  Exactly
    inverted by :func:`inject_kv` on any cache with room:
    ``inject_kv(dst, *extract_kv(src, n))`` leaves ``dst`` decoding
    token-identically to ``src`` (tests/test_serving_handoff.py pins it
    across layout pairs)."""
    if length < 1:
        raise ValueError(f"length={length} must be >= 1")
    if "block_tables" in cache:
        from apex_tpu.serving.paged_cache import (
            blocks_for, dequantize_kv, gather_block_kv)

        bs = cache["k"].shape[2]
        tables = cache["block_tables"]
        need = blocks_for(int(length), bs)
        if need > tables.shape[1]:
            raise ValueError(
                f"length {length} needs {need} blocks but the table "
                f"holds {tables.shape[1]}")
        ids = np.asarray(tables)[row, :need]
        nb = cache["k"].shape[1]
        if (ids >= nb).any() or (ids < 0).any():
            # an unmapped sentinel inside the requested range means
            # `length` exceeds the row's materialized tokens — the
            # gather would CLAMP onto a real pool block and silently
            # ship another request's pages over the wire
            raise ValueError(
                f"length {length} reaches unmapped table entries for "
                f"row {row} (sentinel >= {nb}); it exceeds the row's "
                "materialized tokens")
        k, v = gather_block_kv(cache["k"], cache["v"], ids)
        if "k_scale" in cache:
            # int8 pool: the handoff contract ships FLOAT per-token K/V
            # (the wire layer owns its own quantization); dequantize
            # through the gathered scales — fp32, since the at-rest
            # quantization already spent the precision budget
            idj = jnp.asarray(ids, jnp.int32)
            L, g = cache["k"].shape[0], cache["k"].shape[3]
            sk = jnp.take(cache["k_scale"], idj, axis=1).reshape(
                L, need * bs, g)
            sv = jnp.take(cache["v_scale"], idj, axis=1).reshape(
                L, need * bs, g)
            k = dequantize_kv(k, sk)
            v = dequantize_kv(v, sv)
        return k[:, :length], v[:, :length]
    if length > cache["k"].shape[2]:
        raise ValueError(
            f"length {length} exceeds the cache max_len "
            f"{cache['k'].shape[2]}")
    return cache["k"][:, row, :length], cache["v"][:, row, :length]


def inject_kv(cache: dict, k, v, *, row: int = 0) -> dict:
    """Write per-token K/V ``[L, n, kv_groups, dh]`` into positions
    ``[0, n)`` of sequence ``row`` and set ``pos[row] = n`` — the
    decode-side half of the cluster KV handoff (inverse of
    :func:`extract_kv`).  Paged caches scatter each token through the
    row's block table (cells ``(tables[row, t//bs], t % bs)``; unmapped
    sentinel entries drop, so a short table cannot be corrupted);
    contiguous caches overwrite the row's stripe head.  The arrays are
    cast to the cache dtype — a raw-wire handoff between same-dtype
    caches is bit-exact."""
    k = jnp.asarray(k)
    v = jnp.asarray(v)
    if k.ndim != 4 or k.shape != v.shape:
        raise ValueError(
            f"expected matching [L, n, g, dh] K/V, got {k.shape} / "
            f"{v.shape}")
    n = k.shape[1]
    if "block_tables" in cache:
        from apex_tpu.serving.paged_cache import blocks_for

        tables = cache["block_tables"].astype(jnp.int32)
        nb, bs = cache["k"].shape[1], cache["k"].shape[2]
        mb = tables.shape[1]
        need = blocks_for(int(n), bs)
        if need > mb:
            raise ValueError(
                f"{n} handoff tokens need {need} blocks but the "
                f"table holds {mb}")
        ids = np.asarray(cache["block_tables"])[row, :need]
        if (ids >= nb).any() or (ids < 0).any():
            # scattering through an unmapped sentinel would DROP the
            # write while pos still claims the token — the cache
            # would silently attend over stale pool data
            raise ValueError(
                f"{n} handoff tokens reach unmapped table entries "
                f"for row {row} (sentinel >= {nb}); map blocks for "
                "the full range before injecting")
        t = jnp.arange(n)
        blk = tables[row, jnp.minimum(t // bs, mb - 1)]
        blk = jnp.where(t < mb * bs, blk, nb)
        off = t % bs
        if "k_scale" in cache:
            # int8 pool: quantize the float handoff at the write edge
            # (the shared scatter keeps wire + scale cells paired)
            from apex_tpu.serving.paged_cache import scatter_kv_quantized

            ck, cv, sk, sv = scatter_kv_quantized(
                cache["k"], cache["v"], cache["k_scale"],
                cache["v_scale"], k, v, (slice(None), blk, off))
            return {
                "k": ck, "v": cv, "k_scale": sk, "v_scale": sv,
                "pos": cache["pos"].at[row].set(n),
                "block_tables": cache["block_tables"],
            }
        return {
            "k": cache["k"].at[:, blk, off].set(
                k.astype(cache["k"].dtype), mode="drop"),
            "v": cache["v"].at[:, blk, off].set(
                v.astype(cache["v"].dtype), mode="drop"),
            "pos": cache["pos"].at[row].set(n),
            "block_tables": cache["block_tables"],
        }
    if n > cache["k"].shape[2]:
        raise ValueError(
            f"{n} handoff tokens exceed the cache max_len "
            f"{cache['k'].shape[2]}")
    return {
        "k": cache["k"].at[:, row, :n].set(k.astype(cache["k"].dtype)),
        "v": cache["v"].at[:, row, :n].set(v.astype(cache["v"].dtype)),
        "pos": cache["pos"].at[row].set(n),
    }


def _check_sampling_args(temperature: float,
                         top_k: Optional[int]) -> None:
    """Shared static-argument guard for sample_logits / generate."""
    if temperature < 0:
        raise ValueError(
            f"temperature={temperature}: negative temperatures would "
            "silently invert the distribution (prefer the *least* "
            "likely tokens); pass 0 for greedy or a positive value")
    if top_k is not None and top_k < 1:
        raise ValueError(
            f"top_k={top_k}: pass None (not 0) to disable the cutoff — "
            "a zero-width cutoff would silently break the nucleus mask")


def _check_decode_cfg(cfg: TransformerConfig) -> None:
    """Shared config guard for every cached-inference entry point."""
    if cfg.num_experts:
        raise ValueError(
            "KV-cache decoding does not support MoE configs yet")
    if cfg.attn_mask_type != "causal":
        raise ValueError(
            "KV-cache decoding is causal by construction; "
            f"attn_mask_type={cfg.attn_mask_type!r} would silently "
            "decode with the wrong mask")


def _vector_pos(cache: dict) -> jax.Array:
    """The ``[b]`` int32 cache position.  The pre-PR-3 scalar-counter
    broadcast form is gone — everything in-tree has written vector
    positions since the ragged-decode rework, so a scalar here is a
    stale caller bug, not a layout to silently paper over."""
    pos = cache["pos"]
    if pos.ndim != 1:
        raise ValueError(
            f"cache['pos'] must be a [b] int32 vector, got shape "
            f"{pos.shape}; the legacy scalar-counter broadcast path "
            "was removed (PR 6) — build caches with init_kv_cache")
    return pos.astype(jnp.int32)


def _lora_operands(lora, m: int = 1):
    """Resolve the optional per-request LoRA bundle (``{"idx": [b] int32
    slot ids, "slabs": {target: {"a": [L, G, in, r], "b": [L, G, r,
    out]}}}``, ISSUE 20) into forward operands: the slab pytree (leading
    layer axis — scanned beside the base layer stack) and the sort plan
    over the forward's ``b * m`` rows.  A verify block's token (i, j)
    flattens row-major, so each sequence's slot id repeats m ways.  Both
    the ids and the plan are traced — one compiled step serves every
    adapter mix."""
    if lora is None:
        return None, None
    from apex_tpu.models.lora import lora_plan

    slabs = lora["slabs"]
    n_slots = next(iter(slabs.values()))["a"].shape[1]
    idx = lora["idx"].astype(jnp.int32)
    if m > 1:
        idx = jnp.repeat(idx, m)
    return slabs, lora_plan(idx, n_slots)


def _decode_qkv(cfg, lp, x, pos, rope, rope_q: bool = True, ll=None,
                plan=None):
    """Shared pre-attention math (norm → qkv projection → GQA split →
    per-sequence rotary) for ``x`` [b, s, h] appended at per-sequence
    offsets ``pos`` [b] — token (i, j) sits at absolute position
    ``pos[i] + j`` (s=1 is the decode step, s=k+1 the speculative
    verify block): the contiguous and paged layer bodies differ only in
    where K/V land and how the cache is read, so this is ONE
    implementation of everything before that fork.

    ``rope_q=False`` returns the query PRE-rope (K still ropes for the
    cache write) — the fused decode layer (``ops/decode_step.py``)
    applies the query rotation in-kernel."""
    from apex_tpu.ops.dense import quantized_matmul

    b, s = x.shape[0], x.shape[1]
    nh = cfg.num_attention_heads
    dh = cfg.kv_channels
    h = apply_norm(cfg, x, lp["ln1_scale"], lp["ln1_bias"])
    # quantized_matmul: the plain array path is byte-identical to the
    # historical `h @ kernel.astype(...)`; an int8 weight-slab leaf
    # (models/quantized.quantize_params, ISSUE 14) runs the in-kernel
    # dequantizing matmul so decode reads int8 weight bytes
    qkv = quantized_matmul(h, lp["qkv_kernel"]) + lp["qkv_bias"].astype(
        x.dtype)
    if ll is not None and "qkv" in ll:
        from apex_tpu.models.lora import batched_lora_delta

        qkv = qkv + batched_lora_delta(h, ll["qkv"]["a"],
                                       ll["qkv"]["b"], plan)
    if cfg.is_gqa:
        from apex_tpu.models.transformer_lm import split_qkv_gqa
        q, k, v = split_qkv_gqa(cfg, qkv, b, s, nh)
    else:
        qkv = qkv.reshape(b, s, nh, 3 * dh)
        q, k, v = jnp.split(qkv, 3, axis=-1)
    if rope is not None:
        cos, sin = rope          # [max_len, d]
        from apex_tpu.ops.rope import fused_apply_rotary_pos_emb_ragged

        if rope_q:
            q = fused_apply_rotary_pos_emb_ragged(q, cos, sin, pos)
        k = fused_apply_rotary_pos_emb_ragged(k, cos, sin, pos)
    return h, q, k, v


def _decode_rope_rows(rope, pos):
    """Gather each sequence's angle-table row for its decode position
    (clamped like ``fused_apply_rotary_pos_emb_ragged``) → f32
    ``(cos, sin)`` of ``[b, d]`` — the per-sequence rope operand of the
    fused decode layer."""
    if rope is None:
        return None, None
    cos, sin = rope
    rows = jnp.clip(pos, 0, cos.shape[0] - 1)
    return (jnp.take(cos.astype(jnp.float32), rows, axis=0),
            jnp.take(sin.astype(jnp.float32), rows, axis=0))


def _decode_out_post(cfg, lp, x, h, a, ll=None, plan=None):
    """Post-projection tail (bias → residual → MLP) shared by the
    unfused path and the fused decode layer, whose kernel already owns
    the projection GEMM; ``a`` [b, s, h_model] is the projected
    attention output, bias not yet applied."""
    a = a + lp["proj_bias"].astype(x.dtype)
    res = h if cfg.apply_residual_connection_post_layernorm else x
    x = res + a
    h = apply_norm(cfg, x, lp["ln2_scale"], lp["ln2_bias"])
    from apex_tpu.models.transformer_lm import _mlp, single_device_ctx

    if ll is not None and ("fc1" in ll or "fc2" in ll):
        from apex_tpu.models.lora import lora_mlp

        m = lora_mlp(cfg, lp, h, ll, plan)
    else:
        m = _mlp(cfg, lp, h, single_device_ctx())
    res = h if cfg.apply_residual_connection_post_layernorm else x
    return res + m


def _decode_out(cfg, lp, x, h, ctx_flat, ll=None, plan=None):
    """Shared post-attention math (output projection → residual →
    MLP); ``ctx_flat`` [b, s, nh*dh] (s=1 decode, s=k+1 verify)."""
    from apex_tpu.ops.dense import quantized_matmul

    a = quantized_matmul(ctx_flat, lp["proj_kernel"])
    if ll is not None and "proj" in ll:
        from apex_tpu.models.lora import batched_lora_delta

        a = a + batched_lora_delta(ctx_flat, ll["proj"]["a"],
                                   ll["proj"]["b"], plan)
    return _decode_out_post(cfg, lp, x, h, a, ll=ll, plan=plan)


def _stripe_block(total: int) -> int:
    """Largest block size <= 128 dividing a contiguous stripe length
    (preferring a sublane multiple) — lets the fused decode kernel view
    the ``[b, T, g, dh]`` stripe as a linear ``[b·(T/bs), bs, g, dh]``
    pool without copying a byte."""
    cands = [d for d in range(1, min(total, 128) + 1) if total % d == 0]
    mult8 = [d for d in cands if d % 8 == 0]
    return max(mult8 or cands)


def _layer_decode(cfg, lp, x, cache_k, cache_v, pos, rope,
                  decode_fused: str = "reference", ll=None, plan=None):
    """One layer, one token, contiguous layout: x [b, 1, h] + cache
    slice [b, T, nh, dh]; ``pos`` [b] int32 — each sequence writes and
    attends at its own offset.

    ``decode_fused="kernel"`` runs rope + attention + output projection
    as ONE fused kernel (``ops/decode_step.py``) over the stripe viewed
    as a linear block pool; ``"reference"`` keeps the historical inline
    dense math below bit-for-bit."""
    from apex_tpu.ops.dense import is_quantized

    b = x.shape[0]
    nh = cfg.num_attention_heads
    dh = cfg.kv_channels
    # quantized projection slabs stay on the unfused path — their
    # in-kernel dequantizing matmul (ops/dense) owns the weight tiling;
    # LoRA lanes likewise — the fused kernel owns the projection GEMM,
    # and the per-row delta must land on its output
    fuse = (decode_fused == "kernel" and ll is None
            and not is_quantized(lp["proj_kernel"]))
    h, q, k, v = _decode_qkv(cfg, lp, x, pos, rope, rope_q=not fuse,
                             ll=ll, plan=plan)

    # per-sequence scatter: row (i, pos[i]) only — O(b·nh·dh) written
    # per step, not a full-buffer select; out-of-bounds positions
    # (finished rows parked past the cache) drop, matching the masked
    # semantics below
    b_idx = jnp.arange(b)
    cache_k = cache_k.at[b_idx, pos].set(k[:, 0].astype(cache_k.dtype))
    cache_v = cache_v.at[b_idx, pos].set(v[:, 0].astype(cache_v.dtype))
    if fuse:
        from apex_tpu.ops.decode_step import fused_decode_layer

        T = cache_k.shape[1]
        g = cfg.kv_groups
        bs = _stripe_block(T)
        nbl = T // bs
        tables = (jnp.arange(b, dtype=jnp.int32)[:, None] * nbl
                  + jnp.arange(nbl, dtype=jnp.int32)[None])
        rope_cos, rope_sin = _decode_rope_rows(rope, pos)
        a = fused_decode_layer(
            q[:, 0], cache_k.reshape(b * nbl, bs, g, dh),
            cache_v.reshape(b * nbl, bs, g, dh), tables, pos + 1,
            lp["proj_kernel"], rope_cos=rope_cos, rope_sin=rope_sin,
            backend="kernel")
        return (_decode_out_post(cfg, lp, x, h, a[:, None]),
                cache_k, cache_v)
    t_idx = jnp.arange(cache_k.shape[1])

    # dense attention over the (masked) cache; under GQA the query
    # heads fold as [groups, rep] against the group-width cache — no
    # repeated K/V is ever materialized
    scale = 1.0 / dh ** 0.5
    g = cfg.kv_groups
    rep = nh // g
    qg = q.reshape(b, 1, g, rep, dh)
    s = jnp.einsum("bqgrd,btgd->bgrqt", qg, cache_k,
                   preferred_element_type=jnp.float32) * scale
    live = (t_idx[None] <= pos[:, None])[:, None, None, None, :]
    s = jnp.where(live, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    ctxv = jnp.einsum("bgrqt,btgd->bqgrd", p.astype(cache_v.dtype),
                      cache_v,
                      preferred_element_type=jnp.float32).astype(x.dtype)
    x = _decode_out(cfg, lp, x, h, ctxv.reshape(b, 1, nh * dh),
                    ll=ll, plan=plan)
    return x, cache_k, cache_v


def _layer_decode_paged(cfg, lp, x, cache_k, cache_v, tables, pos, rope,
                        k_scale=None, v_scale=None,
                        decode_fused: str = "reference", ll=None,
                        plan=None):
    """One layer, one token, paged layout: x [b, 1, h] + this layer's
    block pool [num_blocks, block_size, g, dh] + ``tables``
    [b, max_blocks].  The new K/V append to each sequence's tail block
    (one-cell scatter through the table); attention runs through the
    fused decode layer (``ops/decode_step.py``) — ``decode_fused=
    "kernel"`` is rope + paged attention + output projection as ONE
    kernel with one VMEM residency, ``"reference"`` the exact
    historical op sequence (ragged-paged kernel + XLA matmul); either
    way the gathered cache never materializes.

    int8 pool (``k_scale``/``v_scale`` given, ISSUE 14): the append
    quantizes the fresh token per (sequence, group) and scatters wire +
    scale through the same table cell; the attention kernel dequantizes
    in-VMEM (scales ride the table-dereferenced DMA)."""
    from apex_tpu.ops.dense import is_quantized
    from apex_tpu.ops.paged_attention import ragged_paged_attention

    b = x.shape[0]
    nh = cfg.num_attention_heads
    dh = cfg.kv_channels
    # quantized projection slabs stay on the unfused path — their
    # in-kernel dequantizing matmul (ops/dense) owns the weight tiling;
    # LoRA lanes likewise (the fused kernel owns the projection GEMM)
    fuse = ll is None and not is_quantized(lp["proj_kernel"])
    h, q, k, v = _decode_qkv(cfg, lp, x, pos, rope, rope_q=not fuse,
                             ll=ll, plan=plan)

    nb, bs = cache_k.shape[0], cache_k.shape[1]
    mb = tables.shape[1]
    # tail-block append: cell (tables[i, pos//bs], pos % bs).  Unmapped
    # table entries (>= nb: released serving lanes, short tables) and
    # positions past the table's reach drop — a lane can never write
    # into a block it does not own.
    blk = jnp.take_along_axis(
        tables, jnp.minimum(pos // bs, mb - 1)[:, None], axis=1)[:, 0]
    blk = jnp.where(pos < mb * bs, blk, nb)
    off = pos % bs
    if k_scale is not None:
        from apex_tpu.serving.paged_cache import scatter_kv_quantized

        cache_k, cache_v, k_scale, v_scale = scatter_kv_quantized(
            cache_k, cache_v, k_scale, v_scale, k[:, 0], v[:, 0],
            (blk, off))
    else:
        cache_k = cache_k.at[blk, off].set(
            k[:, 0].astype(cache_k.dtype), mode="drop")
        cache_v = cache_v.at[blk, off].set(
            v[:, 0].astype(cache_v.dtype), mode="drop")

    if fuse:
        from apex_tpu.ops.decode_step import fused_decode_layer

        rope_cos, rope_sin = _decode_rope_rows(rope, pos)
        a = fused_decode_layer(
            q[:, 0], cache_k, cache_v, tables, pos + 1,
            lp["proj_kernel"], rope_cos=rope_cos, rope_sin=rope_sin,
            backend=decode_fused, k_scale=k_scale, v_scale=v_scale)
        x = _decode_out_post(cfg, lp, x, h, a[:, None])
        return x, cache_k, cache_v, k_scale, v_scale
    ctx = ragged_paged_attention(q[:, 0], cache_k, cache_v, tables,
                                 pos + 1, k_scale=k_scale,
                                 v_scale=v_scale)
    x = _decode_out(cfg, lp, x, h,
                    ctx.astype(x.dtype).reshape(b, 1, nh * dh),
                    ll=ll, plan=plan)
    return x, cache_k, cache_v, k_scale, v_scale


def decode_step(params: dict, token: jax.Array, cache: dict,
                cfg: TransformerConfig, *,
                decode_fused: Optional[str] = None, lora=None):
    """One decoding step: token [b] int32 at per-sequence position
    ``cache['pos']`` ([b] int32) → (logits [b, v], updated cache).

    ``lora`` (ISSUE 20): ``{"idx": [b] int32 slot ids, "slabs":
    stacked adapter factors}`` — per-row low-rank deltas added at each
    target matmul via the ragged grouped-matmul path
    (``models/lora.py``); slot 0 rows are computed delta-free.  LoRA
    lanes run the unfused reference attention route (the fused kernel
    owns the projection GEMM the delta must land on).

    The cache dict selects the layout: a ``block_tables`` entry means
    paged (pool ``[L, num_blocks, block_size, g, dh]``, tail-block
    append + the fused ragged-paged attention kernel); otherwise the
    contiguous ``[L, b, max_len, g, dh]`` stripe layout.

    ``decode_fused`` picks the fused decode-layer route
    (``ops/decode_step.py``: rope + attention + output projection in
    one kernel): ``"kernel"``/``"reference"`` pin, ``None``/``"auto"``
    resolve ``APEX_TPU_DECODE_FUSED`` here and now — jitted callers
    (``generate``, the serving engine) resolve the route ONCE outside
    their jit and pass it as a static argument, because an env read at
    trace time would freeze the first call's route into every cached
    trace."""
    from apex_tpu.ops.decode_step import route_decode_fused

    _check_decode_cfg(cfg)
    decode_fused = route_decode_fused(decode_fused)
    cd = cfg.compute_dtype
    paged = "block_tables" in cache
    pos = _vector_pos(cache)
    x = jnp.take(params["embedding"]["word"].astype(cd), token,
                 axis=0)[:, None]
    if cfg.position_embedding_type == "learned":
        pe = jnp.take(params["embedding"]["position"], pos, axis=0)
        x = x + pe.astype(cd)[:, None]
    rope = None
    if cfg.position_embedding_type == "rope":
        if paged:
            max_pos = cache["block_tables"].shape[1] * cache["k"].shape[2]
        else:
            max_pos = cache["k"].shape[2]
        rope = rope_cos_sin(max_pos, cfg.kv_channels)

    # one compiled layer body scanned over the stacked layer params
    # (transformer_backbone's shape — compile time constant in depth).
    # LoRA slabs ride the scan xs beside the base layers (None — an
    # empty pytree — when absent, so the no-adapter trace is unchanged)
    slabs, plan = _lora_operands(lora)
    quant = "k_scale" in cache
    new_scales = None
    if paged and quant:
        tables = cache["block_tables"].astype(jnp.int32)

        def body(x, layer_in):
            lp, ck, cv, sk, sv, ll = layer_in
            x, ck, cv, sk, sv = _layer_decode_paged(
                cfg, lp, x, ck, cv, tables, pos, rope, sk, sv,
                decode_fused=decode_fused, ll=ll, plan=plan)
            return x, (ck, cv, sk, sv)

        x, (new_k, new_v, *new_scales) = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"],
                      cache["k_scale"], cache["v_scale"], slabs))
    elif paged:
        tables = cache["block_tables"].astype(jnp.int32)

        def body(x, layer_in):
            lp, ck, cv, ll = layer_in
            x, ck, cv, _sk, _sv = _layer_decode_paged(
                cfg, lp, x, ck, cv, tables, pos, rope,
                decode_fused=decode_fused, ll=ll, plan=plan)
            return x, (ck, cv)

        x, (new_k, new_v) = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"], slabs))
    else:
        def body(x, layer_in):
            lp, ck, cv, ll = layer_in
            x, ck, cv = _layer_decode(cfg, lp, x, ck, cv, pos, rope,
                                      decode_fused=decode_fused,
                                      ll=ll, plan=plan)
            return x, (ck, cv)

        x, (new_k, new_v) = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"], slabs))

    x = apply_norm(cfg, x, params["final_ln"]["scale"],
                   params["final_ln"]["bias"])
    logits = jnp.einsum(
        "bsh,vh->bsv", x, lm_head_weight(params, cfg).astype(cd),
        preferred_element_type=jnp.float32)[:, 0]
    cache = {"k": new_k, "v": new_v, "pos": pos + 1}
    if new_scales is not None:
        cache["k_scale"], cache["v_scale"] = new_scales
    if paged:
        cache["block_tables"] = tables
    return logits, cache


def _verify_attention(cfg, x, h, lp, q, kk, vv, pos, ll=None, plan=None):
    """Dense masked attention of ``m`` appended query tokens over a
    gathered/contiguous cache view ``kk``/``vv`` [b, T, g, dh]: query
    ``j`` of sequence ``i`` sees positions ``t <= pos[i] + j`` — the
    causal pattern of a verification block (each drafted token attends
    to the cache prefix plus the drafts before it)."""
    b, m = q.shape[0], q.shape[1]
    nh = cfg.num_attention_heads
    dh = cfg.kv_channels
    g = cfg.kv_groups
    rep = nh // g
    scale = 1.0 / dh ** 0.5
    qg = q.reshape(b, m, g, rep, dh)
    s = jnp.einsum("bqgrd,btgd->bgrqt", qg, kk,
                   preferred_element_type=jnp.float32) * scale
    t_idx = jnp.arange(kk.shape[1])
    qpos = pos[:, None] + jnp.arange(m, dtype=jnp.int32)[None]  # [b, m]
    live = (t_idx[None, None] <= qpos[:, :, None])[:, None, None]
    s = jnp.where(live, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    ctxv = jnp.einsum("bgrqt,btgd->bqgrd", p.astype(vv.dtype), vv,
                      preferred_element_type=jnp.float32).astype(x.dtype)
    return _decode_out(cfg, lp, x, h, ctxv.reshape(b, m, nh * dh),
                       ll=ll, plan=plan)


def _layer_verify(cfg, lp, x, cache_k, cache_v, pos, rope, ll=None,
                  plan=None):
    """One layer, ``m`` appended tokens, contiguous layout: x [b, m, h]
    + cache slice [b, T, nh, dh]; writes land at rows
    ``(i, pos[i]+j)`` (out-of-bounds writes drop — rejected tails past
    the stripe are rolled back by the caller's position decrement)."""
    b, m = x.shape[0], x.shape[1]
    h, q, k, v = _decode_qkv(cfg, lp, x, pos, rope, ll=ll, plan=plan)
    b_idx = jnp.arange(b)[:, None]
    wpos = pos[:, None] + jnp.arange(m, dtype=jnp.int32)[None]
    cache_k = cache_k.at[b_idx, wpos].set(
        k.astype(cache_k.dtype), mode="drop")
    cache_v = cache_v.at[b_idx, wpos].set(
        v.astype(cache_v.dtype), mode="drop")
    x = _verify_attention(cfg, x, h, lp, q, cache_k, cache_v, pos,
                          ll=ll, plan=plan)
    return x, cache_k, cache_v


def _layer_verify_paged(cfg, lp, x, cache_k, cache_v, tables, pos, rope,
                        k_scale=None, v_scale=None, ll=None, plan=None):
    """One layer, ``m`` appended tokens, paged layout: the new K/V
    scatter through the block tables (cells ``(tables[i, p//bs],
    p % bs)``, unmapped entries drop), then attention runs over the
    gathered block view.  Unlike the sq=1 decode step this
    materializes the gather — a verification block amortizes the one
    gather over its m tokens, which is exactly the batched-prefill
    economics speculative decoding exists to exploit.  int8 pool: the
    drafted K/V quantize at the write edge and the gathered view
    dequantizes through the gathered scales; rejected drafts roll back
    by the caller's pos decrement exactly as in the native pool (their
    wire cells and scale cells are overwritten together by the next
    append)."""
    b, m = x.shape[0], x.shape[1]
    h, q, k, v = _decode_qkv(cfg, lp, x, pos, rope, ll=ll, plan=plan)
    nb, bs = cache_k.shape[0], cache_k.shape[1]
    mb = tables.shape[1]
    wpos = pos[:, None] + jnp.arange(m, dtype=jnp.int32)[None]  # [b, m]
    blk = jnp.take_along_axis(
        tables, jnp.clip(wpos // bs, 0, mb - 1), axis=1)
    blk = jnp.where(wpos < mb * bs, blk, nb)
    off = wpos % bs
    if k_scale is not None:
        from apex_tpu.serving.paged_cache import scatter_kv_quantized

        cache_k, cache_v, k_scale, v_scale = scatter_kv_quantized(
            cache_k, cache_v, k_scale, v_scale, k, v, (blk, off))
    else:
        cache_k = cache_k.at[blk, off].set(
            k.astype(cache_k.dtype), mode="drop")
        cache_v = cache_v.at[blk, off].set(
            v.astype(cache_v.dtype), mode="drop")
    tbl = jnp.minimum(tables, nb - 1)
    kk = cache_k[tbl].reshape(b, mb * bs, cache_k.shape[2],
                              cache_k.shape[3])
    vv = cache_v[tbl].reshape(b, mb * bs, cache_v.shape[2],
                              cache_v.shape[3])
    if k_scale is not None:
        from apex_tpu.serving.paged_cache import dequantize_kv

        kk = dequantize_kv(kk, k_scale[tbl].reshape(b, mb * bs, -1))
        vv = dequantize_kv(vv, v_scale[tbl].reshape(b, mb * bs, -1))
    x = _verify_attention(cfg, x, h, lp, q, kk, vv, pos, ll=ll,
                          plan=plan)
    return x, cache_k, cache_v, k_scale, v_scale


def decode_verify(params: dict, tokens: jax.Array, cache: dict,
                  cfg: TransformerConfig, *, lora=None):
    """Verification forward: ``m`` tokens per sequence in ONE batched
    pass → (logits [b, m, v], cache with ``pos`` advanced by m).

    ``lora`` (ISSUE 20): same bundle as ``decode_step`` — each
    sequence's slot id applies to all m of its rows, so a LoRA-serving
    engine's spec-verify (and its verify-based adapter prefill) runs
    the same per-row deltas as its decode steps.

    ``tokens`` [b, m] append at each sequence's ``cache['pos']``; token
    (i, j) lands at absolute position ``pos[i]+j``, attends to the
    cache prefix plus the tokens before it in the block, and its
    logits row predicts position ``pos[i]+j+1`` — feeding the gold
    sequence through this must reproduce ``decode_step`` run m times
    (tests/test_speculative.py pins it).

    This is speculative decoding's verify half (``models/
    speculative.py``): k drafted tokens cost one forward instead of k
    sequential decode steps, the per-step weight read amortized m ways
    — the batched-prefill economics of PR 3 applied to decode.
    Rollback of rejected tokens is the caller decrementing ``pos``:
    in BOTH layouts the rejected K/V entries become invisible (masks
    read ``t <= pos``) and are overwritten in place by the next
    append — no copy, and in the paged layout not even a block
    operation (the tail block simply has fewer live cells)."""
    _check_decode_cfg(cfg)
    cd = cfg.compute_dtype
    paged = "block_tables" in cache
    pos = _vector_pos(cache)
    b, m = tokens.shape
    x = jnp.take(params["embedding"]["word"].astype(cd), tokens, axis=0)
    if cfg.position_embedding_type == "learned":
        rows = jnp.clip(pos[:, None] + jnp.arange(m, dtype=jnp.int32),
                        0, cfg.max_position_embeddings - 1)
        pe = jnp.take(params["embedding"]["position"], rows, axis=0)
        x = x + pe.astype(cd)
    rope = None
    if cfg.position_embedding_type == "rope":
        if paged:
            max_pos = cache["block_tables"].shape[1] * cache["k"].shape[2]
        else:
            max_pos = cache["k"].shape[2]
        rope = rope_cos_sin(max_pos, cfg.kv_channels)

    slabs, plan = _lora_operands(lora, m=m)
    quant = "k_scale" in cache
    new_scales = None
    if paged and quant:
        tables = cache["block_tables"].astype(jnp.int32)

        def body(x, layer_in):
            lp, ck, cv, sk, sv, ll = layer_in
            x, ck, cv, sk, sv = _layer_verify_paged(
                cfg, lp, x, ck, cv, tables, pos, rope, sk, sv,
                ll=ll, plan=plan)
            return x, (ck, cv, sk, sv)

        x, (new_k, new_v, *new_scales) = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"],
                      cache["k_scale"], cache["v_scale"], slabs))
    elif paged:
        tables = cache["block_tables"].astype(jnp.int32)

        def body(x, layer_in):
            lp, ck, cv, ll = layer_in
            x, ck, cv, _sk, _sv = _layer_verify_paged(
                cfg, lp, x, ck, cv, tables, pos, rope, ll=ll, plan=plan)
            return x, (ck, cv)

        x, (new_k, new_v) = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"], slabs))
    else:
        def body(x, layer_in):
            lp, ck, cv, ll = layer_in
            x, ck, cv = _layer_verify(cfg, lp, x, ck, cv, pos, rope,
                                      ll=ll, plan=plan)
            return x, (ck, cv)

        x, (new_k, new_v) = jax.lax.scan(
            body, x, (params["layers"], cache["k"], cache["v"], slabs))
    x = apply_norm(cfg, x, params["final_ln"]["scale"],
                   params["final_ln"]["bias"])
    logits = jnp.einsum(
        "bsh,vh->bsv", x, lm_head_weight(params, cfg).astype(cd),
        preferred_element_type=jnp.float32)
    cache = {"k": new_k, "v": new_v, "pos": pos + m}
    if new_scales is not None:
        cache["k_scale"], cache["v_scale"] = new_scales
    if paged:
        cache["block_tables"] = tables
    return logits, cache


def _layer_prefill(cfg, lp, x, kpm, rope):
    """One layer over the whole prompt [b, s, h]: the training
    forward's attention block (``transformer_lm._attention`` with
    ``return_kv`` — ONE implementation of the projection/split/rope/
    flash-attention math, so prefill cannot drift from training) plus
    the residual/MLP wiring of ``_layer`` without dropout."""
    from apex_tpu.models.transformer_lm import (
        _attention, _mlp, single_device_ctx)

    ctx = single_device_ctx()
    h = apply_norm(cfg, x, lp["ln1_scale"], lp["ln1_bias"])
    a, k, v = _attention(cfg, lp, h, ctx, kpm, rope, None,
                         return_kv=True)
    res = h if cfg.apply_residual_connection_post_layernorm else x
    x = res + a
    h = apply_norm(cfg, x, lp["ln2_scale"], lp["ln2_bias"])
    m = _mlp(cfg, lp, h, ctx)
    res = h if cfg.apply_residual_connection_post_layernorm else x
    return res + m, k, v


@functools.partial(jax.jit, static_argnames=("cfg", "max_len",
                                             "cache_dtype"))
def prefill(
    params: dict,
    prompt: jax.Array,
    cfg: TransformerConfig,
    *,
    prompt_lens: Optional[jax.Array] = None,
    cache: Optional[dict] = None,
    max_len: Optional[int] = None,
    cache_dtype=None,
):
    """Consume a whole prompt [b, s] in ONE batched forward →
    (last-token logits [b, v], filled KV cache).

    This is the fast half of the prefill/decode split: the prompt runs
    through the full-sequence training forward (flash attention for the
    causal pattern — O(s·d) memory, MXU-tiled) and every layer's
    post-rope K/V lands in the cache in a single dynamic-update, so a
    512-token prompt costs one forward instead of 512 sequential
    :func:`decode_step` calls.

    Ragged batches: ``prompt_lens`` [b] int32 marks each row's real
    length (rows are LEFT-aligned, padding on the right).  Padding keys
    are masked in-kernel via the flash key-padding path; the garbage
    K/V written at a row's padding slots is invisible (decode masks
    ``t <= pos[i]``) and is overwritten slot-by-slot as that sequence
    decodes.  The returned ``cache['pos']`` equals ``prompt_lens``.

    ``cache``: fill an existing cache (e.g. a serving slot buffer of
    ``max_len`` > s); otherwise one is allocated at ``max_len``
    (default ``s``) with ``cache_dtype``.  A PAGED cache (built by
    ``init_kv_cache(..., cache_layout="paged")`` or the serving
    engine's block manager) is recognized by its ``block_tables``
    entry: prefill then writes whole pages — every position scatters
    through the table in one update, padding and unmapped pages
    dropping — and returns the same paged dict.
    """
    _check_decode_cfg(cfg)
    b, s = prompt.shape
    if cache is None:
        cache = init_kv_cache(cfg, b, max_len if max_len else s,
                              cache_dtype=cache_dtype)
    paged = "block_tables" in cache
    cache_len = (cache["block_tables"].shape[1] * cache["k"].shape[2]
                 if paged else cache["k"].shape[2])
    if s > cache_len:
        raise ValueError(
            f"prompt length {s} exceeds the cache max_len {cache_len}")
    cd = cfg.compute_dtype
    lens = (jnp.full((b,), s, jnp.int32) if prompt_lens is None
            else prompt_lens.astype(jnp.int32))
    # key-padding mask (True = masked) only when the batch is ragged —
    # the uniform path keeps the exact training-forward flash variant
    kpm = None
    if prompt_lens is not None:
        kpm = jnp.arange(s)[None] >= lens[:, None]

    x = jnp.take(params["embedding"]["word"].astype(cd), prompt, axis=0)
    if cfg.position_embedding_type == "learned":
        x = x + params["embedding"]["position"][:s].astype(cd)[None]
    rope = None
    if cfg.position_embedding_type == "rope":
        rope = rope_cos_sin(s, cfg.kv_channels)

    quant = "k_scale" in cache

    def body(x, lp):
        x, k, v = _layer_prefill(cfg, lp, x, kpm, rope)
        if quant:
            # int8 pool: keep the float K/V through the scan and
            # quantize once at the scatter edge below
            return x, (k, v)
        return x, (k.astype(cache["k"].dtype), v.astype(cache["v"].dtype))

    x, (ks, vs) = jax.lax.scan(body, x, params["layers"])

    x = apply_norm(cfg, x, params["final_ln"]["scale"],
                   params["final_ln"]["bias"])
    # logits for each row's LAST REAL token only ([b, h] @ head — the
    # [b, s, v] prompt logits are never materialized)
    x_last = jnp.take_along_axis(
        x, jnp.maximum(lens - 1, 0)[:, None, None], axis=1)[:, 0]
    logits = jnp.einsum(
        "bh,vh->bv", x_last, lm_head_weight(params, cfg).astype(cd),
        preferred_element_type=jnp.float32)
    if paged:
        # whole-page scatter through the block tables: position t of
        # row i lands in cell (tables[i, t//bs], t % bs).  Row padding
        # (t >= lens[i]) and unmapped table entries drop, so a ragged
        # prefill can never write into blocks the row does not own.
        tables = cache["block_tables"].astype(jnp.int32)
        nb, bs = cache["k"].shape[1], cache["k"].shape[2]
        mb = tables.shape[1]
        t = jnp.arange(s)
        blk = jnp.take_along_axis(
            tables, jnp.broadcast_to(
                jnp.minimum(t // bs, mb - 1)[None], (b, s)), axis=1)
        blk = jnp.where(t[None] < lens[:, None], blk, nb)
        off = jnp.broadcast_to(t % bs, (b, s))
        if quant:
            # quantize the whole prompt's K/V per (token, group); the
            # shared scatter keeps wire + scale cells paired (padding
            # and unmapped pages drop both together)
            from apex_tpu.serving.paged_cache import scatter_kv_quantized

            ck, cv, sk, sv = scatter_kv_quantized(
                cache["k"], cache["v"], cache["k_scale"],
                cache["v_scale"], ks, vs, (slice(None), blk, off))
            cache = {
                "k": ck, "v": cv, "k_scale": sk, "v_scale": sv,
                "pos": lens,
                "block_tables": tables,
            }
            return logits, cache
        cache = {
            "k": cache["k"].at[:, blk, off].set(ks, mode="drop"),
            "v": cache["v"].at[:, blk, off].set(vs, mode="drop"),
            "pos": lens,
            "block_tables": tables,
        }
        return logits, cache
    cache = {
        "k": jax.lax.dynamic_update_slice_in_dim(
            cache["k"], ks, 0, axis=2),
        "v": jax.lax.dynamic_update_slice_in_dim(
            cache["v"], vs, 0, axis=2),
        "pos": lens,
    }
    return logits, cache


@functools.partial(jax.jit, static_argnames=("cfg",))
def _prefill_chunk_forward(params, chunk, cache, cfg):
    """One jitted chunk of a chunked prefill: ``chunk`` [b, m] appends
    at ``cache['pos']`` and attends to the already-written KV prefix
    plus itself causally — exactly a verification forward, so this IS
    :func:`decode_verify` under a shape-keyed jit (equal chunk sizes
    share one compile; the serving engine additionally pins its chunk
    shape to a single bucket)."""
    return decode_verify(params, chunk, cache, cfg)


@functools.partial(jax.jit, static_argnames=("cfg",),
                   donate_argnames=("cache",))
def _prefill_chunk_forward_donated(params, chunk, cache, cfg):
    """The donated form for chunks after the first: their input cache
    is loop-local (the previous chunk's output), so the pool updates
    in place instead of copying the whole K/V buffer per chunk.  The
    FIRST chunk must not donate — its cache belongs to the caller."""
    return decode_verify(params, chunk, cache, cfg)


def prefill_chunked(
    params: dict,
    prompt: jax.Array,
    cfg: TransformerConfig,
    *,
    chunk_tokens: int,
    prompt_lens: Optional[jax.Array] = None,
    cache: Optional[dict] = None,
    max_len: Optional[int] = None,
    cache_dtype=None,
):
    """Chunked prefill (ISSUE 15, Sarathi-style): consume a prompt
    [b, s] in ``ceil(s / chunk_tokens)`` fixed-size forwards instead of
    one monolithic pass → (last-real-token logits [b, v], filled KV
    cache) — the same contract as :func:`prefill`.

    Each chunk is ONE batched forward whose queries attend to the KV
    prefix the earlier chunks already wrote plus the chunk itself
    causally — the verification-block attention pattern
    (:func:`decode_verify`), which is why chunk c's compute is
    O(chunk · (c·chunk)) and the total stays the O(s²) of the
    monolithic prefill: nothing is recomputed, only *scheduled*
    differently.  That scheduling is the point: a serving engine can
    interleave decode steps for co-resident requests between chunks,
    so a 32k-token prompt stalls its neighbors for one ``chunk_tokens``
    forward at a time instead of one 32k forward
    (``ServingEngine(chunk_tokens=...)`` builds on this; the TPOT
    interference bound is measured by ``bench.py``'s chunked
    starvation row).

    Greedy-token-identity: the final chunk's last-token logits ARE the
    first-token logits — ``argmax`` equal to :func:`prefill`'s, and a
    greedy continuation from the chunked cache is token-identical to
    one from the monolithic cache on BOTH cache layouts
    (tests/test_serving_chunked.py pins it; K/V written by a chunk
    may differ from the monolithic writer's in low-order bits — flash
    vs verify accumulation order — which is also why the serving
    engine never prefix-shares chunk-written blocks).  On an int8
    ``cache_wire`` pool later chunks read the *quantized* prefix
    (monolithic prefill quantizes only at the final scatter), so the
    contract there is the PR-14 one: deterministic,
    first-token-identical, trajectory may diverge.

    Ragged batches: ``prompt_lens`` [b] marks real row lengths.  Rows
    whose prompt ends inside an earlier chunk ride later chunks
    inertly — their writes land past their length (invisible to every
    masked read, overwritten by decode before it ever attends there)
    and their last-token logits are taken from the chunk that held
    position ``lens[i]-1``.

    ``cache`` / ``max_len`` / ``cache_dtype`` behave as in
    :func:`prefill`; a paged cache (``block_tables`` present, int8
    ``cache_wire`` included) scatters each chunk through its block
    tables via the existing verify write edges.
    """
    _check_decode_cfg(cfg)
    if chunk_tokens < 1:
        raise ValueError(
            f"chunk_tokens={chunk_tokens} must be >= 1")
    b, s = prompt.shape
    if cache is None:
        cache = init_kv_cache(cfg, b, max_len if max_len else s,
                              cache_dtype=cache_dtype)
    paged = "block_tables" in cache
    cache_len = (cache["block_tables"].shape[1] * cache["k"].shape[2]
                 if paged else cache["k"].shape[2])
    if s > cache_len:
        raise ValueError(
            f"prompt length {s} exceeds the cache max_len {cache_len}")
    lens = (jnp.full((b,), s, jnp.int32) if prompt_lens is None
            else jnp.asarray(prompt_lens, jnp.int32))
    logits_last = None
    for lo in range(0, s, chunk_tokens):
        hi = min(s, lo + chunk_tokens)
        # rows already complete park at their own length: their chunk
        # writes land past it (masked reads never see them, decode
        # overwrites them in place) and their pos is restored below
        cache = dict(cache, pos=jnp.minimum(lens, lo))
        fwd = (_prefill_chunk_forward if lo == 0
               else _prefill_chunk_forward_donated)
        logits, cache = fwd(params, prompt[:, lo:hi], cache, cfg)
        take = jnp.clip(lens - 1 - lo, 0, hi - lo - 1)
        lg = jnp.take_along_axis(
            logits, take[:, None, None], axis=1)[:, 0]
        hit = (lens - 1 >= lo) & (lens - 1 < hi)
        logits_last = (lg if logits_last is None
                       else jnp.where(hit[:, None], lg, logits_last))
    return logits_last, dict(cache, pos=lens)


def sample_logits(logits, key, *, temperature: float = 0.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None,
                  vocab_limit: Optional[int] = None):
    """Pick next tokens [b] from logits [b, v] (greedy at
    ``temperature=0``; otherwise softmax sampling with optional
    ``top_k`` and/or nucleus ``top_p`` cutoffs — both given =
    intersection, top_k first).

    ``vocab_limit`` masks logits at and beyond that id — REQUIRED
    knowledge for padded vocab tables (tools/import_hf.py pads GPT-2's
    50257 to 50304; the zero-logit pad ids would otherwise be sampleable
    and can even win argmax when all real logits are negative).

    Since ISSUE 8 this is a thin wrapper over
    :func:`apex_tpu.ops.fused_sampling.fused_sample`, which fuses the
    whole temperature → top-k/top-p → draw chain into one kernel on the
    decode hot path (``APEX_TPU_FUSED_SAMPLING`` routes; the XLA
    reference path is bit-identical to the historical op sequence
    given the same key, so seeded callers see no change off-TPU).
    ``temperature == 0`` short-circuits every filter and returns the
    argmax — the cutoffs cannot change which token is largest
    (regression-pinned in tests/test_fused_sampling.py).
    """
    _check_sampling_args(temperature, top_k)
    return fused_sample(logits, key, temperature=temperature,
                        top_k=top_k, top_p=top_p,
                        vocab_limit=vocab_limit)


@functools.partial(jax.jit, static_argnames=(
    "cfg", "max_new_tokens", "temperature", "top_k", "top_p",
    "vocab_limit", "eos_token_id", "cache_dtype", "cache_layout",
    "block_size", "cache_wire", "decode_fused"))
def _generate_impl(params, prompt, prompt_lens, rng, *, cfg,
                   max_new_tokens, temperature, top_k, top_p,
                   vocab_limit, eos_token_id, cache_dtype,
                   cache_layout, block_size, cache_wire=None,
                   decode_fused="reference"):
    """Prefill + while-loop decode; returns (tokens, realized steps)."""
    b, s = prompt.shape
    total = s + max_new_tokens
    cache = init_kv_cache(cfg, b, total, cache_dtype=cache_dtype,
                          cache_layout=cache_layout,
                          block_size=block_size, cache_wire=cache_wire)
    lens = (jnp.full((b,), s, jnp.int32) if prompt_lens is None
            else prompt_lens.astype(jnp.int32))
    logits, cache = prefill(params, prompt, cfg,
                            prompt_lens=prompt_lens, cache=cache)
    tokens = jnp.concatenate(
        [prompt, jnp.zeros((b, max_new_tokens), prompt.dtype)], axis=1)
    col = jnp.arange(total)

    def pick(lg, key):
        return sample_logits(lg, key, temperature=temperature,
                             top_k=top_k, top_p=top_p,
                             vocab_limit=vocab_limit)

    def cond(carry):
        i, done = carry[0], carry[1]
        # the loop only needs max_new_tokens - 1 decode forwards: the
        # first token comes from the prefill logits and the LAST one
        # needs no decode_step (nothing ever consumes its K/V)
        return (i < max_new_tokens - 1) & ~jnp.all(done)

    def body(carry):
        i, done, logits, tokens, cache, key = carry
        key, sub = jax.random.split(key)
        nxt = pick(logits, sub)
        # each live sequence appends at its own end (lens[i] + step) —
        # the emitted EOS itself is written, later steps are not
        wmask = (col[None] == (lens + i)[:, None]) & (~done)[:, None]
        tokens = jnp.where(wmask, nxt[:, None].astype(tokens.dtype),
                           tokens)
        if eos_token_id is not None:
            done = done | (nxt == eos_token_id)
        # the decode batch stays rectangular: finished sequences still
        # step (their logits are ignored) but their cache position is
        # frozen so they stop consuming slots
        prev = cache["pos"]
        logits, cache = decode_step(params, nxt.astype(prompt.dtype),
                                    cache, cfg,
                                    decode_fused=decode_fused)
        cache = dict(cache, pos=jnp.where(done, prev, cache["pos"]))
        return (i + 1, done, logits, tokens, cache, key)

    carry = (jnp.int32(0), jnp.zeros((b,), bool), logits, tokens, cache,
             rng)
    i, done, logits, tokens, _, key = jax.lax.while_loop(cond, body,
                                                         carry)
    # the final token: sampled from the last logits, no decode behind it
    if max_new_tokens > 0:
        _, sub = jax.random.split(key)
        nxt = pick(logits, sub)
        wmask = (col[None] == (lens + i)[:, None]) & (~done)[:, None]
        tokens = jnp.where(wmask, nxt[:, None].astype(tokens.dtype),
                           tokens)
    return tokens, i


def generate(
    params: dict,
    prompt: jax.Array,
    cfg: TransformerConfig,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
    vocab_limit: Optional[int] = None,
    prompt_lens: Optional[jax.Array] = None,
    eos_token_id: Optional[int] = None,
    cache_dtype=None,
    cache_layout: str = "contiguous",
    block_size: int = DEFAULT_BLOCK_SIZE,
    cache_wire=None,
    spec=None,
) -> jax.Array:
    """Decode up to ``max_new_tokens`` past ``prompt`` [b, s] →
    [b, s+max_new_tokens].

    ``cache_wire="int8"`` (paged layout only, ISSUE 14) stores the
    block pool at rest as block-scaled int8 — halving-plus the
    resident cache bytes, with K/V quantized at every write and
    dequantized inside the paged-attention kernel.  Greedy output is
    deterministic but MAY diverge from the native-pool trajectory
    (each decoded token's hidden state reads slightly-lossy K/V);
    docs/inference.md "Quantized serving" has the accuracy story and
    the spec-decode accept-rate gate that bounds it.

    ``spec`` enables speculative decoding (``"ngram"`` for n-gram
    self-drafting with the default knobs, a ``models.speculative.
    SpecConfig`` for tuning or a draft-model hook, ``None``/``"off"``
    for the plain path): k drafted tokens are verified by ONE batched
    :func:`decode_verify` forward per round instead of k sequential
    decode steps.  Greedy output is token-identical to ``spec=None``
    on both cache layouts and sampling is distribution-identical
    (``models/speculative.py`` has the correctness argument); the
    realized ``generate.spec.{draft_tokens,accepted_tokens,
    verify_calls}`` counters land in telemetry when configured.

    The decode layer routes through the FUSED decode step
    (``ops/decode_step.py``: rope + attention + output projection in
    one kernel, ``APEX_TPU_DECODE_FUSED=kernel|reference|auto``) —
    greedy output is token-identical across routes on both layouts and
    both ``cache_wire`` forms (tests/test_decode_fused.py pins it);
    the route is resolved here, outside the jit, and threaded as a
    static argument so env flips retrace instead of replaying a stale
    trace.

    ``cache_layout="paged"`` runs the same prefill + while-loop decode
    over the block-pool cache (``block_size`` tokens per block, tables
    filled linearly) and the fused ragged-paged attention kernel —
    greedy output is token-identical to the contiguous layout
    (tests/test_generate_paged.py pins it); the layout exists for the
    serving engine, where blocks are allocated dynamically.

    The prompt is consumed by ONE batched :func:`prefill` forward
    (flash attention, whole KV cache written in one pass); decoding is
    a ``lax.while_loop`` over :func:`decode_step` that exits as soon as
    every sequence has emitted ``eos_token_id`` (when given) instead of
    always scanning ``max_new_tokens``.

    ``temperature=0`` is greedy; otherwise softmax sampling with the
    optional ``top_k`` / nucleus ``top_p`` cutoffs of
    :func:`sample_logits`.  ``vocab_limit`` masks padded vocab ids
    (tools/import_hf.py).

    Ragged batches: pass right-padded prompts plus ``prompt_lens`` [b]
    int32.  Each sequence decodes from its own length — generated
    tokens overwrite the row's padding left-to-right, so row ``i``
    holds its prompt in ``[:lens[i]]``, its generation in
    ``[lens[i]:lens[i]+n_i]``, and untouched padding after.  Greedy
    output is token-identical to running each sequence through its own
    unbatched ``generate`` call (tests/test_generate.py pins this).

    When telemetry is configured the call records
    ``generate.prefill_calls`` and ``generate.decode_steps`` counters —
    the decode-step count equals the realized while-loop trip count
    (``== max_new_tokens - 1`` when no sequence stops early: the first
    token comes from the prefill logits and the last needs no decode
    behind it), which is how the prefill-not-per-token property is
    asserted in tests — the count scales with the NEW tokens, never
    with the prompt length.
    """
    b, s = prompt.shape
    total = s + max_new_tokens
    if (cfg.position_embedding_type == "learned"
            and total > cfg.max_position_embeddings):
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_position_embeddings ({cfg.max_position_embeddings}); "
            "the learned position lookup would silently clamp")
    _check_sampling_args(temperature, top_k)
    _check_decode_cfg(cfg)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if prompt_lens is not None:
        prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
    if cache_layout not in ("contiguous", "paged"):
        raise ValueError(
            f"cache_layout={cache_layout!r}: expected 'contiguous' or "
            "'paged'")
    from apex_tpu.models.speculative import resolve_spec, spec_generate

    if resolve_spec(spec) is not None:
        tokens, stats = spec_generate(
            params, prompt, cfg, spec=spec,
            max_new_tokens=max_new_tokens, temperature=temperature,
            top_k=top_k, top_p=top_p, rng=rng, vocab_limit=vocab_limit,
            prompt_lens=prompt_lens, eos_token_id=eos_token_id,
            cache_dtype=cache_dtype, cache_layout=cache_layout,
            block_size=block_size, cache_wire=cache_wire)
        if _telemetry.enabled():
            _telemetry.counter("generate.prefill_calls").inc()
            _telemetry.counter("generate.spec.draft_tokens").inc(
                stats["draft_tokens"])
            _telemetry.counter("generate.spec.accepted_tokens").inc(
                stats["accepted_tokens"])
            _telemetry.counter("generate.spec.verify_calls").inc(
                stats["verify_calls"])
        return tokens
    # resolve the fused-decode route HERE, outside the jit: threading
    # the resolved route through the static args keys the trace cache
    # on it, so flipping APEX_TPU_DECODE_FUSED between calls retraces
    # instead of replaying the first call's frozen route
    from apex_tpu.ops.decode_step import route_decode_fused

    tokens, n_steps = _generate_impl(
        params, prompt, prompt_lens, rng, cfg=cfg,
        max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, top_p=top_p, vocab_limit=vocab_limit,
        eos_token_id=eos_token_id, cache_dtype=cache_dtype,
        cache_layout=cache_layout, block_size=block_size,
        cache_wire=cache_wire, decode_fused=route_decode_fused(None))
    if _telemetry.enabled():
        # host-side counters (the jitted loop cannot emit); reading the
        # realized trip count syncs — acceptable when telemetry is on
        _telemetry.counter("generate.prefill_calls").inc()
        _telemetry.counter("generate.decode_steps").inc(int(n_steps))
    return tokens
