"""Weight-only quantized serving params: the one-shot conversion.

Decode is HBM-bandwidth-bound — every generated token re-reads the
whole weight set, so resident weight bytes set tokens/s (ISSUE 14,
ROADMAP item 3).  :func:`quantize_params` converts a trained/imported
GPT parameter tree into the int8 weight-slab form the serving stack
consumes: every per-layer matmul kernel (``qkv_kernel``,
``proj_kernel``, ``fc1_kernel``, ``fc2_kernel``, and — MoE configs —
the ``moe_fc1``/``moe_fc2`` expert slabs) becomes a ``{"wire": int8,
"scale": fp32}`` dict with per-(contraction-block, output-column)
scales (:func:`~apex_tpu.ops.dense.quantize_weight` /
:func:`~apex_tpu.ops.grouped_matmul.quantize_group_weights`).  The
model code branches on :func:`~apex_tpu.ops.dense.is_quantized` at
each matmul site and runs the in-kernel dequantizing matmul, so the
HBM weight read per decode step drops to the int8 bytes
(~1/4 of fp32, ~1/2 of bf16) — compounding with the int8 KV pool.

What stays high-precision, on purpose:

- **embedding / LM head** — the embedding is a gather (no bandwidth
  win from int8 without a fused dequantizing gather) and the tied head
  shares its table; the head matmul runs once per token against
  activations that just left a norm — keep it exact;
- **biases, norms, rope** — O(h) parameters, noise in the byte budget;
- **everything under training** — the quantized tree is a SERVING
  artifact: gradients through :func:`~apex_tpu.ops.dense.
  dense_quantized` flow to activations only (wire/scales frozen), and
  the manual-TP training contexts reject quantized leaves loudly.

:func:`dequantize_params` is the fake-quant oracle: a float tree whose
kernels equal the dequantized slabs exactly, so
``generate(quantize_params(p)) == generate(dequantize_params(
quantize_params(p)))`` greedy token-for-token — the pin that separates
"the int8 path computes what it claims" from "int8 changed the model"
(tests/test_quantized_matmul.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.dense import (
    QUANT_BLOCK, dequantize_weight, is_quantized, quantize_weight)
from apex_tpu.ops.grouped_matmul import quantize_group_weights

__all__ = ["dequantize_params", "is_quantized_tree", "param_bytes",
           "quantize_params"]

# per-layer kernels quantized through the dense path ([in, *out],
# contraction axis first)
_DENSE_KERNELS = ("qkv_kernel", "proj_kernel", "fc1_kernel",
                  "fc2_kernel")
# expert slabs quantized through the grouped path ([G, k, p])
_GROUPED_KERNELS = ("moe_fc1", "moe_fc2")


def _q_dense_stacked(w, block):
    """Quantize a stacked per-layer kernel ``[L, in, *out]`` (vmapped
    over the layer axis; the block is picked ONCE from the shared
    in-dim so every layer's scale grid lines up)."""
    return jax.vmap(lambda wl: quantize_weight(wl, block))(w)


def _q_grouped_stacked(w, block):
    """Quantize a stacked expert slab ``[L, G, k, p]``."""
    return jax.vmap(lambda wl: quantize_group_weights(wl, block))(w)


def quantize_params(params: dict, *,
                    block: Optional[int] = None) -> dict:
    """One-shot serving conversion: return a new parameter tree whose
    per-layer matmul kernels are int8 weight slabs (module docstring
    has the scope).  ``block`` bounds the contraction-axis scale block
    (default 128, clamped to a divisor of each kernel's in-dim).  The
    input tree is not modified; unquantized leaves are shared, not
    copied.  Idempotent-hostile by design: quantizing an
    already-quantized tree raises (re-quantizing dequantized weights
    would silently stack error)."""
    block = int(block or QUANT_BLOCK)
    layers = dict(params["layers"])
    for name in _DENSE_KERNELS:
        w = layers.get(name)
        if w is None:
            continue
        if is_quantized(w):
            raise ValueError(
                f"params['layers'][{name!r}] is already quantized — "
                "quantize_params expects a float tree")
        layers[name] = _q_dense_stacked(jnp.asarray(w), block)
    for name in _GROUPED_KERNELS:
        w = layers.get(name)
        if w is None:
            continue
        if is_quantized(w):
            raise ValueError(
                f"params['layers'][{name!r}] is already quantized — "
                "quantize_params expects a float tree")
        layers[name] = _q_grouped_stacked(jnp.asarray(w), block)
    return dict(params, layers=layers)


def dequantize_params(params: dict) -> dict:
    """The fake-quant oracle: replace every quantized slab with its
    fp32-dequantized float kernel.  ``generate`` over this tree is
    greedy token-identical to the quantized tree (the quantized matmul
    computes exactly ``x @ dequantize(w)`` up to fp32 summation
    order)."""
    layers = dict(params["layers"])
    for name, leaf in list(layers.items()):
        if not is_quantized(leaf):
            continue
        wire, scale = leaf["wire"], leaf["scale"]
        if name in _GROUPED_KERNELS:
            # stacked [L, G, k, p] expert slab: per-layer grouped form
            from apex_tpu.ops.grouped_matmul import _dequantize_group

            layers[name] = jax.vmap(_dequantize_group)(wire, scale)
        else:
            # stacked [L, in, *out] dense kernel (swiglu fc1 included)
            layers[name] = jax.vmap(dequantize_weight)(wire, scale)
    return dict(params, layers=layers)


def is_quantized_tree(params: dict) -> bool:
    """True when any layer kernel carries the int8 slab form."""
    return any(is_quantized(leaf)
               for leaf in params.get("layers", {}).values())


def param_bytes(params: dict) -> int:
    """Resident bytes of a parameter tree (quantized dicts count wire
    + scales) — the number the bench weight-bytes ratio reports."""
    return sum(x.size * jnp.dtype(x.dtype).itemsize
               for x in jax.tree_util.tree_leaves(params))
