"""Standalone parallel transformer LM — the flagship model family.

Reference: apex/transformer/testing/standalone_transformer_lm.py (1,574 LoC
Megatron LM: ``Embedding`` :1239, ``ParallelAttention`` :358, ``ParallelMLP``
:165, ``ParallelTransformerLayer`` :598, ``ParallelTransformer`` :780,
``TransformerLanguageModel`` :1358, ``parallel_lm_logits`` :1130).

TPU-native redesign — *one functional core, two parallel modes*:

- Parameters are a plain pytree (layers stacked on a leading ``L`` axis so
  the whole decoder is a single ``lax.scan`` — one compiled layer body
  regardless of depth, the XLA-friendly shape of Megatron's ModuleList).
- The forward is a pure function ``gpt_forward(params, tokens, cfg, ctx)``.
  All tensor-parallel communication is injected through a tiny
  :class:`TPContext`, with two implementations:

  * :func:`gspmd_ctx` — sharding *constraints*; run under ``jit`` over a
    mesh and XLA's SPMD partitioner inserts the collectives the reference
    issues by hand (the recommended path).
  * :func:`manual_ctx` — the eight mapping collectives
    (tensor_parallel/mappings.py) for use inside ``shard_map``; params are
    local shards and head/ffn counts divide by ``tp``. This is the mode the
    pipeline schedules compose with.

- Activations are batch-major ``[b, s, h]`` (TPU/XLA convention) rather
  than the reference's ``[s, b, h]``.
"""

from __future__ import annotations

import functools
import os
import warnings
from typing import Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.models.config import TransformerConfig
from apex_tpu.ops import (
    fused_apply_rotary_pos_emb_cached,
    fused_layer_norm,
    fused_rms_norm,
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
    softmax_cross_entropy_loss,
)
from apex_tpu.ops.dense import is_quantized as _is_quantized
from apex_tpu.ops.swiglu import fused_bias_swiglu_paired
from apex_tpu.transformer.tensor_parallel.mappings import (
    copy_to_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
)

__all__ = [
    "TPContext",
    "gspmd_ctx",
    "manual_ctx",
    "single_device_ctx",
    "init_gpt_params",
    "gpt_param_specs",
    "gpt_forward",
    "gpt_loss",
    "lm_cross_entropy",
    "apply_norm",
    "rope_cos_sin",
]


# ---------------------------------------------------------------------------
# Tensor-parallel context
# ---------------------------------------------------------------------------


class TPContext(NamedTuple):
    """Injected TP communication — the model's only coupling to parallelism.

    ``tp`` is the degree by which *local* param shards are divided (1 under
    GSPMD where shapes stay global) and ``tp_axis`` the mesh axis name the
    vocab-parallel embed/CE collectives run over. ``copy_in`` enters a
    column-parallel region (reference mappings.py:268
    ``copy_to_tensor_model_parallel_region``); ``reduce_out`` exits a
    row-parallel region (allreduce of partials, mappings.py:83). The
    ``constrain_*`` hooks are GSPMD sharding hints and identity in manual
    mode; ``constrain_col`` receives activations of any rank with the
    tp-sharded dim last.
    """

    tp: int
    tp_axis: str
    copy_in: Callable[[jax.Array], jax.Array]
    reduce_out: Callable[[jax.Array], jax.Array]
    constrain_hidden: Callable[[jax.Array], jax.Array]
    constrain_col: Callable[[jax.Array], jax.Array]
    vocab_parallel: bool
    # context parallelism: when set, core attention stays
    # sequence-sharded over this mesh axis.  cp_mode picks the
    # algorithm: "ring" (K/V chunks ppermute around the ring,
    # O(s_local·n·d) memory — parallel/ring_attention.py) or "ulysses"
    # (all-to-all head re-sharding, one full-sequence flash call per
    # head group, O(s_global·n/sp·d) — parallel/ulysses.py).  The
    # reference has neither (SURVEY §5); this is the TPU-native
    # long-context path, first-class in the flagship model.  cp_qkv_spec
    # is the [b, s, n, d] partitioning the shard_map wrapper pins so the
    # batch (dp) and head (tp) shardings survive the manual region.
    cp_axis: Optional[str] = None
    cp_qkv_spec: Optional[P] = None
    cp_mode: str = "ring"
    # overlapped TP collectives (ops/collective_matmul): when set, the
    # row-parallel exits (attention proj, MLP fc2) call
    # ``row_parallel_matmul(x, w)`` instead of ``reduce_out(x @ w)`` —
    # the hook fuses the matmul with its reduction as a ppermute ring so
    # transfer hops overlap partial-product chunks.  The hook returns
    # ``None`` whenever the ring path does not apply (overlap disabled,
    # no mesh, tp absent/1, indivisible shapes) and the caller falls
    # back to the exact monolithic expression.
    row_parallel_matmul: Optional[Callable] = None


def _constrain(x, spec: P):
    """Apply a sharding constraint when a mesh context is active; no-op
    outside one (single-device tests). Never swallows real sharding errors:
    the mesh/axis check is explicit rather than a blanket except."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty:
        return x
    names = set(mesh.axis_names)
    for part in spec:
        axes = part if isinstance(part, tuple) else (part,)
        for a in axes:
            if a is not None and a not in names:
                return x
    return jax.lax.with_sharding_constraint(x, spec)


def gspmd_ctx(batch_axis: str = "dp", tp_axis: str = "tp",
              seq_axis: Optional[str] = None,
              context_parallel: Union[bool, str] = False,
              overlap_comm: Optional[bool] = None) -> TPContext:
    """Constraint-based context: annotate, let XLA partition.

    ``seq_axis`` shards activations along sequence (Megatron SP under
    GSPMD).  ``context_parallel`` additionally keeps core attention
    sequence-sharded over ``seq_axis`` — without it, XLA's default
    strategy all-gathers K/V per device, whose O(s_global) activations
    cap the sequence length.  ``True`` or ``"ring"`` selects ring
    attention (O(s_local) memory); ``"ulysses"`` selects all-to-all
    head re-sharding (one full-sequence flash call per head group —
    needs num_heads divisible by the axis size).

    ``overlap_comm`` routes the row-parallel matmul+reduce exits
    through the ring collective-matmul (``ops/collective_matmul``):
    ``True``/``False`` is explicit, ``None`` (default) inherits
    ``collective_matmul.overlap_scope`` at trace time — which is how
    ``amp.frontend.make_train_step(overlap_comm=...)`` reaches contexts
    it never sees."""
    if context_parallel and seq_axis is None:
        raise ValueError(
            "context_parallel requires seq_axis (the mesh axis the "
            "sequence is sharded over)")
    if context_parallel not in (False, True, "ring", "ulysses"):
        raise ValueError(
            f"context_parallel={context_parallel!r}: expected "
            "False | True | 'ring' | 'ulysses'")
    cp_mode = "ulysses" if context_parallel == "ulysses" else "ring"

    def hidden(x):
        return _constrain(x, P(batch_axis, seq_axis, *([None] * (x.ndim - 2))))

    def col(x):
        return _constrain(
            x, P(batch_axis, *([None] * (x.ndim - 2)), tp_axis))

    def row_mm(x, w):
        # ring matmul-reduce-scatter island over tp; the hidden
        # constraint re-gathers the sequence-scattered result lazily
        # (XLA overlaps that all-gather with downstream compute)
        from apex_tpu.ops.collective_matmul import gspmd_row_parallel_matmul

        y = gspmd_row_parallel_matmul(
            x, w, tp_axis=tp_axis, batch_axis=batch_axis,
            seq_axis=seq_axis, enable=overlap_comm)
        return None if y is None else hidden(y)

    return TPContext(
        tp=1,
        tp_axis=tp_axis,
        copy_in=lambda x: x,
        reduce_out=hidden,
        constrain_hidden=hidden,
        constrain_col=col,
        vocab_parallel=False,
        cp_axis=seq_axis if context_parallel else None,
        cp_qkv_spec=(P(batch_axis, seq_axis, tp_axis, None)
                     if context_parallel else None),
        cp_mode=cp_mode,
        row_parallel_matmul=row_mm if overlap_comm is not False else None,
    )


def manual_ctx(tp: int, axis: str = "tp",
               overlap_comm: Optional[bool] = None) -> TPContext:
    """shard_map context: explicit mapping collectives, local shards.

    ``overlap_comm`` (tri-state like :func:`gspmd_ctx`) swaps the
    row-parallel exits' matmul → psum for the ring
    ``matmul_all_reduce`` (reduce-scatter hops overlapped with the
    partial-product chunks, then an all-gather; backward stays
    communication-free exactly like ``reduce_from``'s identity)."""

    def row_mm(x, w):
        from apex_tpu.ops import collective_matmul as _cm

        if tp <= 1 or not _cm.overlap_enabled(overlap_comm):
            return None
        # scatter the largest leading dim the axis divides (prefer the
        # sequence dim of [b, s, k] inputs); no fit → monolithic psum
        for d in (1, 0) if x.ndim >= 3 else (0,):
            if x.shape[d] % tp == 0:
                return _cm.matmul_all_reduce(x, w, axis, scatter_dim=d)
        return None

    return TPContext(
        tp=tp,
        tp_axis=axis,
        copy_in=lambda x: copy_to_tensor_model_parallel_region(x, axis),
        reduce_out=lambda x: reduce_from_tensor_model_parallel_region(
            x, axis),
        constrain_hidden=lambda x: x,
        constrain_col=lambda x: x,
        vocab_parallel=tp > 1,
        row_parallel_matmul=row_mm if overlap_comm is not False else None,
    )


def single_device_ctx() -> TPContext:
    return TPContext(
        tp=1, tp_axis="tp", copy_in=lambda x: x, reduce_out=lambda x: x,
        constrain_hidden=lambda x: x, constrain_col=lambda x: x,
        vocab_parallel=False,
    )


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_gpt_params(rng: jax.Array, cfg: TransformerConfig) -> dict:
    """Full (unsharded) parameter pytree.

    Init follows the reference: N(0, std) everywhere
    (standalone_transformer_lm.py:146 ``init_method_normal``), with output
    projections scaled by 1/sqrt(2L) (:155 ``scaled_init_method_normal``).
    Layers are stacked on a leading ``num_layers`` axis.
    """
    h, L = cfg.hidden_size, cfg.num_layers
    p = cfg.projection_size
    f = cfg.ffn_hidden_size
    std = cfg.init_method_std
    out_std = std / (2.0 * L) ** 0.5
    dt = cfg.params_dtype

    ks = jax.random.split(rng, 8)

    def nrm(k, shape, s):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(dt)

    # swiglu uses the paired [h, 2, f] layout: sharding the trailing f dim
    # keeps each tp shard a (gate, up) pair (see ops.swiglu paired variant)
    fc1_shape = ((L, h, 2, f) if cfg.activation == "swiglu" else (L, h, f))
    fc1_bias_shape = ((L, 2, f) if cfg.activation == "swiglu" else (L, f))

    layers = {
        "ln1_scale": jnp.ones((L, h), dt),
        "ln1_bias": jnp.zeros((L, h), dt),
        # MHA keeps the legacy per-head-interleaved 3p layout (golden
        # traces + the HF importer depend on it); GQA uses the
        # group-major layout — per query group [q x rep | k | v] — the
        # direct generalization of the MHA per-head [q|k|v] (rep=1),
        # chosen so a contiguous tp chunk of this axis holds whole
        # groups and manual tensor parallelism stays legal (see
        # split_qkv_gqa)
        "qkv_kernel": nrm(ks[1], (L, h, p + 2 * cfg.kv_projection_size),
                          std),
        "qkv_bias": jnp.zeros((L, p + 2 * cfg.kv_projection_size), dt),
        "proj_kernel": nrm(ks[2], (L, p, h), out_std),
        "proj_bias": jnp.zeros((L, h), dt),
        "ln2_scale": jnp.ones((L, h), dt),
        "ln2_bias": jnp.zeros((L, h), dt),
    }
    if cfg.num_experts:
        E = cfg.num_experts
        # swiglu experts carry the concatenated [gate ‖ up] fc1 (2f)
        f1 = 2 * f if cfg.activation == "swiglu" else f
        layers.update({
            "router_kernel": nrm(ks[3], (L, h, E), std),
            "moe_fc1": nrm(ks[4], (L, E, h, f1), std),
            "moe_fc1_bias": jnp.zeros((L, E, f1), dt),
            "moe_fc2": nrm(ks[7], (L, E, f, h), out_std),
            "moe_fc2_bias": jnp.zeros((L, E, h), dt),
        })
    else:
        layers.update({
            "fc1_kernel": nrm(ks[3], fc1_shape, std),
            "fc1_bias": jnp.zeros(fc1_bias_shape, dt),
            "fc2_kernel": nrm(ks[4], (L, f, h), out_std),
            "fc2_bias": jnp.zeros((L, h), dt),
        })

    params = {
        "embedding": {
            "word": nrm(ks[0], (cfg.vocab_size, h), std),
        },
        "layers": layers,
        "final_ln": {
            "scale": jnp.ones((h,), dt),
            "bias": jnp.zeros((h,), dt),
        },
    }
    if cfg.position_embedding_type == "learned":
        params["embedding"]["position"] = nrm(
            ks[5], (cfg.max_position_embeddings, h), std)
    if cfg.untie_embeddings_and_output_weights:
        params["lm_head"] = {"kernel": nrm(ks[6], (cfg.vocab_size, h), std)}
    return params


def gpt_param_specs(cfg: TransformerConfig, *, tp_axis: str = "tp",
                    pp_axis: Optional[str] = None) -> dict:
    """PartitionSpec tree matching :func:`init_gpt_params`.

    Used both for GSPMD ``device_put``/``in_shardings`` and as ``shard_map``
    in_specs (with ``pp_axis`` set, layer stacks gain a leading pipeline
    shard dim — see models/pipeline.py). Mirrors the reference's sharding:
    vocab rows over tp (layers.py:167), qkv/fc1 columns over tp (:429),
    proj/fc2 rows over tp (:613).
    """
    t = tp_axis
    pp = (pp_axis,) if pp_axis else ()
    swiglu = cfg.activation == "swiglu"

    layer_specs = {
        "ln1_scale": P(*pp, None, None),
        "ln1_bias": P(*pp, None, None),
        "qkv_kernel": P(*pp, None, None, t),
        "qkv_bias": P(*pp, None, t),
        "proj_kernel": P(*pp, None, t, None),
        "proj_bias": P(*pp, None, None),
        "ln2_scale": P(*pp, None, None),
        "ln2_bias": P(*pp, None, None),
    }
    if cfg.num_experts:
        # experts shard over cfg.moe_ep_axis under GSPMD; on the
        # shard_map pipeline path (pp_axis set) the stage fns run their
        # experts locally (make_gpt_pipeline_stage overrides
        # moe_ep_axis=None), so the specs drop 'ep' to match — callers
        # can feed these straight into shard_map in_specs
        ep = None if pp_axis else cfg.moe_ep_axis
        layer_specs.update({
            "router_kernel": P(*pp, None, None, None),
            "moe_fc1": P(*pp, None, ep, None, None),
            "moe_fc1_bias": P(*pp, None, ep, None),
            "moe_fc2": P(*pp, None, ep, None, None),
            "moe_fc2_bias": P(*pp, None, ep, None),
        })
    else:
        layer_specs.update({
            "fc1_kernel": (P(*pp, None, None, None, t) if swiglu
                           else P(*pp, None, None, t)),
            "fc1_bias": (P(*pp, None, None, t) if swiglu
                         else P(*pp, None, t)),
            "fc2_kernel": P(*pp, None, t, None),
            "fc2_bias": P(*pp, None, None),
        })

    specs = {
        "embedding": {"word": P(t, None)},
        "layers": layer_specs,
        "final_ln": {"scale": P(None), "bias": P(None)},
    }
    if cfg.position_embedding_type == "learned":
        specs["embedding"]["position"] = P(None, None)
    if cfg.untie_embeddings_and_output_weights:
        specs["lm_head"] = {"kernel": P(t, None)}
    return specs


# ---------------------------------------------------------------------------
# Forward pieces
# ---------------------------------------------------------------------------


def rope_cos_sin(seq_len: int, dim: int, base: float = 10000.0):
    """Rotary tables [s, d2] (reference fused_rope RotaryPositionEmbedding)."""
    inv = 1.0 / base ** (jnp.arange(0, dim, 2, jnp.float32) / dim)
    ang = jnp.outer(jnp.arange(seq_len, dtype=jnp.float32), inv)
    ang = jnp.concatenate([ang, ang], axis=-1)
    return jnp.cos(ang), jnp.sin(ang)


def _apply_rope(t, cos, sin):
    # t [b, s, n, d]; cos/sin [s, d] — reshape to broadcast over batch and
    # heads, then reuse the fused op (custom VJP recomputes from cos/sin)
    return fused_apply_rotary_pos_emb_cached(
        t, cos[None, :, None, :], sin[None, :, None, :])


def apply_norm(cfg, x, scale, bias):
    if cfg.normalization == "rmsnorm":
        return fused_rms_norm(x, scale, eps=cfg.layernorm_epsilon)
    return fused_layer_norm(x, scale, bias, eps=cfg.layernorm_epsilon)


def _dropout(x, rate, rng):
    if rate == 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0).astype(x.dtype)


def _drop_path(x, rate, rng):
    """Stochastic depth: drop a sample's whole residual branch
    (reference DropPath, standalone_transformer_lm.py:712-728 — applied
    to the post-dropout branch output, scaled by 1/keep_prob)."""
    if rate == 0.0 or rng is None:
        return x
    shape = (x.shape[0],) + (1,) * (x.ndim - 1)
    keep = jax.random.bernoulli(rng, 1.0 - rate, shape)
    return jnp.where(keep, x / (1.0 - rate), 0).astype(x.dtype)


def _core_attention(cfg: TransformerConfig, q, k, v, attention_mask,
                    dropout_rng, ctx: Optional[TPContext] = None):
    """softmax(QK^T/sqrt(d)) V (reference CoreAttention,
    standalone_transformer_lm.py:213 → FusedScaleMaskSoftmax →
    csrc/megatron/scaled_*_softmax).

    Backend: ring attention over ``ctx.cp_axis`` under context
    parallelism (sequence stays sharded through attention); else the
    Pallas flash-attention kernel when the pattern allows (causal /
    unmasked / key-padding, attention dropout fused in-kernel);
    otherwise the fused-softmax family on materialized scores (generic
    4-D masks).
    """
    hd = q.shape[-1]
    scale = 1.0 / hd ** 0.5
    use_dropout = cfg.attention_dropout > 0 and dropout_rng is not None
    causal = cfg.attn_mask_type == "causal"

    def full_kv():
        return _broadcast_kv(q, k, v)

    if ctx is not None and ctx.cp_axis is not None:
        # k/v may still be grouped (GQA): _cp_core_attention keeps them
        # at group width where the mode supports it (ring — rep-x
        # smaller ppermute messages) and broadcasts otherwise
        cp = _cp_core_attention(ctx, q, k, v, causal, scale,
                                attention_mask, use_dropout)
        if cp is not None:
            return cp
    # a 2-D [b, s_k] mask means key padding (True = masked key) — the
    # fused kernels handle it in-kernel without materializing [b,n,sq,sk]
    kpm = None
    if attention_mask is not None and attention_mask.ndim == 2:
        kpm = attention_mask
        attention_mask = None
    if cfg.attention_backend == "flash" and attention_mask is None:
        from apex_tpu.ops.flash_attention import flash_attention
        return flash_attention(
            q, k, v, causal=causal, key_padding_mask=kpm, scale=scale,
            dropout_p=cfg.attention_dropout if use_dropout else 0.0,
            dropout_rng=dropout_rng if use_dropout else None)
    k, v = full_kv()
    if kpm is not None:
        attention_mask = kpm[:, None, None, :]   # broadcastable 4-D
    # [b, s, n, d] x [b, t, n, d] -> [b, n, s, t]
    scores = jnp.einsum(
        "bsnd,btnd->bnst", q, k,
        preferred_element_type=jnp.float32,
    )
    if not cfg.softmax_in_fp32:
        scores = scores.astype(q.dtype)
    if cfg.attn_mask_type == "causal":
        if attention_mask is not None:
            # combine the causal triangle with the user mask rather than
            # silently dropping either (e.g. padding inside a causal LM)
            sq, sk = scores.shape[-2], scores.shape[-1]
            row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
            causal_mask = (col > row)[None, None]
            probs = scaled_masked_softmax(
                scores, attention_mask | causal_mask, scale)
        else:
            probs = scaled_upper_triang_masked_softmax(scores, scale)
    elif attention_mask is not None:
        probs = scaled_masked_softmax(scores, attention_mask, scale)
    else:
        probs = scaled_softmax(scores, scale)
    probs = _dropout(probs, cfg.attention_dropout, dropout_rng)
    ctxv = jnp.einsum(
        "bnst,btnd->bsnd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(v.dtype)
    return ctxv


def _broadcast_kv(q, k, v):
    """Broadcast grouped (GQA) k/v up to the query head count — THE one
    model-side definition of the repeat, for paths that need equal head
    counts (XLA dense scores, Ulysses, tp-incompatible ring shards); the
    flash/ring kernels broadcast via index maps instead."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)
    return k, v


_cp_fallback_warned = False


def _cp_degraded_fallback(reason: str) -> None:
    """A context-parallel-configured model is about to take the gathered
    dense path: numerically correct, but K/V get all-gathered across the
    cp axis — the exact memory blowup context parallelism exists to
    avoid.  Loud once-per-process warning (trace-time, so it fires at
    compile, before the step OOMs); ``APEX_TPU_CP_STRICT=1`` raises."""
    global _cp_fallback_warned
    msg = (
        f"context parallelism DEGRADED: {reason}, which the ring/Ulysses "
        "kernels do not cover — falling back to dense attention with "
        "K/V all-gathered over the cp axis. At long context this is the "
        "memory blowup cp exists to avoid (OOM or crawl). Drop the mask "
        "/ attention dropout for cp training, or set APEX_TPU_CP_STRICT=1 "
        "to make this an error.")
    if os.environ.get("APEX_TPU_CP_STRICT", "") not in ("", "0"):
        raise ValueError(msg)
    if not _cp_fallback_warned:
        _cp_fallback_warned = True
        warnings.warn(msg, RuntimeWarning, stacklevel=3)


def _cp_core_attention(ctx, q, k, v, causal, scale, attention_mask,
                       use_dropout):
    """Run core attention sequence-sharded over ``ctx.cp_axis`` (ring
    or Ulysses per ``ctx.cp_mode``), or return None when the pattern
    forces the gather path.

    Both modes cover the flagship patterns (causal / full, no mask, no
    attention dropout).  Masked or attention-dropout configs fall back
    to the dense core — correct, but K/V get gathered, so long-context
    training should keep those off (hidden dropout is unaffected; it
    rides the sequence-sharded regions).  The fallback warns once per
    process (it is the exact memory blowup cp exists to avoid — at
    s8192 it means OOM-or-crawl with no hint why); set
    ``APEX_TPU_CP_STRICT=1`` to make it a hard error instead."""
    axis = ctx.cp_axis
    mesh = jax.sharding.get_abstract_mesh()
    if mesh is None or mesh.empty or axis not in mesh.axis_names:
        return None   # single-device run of a cp-configured model
    if int(mesh.shape[axis]) == 1:
        return None   # cp degree 1: the dense path gathers nothing
    if attention_mask is not None or use_dropout:
        _cp_degraded_fallback(
            "attention_mask is set" if attention_mask is not None
            else "attention dropout is active")
        return None
    if ctx.cp_mode == "ulysses":
        from apex_tpu.parallel.ulysses import ulysses_attention as cp_fn
        grouped_ok = False   # the all-to-all reshards the head axis
    else:
        from apex_tpu.parallel.ring_attention import ring_attention as cp_fn
        grouped_ok = True    # groups ride the ring (rep-x smaller msgs)

    # keep batch (dp) and head (tp) shardings through the manual region;
    # axes absent from the mesh drop to replicated, like _constrain
    names = set(mesh.axis_names)
    spec = P(*(a if (a is None or a in names) else None
               for a in ctx.cp_qkv_spec))
    if k.shape[2] != q.shape[2]:
        # grouped K/V: legal only when the mode supports it AND the
        # head-axis sharding still divides the group count
        head_ax = ctx.cp_qkv_spec[2]
        head_shards = (int(mesh.shape[head_ax])
                       if head_ax in names else 1)
        if not grouped_ok or k.shape[2] % head_shards:
            k, v = _broadcast_kv(q, k, v)
    f = jax.shard_map(
        functools.partial(cp_fn, axis_name=axis, causal=causal,
                          scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)


def split_qkv_gqa(cfg: TransformerConfig, qkv, b, s, nh):
    """Split the GQA group-major layout — per query group
    ``[q x rep | k | v]`` heads — into per-head tensors; THE one
    definition of the layout: the training forward and the KV-cache
    decode both use it, so they cannot drift apart (only the
    cache-parity test would catch that otherwise).

    Group-major (not the block ``[q|k|v]`` sections) so that a
    contiguous tp chunk of the fused axis holds whole groups: the same
    function serves the global view (``nh`` = all query heads) and a
    manual-TP rank's local view (``nh`` = heads/tp, requiring
    ``kv_groups % tp == 0``).  With ``rep == 1`` this degenerates to the
    MHA per-head ``[q|k|v]`` interleave.  Query head ``h`` belongs to
    group ``h // rep`` in both views — the decode path's
    ``q.reshape(b, 1, g, rep, dh)`` fold depends on that ordering."""
    dh = cfg.kv_channels
    rep = cfg.num_attention_heads // cfg.kv_groups
    g = nh // rep   # local group count (nh may be per-rank heads/tp)
    blk = qkv.reshape(b, s, g, rep + 2, dh)
    q = blk[..., :rep, :].reshape(b, s, nh, dh)
    k = blk[..., rep, :]
    v = blk[..., rep + 1, :]
    return q, k, v


def _attention(cfg: TransformerConfig, lp: dict, x, ctx: TPContext,
               attention_mask, rope, dropout_rng, return_kv: bool = False):
    """ParallelAttention (reference :358): column-parallel fused QKV,
    core attention, row-parallel output projection.

    ``return_kv=True`` additionally returns the post-rope group-width
    K/V — the KV-cache prefill (models/generate.py) consumes them, so
    the inference prefill and the training forward share ONE
    implementation of the projection/split/rope/core-attention math."""
    nh = cfg.num_attention_heads // ctx.tp
    b, s, _ = x.shape

    xi = ctx.copy_in(x)
    wq = lp["qkv_kernel"]
    if _is_quantized(wq):
        # weight-only int8 serving path (ISSUE 14): single-device by
        # contract — quantize_params is a serving conversion, manual-TP
        # training never sees quantized leaves
        if ctx.tp > 1:
            raise ValueError(
                "quantized kernels (models/quantized.quantize_params) "
                "are a single-device serving path; they cannot shard "
                f"over the manual tp={ctx.tp} context")
        from apex_tpu.ops.dense import quantized_matmul

        qkv = quantized_matmul(xi, wq) + lp["qkv_bias"].astype(x.dtype)
    else:
        qkv = xi @ wq.astype(x.dtype) + lp["qkv_bias"].astype(x.dtype)
    qkv = ctx.constrain_col(qkv)
    if cfg.is_gqa:
        # group-major layout (per group [q x rep | k | v]): a contiguous
        # tp chunk holds whole groups, so manual TP is legal whenever
        # each rank gets an integral number of groups
        if ctx.tp > 1 and cfg.kv_groups % ctx.tp:
            raise ValueError(
                f"GQA with num_query_groups={cfg.kv_groups} cannot "
                f"shard over the manual shard_map tensor-parallel "
                f"context with tp={ctx.tp}: tp must divide the group "
                "count (each rank needs whole [q x rep | k | v] "
                "groups). Use a tp that divides num_query_groups, or "
                "the GSPMD context (make_gpt_train_step over a mesh), "
                "which replicates KV heads as needed")
        q, k, v = split_qkv_gqa(cfg, qkv, b, s, nh)
    else:
        qkv = qkv.reshape(b, s, nh, -1)
        q, k, v = jnp.split(qkv, 3, axis=-1)
    if rope is not None:
        cos, sin = rope
        q = _apply_rope(q, cos, sin)
        k = _apply_rope(k, cos, sin)
    # Under GQA, k/v stay at group width here: the flash kernel consumes
    # them directly (its index maps broadcast each group head to its rep
    # query heads — the repeated tensor never exists in HBM); the paths
    # that need full-width heads (XLA dense, context parallel) broadcast
    # inside _core_attention.  The decode path keeps the cache at group
    # width too — that persistent memory is the GQA win.
    if dropout_rng is not None and ctx.tp > 1:
        # attention probs are head-sharded over tp: each tp rank needs its
        # own dropout stream (the reference's model-parallel RNG,
        # tensor_parallel/random.py CudaRNGStatesTracker); replicated
        # hidden-dropout keys stay shared.
        dropout_rng = jax.random.fold_in(
            dropout_rng, jax.lax.axis_index(ctx.tp_axis))
    with jax.named_scope("core_attention"):
        ctxv = _core_attention(cfg, q, k, v, attention_mask, dropout_rng,
                               ctx)
    ctxv = ctxv.reshape(b, s, -1)
    wp = lp["proj_kernel"]
    if _is_quantized(wp):
        from apex_tpu.ops.dense import quantized_matmul

        out = ctx.reduce_out(quantized_matmul(ctxv, wp))
    else:
        out = _row_parallel_out(ctx, ctxv, wp.astype(x.dtype))
    out = out + lp["proj_bias"].astype(x.dtype)
    return (out, k, v) if return_kv else out


def _row_parallel_out(ctx: TPContext, x, w):
    """The row-parallel exit: overlapped ring matmul+reduce when the
    context's hook applies, else the monolithic matmul → reduce_out."""
    if ctx.row_parallel_matmul is not None:
        y = ctx.row_parallel_matmul(x, w)
        if y is not None:
            return y
    return ctx.reduce_out(x @ w)


def _moe_mlp(cfg: TransformerConfig, lp: dict, x):
    """MoE FFN (transformer/moe.py) in place of the dense MLP when
    ``cfg.num_experts`` is set; returns (out, aux_loss).  Experts shard
    over the 'ep' mesh axis — via GSPMD annotations on the capacity
    path, or the explicit compressed/ring-overlapped shard_map island on
    the ragged path (``cfg.moe_routing='ragged'``, wire dtype
    ``cfg.moe_comm``; overlap follows the ambient
    ``collective_matmul.overlap_scope`` the train step sets).  tp inside
    experts is not combined (experts ARE the parallelism for the FFN
    block)."""
    from apex_tpu.transformer.moe import switch_moe_mlp

    moe_params = {
        "router": lp["router_kernel"],
        "fc1": lp["moe_fc1"],
        "fc1_bias": lp["moe_fc1_bias"],
        "fc2": lp["moe_fc2"],
        "fc2_bias": lp["moe_fc2_bias"],
    }
    o = switch_moe_mlp(
        moe_params, x,
        capacity_factor=cfg.moe_capacity_factor,
        top_k=cfg.moe_top_k,
        ep_axis=cfg.moe_ep_axis,
        activation=cfg.activation,
        routing=cfg.moe_routing,
        moe_comm=cfg.moe_comm)
    return o.out, o.aux_loss


def _mlp(cfg: TransformerConfig, lp: dict, x, ctx: TPContext):
    """ParallelMLP (reference :165): column-parallel fc1 + fused bias-act,
    row-parallel fc2 (fused bias_swiglu / bias+gelu epilogues).

    Quantized fc kernels (ISSUE 14, ``_is_quantized`` dict leaves from
    ``models/quantized.quantize_params``) run the int8 weight-slab
    matmul instead — single-device serving path; the 3-D swiglu paired
    kernel's trailing axes flatten inside ``dense_quantized`` so the
    ``[b, s, 2, f]`` layout is unchanged."""
    xi = ctx.copy_in(x)
    w1 = lp["fc1_kernel"]
    if cfg.activation == "swiglu":
        if _is_quantized(w1):
            from apex_tpu.ops.dense import quantized_matmul

            y = quantized_matmul(xi, w1)          # [b, s, 2, f]
        else:
            # paired [h, 2, f] kernel: each tp shard of the f dim is a
            # (gate, up) pair, matching the single-device layout exactly
            y = jnp.einsum("bsh,hcf->bscf", xi, w1.astype(x.dtype))
        y = ctx.constrain_col(y)
        y = fused_bias_swiglu_paired(y, lp["fc1_bias"].astype(x.dtype))
    else:
        if _is_quantized(w1):
            from apex_tpu.ops.dense import quantized_matmul

            y = quantized_matmul(xi, w1) + lp["fc1_bias"].astype(x.dtype)
        else:
            y = xi @ w1.astype(x.dtype) + lp["fc1_bias"].astype(x.dtype)
        y = ctx.constrain_col(y)
        # 'gelu_tanh' = the tanh approximation (HF gpt2's gelu_new) —
        # needed for bit-comparable imports of reference-ecosystem
        # checkpoints (tools/import_hf.py)
        y = jax.nn.gelu(
            y.astype(jnp.float32),
            approximate=cfg.activation == "gelu_tanh").astype(x.dtype)
    w2 = lp["fc2_kernel"]
    if _is_quantized(w2):
        from apex_tpu.ops.dense import quantized_matmul

        out = ctx.reduce_out(quantized_matmul(y, w2))
    else:
        out = _row_parallel_out(ctx, y, w2.astype(x.dtype))
    return out + lp["fc2_bias"].astype(x.dtype)


def _layer(cfg: TransformerConfig, lp: dict, x, ctx: TPContext,
           attention_mask, rope, rngs):
    """Pre-LN transformer block (reference ParallelTransformerLayer :598:
    LN → attn → residual → LN → MLP → residual, bias_dropout_add fused).

    ``jax.named_scope`` blocks are the NVTX-range analog (reference DDP
    ``prof`` flag, distributed.py:193; SURVEY.md §5) — they label the
    profiler trace in xprof/TensorBoard without touching the compute.
    """
    r1, r2, r3, r4, r5 = (rngs if rngs is not None
                          else (None,) * 5)
    with jax.named_scope("ln1"):
        h = apply_norm(cfg, x, lp["ln1_scale"], lp["ln1_bias"])
    with jax.named_scope("attention"):
        a = _attention(cfg, lp, h, ctx, attention_mask, rope, r1)
    # residual source: block input, or the LN output under the
    # apply_residual_connection_post_layernorm flag (reference
    # standalone_transformer_lm.py:707-710)
    res = h if cfg.apply_residual_connection_post_layernorm else x
    x = res + _drop_path(_dropout(a, cfg.hidden_dropout, r2),
                         cfg.drop_path_rate, r4)
    with jax.named_scope("ln2"):
        h = apply_norm(cfg, x, lp["ln2_scale"], lp["ln2_bias"])
    with jax.named_scope("mlp"):
        if cfg.num_experts:
            m, aux = _moe_mlp(cfg, lp, h)
        else:
            m = _mlp(cfg, lp, h, ctx)
            aux = jnp.float32(0.0)
    res = h if cfg.apply_residual_connection_post_layernorm else x
    x = res + _drop_path(_dropout(m, cfg.hidden_dropout, r3),
                         cfg.drop_path_rate, r5)
    return ctx.constrain_hidden(x), aux


def vocab_parallel_embed(table, tokens, ctx: TPContext):
    """Masked local lookup + allreduce (reference VocabParallelEmbedding
    :167) in manual mode; plain take under GSPMD."""
    if not ctx.vocab_parallel:
        return jnp.take(table, tokens, axis=0)
    axis = ctx.tp_axis
    n_local = table.shape[0]
    start = jax.lax.axis_index(axis) * n_local
    local = tokens - start
    in_range = (local >= 0) & (local < n_local)
    local = jnp.clip(local, 0, n_local - 1)
    out = jnp.take(table, local, axis=0)
    out = jnp.where(in_range[..., None], out, 0).astype(table.dtype)
    return jax.lax.psum(out, axis)


def embed_tokens(emb: dict, tokens, cfg: TransformerConfig,
                 ctx: TPContext):
    """Word embedding lookup + learned position add (shared by the GSPMD
    forward and the shard_map pipeline stage)."""
    cd = cfg.compute_dtype
    h = vocab_parallel_embed(emb["word"].astype(cd), tokens, ctx)
    if cfg.position_embedding_type == "learned":
        h = h + emb["position"][: tokens.shape[1]].astype(cd)[None]
    return h


def lm_head_weight(params: dict, cfg: TransformerConfig):
    """Tied/untied output-head weight [v, h] (the single home for the
    selection — reference parallel_lm_logits' tied-weight argument)."""
    return (params["lm_head"]["kernel"]
            if cfg.untie_embeddings_and_output_weights
            else params["embedding"]["word"])


def lm_head_logits(params: dict, hidden, cfg: TransformerConfig):
    """Final-hidden → vocab logits with tied/untied head selection
    (reference parallel_lm_logits, standalone_transformer_lm.py:1130)."""
    head = lm_head_weight(params, cfg)
    # [b,s,h] @ [v,h]^T; vocab dim sharded over tp in both modes
    return jnp.einsum(
        "bsh,vh->bsv", hidden, head.astype(cfg.compute_dtype),
        preferred_element_type=jnp.float32,
    )


def transformer_backbone(params: dict, hidden, cfg: TransformerConfig,
                         ctx: TPContext, *, attention_mask=None,
                         dropout_rng=None, apply_final_norm: bool = True,
                         with_aux: bool = False):
    """The scanned decoder stack + final norm. ``hidden`` [b, s, h].

    ``with_aux=True`` additionally returns the summed per-layer auxiliary
    loss (the MoE load-balance term; 0 for dense configs)."""
    s = hidden.shape[1]
    rope = None
    if cfg.position_embedding_type == "rope":
        rope = rope_cos_sin(s, cfg.kv_channels)

    n_layers = jax.tree_util.tree_leaves(params["layers"])[0].shape[0]

    def body(carry, layer_in):
        x, aux_acc = carry
        lp, key = layer_in
        rngs = jax.random.split(key, 5) if key is not None else None
        x, aux = _layer(cfg, lp, x, ctx, attention_mask, rope, rngs)
        return (x, aux_acc + aux), None

    step = jax.checkpoint(body) if cfg.remat else body

    needs_rng = dropout_rng is not None and (
        cfg.hidden_dropout > 0 or cfg.attention_dropout > 0
        or cfg.drop_path_rate > 0)
    keys = jax.random.split(dropout_rng, n_layers) if needs_rng else None

    aux0 = jnp.float32(0.0)
    # inside shard_map the per-layer aux inherits the hidden's varying
    # axes (e.g. 'pp' in a pipeline stage) — the scan carry must start
    # with the same type
    for axis in getattr(jax.typeof(hidden), "vma", ()) or ():
        from apex_tpu.utils.collectives import pvary as _pvary_

        aux0 = _pvary_(aux0, axis)
    if cfg.scan_layers:
        (hidden, aux), _ = jax.lax.scan(
            step, (hidden, aux0), (params["layers"], keys))
    else:
        carry = (hidden, aux0)
        for i in range(n_layers):
            lp = jax.tree_util.tree_map(lambda v: v[i], params["layers"])
            carry, _ = step(carry, (lp, keys[i] if needs_rng else None))
        hidden, aux = carry

    if not apply_final_norm:
        return (hidden, aux) if with_aux else hidden
    out = apply_norm(cfg, hidden, params["final_ln"]["scale"],
                     params["final_ln"]["bias"])
    return (out, aux) if with_aux else out


def gpt_forward(params: dict, tokens: jax.Array, cfg: TransformerConfig,
                ctx: Optional[TPContext] = None, *, attention_mask=None,
                dropout_rng=None, with_aux: bool = False):
    """Token ids [b, s] → logits (reference GPTModel.forward,
    standalone_gpt.py:45 → TransformerLanguageModel :1358 →
    parallel_lm_logits :1130).

    Logits come back tp-sharded on the vocab dim in manual mode (pair with
    ``vocab_parallel_cross_entropy``) and full under GSPMD.
    """
    ctx = ctx or single_device_ctx()
    h, aux = gpt_hidden(params, tokens, cfg, ctx,
                        attention_mask=attention_mask,
                        dropout_rng=dropout_rng)
    logits = lm_head_logits(params, h, cfg)
    return (logits, aux) if with_aux else logits


def gpt_hidden(params: dict, tokens: jax.Array, cfg: TransformerConfig,
               ctx: TPContext, *, attention_mask=None, dropout_rng=None):
    """Embed + decoder stack + final norm → (hidden [b,s,h], moe_aux).
    The shared prologue of :func:`gpt_forward` and the fused head+CE
    loss path."""
    h = ctx.constrain_hidden(embed_tokens(params["embedding"], tokens,
                                          cfg, ctx))
    return transformer_backbone(params, h, cfg, ctx,
                                attention_mask=attention_mask,
                                dropout_rng=dropout_rng, with_aux=True)


def gpt_loss(params: dict, tokens: jax.Array, labels: jax.Array,
             cfg: TransformerConfig, ctx: Optional[TPContext] = None,
             *, attention_mask=None, dropout_rng=None) -> jax.Array:
    """Mean next-token CE. Uses the fused xentropy op (GSPMD/single) or the
    vocab-parallel CE (manual TP) — reference post_language_model_processing
    (standalone_transformer_lm.py:1547 → tensor_parallel/cross_entropy.py:23).
    ``attention_mask`` (True = masked) feeds ``attn_mask_type='padding'``
    models; causal masking needs none.
    """
    ctx = ctx or single_device_ctx()
    if cfg.fused_head_ce and not ctx.vocab_parallel:
        # fused head+CE: stop before the head and chunk the vocab matmul
        # into the loss (ops/lm_head_ce.py) — the [tokens, vocab] logits
        # are never materialized
        from apex_tpu.ops.lm_head_ce import lm_head_cross_entropy

        h, aux = gpt_hidden(params, tokens, cfg, ctx,
                            attention_mask=attention_mask,
                            dropout_rng=dropout_rng)
        head = lm_head_weight(params, cfg).astype(cfg.compute_dtype)
        losses = lm_head_cross_entropy(
            h, head, labels, chunk=cfg.head_ce_chunk, ignore_index=-1)
        n_valid = jnp.maximum(jnp.sum(labels != -1), 1)
        loss = jnp.sum(losses) / n_valid.astype(jnp.float32)
        if cfg.num_experts:
            loss = loss + cfg.moe_aux_loss_coeff * aux / cfg.num_layers
        return loss
    logits, aux = gpt_forward(params, tokens, cfg, ctx,
                              attention_mask=attention_mask,
                              dropout_rng=dropout_rng, with_aux=True)
    loss = lm_cross_entropy(logits, labels, ctx)
    if cfg.num_experts:
        # Switch load-balance term, mean over layers
        loss = loss + cfg.moe_aux_loss_coeff * aux / cfg.num_layers
    return loss


def lm_cross_entropy(logits, labels, ctx: TPContext) -> jax.Array:
    """Mean token CE over (possibly vocab-sharded) logits; labels of -1 are
    padding and contribute zero (both paths agree — the fused xentropy op's
    ``padding_idx`` semantics, xentropy_kernel.cu:431-436)."""
    if ctx.vocab_parallel:
        from apex_tpu.transformer.tensor_parallel.cross_entropy import (
            vocab_parallel_cross_entropy,
        )
        losses = vocab_parallel_cross_entropy(
            logits.astype(jnp.float32), labels, ctx.tp_axis)
        losses = jnp.where(labels == -1, 0.0, losses)
    else:
        losses = softmax_cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]),
            jnp.maximum(labels.reshape(-1), 0),
            padding_idx=None,
        )
        losses = jnp.where(labels.reshape(-1) == -1, 0.0, losses)
    # normalize by non-padding count (Megatron loss_mask.sum() semantics)
    n_valid = jnp.maximum(jnp.sum(labels != -1), 1)
    return jnp.sum(losses) / n_valid.astype(jnp.float32)
