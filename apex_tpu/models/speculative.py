"""Speculative decoding: n-gram self-drafting + batched verification.

Decode is bandwidth-bound: every ``decode_step`` reads the whole
parameter set to produce ONE token.  Speculative decoding amortizes
that weight read — a cheap *drafter* proposes ``k`` tokens, one
batched :func:`~apex_tpu.models.generate.decode_verify` forward scores
all of them (the PR 3 flash-prefill economics applied to decode), and
standard leftover-distribution rejection sampling keeps exactly the
prefix the target model agrees with (ROADMAP item 2).

Correctness contract (tests/test_speculative.py pins both halves):

- **greedy** (temperature 0): a draft token is accepted iff it equals
  the target argmax, and the correction token IS the target argmax at
  the first disagreement — so spec-on output is *token-identical* to
  spec-off greedy decoding, on both cache layouts;
- **sampling**: a draft ``d`` proposed with probability ``q(d)`` is
  accepted with probability ``min(1, p(d)/q(d))``; on rejection the
  replacement is drawn from ``norm(max(p − q, 0))``.  The emitted
  marginal is exactly ``p`` (the Leviathan/Chen speculative-sampling
  identity), so spec-on sampling is *distribution-identical* —
  drafting quality affects only speed, never the distribution.  The
  n-gram drafter is a point mass (``q(d) = 1``), for which the rule
  degenerates to: accept with probability ``p(d)``, else resample from
  ``p`` with ``d`` removed.

The default drafter needs NO draft model: :func:`ngram_draft` is
prompt-lookup decoding — find the most recent earlier occurrence of
the current suffix n-gram in prompt+generated tokens and propose the
tokens that followed it.  It is fully vectorized (device-side, jits
into the decode ``while_loop`` — no host sync per round) and wins
exactly where LLM serving traffic repeats itself: code, quoted
context, templated text, and the self-repetition loops of greedy
decoding.  A small draft *model* plugs in through
``SpecConfig(draft_fn=...)`` — any traceable callable proposing
``(draft, q_probs)``.

Cache interplay: verification writes k+1 speculative K/V entries;
rollback of the rejected tail is just the position decrement
``decode_verify`` documents — in the paged layout (PR 6) not even a
block operation, which is why the two compose so cheaply.

Telemetry: ``generate(spec=...)`` and the serving engine surface the
realized counters ``generate.spec.{draft_tokens,accepted_tokens,
verify_calls}`` (host-side — the values are data-dependent;
``verify_calls`` counts per-sequence verify passes, so a batched
forward books once per live row and every ratio below is
batch-size-independent); accept rate = accepted/draft and
tokens-per-verify = (accepted+verify)/verify (ceiling k+1) are the
two derived numbers ``tools/telemetry_report.py`` prints.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.models.config import TransformerConfig
from apex_tpu.models.generate import (
    _check_decode_cfg, _check_sampling_args, decode_verify,
    init_kv_cache, prefill, sample_logits)
from apex_tpu.ops.fused_sampling import filter_logits

__all__ = ["SpecConfig", "resolve_spec", "ngram_draft", "spec_round",
           "spec_generate"]

_NEG_INF = -1e30


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculative-decoding knobs (static — part of the jit key).

    ``k``: drafted tokens per verify round; each round emits between 1
    and k+1 tokens for one verify forward, so k bounds the speedup at
    (k+1)x and the per-round wasted FLOPs at kx.  ``max_ngram`` /
    ``min_ngram``: suffix sizes the n-gram drafter tries, longest
    first (longer suffixes make rarer but more reliable matches).
    ``draft_fn``: optional draft-model hook — a traceable
    ``f(tokens [b, T], lens [b], k) -> (draft [b, k] int32, q_probs
    [b, k, v] | None)``; ``None`` q_probs means a point-mass proposal
    (the n-gram case).  The callable must be hashable (a plain
    function or functools.partial), since it keys the jit cache."""

    k: int = 8
    max_ngram: int = 3
    min_ngram: int = 1
    draft_fn: Optional[Callable] = None

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec k={self.k} must be >= 1")
        if not (1 <= self.min_ngram <= self.max_ngram):
            raise ValueError(
                f"need 1 <= min_ngram ({self.min_ngram}) <= max_ngram "
                f"({self.max_ngram})")


def resolve_spec(spec) -> Optional[SpecConfig]:
    """Normalize the ``spec=`` argument: None/"off" disables,
    ``"ngram"`` takes the defaults, a :class:`SpecConfig` passes
    through."""
    if spec is None or spec == "off":
        return None
    if spec == "ngram":
        return SpecConfig()
    if isinstance(spec, SpecConfig):
        return spec
    raise ValueError(
        f"spec={spec!r}: expected None, 'off', 'ngram', or a SpecConfig")


def ngram_draft(tokens: jax.Array, lens: jax.Array, *, k: int,
                max_ngram: int = 3, min_ngram: int = 1) -> jax.Array:
    """Prompt-lookup drafting, fully vectorized: propose the ``k``
    tokens that followed the most recent earlier occurrence of the
    current suffix n-gram.

    ``tokens`` [b, T] is the emitted history (prompt + generated,
    entries at and past ``lens[i]`` ignored), ``lens`` [b] the live
    length — the suffix ends at ``tokens[i, lens[i]-1]``.  Sizes
    ``max_ngram..min_ngram`` are tried longest-first; the first size
    with a match wins, and within a size the MOST RECENT match wins
    (recency tracks the local pattern, the property prompt-lookup
    decoding relies on).  Rows with no match (or a match at the very
    end) draft the clamped continuation — reads past ``lens-1`` repeat
    the final token, a deliberately cheap guess that simply gets
    rejected when wrong."""
    b, T = tokens.shape
    lens = lens.astype(jnp.int32)
    idx = jnp.arange(T, dtype=jnp.int32)
    best_j = jnp.maximum(lens - 1, 0)       # fallback: repeat last token
    found = jnp.zeros((b,), bool)
    for n in range(max_ngram, min_ngram - 1, -1):
        eq = jnp.ones((b, T), bool)
        for i in range(n):
            suf = jnp.take_along_axis(
                tokens, jnp.maximum(lens - 1 - i, 0)[:, None], axis=1)
            # token at j-i aligned under j (rolled entries at j < i are
            # masked out by the validity window below)
            shifted = jnp.roll(tokens, i, axis=1)
            eq = eq & (shifted == suf)
        # window: full n-gram exists (j >= n-1), strictly earlier than
        # the suffix itself (j <= lens-2), and the row holds >= n tokens
        valid = ((idx[None] >= n - 1) & (idx[None] <= lens[:, None] - 2)
                 & (lens[:, None] >= n))
        cand = jnp.where(eq & valid, idx[None], -1)
        jn = jnp.max(cand, axis=1)
        best_j = jnp.where(~found & (jn >= 0), jn, best_j)
        found = found | (jn >= 0)
    gidx = best_j[:, None] + 1 + jnp.arange(k, dtype=jnp.int32)[None]
    gidx = jnp.clip(gidx, 0, jnp.maximum(lens[:, None] - 1, 0))
    return jnp.take_along_axis(tokens, gidx, axis=1).astype(jnp.int32)


def _spec_probs(logits, temperature, top_k, top_p, vocab_limit,
                token_mask=None):
    """Per-position target distributions [b, m, v] for acceptance: the
    SAME filter chain the sampler applies (``filter_logits``), so the
    accept/resample arithmetic runs against exactly the distribution a
    non-speculative step would have sampled from.  Greedy rows
    (temperature 0) become one-hot argmax — under which the generic
    rejection rule degenerates to exact token matching.

    ``token_mask`` (constrained decoding, ISSUE 20): bool ``[v]`` or
    per-row ``[b, v]``, applied BEFORE the filters — the same masked
    target a non-speculative constrained step samples from.  A drafted
    token outside the mask gets target probability 0 and is rejected
    outright, and the correction draw comes from the masked leftover —
    acceptance stays exact against constrained autoregression with no
    drafter cooperation required."""
    b, m, v = logits.shape
    flat = logits.reshape(b * m, v)
    if vocab_limit is not None:
        over = jnp.arange(v) >= vocab_limit
        flat = jnp.where(over[None], _NEG_INF, flat)
    if token_mask is not None:
        mask = token_mask
        if mask.ndim == 1:
            mask = mask[None]
        else:
            # per-row [b, v] masks repeat across the row's m verify
            # positions (one request, one constraint)
            mask = jnp.repeat(mask, m, axis=0)
        flat = jnp.where(mask, flat, _NEG_INF)
    onehot = jax.nn.one_hot(jnp.argmax(flat, axis=-1), v,
                            dtype=jnp.float32)
    if hasattr(temperature, "ndim") and getattr(temperature, "ndim", 0):
        temps = jnp.repeat(temperature.astype(jnp.float32), m)
        scaled = flat / jnp.maximum(temps, 1e-6)[:, None]
        soft = jax.nn.softmax(
            filter_logits(scaled, top_k=top_k, top_p=top_p), axis=-1)
        probs = jnp.where((temps > 0)[:, None], soft, onehot)
    # the ndim guard above already captured every traced form; what
    # reaches these branches is a python scalar (the generate() path),
    # so float() here is host arithmetic, not a concretization
    elif float(temperature) == 0.0:   # apexlint: disable=APX301
        probs = onehot
    else:
        scaled = flat / float(temperature)   # apexlint: disable=APX301
        probs = jax.nn.softmax(
            filter_logits(scaled, top_k=top_k, top_p=top_p), axis=-1)
    return probs.reshape(b, m, v)


def _accept(draft, probs, q_probs, key):
    """Leftover-distribution rejection sampling over one verify block.

    ``draft`` [b, k], ``probs`` [b, k+1, v] target distributions (row
    j for the position draft j+1 sits at; row k is the bonus
    position), ``q_probs`` [b, k, v] proposal distributions or None
    (point mass).  Returns ``(n_acc [b], y [b])``: the accepted-prefix
    length and the correction token (drawn from
    ``norm(max(p − q, 0))`` at the first rejection) or bonus token
    (drawn from ``p`` when everything was accepted)."""
    b, k = draft.shape
    v = probs.shape[-1]
    key_u, key_y = jax.random.split(key)
    u = jax.random.uniform(key_u, (b, k), jnp.float32)
    pd = jnp.take_along_axis(probs[:, :k], draft[..., None],
                             axis=-1)[..., 0]
    if q_probs is None:
        ratio = pd                                   # q(d) = 1
    else:
        qd = jnp.take_along_axis(q_probs, draft[..., None],
                                 axis=-1)[..., 0]
        ratio = pd / jnp.maximum(qd, 1e-20)
    accept = u < ratio
    n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                    axis=1)
    p_at = jnp.take_along_axis(probs, n_acc[:, None, None],
                               axis=1)[:, 0]          # [b, v]
    rej_col = jnp.minimum(n_acc, k - 1)
    d_rej = jnp.take_along_axis(draft, rej_col[:, None], axis=1)[:, 0]
    if q_probs is None:
        q_at = jax.nn.one_hot(d_rej, v, dtype=jnp.float32)
    else:
        q_at = jnp.take_along_axis(q_probs, rej_col[:, None, None],
                                   axis=1)[:, 0]
    leftover = jnp.maximum(p_at - q_at, 0.0)
    z = jnp.sum(leftover, axis=-1, keepdims=True)
    rejected = (n_acc < k)[:, None]
    # all-accept rows draw the bonus token from p; rejected rows from
    # the leftover (falling back to p in the measure-zero corner where
    # the leftover mass underflows — p(d) ≈ 1 yet u >= p(d))
    dist = jnp.where(rejected & (z > 1e-9),
                     leftover / jnp.maximum(z, 1e-9), p_at)
    y = jax.random.categorical(
        key_y, jnp.log(jnp.maximum(dist, 1e-38)))
    return n_acc, y.astype(jnp.int32)


def spec_round(params, cfg, cache, nxt, tokens, lens, key, *, spec,
               temperature, top_k=None, top_p=None, vocab_limit=None,
               token_mask=None, lora=None):
    """One draft → verify → accept round (the shared core of
    ``generate(spec=...)``'s jitted loop and the serving engine's
    jitted multi-token step).

    ``nxt`` [b]: the pending token — emitted, not yet in the cache
    (``cache['pos']`` points at its position).  ``tokens`` [b, T]:
    emitted history including ``nxt`` (the drafter's haystack);
    ``lens`` [b]: its live length.  Returns ``(em, n_acc, y, cache,
    prev_pos)`` where ``em`` [b, k+1] holds the round's candidate
    emission (accepted drafts then the correction/bonus token ``y`` at
    column ``n_acc`` — columns past it are dead), the cache has all
    k+1 speculative entries written and ``pos`` advanced by k+1, and
    ``prev_pos`` is the entry position: the caller commits
    ``pos = prev_pos + n_emit`` once it has applied its own EOS/budget
    truncation — the rollback-is-a-decrement contract."""
    k = spec.k
    if spec.draft_fn is not None:
        draft, q_probs = spec.draft_fn(tokens, lens, k)
        draft = draft.astype(jnp.int32)
    else:
        draft = ngram_draft(tokens, lens, k=k, max_ngram=spec.max_ngram,
                            min_ngram=spec.min_ngram)
        q_probs = None
    prev_pos = cache["pos"]
    seq = jnp.concatenate([nxt[:, None].astype(jnp.int32), draft],
                          axis=1)
    logits, cache = decode_verify(params, seq, cache, cfg, lora=lora)
    probs = _spec_probs(logits, temperature, top_k, top_p, vocab_limit,
                        token_mask=token_mask)
    n_acc, y = _accept(draft, probs, q_probs, key)
    # candidate emission: draft prefix with y scattered at column n_acc
    em = jnp.concatenate([draft, draft[:, -1:]], axis=1)
    em = jnp.where(jnp.arange(k + 1)[None] == n_acc[:, None],
                   y[:, None], em)
    return em, n_acc, y, cache, prev_pos


@functools.partial(jax.jit, static_argnames=(
    "cfg", "spec", "max_new_tokens", "temperature", "top_k", "top_p",
    "vocab_limit", "eos_token_id", "cache_dtype", "cache_layout",
    "block_size", "cache_wire"))
def _spec_generate_impl(params, prompt, prompt_lens, rng, *, cfg, spec,
                        max_new_tokens, temperature, top_k, top_p,
                        vocab_limit, eos_token_id, cache_dtype,
                        cache_layout, block_size, cache_wire=None):
    """Prefill + while-loop of spec rounds; returns (tokens [b,
    s+max_new], stats [3] = draft/accepted/verify counters)."""
    b, s = prompt.shape
    total = s + max_new_tokens
    k = spec.k
    # k+1 headroom: a verify block may write past the budget before its
    # tail is rolled back — those cells must exist in both layouts
    cache = init_kv_cache(cfg, b, total + k + 1, cache_dtype=cache_dtype,
                          cache_layout=cache_layout,
                          block_size=block_size, cache_wire=cache_wire)
    lens = (jnp.full((b,), s, jnp.int32) if prompt_lens is None
            else prompt_lens.astype(jnp.int32))
    logits, cache = prefill(params, prompt, cfg,
                            prompt_lens=prompt_lens, cache=cache)
    tokens = jnp.concatenate(
        [prompt, jnp.zeros((b, max_new_tokens), prompt.dtype)], axis=1)
    b_idx = jnp.arange(b)[:, None]
    col = jnp.arange(total)

    # first token from the prefill logits — the same pick (and the same
    # key schedule) as the non-speculative path
    key, sub = jax.random.split(rng)
    nxt = sample_logits(logits, sub, temperature=temperature,
                        top_k=top_k, top_p=top_p,
                        vocab_limit=vocab_limit)
    tokens = jnp.where(col[None] == lens[:, None],
                       nxt[:, None].astype(tokens.dtype), tokens)
    done = (nxt == eos_token_id) if eos_token_id is not None else (
        jnp.zeros((b,), bool))
    done = done | (max_new_tokens <= 1)
    emitted = jnp.ones((b,), jnp.int32)
    stats = jnp.zeros((3,), jnp.int32)    # draft, accepted, verify

    def cond(carry):
        return ~jnp.all(carry[0])

    def body(carry):
        done, tokens, cache, key, nxt, emitted, stats = carry
        key, sub = jax.random.split(key)
        em, n_acc, y, cache, prev_pos = spec_round(
            params, cfg, cache, nxt, tokens, lens + emitted, sub,
            spec=spec, temperature=temperature, top_k=top_k,
            top_p=top_p, vocab_limit=vocab_limit)
        n_raw = n_acc + 1
        budget = max_new_tokens - emitted
        n_emit = jnp.minimum(n_raw, budget)
        if eos_token_id is not None:
            # truncate at the first emitted EOS (the EOS itself is
            # written; nothing after it)
            is_eos = em == eos_token_id
            first = jnp.min(jnp.where(
                is_eos, jnp.arange(k + 1)[None], k + 1), axis=1)
            n_emit = jnp.minimum(n_emit, first + 1)
        n_emit = jnp.where(done, 0, n_emit)
        # masked columns are pushed out of bounds and DROPPED — a
        # clipped in-bounds dummy column could collide with a real
        # write at the array edge and scatter-order would pick the
        # winner arbitrarily
        wm = (jnp.arange(k + 1)[None] < n_emit[:, None])
        wcols = jnp.where(
            wm, (lens + emitted)[:, None]
            + jnp.arange(k + 1, dtype=jnp.int32)[None], total)
        tokens = tokens.at[b_idx, wcols].set(
            em.astype(tokens.dtype), mode="drop")
        # the new pending token: the last committed one this round
        last = jnp.take_along_axis(
            em, jnp.maximum(n_emit - 1, 0)[:, None], axis=1)[:, 0]
        nxt = jnp.where(done, nxt, last)
        new_done = done | (emitted + n_emit >= max_new_tokens)
        if eos_token_id is not None:
            hit = jnp.any(jnp.where(wm, em == eos_token_id, False),
                          axis=1)
            new_done = new_done | hit
        emitted = emitted + n_emit
        # rollback: keep the committed entries, decrement away the
        # rejected tail (done rows freeze where they were)
        cache = dict(cache, pos=jnp.where(done, prev_pos,
                                          prev_pos + n_emit))
        # verify_calls counts PER-SEQUENCE verify passes (a batched
        # forward counts once per live row): it is the amortization
        # denominator — (accepted + verify) / verify tokens emitted
        # per verify, ceiling k+1 — and stays batch-size-independent
        live = (~done).astype(jnp.int32)
        stats = stats + jnp.stack([
            jnp.int32(k) * jnp.sum(live),
            jnp.sum(n_acc * live),
            jnp.sum(live)])
        return (new_done, tokens, cache, key, nxt, emitted, stats)

    carry = (done, tokens, cache, key, nxt, emitted, stats)
    done, tokens, _, _, _, _, stats = jax.lax.while_loop(cond, body,
                                                         carry)
    return tokens, stats


def spec_generate(
    params: dict,
    prompt: jax.Array,
    cfg: TransformerConfig,
    *,
    spec="ngram",
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng: Optional[jax.Array] = None,
    vocab_limit: Optional[int] = None,
    prompt_lens: Optional[jax.Array] = None,
    eos_token_id: Optional[int] = None,
    cache_dtype=None,
    cache_layout: str = "contiguous",
    block_size: int = 16,
    cache_wire=None,
):
    """Speculative decoding past ``prompt`` [b, s] → (tokens
    [b, s+max_new_tokens], stats dict).

    Same surface and output contract as
    :func:`~apex_tpu.models.generate.generate` — greedy output is
    token-identical to the non-speculative path on both cache layouts
    and stochastic output is distribution-identical (module
    docstring) — plus the realized counters ``stats = {"draft_tokens",
    "accepted_tokens", "verify_calls"}`` so callers (``bench.py
    --spec``) can report accept rates without a telemetry registry.
    ``generate(spec=...)`` wraps this and feeds the same numbers into
    the ``generate.spec.*`` telemetry counters."""
    spec_cfg = resolve_spec(spec)
    if spec_cfg is None:
        raise ValueError("spec_generate needs an enabled spec config; "
                         "call generate() for the plain path")
    _check_sampling_args(temperature, top_k)
    _check_decode_cfg(cfg)
    b, s = prompt.shape
    if (cfg.position_embedding_type == "learned"
            and s + max_new_tokens + spec_cfg.k + 1
            > cfg.max_position_embeddings):
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) + spec "
            f"verify headroom ({spec_cfg.k + 1}) exceeds "
            f"max_position_embeddings ({cfg.max_position_embeddings}); "
            "the learned position lookup would silently clamp")
    if cache_layout not in ("contiguous", "paged"):
        raise ValueError(
            f"cache_layout={cache_layout!r}: expected 'contiguous' or "
            "'paged'")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if prompt_lens is not None:
        prompt_lens = jnp.asarray(prompt_lens, jnp.int32)
    tokens, stats = _spec_generate_impl(
        params, prompt, prompt_lens, rng, cfg=cfg, spec=spec_cfg,
        max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, top_p=top_p, vocab_limit=vocab_limit,
        eos_token_id=eos_token_id, cache_dtype=cache_dtype,
        cache_layout=cache_layout, block_size=block_size,
        cache_wire=cache_wire)
    stats = {
        "draft_tokens": int(stats[0]),
        "accepted_tokens": int(stats[1]),
        "verify_calls": int(stats[2]),
    }
    return tokens, stats
