"""Model configuration for the standalone transformer LM family.

Reference: apex/transformer/testing/arguments.py (971 LoC of Megatron-style
argparse) collapses here into one frozen dataclass — the only fields the
standalone GPT/BERT models (standalone_transformer_lm.py:1358
``TransformerLanguageModel``) actually consume, plus the TPU-specific knobs
(dtypes, remat, scan-over-layers).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

__all__ = ["TransformerConfig", "gpt_tiny", "gpt_125m", "bert_large"]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Static hyperparameters of a ParallelTransformer LM.

    Mirrors the subset of reference ``arguments.py`` used by
    ``standalone_transformer_lm.py`` (hidden_size, num_layers,
    num_attention_heads, ffn_hidden_size, kv_channels,
    max_position_embeddings, padded_vocab_size, hidden_dropout,
    attention_dropout, init_method_std,
    untie_embeddings_and_output_weights…).
    """

    num_layers: int = 2
    hidden_size: int = 128
    num_attention_heads: int = 8
    # grouped-query attention (beyond the reference, whose Megatron-era
    # model is MHA-only): K/V get num_query_groups heads shared by
    # num_attention_heads/groups queries each (GQA, arXiv:2305.13245;
    # groups=1 is MQA).  None = num_attention_heads = classic MHA.  The
    # decode KV cache stores only the group heads — the main win.
    num_query_groups: Optional[int] = None
    ffn_hidden_size: Optional[int] = None         # default 4*h (2/3*4h swiglu)
    kv_channels: Optional[int] = None             # default h // nh
    vocab_size: int = 1024                        # padded to tp divisibility
    max_position_embeddings: int = 512

    # architecture switches
    attn_mask_type: str = "causal"                # 'causal' | 'padding'
    activation: str = "gelu"            # 'gelu' | 'gelu_tanh' | 'swiglu'
    position_embedding_type: str = "learned"      # 'learned' | 'rope'
    normalization: str = "layernorm"              # 'layernorm' | 'rmsnorm'
    untie_embeddings_and_output_weights: bool = False
    layernorm_epsilon: float = 1e-5
    # take the residual from the LN output instead of the block input
    # (reference standalone_transformer_lm.py:620,707,738)
    apply_residual_connection_post_layernorm: bool = False

    # mixture-of-experts (beyond the reference; transformer/moe.py)
    num_experts: "Optional[int]" = None           # None = dense FFN
    moe_capacity_factor: float = 1.25
    moe_top_k: int = 1
    moe_aux_loss_coeff: float = 1e-2
    moe_ep_axis: str = "ep"                       # expert mesh axis name
    # 'capacity' = Switch drop-token einsums (GSPMD-inferred EP);
    # 'ragged' = capacity-free sort-by-expert routing through the
    # grouped matmul with explicit compressed/overlapped EP dispatch
    moe_routing: str = "capacity"
    # EP dispatch/combine wire dtype on the ragged path ('fp32' | 'bf16'
    # | 'int8' — the grad_comm= surface applied to expert all-to-alls)
    moe_comm: str = "fp32"

    # regularization
    hidden_dropout: float = 0.0
    attention_dropout: float = 0.0
    # stochastic depth on the residual branches (reference drop_path,
    # standalone_transformer_lm.py:712-728 DropPath)
    drop_path_rate: float = 0.0
    init_method_std: float = 0.02

    # numerics / TPU execution
    params_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    softmax_in_fp32: bool = True
    attention_backend: str = "flash"              # 'flash' | 'fused_softmax'
    remat: bool = False                           # jax.checkpoint each layer
    scan_layers: bool = True                      # lax.scan over the stack
    # fuse the LM-head matmul into the CE loss, chunked over tokens, so
    # the [tokens, vocab] logits never hit HBM (ops/lm_head_ce.py);
    # applies to the training loss on the non-vocab-parallel path only
    fused_head_ce: bool = False
    head_ce_chunk: int = 2048

    def __post_init__(self):
        if self.ffn_hidden_size is None:
            ffn = (
                int(4 * self.hidden_size * 2 / 3)
                if self.activation == "swiglu"
                else 4 * self.hidden_size
            )
            object.__setattr__(self, "ffn_hidden_size", ffn)
        if self.kv_channels is None:
            if self.hidden_size % self.num_attention_heads:
                raise ValueError(
                    "num_attention_heads must divide hidden_size when "
                    "kv_channels is not given"
                )
            object.__setattr__(
                self, "kv_channels",
                self.hidden_size // self.num_attention_heads,
            )
        if self.moe_routing not in ("capacity", "ragged"):
            raise ValueError(
                f"moe_routing ({self.moe_routing!r}) must be 'capacity' "
                "or 'ragged'")
        if self.moe_comm not in ("fp32", "bf16", "int8"):
            raise ValueError(
                f"moe_comm ({self.moe_comm!r}) must be 'fp32', 'bf16' "
                "or 'int8'")
        if self.num_query_groups is not None:
            if (self.num_query_groups < 1
                    or self.num_attention_heads % self.num_query_groups):
                raise ValueError(
                    f"num_query_groups ({self.num_query_groups}) must "
                    f"be a positive divisor of num_attention_heads "
                    f"({self.num_attention_heads})")

    @property
    def projection_size(self) -> int:
        return self.kv_channels * self.num_attention_heads

    @property
    def kv_groups(self) -> int:
        """Number of K/V heads (== num_attention_heads for MHA)."""
        return (self.num_query_groups
                if self.num_query_groups is not None
                else self.num_attention_heads)

    @property
    def kv_projection_size(self) -> int:
        return self.kv_channels * self.kv_groups

    @property
    def is_gqa(self) -> bool:
        """True when K/V heads differ from query heads (grouped-query).

        Selects the group-major qkv layout — per query group
        ``[q x rep | k | v]`` heads (see ``split_qkv_gqa``, the one
        layout definition) — instead of the legacy per-head-interleaved
        layout, which is kept bit-identical for MHA (golden traces + HF
        import depend on it)."""
        return self.kv_groups != self.num_attention_heads


def gpt_tiny(**kw) -> TransformerConfig:
    """Four-layer toy GPT for tests/dryruns."""
    kw.setdefault("num_layers", 4)
    kw.setdefault("hidden_size", 128)
    kw.setdefault("num_attention_heads", 8)
    kw.setdefault("vocab_size", 512)
    kw.setdefault("max_position_embeddings", 128)
    return TransformerConfig(**kw)


def gpt_125m(**kw) -> TransformerConfig:
    """GPT-2 125M — the reference's benchmark config
    (BASELINE.json: 'GPT-2 125M: FusedLayerNorm + scaled softmax + RoPE')."""
    kw.setdefault("num_layers", 12)
    kw.setdefault("hidden_size", 768)
    kw.setdefault("num_attention_heads", 12)
    kw.setdefault("vocab_size", 50304)            # 50257 padded to 128
    kw.setdefault("max_position_embeddings", 1024)
    return TransformerConfig(**kw)


def bert_large(**kw) -> TransformerConfig:
    """BERT-large pretrain shape (BASELINE.json FusedLAMB config)."""
    kw.setdefault("num_layers", 24)
    kw.setdefault("hidden_size", 1024)
    kw.setdefault("num_attention_heads", 16)
    kw.setdefault("vocab_size", 30592)            # 30522 padded to 128
    kw.setdefault("max_position_embeddings", 512)
    kw.setdefault("attn_mask_type", "padding")    # bidirectional encoder
    return TransformerConfig(**kw)
