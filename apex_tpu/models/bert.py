"""BERT model family: bidirectional encoder + MLM/NSP pretrain heads.

Reference: apex/transformer/testing/standalone_bert.py (``bert_model_
provider`` → TransformerLanguageModel with add_pooler=True, padding mask)
and the BASELINE.json config 4 workload ('BERT-large pretrain with
FusedLAMB + fused_dense + xentropy'). Reuses the shared decoder backbone
(transformer_lm.transformer_backbone) with ``attn_mask_type='padding'``;
adds token-type embeddings, the embedding LayerNorm, the Megatron-style
LM head (dense+gelu+LN, tied word-embedding decoder + bias) and the
binary NSP head (tanh pooler over [CLS]).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.amp.frontend import make_train_step
from apex_tpu.models.config import TransformerConfig, bert_large
from apex_tpu.models.transformer_lm import (
    apply_norm,
    init_gpt_params,
    transformer_backbone,
)
from apex_tpu.ops.layer_norm import fused_layer_norm
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss

__all__ = ["init_bert_params", "bert_forward", "bert_pretrain_loss",
           "make_bert_train_step", "bert_large"]


def init_bert_params(rng: jax.Array, cfg: TransformerConfig,
                     num_tokentypes: int = 2) -> dict:
    """GPT param layout + BERT extras (tokentype emb, embedding LN,
    MLM head, NSP pooler/classifier)."""
    params = init_gpt_params(rng, cfg)
    h = cfg.hidden_size
    std = cfg.init_method_std
    ks = jax.random.split(jax.random.fold_in(rng, 17), 6)

    def nrm(k, shape):
        return (jax.random.normal(k, shape) * std).astype(jnp.float32)

    params["embedding"]["tokentype"] = nrm(ks[0], (num_tokentypes, h))
    params["embedding_ln"] = {"scale": jnp.ones((h,)),
                              "bias": jnp.zeros((h,))}
    params["lm_head"] = {
        "dense_kernel": nrm(ks[1], (h, h)),
        "dense_bias": jnp.zeros((h,)),
        "ln_scale": jnp.ones((h,)),
        "ln_bias": jnp.zeros((h,)),
        "decoder_bias": jnp.zeros((cfg.vocab_size,)),
    }
    params["binary_head"] = {
        "pooler_kernel": nrm(ks[2], (h, h)),
        "pooler_bias": jnp.zeros((h,)),
        "cls_kernel": nrm(ks[3], (h, 2)),
        "cls_bias": jnp.zeros((2,)),
    }
    return params


def _padding_mask(attention_mask):
    """[b, s] validity (1 = real token) → [b, s] bool key-padding mask
    (True = masked); the backbone fuses it into the flash kernel rather
    than materializing a [b, n, sq, sk] score mask."""
    if attention_mask is None:
        return None
    return attention_mask == 0


def bert_forward(params: dict, tokens: jax.Array, cfg: TransformerConfig,
                 *, tokentype_ids=None, attention_mask=None,
                 dropout_rng=None):
    """→ (lm_logits [b,s,v], binary_logits [b,2])."""
    cd = cfg.compute_dtype
    emb = params["embedding"]
    h = jnp.take(emb["word"].astype(cd), tokens, axis=0)
    h = h + emb["position"][: tokens.shape[1]].astype(cd)[None]
    if tokentype_ids is not None:
        h = h + jnp.take(emb["tokentype"].astype(cd), tokentype_ids,
                         axis=0)
    h = fused_layer_norm(h, params["embedding_ln"]["scale"],
                         params["embedding_ln"]["bias"],
                         eps=cfg.layernorm_epsilon)

    kpm = _padding_mask(attention_mask)
    h = transformer_backbone(params, h, cfg, _ident_ctx(),
                             attention_mask=kpm,
                             dropout_rng=dropout_rng)

    # MLM head (Megatron lm_head: dense+gelu+LN then tied decoder)
    lm = params["lm_head"]
    g = jax.nn.gelu(h @ lm["dense_kernel"].astype(cd)
                    + lm["dense_bias"].astype(cd))
    g = apply_norm(cfg, g, lm["ln_scale"], lm["ln_bias"])
    lm_logits = jnp.einsum(
        "bsh,vh->bsv", g, emb["word"].astype(cd),
        preferred_element_type=jnp.float32) + lm["decoder_bias"]

    # NSP head on [CLS] (position 0)
    bh = params["binary_head"]
    pooled = jnp.tanh(h[:, 0].astype(jnp.float32)
                      @ bh["pooler_kernel"] + bh["pooler_bias"])
    binary_logits = pooled @ bh["cls_kernel"] + bh["cls_bias"]
    return lm_logits, binary_logits


def _ident_ctx():
    from apex_tpu.models.transformer_lm import single_device_ctx

    return single_device_ctx()


def bert_pretrain_loss(params, tokens, mlm_labels, nsp_labels, cfg,
                       *, tokentype_ids=None, attention_mask=None,
                       dropout_rng=None):
    """MLM CE over positions with label >= 0 (others ignored, the -1
    convention) + NSP CE — reference standalone_bert loss composition."""
    lm_logits, bin_logits = bert_forward(
        params, tokens, cfg, tokentype_ids=tokentype_ids,
        attention_mask=attention_mask, dropout_rng=dropout_rng)
    v = lm_logits.shape[-1]
    flat_logits = lm_logits.reshape(-1, v)
    flat_labels = mlm_labels.reshape(-1)
    valid = flat_labels >= 0
    per_tok = softmax_cross_entropy_loss(
        flat_logits, jnp.clip(flat_labels, 0, v - 1), padding_idx=None)
    denom = jnp.maximum(jnp.sum(valid), 1)
    mlm_loss = jnp.sum(jnp.where(valid, per_tok, 0.0)) / denom

    nsp_lp = jax.nn.log_softmax(bin_logits, axis=-1)
    nsp_loss = -jnp.mean(
        jnp.take_along_axis(nsp_lp, nsp_labels[:, None], axis=1))
    return mlm_loss + nsp_loss


def make_bert_train_step(
    cfg: TransformerConfig,
    optimizer: Any,
    policy_or_amp="O2",
    mesh: Optional[Mesh] = None,
    *,
    grad_postprocess: Optional[Callable] = None,
):
    """(init_fn, step_fn); step(state, tokens, mlm_labels, nsp_labels,
    tokentype_ids, attention_mask[, rng]). The BASELINE config pairs this
    with optimizers.fused_lamb."""
    has_dropout = (cfg.hidden_dropout > 0 or cfg.attention_dropout > 0
                   or cfg.drop_path_rate > 0)

    def loss_fn(params, tokens, mlm_labels, nsp_labels, tokentype_ids,
                attention_mask, *rest):
        rng = rest[0] if has_dropout else None
        return bert_pretrain_loss(
            params, tokens, mlm_labels, nsp_labels, cfg,
            tokentype_ids=tokentype_ids, attention_mask=attention_mask,
            dropout_rng=rng)

    init_fn, step_fn = make_train_step(
        loss_fn, optimizer, policy_or_amp,
        grad_postprocess=grad_postprocess)

    def init(rng):
        return init_fn(init_bert_params(rng, cfg))

    if mesh is None:
        return init, jax.jit(step_fn, donate_argnums=0)

    bs = NamedSharding(mesh, P("dp"))
    shardings = (None, bs, bs, bs, bs, bs)
    if has_dropout:
        shardings += (NamedSharding(mesh, P()),)
    jstep = jax.jit(step_fn, in_shardings=shardings, donate_argnums=0)

    def step(state, *batch):
        with jax.set_mesh(mesh):
            return jstep(state, *batch)

    return init, step
