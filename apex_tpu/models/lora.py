"""Multi-tenant LoRA adapters: per-request low-rank deltas over a
frozen base model (ISSUE 20, ROADMAP item 2).

Millions of users means thousands of fine-tunes, not one model.  A LoRA
adapter is a pair of low-rank factors per target matmul — ``W' = W +
(alpha / r) * A @ B`` with ``A [in, r]``, ``B [r, out]`` — and the
serving question is how a heterogeneous batch (every lane a different
adapter) shares one decode step.  Two ways to apply one:

- **merge** (:func:`merge_lora`): fold the delta into the base kernels
  and serve the merged tree.  Zero per-token overhead, but the whole
  batch is pinned to ONE adapter, the base must stay float (an int8
  slab cannot absorb a float delta without requantization error), and
  switching adapters costs a full weight-set swap.  This is the
  numerics *reference* the batched path is pinned against.
- **batched** (:func:`batched_lora_delta`): keep the base frozen
  (optionally int8 — the delta rides beside it, never through it),
  stack the resident adapters' factors into ``[G, in, r]`` /
  ``[G, r, out]`` slabs, sort the batch rows by adapter slot
  (:func:`lora_plan`), and run the ragged grouped matmul of
  :mod:`~apex_tpu.ops.grouped_matmul` over the sorted rows — the
  S-LoRA computation, on the same window-offsets primitive the MoE
  ragged path uses.  Rows with no adapter (slot 0) sort BEFORE the
  window start (``offsets[0]``) where the grouped matmul leaves them
  exactly zero: the no-adapter majority of a mixed batch is computed
  for free, not through a zero-weight group.

The slot index per row is a *traced* vector (the serving engine's
``_temps`` pattern), so one compiled decode step serves every adapter
mix — compile keys never fork per adapter.  Slabs are float32 by
convention (adapters stay float over any base form; rank is small, the
delta FLOPs are ~``(in + out) * r`` per row per target against the
base's ``in * out``).

Geometry (matching ``transformer_lm.init_gpt_params``): targets are
``qkv`` ``[h, p + 2*kv]``, ``proj`` ``[p, h]``, ``fc1`` ``[h, f]`` (or
the paired swiglu ``[h, 2, f]``, carried flattened as ``[h, 2f]`` in
the B factor and reshaped at apply/merge time), ``fc2`` ``[f, h]``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops.dense import is_quantized
from apex_tpu.ops.grouped_matmul import grouped_matmul

__all__ = ["LoRAAdapter", "TARGETS", "target_shapes", "init_lora_adapter",
           "adapter_bytes", "merge_lora", "stack_adapter_slabs",
           "lora_plan", "batched_lora_delta", "lora_mlp"]

# target matmul name -> the layer-param kernel it shadows
TARGETS = ("qkv", "proj", "fc1", "fc2")
_KERNEL_OF = {"qkv": "qkv_kernel", "proj": "proj_kernel",
              "fc1": "fc1_kernel", "fc2": "fc2_kernel"}


@dataclasses.dataclass(frozen=True)
class LoRAAdapter:
    """One adapter: per-target ``A [L, in, r]`` / ``B [L, r, out]``
    factor stacks (leading layer axis, like the base layer stack) plus
    the static rank/alpha.  Registered as a pytree (rank/alpha are aux
    data) so an adapter jits, donates, and ``device_put``s like any
    parameter tree.  ``out`` is flattened for multi-axis kernels (the
    swiglu paired fc1): apply/merge reshape against the base kernel."""

    rank: int
    alpha: float
    a: Dict[str, jax.Array]
    b: Dict[str, jax.Array]

    @property
    def targets(self) -> Tuple[str, ...]:
        return tuple(t for t in TARGETS if t in self.a)

    @property
    def scaling(self) -> float:
        return float(self.alpha) / float(self.rank)


def _lora_flatten(ad):
    keys = tuple(sorted(ad.a))
    children = tuple(ad.a[k] for k in keys) + tuple(ad.b[k] for k in keys)
    return children, (ad.rank, ad.alpha, keys)


def _lora_unflatten(aux, children):
    rank, alpha, keys = aux
    n = len(keys)
    return LoRAAdapter(rank=rank, alpha=alpha,
                       a=dict(zip(keys, children[:n])),
                       b=dict(zip(keys, children[n:])))


jax.tree_util.register_pytree_node(
    LoRAAdapter, _lora_flatten, _lora_unflatten)


def target_shapes(cfg) -> Dict[str, Tuple[int, int]]:
    """``target -> (in_dim, out_dim_flat)`` for one layer of ``cfg``
    (the swiglu paired fc1's trailing ``[2, f]`` flattens to ``2f``)."""
    h = cfg.hidden_size
    p = cfg.projection_size
    kv = cfg.kv_projection_size
    f = cfg.ffn_hidden_size
    fc1_out = 2 * f if cfg.activation == "swiglu" else f
    return {"qkv": (h, p + 2 * kv), "proj": (p, h),
            "fc1": (h, fc1_out), "fc2": (f, h)}


def init_lora_adapter(rng: jax.Array, cfg, *, rank: int = 8,
                      alpha: Optional[float] = None,
                      targets: Sequence[str] = TARGETS,
                      b_std: float = 0.0,
                      dtype=jnp.float32) -> LoRAAdapter:
    """Fresh adapter for ``cfg``: ``A ~ N(0, 1/r)``, ``B`` zero (the
    standard identity-at-init) — pass ``b_std > 0`` for a *non-trivial*
    adapter (tests and benches need deltas that change tokens).
    ``alpha`` defaults to ``rank`` (scaling 1)."""
    if rank < 1:
        raise ValueError(f"rank={rank}: need a positive LoRA rank")
    targets = tuple(targets)
    unknown = [t for t in targets if t not in TARGETS]
    if unknown:
        raise ValueError(f"unknown LoRA targets {unknown}; expected a "
                         f"subset of {TARGETS}")
    shapes = target_shapes(cfg)
    L = cfg.num_layers
    keys = jax.random.split(rng, 2 * max(len(targets), 1))
    a, b = {}, {}
    for i, t in enumerate(targets):
        d_in, d_out = shapes[t]
        a[t] = (jax.random.normal(keys[2 * i], (L, d_in, rank),
                                  jnp.float32) / rank ** 0.5).astype(dtype)
        bk = jax.random.normal(keys[2 * i + 1], (L, rank, d_out),
                               jnp.float32) * b_std
        b[t] = bk.astype(dtype)
    return LoRAAdapter(rank=int(rank),
                       alpha=float(rank if alpha is None else alpha),
                       a=a, b=b)


def adapter_bytes(adapter: LoRAAdapter) -> int:
    """Device bytes one adapter occupies (both factors, all targets,
    all layers) — the unit the pool's byte bound divides by."""
    return int(sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(adapter)))


def merge_lora(params: dict, cfg, adapter: LoRAAdapter) -> dict:
    """The per-request merged-weights reference: a NEW params tree with
    each target kernel replaced by ``W + scaling * A @ B`` (reshaped to
    the kernel's layout).  Requires float target kernels — an int8 slab
    cannot absorb a float delta; the batched path exists precisely so a
    quantized base never has to."""
    layers = dict(params["layers"])
    for t in adapter.targets:
        kname = _KERNEL_OF[t]
        w = layers[kname]
        if is_quantized(w):
            raise ValueError(
                f"merge_lora: base kernel {kname!r} is int8-quantized; "
                "merging needs a float base — serve the adapter through "
                "the batched path instead")
        delta = jnp.einsum("lir,lro->lio",
                           adapter.a[t].astype(jnp.float32),
                           adapter.b[t].astype(jnp.float32))
        delta = (adapter.scaling * delta).reshape(w.shape)
        layers[kname] = (w.astype(jnp.float32) + delta).astype(w.dtype)
    out = dict(params)
    out["layers"] = layers
    return out


def stack_adapter_slabs(adapters: Sequence[Optional[LoRAAdapter]],
                        cfg) -> Dict[str, Dict[str, jax.Array]]:
    """Stack ``G`` adapters into the grouped-matmul slab form:
    ``target -> {"a": [L, G, in, r], "b": [L, G, r, out]}`` with the
    alpha/rank scaling folded into ``b`` (one place, once).  ``None``
    entries become zero factors (an empty pool slot contributes a zero
    delta if a stale index ever lands on it).  All non-None adapters
    must agree on rank, targets, and geometry — the slab is one array
    per target, so heterogeneous ranks would need per-slot padding the
    pool deliberately refuses (register-time validation beats a silent
    perf cliff)."""
    live = [a for a in adapters if a is not None]
    if not live:
        raise ValueError("stack_adapter_slabs: no adapters")
    rank = live[0].rank
    targets = live[0].targets
    for a in live[1:]:
        if a.rank != rank or a.targets != targets:
            raise ValueError(
                f"heterogeneous adapters: rank/targets "
                f"({a.rank}, {a.targets}) vs ({rank}, {targets})")
    shapes = target_shapes(cfg)
    L = cfg.num_layers
    out: Dict[str, Dict[str, jax.Array]] = {}
    for t in targets:
        d_in, d_out = shapes[t]
        a_stack, b_stack = [], []
        for ad in adapters:
            if ad is None:
                a_stack.append(jnp.zeros((L, d_in, rank), jnp.float32))
                b_stack.append(jnp.zeros((L, rank, d_out), jnp.float32))
            else:
                a_stack.append(ad.a[t].astype(jnp.float32))
                b_stack.append(ad.b[t].astype(jnp.float32)
                               * ad.scaling)
        # [L, G, in, r] / [L, G, r, out]: layer leading so the decode
        # scan slices per-layer slabs exactly like the base kernels
        out[t] = {"a": jnp.stack(a_stack, axis=1),
                  "b": jnp.stack(b_stack, axis=1)}
    return out


def lora_plan(idx: jax.Array, n_slots: int) -> Dict[str, jax.Array]:
    """Sort plan for one batch: ``idx`` ``[N]`` int32 per-row slot ids
    (0 = no adapter, ``s`` in ``[1, n_slots]`` = slab ``s - 1``) →
    ``{"order": [N], "offsets": [n_slots + 1]}``.  ``order`` is the
    stable sort-by-slot permutation; ``offsets`` are the grouped-matmul
    segment bounds, with the slot-0 rows packed BEFORE ``offsets[0]``
    — outside the window, where :func:`grouped_matmul` returns exactly
    zero (the free no-adapter path).  Everything is traced: one
    compiled step per shape, any adapter mix."""
    idx = idx.astype(jnp.int32)
    order = jnp.argsort(idx, stable=True)
    counts = jnp.bincount(idx, length=n_slots + 1)
    offsets = jnp.cumsum(counts).astype(jnp.int32)
    return {"order": order, "offsets": offsets}


def batched_lora_delta(x: jax.Array, a_slab: jax.Array,
                       b_slab: jax.Array,
                       plan: Dict[str, jax.Array]) -> jax.Array:
    """Heterogeneous-adapter delta for one target matmul: ``x``
    ``[..., in]`` (leading dims flattened to the plan's ``N`` rows) →
    ``scaling * x @ A[slot] @ B[slot]`` per row, ``[..., out]``, zero
    for slot-0 rows.  Two ragged grouped matmuls over the sorted rows,
    then the inverse permutation — the S-LoRA fast path.  The rank-r
    bottleneck keeps this ~``(in + out) * r`` FLOPs/row against the
    base matmul's ``in * out``."""
    shape = x.shape
    n = 1
    for d in shape[:-1]:
        n *= d
    xs = x.reshape(n, shape[-1])[plan["order"]].astype(a_slab.dtype)
    mid = grouped_matmul(xs, a_slab, plan["offsets"])
    out = grouped_matmul(mid.astype(b_slab.dtype), b_slab,
                         plan["offsets"])
    delta = jnp.zeros_like(out).at[plan["order"]].set(out)
    return delta.reshape(shape[:-1] + (b_slab.shape[-1],)).astype(x.dtype)


def lora_mlp(cfg, lp: dict, x: jax.Array, ll: dict, plan: dict):
    """``transformer_lm._mlp`` (single-device form) with fc1/fc2 LoRA
    deltas spliced in at the two matmul seams.  The fc1 delta lands
    BEFORE the activation (it changes the activation's input — a
    post-hoc add would be a different function); the swiglu paired
    ``[b, s, 2, f]`` layout takes the flattened delta reshaped.  Kept
    beside the slab machinery so the decode path has one lora-aware
    MLP, not a fork per call site."""
    from apex_tpu.ops.dense import quantized_matmul
    from apex_tpu.ops.swiglu import fused_bias_swiglu_paired

    w1 = lp["fc1_kernel"]
    d1 = (batched_lora_delta(x, ll["fc1"]["a"], ll["fc1"]["b"], plan)
          if "fc1" in ll else None)
    if cfg.activation == "swiglu":
        if is_quantized(w1):
            y = quantized_matmul(x, w1)               # [b, s, 2, f]
        else:
            y = jnp.einsum("bsh,hcf->bscf", x, w1.astype(x.dtype))
        if d1 is not None:
            y = y + d1.reshape(y.shape)
        y = fused_bias_swiglu_paired(y, lp["fc1_bias"].astype(x.dtype))
    else:
        if is_quantized(w1):
            y = quantized_matmul(x, w1)
        else:
            y = x @ w1.astype(x.dtype)
        if d1 is not None:
            y = y + d1.reshape(y.shape)
        y = y + lp["fc1_bias"].astype(x.dtype)
        y = jax.nn.gelu(
            y.astype(jnp.float32),
            approximate=cfg.activation == "gelu_tanh").astype(x.dtype)
    w2 = lp["fc2_kernel"]
    if is_quantized(w2):
        out = quantized_matmul(y, w2)
    else:
        out = y @ w2.astype(x.dtype)
    if "fc2" in ll:
        out = out + batched_lora_delta(y, ll["fc2"]["a"],
                                       ll["fc2"]["b"], plan)
    return out + lp["fc2_bias"].astype(x.dtype)
