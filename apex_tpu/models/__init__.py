"""apex_tpu.models — the standalone model family.

Reference: apex/transformer/testing/{standalone_transformer_lm.py,
standalone_gpt.py, standalone_bert.py} — the in-repo Megatron LM used by
every GPT/BERT minimal/integration test, rebuilt TPU-first (functional core,
scan-over-layers, GSPMD or shard_map parallelism).
"""

from apex_tpu.models.config import (  # noqa: F401
    TransformerConfig,
    bert_large,
    gpt_125m,
    gpt_tiny,
)
from apex_tpu.models.bert import (  # noqa: F401
    bert_forward,
    bert_pretrain_loss,
    init_bert_params,
    make_bert_train_step,
)
from apex_tpu.models.resnet import (  # noqa: F401
    ResNet,
    make_resnet_train_step,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
)
from apex_tpu.models.generate import (  # noqa: F401
    decode_step,
    generate,
    init_kv_cache,
    prefill,
    sample_logits,
)
from apex_tpu.models.quantized import (  # noqa: F401
    dequantize_params,
    quantize_params,
)
from apex_tpu.models.gpt import (  # noqa: F401
    gpt_pipeline_loss_and_grads,
    make_gpt_pipeline_stage,
    make_gpt_train_step,
    pipeline_packet,
    stack_pipeline_params,
)
from apex_tpu.models.transformer_lm import (  # noqa: F401
    TPContext,
    gpt_forward,
    gpt_loss,
    gpt_param_specs,
    gspmd_ctx,
    init_gpt_params,
    manual_ctx,
)
