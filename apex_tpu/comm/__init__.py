"""apex_tpu.comm — compressed & bucketed gradient collectives.

The reference apex's data-parallel performance features are bucketed
gradient all-reduce and fp16-compressed collectives
(apex/parallel/distributed.py ``allreduce_always_fp16`` + the bucketed
``Reducer``).  This package is the TPU-native generalization: a
gradient-communication layer with pluggable wire dtype and scheduling,
wired into every gradient-moving entry point via ``grad_comm=``:

- ``amp.frontend.make_train_step(..., grad_comm="int8")`` — the full
  AMP step reduces gradients through block-scaled quantized
  collectives, with per-leaf error-feedback residuals carried in the
  train state (``TrainState.comm_state``).
- ``parallel.distributed`` — ``allreduce_gradients`` /
  ``DistributedDataParallel`` / ``Reducer`` / ``make_ddp_train_step``
  all take ``grad_comm=``.
- ``contrib.optimizers.distributed_fused_adam`` — the ZeRO grad sync
  becomes a quantized reduce-scatter (scatter phase only; the param
  all-gather already travels at compute precision).

Three layers (see each module's docstring):

- :mod:`apex_tpu.comm.quantize` — block-scaled int8 / bf16 wire
  formats (EQuARX-style per-block fp32 scales).
- :mod:`apex_tpu.comm.bucketing` — greedy dtype-segregated buckets
  with giant-leaf chunking (the reference Reducer's geometry), sized
  so XLA's latency-hiding scheduler can overlap the resulting
  collectives with remaining backward compute.
- :mod:`apex_tpu.comm.reduce` — the shard_map collectives
  (reduce-scatter → local dequant-sum → requant → all-gather),
  error-feedback state helpers, and the
  ``collectives.compressed.{calls,bytes,raw_bytes}`` telemetry.

Wire-byte arithmetic (per gradient element, block=256): fp32 moves
8 bytes per all-reduce (scatter+gather passes), bf16 4 bytes, int8
~2.03 bytes (1 byte payload + fp32 scale per block, both passes) —
under 0.26x the fp32 bytes.
"""

from apex_tpu.comm.config import GradCommConfig, resolve  # noqa: F401
from apex_tpu.comm.bucketing import (  # noqa: F401
    Bucket,
    BucketSlice,
    gather_bucket,
    plan_buckets,
    scatter_buckets,
)
from apex_tpu.comm.quantize import (  # noqa: F401
    WIRE_DTYPES,
    dequantize_blocks,
    quantize_blocks,
)
from apex_tpu.comm.reduce import (  # noqa: F401
    compressed_allreduce,
    compressed_reduce_scatter,
    error_state_spec,
    expand_error_state,
    init_error_state,
    reduce_gradients,
)

__all__ = [
    "GradCommConfig",
    "resolve",
    "WIRE_DTYPES",
    "quantize_blocks",
    "dequantize_blocks",
    "Bucket",
    "BucketSlice",
    "plan_buckets",
    "gather_bucket",
    "scatter_buckets",
    "compressed_allreduce",
    "compressed_reduce_scatter",
    "reduce_gradients",
    "init_error_state",
    "expand_error_state",
    "error_state_spec",
]
