"""Gradient-communication spec: wire dtype, scaling blocks, error
feedback, and bucket geometry.

Every entry point that moves gradients (``amp.frontend.make_train_step``,
``parallel.distributed``, ``contrib.optimizers.distributed_fused_adam``)
takes a ``grad_comm=`` argument resolved here: the strings ``"fp32"`` /
``"bf16"`` / ``"int8"`` pick a wire dtype with defaults, a
:class:`GradCommConfig` sets everything explicitly, and ``None`` keeps
the legacy uncompressed behavior byte-for-byte.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

from apex_tpu.comm.quantize import WIRE_DTYPES

__all__ = ["GradCommConfig", "resolve"]


@dataclasses.dataclass(frozen=True)
class GradCommConfig:
    """How gradients travel over the data-parallel axis.

    Attributes:
      wire_dtype: ``"fp32"`` (no compression — plain psum/pmean),
        ``"bf16"`` (elementwise cast, 2 bytes/element, bitwise
        independent of bucket geometry), or ``"int8"`` (block-scaled
        symmetric int8, ~1 byte/element + ``4/block`` scale overhead).
      block: elements per fp32 scale block for ``"int8"`` (EQuARX-style
        per-block dynamic range).  256 keeps scale overhead under 2%.
      error_feedback: carry a per-leaf fp32 residual of the local
        quantization error into the next step so compression error
        cancels instead of accumulating (1-bit-Adam/EF-SGD residual
        trick).  ``None`` resolves to True for int8 and False
        otherwise; bf16's rounding error is small enough that the
        extra state rarely pays for itself.
      bucket_bytes: greedy bucket target in **raw fp32 bytes**
        (reference Reducer default ~16MB; 4MB here keeps several
        independent collectives in flight for the latency-hiding
        scheduler to overlap with backward).  Leaves larger than one
        bucket are split into bucket-sized chunks.
    """

    wire_dtype: str = "fp32"
    block: int = 256
    error_feedback: Optional[bool] = None
    bucket_bytes: int = 4 << 20

    def __post_init__(self):
        if self.wire_dtype not in WIRE_DTYPES:
            raise ValueError(
                f"wire_dtype {self.wire_dtype!r} not in {WIRE_DTYPES}")
        if self.block <= 0:
            raise ValueError(f"block must be positive, got {self.block}")
        if self.bucket_bytes <= 0:
            raise ValueError(
                f"bucket_bytes must be positive, got {self.bucket_bytes}")

    @property
    def compresses(self) -> bool:
        """True when the wire dtype actually shrinks the payload."""
        return self.wire_dtype != "fp32"

    @property
    def use_error_feedback(self) -> bool:
        if self.error_feedback is None:
            return self.wire_dtype == "int8"
        return self.error_feedback and self.compresses


def resolve(
    spec: Union[None, str, GradCommConfig]
) -> Optional[GradCommConfig]:
    """``None`` | ``"fp32"``/``"bf16"``/``"int8"`` | config → config.

    ``None`` stays ``None`` so call sites can distinguish "not asked"
    (legacy path, no comm import at all) from an explicit fp32 spec.
    """
    if spec is None:
        return None
    if isinstance(spec, GradCommConfig):
        return spec
    if isinstance(spec, str):
        return GradCommConfig(wire_dtype=spec)
    raise TypeError(
        "grad_comm must be None, one of "
        f"{WIRE_DTYPES}, or a GradCommConfig; got {type(spec).__name__}")
