"""Compressed gradient collectives under ``shard_map``.

The bandwidth-optimal decomposition of a gradient all-reduce is
reduce-scatter + all-gather (the cross-replica weight-update-sharding
recipe, arXiv:2004.13336); EQuARX (arXiv:2506.17615) adds block-scaled
quantization to both phases inside XLA.  This module implements that
shape with explicit shard_map collectives:

1. **scatter phase** — each rank splits its (error-compensated) local
   gradient into ``n`` equal shards, quantizes, and ``all_to_all``s the
   wire bytes: rank *j* receives every rank's quantized copy of shard
   *j*, dequantizes in fp32, and sums over ranks in fixed rank order
   (deterministic, bucket-independent).
2. **gather phase** — the owner re-quantizes its reduced shard and
   ``all_gather``s the wire bytes; everyone dequantizes back to fp32.

:func:`compressed_allreduce` runs both phases (DDP semantics);
:func:`compressed_reduce_scatter` stops after (1) for consumers that
only need their own shard (the ZeRO optimizer — its param all-gather
already travels at compute precision).

Error feedback keeps a per-leaf fp32 residual of the *local*
quantization error (``contribution - dequant(wire)``), added back into
the next step's contribution — the EF-SGD/1-bit-Adam trick that stops
deterministic rounding error from accumulating in the params.  The
residual is rank-local state: carried in the train state with a leading
rank axis and sharded ``P(axis)`` by the shard_map wrapper (see
``amp.frontend.make_train_step`` / ``parallel.make_ddp_train_step``).

Like ``utils.collectives``, the tree-level entry is **vma-aware**:
leaves SPMD-AD already summed (axis-invariant under jax≥0.9 shard_map)
cannot be compressed after the fact — they take the plain division,
and only shard-varying leaves pay a collective.  Callers that want
compression therefore differentiate w.r.t. ``pvary``-ed params so the
gradients arrive per-shard (see the ``grad_comm`` wiring in
``amp.frontend``).

Telemetry (trace-time, like ``_note_collective``): counters
``collectives.compressed.calls``, ``collectives.compressed.bytes``
(wire payload + scale bytes actually moved, both phases) and
``collectives.compressed.raw_bytes`` (what the uncompressed fp32 form
would move: 2 passes for an all-reduce, 1 for a reduce-scatter).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.comm.bucketing import (
    gather_bucket,
    plan_buckets,
    scatter_buckets,
)
from apex_tpu.comm.config import GradCommConfig
from apex_tpu.comm.quantize import (
    dequantize_blocks,
    quantize_blocks,
    scale_bytes_per_element,
    wire_itemsize,
)
from apex_tpu.observability import metrics as _telemetry

__all__ = [
    "compressed_allreduce",
    "compressed_reduce_scatter",
    "reduce_gradients",
    "init_error_state",
    "expand_error_state",
    "error_state_spec",
]


def _note_compressed(cfg: GradCommConfig, n_elements: int,
                     passes_raw: int, passes_wire: int) -> None:
    """Trace-time byte accounting: one record per collective emitted
    into the compiled program (host-callback-free, like
    ``utils.collectives._note_collective``)."""
    reg = _telemetry.registry()
    if reg is None:
        return
    per_el = wire_itemsize(cfg.wire_dtype) + scale_bytes_per_element(
        cfg.wire_dtype, cfg.block)
    reg.counter("collectives.compressed.calls").inc()
    reg.counter("collectives.compressed.bytes").inc(
        int(passes_wire * per_el * n_elements))
    reg.counter("collectives.compressed.raw_bytes").inc(
        int(passes_raw * 4 * n_elements))


def _axis_size(axis_name: str) -> int:
    """Static mapped-axis size. ``jax.lax.axis_size`` where available
    (jax≥0.9); on older jax ``psum(1, axis)`` folds to a python int at
    trace time — shard shapes below need a static value."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return jax.lax.psum(1, axis_name)


def _all_to_all(x: jax.Array, axis_name: str) -> jax.Array:
    # [n, shard] → [n, shard]: row j goes to rank j; row i of the
    # result is rank i's copy of MY shard.  Counted wrapper: the wire
    # traffic also lands in collectives.all_to_all.* next to the
    # compressed-byte accounting.
    from apex_tpu.utils.collectives import all_to_all

    return all_to_all(x, axis_name, 0, 0, tiled=True)


def _scatter_phase(
    flat: jax.Array,
    axis_name: str,
    cfg: GradCommConfig,
    *,
    shard_size: Optional[int] = None,
    residual: Optional[jax.Array] = None,
    predivide: Optional[float] = None,
) -> Tuple[jax.Array, Optional[jax.Array], int, int]:
    """Quantize → all_to_all → local dequant-sum.

    Returns ``(local_sum [shard], err [L] | None, shard, padded)`` where
    ``local_sum`` is this rank's shard of the cross-rank SUM.
    """
    n = _axis_size(axis_name)
    length = flat.shape[0]
    x = flat.astype(jnp.float32)
    if predivide:
        x = x / predivide
    c = x + residual if residual is not None else x
    if shard_size is not None:
        shard = shard_size
    else:
        shard = -(-length // n)
        if cfg.wire_dtype == "int8":
            # block-align the shard rows: each row of the [n, shard]
            # wire matrix starts its own scale-block grid, so a
            # non-multiple shard would let a block straddle two leaves'
            # block-aligned spans (see bucketing.plan_buckets align)
            shard = -(-shard // cfg.block) * cfg.block
    padded = shard * n
    if length > padded:
        raise ValueError(
            f"flat length {length} exceeds shard_size*n = {padded}")
    cp = jnp.pad(c, (0, padded - length)).reshape(n, shard)
    wire, scales = quantize_blocks(cp, cfg.wire_dtype, cfg.block)
    recv_w = _all_to_all(wire, axis_name)
    recv_s = _all_to_all(scales, axis_name) if scales is not None else None
    contrib = dequantize_blocks(recv_w, recv_s, cfg.block, shard)
    # fixed rank-order reduction: elementwise over the rank axis, so the
    # result is independent of bucket geometry (bf16 bitwise stability)
    local_sum = jnp.sum(contrib, axis=0)
    err = None
    if residual is not None:
        own = dequantize_blocks(wire, scales, cfg.block, shard)
        err = c - own.reshape(padded)[:length]
    return local_sum, err, shard, padded


def compressed_allreduce(
    flat: jax.Array,
    axis_name: str,
    cfg: GradCommConfig,
    *,
    residual: Optional[jax.Array] = None,
    average: bool = True,
    predivide: Optional[float] = None,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Block-scaled quantized all-reduce of a flat fp32 vector.

    Must run inside ``shard_map``/``pmap`` with ``axis_name`` bound and
    ``flat`` shard-varying.  Returns ``(reduced [L], new_residual [L] |
    None)`` — the mean over ranks when ``average`` (the
    ``gradient_predivide_factor`` arithmetic mirrors
    ``parallel.allreduce_gradients``), identical on every rank.
    """
    n = _axis_size(axis_name)
    length = flat.shape[0]
    local_sum, err, shard, padded = _scatter_phase(
        flat, axis_name, cfg, residual=residual, predivide=predivide)
    if average:
        local_sum = local_sum / (n / predivide if predivide else n)
    # gather phase: requantize the reduced shard, move wire bytes only
    wire2, scales2 = quantize_blocks(local_sum, cfg.wire_dtype, cfg.block)
    from apex_tpu.utils.collectives import all_gather as _counted_ag

    full_w = _counted_ag(wire2, axis_name)
    full_s = (_counted_ag(scales2, axis_name)
              if scales2 is not None else None)
    rows = dequantize_blocks(full_w, full_s, cfg.block, shard)
    out = rows.reshape(padded)[:length]
    _note_compressed(cfg, padded, passes_raw=2, passes_wire=2)
    return out, err


def compressed_reduce_scatter(
    flat: jax.Array,
    axis_name: str,
    cfg: GradCommConfig,
    *,
    shard_size: int,
    residual: Optional[jax.Array] = None,
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Scatter phase only: this rank's ``shard_size`` shard of the
    cross-rank SUM (not mean), plus the new error-feedback residual.

    ``flat`` is zero-padded to ``shard_size * n``; the caller owns the
    shard layout (rank *i* holds elements ``[i*shard, (i+1)*shard)`` —
    the same contiguous split ``dynamic_slice`` on a psum-ed vector
    would give, so it drops into the ZeRO optimizer unchanged).
    """
    local_sum, err, _, padded = _scatter_phase(
        flat, axis_name, cfg, shard_size=shard_size, residual=residual)
    _note_compressed(cfg, padded, passes_raw=1, passes_wire=1)
    return local_sum, err


# ---- tree-level entry + error-feedback state ---------------------------------


def _is_float(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact)


def init_error_state(tree: Any) -> Tuple[jax.Array, ...]:
    """Zero residuals for every floating leaf of ``tree`` (flatten
    order), each with a leading rank axis of size 1.

    The leading axis is the sharding handle: a shard_map wrapper stores
    the global residual as ``[n_ranks, *leaf.shape]`` (see
    :func:`expand_error_state`) and specs it ``P(axis)`` so each rank
    carries its own rank-local error (:func:`error_state_spec`).
    """
    return tuple(
        jnp.zeros((1,) + tuple(leaf.shape), jnp.float32)
        for leaf in jax.tree_util.tree_leaves(tree) if _is_float(leaf))


def expand_error_state(
    state: Sequence[jax.Array], n_ranks: int
) -> Tuple[jax.Array, ...]:
    """Grow the leading rank axis to ``n_ranks`` (zeros — fresh
    residuals are zero on every rank)."""
    return tuple(
        jnp.zeros((n_ranks,) + tuple(r.shape[1:]), r.dtype) for r in state)


def error_state_spec(state: Sequence[Any], axis_name: str) -> Tuple:
    """Per-leaf ``PartitionSpec`` splitting the leading rank axis."""
    from jax.sharding import PartitionSpec as P

    return tuple(P(axis_name) for _ in state)


def reduce_gradients(
    tree: Any,
    axis_name: str,
    cfg: GradCommConfig,
    residuals: Optional[Sequence[jax.Array]] = None,
    *,
    average: bool = True,
    predivide: Optional[float] = None,
) -> Tuple[Any, Optional[Tuple[jax.Array, ...]]]:
    """Bucketed compressed reduction of a gradient pytree.

    Floating, shard-varying leaves are packed into dtype-segregated
    greedy buckets (giant leaves split — ``cfg.bucket_bytes``) and each
    bucket takes one :func:`compressed_allreduce`; SPMD-AD pre-summed
    (axis-invariant) leaves take the plain division, and non-float
    leaves pass through.  ``residuals`` is the per-leaf error-feedback
    tuple from :func:`init_error_state` (aligned with the tree's
    floating leaves); returns ``(reduced_tree, new_residuals)`` with
    residuals in the same per-leaf layout.
    """
    if not cfg.compresses:
        raise ValueError(
            "reduce_gradients is the compressed path; use "
            "utils.collectives.grad_mean / parallel.allreduce_gradients "
            "for fp32 wire")
    from apex_tpu.utils.collectives import is_varying

    n = _axis_size(axis_name)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    float_idx = [i for i, leaf in enumerate(leaves) if _is_float(leaf)]
    if residuals is not None and len(residuals) != len(float_idx):
        raise ValueError(
            f"residual count {len(residuals)} != floating leaf count "
            f"{len(float_idx)} (build it with comm.init_error_state)")

    comp_idx = [i for i in float_idx if is_varying(leaves[i], axis_name)]
    comp_leaves = [leaves[i] for i in comp_idx]
    # int8: align slices to the scale-block grid so no block mixes two
    # leaves (a bias block inheriting a weight's dynamic range would
    # quantize to pure noise); bf16 has no blocks to protect
    plan = plan_buckets(
        comp_leaves, cfg.bucket_bytes,
        align=cfg.block if cfg.wire_dtype == "int8" else 1)

    res_for = {}
    if residuals is not None:
        res_for = dict(zip(float_idx, residuals))
    # residuals carry a leading rank axis (1 inside shard_map) — view
    # them leaf-shaped for bucketing
    res_comp = [res_for[i].reshape(leaves[i].shape) for i in comp_idx] \
        if residuals is not None else None

    outs: List[jax.Array] = []
    errs: List[jax.Array] = []
    for bucket in plan:
        flat = gather_bucket(comp_leaves, bucket)
        rflat = (gather_bucket(res_comp, bucket)
                 if res_comp is not None else None)
        out, err = compressed_allreduce(
            flat, axis_name, cfg, residual=rflat,
            average=average, predivide=predivide)
        outs.append(out)
        if err is not None:
            errs.append(err)

    new_comp = scatter_buckets(comp_leaves, plan, outs)
    new_res_comp = (scatter_buckets(res_comp, plan, errs)
                    if res_comp is not None else None)

    out_leaves = list(leaves)
    for k, i in enumerate(comp_idx):
        out_leaves[i] = new_comp[k]
    comp_set = set(comp_idx)
    for i in float_idx:
        if i in comp_set:
            continue
        # SPMD-AD already summed this leaf over the axis: apply the
        # same net scaling the varying path would (predivide by f, sum,
        # then /(n/f) when averaging — net /n averaged, /f otherwise)
        if average:
            out_leaves[i] = leaves[i] / n
        elif predivide:
            out_leaves[i] = leaves[i] / predivide

    new_residuals = None
    if residuals is not None:
        by_idx = dict(res_for)
        for k, i in enumerate(comp_idx):
            by_idx[i] = new_res_comp[k].reshape(res_for[i].shape)
        new_residuals = tuple(by_idx[i] for i in float_idx)
    return jax.tree_util.tree_unflatten(treedef, out_leaves), new_residuals
