"""Block-scaled wire-format quantization for gradient collectives.

EQuARX (arXiv:2506.17615) shows that quantizing AllReduce payloads with
*per-block* scales — rather than per-tensor — keeps the dynamic range of
every 256-element neighborhood and recovers near-lossless quality at a
fraction of the interconnect bytes.  This module is the dtype layer of
``apex_tpu.comm``: pure elementwise/blockwise math with no collectives,
so it is trivially correct to test single-device and reusable by both
the all-reduce and reduce-scatter forms in :mod:`apex_tpu.comm.reduce`.

Wire formats (``GradCommConfig.wire_dtype``):

- ``"int8"``  — symmetric round-to-nearest int8 in [-127, 127] with one
  fp32 scale per ``block`` elements (``scale = max|x| / 127``; all-zero
  blocks get scale 1 so dequantization is exact).  ~4x fewer payload
  bytes than fp32 plus ``4/block`` overhead for the scales.
- ``"bf16"``  — a plain elementwise cast; no scales.  bf16 keeps fp32's
  exponent range, so block scaling buys nothing — and the elementwise
  form makes the reduction *bitwise independent of bucket geometry*
  (the property the bucket-stability tests pin down).
- ``"fp32"``  — identity passthrough (no compression; callers normally
  short-circuit to a plain psum/pmean before reaching here).

Quantization is over the **last** axis so the reduce layer can operate
on ``[n_shards, shard]`` wire matrices; lengths that do not divide
``block`` are zero-padded internally (zero pads quantize exactly and
are truncated by :func:`dequantize_blocks`).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

__all__ = [
    "WIRE_DTYPES",
    "quantize_blocks",
    "dequantize_blocks",
    "wire_itemsize",
    "scale_bytes_per_element",
]

WIRE_DTYPES = ("fp32", "bf16", "int8")

_INT8_MAX = 127.0


def wire_itemsize(wire_dtype: str) -> int:
    """Bytes per element on the wire for ``wire_dtype``."""
    return {"fp32": 4, "bf16": 2, "int8": 1}[wire_dtype]


def scale_bytes_per_element(wire_dtype: str, block: int) -> float:
    """Amortized fp32-scale overhead per payload element (0 for
    scale-free wire dtypes)."""
    return 4.0 / block if wire_dtype == "int8" else 0.0


def _pad_last(x: jnp.ndarray, multiple: int) -> jnp.ndarray:
    length = x.shape[-1]
    rem = length % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, multiple - rem)]
    return jnp.pad(x, pad)


def quantize_blocks(
    x: jnp.ndarray, wire_dtype: str, block: int
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Quantize fp32 ``x`` over its last axis → ``(wire, scales)``.

    ``wire`` has the same leading shape as ``x`` with the last axis
    zero-padded up to a multiple of ``block`` (int8) or unchanged
    (bf16/fp32); ``scales`` is fp32 ``[..., ceil(L/block)]`` for int8
    and ``None`` otherwise.
    """
    if wire_dtype == "fp32":
        return x, None
    if wire_dtype == "bf16":
        return x.astype(jnp.bfloat16), None
    if wire_dtype != "int8":
        raise ValueError(
            f"unknown wire dtype {wire_dtype!r}; expected one of "
            f"{WIRE_DTYPES}")
    xp = _pad_last(x.astype(jnp.float32), block)
    blocks = xp.reshape(xp.shape[:-1] + (-1, block))
    amax = jnp.max(jnp.abs(blocks), axis=-1)
    # all-zero block → scale 1: 0/1 quantizes and dequantizes exactly,
    # and the zero padding added above introduces no error.  The
    # comparison is amax == 0 (not amax > 0) so a NaN amax falls into
    # the amax/127 branch and the scale itself goes NaN — int8 casting
    # would otherwise launder NaN gradients into finite wire values and
    # defeat every downstream isfinite overflow check.
    scales = jnp.where(amax == 0, 1.0, amax / _INT8_MAX)
    q = jnp.round(blocks / scales[..., None])
    wire = jnp.clip(q, -_INT8_MAX, _INT8_MAX).astype(jnp.int8)
    return wire.reshape(xp.shape), scales


def dequantize_blocks(
    wire: jnp.ndarray,
    scales: Optional[jnp.ndarray],
    block: int,
    length: int,
) -> jnp.ndarray:
    """Invert :func:`quantize_blocks`, truncating the last axis back to
    ``length`` (drops the internal block padding)."""
    if scales is None:
        out = wire.astype(jnp.float32)
        return out[..., :length]
    blocks = wire.astype(jnp.float32).reshape(
        wire.shape[:-1] + (-1, block))
    out = (blocks * scales[..., None]).reshape(wire.shape)
    return out[..., :length]
