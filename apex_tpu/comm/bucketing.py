"""Greedy size-bucketed flattening of gradient pytrees.

Reference: apex DDP's ``Reducer`` builds *dtype-segregated greedy
buckets* on the first backward (apex/parallel/distributed.py:369-390) —
small tensors are flattened together so each NCCL call moves a
worthwhile payload, and the bucket boundaries let allreduces launch
while the tail of backward is still producing grads.  Under XLA the
motivation inverts but survives: ONE whole-model collective serializes
against the last grad's producer, while several bucket-sized
collectives give the latency-hiding scheduler independent operands to
overlap with remaining backward compute.  Giant leaves (embeddings) are
*split* across buckets for the same reason.

This module is pure trace-time planning + gather/scatter math — no
collectives, no jax transforms — so the plan is recomputed from static
shapes at every trace (cheap python) and the data movement is plain
``concatenate``/``dynamic_slice``-free reshaping XLA fuses away.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = ["BucketSlice", "Bucket", "plan_buckets", "gather_bucket",
           "scatter_buckets"]


class BucketSlice(NamedTuple):
    """One contiguous span of a flattened leaf assigned to a bucket."""

    leaf_index: int
    start: int     # element offset into the flattened leaf
    stop: int


def _aligned(n: int, align: int) -> int:
    return -(-n // align) * align


class Bucket(NamedTuple):
    slices: Tuple[BucketSlice, ...]
    size: int       # flat elements including per-slice alignment padding
    align: int = 1  # per-slice padding granularity (the scale block)

    @property
    def nbytes(self) -> int:
        # planning accounting is in raw fp32 gradient bytes
        return self.size * 4


def plan_buckets(
    leaves: Sequence[Any],
    bucket_bytes: int,
    align: int = 1,
) -> List[Bucket]:
    """Partition ``leaves`` (abstract or concrete arrays) into greedy
    buckets of at most ``bucket_bytes`` raw fp32 bytes.

    Dtype-segregated like the reference Reducer: leaves of different
    dtypes never share a bucket (tp_bucket keying, distributed.py:378).
    Leaves larger than a bucket are split into bucket-sized chunks —
    each chunk becomes its own collective so XLA can overlap them.
    Every element of every leaf is covered exactly once; empty leaves
    are skipped.

    ``align > 1`` zero-pads every slice's span in the flat bucket to a
    multiple of ``align``.  With ``align`` = the quantization block
    size, no scale block ever mixes elements from two leaves — a
    small-magnitude bias sharing a block with a large weight would
    otherwise inherit the weight's int8 step and lose all its bits
    (zero padding quantizes exactly, so the pad costs bytes but no
    precision).
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    if align <= 0:
        raise ValueError(f"align must be positive, got {align}")
    cap = max(align, (bucket_bytes // 4) // align * align)
    buckets: List[Bucket] = []
    # dtype segregation: one open bucket per dtype key
    open_slices: dict = {}
    open_size: dict = {}

    def close(key):
        if open_slices.get(key):
            buckets.append(
                Bucket(tuple(open_slices[key]), open_size[key], align))
            open_slices[key] = []
            open_size[key] = 0

    for i, leaf in enumerate(leaves):
        shape = getattr(leaf, "shape", ())
        n = 1
        for d in shape:
            n *= int(d)
        if n == 0:
            continue
        key = str(getattr(leaf, "dtype", "f32"))
        open_slices.setdefault(key, [])
        open_size.setdefault(key, 0)
        off = 0
        while off < n:
            room = cap - open_size[key]
            take = min(n - off, room)
            if take == 0:
                close(key)
                continue
            open_slices[key].append(BucketSlice(i, off, off + take))
            open_size[key] += _aligned(take, align)
            off += take
            if open_size[key] >= cap:
                close(key)
    for key in list(open_slices):
        close(key)
    return buckets


def gather_bucket(leaves: Sequence[jax.Array], bucket: Bucket) -> jax.Array:
    """Concatenate the bucket's slices into one flat fp32 vector
    (zero-padding each slice to the bucket's alignment)."""
    parts = []
    for s in bucket.slices:
        piece = (leaves[s.leaf_index].reshape(-1)[s.start:s.stop]
                 .astype(jnp.float32))
        pad = _aligned(s.stop - s.start, bucket.align) - (s.stop - s.start)
        if pad:
            piece = jnp.pad(piece, (0, pad))
        parts.append(piece)
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def scatter_buckets(
    leaves: Sequence[jax.Array],
    buckets: Sequence[Bucket],
    flats: Sequence[jax.Array],
) -> List[jax.Array]:
    """Rebuild full leaves from per-bucket flat vectors (inverse of
    :func:`gather_bucket` — alignment padding is dropped).

    Returns a list the same length as ``leaves``: leaves covered by the
    plan are reassembled (in each leaf's original dtype and shape) from
    their slices; uncovered leaves (not floating, empty) pass through
    unchanged.
    """
    pieces: dict = {i: [] for i in range(len(leaves))}
    for bucket, flat in zip(buckets, flats):
        off = 0
        for s in bucket.slices:
            take = s.stop - s.start
            pieces[s.leaf_index].append((s.start, flat[off:off + take]))
            off += _aligned(take, bucket.align)
    out: List[jax.Array] = []
    for i, leaf in enumerate(leaves):
        if not pieces[i]:
            out.append(leaf)
            continue
        parts = [p for _, p in sorted(pieces[i], key=lambda t: t[0])]
        flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        out.append(flat.reshape(leaf.shape).astype(leaf.dtype))
    return out
