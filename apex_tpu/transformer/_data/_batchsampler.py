"""Megatron-style batch samplers for DP-sharded pretraining input.

Reference: ``apex/transformer/_data/_batchsampler.py``
(``MegatronPretrainingSampler``, ``MegatronPretrainingRandomSampler`` —
themselves extracted from Megatron-LM's data_samplers).  Semantics
preserved torch-free:

- a *local minibatch* is ``global_batch_size / data_parallel_size``
  indices for THIS dp rank;
- ``consumed_samples`` makes sampling resumable mid-epoch (the
  checkpoint carries it);
- the random sampler shards the dataset into per-rank buckets and
  reshuffles per epoch with a deterministic seed (epoch number), so
  every rank draws a disjoint, epoch-stable permutation — numpy
  ``default_rng(epoch)`` replaces ``torch.Generator.manual_seed``.

On TPU the yielded index lists feed whatever host pipeline stages the
batch (e.g. ``examples/imagenet_rn50.prefetcher``); the arrays then land
on device via ``jax.device_put`` with a ('dp',)-sharded layout.
"""

from __future__ import annotations

import abc

import numpy as np

__all__ = [
    "MegatronPretrainingSampler",
    "MegatronPretrainingRandomSampler",
]


class _Base(abc.ABC):
    """Base class for Megatron-style batch samplers."""

    @abc.abstractmethod
    def __len__(self) -> int:
        ...

    @abc.abstractmethod
    def __iter__(self):
        ...

    @property
    def local_minibatch_size(self) -> int:
        return self._local_minibatch_size

    @local_minibatch_size.setter
    def local_minibatch_size(self, new: int) -> None:
        self._local_minibatch_size = new
        self.local_minibatch_times_data_parallel_size = (
            new * self.data_parallel_size)


class MegatronPretrainingSampler(_Base):
    """Sequential sampler: global batches walk the dataset in order; each
    rank takes its contiguous slice of every global batch.

    Deviation note: the apex fork fills its buffer only to
    ``local_minibatch_size`` before slicing ``[rank*lmbs:(rank+1)*lmbs]``
    (_batchsampler.py:88-97), which yields an empty list for every rank
    > 0; this port implements the upstream Megatron-LM semantics the
    fork was extracted from (fill to ``lmbs * data_parallel_size``, then
    slice), which is the behavior its own docstring describes."""

    def __init__(
        self,
        total_samples: int,
        consumed_samples: int,
        local_minibatch_size: int,
        data_parallel_rank: int,
        data_parallel_size: int,
        drop_last: bool = True,
    ):
        if total_samples <= 0:
            raise RuntimeError(f"no sample to consume: {total_samples}")
        if consumed_samples >= total_samples:
            raise RuntimeError(
                f"no samples left to consume: {consumed_samples}, "
                f"{total_samples}")
        if local_minibatch_size <= 0:
            raise RuntimeError(
                "local minibatch size must be greater than 0: "
                f"{local_minibatch_size}")
        if data_parallel_size <= 0:
            raise RuntimeError(
                f"data parallel size must be greater than 0: "
                f"{data_parallel_size}")
        if data_parallel_rank >= data_parallel_size:
            raise RuntimeError(
                "data_parallel_rank should be smaller than data size: "
                f"{data_parallel_rank}, {data_parallel_size}")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.drop_last = drop_last

    def __len__(self):
        return self.total_samples

    def get_start_end_idx(self):
        start = self.data_parallel_rank * self.local_minibatch_size
        return start, start + self.local_minibatch_size

    def __iter__(self):
        batch = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.local_minibatch_times_data_parallel_size:
                start, end = self.get_start_end_idx()
                yield batch[start:end]
                batch = []
        if batch and not self.drop_last:
            start, end = self.get_start_end_idx()
            yield batch[start:end]


class MegatronPretrainingRandomSampler(_Base):
    """Random sampler: per-rank disjoint buckets, epoch-seeded shuffles,
    resumable via ``consumed_samples``."""

    def __init__(
        self,
        total_samples: int,
        consumed_samples: int,
        local_minibatch_size: int,
        data_parallel_rank: int,
        data_parallel_size: int,
    ) -> None:
        if total_samples <= 0:
            raise ValueError(
                f"no sample to consume: total_samples of {total_samples}")
        if local_minibatch_size <= 0:
            raise ValueError(
                f"Invalid local_minibatch_size: {local_minibatch_size}")
        if data_parallel_size <= 0:
            raise ValueError(
                f"Invalid data_parallel_size: {data_parallel_size}")
        if data_parallel_rank >= data_parallel_size:
            raise ValueError(
                "data_parallel_rank should be smaller than data parallel "
                f"size: {data_parallel_rank} < {data_parallel_size}")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.last_batch_size = (
            self.total_samples
            % self.local_minibatch_times_data_parallel_size)

    def __len__(self) -> int:
        return self.total_samples

    def __iter__(self):
        active_total = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active_total
        current_epoch_samples = self.consumed_samples % active_total

        bucket_size = (
            self.total_samples
            // self.local_minibatch_times_data_parallel_size
        ) * self.local_minibatch_size
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        rng = np.random.default_rng(self.epoch)
        random_idx = rng.permutation(bucket_size).tolist()
        idx_range = [start_idx + x for x in random_idx[bucket_offset:]]

        batch = []
        # Last batch if not complete will be dropped.
        for idx in idx_range:
            batch.append(idx)
            if len(batch) == self.local_minibatch_size:
                self.consumed_samples += (
                    self.local_minibatch_times_data_parallel_size)
                yield batch
                batch = []
