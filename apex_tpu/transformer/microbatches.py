"""Microbatch calculators.

Reference: apex/transformer/microbatches.py — ``ConstantNumMicroBatches``
(:93) and ``RampupBatchsizeNumMicroBatches`` (:112), built by
``build_num_microbatches_calculator`` (:24).
"""

from __future__ import annotations

from typing import Optional, Sequence

__all__ = [
    "build_num_microbatches_calculator",
    "ConstantNumMicroBatches",
    "RampupBatchsizeNumMicroBatches",
]


class NumMicroBatchesCalculator:
    num_micro_batches: int
    current_global_batch_size: int

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """reference microbatches.py:93."""

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_dp != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible "
                f"by micro batch size ({micro_batch_size}) times "
                f"data parallel size ({data_parallel_size})"
            )
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        self.current_global_batch_size = global_batch_size


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Linear global-batch-size rampup (reference microbatches.py:112)."""

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.start_batch_size = start_batch_size
        self.batch_size_increment = batch_size_increment
        self.ramup_samples = ramup_samples
        self.global_batch_size = global_batch_size
        diff = global_batch_size - start_batch_size
        if diff < 0 or diff % batch_size_increment != 0:
            raise ValueError(
                "expected global batch size to be reachable from "
                "start_batch_size by increments of batch_size_increment"
            )
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size
        )
        self.rampup_samples_per_increment = (
            ramup_samples / (diff / batch_size_increment) if diff > 0 else 0
        )
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool):
        if consumed_samples > self.ramup_samples or (
            self.rampup_samples_per_increment == 0
        ):
            current = self.global_batch_size
        else:
            steps = int(
                consumed_samples // self.rampup_samples_per_increment
            )
            current = min(
                self.global_batch_size,
                self.start_batch_size + steps * self.batch_size_increment,
            )
        if consistency_check and (
            current % self.micro_batch_times_data_parallel_size != 0
        ):
            raise ValueError(
                f"current global batch size ({current}) is not divisible "
                "by micro-batch-size * data-parallel-size"
            )
        if current < self.micro_batch_times_data_parallel_size:
            raise ValueError(
                f"current global batch size ({current}) is smaller than "
                "micro-batch-size * data-parallel-size "
                f"({self.micro_batch_times_data_parallel_size}); lower the "
                "micro batch size or raise start_batch_size"
            )
        self.num_micro_batches = (
            current // self.micro_batch_times_data_parallel_size
        )
        self.current_global_batch_size = current


def build_num_microbatches_calculator(
    rampup_batch_size: Optional[Sequence[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> NumMicroBatchesCalculator:
    """reference microbatches.py:24."""
    if rampup_batch_size is None:
        return ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size
        )
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "rampup_batch_size must be [start, increment, samples]"
        )
    start, inc, samples = (int(v) for v in rampup_batch_size)
    return RampupBatchsizeNumMicroBatches(
        start, inc, samples, global_batch_size, micro_batch_size,
        data_parallel_size,
    )
