"""Global-variables singleton for Megatron-shaped launch scripts.

Reference: ``apex/transformer/testing/global_vars.py`` — args, the
microbatch calculator, tensorboard writer, ADLR AutoResume, and timers
behind ``get_*`` accessors with initialize-once semantics.
"""

from __future__ import annotations


__all__ = [
    "get_args",
    "get_num_microbatches",
    "get_current_global_batch_size",
    "update_num_microbatches",
    "get_tensorboard_writer",
    "get_adlr_autoresume",
    "get_timers",
    "set_global_variables",
    "destroy_global_vars",
]

_GLOBAL_ARGS = None
_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
_GLOBAL_TENSORBOARD_WRITER = None
_GLOBAL_ADLR_AUTORESUME = None
_GLOBAL_TIMERS = None


def _ensure(var, name):
    assert var is not None, f"{name} is not initialized."
    return var


def _ensure_not(var, name):
    assert var is None, f"{name} is already initialized."


def get_args():
    return _ensure(_GLOBAL_ARGS, "args")


def get_num_microbatches() -> int:
    return _ensure(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR,
        "num microbatches calculator").get()


def get_current_global_batch_size() -> int:
    return _ensure(
        _GLOBAL_NUM_MICROBATCHES_CALCULATOR,
        "num microbatches calculator").get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, *,
                            consistency_check: bool = True) -> None:
    _ensure(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
            "num microbatches calculator").update(
        consumed_samples, consistency_check)


def get_tensorboard_writer():
    """May be None (only set when --tensorboard-dir is given and
    tensorboard is importable) — same contract as the reference."""
    return _GLOBAL_TENSORBOARD_WRITER


def get_adlr_autoresume():
    return _GLOBAL_ADLR_AUTORESUME


def get_timers():
    return _ensure(_GLOBAL_TIMERS, "timers")


def set_global_variables(extra_args_provider=None, args_defaults=None,
                         ignore_unknown_args=False, args=None):
    """Parse args and initialize every global (reference
    global_vars.py:87 ``set_global_variables``)."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    global _GLOBAL_TENSORBOARD_WRITER, _GLOBAL_ADLR_AUTORESUME
    global _GLOBAL_TIMERS

    from apex_tpu.transformer.microbatches import (
        build_num_microbatches_calculator,
    )
    from apex_tpu.transformer.pipeline_parallel._timers import Timers
    from apex_tpu.utils.checkpoint import AutoResume

    from .arguments import parse_args

    _ensure_not(_GLOBAL_ARGS, "args")
    a = parse_args(extra_args_provider, args_defaults or {},
                   ignore_unknown_args, args)
    _GLOBAL_ARGS = a

    dp = a.data_parallel_size or 1
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rampup_batch_size=a.rampup_batch_size,
        global_batch_size=a.global_batch_size,
        micro_batch_size=a.micro_batch_size,
        data_parallel_size=dp,
    )

    if a.tensorboard_dir:
        try:
            from torch.utils.tensorboard import SummaryWriter

            _GLOBAL_TENSORBOARD_WRITER = SummaryWriter(
                log_dir=a.tensorboard_dir)
        except ImportError:
            _GLOBAL_TENSORBOARD_WRITER = None

    if a.adlr_autoresume:
        _GLOBAL_ADLR_AUTORESUME = AutoResume().init()

    _GLOBAL_TIMERS = Timers()
    return a


def destroy_global_vars():
    """Reset (TPU addition, for tests — the reference leaks globals)."""
    global _GLOBAL_ARGS, _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    global _GLOBAL_TENSORBOARD_WRITER, _GLOBAL_ADLR_AUTORESUME
    global _GLOBAL_TIMERS
    _GLOBAL_ARGS = None
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None
    _GLOBAL_TENSORBOARD_WRITER = None
    _GLOBAL_ADLR_AUTORESUME = None
    _GLOBAL_TIMERS = None
