"""Megatron-style trainer plumbing for the standalone test models.

Reference: ``apex/transformer/testing`` — the 971-LoC Megatron argparse
(arguments.py), the global-vars singleton (global_vars.py), and the
standalone GPT/BERT models.  The models live in ``apex_tpu.models``
(transformer_lm / gpt / bert); this package supplies the argparse →
``TransformerConfig`` bridge and the global-vars surface so
Megatron-shaped launch scripts port directly.
"""

from .arguments import parse_args  # noqa: F401
from .global_vars import (  # noqa: F401
    get_args,
    get_adlr_autoresume,
    get_num_microbatches,
    get_timers,
    set_global_variables,
)
