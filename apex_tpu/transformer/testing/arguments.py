"""Megatron-style argparse → TransformerConfig + parallel topology.

Reference: ``apex/transformer/testing/arguments.py`` (971 LoC).  The TPU
port keeps the flag names the reference's launch scripts use (network
size, regularization, training, mixed precision, parallelism groups) and
adds ``to_transformer_config`` to materialize ``apex_tpu``'s config
object.  Flags whose machinery has no TPU analog (NCCL/UCC transport,
CUDA graphs, CPU offload) are accepted-and-ignored with a warning so
ported scripts keep running.
"""

from __future__ import annotations

import argparse
import warnings

import jax.numpy as jnp

__all__ = ["parse_args", "to_transformer_config", "core_parser"]

_IGNORED = {
    "cpu_offload", "use_cpu_initialization", "empty_unused_memory_level",
}


def core_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        description="apex_tpu Megatron-style arguments",
        allow_abbrev=False)

    g = parser.add_argument_group(title="network size")
    g.add_argument("--num-layers", type=int, default=2)
    g.add_argument("--hidden-size", type=int, default=64)
    g.add_argument("--ffn-hidden-size", type=int, default=None)
    g.add_argument("--num-attention-heads", type=int, default=4)
    g.add_argument("--kv-channels", type=int, default=None)
    g.add_argument("--max-position-embeddings", type=int, default=128)
    g.add_argument("--make-vocab-size-divisible-by", type=int, default=128)
    g.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    g.add_argument("--vocab-size", type=int, default=8192)

    g = parser.add_argument_group(title="regularization")
    g.add_argument("--attention-dropout", type=float, default=0.1)
    g.add_argument("--hidden-dropout", type=float, default=0.1)
    g.add_argument("--weight-decay", type=float, default=0.01)
    g.add_argument("--clip-grad", type=float, default=1.0)
    g.add_argument("--adam-beta1", type=float, default=0.9)
    g.add_argument("--adam-beta2", type=float, default=0.999)
    g.add_argument("--adam-eps", type=float, default=1e-8)

    g = parser.add_argument_group(title="training")
    g.add_argument("--micro-batch-size", type=int, default=2)
    g.add_argument("--global-batch-size", type=int, default=None)
    g.add_argument("--rampup-batch-size", nargs="*", default=None)
    g.add_argument("--train-iters", type=int, default=10)
    g.add_argument("--lr", type=float, default=1e-4)
    g.add_argument("--seed", type=int, default=1234)
    g.add_argument("--cpu-offload", action="store_true", default=False)
    g.add_argument("--use-cpu-initialization", action="store_true",
                   default=False)

    g = parser.add_argument_group(title="mixed precision")
    g.add_argument("--fp16", action="store_true")
    g.add_argument("--bf16", action="store_true")
    g.add_argument("--loss-scale", type=float, default=None)
    g.add_argument("--initial-loss-scale", type=float, default=2 ** 32)
    g.add_argument("--hysteresis", type=int, default=2)

    g = parser.add_argument_group(title="distributed")
    g.add_argument("--tensor-model-parallel-size", type=int, default=1)
    g.add_argument("--pipeline-model-parallel-size", type=int, default=1)
    g.add_argument("--virtual-pipeline-model-parallel-size", type=int,
                   default=None)
    g.add_argument("--sequence-parallel", action="store_true")
    g.add_argument("--distributed-backend", default="xla",
                   choices=["xla", "nccl", "ucc", "gloo"])

    g = parser.add_argument_group(title="checkpointing / autoresume")
    g.add_argument("--save", type=str, default=None)
    g.add_argument("--load", type=str, default=None)
    g.add_argument("--save-interval", type=int, default=None)
    g.add_argument("--adlr-autoresume", action="store_true")
    g.add_argument("--adlr-autoresume-interval", type=int, default=1000)

    g = parser.add_argument_group(title="logging")
    g.add_argument("--log-interval", type=int, default=100)
    g.add_argument("--tensorboard-dir", type=str, default=None)
    return parser


def parse_args(extra_args_provider=None, defaults=None,
               ignore_unknown_args=False, args=None):
    """Reference-shaped entry (arguments.py ``parse_args``)."""
    parser = core_parser()
    if extra_args_provider is not None:
        parser = extra_args_provider(parser)
    if ignore_unknown_args:
        parsed, _ = parser.parse_known_args(args)
    else:
        parsed = parser.parse_args(args)
    for key, value in (defaults or {}).items():
        if getattr(parsed, key, None) is None:
            setattr(parsed, key, value)

    for flag in _IGNORED:
        if getattr(parsed, flag, False):
            warnings.warn(
                f"--{flag.replace('_', '-')} has no TPU analog; ignored")
    if parsed.distributed_backend in ("nccl", "ucc", "gloo"):
        warnings.warn(
            f"distributed backend {parsed.distributed_backend!r} maps to "
            "XLA collectives on TPU (SURVEY.md §5); proceeding with xla")

    # world sizing: DP is whatever the mesh leaves after tp × pp
    parsed.data_parallel_size = None  # resolved against the actual mesh
    if parsed.global_batch_size is None:
        parsed.global_batch_size = parsed.micro_batch_size
    # pad vocab like the reference (arguments.py _vocab_size_with_padding)
    mult = parsed.make_vocab_size_divisible_by * \
        parsed.tensor_model_parallel_size
    parsed.padded_vocab_size = ((parsed.vocab_size + mult - 1)
                                // mult) * mult
    return parsed


def to_transformer_config(args):
    """Materialize ``apex_tpu.models.config.TransformerConfig``."""
    from apex_tpu.models.config import TransformerConfig

    compute = jnp.bfloat16 if (args.bf16 or args.fp16) else jnp.float32
    return TransformerConfig(
        num_layers=args.num_layers,
        hidden_size=args.hidden_size,
        num_attention_heads=args.num_attention_heads,
        ffn_hidden_size=args.ffn_hidden_size,
        kv_channels=args.kv_channels,
        vocab_size=args.padded_vocab_size,
        max_position_embeddings=args.max_position_embeddings,
        attention_dropout=args.attention_dropout,
        hidden_dropout=args.hidden_dropout,
        layernorm_epsilon=args.layernorm_epsilon,
        compute_dtype=compute,
    )
