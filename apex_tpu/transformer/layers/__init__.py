from apex_tpu.transformer.layers.layer_norm import FusedLayerNorm  # noqa: F401
