"""Sequence-parallel-aware FusedLayerNorm.

Reference: apex/transformer/layers/layer_norm.py:26-54 — a FusedLayerNorm
subclass that tags its params ``sequence_parallel_enabled`` so the DDP/grad
sync knows these small replicated params need an extra allreduce over the
TP group (their grads come from sequence shards).

Under SPMD-AD the extra allreduce is automatic: norm params are replicated
over 'tp', so their grads from tp-sharded (sequence-parallel) activations
arrive pre-summed over the axis. The flag is kept for API parity and for
the manual shard_map path, where ``grad_sum`` does the same.
"""

from __future__ import annotations

import flax.linen as nn
import jax

from apex_tpu.normalization.fused_layer_norm import (
    FusedLayerNorm as _BaseFusedLayerNorm,
)

__all__ = ["FusedLayerNorm"]


class FusedLayerNorm(_BaseFusedLayerNorm):
    sequence_parallel_enabled: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        if self.sequence_parallel_enabled:
            from jax.sharding import PartitionSpec as P

            from apex_tpu.transformer.tensor_parallel.layers import constrain

            # activations sharded along sequence (dim 0) over 'tp'
            x = constrain(x, P("tp", *([None] * (x.ndim - 1))))
        return super().__call__(x)
