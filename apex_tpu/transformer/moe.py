"""Switch-style Mixture-of-Experts MLP with expert parallelism.

Beyond the reference: ROCm/apex has no MoE runtime (its testing argparse
reserves ``--num-experts``, arguments.py:389, but nothing consumes it).
Expert parallelism is first-class on a TPU mesh, so apex_tpu supplies it
the GSPMD way (the GShard/Switch formulation):

- top-1 (or top-2) routing with a capacity limit per expert;
- dispatch/combine expressed as one-hot einsums, so the entire layer is
  dense linear algebra the partitioner can shard: the expert-major
  tensors carry a ``P('ep', ...)`` constraint and XLA inserts the
  all-to-alls between the token-major and expert-major layouts;
- the standard load-balancing auxiliary loss
  (num_experts · Σ_e fraction_of_tokens(e) · mean_router_prob(e)).

Works on one device (constraints no-op), under ``jit`` over a mesh with
an ``ep`` axis (``parallel.mesh.create_mesh(ep=...)``), and composes
with dp/tp the same way the rest of the model does.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.models.transformer_lm import _constrain

__all__ = ["init_moe_params", "switch_moe_mlp", "MoEOutput"]


class MoEOutput(NamedTuple):
    out: jax.Array          # [b, s, h]
    aux_loss: jax.Array     # scalar load-balance loss
    dropped_fraction: jax.Array  # scalar: tokens over capacity


def init_moe_params(
    rng: jax.Array,
    hidden_size: int,
    ffn_hidden_size: int,
    num_experts: int,
    *,
    init_std: float = 0.02,
    dtype=jnp.float32,
    activation: str = "gelu",
) -> dict:
    """Expert-stacked FFN params [E, ...] + router [h, E].  With
    ``activation='swiglu'`` fc1 carries the concatenated [gate ‖ up]
    columns (trailing dim 2f)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    f1 = 2 * ffn_hidden_size if activation == "swiglu" else ffn_hidden_size

    def nrm(k, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                * init_std).astype(dtype)

    return {
        "router": nrm(k1, (hidden_size, num_experts)),
        "fc1": nrm(k2, (num_experts, hidden_size, f1)),
        "fc1_bias": jnp.zeros((num_experts, f1), dtype),
        "fc2": nrm(k3, (num_experts, ffn_hidden_size, hidden_size)),
        "fc2_bias": jnp.zeros((num_experts, hidden_size), dtype),
    }


def _expert_constrain(x, ep_axis: Optional[str]):
    """Shard the leading expert dim over the ep mesh axis (no-op when no
    mesh / axis — same contract as the model's other constraints)."""
    if ep_axis is None:
        return x
    return _constrain(x, P(ep_axis, *([None] * (x.ndim - 1))))


def switch_moe_mlp(
    params: dict,
    x: jax.Array,
    *,
    capacity_factor: float = 1.25,
    top_k: int = 1,
    ep_axis: Optional[str] = "ep",
    router_noise_rng: Optional[jax.Array] = None,
    activation: str = "gelu",
) -> MoEOutput:
    """Token-choice top-k MoE FFN over ``x`` [b, s, h].

    Static shapes throughout: each expert processes a fixed capacity of
    ``ceil(top_k * s * capacity_factor / E)`` token slots per batch row;
    tokens over capacity fall through with a zero update (the Switch
    drop-token rule) and are reported in ``dropped_fraction``.

    ``activation='swiglu'`` expects ``fc1``/``fc1_bias`` with a doubled
    trailing dim ``2f`` ([gate ‖ up] concatenated) and applies the fused
    bias-SwiGLU epilogue (ops/swiglu.py) inside each expert.
    """
    b, s, h = x.shape
    E = params["router"].shape[-1]
    cap = max(1, math.ceil(top_k * s * capacity_factor / E))

    logits = (x.astype(jnp.float32)
              @ params["router"].astype(jnp.float32))  # [b, s, E]
    if router_noise_rng is not None:
        logits = logits + jax.random.uniform(
            router_noise_rng, logits.shape, jnp.float32, -1e-2, 1e-2)
    probs = jax.nn.softmax(logits, axis=-1)

    combine = jnp.zeros((b, s, E, cap), jnp.float32)
    remaining = probs
    position_in_expert = jnp.zeros((b, E), jnp.int32)
    dropped = jnp.zeros((), jnp.float32)
    for _ in range(top_k):
        choice = jnp.argmax(remaining, axis=-1)           # [b, s]
        gate = jnp.take_along_axis(
            remaining, choice[..., None], axis=-1)[..., 0]  # [b, s]
        onehot = jax.nn.one_hot(choice, E)                 # [b, s, E]
        # position of each token within its chosen expert's queue
        pos = (jnp.cumsum(onehot, axis=1) - 1.0)           # [b, s, E]
        pos_tok = jnp.sum(pos * onehot, axis=-1)           # [b, s]
        pos_tok = pos_tok + jnp.take_along_axis(
            position_in_expert.astype(jnp.float32),
            choice, axis=-1)
        keep = pos_tok < cap
        dropped = dropped + jnp.sum(~keep) / (b * s * top_k)
        slot = jax.nn.one_hot(
            jnp.where(keep, pos_tok, cap).astype(jnp.int32),
            cap)                                           # [b, s, cap]
        combine = combine + (gate * keep)[..., None, None] \
            * onehot[..., None] * slot[:, :, None, :]
        position_in_expert = position_in_expert + jnp.sum(
            (onehot * keep[..., None]).astype(jnp.int32), axis=1)
        remaining = remaining * (1.0 - onehot)

    dispatch = (combine > 0.0).astype(x.dtype)             # [b, s, E, cap]

    # token-major -> expert-major (GSPMD inserts the all-to-all here)
    expert_in = jnp.einsum(
        "bsec,bsh->ebch", dispatch, x)                     # [E, b, cap, h]
    expert_in = _expert_constrain(expert_in, ep_axis)
    fc1 = _expert_constrain(params["fc1"], ep_axis)
    fc2 = _expert_constrain(params["fc2"], ep_axis)
    h1 = jnp.einsum("ebch,ehf->ebcf", expert_in, fc1.astype(x.dtype))
    bias1 = _expert_constrain(params["fc1_bias"], ep_axis)
    if activation == "swiglu":
        from apex_tpu.ops.swiglu import fused_bias_swiglu

        # vmap over experts so each expert's [2f] bias rides the op's
        # own fp32 bias path (same precision contract as the dense FFN)
        h1 = jax.vmap(fused_bias_swiglu)(h1, bias1)
    else:
        h1 = h1 + bias1[:, None, None, :].astype(x.dtype)
        h1 = jax.nn.gelu(h1.astype(jnp.float32),
                         approximate=activation == "gelu_tanh"
                         ).astype(x.dtype)
    h2 = jnp.einsum("ebcf,efh->ebch", h1, fc2.astype(x.dtype))
    h2 = h2 + _expert_constrain(params["fc2_bias"], ep_axis)[
        :, None, None, :].astype(x.dtype)
    h2 = _expert_constrain(h2, ep_axis)

    # expert-major -> token-major, weighted by the router gates
    out = jnp.einsum(
        "bsec,ebch->bsh", combine.astype(x.dtype), h2)     # [b, s, h]

    # load-balance aux loss (Switch eq. 4): E * Σ_e f_e * P_e
    token_frac = jnp.mean(
        jax.nn.one_hot(jnp.argmax(probs, -1), E), axis=(0, 1))
    prob_frac = jnp.mean(probs, axis=(0, 1))
    aux = E * jnp.sum(token_frac * prob_frac)

    return MoEOutput(out=out.astype(x.dtype),
                     aux_loss=aux,
                     dropped_fraction=dropped / 1.0)
