"""Mixture-of-Experts MLP with capacity-limited and capacity-free routing.

Beyond the reference: ROCm/apex has no MoE runtime (its testing argparse
reserves ``--num-experts``, arguments.py:389, but nothing consumes it).
Expert parallelism is first-class on a TPU mesh, so apex_tpu supplies it
two ways, selected by ``routing=``:

- ``"capacity"`` — the GShard/Switch formulation: top-k routing with a
  static per-expert capacity, dispatch/combine as one-hot einsums the
  GSPMD partitioner shards (expert-major tensors carry ``P('ep', ...)``
  and XLA inserts the all-to-alls).  Over-capacity tokens drop (reported
  in ``dropped_fraction``) and every expert pads to ``cap`` slots.
- ``"ragged"`` — capacity-free: tokens are *sorted by expert* (argsort of
  the assignment, segment boundaries from a bincount) and the expert FFNs
  run over ragged ``[tokens, h]`` segments via the grouped matmul
  (``ops/grouped_matmul.py``); an inverse-permutation scatter weighted by
  the gates combines.  No token is ever dropped
  (``dropped_fraction == 0`` by construction) and no pad-to-capacity
  slots are computed.

On a mesh with an ``ep`` axis the ragged path runs expert parallelism
*explicitly* inside a ``jax.shard_map`` island instead of leaving the
all-to-alls to the partitioner:

- dispatch/combine use the counted ``all_to_all`` wrappers
  (``utils/collectives.py``) with wire compression through
  ``comm/quantize`` — ``moe_comm="fp32"|"bf16"|"int8"`` mirrors the
  ``grad_comm=`` surface, per-block fp32 scales ride the header exactly
  like the PR-2 gradient buckets (EQuARX, arXiv:2506.17615);
- under ``overlap_comm`` (the ``ops/collective_matmul`` tri-state /
  ``overlap_scope``) dispatch becomes a ``ppermute`` ring
  (``_ring_visit`` shape) and combine a rotating-accumulator ring
  (``_ring_scatter_sum``) whose per-hop ``part`` runs the local experts'
  grouped FFN for the chunk the traveling accumulator is destined for —
  expert compute overlaps the ring transfers, and the backward is
  hop-wise too (ppermute transposes to the reversed ring; the compressed
  gather carries a straight-through custom VJP whose cotangent rides a
  reduce-scatter ring).

Trace-time telemetry (the PR-1 registry; zero-overhead when
unconfigured): ``moe.dispatch_bytes`` / ``moe.dispatch_raw_bytes`` (wire
vs uncompressed fp32 payload), ``moe.ring_calls`` / ``moe.ring_hops``
(``hops == (ep−1) × calls`` by construction), and the
``moe.dropped_fraction`` gauge (pinned 0.0 on the ragged path).  The
data-dependent per-expert assignment counts come back in
``MoEOutput.expert_load`` for host-side gauges (bench ``--moe``).
The dispatch accounting is structurally audited: the ``static_audit``
dryrun phase traces the EP island and asserts its jaxpr's
``all_to_all`` census equals the counted-wrapper deltas
(``analysis/jaxpr_audit.py`` — an exchange emitted around the counted
wrappers fails CI as accounting drift).

Works on one device (constraints no-op), under ``jit`` over a mesh with
an ``ep`` axis (``parallel.mesh.create_mesh(ep=...)``), and composes
with dp/tp the same way the rest of the model does.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.comm.quantize import (
    WIRE_DTYPES,
    dequantize_blocks,
    quantize_blocks,
)
from apex_tpu.models.transformer_lm import _constrain
from apex_tpu.observability import metrics as _telemetry
# shared with the ring collective-matmuls so byte/axis accounting
# cannot drift between the TP and EP overlap paths
from apex_tpu.ops.collective_matmul import _mesh_axis, _nbytes
from apex_tpu.ops.grouped_matmul import grouped_matmul, group_ids

__all__ = ["init_moe_params", "switch_moe_mlp", "MoEOutput",
           "MOE_ROUTINGS"]

MOE_ROUTINGS = ("capacity", "ragged")


class MoEOutput(NamedTuple):
    out: jax.Array          # [b, s, h]
    aux_loss: jax.Array     # scalar load-balance loss
    dropped_fraction: jax.Array  # scalar: tokens over capacity
    # per-expert router assignment counts [E] (all top-k selections,
    # pre-drop) — the host-side load-imbalance signal (bench --moe sets
    # the moe.expert_load_* gauges from it); None on legacy callers
    expert_load: Optional[jax.Array] = None


def init_moe_params(
    rng: jax.Array,
    hidden_size: int,
    ffn_hidden_size: int,
    num_experts: int,
    *,
    init_std: float = 0.02,
    dtype=jnp.float32,
    activation: str = "gelu",
) -> dict:
    """Expert-stacked FFN params [E, ...] + router [h, E].  With
    ``activation='swiglu'`` fc1 carries the concatenated [gate ‖ up]
    columns (trailing dim 2f)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    f1 = 2 * ffn_hidden_size if activation == "swiglu" else ffn_hidden_size

    def nrm(k, shape):
        return (jax.random.normal(k, shape, jnp.float32)
                * init_std).astype(dtype)

    return {
        "router": nrm(k1, (hidden_size, num_experts)),
        "fc1": nrm(k2, (num_experts, hidden_size, f1)),
        "fc1_bias": jnp.zeros((num_experts, f1), dtype),
        "fc2": nrm(k3, (num_experts, ffn_hidden_size, hidden_size)),
        "fc2_bias": jnp.zeros((num_experts, hidden_size), dtype),
    }


def _expert_constrain(x, ep_axis: Optional[str]):
    """Shard the leading expert dim over the ep mesh axis (no-op when no
    mesh / axis — same contract as the model's other constraints)."""
    if ep_axis is None:
        return x
    return _constrain(x, P(ep_axis, *([None] * (x.ndim - 1))))


# ---------------------------------------------------------------------------
# shared routing / aux-loss pieces
# ---------------------------------------------------------------------------


def _router_probs(router, x2, router_noise_rng):
    logits = x2.astype(jnp.float32) @ router.astype(jnp.float32)
    if router_noise_rng is not None:
        logits = logits + jax.random.uniform(
            router_noise_rng, logits.shape, jnp.float32, -1e-2, 1e-2)
    return jax.nn.softmax(logits, axis=-1)


def _topk_routing(probs, top_k):
    """Iterative-argmax top-k (the Switch selection rule, ties and all):
    ``(choice [..., k] int32, gates [..., k] fp32)``."""
    e_n = probs.shape[-1]
    remaining = probs
    choices, gates = [], []
    for _ in range(top_k):
        c = jnp.argmax(remaining, axis=-1)
        g = jnp.take_along_axis(remaining, c[..., None], axis=-1)[..., 0]
        choices.append(c.astype(jnp.int32))
        gates.append(g)
        remaining = remaining * (1.0 - jax.nn.one_hot(c, e_n))
    return jnp.stack(choices, axis=-1), jnp.stack(gates, axis=-1)


def _aux_loss(probs_mean, sel_counts, n_assignments):
    """Switch eq. 4 generalized to top-k: ``E · Σ_e f_e · P_e`` where
    ``f_e`` counts ALL k selections (not just the argmax — with top_k=2
    the runner-up expert's traffic must be visible to the balance
    term) normalized by the total assignment count."""
    e_n = probs_mean.shape[-1]
    token_frac = sel_counts.astype(jnp.float32) / n_assignments
    return e_n * jnp.sum(token_frac * probs_mean)


# ---------------------------------------------------------------------------
# telemetry (trace-time; module-level helpers fast-path when disabled)
# ---------------------------------------------------------------------------


def _note_dispatch(wire, scales, raw_elements: int) -> None:
    """Wire vs raw bytes THIS rank puts on the interconnect per emitted
    dispatch/combine exchange (trace-time accounting, the
    ``collectives.compressed.*`` discipline)."""
    n = _nbytes(wire) + (_nbytes(scales) if scales is not None else 0)
    _telemetry.counter("moe.dispatch_bytes").inc(n)
    _telemetry.counter("moe.dispatch_raw_bytes").inc(4 * int(raw_elements))


def _note_moe_ring(n: int, rings: int = 1) -> None:
    """``moe.ring_hops == (ep − 1) × moe.ring_calls`` by construction —
    the invariant the overlap tests pin."""
    _telemetry.counter("moe.ring_calls").inc(rings)
    _telemetry.counter("moe.ring_hops").inc((n - 1) * rings)


def _note_dropped(value: float) -> None:
    _telemetry.gauge("moe.dropped_fraction").set(float(value))


def _wire_block(h: int, block: int) -> int:
    """Per-row scale-block size: ``block`` when it tiles ``h`` exactly,
    else one block per row — ``quantize_blocks`` zero-pads to a block
    multiple, and padding a 64-wide row to 256 would *quadruple* the
    int8 wire instead of shrinking it."""
    return block if h % block == 0 else h


# ---------------------------------------------------------------------------
# grouped expert FFN over a sorted ragged layout
# ---------------------------------------------------------------------------


def _expert_matmul(xs, w, offsets, dtype, backend):
    """One expert-slab matmul: a float slab runs the historical
    :func:`grouped_matmul` path byte-identically; a pre-quantized slab
    (``{"wire", "scale"}`` from ``ops/grouped_matmul.
    quantize_group_weights`` via ``models/quantized.quantize_params``,
    ISSUE 14) runs the in-kernel dequantizing grouped matmul so the
    HBM expert-weight read is the int8 bytes."""
    from apex_tpu.ops.dense import is_quantized

    if is_quantized(w):
        from apex_tpu.ops.grouped_matmul import grouped_matmul_quantized

        # the caller's backend pin carries through (a parity run that
        # pinned the reference must not get the kernel's summation
        # order); None keeps the APEX_TPU_QUANT_MATMUL/auto routing
        return grouped_matmul_quantized(
            xs.astype(dtype), w["wire"], w["scale"], offsets,
            backend=backend)
    return grouped_matmul(xs.astype(dtype), w.astype(dtype), offsets,
                          backend=backend)


def _slab_groups(w) -> int:
    from apex_tpu.ops.dense import is_quantized

    if is_quantized(w):
        return int(w["wire"].shape[0])
    return int(w.shape[0])


def _grouped_ffn(xs, offsets, fc1, b1, fc2, b2, activation, dtype,
                 backend=None):
    """Expert FFN over ``xs`` [N, h] sorted by expert with segment
    ``offsets`` [G+1] (window allowed: rows outside stay exactly zero).
    Per-row biases gather through a zero-padded table so sentinel rows
    (outside the window / past the valid count) contribute nothing.
    ``fc1``/``fc2`` may be weight-only quantized slabs (ISSUE 14) —
    see :func:`_expert_matmul`."""
    g_n = _slab_groups(fc1)
    gid = group_ids(offsets, xs.shape[0], g_n)
    b1e = jnp.concatenate(
        [b1, jnp.zeros((1,) + b1.shape[1:], b1.dtype)])[gid]
    b2e = jnp.concatenate(
        [b2, jnp.zeros((1,) + b2.shape[1:], b2.dtype)])[gid]
    h1 = _expert_matmul(xs, fc1, offsets, dtype, backend)
    if activation == "swiglu":
        from apex_tpu.ops.swiglu import fused_bias_swiglu

        # the op's own fp32 bias path — the same precision contract as
        # the capacity path's per-expert vmapped application
        h1 = fused_bias_swiglu(h1, b1e)
    else:
        h1 = h1 + b1e.astype(dtype)
        h1 = jax.nn.gelu(h1.astype(jnp.float32),
                         approximate=activation == "gelu_tanh"
                         ).astype(dtype)
    h2 = _expert_matmul(h1, fc2, offsets, dtype, backend)
    return h2 + b2e.astype(dtype)


def _sorted_assignment(choice, gates, e_n):
    """Flatten [T, k] assignments into the sorted-by-expert slot layout:
    ``(order [N], counts [E], token_of_sorted [N], gates_sorted [N],
    expert_sorted [N])`` with ``N = T·k``."""
    k = choice.shape[-1]
    fe = choice.reshape(-1)
    order = jnp.argsort(fe)                       # stable
    counts = jnp.bincount(fe, length=e_n).astype(jnp.int32)
    return (order, counts, order // k, gates.reshape(-1)[order],
            fe[order])


# ---------------------------------------------------------------------------
# compressed wire exchanges (straight-through VJPs: the backward wire is
# the same collective on the quantized cotangent)
# ---------------------------------------------------------------------------


def _caa_impl(x, axis_name, wire_dtype, block):
    from apex_tpu.utils.collectives import all_to_all

    xf = x.astype(jnp.float32)
    if wire_dtype == "fp32":
        _note_dispatch(xf, None, xf.size)
        return all_to_all(xf, axis_name, 0, 0, tiled=True)
    wire, scales = quantize_blocks(xf, wire_dtype, block)
    _note_dispatch(wire, scales, xf.size)
    rw = all_to_all(wire, axis_name, 0, 0, tiled=True)
    rs = (all_to_all(scales, axis_name, 0, 0, tiled=True)
          if scales is not None else None)
    return dequantize_blocks(rw, rs, block, x.shape[-1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _compressed_all_to_all(x, axis_name, wire_dtype, block):
    """``all_to_all`` over dim 0 with the payload quantized on the wire
    (``comm/quantize`` block scales ride as a separate header exchange).
    Straight-through VJP: the cotangent takes the same compressed
    exchange back (all_to_all is its own transpose)."""
    return _caa_impl(x, axis_name, wire_dtype, block)


def _caa_fwd(x, axis_name, wire_dtype, block):
    return _caa_impl(x, axis_name, wire_dtype, block), None


def _caa_bwd(axis_name, wire_dtype, block, _res, g):
    return (_caa_impl(g, axis_name, wire_dtype, block),)


_compressed_all_to_all.defvjp(_caa_fwd, _caa_bwd)


def _crg_impl(x, axis_name, wire_dtype, block, n):
    from apex_tpu.ops.collective_matmul import ring_all_gather

    xf = x.astype(jnp.float32)
    if wire_dtype == "fp32":
        _note_dispatch(xf, None, xf.size)
        _note_moe_ring(n)
        return ring_all_gather(xf, axis_name, dim=0).reshape(
            (n,) + x.shape)
    wire, scales = quantize_blocks(xf, wire_dtype, block)
    _note_dispatch(wire, scales, xf.size)
    gw = ring_all_gather(wire, axis_name, dim=0)
    rings = 1
    gs = None
    if scales is not None:
        gs = ring_all_gather(scales, axis_name, dim=0).reshape(
            (n,) + scales.shape)
        rings += 1
    _note_moe_ring(n, rings)
    return dequantize_blocks(
        gw.reshape((n,) + wire.shape), gs, block, x.shape[-1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _compressed_ring_gather(x, axis_name, wire_dtype, block, n):
    """All-gather ``x`` [C, ...] → [n, C, ...] as n−1 ``ppermute`` hops
    (``_ring_visit`` shape) with the payload quantized once at the
    source — every hop forwards the int8 wire + scale header, never the
    fp32 tensor.  Straight-through VJP: the cotangent rides the dual
    reduce-scatter ring (hop-wise backward; fp32 accumulator, since
    partial sums cannot ride int8 without per-hop requantization
    error)."""
    return _crg_impl(x, axis_name, wire_dtype, block, n)


def _crg_fwd(x, axis_name, wire_dtype, block, n):
    return _crg_impl(x, axis_name, wire_dtype, block, n), None


def _crg_bwd(axis_name, wire_dtype, block, n, _res, g):
    from apex_tpu.ops.collective_matmul import ring_reduce_scatter

    _note_moe_ring(n)
    gf = g.astype(jnp.float32)
    # the backward leg is fp32 on the wire (the accumulator cannot ride
    # int8 without per-hop requantization error) — book it so overlap
    # rows account fwd+bwd exchanges like the all_to_all rows do
    _note_dispatch(gf, None, gf.size)
    return (ring_reduce_scatter(
        gf.reshape((-1,) + g.shape[2:]), axis_name, dim=0),)


_compressed_ring_gather.defvjp(_crg_fwd, _crg_bwd)


# ---------------------------------------------------------------------------
# ragged (capacity-free) routing
# ---------------------------------------------------------------------------


def _ragged_local(params, x2, probs, top_k, activation, gmm_backend):
    """Single-shard ragged path: sort-by-expert, grouped FFN, inverse-
    permutation combine.  Also the fallback under GSPMD when the
    explicit island does not apply (the partitioner then gathers the
    expert weights — correct, just not expert-parallel)."""
    e_n = params["router"].shape[-1]
    t_n, h = x2.shape
    choice, gates = _topk_routing(probs, top_k)
    order, counts, tok, gate_s, _ = _sorted_assignment(choice, gates, e_n)
    offsets = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)])
    xs = x2[tok]
    h2 = _grouped_ffn(xs, offsets, params["fc1"], params["fc1_bias"],
                      params["fc2"], params["fc2_bias"], activation,
                      x2.dtype, gmm_backend)
    out = jnp.zeros((t_n, h), jnp.float32).at[tok].add(
        gate_s[:, None] * h2.astype(jnp.float32))
    return out.astype(x2.dtype), counts


def _ep_abstract_mesh():
    from apex_tpu.ops.collective_matmul import _abstract_mesh

    return _abstract_mesh()


def _mesh_axis_size(mesh, axis_name) -> int:
    if mesh is None or axis_name is None:
        return 0
    return _mesh_axis(mesh, axis_name)


def _ragged_ep_island(params, x2, *, mesh, ep_axis, top_k,
                      router_noise_rng, activation, moe_comm, block,
                      overlap, gmm_backend):
    """Explicit expert-parallel ragged MoE: a shard_map island over the
    ``ep`` axis.  Tokens enter sharded over ep (``[T, h]`` per rank),
    experts live sharded (``E/ep`` per rank); each rank routes its own
    tokens, sorts them by global expert, and the dispatch/combine either

    - exchanges per-destination chunks through the counted
      ``all_to_all`` wrappers with the payload compressed per
      ``moe_comm`` (per-rank worst-case chunk size ``T·k`` — capacity-
      free means the wire must fit every token landing on one rank), or
    - (``overlap``) ring-gathers the compressed sorted token sets and
      runs the combine as a ``_ring_scatter_sum`` whose per-hop ``part``
      computes the local experts' grouped FFN for the rank the
      traveling accumulator is destined for — expert compute rides
      *inside* the ring, overlapped with the hops.
    """
    from apex_tpu.ops.collective_matmul import _ring_scatter_sum
    from apex_tpu.utils.collectives import all_gather, all_to_all, \
        match_vma, vma_of

    e_n = params["router"].shape[-1]
    tokens_total, h = x2.shape
    ep = _mesh_axis_size(mesh, ep_axis)
    e_local = e_n // ep
    block = _wire_block(h, block)
    dtype = x2.dtype

    def island(router, fc1, b1, fc2, b2, xt):
        t_n = xt.shape[0]                       # tokens per rank
        rank = jax.lax.axis_index(ep_axis)
        rng = router_noise_rng
        if rng is not None:
            rng = jax.random.fold_in(rng, rank)
        probs = _router_probs(router, xt, rng)
        choice, gates = _topk_routing(probs, top_k)
        n_slots = t_n * top_k
        order, counts, tok, gate_s, fe_s = _sorted_assignment(
            choice, gates, e_n)
        xs = xt[tok]                            # [N, h] sorted by expert
        off_full = jnp.concatenate(
            [jnp.zeros(1, jnp.int32),
             jnp.cumsum(counts, dtype=jnp.int32)])

        # global load / aux: every rank contributes its local counts and
        # prob mass; psum makes both axis-invariant (out_specs P())
        load = jax.lax.psum(counts.astype(jnp.float32), ep_axis)
        probs_mean = jax.lax.psum(
            jnp.sum(probs, axis=0), ep_axis) / tokens_total
        aux = _aux_loss(probs_mean, load, tokens_total * top_k)

        if overlap:
            # ---- ring dispatch: compressed sorted token sets travel
            # the ring; counts ride as the (tiny) header ----
            counts_all = all_gather(counts, ep_axis, axis=0,
                                    tiled=False)            # [ep, E]
            # fp32 into the exchange: the straight-through VJP's
            # cotangent comes back fp32, so the primal must be too
            gathered = _compressed_ring_gather(
                xs.astype(jnp.float32), ep_axis, moe_comm, block,
                ep)                                         # [ep, N, h]

            # ---- combine ring: the rotating accumulator visits every
            # rank; part(d) computes MY experts' grouped FFN over rank
            # d's sorted tokens (their window of the global expert
            # range) the hop the accumulator destined for d is here —
            # compute overlaps transfer, the collective-matmul way ----
            def part(d):
                xd = jnp.take(gathered, d, axis=0)          # [N, h]
                cnt = jnp.take(counts_all, d, axis=0)       # [E]
                offd = jnp.concatenate(
                    [jnp.zeros(1, jnp.int32),
                     jnp.cumsum(cnt, dtype=jnp.int32)])
                window = jax.lax.dynamic_slice(
                    offd, (rank * e_local,), (e_local + 1,))
                return _grouped_ffn(
                    xd.astype(dtype), window, fc1, b1, fc2, b2,
                    activation, dtype, gmm_backend).astype(jnp.float32)

            res_sorted = _ring_scatter_sum(
                ep_axis, ep, (n_slots, h), jnp.float32, part, xs)
            _note_moe_ring(ep)
            # combine ring: the fp32 accumulator chunk is the wire
            # payload (one chunk traveling per rank per trace)
            _note_dispatch(res_sorted, None, res_sorted.size)
        else:
            # ---- counted all_to_all dispatch: per-destination chunks
            # of the sorted layout; the count matrix is the header the
            # receiver rebuilds expert ids from (slots arrive sorted by
            # local expert within each source chunk) ----
            cap = n_slots                       # worst case: all → one
            dest = fe_s // e_local              # [N] destination rank
            doff = off_full[jnp.arange(ep + 1) * e_local]
            within = jnp.arange(n_slots, dtype=jnp.int32) - doff[dest]
            buf = match_vma(jnp.zeros((ep, cap, h), jnp.float32),
                            vma_of(xs))
            buf = buf.at[dest, within].set(xs.astype(jnp.float32))
            cmat = counts.reshape(ep, e_local)
            recv_cmat = all_to_all(cmat, ep_axis, 0, 0, tiled=True)
            recv = _compressed_all_to_all(
                buf, ep_axis, moe_comm, block)  # [ep(src), cap, h]

            # regroup by local expert across sources (stable sort keeps
            # source order within an expert — the return trip relies on
            # positions, not ids)
            rtot = jnp.sum(recv_cmat, axis=1)
            eid = jax.vmap(lambda c: jnp.repeat(
                jnp.arange(e_local, dtype=jnp.int32), c,
                total_repeat_length=cap))(recv_cmat)
            valid = (jnp.arange(cap, dtype=jnp.int32)[None, :]
                     < rtot[:, None])
            keys = jnp.where(valid, eid, e_local).reshape(-1)
            order2 = jnp.argsort(keys)
            xs2 = recv.reshape(ep * cap, h)[order2]
            gcounts = jnp.sum(recv_cmat, axis=0)
            goff = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(gcounts, dtype=jnp.int32)])
            h2 = _grouped_ffn(xs2.astype(dtype), goff, fc1, b1, fc2, b2,
                              activation, dtype, gmm_backend)

            ret = match_vma(jnp.zeros((ep * cap, h), jnp.float32),
                            vma_of(h2))
            ret = ret.at[order2].set(h2.astype(jnp.float32))
            back = _compressed_all_to_all(
                ret.reshape(ep, cap, h), ep_axis, moe_comm, block)
            res_sorted = back[dest, within]     # [N, h]

        outf = match_vma(jnp.zeros((t_n, h), jnp.float32),
                         vma_of(res_sorted))
        outf = outf.at[tok].add(gate_s[:, None] * res_sorted)
        return outf.astype(dtype), aux, load

    rest = tuple(None for _ in range(x2.ndim - 1))
    f = jax.shard_map(
        island, mesh=mesh,
        in_specs=(P(), P(ep_axis), P(ep_axis), P(ep_axis), P(ep_axis),
                  P(ep_axis, *rest)),
        out_specs=(P(ep_axis, *rest), P(), P()))
    return f(params["router"], params["fc1"], params["fc1_bias"],
             params["fc2"], params["fc2_bias"], x2)


# ---------------------------------------------------------------------------
# capacity (Switch drop-token) routing — the original einsum formulation
# ---------------------------------------------------------------------------


def _capacity_moe(params, x, *, capacity_factor, top_k, ep_axis,
                  router_noise_rng, activation):
    b, s, h = x.shape
    e_n = params["router"].shape[-1]
    cap = max(1, math.ceil(top_k * s * capacity_factor / e_n))

    probs = _router_probs(params["router"],
                          x.reshape(b * s, h), router_noise_rng
                          ).reshape(b, s, e_n)

    combine = jnp.zeros((b, s, e_n, cap), jnp.float32)
    remaining = probs
    position_in_expert = jnp.zeros((b, e_n), jnp.int32)
    dropped = jnp.zeros((), jnp.float32)
    sel_counts = jnp.zeros((e_n,), jnp.float32)
    for _ in range(top_k):
        choice = jnp.argmax(remaining, axis=-1)           # [b, s]
        gate = jnp.take_along_axis(
            remaining, choice[..., None], axis=-1)[..., 0]  # [b, s]
        onehot = jax.nn.one_hot(choice, e_n)               # [b, s, E]
        # all k selections feed the balance term (and expert_load) —
        # an argmax-only count would hide the runner-up traffic
        sel_counts = sel_counts + jnp.sum(onehot, axis=(0, 1))
        # position of each token within its chosen expert's queue
        pos = (jnp.cumsum(onehot, axis=1) - 1.0)           # [b, s, E]
        pos_tok = jnp.sum(pos * onehot, axis=-1)           # [b, s]
        pos_tok = pos_tok + jnp.take_along_axis(
            position_in_expert.astype(jnp.float32),
            choice, axis=-1)
        keep = pos_tok < cap
        dropped = dropped + jnp.sum(~keep) / (b * s * top_k)
        slot = jax.nn.one_hot(
            jnp.where(keep, pos_tok, cap).astype(jnp.int32),
            cap)                                           # [b, s, cap]
        combine = combine + (gate * keep)[..., None, None] \
            * onehot[..., None] * slot[:, :, None, :]
        position_in_expert = position_in_expert + jnp.sum(
            (onehot * keep[..., None]).astype(jnp.int32), axis=1)
        remaining = remaining * (1.0 - onehot)

    dispatch = (combine > 0.0).astype(x.dtype)             # [b, s, E, cap]

    # token-major -> expert-major (GSPMD inserts the all-to-all here)
    expert_in = jnp.einsum(
        "bsec,bsh->ebch", dispatch, x)                     # [E, b, cap, h]
    expert_in = _expert_constrain(expert_in, ep_axis)
    fc1 = _expert_constrain(params["fc1"], ep_axis)
    fc2 = _expert_constrain(params["fc2"], ep_axis)
    h1 = jnp.einsum("ebch,ehf->ebcf", expert_in, fc1.astype(x.dtype))
    bias1 = _expert_constrain(params["fc1_bias"], ep_axis)
    if activation == "swiglu":
        from apex_tpu.ops.swiglu import fused_bias_swiglu

        # vmap over experts so each expert's [2f] bias rides the op's
        # own fp32 bias path (same precision contract as the dense FFN)
        h1 = jax.vmap(fused_bias_swiglu)(h1, bias1)
    else:
        h1 = h1 + bias1[:, None, None, :].astype(x.dtype)
        h1 = jax.nn.gelu(h1.astype(jnp.float32),
                         approximate=activation == "gelu_tanh"
                         ).astype(x.dtype)
    h2 = jnp.einsum("ebcf,efh->ebch", h1, fc2.astype(x.dtype))
    h2 = h2 + _expert_constrain(params["fc2_bias"], ep_axis)[
        :, None, None, :].astype(x.dtype)
    h2 = _expert_constrain(h2, ep_axis)

    # expert-major -> token-major, weighted by the router gates
    out = jnp.einsum(
        "bsec,ebch->bsh", combine.astype(x.dtype), h2)     # [b, s, h]

    aux = _aux_loss(jnp.mean(probs, axis=(0, 1)), sel_counts,
                    b * s * top_k)
    return MoEOutput(out=out.astype(x.dtype),
                     aux_loss=aux,
                     dropped_fraction=dropped,
                     expert_load=sel_counts)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def switch_moe_mlp(
    params: dict,
    x: jax.Array,
    *,
    capacity_factor: float = 1.25,
    top_k: int = 1,
    ep_axis: Optional[str] = "ep",
    router_noise_rng: Optional[jax.Array] = None,
    activation: str = "gelu",
    routing: str = "capacity",
    moe_comm: str = "fp32",
    comm_block: int = 256,
    overlap_comm: Optional[bool] = None,
    ep_mesh=None,
    gmm_backend: Optional[str] = None,
) -> MoEOutput:
    """Token-choice top-k MoE FFN over ``x`` [b, s, h].

    ``routing="capacity"`` (default): static shapes throughout — each
    expert processes ``ceil(top_k · s · capacity_factor / E)`` token
    slots per batch row; tokens over capacity fall through with a zero
    update (the Switch drop-token rule) and are reported in
    ``dropped_fraction``.  EP comes from the GSPMD partitioner via the
    ``P(ep_axis, ...)`` constraints on the expert-major einsums.

    ``routing="ragged"``: capacity-free — no token is dropped
    (``dropped_fraction == 0.0`` by construction) and no pad slots are
    computed; expert FFNs run over sorted ragged segments through
    ``ops/grouped_matmul``.  ``capacity_factor`` is ignored.  On a mesh
    with a ``>1``-sized ``ep_axis`` (the ambient abstract mesh, or an
    explicit ``ep_mesh``) and divisible token/expert counts, dispatch
    and combine run *explicitly* in a shard_map island through the
    counted ``all_to_all`` wrappers with the wire compressed per
    ``moe_comm`` (``"fp32"|"bf16"|"int8"``, block scales of
    ``comm_block``); ``overlap_comm`` (tri-state — ``None`` reads the
    ambient ``ops.collective_matmul.overlap_scope``) swaps the
    all-to-alls for ``ppermute`` rings with per-hop expert compute.
    When the island does not apply the ragged math runs unsharded
    (GSPMD then gathers the expert weights — correct, not
    expert-parallel).

    ``activation='swiglu'`` expects ``fc1``/``fc1_bias`` with a doubled
    trailing dim ``2f`` ([gate ‖ up] concatenated) and applies the fused
    bias-SwiGLU epilogue (ops/swiglu.py) inside each expert.
    """
    if routing not in MOE_ROUTINGS:
        raise ValueError(
            f"routing={routing!r}: expected one of {MOE_ROUTINGS}")
    if moe_comm not in WIRE_DTYPES:
        raise ValueError(
            f"moe_comm={moe_comm!r}: expected one of {WIRE_DTYPES}")
    from apex_tpu.ops.dense import is_quantized

    if is_quantized(params.get("fc1")) or is_quantized(params.get("fc2")):
        # weight-only quantized expert slabs (ISSUE 14) run ONLY on the
        # local ragged path: the capacity einsum would need a dense
        # dequantize (no bandwidth win) and the EP island would ship
        # dict leaves through shard_map specs built for arrays
        if routing != "ragged":
            raise ValueError(
                "quantized expert slabs need routing='ragged' (the "
                "capacity einsum path has no int8 form)")
        if ep_mesh is not None or _mesh_axis_size(
                _ep_abstract_mesh(), ep_axis) >= 2:
            raise ValueError(
                "quantized expert slabs are a single-device serving "
                "path; run them outside an expert-parallel mesh")
    if routing == "capacity":
        return _capacity_moe(
            params, x, capacity_factor=capacity_factor, top_k=top_k,
            ep_axis=ep_axis, router_noise_rng=router_noise_rng,
            activation=activation)

    from apex_tpu.ops.collective_matmul import overlap_enabled

    b, s, h = x.shape
    e_n = params["router"].shape[-1]
    x2 = x.reshape(b * s, h)
    _note_dropped(0.0)   # drop-free by construction (asserted in tests)

    mesh = ep_mesh if ep_mesh is not None else _ep_abstract_mesh()
    ep = _mesh_axis_size(mesh, ep_axis)
    if ep >= 2 and (b * s) % ep == 0 and e_n % ep == 0:
        out2, aux, load = _ragged_ep_island(
            params, x2, mesh=mesh, ep_axis=ep_axis, top_k=top_k,
            router_noise_rng=router_noise_rng, activation=activation,
            moe_comm=moe_comm, block=comm_block,
            overlap=overlap_enabled(overlap_comm),
            gmm_backend=gmm_backend)
    else:
        probs = _router_probs(params["router"], x2, router_noise_rng)
        out2, counts = _ragged_local(
            params, x2, probs, top_k, activation, gmm_backend)
        load = counts.astype(jnp.float32)
        aux = _aux_loss(jnp.mean(probs, axis=0), load, b * s * top_k)

    return MoEOutput(out=out2.reshape(b, s, h).astype(x.dtype),
                     aux_loss=aux,
                     dropped_fraction=jnp.zeros((), jnp.float32),
                     expert_load=load)
