"""Parallel RNG state tracking + activation checkpointing.

Reference: apex/transformer/tensor_parallel/random.py —
``CudaRNGStatesTracker`` (:124) forks a named RNG state per region so
dropout differs across TP ranks where it must ('model-parallel-rng') and
matches where it must (default state); ``model_parallel_cuda_manual_seed``
(:204) seeds both; ``CheckpointFunction`` (:237) re-plays RNG states during
activation recompute.

JAX translation: randomness is explicit keys, so the tracker deals in
``jax.random.PRNGKey``s — the model-parallel key folds in the tp rank
(``fold_in(axis_index)``), the default key is shared. Recompute-correctness
is free: ``jax.checkpoint`` replays the same traced key uses. The tracker
exists for API parity and for code that wants named streams.
"""

from __future__ import annotations

import contextlib
from typing import Dict

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TP_AXIS

__all__ = [
    "RNGStatesTracker",
    "get_rng_tracker",
    "model_parallel_seed",
    "checkpoint",
    "CheckpointFunction",
]

_MODEL_PARALLEL_RNG = "model-parallel-rng"


class RNGStatesTracker:
    """Named PRNG-key streams (reference CudaRNGStatesTracker :124)."""

    def __init__(self):
        self.states_: Dict[str, jax.Array] = {}

    def reset(self):
        self.states_.clear()

    def get_states(self):
        return dict(self.states_)

    def set_states(self, states):
        self.states_ = dict(states)

    def add(self, name: str, key):
        if name in self.states_:
            raise ValueError(f"rng state {name!r} already exists")
        self.states_[name] = key

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG):
        """Yield the stream's key and advance it (the mutable-state analog
        of the reference's fork context manager :154)."""
        if name not in self.states_:
            raise KeyError(f"rng state {name!r} was never seeded")
        key = self.states_[name]
        key, sub = jax.random.split(key)
        self.states_[name] = key
        yield sub


_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    """reference get_cuda_rng_tracker (:183)."""
    return _TRACKER


def model_parallel_seed(seed: int, axis: str = TP_AXIS):
    """Seed default + model-parallel streams
    (reference model_parallel_cuda_manual_seed :204).

    Inside a mapped computation the model-parallel key folds in the tp
    rank (2718 offset mirrors the reference's +2718); outside, it folds a
    zero (single shard).
    """
    _TRACKER.reset()
    base = jax.random.PRNGKey(seed)
    try:
        rank = jax.lax.axis_index(axis)
    except NameError:
        rank = jnp.zeros((), jnp.int32)
    _TRACKER.add("default", base)
    _TRACKER.add(
        _MODEL_PARALLEL_RNG, jax.random.fold_in(base, 2718 + rank)
    )
    return _TRACKER


# Activation checkpointing: jax.checkpoint already saves/replays RNG uses
# deterministically, which is the entire hard part of the reference's
# CheckpointFunction (random.py:237-305 — saving CPU+CUDA+tracker states
# around the recompute). Re-exported under the reference name.
checkpoint = jax.checkpoint
CheckpointFunction = jax.checkpoint
