"""The 8 tensor-parallel collective mappings.

Reference: apex/transformer/tensor_parallel/mappings.py:23-292 — autograd
Functions pairing a forward collective with its transpose in backward:

| mapping                                   | fwd             | bwd            |
|-------------------------------------------|-----------------|----------------|
| copy_to_tensor_model_parallel_region      | identity        | all-reduce     |
| reduce_from_tensor_model_parallel_region  | all-reduce      | identity       |
| scatter_to_tensor_model_parallel_region   | split last dim  | all-gather     |
| gather_from_tensor_model_parallel_region  | all-gather last | split          |
| scatter_to_sequence_parallel_region       | split first dim | all-gather     |
| gather_from_sequence_parallel_region      | all-gather first| reduce-scatter*|
| reduce_scatter_to_sequence_parallel_region| reduce-scatter  | all-gather     |
| (copy's sequence-parallel dual is the * case: to_model_parallel_region
|  =False makes the backward a plain split)                                |

Implemented as custom-VJP functions over ``jax.lax`` collectives, usable
inside ``shard_map`` on the 'tp' axis. jax≥0.9 varying-axes typing is kept
consistent: identities that move a value into per-shard compute insert
``pvary``; reductions produce axis-invariant values.

(The GSPMD layer path — apex_tpu.transformer.tensor_parallel.layers — does
not call these; XLA inserts the same collectives from sharding annotations.
These exist for manual shard_map programming and 1:1 reference parity.)
"""

from __future__ import annotations

import functools

import jax

from apex_tpu.transformer.parallel_state import TP_AXIS

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
]


from apex_tpu.utils.collectives import pvary as _pvary  # noqa: E402


def _split_along(x, dim, axis):
    """Local shard of x along ``dim`` for this tp rank
    (reference _split_along_last_dim :40 / _split_along_first_dim :55)."""
    n = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    size = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, rank * size, size, axis=dim)


# ---- copy (f): identity fwd, allreduce bwd  (mappings.py:133) -------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis=TP_AXIS):
    return _pvary(x, axis)


def _copy_fwd(x, axis):
    return _pvary(x, axis), None


def _copy_bwd(axis, _, g):
    return (jax.lax.psum(g, axis),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


# ---- reduce (g): allreduce fwd, identity bwd  (mappings.py:152) -----------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis=TP_AXIS):
    return jax.lax.psum(x, axis)


def _reduce_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _reduce_bwd(axis, _, g):
    return (_pvary(g, axis),)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# ---- scatter/gather along the LAST dim (mappings.py:170,196) --------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis=TP_AXIS):
    return _split_along(_pvary(x, axis), -1, axis)


def _scatter_fwd(x, axis):
    return _split_along(_pvary(x, axis), -1, axis), None


def _scatter_bwd(axis, _, g):
    return (jax.lax.all_gather(g, axis, axis=g.ndim - 1, tiled=True),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis=TP_AXIS):
    return jax.lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)


def _gather_fwd(x, axis):
    return jax.lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True), None


def _gather_bwd(axis, _, g):
    return (_split_along(g, -1, axis),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# ---- sequence-parallel: FIRST dim (mappings.py:55,95,114,223,245) ---------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, axis=TP_AXIS):
    return _split_along(_pvary(x, axis), 0, axis)


def _sp_scatter_fwd(x, axis):
    return _split_along(_pvary(x, axis), 0, axis), None


def _sp_scatter_bwd(axis, _, g):
    return (jax.lax.all_gather(g, axis, axis=0, tiled=True),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_from_sequence_parallel_region(
    x, to_model_parallel: bool = True, axis=TP_AXIS
):
    """fwd: all-gather along dim 0. bwd: reduce-scatter when the gathered
    value feeds tensor-parallel compute (reference
    _GatherFromSequenceParallelRegion :223, to_model_parallel flag), else a
    plain split."""
    return jax.lax.all_gather(x, axis, axis=0, tiled=True)


def _sp_gather_fwd(x, to_model_parallel, axis):
    return jax.lax.all_gather(x, axis, axis=0, tiled=True), None


def _sp_gather_bwd(to_model_parallel, axis, _, g):
    if to_model_parallel:
        return (jax.lax.psum_scatter(g, axis, scatter_dimension=0,
                                     tiled=True),)
    return (_split_along(g, 0, axis),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_scatter_to_sequence_parallel_region(x, axis=TP_AXIS):
    return jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)


def _sp_rs_fwd(x, axis):
    return (
        jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True),
        None,
    )


def _sp_rs_bwd(axis, _, g):
    return (_pvary(jax.lax.all_gather(g, axis, axis=0, tiled=True), axis),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)
