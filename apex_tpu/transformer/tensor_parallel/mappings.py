"""The 8 tensor-parallel collective mappings.

Reference: apex/transformer/tensor_parallel/mappings.py:23-292 — autograd
Functions pairing a forward collective with its transpose in backward:

| mapping                                   | fwd             | bwd            |
|-------------------------------------------|-----------------|----------------|
| copy_to_tensor_model_parallel_region      | identity        | all-reduce     |
| reduce_from_tensor_model_parallel_region  | all-reduce      | identity       |
| scatter_to_tensor_model_parallel_region   | split last dim  | all-gather     |
| gather_from_tensor_model_parallel_region  | all-gather last | split          |
| scatter_to_sequence_parallel_region       | split first dim | all-gather     |
| gather_from_sequence_parallel_region      | all-gather first| reduce-scatter*|
| reduce_scatter_to_sequence_parallel_region| reduce-scatter  | all-gather     |
| (copy's sequence-parallel dual is the * case: to_model_parallel_region
|  =False makes the backward a plain split)                                |

Implemented as custom-VJP functions over ``jax.lax`` collectives, usable
inside ``shard_map`` on the 'tp' axis. jax≥0.9 varying-axes typing is kept
consistent: identities that move a value into per-shard compute insert
``pvary``; reductions produce axis-invariant values.

The two sequence-parallel mappings with a collective on *both* sides of
the table take an ``overlap_comm`` tri-state (explicit bool, or ``None``
to inherit ``ops.collective_matmul.overlap_scope``): when enabled, the
monolithic all-gather / reduce-scatter is decomposed into n−1
``ppermute`` ring hops (``ring_all_gather`` / ``ring_reduce_scatter``)
in the forward AND the backward, so the XLA scheduler can overlap each
hop with neighboring compute — and a mapping whose forward rides the
ring never falls back to a monolithic collective under grad.

(The GSPMD layer path — apex_tpu.transformer.tensor_parallel.layers — does
not call these; XLA inserts the same collectives from sharding annotations.
These exist for manual shard_map programming and 1:1 reference parity.)
"""

from __future__ import annotations

import functools

import jax

from apex_tpu.transformer.parallel_state import TP_AXIS

__all__ = [
    "copy_to_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "scatter_to_sequence_parallel_region",
    "gather_from_sequence_parallel_region",
    "reduce_scatter_to_sequence_parallel_region",
]


from apex_tpu.utils.collectives import pvary as _pvary  # noqa: E402


def _split_along(x, dim, axis):
    """Local shard of x along ``dim`` for this tp rank
    (reference _split_along_last_dim :40 / _split_along_first_dim :55)."""
    n = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    size = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, rank * size, size, axis=dim)


# ---- copy (f): identity fwd, allreduce bwd  (mappings.py:133) -------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tensor_model_parallel_region(x, axis=TP_AXIS):
    return _pvary(x, axis)


def _copy_fwd(x, axis):
    return _pvary(x, axis), None


def _copy_bwd(axis, _, g):
    return (jax.lax.psum(g, axis),)


copy_to_tensor_model_parallel_region.defvjp(_copy_fwd, _copy_bwd)


# ---- reduce (g): allreduce fwd, identity bwd  (mappings.py:152) -----------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tensor_model_parallel_region(x, axis=TP_AXIS):
    return jax.lax.psum(x, axis)


def _reduce_fwd(x, axis):
    return jax.lax.psum(x, axis), None


def _reduce_bwd(axis, _, g):
    return (_pvary(g, axis),)


reduce_from_tensor_model_parallel_region.defvjp(_reduce_fwd, _reduce_bwd)


# ---- scatter/gather along the LAST dim (mappings.py:170,196) --------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_tensor_model_parallel_region(x, axis=TP_AXIS):
    return _split_along(_pvary(x, axis), -1, axis)


def _scatter_fwd(x, axis):
    return _split_along(_pvary(x, axis), -1, axis), None


def _scatter_bwd(axis, _, g):
    return (jax.lax.all_gather(g, axis, axis=g.ndim - 1, tiled=True),)


scatter_to_tensor_model_parallel_region.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def gather_from_tensor_model_parallel_region(x, axis=TP_AXIS):
    return jax.lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)


def _gather_fwd(x, axis):
    return jax.lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True), None


def _gather_bwd(axis, _, g):
    return (_split_along(g, -1, axis),)


gather_from_tensor_model_parallel_region.defvjp(_gather_fwd, _gather_bwd)


# ---- sequence-parallel: FIRST dim (mappings.py:55,95,114,223,245) ---------


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scatter_to_sequence_parallel_region(x, axis=TP_AXIS):
    return _split_along(_pvary(x, axis), 0, axis)


def _sp_scatter_fwd(x, axis):
    return _split_along(_pvary(x, axis), 0, axis), None


def _sp_scatter_bwd(axis, _, g):
    return (jax.lax.all_gather(g, axis, axis=0, tiled=True),)


scatter_to_sequence_parallel_region.defvjp(_sp_scatter_fwd, _sp_scatter_bwd)


def _seq_all_gather(x, axis, overlap_comm):
    """Dim-0 all-gather: monolithic (counted) or the n−1-hop ring form
    under ``overlap_comm`` (ops.collective_matmul.ring_all_gather)."""
    from apex_tpu.ops import collective_matmul as _cm

    if _cm.overlap_enabled(overlap_comm):
        return _cm.ring_all_gather(x, axis, dim=0)
    from apex_tpu.utils.collectives import all_gather

    return all_gather(x, axis, axis=0, tiled=True)


def _seq_reduce_scatter(x, axis, overlap_comm):
    """Dim-0 sum-scatter: monolithic (counted) or the rotating-
    accumulator ring form under ``overlap_comm``."""
    from apex_tpu.ops import collective_matmul as _cm

    if _cm.overlap_enabled(overlap_comm):
        return _cm.ring_reduce_scatter(x, axis, dim=0)
    from apex_tpu.utils.collectives import psum_scatter

    return psum_scatter(x, axis, scatter_dimension=0, tiled=True)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def gather_from_sequence_parallel_region(
    x, to_model_parallel: bool = True, axis=TP_AXIS, overlap_comm=None
):
    """fwd: all-gather along dim 0. bwd: reduce-scatter when the gathered
    value feeds tensor-parallel compute (reference
    _GatherFromSequenceParallelRegion :223, to_model_parallel flag), else a
    plain split.  ``overlap_comm`` (tri-state; ``None`` inherits
    ``overlap_scope``) rides both directions on the ppermute ring."""
    return _seq_all_gather(x, axis, overlap_comm)


def _sp_gather_fwd(x, to_model_parallel, axis, overlap_comm):
    return _seq_all_gather(x, axis, overlap_comm), None


def _sp_gather_bwd(to_model_parallel, axis, overlap_comm, _, g):
    if to_model_parallel:
        return (_seq_reduce_scatter(g, axis, overlap_comm),)
    return (_split_along(g, 0, axis),)


gather_from_sequence_parallel_region.defvjp(_sp_gather_fwd, _sp_gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reduce_scatter_to_sequence_parallel_region(x, axis=TP_AXIS,
                                               overlap_comm=None):
    """fwd: sum-scatter along dim 0; bwd: all-gather.  ``overlap_comm``
    (tri-state) decomposes both into ppermute ring hops."""
    return _seq_reduce_scatter(x, axis, overlap_comm)


def _sp_rs_fwd(x, axis, overlap_comm):
    return _seq_reduce_scatter(x, axis, overlap_comm), None


def _sp_rs_bwd(axis, overlap_comm, _, g):
    return (_pvary(_seq_all_gather(g, axis, overlap_comm), axis),)


reduce_scatter_to_sequence_parallel_region.defvjp(_sp_rs_fwd, _sp_rs_bwd)
