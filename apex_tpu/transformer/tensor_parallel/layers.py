"""Tensor-parallel layers — GSPMD sharding-annotated flax modules.

Reference: apex/transformer/tensor_parallel/layers.py —
``VocabParallelEmbedding`` (:167, masked lookup + allreduce),
``ColumnParallelLinear`` (:429), ``RowParallelLinear`` (:613), plus
``LinearWithGradAccumulationAndAsyncCommunication`` (:272) which hand-
overlaps the grad allreduce with the wgrad GEMM.

TPU-native translation: the layer *annotates* — parameters carry a
``PartitionSpec`` via ``nn.with_partitioning`` and activations get
``with_sharding_constraint`` — and XLA's SPMD partitioner inserts the exact
collectives the reference issues manually (allreduce after row-parallel
matmul, all-gather for sequence-parallel inputs, …) plus the async overlap
the reference hand-codes (latency-hiding scheduler). Shardings:

- VocabParallelEmbedding: table P('tp', None) — vocab-sharded rows.
- ColumnParallelLinear: kernel P(None, 'tp'), bias P('tp'); output
  tp-sharded on the last dim unless ``gather_output``.
- RowParallelLinear: kernel P('tp', None); input tp-sharded on the last
  dim; output summed (replicated) or reduce-scattered to sequence shards
  when ``sequence_parallel_enabled``.

The manual shard_map path uses the mappings module directly; these modules
are the recommended (GSPMD) path.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


__all__ = [
    "VocabParallelEmbedding",
    "ColumnParallelLinear",
    "RowParallelLinear",
    "constrain",
]


def constrain(x, spec: P):
    """Best-effort ``with_sharding_constraint``: a no-op when no mesh is
    active (single-device tests) so modules stay usable everywhere."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except Exception:
        return x


def _maybe_partition(init_fn, spec: P, use_partitioning: bool):
    if use_partitioning:
        return nn.with_partitioning(init_fn, tuple(spec))
    return init_fn


class VocabParallelEmbedding(nn.Module):
    """Embedding with the vocab dimension sharded over 'tp'
    (reference layers.py:167)."""

    num_embeddings: int
    embedding_dim: int
    init_method: Callable = nn.initializers.normal(stddev=0.02)
    params_dtype: jnp.dtype = jnp.float32
    use_partitioning: bool = True

    @nn.compact
    def __call__(self, input_ids: jax.Array) -> jax.Array:
        table = self.param(
            "embedding",
            _maybe_partition(self.init_method, P("tp", None),
                             self.use_partitioning),
            (self.num_embeddings, self.embedding_dim),
            self.params_dtype,
        )
        table = jnp.asarray(table)
        # XLA partitions the gather over the vocab-sharded table into the
        # masked-lookup + allreduce the reference writes out (:210-230).
        out = jnp.take(table, input_ids, axis=0)
        return out


class ColumnParallelLinear(nn.Module):
    """Y = X·A + b with A column-sharded: A = [A_1 … A_p]
    (reference layers.py:429). Returns ``(out, bias)`` with bias separate
    when ``skip_bias_add`` (for downstream bias+act fusions).

    ``overlap_comm`` (with ``sequence_parallel_enabled``) replaces the
    monolithic sequence all-gather → matmul with the ring
    ``ops.collective_matmul.all_gather_matmul``: each hop's incoming
    sequence shard is matmul'd while the next shard is in flight, and the
    backward rides the dual ring (matmul-reduce-scatter).  Falls back to
    the monolithic path when no 'tp' mesh axis is active or shapes don't
    divide.  Without sequence parallelism the column matmul has no tp
    collective, so the flag is a no-op there."""

    input_size: int
    output_size: int
    bias: bool = True
    gather_output: bool = True
    skip_bias_add: bool = False
    sequence_parallel_enabled: bool = False
    overlap_comm: bool = False
    init_method: Callable = nn.initializers.lecun_normal()
    params_dtype: jnp.dtype = jnp.float32
    use_partitioning: bool = True

    @nn.compact
    def __call__(self, x: jax.Array):
        kernel = self.param(
            "kernel",
            _maybe_partition(self.init_method, P(None, "tp"),
                             self.use_partitioning),
            (self.input_size, self.output_size),
            self.params_dtype,
        )
        kernel = jnp.asarray(kernel)
        b = None
        if self.bias:
            b = self.param(
                "bias",
                _maybe_partition(nn.initializers.zeros, P("tp"),
                                 self.use_partitioning),
                (self.output_size,),
                self.params_dtype,
            )
            b = jnp.asarray(b)

        y = None
        if self.sequence_parallel_enabled and self.overlap_comm:
            from apex_tpu.ops.collective_matmul import (
                sequence_parallel_matmul,
            )

            y = sequence_parallel_matmul(
                x, kernel.astype(x.dtype), mode="gather", enable=True)
            if y is not None:
                y = y.astype(x.dtype)
        if y is None:
            if self.sequence_parallel_enabled:
                # input arrives sequence-sharded [s/tp, b, h]; the matmul
                # needs the full sequence — constrain to replicated so XLA
                # emits the all-gather (reference
                # gather_from_sequence_parallel_region, layers.py:577-612).
                x = constrain(x, P(*([None] * x.ndim)))

            y = jax.lax.dot_general(
                x, kernel.astype(x.dtype),
                dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(x.dtype)
        if not self.gather_output:
            y = constrain(y, P(*([None] * (y.ndim - 1) + ["tp"])))
        out_bias = None
        if b is not None:
            if self.skip_bias_add:
                out_bias = b
            else:
                y = y + b.astype(y.dtype)
        return y, out_bias


class RowParallelLinear(nn.Module):
    """Y = X·A + b with A row-sharded; the partial products sum over 'tp'
    (reference layers.py:613).

    ``overlap_comm`` replaces the serialized matmul → reduce-scatter
    (``sequence_parallel_enabled``) / all-reduce with the ring
    ``ops.collective_matmul.matmul_reduce_scatter``: the rotating
    accumulator overlaps each hop's transfer with the next partial-
    product chunk.  Without sequence parallelism the ring output stays
    sequence-scattered inside the island and the replicated-output
    constraint re-gathers it — same wire bytes as the all-reduce, with
    the reduce-scatter half overlapped.  Falls back monolithic when no
    'tp' mesh axis is active or shapes don't divide."""

    input_size: int
    output_size: int
    bias: bool = True
    input_is_parallel: bool = False
    skip_bias_add: bool = False
    sequence_parallel_enabled: bool = False
    overlap_comm: bool = False
    init_method: Callable = nn.initializers.lecun_normal()
    params_dtype: jnp.dtype = jnp.float32
    use_partitioning: bool = True

    @nn.compact
    def __call__(self, x: jax.Array):
        kernel = self.param(
            "kernel",
            _maybe_partition(self.init_method, P("tp", None),
                             self.use_partitioning),
            (self.input_size, self.output_size),
            self.params_dtype,
        )
        kernel = jnp.asarray(kernel)
        if self.input_is_parallel:
            x = constrain(x, P(*([None] * (x.ndim - 1) + ["tp"])))
        y = None
        if self.overlap_comm:
            from apex_tpu.ops.collective_matmul import (
                sequence_parallel_matmul,
            )

            y = sequence_parallel_matmul(
                x, kernel.astype(x.dtype), mode="scatter", enable=True)
            if y is not None:
                y = y.astype(x.dtype)
        if y is None:
            y = jax.lax.dot_general(
                x, kernel.astype(x.dtype),
                dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(x.dtype)
        if self.sequence_parallel_enabled:
            # reduce-scatter to sequence shards (reference layers.py:744-780;
            # already scattered on the overlap path — idempotent)
            y = constrain(y, P("tp", *([None] * (y.ndim - 1))))
        else:
            y = constrain(y, P(*([None] * y.ndim)))
        b = None
        if self.bias:
            b = self.param("bias", nn.initializers.zeros,
                           (self.output_size,), self.params_dtype)
            b = jnp.asarray(b)
        out_bias = None
        if b is not None:
            if self.skip_bias_add:
                out_bias = b
            else:
                y = y + b.astype(y.dtype)
        return y, out_bias
