"""MemoryBuffer / RingMemBuffer — documented N/A with API shims.

Reference: ``apex/transformer/tensor_parallel/memory.py`` —
``MemoryBuffer`` pre-allocates one contiguous CUDA tensor and hands out
zero-copy views (``get``) to dodge allocator fragmentation and
per-tensor malloc latency; ``RingMemBuffer`` rotates N of them.

On TPU this is a **non-problem by construction**: XLA owns all device
memory, buffers are planned at compile time inside each executable, and
jit boundaries donate/alias arrays (``donate_argnums``), so there is no
allocator churn for a pre-allocation pool to absorb.  The classes below
keep the reference API importable for ported code — ``get`` returns a
correctly-shaped zero view into one flat array, which under jit compiles
to exactly the same thing any fresh ``jnp.zeros`` would.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["MemoryBuffer", "RingMemBuffer"]


def _prod(shape):
    out = 1
    for d in shape:
        out *= int(d)
    return out


class MemoryBuffer:
    """API shim of reference ``MemoryBuffer(numel, dtype)``."""

    def __init__(self, numel: int, dtype=jnp.float32):
        self.numel = int(numel)
        self.dtype = dtype
        self.data = jnp.zeros((self.numel,), dtype)

    def zero(self):
        self.data = jnp.zeros((self.numel,), self.dtype)

    def get(self, shape, start_index: int = 0):
        end = start_index + _prod(shape)
        if end > self.numel:
            raise ValueError(
                f"requested tensor [{start_index}:{end}) is out of the "
                f"buffer's {self.numel} elements")
        return self.data[start_index:end].reshape(shape)


class RingMemBuffer:
    """API shim of reference ``RingMemBuffer(name, num_buffers, numel,
    dtype)`` — rotates through ``num_buffers`` MemoryBuffers."""

    def __init__(self, num_buffers: int, numel: int, dtype=jnp.float32):
        self.buffers = [MemoryBuffer(numel, dtype)
                        for _ in range(num_buffers)]
        self._index = -1

    def get_next_buffer(self) -> MemoryBuffer:
        self._index = (self._index + 1) % len(self.buffers)
        return self.buffers[self._index]
