"""Data broadcast utilities.

Reference: apex/transformer/tensor_parallel/data.py:80 ``broadcast_data`` —
rank 0 of each TP group torch-broadcasts the batch so TP peers see
identical data. Under SPMD every device already receives the same program
inputs; replication across 'tp' is a sharding fact, not a runtime copy. The
function survives as a sharding constraint (and a shape/dtype check mirror
of the reference's ``_check_data_types``).
"""

from __future__ import annotations

from typing import Dict

import jax
from jax.sharding import PartitionSpec as P

from apex_tpu.transformer.tensor_parallel.layers import constrain

__all__ = ["broadcast_data"]


def broadcast_data(keys, data: Dict[str, jax.Array], datatype=None):
    """Constrain each ``data[key]`` replicated over 'tp'
    (no-op outside a mesh context)."""
    out = {}
    for k in keys:
        v = data[k]
        if datatype is not None and v.dtype != datatype:
            raise TypeError(
                f"broadcast_data: {k} has dtype {v.dtype}, expected {datatype}"
            )
        out[k] = constrain(v, P(*([None] * v.ndim)))
    return out
