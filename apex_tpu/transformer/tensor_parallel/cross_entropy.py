"""Vocab-parallel cross entropy.

Reference: apex/transformer/tensor_parallel/cross_entropy.py:23
(``_VocabParallelCrossEntropy``): logits arrive vocab-sharded over the TP
group; the stable CE runs as max-allreduce → masked local gather →
sum-allreduce, and backward adjusts the local softmax without ever
materializing the full-vocab logits on one rank.

This is the shard_map (manual) form on the 'tp' axis. Under the GSPMD layer
path, plain ``apex_tpu.ops.softmax_cross_entropy_loss`` on sharded logits
partitions to the same collectives automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.transformer.parallel_state import TP_AXIS

__all__ = ["vocab_parallel_cross_entropy"]


def _fwd_math(logits, target, axis):
    """Returns (loss, residuals). logits: [..., vocab/tp] local shard."""
    n = jax.lax.axis_size(axis)
    rank = jax.lax.axis_index(axis)
    vocab_local = logits.shape[-1]
    x = logits.astype(jnp.float32)

    # 1. global max for stability (max-allreduce, reference :31-36)
    lmax = jax.lax.pmax(jnp.max(x, axis=-1), axis)
    x = x - lmax[..., None]

    # 2. local masked pick of the target logit (reference :38-55)
    vocab_start = rank * vocab_local
    local_idx = target - vocab_start
    in_range = (local_idx >= 0) & (local_idx < vocab_local)
    picked = jnp.take_along_axis(
        x, jnp.clip(local_idx, 0, vocab_local - 1)[..., None].astype(jnp.int32),
        axis=-1,
    )[..., 0]
    picked = jnp.where(in_range, picked, 0.0)
    picked = jax.lax.psum(picked, axis)          # sum-allreduce

    # 3. global log-sum-exp (sum-allreduce, reference :57-62)
    sum_exp = jax.lax.psum(jnp.sum(jnp.exp(x), axis=-1), axis)
    loss = jnp.log(sum_exp) - picked
    return loss, (x, sum_exp, local_idx, in_range)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def vocab_parallel_cross_entropy(vocab_parallel_logits, target,
                                 axis=TP_AXIS):
    loss, _ = _fwd_math(vocab_parallel_logits, target, axis)
    return loss


def _vp_fwd(logits, target, axis):
    loss, res = _fwd_math(logits, target, axis)
    # zero-size array carries the original dtype through the residuals
    # (a raw dtype object is not a valid jax residual type)
    dtype_token = jnp.zeros((0,), logits.dtype)
    return loss, (res, dtype_token)


def _vp_bwd(axis, carry, g):
    (x, sum_exp, local_idx, in_range), dtype_token = carry
    probs = jnp.exp(x) / sum_exp[..., None]
    onehot = (
        jax.nn.one_hot(local_idx, x.shape[-1], dtype=jnp.float32)
        * in_range[..., None]
    )
    dx = (probs - onehot) * g.astype(jnp.float32)[..., None]
    return dx.astype(dtype_token.dtype), None


vocab_parallel_cross_entropy.defvjp(_vp_fwd, _vp_bwd)
