"""FusedScaleMaskSoftmax — the dispatching softmax module.

Reference: apex/transformer/functional/fused_softmax.py — picks between the
fused CUDA kernels and a torch fallback based on mask type, dtype, and the
kernel's seq-len limits (:222-246), with ``scale`` validation and optional
input-in-fp16/output-in-fp32 handling. Here the Pallas/XLA dispatch lives
inside the ops themselves, so this module only routes on mask type.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.softmax import (
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.transformer.enums import AttnMaskType

__all__ = ["FusedScaleMaskSoftmax"]


class FusedScaleMaskSoftmax:
    """Callable matching the reference module's constructor surface.

    Args mirror fused_softmax.py ``FusedScaleMaskSoftmax.__init__``:
    ``input_in_fp16``/``input_in_bf16`` (informational), ``attn_mask_type``
    (padding|causal), ``scaled_masked_softmax_fusion`` (kept; fusion is
    always available here), ``mask_func`` (applied when the fused path
    can't express it), ``softmax_in_fp32``, ``scale``.
    """

    def __init__(
        self,
        input_in_fp16: bool = False,
        input_in_bf16: bool = False,
        attn_mask_type: AttnMaskType = AttnMaskType.padding,
        scaled_masked_softmax_fusion: bool = True,
        mask_func: Optional[Callable] = None,
        softmax_in_fp32: bool = True,
        scale: Optional[float] = None,
    ):
        if not softmax_in_fp32 and scale is not None:
            # reference asserts the same invariant (:210)
            raise ValueError("softmax should be in fp32 when scaled")
        self.attn_mask_type = attn_mask_type
        self.mask_func = mask_func
        self.scale = 1.0 if scale is None else float(scale)
        self.fusion = scaled_masked_softmax_fusion

    def __call__(self, x: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
        if self.attn_mask_type == AttnMaskType.causal:
            if x.shape[-2] == x.shape[-1]:
                return scaled_upper_triang_masked_softmax(x, self.scale)
            # rectangular causal (inference/kv-cache): build explicit mask
            sq, sk = x.shape[-2], x.shape[-1]
            row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
            causal = col > row + (sk - sq)
            return scaled_masked_softmax(x, causal, self.scale)
        if mask is not None and self.mask_func is not None:
            x = self.mask_func(x, mask)
            mask = None
        if mask is None:
            return scaled_softmax(x, self.scale)
        return scaled_masked_softmax(x, mask, self.scale)
