"""Model-parallel-aware grad scaler.

Reference: apex/transformer/amp/grad_scaler.py:21 — a GradScaler subclass
whose found-inf check allreduces the flag across the TP and PP groups so
every shard of a model skips the step together.

Here: the same ``LossScaleState`` machinery as ``apex_tpu.amp`` with the
flag combined over any set of mesh axes (vma-aware; see
utils/collectives.py).
"""

from __future__ import annotations

from typing import Sequence, Tuple


from apex_tpu.amp import scaler as scaler_lib
from apex_tpu.utils.collectives import flag_or

__all__ = ["GradScaler", "combine_found_inf"]


def combine_found_inf(found_inf, axes: Sequence[str] = ("tp", "pp")):
    """OR the overflow flag across model-parallel axes
    (reference grad_scaler.py:55-70 allreduce MAX)."""
    for axis in axes:
        found_inf = flag_or(found_inf, axis)
    return found_inf


class GradScaler:
    """Functional scaler bundle with model-parallel found-inf combining.

    Usage inside the mapped train step::

        gs = GradScaler(axes=("tp", "pp"))
        cfg, state = gs.init()
        scaled = gs.scale(loss, state)
        grads, finite = gs.unscale(grads, state)
        state, skip = gs.update(cfg, state, ~finite)
    """

    def __init__(self, loss_scale="dynamic",
                 axes: Sequence[str] = ("tp", "pp"), **kwargs):
        self.loss_scale = loss_scale
        self.kwargs = kwargs
        self.axes = tuple(axes)

    def init(self) -> Tuple[scaler_lib.LossScaleConfig,
                            scaler_lib.LossScaleState]:
        return scaler_lib.init_loss_scale(self.loss_scale, **self.kwargs)

    def scale(self, loss, state):
        return scaler_lib.scale_loss(loss, state)

    def unscale(self, grads, state):
        return scaler_lib.unscale_grads(grads, state)

    def update(self, cfg, state, found_inf):
        found_inf = combine_found_inf(found_inf, self.axes)
        return scaler_lib.update_loss_scale(cfg, state, found_inf)
