"""apex_tpu.transformer — Megatron-style model parallelism on a TPU mesh.

Reference: apex/transformer/ (parallel_state, tensor_parallel,
pipeline_parallel, functional, layers, microbatches, amp.grad_scaler).
"""

from apex_tpu.transformer import parallel_state  # noqa: F401
from apex_tpu.transformer import tensor_parallel  # noqa: F401
from apex_tpu.transformer import functional  # noqa: F401
from apex_tpu.transformer.enums import (  # noqa: F401
    AttnMaskType,
    AttnType,
    LayerType,
    ModelType,
)
