"""Small helpers (reference apex/transformer/utils.py)."""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["ensure_divisibility", "divide", "split_tensor_along_last_dim"]


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(
            f"{numerator} is not divisible by {denominator}"
        )


def divide(numerator: int, denominator: int) -> int:
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(tensor, num_partitions: int):
    """reference utils.py split (contiguity flags are meaningless here)."""
    return jnp.split(tensor, num_partitions, axis=-1)
