"""Model-parallel topology state.

Reference: apex/transformer/parallel_state.py — builds NCCL process groups
for the TP×PP×DP grid (``initialize_model_parallel`` :81, group getters
:336-644, ``destroy_model_parallel`` :646). On TPU the topology is one
``jax.sharding.Mesh`` with named axes ('pp','dp','sp','tp'); "groups" are
axis names, and rank-within-group is ``jax.lax.axis_index`` (meaningful
only inside a mapped computation — SPMD runs one program on all devices).

World sizes are static (mesh shape) and available everywhere; rank getters
return traced values inside ``shard_map``/GSPMD contexts, mirroring the
reference's rank queries at the sites that need them.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import Mesh

from apex_tpu.parallel.mesh import create_mesh

__all__ = [
    "initialize_model_parallel",
    "model_parallel_is_initialized",
    "destroy_model_parallel",
    "get_mesh",
    "get_tensor_model_parallel_world_size",
    "get_pipeline_model_parallel_world_size",
    "get_data_parallel_world_size",
    "get_context_parallel_world_size",
    "get_tensor_model_parallel_rank",
    "get_pipeline_model_parallel_rank",
    "get_data_parallel_rank",
    "get_virtual_pipeline_model_parallel_rank",
    "set_virtual_pipeline_model_parallel_rank",
    "get_virtual_pipeline_model_parallel_world_size",
    "is_pipeline_first_stage",
    "is_pipeline_last_stage",
    "get_pipeline_model_parallel_split_rank",
    "get_rank_info",
    "TP_AXIS",
    "PP_AXIS",
    "DP_AXIS",
    "SP_AXIS",
]

TP_AXIS = "tp"
PP_AXIS = "pp"
DP_AXIS = "dp"
SP_AXIS = "sp"


class _State:
    mesh: Optional[Mesh] = None
    virtual_pipeline_model_parallel_size: Optional[int] = None
    virtual_pipeline_model_parallel_rank: Optional[int] = None
    pipeline_model_parallel_split_rank: Optional[int] = None


_STATE = _State()


def initialize_model_parallel(
    tensor_model_parallel_size_: int = 1,
    pipeline_model_parallel_size_: int = 1,
    virtual_pipeline_model_parallel_size_: Optional[int] = None,
    pipeline_model_parallel_split_rank_: Optional[int] = None,
    context_parallel_size: int = 1,
    *,
    devices=None,
) -> Mesh:
    """Build and install the global mesh (reference parallel_state.py:81).

    ``context_parallel_size`` maps to the 'sp' axis — the long-context
    sequence/ring-attention axis the reference lacks.
    Returns the mesh (also retrievable via :func:`get_mesh`).
    """
    mesh = create_mesh(
        tp=tensor_model_parallel_size_,
        pp=pipeline_model_parallel_size_,
        sp=context_parallel_size,
        devices=devices,
    )
    _STATE.mesh = mesh
    _STATE.virtual_pipeline_model_parallel_size = (
        virtual_pipeline_model_parallel_size_
    )
    _STATE.virtual_pipeline_model_parallel_rank = (
        0 if virtual_pipeline_model_parallel_size_ is not None else None
    )
    _STATE.pipeline_model_parallel_split_rank = (
        pipeline_model_parallel_split_rank_
    )
    return mesh


def model_parallel_is_initialized() -> bool:
    return _STATE.mesh is not None


def destroy_model_parallel() -> None:
    """reference parallel_state.py:646."""
    _STATE.mesh = None
    _STATE.virtual_pipeline_model_parallel_size = None
    _STATE.virtual_pipeline_model_parallel_rank = None
    _STATE.pipeline_model_parallel_split_rank = None


def get_mesh() -> Mesh:
    if _STATE.mesh is None:
        raise RuntimeError(
            "model parallel is not initialized; call "
            "initialize_model_parallel() first"
        )
    return _STATE.mesh


def _axis_size(axis: str) -> int:
    return get_mesh().shape[axis]


def get_tensor_model_parallel_world_size() -> int:
    return _axis_size(TP_AXIS)


def get_pipeline_model_parallel_world_size() -> int:
    return _axis_size(PP_AXIS)


def get_data_parallel_world_size() -> int:
    return _axis_size(DP_AXIS)


def get_context_parallel_world_size() -> int:
    return _axis_size(SP_AXIS)


def _axis_index(axis: str):
    """Traced rank — valid inside shard_map/pmap over the mesh."""
    return jax.lax.axis_index(axis)


def get_tensor_model_parallel_rank():
    return _axis_index(TP_AXIS)


def get_pipeline_model_parallel_rank():
    return _axis_index(PP_AXIS)


def get_data_parallel_rank():
    return _axis_index(DP_AXIS)


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _STATE.virtual_pipeline_model_parallel_rank


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    _STATE.virtual_pipeline_model_parallel_rank = rank


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _STATE.virtual_pipeline_model_parallel_size


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _STATE.pipeline_model_parallel_split_rank


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Traced predicate (reference parallel_state.py:560). Inside a mapped
    context this is a device-varying bool; with pp=1 it is statically True."""
    if get_pipeline_model_parallel_world_size() == 1:
        return True
    if not ignore_virtual and _STATE.virtual_pipeline_model_parallel_size:
        if _STATE.virtual_pipeline_model_parallel_rank != 0:
            return False
    return _axis_index(PP_AXIS) == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if get_pipeline_model_parallel_world_size() == 1:
        return True
    vp = _STATE.virtual_pipeline_model_parallel_size
    if not ignore_virtual and vp:
        if _STATE.virtual_pipeline_model_parallel_rank != vp - 1:
            return False
    return _axis_index(PP_AXIS) == get_pipeline_model_parallel_world_size() - 1


def get_rank_info() -> str:
    """Compact topology string for log formatting
    (reference parallel_state.py:313)."""
    if not model_parallel_is_initialized():
        return ""
    m = get_mesh()
    return (
        f"[mesh pp={m.shape['pp']} dp={m.shape['dp']} "
        f"sp={m.shape['sp']} tp={m.shape['tp']}]"
    )
