"""Pipeline-parallel schedules.

Reference: apex/transformer/pipeline_parallel/schedules/ — three schedules
selected by ``get_forward_backward_func`` (schedules/__init__.py:22):
no-pipelining (:31), 1F1B fill/steady/drain
(fwd_bwd_pipelining_without_interleaving.py:228), and interleaved
virtual-pipeline (fwd_bwd_pipelining_with_interleaving.py:26). They
hand-schedule eager p2p sends/recvs and per-microbatch backward calls.

TPU-native design — *pipelining as a differentiable scan*:

The whole fill→steady→drain schedule is one ``lax.scan`` over
``T = n_micro + n_stages - 1`` ticks inside ``shard_map`` over the 'pp'
axis. Each tick every device applies its stage to whatever activation
packet timing says it holds, then ``ppermute``s the packet to its
successor. Reverse-mode autodiff of the scan IS the backward schedule:
XLA reverses the scan, transposes each ppermute (gradients flow backward
through the ring), and the latency-hiding scheduler overlaps collectives
with compute — the 1F1B warmup/steady/cooldown emerges from the compiler's
schedule rather than hand-written isend/irecv ordering. Memory follows the
remat policy: wrap ``stage_fn`` in ``jax.checkpoint`` and each stage keeps
only per-microbatch boundary activations, the same working set as 1F1B.

Timing model (GPipe/1F1B fill-drain): stage ``s`` processes microbatch
``m`` at tick ``t = m + s``. Interleaved virtual pipelining generalizes to
chunks ``c ∈ [0, pp·vpp)`` placed round-robin (chunk c on device c%pp,
virtual slot c//pp) with tick ``t = m + c``; packets move device d→d+1
within a slot and jump slot j→j+1 at the ring wrap, giving the reference's
interleaved dataflow with 1/vpp-sized bubbles.

Shared contract across all three schedules (unlike the reference, the
stage/loss split is explicit):

- ``forward_step_func(stage_params, x) -> y`` — one stage's (or, for
  no-pipelining, the whole model's) forward on one microbatch.
- ``loss_fn(last_stage_output, loss_microbatch) -> scalar`` — computed on
  the last stage; defaults to the mean of the first output leaf.
- ``batch`` — [n_micro, ...] stacked pipeline inputs; ``loss_batch`` —
  [n_micro, ...] per-microbatch loss inputs (targets), defaults to batch.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.observability import metrics as _telemetry
from apex_tpu.transformer.parallel_state import PP_AXIS
from apex_tpu.transformer.pipeline_parallel.p2p_communication import (
    send_forward_recv_forward,
)
from apex_tpu.utils.collectives import pvary

__all__ = [
    "get_forward_backward_func",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_without_interleaving",
    "forward_backward_pipelining_with_interleaving",
    "pipeline_forward",
    "record_schedule_telemetry",
]


def record_schedule_telemetry(schedule: str, *, n_micro: int,
                              n_stages: int, ticks: int) -> None:
    """Analytic per-microbatch bubble/stall accounting for a pipeline
    schedule invocation.

    The scan-based schedules are fully determined by their geometry:
    stage (or chunk) ``s`` processes microbatch ``m`` at tick
    ``t = m + s``, so every stage computes for exactly ``n_micro`` of
    the ``ticks`` scan steps and idles (zero-packet ticks) for the
    remaining ``ticks - n_micro`` — the fill/drain bubble.  Recorded as
    gauges under ``pipeline.<schedule>.*`` plus an invocation counter.

    Host-side and trace-time only (the geometry is static); one
    enabled() check when telemetry is off.
    """
    reg = _telemetry.registry()
    if reg is None:
        return
    bubble = ticks - n_micro
    reg.counter(f"pipeline.{schedule}.invocations").inc()
    reg.gauge(f"pipeline.{schedule}.n_micro").set(n_micro)
    reg.gauge(f"pipeline.{schedule}.stages").set(n_stages)
    reg.gauge(f"pipeline.{schedule}.ticks").set(ticks)
    reg.gauge(f"pipeline.{schedule}.bubble_ticks_per_stage").set(bubble)
    reg.gauge(f"pipeline.{schedule}.bubble_fraction").set(
        bubble / ticks if ticks else 0.0)


def _default_loss(out, _mb):
    return jnp.mean(
        jax.tree_util.tree_leaves(out)[0].astype(jnp.float32)
    )


def _per_microbatch_losses(outs, batch, loss_batch, loss_fn):
    """vmap the loss over the stacked microbatch axis."""
    fn = loss_fn if loss_fn is not None else _default_loss
    lb = batch if loss_batch is None else loss_batch
    return jax.vmap(fn)(outs, lb)


def _reduce_pipeline_loss(outs, batch, loss_batch, loss_fn, axis):
    """Mean per-microbatch loss on the last stage, psum'd so every device
    returns the global value (other stages contributed zeros)."""
    pp = jax.lax.axis_size(axis)
    my = jax.lax.axis_index(axis)
    per_mb = _per_microbatch_losses(outs, batch, loss_batch, loss_fn)
    loss = jnp.where(my == pp - 1, jnp.mean(per_mb), 0.0)
    return jax.lax.psum(loss, axis)


def _zeros_like_output(stage_fn, stage_params, x0):
    shapes = jax.eval_shape(stage_fn, stage_params, x0)
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes
    )


def forward_backward_no_pipelining(
    forward_step_func: Callable,
    batch: Any,
    model_params: Any,
    *,
    n_micro: Optional[int] = None,
    loss_fn: Optional[Callable] = None,
    loss_batch: Any = None,
    **unused,
):
    """Sequential microbatches with gradient accumulation
    (reference fwd_bwd_no_pipelining.py:31). Same contract as the pipelined
    schedules; with ``loss_fn=None`` and a scalar-returning
    ``forward_step_func`` this degrades to the reference's loss-returning
    convention.
    """
    lb = batch if loss_batch is None else loss_batch
    fn = loss_fn if loss_fn is not None else None

    def loss_total(p):
        def per_mb(mb, mb_loss):
            out = forward_step_func(p, mb)
            if fn is None:
                if jax.tree_util.tree_leaves(out)[0].ndim != 0:
                    raise ValueError(
                        "forward_step_func returned non-scalar output but "
                        "no loss_fn was given; pass loss_fn= (the shared "
                        "schedule contract) or return a scalar loss"
                    )
                return jnp.asarray(out, jnp.float32)
            return jnp.asarray(fn(out, mb_loss), jnp.float32)

        losses = jax.vmap(per_mb)(batch, lb)
        return jnp.mean(losses)

    loss, grads = jax.value_and_grad(loss_total)(model_params)
    return loss, grads


def pipeline_forward(
    stage_fn: Callable,
    stage_params: Any,
    microbatches: Any,
    *,
    n_micro: int,
    axis: str = PP_AXIS,
):
    """Run the fill-drain pipeline forward inside shard_map; returns the
    last stage's outputs for every microbatch, stacked [n_micro, ...].

    - ``stage_fn(stage_params, x)`` — one stage's computation. The same
      callable runs on every device; per-stage behavior comes from
      ``stage_params`` (this device's shard).
    - ``microbatches`` — [n_micro, mb, ...] inputs, consumed by stage 0.
      The activation shape must equal the stage output shape (embed/head
      belong inside the first/last stage's ``stage_fn``).
    """
    pp = jax.lax.axis_size(axis)
    my = jax.lax.axis_index(axis)
    ticks = n_micro + pp - 1
    record_schedule_telemetry("1f1b", n_micro=n_micro, n_stages=pp,
                              ticks=ticks)

    x0 = jax.tree_util.tree_map(lambda v: v[0], microbatches)
    zero_like = _zeros_like_output(stage_fn, stage_params, x0)

    def tick(carry, t):
        buf, outputs = carry
        mb = t - my                      # my microbatch index this tick
        active = (mb >= 0) & (mb < n_micro)
        mb_c = jnp.clip(mb, 0, n_micro - 1)
        inject = jax.tree_util.tree_map(
            lambda v: jax.lax.dynamic_index_in_dim(v, mb_c, 0, False),
            microbatches,
        )
        x_in = jax.lax.cond(my == 0, lambda: inject, lambda: buf)
        y = stage_fn(stage_params, x_in)
        y = jax.tree_util.tree_map(
            lambda v: jnp.where(active, v, jnp.zeros_like(v)), y
        )
        # last stage banks its output for this microbatch
        is_last = my == pp - 1
        outputs = jax.tree_util.tree_map(
            lambda o, v: jax.lax.dynamic_update_index_in_dim(
                o,
                jnp.where(active & is_last, v,
                          jax.lax.dynamic_index_in_dim(o, mb_c, 0, False)),
                mb_c, 0,
            ),
            outputs, y,
        )
        buf = send_forward_recv_forward(y, axis)
        return (buf, outputs), None

    outputs0 = jax.tree_util.tree_map(
        lambda z: jnp.zeros((n_micro,) + z.shape, z.dtype), zero_like
    )
    # the carry becomes pp-varying after one tick; type the initial value
    # to match (jax 0.9 varying-axes check)
    (_, outputs), _ = jax.lax.scan(
        tick,
        (pvary(zero_like, axis), pvary(outputs0, axis)),
        jnp.arange(ticks),
    )
    return outputs


def forward_backward_pipelining_without_interleaving(
    forward_step_func: Callable,
    batch: Any,
    model_params: Any,
    *,
    n_micro: int,
    loss_fn: Optional[Callable] = None,
    loss_batch: Any = None,
    axis: str = PP_AXIS,
    remat: bool = True,
):
    """Fill-drain (1F1B-class) pipeline loss+grad inside shard_map
    (reference fwd_bwd_pipelining_without_interleaving.py:228).

    Returns ``(loss, grads)`` where grads are w.r.t. this device's stage
    params — already correct per stage; the backward pipeline (reverse scan
    + transposed ppermutes) is generated by autodiff.
    """
    stage = jax.checkpoint(forward_step_func) if remat else forward_step_func

    def total_loss(p):
        outs = pipeline_forward(stage, p, batch, n_micro=n_micro, axis=axis)
        return _reduce_pipeline_loss(outs, batch, loss_batch, loss_fn, axis)

    loss, grads = jax.value_and_grad(total_loss)(model_params)
    return loss, grads


def forward_backward_pipelining_with_interleaving(
    forward_step_func: Callable,
    batch: Any,
    model_params: Any,
    *,
    n_micro: int,
    num_model_chunks: int,
    loss_fn: Optional[Callable] = None,
    loss_batch: Any = None,
    axis: str = PP_AXIS,
    remat: bool = True,
):
    """Interleaved virtual pipeline
    (reference fwd_bwd_pipelining_with_interleaving.py:26).

    ``model_params`` here is [vpp, ...]-stacked per-device chunk params
    (chunk c lives on device c%pp, slot c//pp).
    ``forward_step_func(chunk_params, x) -> y`` applies ONE chunk.
    """
    pp = jax.lax.axis_size(axis)
    my = jax.lax.axis_index(axis)
    vpp = num_model_chunks
    n_chunks = pp * vpp
    ticks = n_micro + n_chunks - 1
    record_schedule_telemetry("interleaved", n_micro=n_micro,
                              n_stages=n_chunks, ticks=ticks)
    stage = jax.checkpoint(forward_step_func) if remat else forward_step_func

    def total_loss(params_stacked):
        x0 = jax.tree_util.tree_map(lambda v: v[0], batch)
        zeros = _zeros_like_output(
            stage, jax.tree_util.tree_map(lambda v: v[0], params_stacked), x0
        )

        bufs0 = jax.tree_util.tree_map(
            lambda z: jnp.zeros((vpp,) + z.shape, z.dtype), zeros
        )
        outs0 = jax.tree_util.tree_map(
            lambda z: jnp.zeros((n_micro,) + z.shape, z.dtype), zeros
        )

        def tick(carry, t):
            bufs, outs = carry
            new_slots = []
            for j in range(vpp):
                c = my + pp * j                    # global chunk index
                mb = t - c                          # packet timing
                active = (mb >= 0) & (mb < n_micro)
                mb_c = jnp.clip(mb, 0, n_micro - 1)
                x_j = jax.tree_util.tree_map(lambda v: v[j], bufs)
                inject = jax.tree_util.tree_map(
                    lambda v: jax.lax.dynamic_index_in_dim(v, mb_c, 0, False),
                    batch,
                )
                # chunk 0 (device 0, slot 0) reads fresh microbatches
                x_in = jax.lax.cond(
                    (my == 0) & (j == 0), lambda: inject, lambda: x_j
                )
                p_j = jax.tree_util.tree_map(lambda v: v[j], params_stacked)
                y = stage(p_j, x_in)
                y = jax.tree_util.tree_map(
                    lambda v: jnp.where(active, v, jnp.zeros_like(v)), y
                )
                # final chunk (device pp-1, slot vpp-1) banks outputs
                is_final = (my == pp - 1) & (j == vpp - 1)
                outs = jax.tree_util.tree_map(
                    lambda o, v: jax.lax.dynamic_update_index_in_dim(
                        o,
                        jnp.where(
                            active & is_final, v,
                            jax.lax.dynamic_index_in_dim(o, mb_c, 0, False),
                        ),
                        mb_c, 0,
                    ),
                    outs, y,
                )
                new_slots.append(y)

            stacked = jax.tree_util.tree_map(
                lambda *vs: jnp.stack(vs), *new_slots
            )
            # every slot ships device d → d+1 (ring); the wrap from the
            # last device re-enters device 0 one slot higher
            ring = [(i, (i + 1) % pp) for i in range(pp)]
            shipped = jax.tree_util.tree_map(
                lambda v: jax.lax.ppermute(v, axis, ring), stacked
            )

            def advance(v):
                rolled = jnp.roll(v, 1, axis=0)      # slot j-1 → j
                rolled = rolled.at[0].set(jnp.zeros_like(rolled[0]))
                return jnp.where(my == 0, rolled, v)

            shipped = jax.tree_util.tree_map(advance, shipped)
            return (shipped, outs), None

        (_, outs), _ = jax.lax.scan(
            tick,
            (pvary(bufs0, axis), pvary(outs0, axis)),
            jnp.arange(ticks),
        )
        return _reduce_pipeline_loss(outs, batch, loss_batch, loss_fn, axis)

    loss, grads = jax.value_and_grad(total_loss)(model_params)
    return loss, grads


def get_forward_backward_func(
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_size: int = 1,
):
    """Schedule selection (reference schedules/__init__.py:22). All three
    schedules share one ``forward_step_func``/``loss_fn`` contract (see
    module docstring), so the selection is transparent to callers."""
    if pipeline_model_parallel_size <= 1:
        return forward_backward_no_pipelining
    if virtual_pipeline_model_parallel_size is not None and (
        virtual_pipeline_model_parallel_size > 1
    ):
        return forward_backward_pipelining_with_interleaving
    return forward_backward_pipelining_without_interleaving
