"""Timers (reference apex/transformer/pipeline_parallel/_timers.py:6,51 —
``_Timer``/``_Timers`` with barrier-synced elapsed and TensorBoard write).

On TPU a "barrier" is ``jax.block_until_ready`` on the values produced by
the timed region — actual tracing/compile time is excluded on steady-state
steps. TensorBoard writing is delegated to the caller (no torch SummaryWriter
here); ``write`` returns the scalars instead.
"""

from __future__ import annotations

import time
from typing import Dict

import jax

from apex_tpu.observability import metrics as _telemetry

__all__ = ["Timer", "Timers", "get_timers"]


class Timer:
    def __init__(self, name: str):
        self.name_ = name
        self.elapsed_ = 0.0
        self.started_ = False
        self._start_time = 0.0

    def start(self, barrier_obj=None):
        if self.started_:
            raise RuntimeError(f"timer {self.name_} has already been started")
        if barrier_obj is not None:
            jax.block_until_ready(barrier_obj)
        self._start_time = time.perf_counter()
        self.started_ = True

    def stop(self, barrier_obj=None):
        if not self.started_:
            raise RuntimeError(f"timer {self.name_} is not started")
        if barrier_obj is not None:
            jax.block_until_ready(barrier_obj)
        dur = time.perf_counter() - self._start_time
        self.elapsed_ += dur
        self.started_ = False
        # converge on the shared registry: each start/stop interval is a
        # span observation (no-op when telemetry is disabled)
        reg = _telemetry.registry()
        if reg is not None:
            reg.observe_span(f"pipeline.timer.{self.name_}", dur)

    def reset(self):
        self.elapsed_ = 0.0
        self.started_ = False

    def elapsed(self, reset: bool = True) -> float:
        was_started = self.started_
        if was_started:
            self.stop()
        value = self.elapsed_
        if reset:
            self.reset()
        if was_started:
            self.start()
        return value


class Timers:
    def __init__(self):
        self.timers: Dict[str, Timer] = {}

    def __call__(self, name: str) -> Timer:
        if name not in self.timers:
            self.timers[name] = Timer(name)
        return self.timers[name]

    def write(self, names, iteration: int, normalizer: float = 1.0,
              reset: bool = False) -> Dict[str, float]:
        """Return {name: seconds/normalizer} (caller logs it;
        reference writes to TensorBoard)."""
        assert normalizer > 0.0
        return {
            name: self.timers[name].elapsed(reset=reset) / normalizer
            for name in names if name in self.timers
        }

    def log(self, names, normalizer: float = 1.0, reset: bool = True) -> str:
        assert normalizer > 0.0
        parts = ["time (ms)"]
        for name in names:
            if name in self.timers:
                ms = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"| {name}: {ms:.2f}")
        line = " ".join(parts)
        from apex_tpu.utils.logging import print_rank_0

        print_rank_0(line)
        return line


_TIMERS = Timers()


def get_timers() -> Timers:
    """reference pipeline_parallel/utils.py:153."""
    return _TIMERS
