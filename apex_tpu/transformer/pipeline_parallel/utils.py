"""Pipeline utilities.

Reference: apex/transformer/pipeline_parallel/utils.py — the microbatch
calculator singleton (:58,:92), loss averaging over DP (:242),
``report_memory`` (:253), rank-0 printing (:159,:172).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.transformer.microbatches import (
    NumMicroBatchesCalculator,
    build_num_microbatches_calculator,
)
from apex_tpu.utils.logging import print_rank_0  # noqa: F401  (re-export)

__all__ = [
    "setup_microbatch_calculator",
    "get_num_microbatches",
    "get_current_global_batch_size",
    "update_num_microbatches",
    "average_losses_across_data_parallel_group",
    "report_memory",
    "print_rank_0",
    "split_batch_into_microbatches",
]

_CALCULATOR: Optional[NumMicroBatchesCalculator] = None


def setup_microbatch_calculator(
    rank: int,
    rampup_batch_size: Optional[Sequence[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
) -> None:
    """reference utils.py:58 (rank arg kept for signature parity)."""
    global _CALCULATOR
    _CALCULATOR = build_num_microbatches_calculator(
        rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size,
    )


def _get() -> NumMicroBatchesCalculator:
    if _CALCULATOR is None:
        raise RuntimeError(
            "microbatch calculator is not set up; call "
            "setup_microbatch_calculator() first"
        )
    return _CALCULATOR


def get_num_microbatches() -> int:
    return _get().get()


def get_current_global_batch_size() -> int:
    return _get().get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True) -> None:
    _get().update(consumed_samples, consistency_check)


def average_losses_across_data_parallel_group(losses, axis: str = "dp"):
    """reference utils.py:242 — must run inside the mapped context."""
    stacked = jnp.stack([jnp.asarray(l, jnp.float32) for l in losses])
    return jax.lax.pmean(stacked, axis)


def report_memory(name: str = "") -> str:
    """Device-memory report (reference utils.py:253 reports CUDA stats)."""
    lines = [f"memory report{(' ' + name) if name else ''}:"]
    for d in jax.local_devices():
        try:
            stats = d.memory_stats() or {}
            used = stats.get("bytes_in_use", 0) / 2**30
            limit = stats.get("bytes_limit", 0) / 2**30
            lines.append(f"  {d}: {used:.2f}/{limit:.2f} GiB in use")
        except Exception:
            lines.append(f"  {d}: memory stats unavailable")
    report = "\n".join(lines)
    print_rank_0(report)
    return report


def split_batch_into_microbatches(batch, n_micro: int):
    """[B, ...] → [n_micro, B/n_micro, ...] for the schedule functions."""

    def leaf(v):
        b = v.shape[0]
        if b % n_micro != 0:
            raise ValueError(
                f"batch dim {b} not divisible by n_micro={n_micro}"
            )
        return v.reshape(n_micro, b // n_micro, *v.shape[1:])

    return jax.tree_util.tree_map(leaf, batch)


def print_params_min_max_norm(params, iteration: int) -> str:
    """Debug dump: per-parameter (min, max, l2-norm) — reference
    pipeline_parallel/utils.py:265 ``print_params_min_max_norm`` (which
    walks optimizer param groups; here the pytree)."""
    import jax.numpy as jnp

    lines = [f"iteration {iteration}"]
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        if not hasattr(leaf, "dtype") or not jnp.issubdtype(
                leaf.dtype, jnp.floating):
            continue
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        v = leaf.astype(jnp.float32)
        lines.append(
            f"  {name}: min {float(v.min()):+.3e} "
            f"max {float(v.max()):+.3e} "
            f"norm {float(jnp.sqrt(jnp.sum(v * v))):.3e}")
    report = "\n".join(lines)
    print_rank_0(report)
    return report
