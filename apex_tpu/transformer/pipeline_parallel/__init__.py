from apex_tpu.transformer.pipeline_parallel.p2p_communication import (  # noqa: F401
    recv_backward,
    recv_forward,
    send_backward,
    send_backward_recv_backward,
    send_forward,
    send_forward_recv_forward,
)
from apex_tpu.transformer.pipeline_parallel.schedules import (  # noqa: F401
    forward_backward_no_pipelining,
    forward_backward_pipelining_with_interleaving,
    forward_backward_pipelining_without_interleaving,
    get_forward_backward_func,
    pipeline_forward,
)
from apex_tpu.transformer.pipeline_parallel.utils import (  # noqa: F401
    average_losses_across_data_parallel_group,
    get_num_microbatches,
    report_memory,
    setup_microbatch_calculator,
    split_batch_into_microbatches,
)
