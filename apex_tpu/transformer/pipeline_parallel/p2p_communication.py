"""Stage-to-stage communication primitives.

Reference: apex/transformer/pipeline_parallel/p2p_communication.py —
``_communicate`` (:124) batches torch.distributed isend/irecv pairs between
pipeline neighbors (``_run_p2pops`` :48), negotiates shapes (seq-parallel
division included), and returns ``FutureTensor``s for async variants.

TPU translation: neighbor exchange is ``lax.ppermute`` over the 'pp' mesh
axis inside the jitted step. There is no shape negotiation (shapes are
static under jit), no async API surface (XLA's latency-hiding scheduler
overlaps the collective with compute), and no process boundary visible to
user code. The send/recv names survive as thin ppermute wrappers so
schedule code reads like the reference.

All functions must run inside shard_map with the 'pp' axis bound. A
"recv" is the same ppermute as its paired "send" — under SPMD both sides
execute the identical collective; the wrappers differ only in which
direction the permutation points.
"""

from __future__ import annotations

from typing import Any

import jax

from apex_tpu.transformer.parallel_state import PP_AXIS

__all__ = [
    "send_forward_recv_forward",
    "send_backward_recv_backward",
    "send_forward",
    "recv_forward",
    "send_backward",
    "recv_backward",
]


def _shift(x: Any, axis: str, step: int) -> Any:
    """ppermute every leaf by ``step`` along the pp ring (non-wrapping ends
    receive zeros, like a silent recv of nothing)."""
    n = jax.lax.axis_size(axis)
    perm = [(i, i + step) for i in range(n) if 0 <= i + step < n]

    def leaf(v):
        return jax.lax.ppermute(v, axis, perm)

    return jax.tree_util.tree_map(leaf, x)


def send_forward_recv_forward(x: Any, axis: str = PP_AXIS) -> Any:
    """Ship activations to the next stage; receive from the previous
    (reference _communicate with both tensors set)."""
    return _shift(x, axis, +1)


def send_backward_recv_backward(g: Any, axis: str = PP_AXIS) -> Any:
    """Ship gradients to the previous stage; receive from the next."""
    return _shift(g, axis, -1)


# Under SPMD a lone send or recv is still the same collective — aliases
# keep reference-looking schedule code readable.
send_forward = send_forward_recv_forward
recv_forward = send_forward_recv_forward
send_backward = send_backward_recv_backward
recv_backward = send_backward_recv_backward
