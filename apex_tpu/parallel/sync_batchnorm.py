"""SyncBatchNorm — batch statistics reduced over a mesh axis.

Reference: apex/parallel/optimized_sync_batchnorm.py (+ syncbn CUDA kernels,
csrc/welford.cu): local Welford mean/var → all_gather of per-rank
(mean, var, count) → Welford merge → normalize; backward allreduces
(Σdy, Σdy·x̂) (optimized_sync_batchnorm_kernel.py:36-111). The pure-python
fallback (sync_batchnorm.py:9) has the same math.

SPMD simplification: every shard holds the same per-device batch size, so
the Welford merge over equal counts collapses to ``pmean`` of the first two
moments — one fused collective, and backward's reductions are inserted by
XLA when the stats carry a ``pmean``. Channel-last (NHWC) layout is native
on TPU; channels are the last dim (reference groupbn's NHWC layout is the
default here, not a variant).

Use inside ``shard_map``/``pmap`` with ``axis_name`` bound; outside one
(axis_name=None) it degrades to plain BatchNorm — matching
``convert_syncbn_model``'s behavior when no process group exists.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

__all__ = ["SyncBatchNorm", "convert_syncbn_model"]


class SyncBatchNorm(nn.Module):
    """Drop-in for reference ``apex.parallel.SyncBatchNorm``.

    Channels on the LAST axis (TPU-native NHWC). ``use_running_average``
    selects eval behavior (torch ``.eval()`` analog).
    """

    num_features: int
    eps: float = 1e-5
    momentum: float = 0.1
    affine: bool = True
    track_running_stats: bool = True
    axis_name: Optional[str] = "dp"
    # fuse_relu mirrors the contrib groupbn BatchNorm2d_NHWC(fuse_relu=...)
    fuse_relu: bool = False

    @nn.compact
    def __call__(
        self, x: jax.Array, use_running_average: bool = False
    ) -> jax.Array:
        c = self.num_features
        if x.shape[-1] != c:
            raise ValueError(
                f"expected channels-last input with {c} channels, got "
                f"shape {x.shape}"
            )
        reduce_axes = tuple(range(x.ndim - 1))
        x32 = x.astype(jnp.float32)

        running_mean = self.variable(
            "batch_stats", "mean", lambda: jnp.zeros((c,), jnp.float32)
        )
        running_var = self.variable(
            "batch_stats", "var", lambda: jnp.ones((c,), jnp.float32)
        )

        if use_running_average and self.track_running_stats:
            mean, var = running_mean.value, running_var.value
        else:
            mean = jnp.mean(x32, axis=reduce_axes)
            mean_sq = jnp.mean(jnp.square(x32), axis=reduce_axes)
            # Skip the collective while initializing params outside the
            # mapped context (axis unbound during .init()).
            if self.axis_name is not None and not self.is_initializing():
                # equal per-shard counts ⇒ Welford merge == pmean of moments
                mean = jax.lax.pmean(mean, self.axis_name)
                mean_sq = jax.lax.pmean(mean_sq, self.axis_name)
            var = mean_sq - jnp.square(mean)
            if self.track_running_stats and not self.is_initializing():
                # torch-convention EMA: new = (1-m)*old + m*batch
                n = x32.size // c
                if self.axis_name is not None:
                    n = n * jax.lax.axis_size(self.axis_name)
                unbiased = var * (n / max(n - 1, 1))
                running_mean.value = (
                    (1 - self.momentum) * running_mean.value
                    + self.momentum * mean
                )
                running_var.value = (
                    (1 - self.momentum) * running_var.value
                    + self.momentum * unbiased
                )

        y = (x32 - mean) * jax.lax.rsqrt(var + self.eps)
        if self.affine:
            weight = self.param("scale", nn.initializers.ones, (c,),
                                jnp.float32)
            bias = self.param("bias", nn.initializers.zeros, (c,),
                              jnp.float32)
            y = y * weight + bias
        if self.fuse_relu:
            y = jax.nn.relu(y)
        return y.astype(x.dtype)


def convert_syncbn_model(module, axis_name: str = "dp"):
    """Best-effort analog of reference ``convert_syncbn_model``
    (apex/parallel/__init__.py:21), which walks a torch module tree replacing
    BatchNorm with SyncBatchNorm.

    Flax modules are immutable dataclasses, so only direct conversion of an
    ``nn.BatchNorm`` instance is supported; for composite models, construct
    them with :class:`SyncBatchNorm` (or pass ``axis_name`` to flax's own
    ``nn.BatchNorm``, which also syncs) from the start.
    """
    if isinstance(module, SyncBatchNorm):
        return module
    if isinstance(module, nn.BatchNorm):
        return nn.BatchNorm(
            use_running_average=module.use_running_average,
            momentum=module.momentum,
            epsilon=module.epsilon,
            axis_name=axis_name,
        )
    raise NotImplementedError(
        "convert_syncbn_model can only convert nn.BatchNorm instances under "
        "flax's immutable module system; build composite models with "
        "apex_tpu.parallel.SyncBatchNorm (channels-last) or flax "
        "nn.BatchNorm(axis_name=...) directly."
    )
