"""Ring attention — context parallelism over a sequence mesh axis.

The reference implements only Megatron sequence parallelism (activations
sharded between, not inside, attention — apex/transformer/tensor_parallel/
mappings.py:55,95,114) and has **no** ring attention / context parallel /
Ulysses path (SURVEY.md §5). This module is the TPU-native long-context
answer: Q stays resident, K/V rotate around the 'sp' axis via
``lax.ppermute`` while each step runs the Pallas flash-attention kernels on
the local (q, kv-chunk) pair and merges results with a numerically stable
logsumexp combine. Per-device memory is O(s_local·d) regardless of the
global sequence length.

Backward is the true ring algorithm (not autodiff through the scan): dK/dV
accumulators travel around the ring *with* their K/V chunks, each step
calling the flash backward kernels with the **final** logsumexp and delta
(valid because p = exp(s - lse_final) globally); after world-size steps
every accumulator has gone full circle and lands on its home shard.

Causality is resolved per (q-shard, kv-chunk) pair with a 3-way
``lax.switch``: chunks fully below the diagonal attend unmasked, the
diagonal chunk runs the causal kernel, chunks above contribute nothing —
so causal ring attention also skips ~half the FLOPs.

Call inside ``jax.shard_map`` with q/k/v sharded along the sequence axis:

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=P(None, 'sp', None, None), out_specs=...)
    def f(q, k, v):
        return ring_attention(q, k, v, axis_name='sp', causal=True)
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl

from apex_tpu.ops.flash_attention import (
    _NEG_INF,
    _bwd_pallas,
    _from_bh,
    _fwd_pallas,
    _pad_to,
    _to_bh,
)
from apex_tpu.utils.collectives import (
    match_vma,
    ppermute as _ppermute,
    vma_of,
)
from apex_tpu.utils.registry import on_tpu

__all__ = ["ring_attention"]


def _merge(o_a, lse_a, o_b, lse_b):
    """Stable combine of two partial attention results ([bh,s,d] f32 with
    per-row lse [bh,s])."""
    lse_max = jnp.maximum(lse_a, lse_b)
    ea = jnp.exp(lse_a - lse_max)
    eb = jnp.exp(lse_b - lse_max)
    lse = lse_max + jnp.log(ea + eb)
    wa = jnp.exp(lse_a - lse)[..., None]
    wb = jnp.exp(lse_b - lse)[..., None]
    return o_a * wa + o_b * wb, lse


def _chunk_mask(s, causal, s_local):
    """Validity predicate on padded [.., sp, sp] scores: real keys only,
    plus the intra-chunk causal triangle on the diagonal chunk."""
    rows, cols = s.shape[-2], s.shape[-1]
    col = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1)
    pred = col < s_local
    if causal:
        row = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0)
        pred = pred & (col <= row)
    return pred


def _expand_groups(x3, gqa):
    """[b*g, s, d] → [b*n, s, d] matching the batch-major _to_bh layout
    (row b·n+h reads group h // rep) — reference-path analog of the
    kernels' grouped index maps."""
    if gqa is None:
        return x3
    n, g = gqa
    rep = n // g
    bg, s, d = x3.shape
    b = bg // g
    return jnp.repeat(x3.reshape(b, g, s, d), rep, axis=1).reshape(
        b * n, s, d)


def _reduce_groups(x3, gqa):
    """[b*n, s, d] gradient → [b*g, s, d] by summing each group's rep
    query-head contributions (the transpose of _expand_groups)."""
    if gqa is None:
        return x3
    n, g = gqa
    rep = n // g
    bn, s, d = x3.shape
    b = bn // n
    return x3.reshape(b, g, rep, s, d).sum(axis=2).reshape(b * g, s, d)


def _chunk_fwd_ref(q3, k3, v3, scale, causal, s_local):
    """Closed-form (o, lse) for one chunk — XLA path used off-TPU, where
    the Pallas interpreter cannot run under shard_map vma typing."""
    s = jnp.einsum("bqd,bkd->bqk", q3.astype(jnp.float32),
                   k3.astype(jnp.float32)) * scale
    s = jnp.where(_chunk_mask(s, causal, s_local), s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.where(m > _NEG_INF / 2, jnp.exp(s - m), 0.0)
    l = jnp.sum(e, axis=-1, keepdims=True)
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o = jnp.einsum("bqk,bkd->bqd", e / safe_l, v3.astype(jnp.float32))
    lse = jnp.where(l[..., 0] == 0.0, _NEG_INF, m[..., 0] + jnp.log(
        safe_l[..., 0]))
    return o, lse


def _chunk_bwd_ref(q3, k3, v3, do3, lse, delta, scale, causal, s_local):
    s = jnp.einsum("bqd,bkd->bqk", q3.astype(jnp.float32),
                   k3.astype(jnp.float32)) * scale
    p = jnp.where(_chunk_mask(s, causal, s_local),
                  jnp.exp(s - lse[..., None]), 0.0)
    do = do3.astype(jnp.float32)
    dv = jnp.einsum("bqk,bqd->bkd", p, do)
    dp = jnp.einsum("bqd,bkd->bqk", do, v3.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, k3.astype(jnp.float32))
    dk = jnp.einsum("bqk,bqd->bkd", ds, q3.astype(jnp.float32))
    return dq, dk, dv


def _chunk_fwd(q3, k3, v3, scale, causal_mode, s_local, block_q,
               block_k, gqa=None):
    """One (q-shard, kv-chunk) flash forward. causal_mode: 0 full,
    1 diagonal (causal), 2 skip.  ``gqa=(n, g)`` keeps the chunk at
    group width: the kernels broadcast via index maps, the reference
    path via an explicit expand."""
    use_pallas = on_tpu()

    def run(causal):
        if use_pallas:
            # f32 kernel outputs: chunk results feed the f32 lse merge /
            # traveling accumulators; rounding to bf16 per chunk would
            # compound error with ring size
            o, lse = _fwd_pallas(q3, k3, v3, None, None, None, scale,
                                 causal, s_local, block_q, block_k, 0.0,
                                 False, out_dtype=jnp.float32, gqa=gqa)
            return o, lse
        return _chunk_fwd_ref(q3, _expand_groups(k3, gqa),
                              _expand_groups(v3, gqa), scale, causal,
                              s_local)

    def skip(_):
        # match the full vma typing of the kernel branches
        return match_vma(
            (jnp.zeros(q3.shape, jnp.float32),
             jnp.full(q3.shape[:2], _NEG_INF, jnp.float32)),
            vma_of(q3))

    return jax.lax.switch(
        causal_mode, [lambda _: run(False), lambda _: run(True), skip],
        None)


def _chunk_bwd(q3, k3, v3, do3, lse, delta, scale, causal_mode, s_local,
               block_q, block_k, gqa=None):
    use_pallas = on_tpu()

    def run(causal):
        if use_pallas:
            dq, dk, dv = _bwd_pallas(
                q3, k3, v3, do3, lse, delta, None, None, None, scale,
                causal, s_local, s_local, block_q, block_k, 0.0, False,
                out_dtype=jnp.float32, gqa=gqa)
            return dq, dk, dv
        dq, dk, dv = _chunk_bwd_ref(
            q3, _expand_groups(k3, gqa), _expand_groups(v3, gqa), do3,
            lse, delta, scale, causal, s_local)
        return dq, _reduce_groups(dk, gqa), _reduce_groups(dv, gqa)

    def skip(_):
        return match_vma(
            (jnp.zeros(q3.shape, jnp.float32),
             jnp.zeros(k3.shape, jnp.float32),
             jnp.zeros(v3.shape, jnp.float32)), vma_of(q3))

    return jax.lax.switch(
        causal_mode, [lambda _: run(False), lambda _: run(True), skip],
        None)


def _ring_blocks(s_local):
    """One block size for q AND kv: the padded shard length (a block_q
    multiple) must divide the kernels' kv grid exactly, or trailing real
    keys would be silently dropped.

    Block choice minimizes padded work per ring step: cost ~ padded^2 /
    tile_throughput(b), with relative tile throughputs from the round-3
    v5e sweep (fwd s1024: 256-blocks 1494us, 512 1186us, 1024 946us —
    BASELINE.md kernel ledger).  A flat >=1024 cap would pad e.g.
    s_local=1280 to 2048 (2.56x the score elements) and lose more to
    padding than the bigger tile wins."""
    rel = {256: 1.0, 512: 1.26, 1024: 1.58}
    best, best_cost = None, None
    for b, thr in rel.items():
        padded = pl.cdiv(max(s_local, 1), b) * b
        cost = padded * padded / thr
        if best_cost is None or cost < best_cost:
            best, best_cost = b, cost
    b = min(best, pl.cdiv(s_local, 128) * 128)
    return b, b


def _ring_perm(axis_name):
    n = jax.lax.axis_size(axis_name)
    return [(i, (i + 1) % n) for i in range(n)]


def _mode(my, src, causal):
    """0 attend-all, 1 diagonal, 2 skip — chunk ``src`` vs q-shard ``my``."""
    if not causal:
        return jnp.int32(0)
    return jnp.where(src == my, 1, jnp.where(src < my, 0, 2)).astype(
        jnp.int32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring(q, k, v, axis_name, causal, scale):
    o, _ = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return o


def _ring_fwd_impl(q, k, v, axis_name, causal, scale):
    b, s_local, n, d = q.shape
    gqa = (n, k.shape[2]) if k.shape[2] != n else None
    ndev = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    block_q, block_k = _ring_blocks(s_local)
    sp = (s_local + block_q - 1) // block_q * block_q
    perm = _ring_perm(axis_name)

    q3 = _pad_to(_to_bh(q), sp, 1)
    k3 = _pad_to(_to_bh(k), sp, 1)
    v3 = _pad_to(_to_bh(v), sp, 1)

    def step(t, carry):
        k_cur, v_cur, o_acc, lse_acc = carry
        src = (my - t) % ndev                 # global chunk id held now
        mode = _mode(my, src, causal)
        o_c, lse_c = _chunk_fwd(q3, k_cur, v_cur, scale, mode, s_local,
                                block_q, block_k, gqa=gqa)
        o_acc, lse_acc = _merge(o_acc, lse_acc, o_c, lse_c)
        k_nxt = _ppermute(k_cur, axis_name, perm)
        v_nxt = _ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, o_acc, lse_acc

    o0, lse0 = match_vma(
        (jnp.zeros(q3.shape, jnp.float32),
         jnp.full(q3.shape[:2], _NEG_INF, jnp.float32)), vma_of(q3))
    _, _, o_acc, lse = jax.lax.fori_loop(
        0, ndev, step, (k3, v3, o0, lse0))

    o = _from_bh(o_acc.astype(q.dtype), b, n)[:, :s_local]
    return o, lse


def _ring_vjp_fwd(q, k, v, axis_name, causal, scale):
    o, lse = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return o, (q, k, v, o, lse)


def _ring_vjp_bwd(axis_name, causal, scale, res, do):
    q, k, v, o, lse = res
    b, s_local, n, d = q.shape
    gqa = (n, k.shape[2]) if k.shape[2] != n else None
    ndev = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    block_q, block_k = _ring_blocks(s_local)
    sp = (s_local + block_q - 1) // block_q * block_q
    perm = _ring_perm(axis_name)

    q3 = _pad_to(_to_bh(q), sp, 1)
    k3 = _pad_to(_to_bh(k), sp, 1)
    v3 = _pad_to(_to_bh(v), sp, 1)
    do3 = _pad_to(_to_bh(do), sp, 1)
    o3 = _pad_to(_to_bh(o), sp, 1)
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1)

    def step(t, carry):
        k_cur, v_cur, dk_cur, dv_cur, dq_acc = carry
        src = (my - t) % ndev
        mode = _mode(my, src, causal)
        dq_c, dk_c, dv_c = _chunk_bwd(
            q3, k_cur, v_cur, do3, lse, delta, scale, mode, s_local,
            block_q, block_k, gqa=gqa)
        dq_acc = dq_acc + dq_c
        dk_cur = dk_cur + dk_c
        dv_cur = dv_cur + dv_c
        # rotate kv and its traveling gradient accumulators together
        k_nxt = _ppermute(k_cur, axis_name, perm)
        v_nxt = _ppermute(v_cur, axis_name, perm)
        dk_nxt = _ppermute(dk_cur, axis_name, perm)
        dv_nxt = _ppermute(dv_cur, axis_name, perm)
        return k_nxt, v_nxt, dk_nxt, dv_nxt, dq_acc

    z3, zq = match_vma((jnp.zeros(k3.shape, jnp.float32),
                        jnp.zeros(q3.shape, jnp.float32)), vma_of(q3))
    _, _, dk3, dv3, dq3 = jax.lax.fori_loop(
        0, ndev, step, (k3, v3, z3, z3, zq))
    # after ndev rotations the accumulators are home again

    dq = _from_bh(dq3.astype(q.dtype), b, n)[:, :s_local]
    dk = _from_bh(dk3.astype(k.dtype), b, k.shape[2])[:, :s_local]
    dv = _from_bh(dv3.astype(v.dtype), b, v.shape[2])[:, :s_local]
    return dq, dk, dv


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Context-parallel attention over sequence-sharded [b, s_local, n, d]
    tensors. Must be called inside a ``jax.shard_map`` whose mesh has
    ``axis_name``; every device's shard length must be equal (global seq =
    s_local × axis size, q-shard i owning global positions
    [i·s_local, (i+1)·s_local)).

    Grouped K/V (``[b, s_local, g, d]`` with g dividing the query head
    count) ride the ring at group width: the rotating ppermute messages
    — the dominant ICI traffic of ring attention — shrink by n/g, and
    the chunk kernels broadcast groups via their GQA index maps.  dK/dV
    come back at group width.
    """
    if q.ndim != 4:
        raise ValueError(f"expected [b, s_local, n, d], got {q.shape}")
    if k.shape != v.shape:
        raise ValueError("ring attention requires equal k/v shard shapes")
    if q.shape[:2] + q.shape[3:] != k.shape[:2] + k.shape[3:]:
        raise ValueError(
            f"q/k shard shapes differ beyond the head axis: {q.shape} "
            f"vs {k.shape}")
    if q.shape[2] % k.shape[2]:
        raise ValueError(
            f"query heads ({q.shape[2]}) must be a multiple of the K/V "
            f"group count ({k.shape[2]})")
    d = q.shape[-1]
    scale = (1.0 / d ** 0.5) if scale is None else float(scale)
    return _ring(q, k, v, axis_name, causal, scale)
