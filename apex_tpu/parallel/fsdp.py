"""FSDP / ZeRO-3-style fully-sharded parameters under GSPMD.

Beyond the reference: apex stops at ZeRO-2 (optimizer-state sharding,
``DistributedFusedAdam``).  On a TPU mesh, full parameter sharding is a
*placement decision*, not a runtime: shard every parameter (and its
master copy and optimizer state, which inherit the placement through the
AMP train step) across the ``dp`` axis, and GSPMD inserts the
all-gathers before each layer's compute and the reduce-scatters in the
backward — the latency-hiding scheduler overlaps them with compute the
way hand-written FSDP prefetch does.

Usage::

    mesh = create_mesh()                       # dp = world
    init_fn, step_fn = make_train_step(loss_fn, fused_adam(1e-3), "O2")
    state = init_fn(params)
    state = jax.device_put(state, fsdp_shardings(state, mesh))
    step = jax.jit(step_fn, donate_argnums=0)
    with jax.set_mesh(mesh):
        state, metrics = step(state, *batch)   # batch sharded over dp

Works with every optimizer in :mod:`apex_tpu.optimizers` (their state
pytrees mirror param shapes, so :func:`fsdp_shardings` shards them the
same way).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["fsdp_spec", "fsdp_shardings", "fsdp_augment_specs"]


def fsdp_spec(shape, ndev: int, axis: str = "dp") -> P:
    """Shard the largest divisible dim of ``shape`` over ``axis``;
    replicate leaves too small or oddly shaped to split (the scalar /
    norm-vector case — same policy as t5x/maxtext FSDP rules)."""
    best = None
    for i, d in enumerate(shape):
        if d % ndev == 0 and (best is None or d > shape[best]):
            best = i
    if best is None:
        return P()
    return P(*(axis if i == best else None for i in range(len(shape))))


def fsdp_augment_specs(specs: Any, shapes: Any, ndev: int,
                       axis: str = "dp"):
    """Compose FSDP with an existing PartitionSpec tree (e.g. the tp
    specs from ``gpt_param_specs``): shard the largest still-unsharded
    divisible dim of every leaf over ``axis``, keeping the tensor-
    parallel dims where they are.  ``shapes`` mirrors ``specs`` with the
    actual array (or .shape-carrying) leaves."""

    def one(spec: P, arr):
        shape = getattr(arr, "shape", arr)
        entries = list(spec) + [None] * (len(shape) - len(spec))
        best = None
        for i, d in enumerate(shape):
            if entries[i] is None and d % ndev == 0 and (
                    best is None or d > shape[best]):
                best = i
        if best is None:
            return P(*entries)
        entries[best] = axis
        return P(*entries)

    return jax.tree_util.tree_map(
        one, specs, shapes, is_leaf=lambda x: isinstance(x, P))


def fsdp_shardings(tree: Any, mesh: Mesh, axis: str = "dp"):
    """NamedSharding pytree for ``tree``: every float array leaf sharded
    per :func:`fsdp_spec`, everything else replicated."""
    ndev = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]

    def one(x):
        if (hasattr(x, "shape") and hasattr(x, "dtype")
                and jnp.issubdtype(x.dtype, jnp.floating)
                and len(getattr(x, "shape", ())) >= 1):
            return NamedSharding(mesh, fsdp_spec(x.shape, ndev, axis))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(one, tree)
