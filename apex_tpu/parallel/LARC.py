"""LARC — layer-wise adaptive rate control as an optimizer wrapper.

Reference: apex/parallel/LARC.py:5 — before the inner optimizer steps, each
param's grad is replaced by ``(grad + wd·p) · adaptive_lr`` where

    adaptive_lr = tc·‖p‖ / (‖g‖ + wd·‖p‖ + eps)
    adaptive_lr = min(adaptive_lr / lr, 1)        if clip (so lr·alr =
                                                   min(adaptive_lr, lr))

and the inner optimizer's own weight decay is disabled for the step
(LARC.py:77-106). Here: an optax-style wrapper transforming the grads fed to
any inner ``GradientTransformation`` — construct the inner optimizer with
``weight_decay=0`` and give the decay to LARC, matching how the reference
absorbs it.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import (
    GradientTransformation,
    is_float_leaf,
)

__all__ = ["LARC", "larc"]


class LARCState(NamedTuple):
    inner: Any


def larc(
    inner: GradientTransformation,
    lr: float,
    trust_coefficient: float = 0.02,
    clip: bool = True,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
) -> GradientTransformation:
    """Wrap ``inner``; ``lr`` must be the inner optimizer's lr (used by the
    clip calculation exactly as the reference reads ``group['lr']``)."""

    def init(params):
        return LARCState(inner=inner.init(params))

    def update(grads, state: LARCState, params=None):
        if params is None:
            raise ValueError("larc requires params")

        def leaf(g, p):
            if not is_float_leaf(g):
                return g
            g32, p32 = g.astype(jnp.float32), p.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
            alr = trust_coefficient * p_norm / (
                g_norm + p_norm * weight_decay + eps
            )
            if clip:
                alr = jnp.minimum(alr / lr, 1.0)
            adjusted = (g32 + weight_decay * p32) * alr
            ok = (p_norm != 0) & (g_norm != 0)
            return jnp.where(ok, adjusted, g32).astype(g.dtype)

        adj = jax.tree_util.tree_map(leaf, grads, params)
        updates, inner_state = inner.update(adj, state.inner, params)
        return updates, LARCState(inner=inner_state)

    return GradientTransformation(init, update)


LARC = larc
