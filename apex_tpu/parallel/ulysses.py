"""Ulysses-style all-to-all sequence parallelism for attention.

The second long-context mode next to :mod:`ring_attention` (the brief's
"ring attention or all-to-all sequence/context parallelism"; the
reference has neither — SURVEY.md §5).  Where the ring keeps K/V
rotating and per-device memory at O(s_local·d·n), Ulysses re-shards
*heads* across the sequence axis for the duration of attention:

    [b, s_local, n, d]  --all_to_all-->  [b, s_global, n/sp, d]

Each device then runs ordinary (flash) attention over the FULL sequence
for its own head subset — no per-step collectives, one stacked
all-to-all in (q/k/v together), one out — and memory is
O(s_global·d·n/sp).  The trade (DeepSpeed
Ulysses, arXiv:2309.14509): all-to-alls move O(b·s_local·n·d) per
device like the ring's total ppermute traffic, but in 2 large
transfers that overlap poorly vs the ring's ndev small ones that
overlap with compute; the ring wins when s_global·n/sp activations
don't fit, Ulysses wins at moderate lengths where the single flash
call over the full sequence beats ndev chunked calls.

Requires ``num_heads % axis_size == 0`` and equal sequence shards.
Call inside ``jax.shard_map`` with q/k/v sharded along sequence, like
:func:`ring_attention` — or let the flagship model do it:
``make_gpt_train_step(..., seq_axis="sp", context_parallel="ulysses")``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["ulysses_attention"]


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Attention over sequence-sharded [b, s_local, n, d] tensors via
    head re-sharding.  Must run inside a ``jax.shard_map`` whose mesh
    has ``axis_name``; shard i owns global positions
    [i·s_local, (i+1)·s_local)."""
    from apex_tpu.ops.flash_attention import flash_attention

    if q.ndim != 4:
        raise ValueError(f"expected [b, s_local, n, d], got {q.shape}")
    if q.shape != k.shape or k.shape != v.shape:
        raise ValueError("ulysses requires equal q/k/v shard shapes")
    sp = jax.lax.axis_size(axis_name)
    n = q.shape[2]
    if n % sp != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads ({n}) divisible by the "
            f"'{axis_name}' axis size ({sp}); use ring_attention for "
            "head counts that don't factor")

    # one stacked collective for q/k/v: [3, b, s_local, n, d] ->
    # [3, b, s_global, n/sp, d] (fewer collective launches than three)
    from apex_tpu.utils.collectives import all_to_all as _counted_a2a

    qkv = jnp.stack([q, k, v])
    qkv = _counted_a2a(qkv, axis_name, 3, 2, tiled=True)
    out = flash_attention(qkv[0], qkv[1], qkv[2], causal=causal,
                          scale=scale)
    # [b, s_global, n/sp, d] -> [b, s_local, n, d]
    return _counted_a2a(out, axis_name, 1, 2, tiled=True)
