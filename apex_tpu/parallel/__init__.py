"""apex_tpu.parallel — data parallelism, SyncBatchNorm, LARC, grad clipping.

Reference: apex/parallel/ (DistributedDataParallel, SyncBatchNorm,
convert_syncbn_model, LARC, Reducer).
"""

from apex_tpu.parallel.clip_grad import clip_grad_norm, clip_grad_norm_  # noqa: F401
from apex_tpu.parallel.distributed import (  # noqa: F401
    DistributedDataParallel,
    Reducer,
    allreduce_gradients,
    make_ddp_train_step,
)
from apex_tpu.parallel.LARC import LARC, larc  # noqa: F401
from apex_tpu.parallel.launch import (  # noqa: F401
    distributed_env,
    init_distributed,
)
from apex_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from apex_tpu.parallel.mesh import (  # noqa: F401
    create_mesh,
    data_parallel_mesh,
    replicate,
    shard_batch,
)
from apex_tpu.parallel.sync_batchnorm import (  # noqa: F401
    SyncBatchNorm,
    convert_syncbn_model,
)
