"""Device-mesh construction — the process-group layer of the framework.

The reference builds torch.distributed process groups (NCCL/RCCL) for every
parallelism axis (apex/transformer/parallel_state.py:81-310). On TPU the
entire layer is a ``jax.sharding.Mesh``: axes are named, collectives ride
ICI within an axis, and XLA inserts/overlaps the communication.

Axis naming convention used across apex_tpu (outer → inner):

    ('pp', 'dp', 'sp', 'tp')

- ``tp`` innermost so tensor-parallel collectives (every layer!) ride the
  fastest ICI links between physically adjacent chips,
- ``dp`` outer — gradient allreduce happens once per step and tolerates
  longer paths / DCN,
- ``pp`` outermost — only neighbor ppermute traffic,
- ``sp`` (sequence/context parallelism for long-context) sits between; it
  reuses the tp axis in Megatron-SP style (see transformer/tensor_parallel)
  or is its own axis for ring attention.

(The scaling-book recipe: pick the mesh, name the axes, annotate shardings,
let XLA insert collectives.)
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "create_mesh",
    "data_parallel_mesh",
    "replicate",
    "shard_batch",
]


def create_mesh(
    dp: Optional[int] = None,
    tp: int = 1,
    pp: int = 1,
    sp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ('pp','dp','sp','ep','tp') mesh over the available devices.

    ``dp=None`` absorbs whatever is left after tp/pp/sp/ep. Mirrors
    ``initialize_model_parallel``'s world-size divisibility checks
    (parallel_state.py:81-130); the ``ep`` axis carries expert
    parallelism (transformer/moe.py — beyond the reference, which has no
    MoE runtime).
    """
    devices = list(devices if devices is not None else jax.devices())
    world = len(devices)
    denom = tp * pp * sp * ep
    if world % denom != 0:
        raise ValueError(
            f"world size {world} is not divisible by tp*pp*sp*ep = {denom}"
        )
    if dp is None:
        dp = world // denom
    if dp * denom != world:
        raise ValueError(
            f"dp*tp*pp*sp*ep = {dp * denom} != world size {world}"
        )
    arr = np.asarray(devices).reshape(pp, dp, sp, ep, tp)
    return Mesh(arr, axis_names=("pp", "dp", "sp", "ep", "tp"))


def data_parallel_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """Pure data-parallel mesh (apex DDP's world)."""
    return create_mesh(tp=1, pp=1, sp=1, devices=devices)


def replicate(mesh: Mesh):
    """Sharding that replicates across every axis (params in plain DDP)."""
    return NamedSharding(mesh, PartitionSpec())


def shard_batch(mesh: Mesh, *, axis: str = "dp"):
    """Sharding that splits the leading (batch) dim across ``axis``."""
    return NamedSharding(mesh, PartitionSpec(axis))
