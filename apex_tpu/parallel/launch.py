"""Multi-host bootstrap — the ``torch.distributed.init_process_group``
analog.

The reference initializes NCCL/MPI process groups from launcher
environment variables (apex/parallel/__init__.py DDP assumes
``torch.distributed`` is initialized; the test launchers export
MASTER_ADDR/MASTER_PORT/RANK/WORLD_SIZE).  On TPU pods the runtime
equivalent is ``jax.distributed.initialize``: every host connects to a
coordinator, after which ``jax.devices()`` spans the whole pod and the
same ``Mesh``/collective code scales from 1 chip to a multi-host slice
with XLA moving data over ICI/DCN.

:func:`init_distributed` resolves the coordinator/rank/world size from
(in priority order) explicit arguments, the JAX-native variables
(``COORDINATOR_ADDRESS``, ``PROCESS_ID``, ``NUM_PROCESSES``), or the
torch-style ones the reference's launchers export (``MASTER_ADDR`` +
``MASTER_PORT``, ``RANK``/``NODE_RANK``, ``WORLD_SIZE``) — so a
torchrun-style wrapper script ports over unchanged.  On single-host
(no env, no args) it is a no-op: GKE/Cloud-TPU metadata autodetection
is left to ``jax.distributed.initialize()``'s own defaults via
``force=True``.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional

import jax

__all__ = ["init_distributed", "distributed_env"]

# Markers of managed-cluster launches where jax.distributed.initialize
# auto-detects rank/world-size itself and MASTER_ADDR may be exported
# incidentally (e.g. by a site profile) rather than by a torch launcher.
_CLUSTER_ENV_MARKERS = (
    "SLURM_JOB_ID", "SLURM_PROCID", "OMPI_COMM_WORLD_RANK",
    "PMI_RANK", "TPU_WORKER_HOSTNAMES", "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID",
)


def _in_managed_cluster(env) -> bool:
    return any(env.get(k) is not None for k in _CLUSTER_ENV_MARKERS)


def distributed_env(environ=None):
    """Resolve (coordinator, process_id, num_processes) from the
    environment; any field may come back None when unset."""
    env = os.environ if environ is None else environ

    coord = env.get("COORDINATOR_ADDRESS")
    if coord is None and env.get("MASTER_ADDR"):
        port = env.get("MASTER_PORT", "8476")
        coord = f"{env['MASTER_ADDR']}:{port}"

    # RANK (the global torchrun rank) outranks NODE_RANK: with multiple
    # processes per node only RANK is unique across the job
    pid = env.get("PROCESS_ID", env.get("RANK", env.get("NODE_RANK")))
    nproc = env.get("NUM_PROCESSES", env.get("WORLD_SIZE"))
    return (coord,
            int(pid) if pid is not None else None,
            int(nproc) if nproc is not None else None)


_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    force: bool = False,
) -> int:
    """Connect this host to the pod-wide JAX runtime; returns the number
    of participating processes (1 when single-host).

    Call once per process before any device use, exactly like the
    reference's ``init_process_group`` contract.  Safe to call again
    (no-op) and safe on single host with no launcher environment.
    ``force=True`` calls ``jax.distributed.initialize`` even without an
    explicit coordinator, letting JAX's cloud autodetection take over.
    """
    global _initialized
    if _initialized:
        return jax.process_count()

    env_coord, env_pid, env_nproc = distributed_env()
    coord = coordinator_address or env_coord
    pid = process_id if process_id is not None else env_pid
    nproc = num_processes if num_processes is not None else env_nproc

    # Single-host no-ops do NOT latch _initialized: a later call with an
    # explicit coordinator (e.g. after an early library-internal call
    # found no env) must still be able to bootstrap the pod.
    if coord is None and not force:
        if nproc is not None and nproc > 1:
            # a multi-process launch without a reachable coordinator must
            # fail loudly (the init_process_group contract) — silently
            # training 8 independent single-host jobs is the worst outcome
            raise RuntimeError(
                f"WORLD_SIZE/NUM_PROCESSES={nproc} but no coordinator "
                "address: set COORDINATOR_ADDRESS or MASTER_ADDR[:PORT], "
                "or pass coordinator_address=")
        return 1
    if nproc is not None and nproc <= 1 and not force:
        return 1
    if (coordinator_address is None and env_coord is not None
            and os.environ.get("COORDINATOR_ADDRESS") is None
            and (nproc is None or pid is None) and not force):
        # The opposite failure of the missing-coordinator case above,
        # scoped to torch-style resolution: MASTER_ADDR came from a
        # launcher that always exports RANK/WORLD_SIZE too, so their
        # absence is a broken launch — initialize(coord, None, None)
        # would hang or die with an opaque runtime error.  An explicit
        # coordinator_address= argument or COORDINATOR_ADDRESS env still
        # passes through.  On managed clusters (Slurm/MPI/Cloud TPU)
        # MASTER_ADDR is often exported incidentally by a site profile
        # while jax auto-detects rank/world-size from the cluster env —
        # there the torch-launcher inference is wrong, so warn and let
        # initialize() resolve the missing fields itself.
        if _in_managed_cluster(os.environ):
            warnings.warn(
                f"MASTER_ADDR resolved coordinator {coord!r} without "
                "WORLD_SIZE/RANK, but a managed-cluster environment "
                "(Slurm/MPI/Cloud TPU) is present; ignoring the "
                "incidental MASTER_ADDR and deferring fully to "
                "jax.distributed.initialize autodetection",
                RuntimeWarning, stacklevel=2)
            # An incidental MASTER_ADDR is untrustworthy (often
            # localhost from a site profile): drop it entirely so the
            # cluster plugin resolves the coordinator too — passing it
            # through would point every node at its own localhost.
            coord = None
        else:
            raise RuntimeError(
                f"MASTER_ADDR resolved coordinator {coord!r} but "
                f"WORLD_SIZE/RANK gave num_processes={nproc} / "
                f"process_id={pid}: a torch-style launcher exports all "
                "three; set WORLD_SIZE and RANK, or pass "
                "num_processes=/process_id=")

    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=nproc,
        process_id=pid,
    )
    _initialized = True
    return jax.process_count()
