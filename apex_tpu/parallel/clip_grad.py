"""Fused gradient clipping.

Reference: apex/contrib/clip_grad/clip_grad.py:16 ``clip_grad_norm_`` — one
``multi_tensor_l2norm`` for the global norm + one ``multi_tensor_scale`` for
the clip, instead of torch's per-tensor loop. Here: one fused tree reduce +
scale (XLA emits exactly two kernels), plus the reference's
``error_if_nonfinite`` option.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.optimizers._common import global_norm, is_float_leaf

__all__ = ["clip_grad_norm", "clip_grad_norm_"]


def clip_grad_norm(
    grads: Any,
    max_norm: float,
    norm_type: float = 2.0,
    error_if_nonfinite: bool = False,
) -> Tuple[Any, jax.Array]:
    """Returns ``(clipped_grads, total_norm)``.

    Functional version of ``clip_grad_norm_`` (in-place has no meaning on
    immutable arrays). ``error_if_nonfinite`` cannot raise under jit; it
    instead poisons the clipped grads with NaN so the overflow machinery
    (amp skip-step) catches it — the jit-compatible equivalent.
    """
    if norm_type == 2.0:
        total = global_norm(grads)
    elif norm_type == jnp.inf or norm_type == float("inf"):
        leaves = [
            jnp.max(jnp.abs(x.astype(jnp.float32)))
            for x in jax.tree_util.tree_leaves(grads)
            if is_float_leaf(x)
        ]
        total = jnp.stack(leaves).max() if leaves else jnp.zeros(())
    else:
        leaves = [
            jnp.sum(jnp.abs(x.astype(jnp.float32)) ** norm_type)
            for x in jax.tree_util.tree_leaves(grads)
            if is_float_leaf(x)
        ]
        total = (sum(leaves) if leaves else jnp.zeros(())) ** (1.0 / norm_type)

    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    if error_if_nonfinite:
        scale = jnp.where(jnp.isfinite(total), scale, jnp.nan)

    clipped = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype)
        if is_float_leaf(g) else g,
        grads,
    )
    return clipped, total


clip_grad_norm_ = clip_grad_norm
