"""Data-parallel training — the apex DDP equivalent.

Reference: apex/parallel/distributed.py — per-param backward hooks build
dtype-segregated greedy buckets on the first backward (:369-390), flatten →
NCCL allreduce on side streams (:426-470), with options for fp32 allreduce,
gradient predivision, and delayed/no-op reduction. All of that machinery
exists to overlap communication with the tail of backward.

Under XLA none of it is user code: grads carry a ``psum`` over the ``dp``
mesh axis inside the jitted step, and the latency-hiding scheduler overlaps
the collective with remaining backward compute — bucketing, streams, and
hooks are the compiler's job. What survives of the reference API:

- ``DistributedDataParallel(loss_fn, ...)``: wraps a loss so its gradients
  are averaged over ``dp`` when taken inside ``shard_map`` (drop-in for
  wrapping the model: grads arrive already-reduced, as with apex DDP).
- ``allreduce_always_fp32`` / ``gradient_predivide_factor`` /
  ``gradient_average`` keep their reference meanings (distributed.py:129
  ctor args) as dtype/scale adjustments around the psum.
- ``Reducer``: the manual "call when you want the allreduce" variant
  (distributed.py:89).
- ``make_ddp_train_step``: whole-step convenience — shard_map over the
  mesh, batch split on dp, params replicated, amp + optimizer inside.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.observability.device import compile_label
from apex_tpu.parallel.mesh import create_mesh

__all__ = [
    "DistributedDataParallel",
    "Reducer",
    "allreduce_gradients",
    "make_ddp_train_step",
]


def allreduce_gradients(
    grads: Any,
    axis_name: str = "dp",
    *,
    allreduce_always_fp32: bool = False,
    gradient_average: bool = True,
    gradient_predivide_factor: Optional[float] = None,
    grad_comm=None,
) -> Any:
    """Average (or sum) grads over a mesh axis — apex DDP's
    ``allreduce_bucket`` semantics (distributed.py:426-470) as one function.

    Must be called inside ``shard_map``/``pmap`` with ``axis_name`` bound.

    SPMD-AD note: under jax≥0.9 shard_map, grads w.r.t. replicated params
    come back *already summed* over the axis (the broadcast transpose). This
    function detects that via the value's varying-axes type and only emits a
    collective when one is still needed — so it is safe on both raw
    per-shard grads and SPMD-AD pre-summed grads. When grads were pre-summed
    the reduction already happened in the grad dtype, so
    ``allreduce_always_fp32`` only affects the post-scaling arithmetic.

    ``grad_comm`` (``"bf16"`` | ``"int8"`` | ``comm.GradCommConfig``)
    routes shard-varying leaves through ``apex_tpu.comm``'s bucketed
    block-scaled quantized collectives instead of the fp32 psum — the
    reference's ``allreduce_always_fp16`` generalized.  This stateless
    entry carries no error feedback (there is nowhere to put the
    residual between calls); for int8 training use
    ``amp.make_train_step(..., grad_comm=...)`` or
    :func:`make_ddp_train_step`, which thread the per-leaf residuals
    through the train state.  ``allreduce_always_fp32`` is moot under
    compression: the dequantized reduction is always fp32.
    """
    from apex_tpu.utils.collectives import is_varying

    if grad_comm is not None:
        from apex_tpu import comm as comm_lib

        cfg = comm_lib.resolve(grad_comm)
        if cfg is not None and cfg.compresses:
            reduced, _ = comm_lib.reduce_gradients(
                grads, axis_name, cfg,
                average=gradient_average,
                predivide=gradient_predivide_factor,
            )
            return reduced

    n = jax.lax.axis_size(axis_name)

    def red(g):
        if not (hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.inexact)):
            return g
        orig = g.dtype
        if allreduce_always_fp32:
            g = g.astype(jnp.float32)
        if gradient_predivide_factor:
            g = g / gradient_predivide_factor
        if is_varying(g, axis_name):
            g = jax.lax.psum(g, axis_name)
        if gradient_average:
            post = (
                n / gradient_predivide_factor
                if gradient_predivide_factor
                else n
            )
            g = g / post
        return g.astype(orig)

    return jax.tree_util.tree_map(red, grads)


class DistributedDataParallel:
    """Wrap a loss/apply function so gradients come back dp-reduced.

    Usage inside a shard_map'd train step::

        ddp_loss = DistributedDataParallel(loss_fn)
        grads = jax.grad(ddp_loss)(params, batch)   # already averaged

    The wrapper attaches the reduction to the *backward* only (forward is
    untouched), exactly like the reference's grad hooks.

    ``grad_comm=`` compresses the reduction (see
    :func:`allreduce_gradients`).  Compression only has bytes to save
    when the wrapped gradients are still shard-varying — under jax≥0.9
    shard_map pass ``pvary``-ed params (``utils.collectives.pvary``) so
    SPMD-AD does not pre-reduce them at fp32; grads w.r.t. replicated
    params fall back to the plain division either way.
    """

    def __init__(
        self,
        fn: Callable,
        axis_name: str = "dp",
        allreduce_always_fp32: bool = False,
        gradient_average: bool = True,
        gradient_predivide_factor: Optional[float] = None,
        grad_comm=None,
    ):
        self.fn = fn
        self.axis_name = axis_name
        self.opts = dict(
            allreduce_always_fp32=allreduce_always_fp32,
            gradient_average=gradient_average,
            gradient_predivide_factor=gradient_predivide_factor,
            grad_comm=grad_comm,
        )

        @jax.custom_vjp
        def wrapped(params, batch):
            return fn(params, *batch)

        def fwd(params, batch):
            out, vjp = jax.vjp(lambda p: fn(p, *batch), params)
            return out, vjp

        def bwd(vjp, g):
            (dparams,) = vjp(g)
            dparams = allreduce_gradients(dparams, self.axis_name, **self.opts)
            return (dparams, None)

        wrapped.defvjp(fwd, bwd)
        self._wrapped = wrapped

    def __call__(self, params, *batch):
        return self._wrapped(params, tuple(batch))


class Reducer:
    """Manual-reduction variant (reference ``Reducer``, distributed.py:89):
    call ``reduce(grads)`` yourself when accumulation is done.  All
    :func:`allreduce_gradients` options pass through, including
    ``grad_comm=`` for compressed wire dtypes."""

    def __init__(self, axis_name: str = "dp", **opts):
        self.axis_name = axis_name
        self.opts = opts

    def reduce(self, grads):
        return allreduce_gradients(grads, self.axis_name, **self.opts)


def make_ddp_train_step(
    loss_fn: Callable,
    optimizer,
    policy_or_amp="O0",
    mesh: Optional[Mesh] = None,
    *,
    batch_axes: int = 1,
    grad_comm=None,
    **ddp_opts,
):
    """Whole-step DDP: amp train step shard_mapped over the dp axis.

    Returns ``(init_fn, step_fn)``; ``step_fn(state, *batch)`` expects each
    batch array's leading dim divisible by the dp size. Params/state are
    replicated, the batch is split, grads pmean over 'dp', the found-inf
    flag combines across shards (transformer/amp/grad_scaler.py analog).

    ``grad_comm="bf16"`` / ``"int8"`` (or a ``comm.GradCommConfig``)
    compresses the gradient reduction (``amp.make_train_step``'s
    ``grad_comm``).  When the config carries error feedback (int8
    default), this wrapper owns the residual plumbing: the train
    state's ``comm_state`` is expanded to one rank-local fp32 residual
    per 'dp' shard and sharded ``P('dp')`` through the shard_map, so
    each rank's quantization error cancels across its own steps.
    """
    from apex_tpu import amp as amp_lib

    if mesh is None:
        mesh = create_mesh()
    init_fn, step = amp_lib.make_train_step(
        loss_fn, optimizer, policy_or_amp, axis_name="dp",
        grad_comm=grad_comm,
    )
    ndev = dict(zip(mesh.axis_names, mesh.devices.shape))["dp"]

    def init(params):
        state = init_fn(params)
        if getattr(state, "comm_state", None):
            from jax.sharding import NamedSharding

            from apex_tpu import comm as comm_lib

            # create the [ndev, ...] residuals directly P('dp')-sharded:
            # an unsharded expand would commit the full grad-sized zeros
            # tree to one device before the first step reshards it
            shard = NamedSharding(mesh, P("dp"))
            state = state._replace(comm_state=tuple(
                jax.device_put(r, shard)
                for r in comm_lib.expand_error_state(
                    state.comm_state, ndev)))
        return state

    def sharded_step(state, *batch):
        new_state, metrics = step(state, *batch)
        metrics = {
            k: (jax.lax.pmean(v, "dp")
                if jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact)
                else v)
            for k, v in metrics.items()
        }
        return new_state, metrics

    def outer_step(state, *batch):
        # per-leaf state specs: everything replicated except the
        # rank-local error-feedback residuals, which split their
        # leading rank axis over 'dp'
        state_spec = jax.tree_util.tree_map(lambda _: P(), state)
        comm_state = getattr(state, "comm_state", None)
        if comm_state:
            from apex_tpu import comm as comm_lib

            state_spec = state_spec._replace(
                comm_state=comm_lib.error_state_spec(comm_state, "dp"))
        fn = jax.shard_map(
            sharded_step,
            mesh=mesh,
            in_specs=(state_spec, *([P("dp")] * batch_axes)),
            out_specs=(state_spec, P()),
        )
        return fn(state, *batch)

    jitted = jax.jit(outer_step)

    def labeled_step(state, *batch):
        # attribute (re)compiles of the whole sharded step to one name
        # in the recompile tracker: steady-state DDP training should
        # land exactly one compile on `compile.ddp_step.*` — a second
        # is a silent retrace (a shape/dtype wobble in the batch or a
        # state spec change), the regression the tracker exists to name
        with compile_label("ddp_step"):
            return jitted(state, *batch)

    return init, labeled_step
