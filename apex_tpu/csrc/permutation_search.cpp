// Native permutation-search kernels for ASP 2:4 sparsity.
//
// Reference: apex/contrib/sparsity/permutation_search_kernels/CUDA_kernels/
// permutation_search_kernels.cu — the reference accelerates the
// magnitude-retention scoring of candidate channel permutations with CUDA
// kernels; the search itself is a host-side loop.  On TPU the search stays
// on host (it runs once, offline — SURVEY.md §2.4), so the native analog is
// a C++ core hot loop called through ctypes, with the vectorized-numpy
// implementation as the portable fallback (apex_tpu/contrib/sparsity/
// permutation_native.py picks whichever is available).
//
// Build (done lazily by permutation_native.py, cached next to this file):
//   g++ -O3 -shared -fPIC -o libpermsearch.so permutation_search.cpp
//
// Exported C ABI:
//   ps_sum_after_2_to_4(mat, rows, cols)            -> double
//   ps_score_permutations(mat, rows, cols, perms, n_perms, out_scores)
//   ps_try_swap_improvement(mat, rows, cols, a, b)  -> double

#include <algorithm>
#include <cmath>
#include <cstdint>

namespace {

// Sum of the two largest of |v0..v3| — the magnitude a 2:4 mask keeps
// from one group of 4.
inline double top2_abs(float v0, float v1, float v2, float v3) {
    float a = std::fabs(v0), b = std::fabs(v1);
    float c = std::fabs(v2), d = std::fabs(v3);
    float lo_ab = std::min(a, b), hi_ab = std::max(a, b);
    float lo_cd = std::min(c, d), hi_cd = std::max(c, d);
    float hi1 = std::max(hi_ab, hi_cd);
    // second largest overall: the loser pair-maximum competes with the
    // winner pair's minimum
    float second = (hi_ab >= hi_cd)
        ? std::max(lo_ab, hi_cd)
        : std::max(lo_cd, hi_ab);
    return (double)hi1 + second;
}

}  // namespace

extern "C" {

// Retained magnitude if 2:4 pruning were applied (reference
// permutation_utilities.py sum_after_2_to_4; trailing columns that do
// not fill a group of 4 are ignored, matching the Python port).
double ps_sum_after_2_to_4(const float* mat, int64_t rows, int64_t cols) {
    const int64_t groups = cols / 4;
    double total = 0.0;
    for (int64_t r = 0; r < rows; ++r) {
        const float* row = mat + r * cols;
        for (int64_t g = 0; g < groups; ++g) {
            total += top2_abs(row[4 * g], row[4 * g + 1],
                              row[4 * g + 2], row[4 * g + 3]);
        }
    }
    return total;
}

// Score a batch of candidate column permutations:
// out_scores[p] = retained magnitude of mat[:, perms[p*cols .. +cols]].
void ps_score_permutations(const float* mat, int64_t rows, int64_t cols,
                           const int32_t* perms, int64_t n_perms,
                           double* out_scores) {
    const int64_t groups = cols / 4;
    for (int64_t p = 0; p < n_perms; ++p) {
        const int32_t* perm = perms + p * cols;
        double total = 0.0;
        for (int64_t r = 0; r < rows; ++r) {
            const float* row = mat + r * cols;
            for (int64_t g = 0; g < groups; ++g) {
                total += top2_abs(row[perm[4 * g]], row[perm[4 * g + 1]],
                                  row[perm[4 * g + 2]],
                                  row[perm[4 * g + 3]]);
            }
        }
        out_scores[p] = total;
    }
}

// Improvement in retained magnitude from swapping columns a and b; only
// the two affected stripes are rescored (reference try_swap).
double ps_try_swap_improvement(const float* mat, int64_t rows,
                               int64_t cols, int64_t a, int64_t b) {
    const int64_t ga = a / 4, gb = b / 4;
    if (ga == gb) return 0.0;
    double before = 0.0, after = 0.0;
    for (int64_t r = 0; r < rows; ++r) {
        const float* row = mat + r * cols;
        float va[4], vb[4];
        for (int k = 0; k < 4; ++k) {
            va[k] = row[4 * ga + k];
            vb[k] = row[4 * gb + k];
        }
        before += top2_abs(va[0], va[1], va[2], va[3])
                + top2_abs(vb[0], vb[1], vb[2], vb[3]);
        va[a % 4] = row[b];
        vb[b % 4] = row[a];
        after += top2_abs(va[0], va[1], va[2], va[3])
               + top2_abs(vb[0], vb[1], vb[2], vb[3]);
    }
    return after - before;
}

}  // extern "C"
