"""apex_tpu — a TPU-native framework with the capabilities of ROCm/apex.

Built from scratch on JAX/XLA/Pallas. The reference (ROCm/apex, see SURVEY.md)
is a library of (a) an automatic mixed-precision engine, (b) fused kernels
exposed as drop-in modules/optimizers, (c) data-parallel wrappers + SyncBN, and
(d) a Megatron-style tensor/pipeline-parallel toolkit. apex_tpu provides the
same capability surface, re-designed TPU-first:

- ``apex_tpu.amp``         — mixed-precision policies O0–O5 (fp16/bf16), fp32
  master weights, *device-side* dynamic loss scaling (no host syncs).
  (reference: apex/amp/frontend.py, apex/amp/scaler.py)
- ``apex_tpu.optimizers``  — fused multi-tensor optimizers (Adam, LAMB, SGD,
  NovoGrad, Adagrad, LARS, MixedPrecisionLamb) as jit-fused updates.
  (reference: apex/optimizers/*, csrc/multi_tensor_*.cu)
- ``apex_tpu.ops``         — the fused op library (LayerNorm/RMSNorm, scaled
  masked softmax family, RoPE, bias+SwiGLU, xentropy, dense/MLP, attention)
  with Pallas TPU kernels + custom_vjp and pure-XLA references.
  (reference: csrc/, apex/contrib/csrc/)
- ``apex_tpu.parallel``    — data parallelism (grad psum over a mesh axis),
  SyncBatchNorm, LARC, fused grad clipping.
  (reference: apex/parallel/)
- ``apex_tpu.transformer`` — tensor/sequence/pipeline parallelism over
  ``jax.sharding.Mesh`` axes with XLA collectives.
  (reference: apex/transformer/)
- ``apex_tpu.contrib``     — xentropy, focal loss, transducer, index_mul_2d,
  sparsity (ASP), ZeRO-style distributed optimizers, peer halo exchange.
  (reference: apex/contrib/)
- ``apex_tpu.models``      — standalone GPT/BERT/ResNet used by tests+bench.
  (reference: apex/transformer/testing/standalone_transformer_lm.py,
  examples/imagenet)
"""

__version__ = "0.1.0"

from apex_tpu.utils.logging import get_logger  # noqa: F401

# Light-weight subpackages are imported eagerly; heavyweight ones lazily via
# attribute access (mirrors the reference's compatibility/ lazy-import shims,
# compatibility/amp_C.py:4-37, without the JIT-build machinery TPUs don't need).
_LAZY_SUBMODULES = (
    "amp",
    "checkpoint",
    "comm",
    "optimizers",
    "ops",
    "parallel",
    "transformer",
    "contrib",
    "models",
    "multi_tensor",
    "fp16_utils",
    "normalization",
    "fused_dense",
    "mlp",
    "RNN",
    "testing",
    "utils",
)


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        try:
            mod = importlib.import_module(f"apex_tpu.{name}")
        except ModuleNotFoundError as e:
            # Translate only "this submodule doesn't exist (yet)" so
            # hasattr()/dir() probes don't crash; missing *dependencies*
            # inside an existing submodule still surface as-is.
            if e.name == f"apex_tpu.{name}":
                raise AttributeError(
                    f"module 'apex_tpu' has no attribute {name!r}"
                ) from None
            raise
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'apex_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals().keys()) + list(_LAZY_SUBMODULES))
