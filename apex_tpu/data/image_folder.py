"""ImageFolder dataset + threaded loader over the Megatron samplers.

Reference: examples/imagenet/main_amp.py:188-218 builds
``torchvision.datasets.ImageFolder`` train/val datasets with
RandomResizedCrop/flip transforms and feeds them through torch
DataLoaders into the ``data_prefetcher``.  The torch-free TPU analog:

- :class:`ImageFolderDataset` — same on-disk contract (one subdirectory
  per class, sorted subdir names become contiguous class ids), PIL
  decode, random-resized-crop + horizontal flip for train / resize +
  center-crop for eval, ImageNet mean/std normalization, NHWC float32
  (the channels-last layout the conv stack wants on TPU).
- :func:`make_image_loader` — drives a
  :class:`~apex_tpu.transformer._data._batchsampler.MegatronPretraining\
RandomSampler` (or the sequential variant) over the dataset with a
  thread pool doing the decodes (PIL releases the GIL around I/O and
  codec work), yielding stacked ``(images, labels)`` numpy batches ready
  for the example's device prefetcher.  Resumability comes from the
  sampler's ``consumed_samples`` contract, exactly like Megatron.

Determinism: every ``__getitem__`` draws its augmentation randomness
from a private RandomState seeded by ``(seed, index, per-index visit
count)`` — thread-interleaving inside the loader pool cannot change the
crops, and repeated epochs still see fresh augmentations.

The decode path stays uint8 end-to-end (decode → crop → resize) and
normalizes to float32 exactly once; float ``.npy`` inputs keep full
precision through a per-channel float resize.
"""

from __future__ import annotations

import hashlib
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, Tuple

import numpy as np

__all__ = ["ImageFolderDataset", "make_image_loader"]

_MEAN = np.array([0.485, 0.456, 0.406], np.float32)   # main_amp.py:200
_STD = np.array([0.229, 0.224, 0.225], np.float32)

_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp", ".npy")


def _resize(img: np.ndarray, size_wh) -> np.ndarray:
    """Bilinear resize preserving dtype: uint8 via PIL RGB, float via
    per-channel PIL 'F' mode (no 8-bit quantization of float inputs)."""
    from PIL import Image

    if img.dtype == np.uint8:
        return np.asarray(
            Image.fromarray(img).resize(size_wh, Image.BILINEAR))
    # mode="F" reinterprets the buffer as float32 — convert first or
    # float64 inputs resize to garbage
    chans = [np.asarray(
        Image.fromarray(img[..., c].astype(np.float32), mode="F").resize(
            size_wh, Image.BILINEAR)) for c in range(img.shape[-1])]
    return np.stack(chans, axis=-1)


class ImageFolderDataset:
    """``root/<class>/<image>`` tree → (image [H,W,3] f32 NHWC, label).

    ``train=True`` applies random-resized-crop (scale 0.08–1.0) and
    horizontal flip (transforms.RandomResizedCrop/RandomHorizontalFlip,
    main_amp.py:196-199); eval resizes the short side to
    ``image_size * 256 // 224`` and center-crops (:207-209).  ``.npy``
    files (H, W, 3 uint8 or float arrays) are accepted alongside images
    so tests and preprocessed datasets skip the codec.
    """

    def __init__(self, root: str, image_size: int = 224,
                 train: bool = True, seed: int = 0):
        self.root = root
        self.image_size = image_size
        self.train = train
        self.seed = seed
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise ValueError(f"no class subdirectories under {root!r}")
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples: list = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith(_EXTS):
                    self.samples.append(
                        (os.path.join(cdir, fn), self.class_to_idx[c]))
        if not self.samples:
            raise ValueError(f"no images found under {root!r}")
        self._visit_lock = threading.Lock()
        self._visits: dict = {}       # guarded-by: self._visit_lock

    def __len__(self) -> int:
        return len(self.samples)

    def _sample_rng(self, idx: int) -> np.random.RandomState:
        """Private per-call RandomState: deterministic under any thread
        interleaving (seeded by (seed, idx, visit#)), fresh each epoch."""
        with self._visit_lock:
            visit = self._visits.get(idx, 0)
            self._visits[idx] = visit + 1
        mix = int.from_bytes(
            hashlib.blake2s(
                f"{self.seed}/{idx}/{visit}".encode()).digest()[:4],
            "little")
        return np.random.RandomState(mix)

    def _decode(self, path: str) -> np.ndarray:
        if path.lower().endswith(".npy"):
            return np.load(path)
        from PIL import Image

        with Image.open(path) as im:
            return np.asarray(im.convert("RGB"))    # uint8 HWC

    def _train_crop(self, img: np.ndarray,
                    rng: np.random.RandomState) -> np.ndarray:
        """RandomResizedCrop(size, scale=(0.08, 1.0)) + flip."""
        h, w = img.shape[:2]
        size = self.image_size
        area = h * w
        for attempt in range(11):
            if attempt == 10:
                # torchvision fallback: center-crop at the clamped
                # aspect ratio instead of squashing the whole image
                in_ratio = w / h
                if in_ratio < 3 / 4:
                    cw, ch = w, min(h, int(round(w / (3 / 4))))
                elif in_ratio > 4 / 3:
                    cw, ch = min(w, int(round(h * (4 / 3)))), h
                else:
                    cw, ch = w, h
                y0 = (h - ch) // 2
                x0 = (w - cw) // 2
                img = img[y0:y0 + ch, x0:x0 + cw]
                break
            target = area * rng.uniform(0.08, 1.0)
            ratio = np.exp(rng.uniform(np.log(3 / 4), np.log(4 / 3)))
            cw = int(round(np.sqrt(target * ratio)))
            ch = int(round(np.sqrt(target / ratio)))
            if cw <= w and ch <= h:
                y0 = rng.randint(0, h - ch + 1)
                x0 = rng.randint(0, w - cw + 1)
                img = img[y0:y0 + ch, x0:x0 + cw]
                break
        out = _resize(img, (size, size))
        if rng.rand() < 0.5:
            out = out[:, ::-1]
        return out

    def _eval_crop(self, img: np.ndarray) -> np.ndarray:
        size = self.image_size
        short = size * 256 // 224
        h, w = img.shape[:2]
        scale = short / min(h, w)
        nh, nw = int(round(h * scale)), int(round(w * scale))
        img = _resize(img, (nw, nh))
        y0 = (nh - size) // 2
        x0 = (nw - size) // 2
        return img[y0:y0 + size, x0:x0 + size]

    def __getitem__(self, idx: int) -> Tuple[np.ndarray, int]:
        path, label = self.samples[idx]
        img = self._decode(path)
        if img.ndim == 2:
            img = np.stack([img] * 3, axis=-1)
        was_uint8 = img.dtype == np.uint8
        if self.train:
            img = self._train_crop(img, self._sample_rng(idx))
        else:
            img = self._eval_crop(img)
        # single dtype conversion + normalization at the very end;
        # float .npy inputs are expected in [0, 1] already
        img = img.astype(np.float32)
        if was_uint8:
            img = img / 255.0
        img = (img - _MEAN) / _STD
        return np.ascontiguousarray(img, np.float32), label


def make_image_loader(
    dataset: ImageFolderDataset,
    sampler,
    num_workers: int = 4,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Yield ``(images [b,H,W,3] f32, labels [b] i32)`` batches for the
    index batches the Megatron sampler emits.

    The sampler owns ordering, data-parallel bucketing, and resume
    (``consumed_samples``); this loader owns decode + collate, with a
    thread pool overlapping the per-image work (the torch DataLoader
    ``workers`` analog, main_amp.py:214).
    """
    pool = ThreadPoolExecutor(max_workers=max(1, num_workers))
    try:
        for batch_idx in sampler:
            items = list(pool.map(dataset.__getitem__, batch_idx))
            images = np.stack([im for im, _ in items])
            labels = np.asarray([lb for _, lb in items], np.int32)
            yield images, labels
    finally:
        pool.shutdown(wait=False)
