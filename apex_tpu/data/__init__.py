"""File-backed input pipelines (the examples/imagenet loader analog)."""

from apex_tpu.data.image_folder import ImageFolderDataset, make_image_loader
from apex_tpu.data.prefetch import device_prefetch

__all__ = ["ImageFolderDataset", "make_image_loader", "device_prefetch"]
