"""Device prefetch: overlap host→device transfer with compute.

The reference's input pipeline hides H2D copies behind compute with
pinned-memory + a side CUDA stream (examples/imagenet/main_amp.py
``data_prefetcher``: ``cuda.Stream`` + ``record_stream``).  The TPU
analog needs no stream juggling: ``jax.device_put`` is asynchronous, so
keeping a small deque of already-transferred batches ahead of the
consumer gives the same overlap — the transfer of batch ``i+k`` rides
under the step computation of batch ``i``.

Passing ``sharding=`` (e.g. ``NamedSharding(mesh, P('dp'))``) places
each batch over the mesh for single-process data parallelism.  On a
multi-process (multi-host) deployment each process holds only its local
batch shard: build the global array with
``jax.make_array_from_process_local_data`` in the loader before handing
batches to this prefetcher, and leave ``sharding=None`` here.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Optional

import jax

__all__ = ["device_prefetch"]


def device_prefetch(
    batches: Iterable,
    size: int = 2,
    sharding: Optional[jax.sharding.Sharding] = None,
) -> Iterator:
    """Yield batches already resident on device, ``size`` ahead.

    ``batches`` yields pytrees of host arrays (e.g. ``(images, labels)``
    from :func:`apex_tpu.data.make_image_loader`).  Each is moved with
    ``jax.device_put`` (async) as soon as a slot frees up, so the copy
    of the next batch overlaps the caller's compute on the current one —
    the ``data_prefetcher`` contract without streams.

    With ``sharding`` (e.g. ``NamedSharding(mesh, P('dp'))``) every
    batch is placed as a sharded global array instead of a single-device
    one.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")

    def _put(batch):
        # device_put handles pytrees natively and batches the transfers
        return jax.device_put(batch, sharding)

    queue = collections.deque()
    it = iter(batches)
    try:
        while True:
            while len(queue) < size:
                queue.append(_put(next(it)))
            yield queue.popleft()
    except StopIteration:
        while queue:
            yield queue.popleft()
