"""Device prefetch: overlap host batch prep + H2D transfer with compute.

The reference's input pipeline hides H2D copies behind compute with
pinned-memory + a side CUDA stream (examples/imagenet/main_amp.py
``data_prefetcher``: ``cuda.Stream`` + ``record_stream``).  The TPU
analog needs no stream juggling: a background thread pulls the next
batches from the host iterator (decode/collate run off the consumer
thread) and ``jax.device_put``s them into a bounded queue — the
transfer of batch ``i+k`` and its host prep both ride under the step
computation of batch ``i``.  A sentinel marks exhaustion and pipeline
exceptions are re-raised in the consumer, so finite iterators end the
epoch instead of hanging.

Passing ``sharding=`` (e.g. ``NamedSharding(mesh, P('dp'))``) places
each batch over the mesh for single-process data parallelism.  On a
multi-process (multi-host) deployment each process holds only its local
batch shard: build the global array with
``jax.make_array_from_process_local_data`` in the loader before handing
batches to this prefetcher, and leave ``sharding=None`` here.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, Optional

import jax

__all__ = ["device_prefetch"]

_DONE = object()


def device_prefetch(
    batches: Iterable,
    size: int = 2,
    sharding: Optional[jax.sharding.Sharding] = None,
) -> Iterator:
    """Yield batches already resident on device, up to ``size`` ahead.

    ``batches`` yields pytrees of host arrays (e.g. ``(images, labels)``
    from :func:`apex_tpu.data.make_image_loader`).  A daemon producer
    thread iterates it and moves each batch with ``jax.device_put``
    (pytree-aware, async), so both the host-side prep and the copy of
    the next batch overlap the caller's compute on the current one —
    the ``data_prefetcher`` contract without streams.  Producer
    exceptions propagate to the consumer; exhaustion ends the iterator.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")

    q: "queue.Queue" = queue.Queue(maxsize=size)   # guarded-by: queue
    stop = threading.Event()                       # guarded-by: event

    def _put(item) -> bool:
        # Bounded put that re-checks the stop flag: an abandoned consumer
        # (break / exception / GC) would otherwise leave this thread
        # blocked on a full queue forever, pinning size+1 device batches.
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for batch in batches:
                if stop.is_set() or not _put(jax.device_put(batch, sharding)):
                    return
            _put(_DONE)
        except BaseException as e:  # surface pipeline errors downstream
            _put(e)

    producer = threading.Thread(target=worker, daemon=True,
                                name="apex-tpu-prefetch")
    producer.start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # Runs on exhaustion, consumer exception, and GeneratorExit alike:
        # release the producer, drop queued device batches, then reap
        # the thread — an abandoned consumer (break mid-epoch) must not
        # leave a producer pinned behind a full queue (it re-checks
        # `stop` every 0.1s, so the join bounds at one poll interval).
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break
        producer.join(timeout=5.0)
