"""Batched elementwise ops over whole tensor lists.

Reference: ``multi_tensor_applier`` (apex/multi_tensor_apply/
multi_tensor_apply.py:3-30) dispatching CUDA kernels that pack up to 110
tensor pointers per launch (csrc/multi_tensor_apply.cuh:19-26,44-136) with a
shared ``noop_flag`` that aborts the whole launch when any value is
non-finite.

On TPU there are no kernel launches to batch: everything lives in one jitted
graph and XLA fuses elementwise chains across the whole list. What survives
is the *semantics*:

- one call covers an arbitrary list/pytree of tensors,
- a single device-side overflow flag covers the whole list
  (``noop_flag``-compatible: 1 ⇒ at least one non-finite value seen),
- ``multi_tensor_scale`` honors an incoming flag by no-op'ing (the CUDA
  kernel stops copying once the flag is set).

These are the building blocks of the LossScaler and every fused optimizer,
exactly as ``amp_C`` is in the reference (csrc/amp_C_frontend.cpp:193-226).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "multi_tensor_scale",
    "multi_tensor_axpby",
    "multi_tensor_l2norm",
    "MultiTensorApply",
    "multi_tensor_applier",
]


def _nonfinite_flag(tensors: Sequence[jax.Array]) -> jax.Array:
    """int32 0/1 flag — 1 iff any element of any tensor is non-finite."""
    if not tensors:
        return jnp.zeros((), jnp.int32)
    flags = [jnp.any(~jnp.isfinite(t.astype(jnp.float32))) for t in tensors]
    return jnp.stack(flags).any().astype(jnp.int32)


def multi_tensor_scale(
    srcs: Sequence[jax.Array],
    scale,
    noop_flag: Optional[jax.Array] = None,
    out_dtypes: Optional[Sequence[Any]] = None,
) -> Tuple[List[jax.Array], jax.Array]:
    """``out[i] = src[i] * scale`` with overflow detection.

    Reference kernel: csrc/multi_tensor_scale_kernel.cu (used for loss
    unscaling and fp16↔fp32 master-grad copies). Returns ``(outs, flag)``;
    when an incoming ``noop_flag`` is already set, outputs pass through
    unscaled (kernel's early-exit semantics).
    """
    srcs = list(srcs)
    flag = _nonfinite_flag(srcs)
    if noop_flag is not None:
        flag = jnp.maximum(flag, noop_flag.astype(jnp.int32))
    out_dtypes = out_dtypes or [t.dtype for t in srcs]
    outs = []
    for t, dt in zip(srcs, out_dtypes):
        scaled = (t.astype(jnp.float32) * scale).astype(dt)
        if noop_flag is not None:
            scaled = jnp.where(noop_flag.astype(bool), t.astype(dt), scaled)
        outs.append(scaled)
    return outs, flag


def multi_tensor_axpby(
    xs: Sequence[jax.Array],
    ys: Sequence[jax.Array],
    a,
    b,
    out_dtypes: Optional[Sequence[Any]] = None,
) -> Tuple[List[jax.Array], jax.Array]:
    """``out[i] = a*x[i] + b*y[i]`` (csrc/multi_tensor_axpby_kernel.cu).

    Used by apex DDP's fp32 allreduce path and scaler add-with-scale.
    """
    xs, ys = list(xs), list(ys)
    flag = jnp.maximum(_nonfinite_flag(xs), _nonfinite_flag(ys))
    out_dtypes = out_dtypes or [t.dtype for t in xs]
    outs = [
        (a * x.astype(jnp.float32) + b * y.astype(jnp.float32)).astype(dt)
        for x, y, dt in zip(xs, ys, out_dtypes)
    ]
    return outs, flag


def multi_tensor_l2norm(
    tensors: Sequence[jax.Array], per_tensor: bool = False
) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Global (and optionally per-tensor) L2 norm over a tensor list.

    Reference kernel: csrc/multi_tensor_l2norm_kernel.cu — feeds FusedLAMB's
    two-phase update and fused ``clip_grad_norm_``.
    """
    tensors = list(tensors)
    if not tensors:
        z = jnp.zeros((), jnp.float32)
        return z, (jnp.zeros((0,), jnp.float32) if per_tensor else None)
    sq = jnp.stack(
        [jnp.sum(jnp.square(t.astype(jnp.float32))) for t in tensors]
    )
    total = jnp.sqrt(jnp.sum(sq))
    return total, (jnp.sqrt(sq) if per_tensor else None)


class MultiTensorApply:
    """API-parity shim for ``multi_tensor_applier(op, noop_flag, lists, *args)``.

    The reference signature (apex/multi_tensor_apply/multi_tensor_apply.py:3)
    takes a kernel, an int overflow buffer, and a list of tensor lists.
    ``op`` must follow the convention
    ``op(noop_flag, tensor_lists, *args) -> (out_lists, flag)`` — the
    conventional-signature kernels live on the :data:`amp_C` namespace below
    (e.g. ``multi_tensor_applier(amp_C.multi_tensor_scale, buf,
    [srcs, outs], scale)``), matching the reference's ``amp_C`` module names
    one-to-one. Being functional, results are *returned* rather than written
    into the out-list tensors; the out list contributes only output dtypes.
    """

    available = True

    def __init__(self, chunk_size: int = 2048 * 32):
        # chunk_size is meaningless on TPU (no launch batching); kept for
        # signature parity.
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag, tensor_lists, *args):
        return op(noop_flag, tensor_lists, *args)


multi_tensor_applier = MultiTensorApply()


class _AmpC:
    """Conventional-signature kernels named after the reference ``amp_C``
    module (csrc/amp_C_frontend.cpp:193-226), for one-to-one porting of
    reference call sites through :data:`multi_tensor_applier`."""

    @staticmethod
    def multi_tensor_scale(noop_flag, tensor_lists, scale):
        # reference: [srcs, outs]; outs give the output dtypes.
        srcs = tensor_lists[0]
        outs = tensor_lists[1] if len(tensor_lists) > 1 else srcs
        return multi_tensor_scale(
            srcs, scale, noop_flag, out_dtypes=[t.dtype for t in outs]
        )

    @staticmethod
    def multi_tensor_axpby(noop_flag, tensor_lists, a, b, arg_to_check=-1):
        # reference: [xs, ys, outs]; arg_to_check kept for signature parity.
        xs, ys = tensor_lists[0], tensor_lists[1]
        outs = tensor_lists[2] if len(tensor_lists) > 2 else xs
        out_lists, flag = multi_tensor_axpby(
            xs, ys, a, b, out_dtypes=[t.dtype for t in outs]
        )
        if noop_flag is not None:
            flag = jnp.maximum(flag, jnp.asarray(noop_flag, jnp.int32))
        return out_lists, flag

    @staticmethod
    def multi_tensor_l2norm(noop_flag, tensor_lists, per_tensor=False):
        return multi_tensor_l2norm(tensor_lists[0], per_tensor=per_tensor)


amp_C = _AmpC()
