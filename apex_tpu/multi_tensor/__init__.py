from apex_tpu.multi_tensor.multi_tensor_apply import (  # noqa: F401
    MultiTensorApply,
    amp_C,
    multi_tensor_applier,
    multi_tensor_axpby,
    multi_tensor_l2norm,
    multi_tensor_scale,
)
