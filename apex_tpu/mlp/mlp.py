"""Whole-MLP fused module.

Reference: apex/mlp/mlp.py (``MLP`` :11, ``mlp_function`` :33) backed by
csrc/mlp_cuda.cu — a C++ loop over layers calling GEMM + bias/activation
epilogues, so the whole MLP is two native calls. Under jit the whole Python
loop below is one XLA computation with every epilogue fused, which is the
same end state without the C++.

Activation choices mirror the reference: 'none', 'relu', 'sigmoid'
(mlp.py activation arg).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.dense import fused_dense_function

__all__ = ["MLP", "mlp_function"]

_ACTS = {
    "none": lambda x: x,
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
}


def mlp_function(x, weights, biases, activation="relu"):
    """Functional MLP: weights[i] is [in_i, out_i]; biases may be None.

    The final layer gets no activation (matches mlp_cuda fwd loop,
    csrc/mlp_cuda.cu:63-110).
    """
    if activation not in _ACTS:
        raise ValueError(f"activation must be one of {sorted(_ACTS)}")
    act = _ACTS[activation]
    h = x
    n = len(weights)
    for i, w in enumerate(weights):
        b = biases[i] if biases is not None else None
        h = fused_dense_function(h, w, b)
        if i < n - 1:
            h = act(h)
    return h


class MLP(nn.Module):
    """Drop-in for reference ``apex.mlp.MLP(mlp_sizes, bias, activation)``."""

    mlp_sizes: Sequence[int]   # [in, hidden..., out]
    bias: bool = True
    activation: str = "relu"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        sizes = list(self.mlp_sizes)
        if len(sizes) < 2:
            raise ValueError("mlp_sizes needs at least [in, out]")
        weights, biases = [], []
        for i, (d_in, d_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            weights.append(
                self.param(f"kernel_{i}", nn.initializers.lecun_normal(),
                           (d_in, d_out), jnp.float32).astype(x.dtype)
            )
            biases.append(
                self.param(f"bias_{i}", nn.initializers.zeros, (d_out,),
                           jnp.float32)
                if self.bias else None
            )
        return mlp_function(
            x, weights, biases if self.bias else None, self.activation
        )
