from apex_tpu.mlp.mlp import MLP, mlp_function  # noqa: F401
