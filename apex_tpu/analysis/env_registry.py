"""The authoritative table of ``APEX_TPU_*`` environment variables.

PR 4 established the pattern for telemetry: one registered, validated,
documented table (``observability.metrics.ENV_VARS``) with warn-by-name
on anything unknown.  This module generalizes it to the whole repo: any
``os.environ`` read of an ``APEX_TPU_*`` name must appear here (exact
name or a ``*``-suffixed family), name the module that owns its
validated parser, and point at the doc file that describes it.  The
linter enforces all three:

- APX201 (``unregistered-env-var``): an env read whose literal name is
  not in this table;
- APX202 (``undocumented-env-var``): a registered variable whose name
  does not appear in its declared doc file;
- APX203 (``env-table-sync``): the telemetry rows here must exactly
  mirror ``observability.metrics.ENV_VARS`` (statically parsed from the
  source, so this module never has to import the package).

Stdlib-only by contract (Tier-A modules run without jax).
"""

from __future__ import annotations

from typing import Dict, NamedTuple

__all__ = ["EnvVar", "ENV_REGISTRY", "lookup", "telemetry_names"]


class EnvVar(NamedTuple):
    name: str          # exact name, or a family ending in "*"
    owner: str         # module whose parser validates it
    doc: str           # repo-relative doc file that describes it
    help: str


def _v(name, owner, doc, help):
    return (name, EnvVar(name, owner, doc, help))


# One row per variable (or per dynamic family, "*"-suffixed).  Keep
# sorted by name within each group; docs/static_analysis.md renders the
# consolidated table and the docs-sync rule holds each row to its
# declared file.
ENV_REGISTRY: Dict[str, EnvVar] = dict([
    # ---- telemetry (must mirror observability.metrics.ENV_VARS) ----
    _v("APEX_TPU_TELEMETRY", "apex_tpu.observability.metrics",
       "docs/observability.md", "JSONL record-stream file"),
    _v("APEX_TPU_TELEMETRY_STDERR", "apex_tpu.observability.metrics",
       "docs/observability.md", "per-metric summary table at shutdown"),
    _v("APEX_TPU_TELEMETRY_PROFILER", "apex_tpu.observability.metrics",
       "docs/observability.md", "jax.profiler span annotations (xprof)"),
    _v("APEX_TPU_TELEMETRY_TRACE", "apex_tpu.observability.metrics",
       "docs/observability.md", "Chrome trace_events JSON timeline"),
    _v("APEX_TPU_TELEMETRY_FLIGHT", "apex_tpu.observability.metrics",
       "docs/observability.md", "flight-recorder post-mortem dump path"),
    _v("APEX_TPU_TELEMETRY_FLIGHT_STEPS", "apex_tpu.observability.metrics",
       "docs/observability.md", "flight-recorder ring size (steps)"),
    _v("APEX_TPU_TELEMETRY_DETECTORS", "apex_tpu.observability.metrics",
       "docs/observability.md", "step-boundary anomaly detectors"),
    _v("APEX_TPU_TELEMETRY_PORT", "apex_tpu.observability.metrics",
       "docs/observability.md", "serve /metrics + /healthz on this port"),
    # ---- kernel/backend routing --------------------------------------
    _v("APEX_TPU_BACKEND", "apex_tpu.utils.registry",
       "docs/static_analysis.md",
       "force the op registry's backend (pallas|xla)"),
    _v("APEX_TPU_PALLAS_INTERPRET", "apex_tpu.utils.registry",
       "docs/inference.md",
       "run Pallas kernels in interpret mode (CPU testing)"),
    _v("APEX_TPU_DISABLE_*", "apex_tpu.utils.registry",
       "docs/static_analysis.md",
       "disable one registered op by name (fall back to XLA)"),
    _v("APEX_TPU_DISABLE_NATIVE", "apex_tpu.contrib.sparsity",
       "docs/static_analysis.md",
       "sparsity permutation search: force the python path"),
    _v("APEX_TPU_FLASH_BWD", "apex_tpu.ops.flash_attention",
       "docs/static_analysis.md",
       "flash-attention backward mode (auto|fused|split)"),
    _v("APEX_TPU_FLASH_BWD_FUSED_MAX", "apex_tpu.ops.flash_attention",
       "docs/static_analysis.md",
       "auto mode's fused/split seq-length crossover (default 512)"),
    _v("APEX_TPU_FLASH_FUSED_BQ", "apex_tpu.ops.flash_attention",
       "docs/static_analysis.md",
       "fused flash backward query-block size override"),
    _v("APEX_TPU_LN_BWD", "apex_tpu.ops.layer_norm",
       "docs/static_analysis.md",
       "layer-norm backward routing (pallas|xla)"),
    _v("APEX_TPU_SOFTMAX", "apex_tpu.ops.softmax",
       "docs/static_analysis.md",
       "softmax family routing (pallas forces the kernel)"),
    _v("APEX_TPU_FUSED_SAMPLING", "apex_tpu.ops.fused_sampling",
       "docs/inference.md",
       "fused sampling kernel routing (kernel|reference|auto)"),
    _v("APEX_TPU_PAGED_ATTENTION", "apex_tpu.ops.paged_attention",
       "docs/inference.md",
       "paged-attention kernel routing (kernel|reference|auto)"),
    _v("APEX_TPU_GROUPED_MATMUL", "apex_tpu.ops.grouped_matmul",
       "docs/parallelism.md",
       "grouped (ragged expert) matmul routing (kernel|reference|auto)"),
    _v("APEX_TPU_DECODE_FUSED", "apex_tpu.ops.decode_step",
       "docs/inference.md",
       "fused decode-layer megakernel routing "
       "(kernel|reference|auto)"),
    _v("APEX_TPU_QUANT_MATMUL", "apex_tpu.ops.dense",
       "docs/inference.md",
       "weight-only int8 dense/grouped matmul routing "
       "(kernel|reference|auto)"),
    # ---- serving knobs -----------------------------------------------
    _v("APEX_TPU_CHUNK_TOKENS", "apex_tpu.serving.engine",
       "docs/serving.md",
       "chunked-prefill chunk size override (positive int; off/0 "
       "forces monolithic prefill)"),
    _v("APEX_TPU_COMPILE_CACHE", "apex_tpu.serving.compile_cache",
       "docs/serving.md",
       "persistent AOT compile-cache directory (engine default when "
       "compile_cache_dir is not passed)"),
    _v("APEX_TPU_HOST_TIER_BYTES", "apex_tpu.serving.host_tier",
       "docs/serving.md",
       "host-DRAM KV offload tier capacity (bytes, 256m/2g suffixes; "
       "off/0 disables)"),
    _v("APEX_TPU_HOST_TIER_WIRE", "apex_tpu.serving.host_tier",
       "docs/serving.md",
       "host-tier at-rest codec (raw|int8; raw keeps digest parking "
       "bitwise)"),
    _v("APEX_TPU_ADAPTER_POOL_BYTES", "apex_tpu.serving.adapter_pool",
       "docs/serving.md",
       "HBM budget for the LoRA adapter slab pool (bytes, 256m/2g "
       "suffixes; admission blocks when a request's adapter cannot "
       "fit)"),
    # ---- training / parallel knobs -----------------------------------
    _v("APEX_TPU_ALLOW_FP16", "apex_tpu.amp.policy",
       "docs/amp.md", "permit raw fp16 on TPU (default maps to bf16)"),
    _v("APEX_TPU_CP_STRICT", "apex_tpu.models.transformer_lm",
       "docs/parallelism.md",
       "context parallel: error instead of falling back"),
    _v("APEX_TPU_TERMINATION_FILE", "apex_tpu.utils.checkpoint",
       "docs/static_analysis.md",
       "AutoResume: scheduler's checkpoint-and-requeue request file"),
    # ---- probe / harness ---------------------------------------------
    _v("APEX_TPU_PROBE_TIMEOUT", "apex_tpu.utils.probe",
       "docs/static_analysis.md",
       "backend-probe subprocess timeout override (seconds)"),
    _v("APEX_TPU_PROBE_CACHE_TTL", "apex_tpu.utils.probe",
       "docs/static_analysis.md",
       "backend-probe result cache TTL (seconds)"),
    _v("APEX_TPU_SKIP_FLAKY_TEST", "apex_tpu.testing.common_utils",
       "docs/static_analysis.md",
       "skip tests marked flaky (reference-parity harness knob)"),
    _v("APEX_TPU_TEST_ON_TPU", "tests.conftest",
       "docs/static_analysis.md",
       "keep the real chip attached for the tpu-marked kernel tests"),
    _v("APEX_TPU_DRYRUN_PHASE", "__graft_entry__",
       "docs/static_analysis.md",
       "pin the dryrun gate to one parity phase"),
    _v("APEX_TPU_DRYRUN_CHILD", "__graft_entry__",
       "docs/static_analysis.md",
       "internal: marks a re-exec'd virtual-CPU dryrun child"),
    _v("APEX_TPU_DRYRUN_CACHE_DIR", "__graft_entry__",
       "docs/static_analysis.md",
       "opt-in persistent XLA compilation cache for the dryrun gate"),
])


def telemetry_names() -> tuple:
    """The registered telemetry variables (APX203 checks these against
    a static parse of ``observability.metrics.ENV_VARS``)."""
    return tuple(sorted(n for n in ENV_REGISTRY
                        if n.startswith("APEX_TPU_TELEMETRY")))


def lookup(name: str):
    """Resolve an env-var name against the table: exact match first,
    then the longest matching ``*`` family.  Returns the
    :class:`EnvVar` row or ``None`` (unregistered)."""
    hit = ENV_REGISTRY.get(name)
    if hit is not None:
        return hit
    best = None
    for key, row in ENV_REGISTRY.items():
        if key.endswith("*") and name.startswith(key[:-1]):
            if best is None or len(key) > len(best[0]):
                best = (key, row)
    return best[1] if best else None
