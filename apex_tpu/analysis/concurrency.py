"""Tier-C concurrency rules: the host control plane's thread discipline.

Tier A audits what the repo *traces*; this module audits what it
*threads*.  Nine PRs quietly grew a real host control plane — the async
checkpoint writer (:mod:`~apex_tpu.checkpoint.async_saver`), the
telemetry exporter's ``ThreadingHTTPServer``, cluster worker stdout
drains, the data-prefetch producer — all sharing lock-guarded ledgers
(the metrics registry, the :class:`~apex_tpu.serving.paged_cache.\
BlockManager`).  Their synchronization contracts lived in docstrings;
this module makes them mechanical (veScale's thesis: an eager control
plane stays consistent at scale only when its disciplines are checkable
by construction).

Rules (stdlib ``ast`` only, same Rule/fingerprint/baseline machinery as
Tier A):

- ``APX501`` unguarded-cross-thread-mutation — build a *thread-escape
  graph* from every ``threading.Thread(target=...)`` /
  ``ThreadingHTTPServer`` spawn site, compute the functions reachable
  from each thread target (same-module, transitively), and flag
  attributes **written** on both the spawning side and the thread side
  with no common ``with <lock>:`` scope.
- ``APX502`` guarded-by-discipline — a ``# guarded-by: <spec>``
  annotation on a shared attribute's defining assignment, enforced at
  every access site.  Specs:

  * ``self._lock`` (a lock expression): every access outside
    ``__init__`` must sit inside ``with <that expr>:``;
  * ``join(self._thread)``: ordering via join — spawning-side accesses
    must be in a function that joins the writer thread first;
  * ``confined(<owner>)``: single-thread confinement — the attribute
    must be unreachable from any thread target in the module;
  * ``queue`` / ``event`` / ``deque`` / ``lock`` / ``local``: the
    object's own synchronization — the annotated initializer must
    construct that thread-safe type.

- ``APX503`` lock-order — a repo-level acquisition-order graph (lexical
  ``with`` nesting plus one level of same-module call propagation);
  any cycle is a potential deadlock.

Honest limits (documented in docs/static_analysis.md): the escape graph
is per-module (a thread target calling an *imported* helper is not
followed), thread targets must be resolvable names (``self.method``, a
local ``def``, a handler class, or an alias bound via ``x = self``),
``__init__`` writes are treated as happens-before the spawn, and
accesses through receivers other than ``self`` (``m.value`` from a
registry loop) are out of scope.  APX501 checks *write/write* races;
annotate read-heavy shared state with ``guarded-by`` so APX502 covers
the reads.

Stdlib-only by contract: no jax, no apex_tpu imports beyond the sibling
analysis modules.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from apex_tpu.analysis.rules import Finding, ModuleInfo, Rule

__all__ = [
    "CONCURRENCY_RULES",
    "GuardSpec",
    "ThreadModel",
    "parse_guard_spec",
    "thread_model",
]


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


# Constructors whose instances carry their own synchronization: an
# attribute initialized to one of these is a handoff object, not shared
# mutable state (queue.Queue puts are the sync; deque append/popleft
# are atomic; Event set/is_set are the flag protocol).
SAFE_TYPE_KEYWORDS: Dict[str, Tuple[str, ...]] = {
    "queue": ("Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"),
    "event": ("Event",),
    "deque": ("deque",),
    "lock": ("Lock", "RLock", "Condition", "Semaphore",
             "BoundedSemaphore", "Barrier"),
    "local": ("local",),
}
_SAFE_CONSTRUCTORS = frozenset(
    t for ts in SAFE_TYPE_KEYWORDS.values() for t in ts)

# method calls that mutate their receiver (so `self._outbox.append(x)`
# counts as a WRITE of _outbox for the escape analysis)
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "update", "setdefault", "add", "discard",
    "popitem", "sort", "reverse", "put", "put_nowait",
})

_SERVER_TYPES = frozenset({
    "ThreadingHTTPServer", "HTTPServer", "ThreadingTCPServer",
    "TCPServer", "ThreadingUDPServer", "UDPServer",
})


# ---------------------------------------------------------------------------
# guarded-by annotations
# ---------------------------------------------------------------------------

_GUARD_RE = re.compile(r"guarded-by:\s*(.+?)\s*$")


def _comments(mod: ModuleInfo) -> Dict[int, str]:
    """lineno -> comment text, via the real tokenizer — a
    ``guarded-by:`` inside a *string literal* (this module's own rule
    descriptions, docstrings quoting the convention) must never parse
    as an annotation."""
    cached = getattr(mod, "_comment_lines_cache", None)
    if cached is not None:
        return cached
    import io
    import tokenize

    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(
                io.StringIO(mod.source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError,
            SyntaxError):   # pragma: no cover — ast.parse ran already
        pass
    mod._comment_lines_cache = out
    return out


@dataclasses.dataclass(frozen=True)
class GuardSpec:
    """One parsed ``# guarded-by:`` annotation."""

    form: str       # "lock" | "join" | "confined" | "safe-type" | "bad"
    value: str      # lock expr / joined thread expr / owner label / kind
    raw: str


def parse_guard_spec(comment_tail: str) -> GuardSpec:
    """Parse the text after ``guarded-by:`` — the first token decides
    the form; trailing prose is allowed and ignored."""
    raw = comment_tail.strip()
    token = raw.split()[0] if raw.split() else ""
    m = re.match(r"(join|confined)\(([^)]*)\)$", token)
    if m:
        return GuardSpec(form=m.group(1), value=m.group(2).strip(),
                         raw=raw)
    if token in SAFE_TYPE_KEYWORDS:
        return GuardSpec(form="safe-type", value=token, raw=raw)
    # a lock expression: a dotted python name like self._lock /
    # _global_lock / self._reg._lock
    if token and re.match(r"[A-Za-z_][\w.]*$", token):
        return GuardSpec(form="lock", value=token, raw=raw)
    return GuardSpec(form="bad", value=token, raw=raw)


def _guard_annotation(mod: ModuleInfo, node: ast.AST) -> Optional[str]:
    """The annotation comment on the statement's first or last line
    (a wrapped assignment may carry it on either)."""
    comments = _comments(mod)
    for lineno in {node.lineno, getattr(node, "end_lineno", None)
                   or node.lineno}:
        comment = comments.get(lineno)
        if comment:
            m = _GUARD_RE.search(comment)
            if m:
                return m.group(1)
    return None


# ---------------------------------------------------------------------------
# module index: functions, classes, calls, self-aliases
# ---------------------------------------------------------------------------


class _Index(ast.NodeVisitor):
    """Qualified-name index of one module: function nodes, their
    enclosing class, the dotted callees each invokes, and ``x = self``
    aliases (the exporter's handler-closure idiom)."""

    def __init__(self):
        self.funcs: Dict[str, ast.AST] = {}
        self.parents: Dict[str, Optional[str]] = {}
        self.class_of: Dict[str, Optional[str]] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.calls: Dict[str, Set[str]] = {}
        self.self_aliases: Dict[str, Set[str]] = {}   # func -> names
        self._stack: List[Tuple[str, str]] = []       # (name, kind)

    def _qual(self, name: str) -> str:
        return ".".join([n for n, _k in self._stack] + [name])

    def _cur_class(self) -> Optional[str]:
        for i in range(len(self._stack) - 1, -1, -1):
            if self._stack[i][1] == "class":
                return ".".join(n for n, _ in self._stack[: i + 1])
        return None

    def _visit_func(self, node):
        qual = self._qual(node.name)
        self.funcs[qual] = node
        self.parents[qual] = ".".join(
            n for n, _ in self._stack) or None
        self.class_of[qual] = self._cur_class()
        self._stack.append((node.name, "func"))
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        qual = self._qual(node.name)
        self.classes[qual] = node
        self._stack.append((node.name, "class"))
        self.generic_visit(node)
        self._stack.pop()

    def visit_Call(self, node):
        if self._stack and self._stack[-1][1] == "func":
            qual = ".".join(n for n, _ in self._stack)
            callee = _dotted(node.func)
            if callee is not None:
                self.calls.setdefault(qual, set()).add(callee)
        self.generic_visit(node)

    def visit_Assign(self, node):
        # `exporter = self` inside a method: calls through `exporter.`
        # resolve like `self.` (the nested-handler-class idiom)
        if (self._stack and self._stack[-1][1] == "func"
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            qual = ".".join(n for n, _ in self._stack)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.self_aliases.setdefault(qual, set()).add(t.id)
        self.generic_visit(node)


def _index(mod: ModuleInfo) -> _Index:
    cached = getattr(mod, "_concurrency_index", None)
    if cached is None:
        cached = _Index()
        cached.visit(mod.tree)
        mod._concurrency_index = cached
    return cached


# ---------------------------------------------------------------------------
# thread-escape graph
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SpawnSite:
    node: ast.Call
    kind: str                 # "thread" | "server"
    owner: Optional[str]      # qualname of the spawning function
    target_quals: Tuple[str, ...]   # resolved same-module functions
    target_text: str          # the target expr as written (diagnostics)
    binding: Optional[str]    # source segment the object is bound to


@dataclasses.dataclass
class ThreadModel:
    """Per-module thread-escape graph: where threads start, which
    functions run on them, and which attributes each side touches."""

    spawns: List[SpawnSite]
    thread_funcs: Set[str]            # qualnames running on a spawned
                                      # thread (targets + same-module
                                      # transitive callees)
    index: _Index

    def is_thread_side(self, qual: Optional[str]) -> bool:
        if qual is None:
            return False
        if qual in self.thread_funcs:
            return True
        # nested defs inherit their parent's side
        return any(qual.startswith(t + ".") for t in self.thread_funcs)


def _enclosing_scopes(owner: Optional[str]):
    """The qualname and every enclosing prefix, innermost first
    (walking string prefixes covers class frames, which the parents
    map does not record)."""
    scope = owner or ""
    while scope:
        yield scope
        scope = scope.rsplit(".", 1)[0] if "." in scope else ""


def _alias_classes(idx: _Index, owner: Optional[str]) -> Dict[str, str]:
    """name -> class qualname whose instance the name denotes inside
    ``owner``: ``self``/``cls`` resolve to the nearest enclosing
    class, and ``x = self`` aliases resolve to the class of the
    function that bound them — the nested-handler-class idiom reaches
    its exporter through such an alias."""
    out: Dict[str, str] = {}
    for scope in _enclosing_scopes(owner):
        cls = idx.class_of.get(scope)
        if cls is not None:
            out.setdefault("self", cls)
            out.setdefault("cls", cls)
            for name in idx.self_aliases.get(scope, ()):
                out.setdefault(name, cls)
    return out


def _resolve_target(idx: _Index, owner: Optional[str],
                    expr: ast.AST) -> Tuple[Tuple[str, ...], str]:
    """Resolve a thread-target expression to same-module function
    qualnames.  Unresolvable targets return () with the source text."""
    text = _dotted(expr) or ast.dump(expr)[:40]
    # self.method (or an alias of self)
    if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                      ast.Name):
        aliases = _alias_classes(idx, owner)
        cls = aliases.get(expr.value.id)
        if cls is not None and f"{cls}.{expr.attr}" in idx.funcs:
            return (f"{cls}.{expr.attr}",), text
        # module-level function referenced through a module alias, or a
        # resource method (self._server.serve_forever): unresolvable
        return (), text
    if isinstance(expr, ast.Name):
        name = expr.id
        # nearest enclosing scope first (nested def), then module level
        for scope in _enclosing_scopes(owner):
            q = f"{scope}.{name}"
            if q in idx.funcs:
                return (q,), text
        if name in idx.funcs:
            return (name,), text
    return (), text


def _callee_quals(idx: _Index, caller: str, callee: str) -> List[str]:
    """Resolve a dotted callee string from ``caller`` to same-module
    function qualnames (the callgraph.py resolution rules, plus
    instance aliases)."""
    parts = callee.split(".")
    if len(parts) == 2:
        cls = _alias_classes(idx, caller).get(parts[0])
        if cls and f"{cls}.{parts[1]}" in idx.funcs:
            return [f"{cls}.{parts[1]}"]
        return []
    if len(parts) == 1:
        name = parts[0]
        for scope in _enclosing_scopes(caller):
            q = f"{scope}.{name}"
            if q in idx.funcs:
                return [q]
        if name in idx.funcs:
            return [name]
    return []


def thread_model(mod: ModuleInfo) -> ThreadModel:
    """Build (and memoize) the module's thread-escape graph."""
    cached = getattr(mod, "_thread_model_cache", None)
    if cached is not None:
        return cached
    idx = _index(mod)
    spawns: List[SpawnSite] = []
    parents = mod.parents()

    def _owner_of(node: ast.AST) -> Optional[str]:
        # nearest enclosing function's qualname
        chain: List[str] = []
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                chain.append(cur.name)
            cur = parents.get(cur)
        chain.reverse()
        while chain:
            qual = ".".join(chain)
            if qual in idx.funcs:
                return qual
            chain.pop()
        return None

    def _binding_of(call: ast.Call) -> Optional[str]:
        stmt = parents.get(call)
        # threading.Thread(...).start(): the call's parent chain goes
        # Attribute -> Call -> Expr — no binding.  A spawn anywhere
        # under an Assign's VALUE (including list comprehensions:
        # `threads = [Thread(...) for ...]`) binds through the target.
        while stmt is not None and not isinstance(stmt, ast.stmt):
            stmt = parents.get(stmt)
        if isinstance(stmt, ast.Assign) and any(
                sub is call for sub in ast.walk(stmt.value)):
            for t in stmt.targets:
                if isinstance(t, (ast.Name, ast.Attribute)):
                    return mod.segment(t)
        if isinstance(stmt, ast.AugAssign) and any(
                sub is call for sub in ast.walk(stmt.value)):
            if isinstance(stmt.target, (ast.Name, ast.Attribute)):
                return mod.segment(stmt.target)
        # threads.append(Thread(...)): the container is the binding
        if isinstance(stmt, ast.Expr):
            val = stmt.value
            if (isinstance(val, ast.Call)
                    and isinstance(val.func, ast.Attribute)
                    and val.func.attr in ("append", "add", "extend")
                    and any(sub is call
                            for a in val.args
                            for sub in ast.walk(a))):
                return mod.segment(val.func.value)
        return None

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        term = _terminal(_dotted(node.func))
        if term == "Thread":
            owner = _owner_of(node)
            target = next((kw.value for kw in node.keywords
                           if kw.arg == "target"), None)
            if target is None and node.args:
                target = node.args[0]
            quals, text = ((), "<no target>")
            if target is not None:
                quals, text = _resolve_target(idx, owner, target)
            spawns.append(SpawnSite(
                node=node, kind="thread", owner=owner,
                target_quals=quals, target_text=text,
                binding=_binding_of(node)))
        elif term in _SERVER_TYPES:
            owner = _owner_of(node)
            handler_quals: Tuple[str, ...] = ()
            text = term or ""
            if len(node.args) >= 2 and isinstance(node.args[1],
                                                  ast.Name):
                hname = node.args[1].id
                text = hname
                for cq, cnode in idx.classes.items():
                    if cq.split(".")[-1] == hname:
                        handler_quals = tuple(
                            q for q in idx.funcs
                            if idx.class_of.get(q) == cq)
                        break
            spawns.append(SpawnSite(
                node=node, kind="server", owner=owner,
                target_quals=handler_quals, target_text=text,
                binding=_binding_of(node)))

    thread_funcs: Set[str] = set()
    frontier = [q for s in spawns for q in s.target_quals]
    while frontier:
        qual = frontier.pop()
        if qual in thread_funcs:
            continue
        thread_funcs.add(qual)
        for callee in idx.calls.get(qual, ()):
            frontier.extend(_callee_quals(idx, qual, callee))
        # nested defs of a thread function run on the thread too
        frontier.extend(q for q in idx.funcs
                        if q.startswith(qual + "."))
    model = ThreadModel(spawns=spawns, thread_funcs=thread_funcs,
                        index=idx)
    mod._thread_model_cache = model
    return model


# ---------------------------------------------------------------------------
# attribute accesses + lock-guard context
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Access:
    attr: str
    node: ast.AST
    func: str            # qualname of the enclosing function
    cls: str             # class of the instance accessed (via alias)
    is_write: bool
    guards: frozenset    # normalized lock exprs held at the access


def _lock_names(mod: ModuleInfo) -> Set[str]:
    """Names/attrs assigned a Lock-family constructor anywhere in the
    module (so ``with self._visit_lock:`` guards even if the name
    doesn't contain 'lock')."""
    cached = getattr(mod, "_lock_names_cache", None)
    if cached is not None:
        return cached
    out: Set[str] = set()
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _terminal(_dotted(node.value.func))
                in SAFE_TYPE_KEYWORDS["lock"]):
            for t in node.targets:
                seg = mod.segment(t)
                if seg:
                    out.add(_norm_lock(seg))
    mod._lock_names_cache = out
    return out


def _norm_lock(expr_text: str) -> str:
    return "".join(expr_text.split())


def _is_lock_expr(mod: ModuleInfo, expr: ast.AST) -> bool:
    text = _dotted(expr)
    if text is None:
        return False
    if "lock" in text.rsplit(".", 1)[-1].lower():
        return True
    return _norm_lock(text) in _lock_names(mod)


def _guards_at(mod: ModuleInfo, node: ast.AST) -> frozenset:
    parents = mod.parents()
    held: Set[str] = set()
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                if _is_lock_expr(mod, item.context_expr):
                    held.add(_norm_lock(
                        _dotted(item.context_expr) or ""))
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        cur = parents.get(cur)
    return frozenset(held)


def _self_accesses(mod: ModuleInfo) -> List[_Access]:
    """Every ``self.<attr>`` access inside a method, classified
    read/write (attr assignment, subscript store on the attr, mutating
    method call, del) with the lock guards held at the site."""
    cached = getattr(mod, "_self_accesses_cache", None)
    if cached is not None:
        return cached
    idx = _index(mod)
    parents = mod.parents()
    out: List[_Access] = []
    for qual, fnode in idx.funcs.items():
        aliases = _alias_classes(idx, qual)
        if not aliases:
            continue
        # walk this function's own body, not nested defs (those are
        # their own quals)
        stack = list(fnode.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for child in ast.iter_child_nodes(node):
                stack.append(child)
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in aliases):
                continue
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            parent = parents.get(node)
            if (not is_write and isinstance(parent, ast.Subscript)
                    and parent.value is node
                    and isinstance(parent.ctx, (ast.Store, ast.Del))):
                is_write = True        # self.x[k] = v
            if (not is_write and isinstance(parent, ast.Attribute)
                    and parent.attr in _MUTATORS):
                gp = parents.get(parent)
                if isinstance(gp, ast.Call) and gp.func is parent:
                    is_write = True    # self.x.append(v)
            out.append(_Access(
                attr=node.attr, node=node, func=qual,
                cls=aliases[node.value.id],
                is_write=is_write, guards=_guards_at(mod, node)))
    mod._self_accesses_cache = out
    return out


def _annotated_attrs(mod: ModuleInfo) -> Dict[Tuple[Optional[str], str],
                                              Tuple[GuardSpec, ast.AST]]:
    """(class_qual | None, attr-or-name) -> (spec, annotated node) for
    every ``# guarded-by:`` annotation in the module.  ``class_qual``
    is None for module-level names; local names register under their
    enclosing function's qualname prefixed with ``<local>``."""
    cached = getattr(mod, "_guard_annotations_cache", None)
    if cached is not None:
        return cached
    idx = _index(mod)
    parents = mod.parents()
    out: Dict[Tuple[Optional[str], str], Tuple[GuardSpec, ast.AST]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        tail = _guard_annotation(mod, node)
        if tail is None:
            continue
        spec = parse_guard_spec(tail)
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                # class attr: find the enclosing class
                cur = parents.get(node)
                cls = None
                while cur is not None:
                    if isinstance(cur, ast.ClassDef):
                        for cq, cnode in idx.classes.items():
                            if cnode is cur:
                                cls = cq
                                break
                        break
                    cur = parents.get(cur)
                out[(cls, t.attr)] = (spec, node)
            elif isinstance(t, ast.Name):
                cur = parents.get(node)
                func = None
                while cur is not None:
                    if isinstance(cur, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        func = cur.name
                        break
                    cur = parents.get(cur)
                if func is None:
                    out[(None, t.id)] = (spec, node)        # module
                else:
                    out[(f"<local>{func}", t.id)] = (spec, node)
    mod._guard_annotations_cache = out
    return out


def _init_safe_type(mod: ModuleInfo, cls: Optional[str],
                    attr: str) -> bool:
    """True when the attribute's initializer constructs an inherently
    thread-safe type (Queue/Event/deque/Lock/local)."""
    idx = _index(mod)
    for qual, fnode in idx.funcs.items():
        if idx.class_of.get(qual) != cls:
            continue
        for node in ast.walk(fnode):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _terminal(_dotted(node.value.func))
                    in _SAFE_CONSTRUCTORS):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and t.attr == attr):
                        return True
    return False


def is_thread_join(node: ast.AST) -> bool:
    """A ``.join(...)`` call that is plausibly ``Thread.join`` rather
    than ``str.join``: thread joins take no positional args (or a
    numeric timeout / ``timeout=`` kwarg); ``str.join`` always takes
    exactly one iterable and often a literal receiver.  Without this
    shape check, a ``", ".join(parts)`` line silently satisfies the
    join-ordering rules — the exact class of false negative they were
    written to prevent."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"):
        return False
    if isinstance(node.func.value, (ast.Constant, ast.JoinedStr)):
        return False                    # literal string receiver
    if any(kw.arg == "timeout" for kw in node.keywords):
        return True
    if not node.args:
        return True
    if len(node.args) != 1:
        return False
    arg = node.args[0]
    # a timeout is a number or a scalar variable; str.join's one arg
    # is iterable-shaped (list/genexp/comprehension/call/literal)
    if isinstance(arg, ast.Constant):
        return isinstance(arg.value, (int, float))
    return isinstance(arg, (ast.Name, ast.Attribute))


def _func_joins(fnode: ast.AST) -> bool:
    """Does the function body contain a thread-shaped ``.join(...)``
    call (the join-form ordering witness)?"""
    return any(is_thread_join(node) for node in ast.walk(fnode))


# ---------------------------------------------------------------------------
# APX501 — unguarded cross-thread mutation
# ---------------------------------------------------------------------------


class CrossThreadMutationRule(Rule):
    id = "APX501"
    name = "unguarded-cross-thread-mutation"
    tier = "C"
    description = ("an attribute written on both the spawning side and "
                   "the thread side of a Thread/server spawn site with "
                   "no common `with <lock>:` scope — a torn write "
                   "waiting for a scheduler interleaving")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not mod.in_pkg:
            return
        model = thread_model(mod)
        if not model.thread_funcs:
            return
        annotated = _annotated_attrs(mod)
        accesses = _self_accesses(mod)
        spawn_lines = {s.node.lineno: s for s in model.spawns}
        # group writes per (class, attr) and side
        writes: Dict[Tuple[Optional[str], str],
                     Dict[str, List[_Access]]] = {}
        for acc in accesses:
            if not acc.is_write:
                continue
            if acc.func.split(".")[-1] == "__init__":
                continue   # construction happens-before the spawn
            side = ("thread" if model.is_thread_side(acc.func)
                    else "main")
            writes.setdefault((acc.cls, acc.attr), {}).setdefault(
                side, []).append(acc)
        for (cls, attr), sides in sorted(
                writes.items(), key=lambda kv: (kv[0][0] or "",
                                                kv[0][1])):
            if "thread" not in sides or "main" not in sides:
                continue
            if (cls, attr) in annotated:
                continue   # APX502 owns annotated attributes
            if _init_safe_type(mod, cls, attr):
                continue
            all_writes = sides["thread"] + sides["main"]
            common = frozenset.intersection(
                *[a.guards for a in all_writes])
            if common:
                continue
            first = min(all_writes, key=lambda a: a.node.lineno)
            other_side = ("thread" if first in sides["main"]
                          else "main")
            other = min(sides[other_side],
                        key=lambda a: a.node.lineno)
            spawn = min(spawn_lines) if spawn_lines else 0
            yield self.finding(
                mod, first.node,
                f"self.{attr} is written on both the spawning side "
                f"and the thread side (other write at line "
                f"{other.node.lineno}; thread spawned at line "
                f"{spawn}) with no common lock — guard both with one "
                "`with <lock>:` or annotate the attribute "
                "`# guarded-by: ...`")
        # nested-def targets: shared locals of the enclosing function
        yield from self._closure_writes(mod, model)

    def _closure_writes(self, mod: ModuleInfo,
                        model: ThreadModel) -> Iterator[Finding]:
        idx = model.index
        for spawn in model.spawns:
            if spawn.kind != "thread" or not spawn.owner:
                continue
            owner_node = idx.funcs.get(spawn.owner)
            if owner_node is None:
                continue
            thread_quals = [q for q in spawn.target_quals
                            if q.startswith(spawn.owner + ".")]
            if not thread_quals:
                continue
            # Only names the thread function declares nonlocal/global
            # actually share a binding cell with the spawner — a plain
            # assignment in a nested def is its own local (`for line
            # in ...` in a drain thread shadows, not shares).
            def _name_stores(fnode, only=None):
                out: Dict[str, ast.AST] = {}
                stack = list(fnode.body)
                while stack:
                    node = stack.pop()
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        continue
                    for child in ast.iter_child_nodes(node):
                        stack.append(child)
                    if (isinstance(node, ast.Name)
                            and isinstance(node.ctx, ast.Store)
                            and (only is None or node.id in only)):
                        out.setdefault(node.id, node)
                return out

            thread_stores: Dict[str, ast.AST] = {}
            for q in model.thread_funcs:
                if not q.startswith(spawn.owner + "."):
                    continue
                fnode = idx.funcs.get(q)
                if fnode is None:
                    continue
                shared = {
                    n for node in ast.walk(fnode)
                    if isinstance(node, (ast.Nonlocal, ast.Global))
                    for n in node.names}
                for k, v in _name_stores(fnode, only=shared).items():
                    thread_stores.setdefault(k, v)
            owner_stores = _name_stores(owner_node)
            safe_locals = self._safe_locals(mod, owner_node)
            annotated = _annotated_attrs(mod)
            for name in sorted(set(thread_stores) & set(owner_stores)):
                if name in safe_locals:
                    continue
                if (f"<local>{owner_node.name}", name) in annotated:
                    continue
                node = thread_stores[name]
                if _guards_at(mod, node) & _guards_at(
                        mod, owner_stores[name]):
                    continue
                yield self.finding(
                    mod, node,
                    f"closure variable {name!r} is written by both "
                    f"the thread target and {spawn.owner}() with no "
                    "common lock — hand it off through a Queue/Event "
                    "or guard both writes")

    @staticmethod
    def _safe_locals(mod: ModuleInfo, fnode) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(fnode):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _terminal(_dotted(node.value.func))
                    in _SAFE_CONSTRUCTORS):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out


# ---------------------------------------------------------------------------
# APX502 — guarded-by discipline
# ---------------------------------------------------------------------------


class GuardedByRule(Rule):
    id = "APX502"
    name = "guarded-by-discipline"
    tier = "C"
    description = ("a `# guarded-by: <spec>` annotation on a shared "
                   "attribute is enforced at every access site: lock "
                   "form requires `with <lock>:`, join form requires a "
                   "join-ordered reader, confined form requires the "
                   "attribute stay off every thread target")

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        if not mod.in_pkg:
            return
        annotated = _annotated_attrs(mod)
        if not annotated:
            return
        model = thread_model(mod)
        idx = _index(mod)
        accesses = _self_accesses(mod)
        for (scope, attr), (spec, decl) in sorted(
                annotated.items(),
                key=lambda kv: kv[1][1].lineno):
            if spec.form == "bad":
                yield self.finding(
                    mod, decl,
                    f"unparseable guarded-by spec {spec.raw!r} — "
                    "expected a lock expression, join(<thread>), "
                    "confined(<owner>), or one of "
                    f"{sorted(SAFE_TYPE_KEYWORDS)}")
                continue
            if scope is not None and scope.startswith("<local>"):
                yield from self._check_local(mod, scope, attr, spec,
                                             decl)
                continue
            if scope is None:
                yield from self._check_module_name(mod, attr, spec,
                                                   decl)
                continue
            cls_accesses = [a for a in accesses
                            if a.attr == attr and a.cls == scope]
            if spec.form == "safe-type":
                yield from self._check_safe_type(mod, decl, attr, spec)
                continue
            for acc in sorted(cls_accesses,
                              key=lambda a: a.node.lineno):
                if acc.func.split(".")[-1] == "__init__":
                    continue
                if spec.form == "lock":
                    if _norm_lock(spec.value) not in acc.guards:
                        yield self.finding(
                            mod, acc.node,
                            f"self.{attr} accessed outside `with "
                            f"{spec.value}:` (declared guarded-by at "
                            f"line {decl.lineno})")
                elif spec.form == "join":
                    if model.is_thread_side(acc.func):
                        continue   # the writer thread owns it
                    fnode = idx.funcs.get(acc.func)
                    if fnode is None or not _func_joins(fnode):
                        yield self.finding(
                            mod, acc.node,
                            f"self.{attr} is join-ordered (guarded-by:"
                            f" join({spec.value}) at line "
                            f"{decl.lineno}) but {acc.func}() touches "
                            "it without joining the writer thread "
                            "first")
                elif spec.form == "confined":
                    if model.is_thread_side(acc.func):
                        yield self.finding(
                            mod, acc.node,
                            f"self.{attr} is declared confined to "
                            f"{spec.value!r} (line {decl.lineno}) but "
                            f"{acc.func}() runs on a spawned thread")

    def _check_safe_type(self, mod, decl, attr, spec):
        value = decl.value
        ok = (isinstance(value, ast.Call)
              and _terminal(_dotted(value.func))
              in SAFE_TYPE_KEYWORDS[spec.value])
        if not ok:
            yield self.finding(
                mod, decl,
                f"{attr} declares guarded-by: {spec.value} but its "
                "initializer does not construct one of "
                f"{SAFE_TYPE_KEYWORDS[spec.value]}")

    def _check_local(self, mod, scope, name, spec, decl):
        # local annotations: only the safe-type form is checkable
        if spec.form == "safe-type":
            yield from self._check_safe_type(mod, decl, name, spec)

    def _check_module_name(self, mod: ModuleInfo, name: str,
                           spec: GuardSpec, decl: ast.AST):
        if spec.form == "safe-type":
            yield from self._check_safe_type(mod, decl, name, spec)
            return
        if spec.form != "lock":
            return   # join/confined on module globals: not modeled
        idx = _index(mod)
        for qual, fnode in idx.funcs.items():
            stack = list(fnode.body)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                for child in ast.iter_child_nodes(node):
                    stack.append(child)
                if (isinstance(node, ast.Name) and node.id == name
                        and isinstance(node.ctx, (ast.Load, ast.Store,
                                                  ast.Del))
                        and _norm_lock(spec.value)
                        not in _guards_at(mod, node)):
                    yield self.finding(
                        mod, node,
                        f"module global {name} accessed outside "
                        f"`with {spec.value}:` (declared guarded-by "
                        f"at line {decl.lineno})")


# ---------------------------------------------------------------------------
# APX503 — lock-acquisition order
# ---------------------------------------------------------------------------


class LockOrderRule(Rule):
    id = "APX503"
    name = "inconsistent-lock-order"
    tier = "C"
    repo_level = True
    description = ("two code paths acquire the same pair of locks in "
                   "opposite orders (lexical `with` nesting plus one "
                   "level of same-module call propagation) — a "
                   "potential deadlock")

    def check_repo(self, modules: Sequence[ModuleInfo],
                   root: str) -> Iterator[Finding]:
        # edges: lock identity -> {inner lock identity: (mod, node)}
        edges: Dict[str, Dict[str, Tuple[ModuleInfo, ast.AST]]] = {}
        for mod in modules:
            if not mod.in_pkg:
                continue
            try:
                self._module_edges(mod, edges)
            except RecursionError:   # pragma: no cover — pathological
                continue
        # Cycle detection: iterative color DFS, one finding per
        # back-edge.  O(V+E) with black-node memoization — the earlier
        # all-simple-paths form was exponential on dense graphs and
        # its recursion could overflow on deep lock chains, neither of
        # which a pre-commit gate can afford.
        seen_cycles: Set[frozenset] = set()
        black: Set[str] = set()
        for start in sorted(edges):
            if start in black:
                continue
            path: List[str] = []
            on_path: Set[str] = set()
            # stack of (lock, iterator over its successors)
            stack: List[Tuple[str, Iterator[str]]] = [
                (start, iter(sorted(edges.get(start, ()))))]
            path.append(start)
            on_path.add(start)
            while stack:
                lock, succ = stack[-1]
                nxt = next(succ, None)
                if nxt is None:
                    stack.pop()
                    path.pop()
                    on_path.discard(lock)
                    black.add(lock)
                    continue
                if nxt in on_path:
                    members = path[path.index(nxt):]
                    cyc = frozenset(members)
                    if cyc not in seen_cycles:
                        seen_cycles.add(cyc)
                        first = members[0]
                        second = members[1 % len(members)]
                        mod, node = edges[first][second]
                        yield self.finding(
                            mod, node,
                            "lock-order cycle: "
                            + " -> ".join(members + [members[0]])
                            + " — another path acquires these locks "
                            "in the opposite order (deadlock under "
                            "contention)")
                elif nxt not in black and nxt in edges:
                    stack.append(
                        (nxt, iter(sorted(edges.get(nxt, ())))))
                    path.append(nxt)
                    on_path.add(nxt)

    def _module_edges(self, mod: ModuleInfo, edges) -> None:
        idx = _index(mod)

        def identity(qual: Optional[str], expr: ast.AST) -> Optional[str]:
            text = _dotted(expr)
            if text is None:
                return None
            cls = idx.class_of.get(qual or "") if qual else None
            base = text[5:] if text.startswith("self.") else text
            where = cls or mod.relpath
            return f"{where}::{_norm_lock(base)}"

        def top_locks(fnode, qual) -> List[str]:
            out = []
            for node in ast.walk(fnode):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        if _is_lock_expr(mod, item.context_expr):
                            lid = identity(qual, item.context_expr)
                            if lid:
                                out.append(lid)
            return out

        parents = mod.parents()
        for qual, fnode in idx.funcs.items():
            for node in ast.walk(fnode):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                outer = [identity(qual, i.context_expr)
                         for i in node.items
                         if _is_lock_expr(mod, i.context_expr)]
                outer = [o for o in outer if o]
                if not outer:
                    continue
                for sub in ast.walk(node):
                    if sub is node:
                        continue
                    if isinstance(sub, (ast.With, ast.AsyncWith)):
                        for item in sub.items:
                            if _is_lock_expr(mod, item.context_expr):
                                inner = identity(qual,
                                                 item.context_expr)
                                for o in outer:
                                    if inner and inner != o:
                                        edges.setdefault(
                                            o, {}).setdefault(
                                            inner, (mod, sub))
                    elif isinstance(sub, ast.Call):
                        callee = _dotted(sub.func)
                        if callee is None:
                            continue
                        for cq in _callee_quals(idx, qual, callee):
                            for inner in top_locks(idx.funcs[cq], cq):
                                for o in outer:
                                    if inner != o:
                                        edges.setdefault(
                                            o, {}).setdefault(
                                            inner, (mod, sub))


CONCURRENCY_RULES: Tuple[Rule, ...] = (
    CrossThreadMutationRule(),
    GuardedByRule(),
    LockOrderRule(),
)
