"""apex_tpu.analysis — two-tier static analysis for the repo's invariants.

Eleven PRs of accreted invariants — the telemetry zero-overhead fast
path, ``APEX_TPU_*=kernel|reference|auto`` env routing with
warn-by-name, ring-only collectives inside ``overlap_scope``,
donation-safe jits, trace-time counter accounting — used to be enforced
by one grep test and reviewer memory.  This package turns them into
checked rules:

- **Tier A** (:mod:`rules` + :mod:`linter`, stdlib ``ast`` only — no
  jax import, runnable on any box): an AST rule framework over the repo
  source.  ``tools/lint.py`` is the CLI;
  ``tests/test_observability_guard.py`` is the tier-1 wrapper.
- **Tier B** (:mod:`jaxpr_audit`): traces the canonical entry points
  (AMP/DDP train step, ``decode_step`` both cache layouts, spec-decode
  verify, MoE ragged, the TP overlap ring) and walks the ClosedJaxpr —
  collective census vs the trace-time ``collectives.*``/``moe.*``
  counters (accounting-drift detector), no monolithic collectives under
  an active ``overlap_scope``, no unexplained bf16→f32 upcasts, donated
  buffers actually donated, no dead equations.  The ``static_audit``
  dryrun phase in ``__graft_entry__.py`` gates it.
- **Tier C** (:mod:`concurrency` + :mod:`lifecycle`, stdlib ``ast``
  like Tier A): the host control plane's thread discipline — a
  thread-escape graph over every Thread/ThreadingHTTPServer spawn
  site (APX501 unguarded cross-thread mutation), the ``# guarded-by:``
  annotation convention (APX502), lock-order cycles (APX503),
  thread/server lifecycle incl. the join-before-server_close ordering
  (APX504), and paired acquire/release with unwind edges — the PR-6
  ``_admit`` leak class — (APX505).  :mod:`stress` is the dynamic
  half: a seeded scrape/flush/save/churn smoke asserting exact sketch
  counts, zero refcount underflow and clean thread shutdown; the
  ``concurrency_audit`` dryrun phase gates both.

Import discipline: everything except :mod:`jaxpr_audit` must stay
importable without jax (``tools/lint.py`` runs on router boxes and in
pre-commit hooks); :mod:`jaxpr_audit` — and :mod:`stress`, which
drives jax-touching subsystems — import their heavy dependencies
lazily inside functions.

The metric-prefix rule (APX105) exempts this package the way it exempts
``apex_tpu/observability``: the auditor *reads* counter values by name
to diff them against the jaxpr census — it never emits into the
accounting streams the rule protects.

See docs/static_analysis.md for the rule table, suppression syntax
(``# apexlint: disable=APX301``) and the baseline workflow.
"""

from __future__ import annotations

__all__ = ["linter", "rules", "env_registry", "callgraph", "jaxpr_audit",
           "concurrency", "lifecycle", "stress"]


def __getattr__(name):
    # lazy: `import apex_tpu.analysis` must not drag jax in (jaxpr_audit
    # imports it lazily itself, but keep even the module load deferred)
    if name in __all__:
        import importlib

        return importlib.import_module(f"{__name__}.{name}")
    raise AttributeError(name)
