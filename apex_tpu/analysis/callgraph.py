"""Heuristic traced-code reachability over one module's AST.

The host-sync rules (APX301/APX302) only make sense inside code that
runs under a jax trace — ``time.time()`` in the serving engine's poll
loop is correct host code; the same call inside a ``lax.while_loop``
body is a silent per-step constant.  Whole-program points-to analysis
is out of scope for a stdlib linter, so this module computes a
*per-module over-approximation* that has proven adequate for the repo's
idioms:

1. **Trace roots.** A function is a root when it is

   - decorated with ``jax.jit`` / ``jit`` / ``jax.pmap`` /
     ``jax.shard_map`` — bare, called (``@jax.jit(...)``,
     ``@functools.partial(jax.jit, ...)``), or nested in ``partial``;
   - passed *by name* to a known tracing entry point anywhere in the
     module: ``jax.jit(f)``, ``jax.lax.scan(f, ...)``,
     ``lax.while_loop(cond, body, ...)``, ``lax.cond``/``switch``
     branches, ``jax.shard_map(f, ...)``, ``jax.vmap``, ``jax.grad`` /
     ``value_and_grad``, ``jax.checkpoint``/``remat``,
     ``jax.custom_vjp``/``custom_jvp`` (+ ``.defvjp`` arguments),
     ``jax.make_jaxpr``;
   - defined *inside* a traced function (local helpers defined under a
     trace are traced when called — the dominant repo pattern).

2. **Propagation.** Tracedness flows through plain ``Name`` calls
   resolved to functions defined in the same module (methods propagate
   through ``self.<name>``/``cls.<name>`` too).

Cross-module edges are NOT followed: a traced function calling an
imported helper does not mark that helper in its home module.  The
repo's traced helpers overwhelmingly live next to their entry points
(generate/speculative/moe/engine), and the per-module approximation
keeps the false-positive rate low enough to run as an error-severity
rule.  Deliberate host paths inside traced regions carry an inline
``# apexlint: disable=...`` with a justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional, Set

__all__ = ["TRACE_ENTRY_NAMES", "traced_functions"]

# dotted-call suffixes that trace their function-valued arguments.
# Matching is on the rightmost attribute path, so ``jax.lax.scan``,
# ``lax.scan`` and a bare ``scan`` (from-imported) all hit "scan".
TRACE_ENTRY_NAMES = {
    "jit", "pmap", "shard_map", "scan", "while_loop", "cond", "switch",
    "vmap", "grad", "value_and_grad", "custom_vjp", "custom_jvp",
    "defvjp", "checkpoint", "remat", "make_jaxpr", "associative_scan",
    "fori_loop",
}

_JIT_DECORATORS = {"jit", "pmap", "shard_map"}


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` → "a.b.c"; plain names → "a"; anything else → None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _decorator_traces(dec: ast.AST) -> bool:
    """Does this decorator expression put the function under a trace?"""
    d = _terminal(_dotted(dec))
    if d in _JIT_DECORATORS:
        return True
    if isinstance(dec, ast.Call):
        # @jax.jit(...)-style, @functools.partial(jax.jit, ...), and
        # nested partials — anything mentioning a jit-family callable
        if _terminal(_dotted(dec.func)) in _JIT_DECORATORS:
            return True
        for sub in ast.walk(dec):
            if (isinstance(sub, (ast.Attribute, ast.Name))
                    and _terminal(_dotted(sub)) in _JIT_DECORATORS):
                return True
    return False


class _FunctionIndex(ast.NodeVisitor):
    """Collect every function with its qualname, parent chain and the
    set of local callee names it invokes."""

    def __init__(self):
        self.funcs: Dict[str, ast.AST] = {}        # qualname -> node
        self.parents: Dict[str, Optional[str]] = {}
        self.calls: Dict[str, Set[str]] = {}       # qualname -> callees
        self._stack = []

    def _visit_func(self, node):
        qual = ".".join([*self._stack, node.name])
        self.funcs[qual] = node
        self.parents[qual] = ".".join(self._stack) or None
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def visit_Call(self, node):
        if self._stack:
            qual = ".".join(self._stack)
            callee = _dotted(node.func)
            if callee is not None:
                # self.f() / cls.f() resolve to the sibling method name
                if callee.startswith(("self.", "cls.")):
                    callee = callee.split(".", 1)[1]
                self.calls.setdefault(qual, set()).add(callee)
        self.generic_visit(node)


def _name_args(call: ast.Call):
    """Bare-Name positional/keyword arguments of a call (the function
    references tracing entry points consume)."""
    for arg in call.args:
        if isinstance(arg, ast.Name):
            yield arg.id
    for kw in call.keywords:
        if isinstance(kw.value, ast.Name):
            yield kw.value.id


def traced_functions(tree: ast.Module) -> Dict[str, str]:
    """Map qualname → reason for every function the heuristic considers
    reachable from a jax trace."""
    index = _FunctionIndex()
    index.visit(tree)

    traced: Dict[str, str] = {}

    def mark(qual: str, reason: str):
        if qual not in traced:
            traced[qual] = reason

    # (a) decorator roots
    for qual, node in index.funcs.items():
        for dec in getattr(node, "decorator_list", ()):
            if _decorator_traces(dec):
                mark(qual, "jit-decorated")

    # (b) by-name arguments of tracing entry points, anywhere
    entry_args: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            if _terminal(_dotted(node.func)) in TRACE_ENTRY_NAMES:
                entry_args.update(_name_args(node))
    for qual, node in index.funcs.items():
        if node.name in entry_args:
            mark(qual, f"passed to a tracing entry point ({node.name})")

    # (c) nesting: a def inside a traced function is traced
    changed = True
    while changed:
        changed = False
        for qual in index.funcs:
            if qual in traced:
                continue
            parent = index.parents.get(qual)
            while parent is not None:
                if parent in traced and parent in index.funcs:
                    mark(qual, f"defined inside traced {parent}")
                    changed = True
                    break
                parent = index.parents.get(parent)
        # (d) propagation through local Name calls
        for qual in list(traced):
            for callee in index.calls.get(qual, ()):
                term = _terminal(callee)
                for cq, cnode in index.funcs.items():
                    if cnode.name == term and cq not in traced:
                        # only same-scope or module-level resolution:
                        # avoid marking an unrelated method of another
                        # class that happens to share the name
                        if ("." not in cq
                                or index.parents.get(cq) ==
                                index.parents.get(qual)
                                or cq.startswith(qual + ".")):
                            mark(cq, f"called from traced {qual}")
                            changed = True
    return traced
